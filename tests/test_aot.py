"""Certified AOT executable store + ``maelstrom lint --aot`` (pass 9).

Acceptance bars pinned here:

- the store key (``pipelined_fingerprint``) is stable per config and
  sensitive to every static knob that changes the compiled executable
  (chunk length, scan-k, carry layout, event cap, fleet size);
- cold -> warm roundtrips through ``run_sim_pipelined`` and
  ``run_sim_sharded_chunked`` are bit-identical to the storeless path,
  and the warm record proves every length was served from the store;
- ``prewarm_pipelined`` populates exactly the keys a production run
  later reads (shape templates only — key-compatibility is the whole
  point of the prewarm);
- a tampered payload or foreign-toolchain entry is refused by the
  runtime (miss, never a wrong executable) AND named by the audit:
  every EXE9xx rule fires on its fixture, and a freshly populated
  store + manifest lints green;
- the compile-cache counters keep the AOT source separate from the
  persistent-XLA source (the double-count regression: an AOT lookup
  must never leak into the legacy ``hits``/``misses`` keys).

Every store populate is a REAL compile by design (the populate path
bypasses the persistent XLA cache), so the compile-heavy roundtrips
beyond the lead-layout representative are ``slow``-marked to protect
the tier-1 wall-clock budget — ``-m aot`` runs the full set.
"""

import json
import os
import shutil
import subprocess
import sys

import jax
import jax.numpy as jnp
import jax.tree_util as tu
import numpy as np
import pytest

from maelstrom_tpu.analysis.aot_audit import (load_aot_manifest,
                                              run_aot_lint)
from maelstrom_tpu.analysis.findings import SEV_ERROR, SEV_WARNING
from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu.aot_store import (AotStore, aot_enabled,
                                         jaxpr_digest,
                                         pipelined_fingerprint,
                                         prewarm_pipelined,
                                         resolve_store_dir, store_key,
                                         wrap_pipelined)
from maelstrom_tpu.tpu.harness import make_sim_config
from maelstrom_tpu.tpu.pipeline import plan_chunks, run_sim_pipelined
from maelstrom_tpu.tpu.runtime import canonical_carry

pytestmark = pytest.mark.aot

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _aot_enabled():
    """conftest.py kills the store suite-wide (MAELSTROM_AOT=0 — an
    incidental populate is a real, cache-bypassing compile); this
    module IS the store's coverage, so re-enable it here."""
    prev = os.environ.pop("MAELSTROM_AOT", None)
    yield
    if prev is not None:
        os.environ["MAELSTROM_AOT"] = prev

# audit-sized echo fleet: the same scale the lint pass traces, so every
# compile in this file is a few seconds on CPU
OPTS = dict(node_count=2, concurrency=2, time_limit=0.25, rate=50.0,
            latency=5.0, n_instances=4, record_instances=2,
            journal_instances=0, seed=3)

# one trace of the three audit subjects, shared by every lint call in
# this module (run_aot_lint re-traces per call otherwise)
TRACE_CACHE = {}


def _setup(layout="lead", **over):
    model = get_model("echo", 2)
    sim = make_sim_config(model, {**OPTS, "layout": layout, **over})
    return model, sim, model.make_params(sim.net.n_nodes)


def _assert_trees_equal(a, b):
    for (path, x), (_, y) in zip(tu.tree_flatten_with_path(a)[0],
                                 tu.tree_flatten_with_path(b)[0]):
        name = "/".join(str(p) for p in path)
        assert x.shape == y.shape, (name, x.shape, y.shape)
        assert (np.asarray(x) == np.asarray(y)).all(), name


def _lint(store, manifest):
    return run_aot_lint(repo_root=REPO, manifest_path=manifest,
                        store_path=store, trace_cache=TRACE_CACHE)


def _errors(findings):
    return [f for f in findings if f.severity == SEV_ERROR]


@pytest.fixture(scope="module")
def fresh_store(tmp_path_factory):
    """One populated store + matching manifest (the three audit
    subjects, compiled once); tamper tests copy it, never mutate it."""
    d = tmp_path_factory.mktemp("aot")
    store, manifest = str(d / "store"), str(d / "manifest.json")
    findings = run_aot_lint(repo_root=REPO, manifest_path=manifest,
                            update_manifest=True, store_path=store,
                            trace_cache=TRACE_CACHE)
    assert [f.rule for f in findings] == ["EXE900"]
    assert len(list(AotStore(store).entries())) == 3
    return store, manifest


def _copy_store(fresh, tmp_path):
    dst = str(tmp_path / "store")
    shutil.copytree(fresh[0], dst)
    return dst


def _edit_meta(store, pick, mutate):
    """Rewrite the sidecar of the first entry ``pick`` accepts; returns
    its key."""
    for key, meta in AotStore(store).entries():
        if not pick(meta):
            continue
        mutate(meta)
        with open(os.path.join(store, key + ".json"), "w") as f:
            json.dump(meta, f)
        return key
    raise AssertionError("no entry matched")


# --- keying ----------------------------------------------------------------


def test_fingerprint_stable():
    model, sim, params = _setup()
    a = pipelined_fingerprint(model, sim, params=params)
    b = pipelined_fingerprint(model, sim, params=params)
    assert a == b
    assert len(a) == 32
    int(a, 16)  # hex


def test_fingerprint_sensitive_to_static_knobs():
    model, sim, params = _setup()
    base = pipelined_fingerprint(model, sim, params=params)
    variants = {
        "chunk": pipelined_fingerprint(model, sim, params=params,
                                       chunk=7),
        "scan-k": pipelined_fingerprint(model, sim, params=params,
                                        scan_k=9),
        "event-cap": pipelined_fingerprint(model, sim, params=params,
                                           event_cap=48),
        "unroll": pipelined_fingerprint(model, sim, params=params,
                                        unroll=2),
    }
    model2, sim2, params2 = _setup(layout="minor")
    variants["layout"] = pipelined_fingerprint(model2, sim2,
                                               params=params2)
    model3, sim3, params3 = _setup(n_instances=8)
    variants["fleet"] = pipelined_fingerprint(model3, sim3,
                                              params=params3)
    for knob, key in variants.items():
        assert key != base, knob
    assert len(set(variants.values())) == len(variants)


def test_store_key_canonicalization():
    # dict insertion order never changes the content address...
    assert store_key({"b": 1, "a": 2}) == store_key({"a": 2, "b": 1})
    # ...but array VALUES do (pipelined params are burned into the
    # binary, so they are hashed by value, not aval)
    assert store_key({"x": np.arange(3)}) == store_key({"x": np.arange(3)})
    assert store_key({"x": np.arange(3)}) != store_key(
        {"x": np.arange(3) + 1})


def test_jaxpr_digest_stable_across_traces():
    f = lambda x: jnp.cumsum(x * 2)
    sds = jax.ShapeDtypeStruct((8,), jnp.float32)
    a = jaxpr_digest(jax.make_jaxpr(f)(sds))
    b = jaxpr_digest(jax.make_jaxpr(f)(sds))
    assert a == b
    g = lambda x: jnp.cumsum(x * 3)
    assert jaxpr_digest(jax.make_jaxpr(g)(sds)) != a


# --- resolution / kill switch ----------------------------------------------


def test_resolve_store_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("MAELSTROM_AOT", raising=False)
    monkeypatch.delenv("MAELSTROM_COMPILE_CACHE", raising=False)
    d = str(tmp_path / "s")
    assert resolve_store_dir(d) == os.path.abspath(d)
    for off in ("off", "0", ""):
        assert resolve_store_dir(off) is None
    # auto rides the compile cache: resolved dir + .aot
    assert resolve_store_dir("auto", str(tmp_path / "cc")) \
        == os.path.abspath(str(tmp_path / "cc")) + ".aot"
    # a disabled compile cache disables the auto store too
    monkeypatch.setenv("MAELSTROM_COMPILE_CACHE", "0")
    assert resolve_store_dir("auto") is None
    assert resolve_store_dir(None) is None


def test_kill_switch_wins_over_everything(tmp_path, monkeypatch):
    monkeypatch.setenv("MAELSTROM_AOT", "0")
    assert not aot_enabled()
    assert resolve_store_dir(str(tmp_path)) is None
    assert resolve_store_dir("auto") is None
    # and the wrapper face: a disabled store is (None, None), the
    # caller keeps the plain jit path
    assert wrap_pipelined(
        None, model=None, sim=None, params=None, instance_ids=None,
        cap=None, unroll=1, scan_k=8, store_dir=None) == (None, None)


# --- roundtrips ------------------------------------------------------------


@pytest.mark.parametrize("layout", [
    "lead",
    pytest.param("minor", marks=pytest.mark.slow)])
def test_pipelined_cold_warm_bit_identity(layout, tmp_path):
    model, sim, params = _setup(layout)
    store = str(tmp_path / "store")
    base = run_sim_pipelined(model, sim, 3, params, chunk=10_000)
    cold = run_sim_pipelined(model, sim, 3, params, chunk=10_000,
                             aot_store=store)
    warm = run_sim_pipelined(model, sim, 3, params, chunk=10_000,
                             aot_store=store)
    rc, rw = cold.perf["aot"], warm.perf["aot"]
    assert rc["hit"] is False
    assert set(rc["lengths"].values()) == {"populated"}
    assert rw["hit"] is True
    assert set(rw["lengths"].values()) == {"hit"}
    assert rw["load-s"] > 0
    assert rc["fingerprint"] == rw["fingerprint"]
    # the heartbeat/campaign provenance key IS the dispatch key
    assert rc["fingerprint"] == pipelined_fingerprint(
        model, sim, params=params, chunk=10_000)
    for res in (cold, warm):
        _assert_trees_equal(canonical_carry(base.carry, sim),
                            canonical_carry(res.carry, sim))
        assert (np.asarray(base.events)
                == np.asarray(res.events)).all()


@pytest.mark.slow
def test_sharded_cold_warm_bit_identity(tmp_path):
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked)
    model, sim, _params = _setup()
    mesh = make_mesh(2)
    store = str(tmp_path / "store")
    base = run_sim_sharded_chunked(model, sim, 3, mesh=mesh,
                                   chunk=10_000)
    pc, pw = {}, {}
    cold = run_sim_sharded_chunked(model, sim, 3, mesh=mesh,
                                   chunk=10_000, perf=pc,
                                   aot_store=store)
    warm = run_sim_sharded_chunked(model, sim, 3, mesh=mesh,
                                   chunk=10_000, perf=pw,
                                   aot_store=store)
    assert pc["aot"]["hit"] is False
    assert set(pc["aot"]["lengths"].values()) == {"populated"}
    assert pw["aot"]["hit"] is True
    assert set(pw["aot"]["lengths"].values()) == {"hit"}
    assert base[0] == cold[0] == warm[0]
    assert np.array_equal(base[1], cold[1])
    assert np.array_equal(base[1], warm[1])
    assert np.array_equal(base[2], cold[2])
    assert np.array_equal(base[2], warm[2])


@pytest.mark.slow
def test_multi_length_plan_fully_served(tmp_path):
    model, sim, params = _setup()
    n = sim.n_ticks
    chunk = next(c for c in range(n - 1, 1, -1)
                 if len({ln for _, ln in plan_chunks(n, c)}) == 2)
    store = str(tmp_path / "store")
    cold = run_sim_pipelined(model, sim, 3, params, chunk=chunk,
                             aot_store=store)
    warm = run_sim_pipelined(model, sim, 3, params, chunk=chunk,
                             aot_store=store)
    assert len(cold.perf["aot"]["lengths"]) == 2
    assert set(cold.perf["aot"]["lengths"].values()) == {"populated"}
    assert set(warm.perf["aot"]["lengths"].values()) == {"hit"}
    _assert_trees_equal(canonical_carry(cold.carry, sim),
                        canonical_carry(warm.carry, sim))


@pytest.mark.slow
def test_store_failure_degrades_to_jit(tmp_path):
    # store dir is a FILE: every put fails, the run must fall back to
    # the plain jit path and stay bit-identical (the store is an
    # accelerator, never a correctness dependency)
    bad = tmp_path / "not-a-dir"
    bad.write_text("x")
    model, sim, params = _setup()
    base = run_sim_pipelined(model, sim, 3, params, chunk=10_000)
    res = run_sim_pipelined(model, sim, 3, params, chunk=10_000,
                            aot_store=str(bad))
    rec = res.perf["aot"]
    assert set(rec["lengths"].values()) == {"error"}
    assert "error" in rec
    _assert_trees_equal(canonical_carry(base.carry, sim),
                        canonical_carry(res.carry, sim))


# --- prewarm ---------------------------------------------------------------


@pytest.mark.slow
def test_prewarm_populates_the_production_keys(tmp_path):
    model, sim, _params = _setup()
    store = str(tmp_path / "store")
    out = prewarm_pipelined(model, sim, store, chunk=10_000)
    assert set(out.values()) == {"populated"}
    # the run never compiles: every length the plan dispatches was
    # prewarmed under the exact key the wrapper recomputes
    res = run_sim_pipelined(model, sim, 3, chunk=10_000,
                            aot_store=store)
    rec = res.perf["aot"]
    assert rec["hit"] is True
    assert set(rec["lengths"].values()) == {"hit"}
    assert set(rec["lengths"]) == set(out)
    # idempotent: a second prewarm touches nothing
    assert set(prewarm_pipelined(model, sim, store,
                                 chunk=10_000).values()) == {"hit"}


# --- runtime refusal faces -------------------------------------------------


def test_tampered_payload_refused_at_load(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)
    key = next(iter(AotStore(store).entries()))[0]
    path = os.path.join(store, key + ".bin")
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    s = AotStore(store)
    assert s.load_payload(key) is None
    assert s.load(key) is None  # a tampered entry is a miss, never code


def test_foreign_toolchain_refused_at_load(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)
    key = _edit_meta(store, lambda m: True,
                     lambda m: m.update({"jax-version": "0.0.0"}))
    s = AotStore(store)
    assert s.load(key) is None
    # the bytes themselves are intact — only the toolchain gate refused
    assert s.load_payload(key) is not None


# --- the audit (EXE9xx) ----------------------------------------------------


def test_fresh_store_lints_green(fresh_store):
    assert _lint(*fresh_store) == []


def test_payload_tamper_is_exe901(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)
    key = next(iter(AotStore(store).entries()))[0]
    path = os.path.join(store, key + ".bin")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    hits = [f for f in _errors(_lint(store, fresh_store[1]))
            if f.rule == "EXE901"]
    assert len(hits) == 1
    assert "tamper" in hits[0].message


def test_fingerprint_drift_is_exe901(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)

    def drift(meta):
        d = meta["fingerprint"]["jaxpr-digest"]
        meta["fingerprint"]["jaxpr-digest"] = \
            ("0" if d[0] != "0" else "1") + d[1:]

    _edit_meta(store, lambda m: m["kind"] == "pipelined", drift)
    hits = [f for f in _errors(_lint(store, fresh_store[1]))
            if f.rule == "EXE901"]
    assert len(hits) == 1
    assert "no longer matches the jaxpr" in hits[0].message
    assert hits[0].symbol == "make_chunk_fn"


def test_donation_lost_is_exe902(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)
    _edit_meta(store, lambda m: m["kind"] == "pipelined",
               lambda m: m.update({"donated-leaves": 9999}))
    hits = [f for f in _errors(_lint(store, fresh_store[1]))
            if f.rule == "EXE902"]
    assert len(hits) == 1
    assert "input_output_alias" in hits[0].message


def test_smuggled_collective_is_exe903(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)
    _edit_meta(store, lambda m: m["kind"] == "pipelined",
               lambda m: m.update({"collectives": {"all-to-all": 2}}))
    hits = [f for f in _errors(_lint(store, fresh_store[1]))
            if f.rule == "EXE903"]
    assert len(hits) == 1
    assert "all-to-all" in hits[0].message


def test_foreign_toolchain_is_exe904(fresh_store, tmp_path):
    store = _copy_store(fresh_store, tmp_path)
    _edit_meta(store, lambda m: True,
               lambda m: m.update({"jax-version": "0.0.0"}))
    findings = _lint(store, fresh_store[1])
    hits = [f for f in _errors(findings) if f.rule == "EXE904"]
    assert len(hits) == 1
    assert "jax-version" in hits[0].message
    # refusal is by name and FINAL: no other rule piles onto the entry
    assert len(_errors(findings)) == 1


def test_missing_manifest_is_exe905(tmp_path):
    findings = _lint("off", str(tmp_path / "absent.json"))
    hits = [f for f in findings if f.rule == "EXE905"]
    assert len(hits) == 3  # one per audit subject
    assert all(f.severity == SEV_ERROR for f in hits)


def test_stale_manifest_entry_is_exe906(fresh_store, tmp_path):
    data = load_aot_manifest(fresh_store[1])
    data["entries"]["ghost/n=9/lead/pipelined"] = {
        "jaxpr-digest": "0" * 32, "chunk-length": 4,
        "donated-leaves": 1, "kind": "pipelined"}
    path = str(tmp_path / "manifest.json")
    with open(path, "w") as f:
        json.dump(data, f)
    findings = _lint("off", path)
    hits = [f for f in findings if f.rule == "EXE906"]
    assert len(hits) == 1
    assert hits[0].severity == SEV_WARNING
    assert "ghost/n=9" in hits[0].message
    assert not _errors(findings)


def test_checked_in_manifest_matches_current_source():
    """The repo's own aot_manifest.json certifies current source — a
    dispatch change without --update-aot fails here first."""
    findings = run_aot_lint(repo_root=REPO, store_path="off",
                            trace_cache=TRACE_CACHE)
    assert findings == []


# --- compile-cache source accounting ---------------------------------------


def test_compile_cache_counts_aot_separately():
    from maelstrom_tpu.utils.compile_cache import (CacheStats,
                                                   compile_source,
                                                   note_aot)
    snap = CacheStats()
    note_aot(True)
    note_aot(False)
    note_aot(False)
    d = snap.delta()
    assert d["aot-hits"] == 1 and d["aot-misses"] == 2
    # the double-count regression: AOT lookups never leak into the
    # legacy keys, which alias the persistent-XLA source only
    assert d["hits"] == d["persistent-hits"]
    assert d["misses"] == d["persistent-misses"]
    snap2 = CacheStats()
    note_aot(True)
    d2 = snap2.delta()
    assert d2["aot-hits"] == 1
    assert d2["hits"] == 0 and d2["misses"] == 0
    # source classification: the store outranks the XLA cache outranks
    # a cold compile outranks a silent in-process warm run
    assert compile_source({"aot-hits": 1,
                           "persistent-misses": 1}) == "aot-hit"
    assert compile_source({"persistent-misses": 2,
                           "persistent-hits": 1}) == "cold-compile"
    assert compile_source({"persistent-hits": 3}) == "xla-cache-hit"
    assert compile_source({}) == "warm-process"


# --- cross-process ---------------------------------------------------------


_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_compilation_cache_dir", sys.argv[2])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu.harness import make_sim_config
from maelstrom_tpu.tpu.pipeline import run_sim_pipelined
model = get_model("echo", 2)
sim = make_sim_config(model, json.loads(sys.argv[3]))
res = run_sim_pipelined(model, sim, 3, chunk=10_000,
                        aot_store=sys.argv[1])
print(json.dumps({"aot": res.perf["aot"],
                  "delivered": int(res.carry.stats.delivered)}))
"""


@pytest.mark.slow
def test_cross_process_warm_start(tmp_path):
    """The store's whole reason to exist: a SECOND process (fresh jit
    caches) deserializes instead of compiling, bit-identically."""
    store = str(tmp_path / "store")
    legs = []
    for _ in range(2):
        out = subprocess.run(
            [sys.executable, "-c", _CHILD, store,
             os.path.join(REPO, ".jax_cache"), json.dumps(OPTS)],
            cwd=REPO, capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stderr[-2000:]
        legs.append(json.loads(out.stdout.strip().splitlines()[-1]))
    cold, warm = legs
    assert cold["aot"]["hit"] is False
    assert set(cold["aot"]["lengths"].values()) == {"populated"}
    assert warm["aot"]["hit"] is True
    assert set(warm["aot"]["lengths"].values()) == {"hit"}
    assert cold["aot"]["fingerprint"] == warm["aot"]["fingerprint"]
    assert cold["delivered"] == warm["delivered"]
