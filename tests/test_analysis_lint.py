"""Tests for the `maelstrom lint` static-analysis subsystem.

Coverage contract (ISSUE acceptance): each of the three passes has at
least 3 distinct rules exercised with positive AND negative fixtures;
the intentional-bug fixture in models/raft_buggy.py is asserted to be
flagged (as status="expected" baseline entries, never silently
accepted); and the repo-wide run is clean modulo the checked-in
baseline.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from conftest import REPO

from maelstrom_tpu.analysis.findings import (Baseline, BaselineEntry,
                                             Finding, LintReport,
                                             render_text)
from maelstrom_tpu.analysis.trace_lint import lint_sources


def _trace(src, path="fixture.py"):
    return lint_sources({path: textwrap.dedent(src)})


def _rules(findings):
    return {f.rule for f in findings}


# --- trace-hygiene rules (TRC1xx) ------------------------------------------

class TestTraceRules:
    def test_traced_branch_flagged(self):
        fs = _trace("""
            class M:
                def handle(self, row, node_idx, msg, t, key, cfg, params):
                    if row > 0:
                        row = row + 1
                    return row, None
        """)
        assert _rules(fs) == {"TRC101"}

    def test_static_branch_not_flagged(self):
        fs = _trace("""
            class M:
                vote_check = True
                def handle(self, row, node_idx, msg, t, key, cfg, params):
                    if self.vote_check:
                        row = row + 1
                    if cfg.n_nodes > 2:
                        row = row - 1
                    if params is None:
                        row = row * 2
                    return row, None
        """)
        assert fs == []

    def test_traced_while_and_assert(self):
        fs = _trace("""
            class M:
                def tick(self, row, node_idx, t, key, cfg, params):
                    while row > 0:
                        break
                    assert t >= 0
                    return row, None
        """)
        assert _rules(fs) == {"TRC102", "TRC103"}

    def test_static_loop_not_flagged(self):
        fs = _trace("""
            class M:
                apply_max = 2
                def tick(self, row, node_idx, t, key, cfg, params):
                    outs = []
                    for _ in range(self.apply_max):
                        outs.append(row)
                    assert cfg.n_nodes > 0
                    return row, outs
        """)
        assert fs == []

    def test_host_sync_flagged(self):
        fs = _trace("""
            import numpy as np
            class M:
                def handle(self, row, node_idx, msg, t, key, cfg, params):
                    a = int(msg)
                    b = row.item()
                    c = np.asarray(row)
                    return row, (a, b, c)
        """)
        assert [f.rule for f in fs] == ["TRC104"] * 3

    def test_host_sync_on_static_not_flagged(self):
        fs = _trace("""
            import numpy as np
            class M:
                def handle(self, row, node_idx, msg, t, key, cfg, params):
                    n = int(cfg.latency_mean)
                    tbl = np.asarray([1, 2, 3])
                    return row, (n, tbl)
        """)
        assert fs == []

    def test_mutable_capture_flagged(self):
        fs = _trace("""
            CACHE = []
            class M:
                def tick(self, row, node_idx, t, key, cfg, params):
                    CACHE.append(t)
                    self.seen = {}
                    return row, None
        """)
        assert _rules(fs) == {"TRC105"}
        assert len(fs) == 2

    def test_local_mutation_not_flagged(self):
        fs = _trace("""
            class M:
                def tick(self, row, node_idx, t, key, cfg, params):
                    outs = []
                    outs.append(row)
                    return row, outs
        """)
        assert fs == []

    def test_data_dependent_shape_warns(self):
        fs = _trace("""
            import jax.numpy as jnp
            class M:
                def invariants(self, node_state, cfg, params):
                    bad = jnp.nonzero(node_state)
                    alt = jnp.where(node_state > 0)
                    return bad, alt
        """)
        assert [f.rule for f in fs] == ["TRC106"] * 2
        assert all(f.severity == "warning" for f in fs)

    def test_three_arg_where_not_flagged(self):
        fs = _trace("""
            import jax.numpy as jnp
            class M:
                def invariants(self, node_state, cfg, params):
                    return jnp.where(node_state > 0, 1, 0)
        """)
        assert fs == []

    def test_bare_python_rng_flagged(self):
        fs = _trace("""
            import random
            import numpy as np
            class M:
                def sample_op(self, key, uniq, cfg, params):
                    a = random.random()
                    b = np.random.randint(3)
                    return a, b
        """)
        assert [f.rule for f in fs] == ["TRC107"] * 2

    def test_jax_random_not_flagged(self):
        fs = _trace("""
            import jax
            class M:
                def sample_op(self, key, uniq, cfg, params):
                    return jax.random.randint(key, (), 0, 5)
        """)
        assert fs == []

    def test_helper_reached_via_fixpoint(self):
        """A `_`-helper called from handle() inherits tracedness; a
        host-side decoder with the same shape does not."""
        fs = _trace("""
            class M:
                def handle(self, row, node_idx, msg, t, key, cfg, params):
                    return self._bump(row), None
                def _bump(self, value):
                    if value > 0:
                        return value + 1
                    return value
                def complete_record(self, f, a, b, c, etype):
                    if f == 1:
                        return {"v": int(a)}
                    return None
        """)
        assert [(f.rule, f.symbol) for f in fs] == [("TRC101", "M._bump")]

    def test_for_iterable_expression_checked(self):
        """Hazards inside the `for` iterator itself are not a blind spot."""
        fs = _trace("""
            import numpy as np
            class M:
                def tick(self, row, node_idx, t, key, cfg, params):
                    for x in np.asarray(row):
                        pass
                    return row, None
        """)
        assert _rules(fs) == {"TRC104"}

    def test_nested_scan_body_checked(self):
        """Bodies nested in host-side factories (make_tick_fn style)."""
        fs = _trace("""
            def make_tick_fn(model, sim, params):
                def tick_fn(carry, t):
                    if t > 0:
                        carry = carry
                    return carry, None
                return tick_fn
        """)
        assert _rules(fs) == {"TRC101"}


# --- abstract-eval contract rules (CON2xx) ---------------------------------

@pytest.fixture(scope="module")
def echo_base():
    from maelstrom_tpu.models.echo import EchoModel
    return EchoModel


def _audit(model, n=1):
    from maelstrom_tpu.analysis.contract_audit import audit_model
    return audit_model(model, n)


class TestContractRules:
    def test_clean_model_passes(self, echo_base):
        assert _audit(echo_base()) == []

    def test_emit_shape_contract_max_out(self, echo_base):
        import jax.numpy as jnp

        class TooManyOuts(echo_base):
            def handle(self, row, node_idx, msg, t, key, cfg, params):
                row, out = super().handle(row, node_idx, msg, t, key,
                                          cfg, params)
                return row, jnp.concatenate([out, out], axis=0)

        fs = _audit(TooManyOuts())
        assert "CON202" in _rules(fs)
        assert any("max_out" in f.message for f in fs)

    def test_carry_fixed_point_dtype_drift(self, echo_base):
        import jax.numpy as jnp

        class DtypeDrift(echo_base):
            def tick(self, row, node_idx, t, key, cfg, params):
                _, outs = super().tick(row, node_idx, t, key, cfg,
                                       params)
                return row.astype(jnp.float32), outs

        fs = _audit(DtypeDrift())
        rules = _rules(fs)
        assert "CON202" in rules          # row not a fixed point of tick
        assert "CON201" in rules          # ...so the scan carry drifts
        assert any("int32 -> float32" in f.message for f in fs)

    def test_client_lane_contract_op_lanes(self, echo_base):
        import jax.numpy as jnp

        class WrongOpLanes(echo_base):
            def sample_op(self, key, uniq, cfg, params):
                return jnp.zeros((7,), jnp.int32)   # declares op_lanes=4

        fs = _audit(WrongOpLanes())
        assert "CON203" in _rules(fs)
        # the full tick also fails to trace (client_step broadcasts the
        # op row against the declared width) — surfaced as a trace
        # failure, not silence
        assert "CON200" in _rules(fs)

    def test_client_lane_contract_decode_width(self, echo_base):
        import jax.numpy as jnp

        class ShortDecode(echo_base):
            def decode_reply(self, op, msg, cfg, params):
                et, _ = super().decode_reply(op, msg, cfg, params)
                return et, jnp.zeros((2,), jnp.int32)   # needs (3,)

        fs = _audit(ShortDecode())
        assert "CON203" in _rules(fs)
        assert any("decode_reply" in f.symbol for f in fs)

    def test_int32_overflow_flake_bits(self, echo_base):
        class TinyFlake(echo_base):
            flake_counter_bits = 10

        fs = _audit(TinyFlake())
        assert "CON204" in _rules(fs)
        assert any("collide" in f.message for f in fs)

    def test_trace_failure_surfaces(self, echo_base):
        class Crashes(echo_base):
            def handle(self, row, node_idx, msg, t, key, cfg, params):
                raise RuntimeError("boom")

        fs = _audit(Crashes())
        assert "CON200" in _rules(fs)


# --- schema/wire conformance rules (SCH3xx) --------------------------------

class TestSchemaRules:
    def _scan(self, src, workload="echo", required=("echo",)):
        from maelstrom_tpu.analysis.schema_lint import scan_node_source
        return scan_node_source("examples/python/fixture.py",
                                textwrap.dedent(src), workload,
                                list(required))

    def test_missing_handler_flagged(self):
        fs = self._scan("""
            from node import Node
            node = Node()
        """)
        assert _rules(fs) == {"SCH302"}

    def test_handler_present_not_flagged(self):
        fs = self._scan("""
            from node import Node
            node = Node()
            @node.on("echo")
            def echo(msg):
                node.reply(msg, {"type": "echo_ok",
                                 "echo": msg["body"]["echo"]})
        """)
        assert fs == []

    def test_loop_registration_resolved(self):
        fs = self._scan("""
            from node import Node
            node = Node()
            def client_op(msg): pass
            for t in ("read", "write", "cas"):
                node.on(t, client_op)
        """, workload="lin-kv", required=("read", "write", "cas"))
        assert fs == []

    def test_response_type_drift_flagged(self):
        fs = self._scan("""
            from node import Node
            node = Node()
            @node.on("echo")
            def echo(msg):
                node.reply(msg, {"type": "echo_okay_ok"})
        """)
        assert "SCH301" in _rules(fs)

    def test_internal_protocol_ok_not_flagged(self):
        fs = self._scan("""
            from node import Node
            node = Node()
            @node.on("echo")
            def echo(msg):
                node.reply(msg, {"type": "echo_ok"})
            @node.on("gossip")
            def gossip(msg):
                node.reply(msg, {"type": "gossip_ok"})
        """)
        assert fs == []

    def test_optional_field_subscript_flagged(self):
        fs = self._scan("""
            from node import Node
            node = Node()
            @node.on("poll")
            def poll(msg):
                offs = msg["body"]["offsets"]
                node.reply(msg, {"type": "poll_ok", "msgs": {}})
        """, workload="kafka", required=("poll",))
        assert "SCH303" in _rules(fs)

    def test_optional_field_get_not_flagged(self):
        fs = self._scan("""
            from node import Node
            node = Node()
            @node.on("poll")
            def poll(msg):
                offs = msg["body"].get("offsets") or {}
                node.reply(msg, {"type": "poll_ok", "msgs": {}})
        """, workload="kafka", required=("poll",))
        assert fs == []

    def test_unknown_error_code_flagged(self):
        from maelstrom_tpu.analysis.schema_lint import check_error_codes
        fs = check_error_codes({"examples/python/x.py": textwrap.dedent("""
            from node import RPCError
            def f(node, msg):
                node.reply_error(msg, RPCError(99, "nope"))
                node.reply(msg, {"type": "error", "code": 1001})
                node.reply_error(msg, RPCError(22, "fine"))
        """)})
        assert [f.rule for f in fs] == ["SCH304"]
        assert "99" in fs[0].message

    def test_definite_codes_conform(self):
        from maelstrom_tpu.analysis.schema_lint import check_definite_codes
        assert check_definite_codes() == []

    def test_wire_coverage_clean_on_repo(self):
        from maelstrom_tpu.analysis.schema_lint import check_wire_coverage
        assert check_wire_coverage() == []

    def test_wire_coverage_missing_type_flagged(self):
        from maelstrom_tpu.analysis.schema_lint import check_wire_coverage
        from maelstrom_tpu.core.schema import rpc, REGISTRY
        rpc("unique-ids", "reserve_lint_probe",
            "synthetic RPC with no wire lane (test only)",
            request={}, response={})
        try:
            fs = check_wire_coverage()
            assert "SCH305" in _rules(fs)
            assert any("reserve_lint_probe" in f.message for f in fs)
        finally:
            del REGISTRY["unique-ids"]["reserve_lint_probe"]


# --- baseline / findings plumbing ------------------------------------------

class TestBaseline:
    def _finding(self, rule="TRC101", path="a.py", symbol="M.tick"):
        return Finding(rule=rule, name="traced-branch", severity="error",
                       pass_name="trace", path=path, line=3,
                       symbol=symbol, message="m")

    def test_fingerprint_is_line_free(self):
        a, b = self._finding(), self._finding()
        b.line = 99
        assert a.fingerprint == b.fingerprint

    def test_match_and_stale(self):
        f = self._finding()
        bl = Baseline([BaselineEntry(f.fingerprint, "why", "accepted"),
                       BaselineEntry("TRC999:gone.py:X", "old", "accepted")])
        assert bl.match(f) is not None
        stale = bl.stale_entries()
        assert [e.fingerprint for e in stale] == ["TRC999:gone.py:X"]

    def test_render_text_mentions_stale(self):
        rep = LintReport(findings=[self._finding()],
                         stale=[BaselineEntry("TRC9:x:y", "r")],
                         files_scanned=1, passes_run=("trace",))
        text = render_text(rep, color=False)
        assert "STALE" in text and "TRC101" in text
        assert "1 error(s)" in text


# --- the raft_buggy intentional fixture ------------------------------------

class TestBuggyFixture:
    def test_linter_flags_the_fixture(self):
        """models/raft_buggy.py must trip every TRC rule family."""
        from maelstrom_tpu.analysis.trace_lint import run_trace_lint
        fs = run_trace_lint(
            REPO, paths=["maelstrom_tpu/models/raft_buggy.py"])
        got = _rules(fs)
        assert {"TRC101", "TRC102", "TRC103", "TRC104", "TRC105",
                "TRC106", "TRC107"} <= got
        assert all(f.symbol == "RaftTracedHazards.tick" for f in fs)

    def test_fixture_findings_are_expected_not_silent(self):
        """Every fixture finding is baselined as status='expected' — a
        visible, test-asserted exception, not silent acceptance."""
        from maelstrom_tpu.analysis.trace_lint import run_trace_lint
        fs = run_trace_lint(
            REPO, paths=["maelstrom_tpu/models/raft_buggy.py"])
        bl = Baseline.load()
        for f in fs:
            entry = bl.match(f)
            assert entry is not None, f.fingerprint
            assert entry.status == "expected", f.fingerprint

    def test_fixture_never_registered(self):
        from maelstrom_tpu.models.raft_buggy import (BUGGY_MODELS,
                                                     RaftTracedHazards)
        assert RaftTracedHazards not in BUGGY_MODELS.values()


# --- repo-wide smoke + CLI ---------------------------------------------------

class TestRepoWide:
    @pytest.mark.slow
    def test_repo_lint_clean_modulo_baseline(self):
        """The full three-pass run is clean given the checked-in
        baseline, and the baseline has no stale entries."""
        from maelstrom_tpu.analysis import run_lint
        report = run_lint(repo_root=REPO)
        assert report.errors() == [], [f.to_dict() for f in
                                       report.errors()]
        assert report.stale == [], [e.fingerprint for e in report.stale]

    def test_trace_and_schema_passes_clean(self):
        """The two sub-second passes are clean modulo baseline (the
        fast-tier slice of the repo-wide gate)."""
        from maelstrom_tpu.analysis import run_lint
        report = run_lint(repo_root=REPO, passes=("trace", "schema"))
        assert report.errors() == [], [f.to_dict() for f in
                                       report.errors()]

    def test_partial_run_reports_no_stale_entries(self):
        """A --pass / paths-restricted run never sees the findings that
        out-of-scope baseline entries suppress, so it must not advise
        deleting them as stale."""
        from maelstrom_tpu.analysis import run_lint
        report = run_lint(repo_root=REPO, passes=("trace",))
        assert report.stale == []
        report = run_lint(repo_root=REPO,
                          paths=["maelstrom_tpu/models/echo.py"])
        assert report.stale == []

    def test_explicit_pass_honored_with_paths(self):
        """--pass schema with file paths runs schema, not trace."""
        from maelstrom_tpu.analysis import run_lint
        report = run_lint(repo_root=REPO, passes=("schema",),
                          paths=["maelstrom_tpu/models/echo.py"])
        assert report.passes_run == ("schema",)

    def test_unreadable_path_does_not_mask_findings(self):
        from maelstrom_tpu.analysis.trace_lint import run_trace_lint
        fs = run_trace_lint(
            REPO, paths=["maelstrom_tpu/models/raft_buggy.py",
                         "does/not/exist.py"])
        rules = _rules(fs)
        assert "TRC100" in rules          # the unreadable target
        assert "TRC101" in rules          # ...without hiding real ones

    @pytest.mark.slow
    def test_cli_strict_gate(self):
        """`maelstrom lint --strict` exits 0 repo-wide (baseline on) and
        nonzero on the fixture with the baseline disabled."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        ok = subprocess.run(
            [sys.executable, "-m", "maelstrom_tpu", "lint", "--strict"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        bad = subprocess.run(
            [sys.executable, "-m", "maelstrom_tpu", "lint", "--strict",
             "--no-baseline", "--json",
             "maelstrom_tpu/models/raft_buggy.py"],
            cwd=REPO, env=env, capture_output=True, text=True)
        assert bad.returncode == 1, bad.stdout + bad.stderr
        payload = json.loads(bad.stdout)
        assert payload["summary"]["errors"] >= 6
        rules = {f["rule"] for f in payload["findings"]}
        assert {"TRC101", "TRC104", "TRC107"} <= rules
