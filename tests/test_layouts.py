"""Carry-layout equivalence: the batch-minor ("minor") tick path must be
bit-identical to the batch-lead ("lead") oracle path.

The minor layout exists purely for TPU tiling (instances on the 128-lane
axis — see runtime._make_tick_fn_minor); it re-derives every RNG key and
runs the same per-instance phase functions, so any divergence is a bug
in the composite tick, not a tolerable reordering. These tests pin that
across nemesis kinds, models, the replay (instance_ids) path, and the
chunked sharded runner.
"""

import jax
import jax.numpy as jnp
import numpy as np
import jax.tree_util as tu
import pytest

from maelstrom_tpu.models.raft import RaftModel
from maelstrom_tpu.tpu.harness import make_sim_config, resolve_layout
from maelstrom_tpu.tpu.runtime import (canonical_carry,
                                       carry_from_canonical, run_sim)

BASE_OPTS = dict(node_count=3, concurrency=6, n_instances=64,
                 record_instances=4, inbox_k=1, pool_slots=16,
                 time_limit=0.12, rate=200.0, latency=5.0,
                 rpc_timeout=1.0, nemesis=["partition"],
                 nemesis_interval=0.04, p_loss=0.05, recovery_time=0.0,
                 seed=7)


def _model():
    return RaftModel(n_nodes_hint=3, log_cap=64, heartbeat=8)


def _run(model, opts, layout, instance_ids=None):
    sim = make_sim_config(model, {**opts, "layout": layout})
    params = model.make_params(sim.net.n_nodes)
    ids = None if instance_ids is None else jnp.asarray(instance_ids,
                                                        jnp.int32)
    carry, ys = run_sim(model, sim, opts["seed"], params, ids)
    return canonical_carry(carry, sim), ys


def _assert_trees_equal(a, b):
    for (path, x), (_, y) in zip(tu.tree_flatten_with_path(a)[0],
                                 tu.tree_flatten_with_path(b)[0]):
        name = "/".join(str(p) for p in path)
        assert x.shape == y.shape, (name, x.shape, y.shape)
        assert (np.asarray(x) == np.asarray(y)).all(), name


@pytest.mark.parametrize("kind", ["random-halves", "isolated-node",
                                  "majorities-ring"])
def test_minor_layout_bit_identical(kind):
    model = _model()
    opts = {**BASE_OPTS, "nemesis_kind": kind}
    cl, yl = _run(model, opts, "lead")
    cm, ym = _run(model, opts, "minor")
    _assert_trees_equal(cl, cm)
    assert (np.asarray(yl.events) == np.asarray(ym.events)).all()
    # the run must actually exercise traffic for the comparison to mean
    # anything
    assert int(cl.stats.delivered) > 100


def test_minor_layout_inbox_k3():
    # K>1 takes the top_k (not argmax) deliver path
    model = _model()
    opts = {**BASE_OPTS, "inbox_k": 3, "pool_slots": 24}
    cl, yl = _run(model, opts, "lead")
    cm, ym = _run(model, opts, "minor")
    _assert_trees_equal(cl, cm)
    assert (np.asarray(yl.events) == np.asarray(ym.events)).all()


def test_minor_layout_replay_instance_ids():
    # the funnel replays arbitrary instance-id subsets; RNG stability
    # must hold in both layouts
    model = _model()
    ids = [3, 17, 42, 63]
    opts = {**BASE_OPTS, "n_instances": len(ids),
            "record_instances": len(ids)}
    cl, yl = _run(model, opts, "lead", instance_ids=ids)
    cm, ym = _run(model, opts, "minor", instance_ids=ids)
    _assert_trees_equal(cl, cm)
    assert (np.asarray(yl.events) == np.asarray(ym.events)).all()


def test_canonical_roundtrip():
    model = _model()
    sim = make_sim_config(model, {**BASE_OPTS, "layout": "minor"})
    params = model.make_params(sim.net.n_nodes)
    carry, _ = run_sim(model, sim, 7, params)
    back = carry_from_canonical(canonical_carry(carry, sim), sim)
    _assert_trees_equal(carry, back)
    # canonical pool really is batch-leading
    assert canonical_carry(carry, sim).pool.shape[0] == sim.n_instances
    assert carry.pool.shape[-1] == sim.n_instances


def test_resolve_layout_auto_cpu():
    # the suite runs on CPU, where auto must pick the lead layout
    assert resolve_layout("auto") == "lead"
    assert resolve_layout("minor") == "minor"
    assert resolve_layout("lead") == "lead"


def test_sharded_chunked_minor_matches_unsharded():
    # the production dispatch pattern (chunked shard_map) with the minor
    # layout inside the shard bodies, against the single-device oracle
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked,
                                             run_sim_unsharded)
    if len(jax.devices()) < 4:
        pytest.skip("needs a >=4-device virtual mesh")
    model = _model()
    opts = {**BASE_OPTS, "n_instances": 8, "record_instances": 2,
            "layout": "minor"}
    sim = make_sim_config(model, opts)
    assert sim.layout == "minor"
    mesh = make_mesh(4)
    stats_s, viol_s, ev_s = run_sim_sharded_chunked(
        model, sim, seed=7, mesh=mesh, chunk=40)
    stats_u, viol_u, ev_u = run_sim_unsharded(model, sim, seed=7,
                                              n_shards=4)
    assert tuple(int(x) for x in stats_s) == \
        tuple(int(x) for x in stats_u)
    assert (viol_s == viol_u).all()
    assert (ev_s == ev_u).all()
