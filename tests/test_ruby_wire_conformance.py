"""Runtime-independent wire conformance for the Ruby SDK + nodes.

No Ruby interpreter exists in this image, so — like the JS and Go
suites — the sources are validated STATICALLY against the wire
protocol and the schema registry: envelope shape, init handshake,
in_reply_to plumbing, error-code catalog membership, and every
client-facing reply type a node emits. The e2e suite
(test_ruby_nodes.py) runs whenever a `ruby` binary appears."""

import os
import re

import pytest

from wire_conformance_common import (assert_error_codes_in_catalog,
                                     assert_node_reply_types)

RB_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "ruby")

SDK = open(os.path.join(RB_DIR, "maelstrom.rb")).read()

NODES = {
    "echo.rb": ("echo", set()),
    "broadcast.rb": ("broadcast", {"gossip"}),
    "g_set.rb": ("g-set", {"merge"}),
    "counter.rb": ("g-counter", set()),
}


def _literal_types(src):
    return set(re.findall(r'"type"\s*=>\s*"([a-z_]+)"', src))


def test_sdk_envelope_shape():
    assert '"src" => @node_id' in SDK and '"dest" => dest' in SDK \
        and '"body" => body' in SDK
    assert '"in_reply_to"' in SDK and '"msg_id"' in SDK


def test_sdk_init_handshake():
    assert '"init_ok"' in SDK
    assert '"node_id"' in SDK and '"node_ids"' in SDK


def test_sdk_error_codes_in_catalog():
    codes = {int(c) for c in re.findall(
        r"^\s+[A-Z_]+ = (\d+)$", SDK, re.M)}
    assert_error_codes_in_catalog(codes)


def test_kv_client_speaks_service_schema():
    for field in ('"type" => "read"', '"type" => "write"',
                  '"type" => "cas"', '"key"', '"value"', '"from"',
                  '"to"', '"create_if_not_exists"'):
        assert field in SDK, field
    assert '"lin-kv"' in SDK and '"seq-kv"' in SDK and '"lww-kv"' in SDK


@pytest.mark.parametrize("name", sorted(NODES))
def test_node_reply_types_in_registry(name):
    namespace, internal = NODES[name]
    src = open(os.path.join(RB_DIR, name)).read()
    emitted = _literal_types(src)
    assert_node_reply_types(namespace, internal, emitted, name)
