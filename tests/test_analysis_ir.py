"""IR-level lint + cost-model tests (analysis/ir_lint.py, cost_model.py).

Pins the PR's acceptance bars: each planted IR-hazard fixture trips its
JXP rule, a donate-without-aliasing regression trips JXP403 while the
REAL compiled pipeline/mesh executors verify clean, cost-baseline
drift detection fires COST501/502/503 on synthetic drift, the baseline
covers every registered model x both carry layouts, and the repo-wide
``maelstrom lint --ir --cost --strict`` gate is green modulo the
expected-fixture baseline entries.
"""

import json
import os
from functools import partial

import jax
import jax.numpy as jnp
import pytest

from maelstrom_tpu.analysis import cost_model, run_lint
from maelstrom_tpu.analysis.findings import Baseline
from maelstrom_tpu.analysis.ir_lint import (aliased_params_of,
                                            audit_donation,
                                            audit_model_ir,
                                            audit_pipeline_donation,
                                            compare_costs, run_ir_lint)
from maelstrom_tpu.models.ir_hazards import (IR_FIXTURE_MODELS,
                                             IrBakedConst, IrFloatLeak,
                                             IrFusionBreaker,
                                             IrHostCallback)

pytestmark = pytest.mark.ir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


@pytest.fixture(scope="module")
def repo_report():
    """ONE repo-wide ir+cost run (the expensive part: every registered
    model x both layouts traced, the real executors compiled), shared
    by every gate-level assertion below."""
    return run_lint(repo_root=REPO, passes=("ir", "cost"))


# --- the planted fixtures trip their rules ---------------------------------


class TestFixturesTrip:
    def test_float_leak_trips_jxp401(self):
        fs, report = audit_model_ir(IrFloatLeak(), 2, "lead")
        assert _rules(fs) == {"JXP401"}
        (f,) = fs
        assert f.severity == "error"
        assert "drift" in f.message and "float32" in f.message
        assert report is not None and report.eqns > 0

    def test_host_callback_trips_jxp402(self):
        fs, _ = audit_model_ir(IrHostCallback(), 2, "lead")
        assert _rules(fs) == {"JXP402"}
        assert "pure_callback" in fs[0].message

    def test_fusion_breaker_trips_jxp404(self):
        fs, report = audit_model_ir(IrFusionBreaker(), 2, "lead")
        assert _rules(fs) == {"JXP404"}
        msgs = " ".join(f.message for f in fs)
        # both planted breakers: the while_loop AND the oversized
        # broadcast intermediate
        assert "while_loop" in msgs and "broadcast_in_dim" in msgs
        assert all(f.severity == "warning" for f in fs)
        assert report.max_broadcast_bytes >= 2 << 20

    def test_baked_const_trips_jxp405(self):
        fs, report = audit_model_ir(IrBakedConst(), 2, "lead")
        assert _rules(fs) == {"JXP405"}
        assert report.max_const_bytes >= 128 << 10

    def test_loop_budget_exceeded_is_an_error(self):
        """Per-model JXP404 budgets: a per-slot-scan tick audited
        under a zero loop budget is an ERROR naming the budget — the
        gate a re-introduced sequential scan would hit on the fused
        raft family (whose legacy scan formulation is deleted;
        models/raft.py) — while the same tick under a budget covering
        its loops stays clean. Echo still runs the legacy per-slot
        driver, so its tick legally carries exactly that loop."""
        from maelstrom_tpu.models.echo import EchoModel

        looped = EchoModel()
        assert not getattr(looped, "fused_node", False)
        fs, report = audit_model_ir(looped, 2, "lead", loop_budget=0)
        budget_fs = [f for f in fs if "budget" in f.message]
        assert budget_fs and all(f.rule == "JXP404"
                                 and f.severity == "error"
                                 for f in budget_fs)
        assert report.loops > 0

        fs_ok, _ = audit_model_ir(looped, 2, "lead",
                                  loop_budget=report.loops)
        assert not [f for f in fs_ok if "budget" in f.message]

    def test_fused_raft_family_has_zero_loops(self):
        """The fused models hold the budget they pin: zero
        fusion-breaking loops in the whole tick, both layouts."""
        from maelstrom_tpu.models.raft import RaftModel

        for layout in ("lead", "minor"):
            fs, report = audit_model_ir(RaftModel(n_nodes_hint=3), 3,
                                        layout, loop_budget=0)
            assert "JXP404" not in _rules(fs)
            assert report.loops == 0

    def test_registered_models_do_not_trip(self):
        """The fixtures' rules must not fire on the honest models —
        the audit's false-positive guard (echo + the raft flagship)."""
        from maelstrom_tpu.models import get_model
        for wl, n in (("echo", 2), ("lin-kv", 5)):
            for layout in ("lead", "minor"):
                fs, report = audit_model_ir(get_model(wl, n), n, layout)
                assert fs == [], [f.to_dict() for f in fs]
                assert report.eqns > 0
                # phase decomposition covers the named scopes
                assert set(cost_model.PHASES) <= set(report.phases)

    def test_fixtures_never_registered(self):
        from maelstrom_tpu.models import get_model
        for kind in IR_FIXTURE_MODELS:
            with pytest.raises(ValueError):
                get_model(f"echo-ir-{kind}", 2)

    def test_fixture_findings_are_expected_not_silent(self):
        """Every fixture finding is baselined as status='expected' — a
        visible, test-asserted exception, not silent acceptance."""
        bl = Baseline.load()
        for kind, cls in IR_FIXTURE_MODELS.items():
            fs, _ = audit_model_ir(cls(), 2, "lead",
                                   label=f"fixture-{kind}")
            assert fs, kind
            for f in fs:
                entry = bl.match(f)
                assert entry is not None, f.fingerprint
                assert entry.status == "expected", f.fingerprint


# --- JXP403: donation aliasing ---------------------------------------------


class TestDonationAliasing:
    def test_planted_regression_trips_jxp403(self):
        """A donate_argnums function whose donated input cannot alias
        (shape/dtype drift between input and outputs) must be flagged —
        XLA drops the donation silently, the audit must not."""
        @partial(jax.jit, donate_argnums=(0,))
        def broken(carry, t):
            a, b = carry
            # neither output matches a donated input buffer
            return (a.astype(jnp.float32).astype(jnp.int32).reshape(2, 8),
                    b[:4]), jnp.sum(a) + t

        args = ((jax.ShapeDtypeStruct((4, 4), jnp.int32),
                 jax.ShapeDtypeStruct((8,), jnp.int32)),
                jax.ShapeDtypeStruct((), jnp.int32))
        fs = audit_donation(broken, args, 2, path="tests/planted.py",
                            symbol="broken", label="planted")
        assert _rules(fs) == {"JXP403"}
        assert any("NOT aliased" in f.message for f in fs)

    def test_clean_donation_passes(self):
        @partial(jax.jit, donate_argnums=(0,))
        def clean(carry, t):
            a, b = carry
            return (a + t, b * 2), jnp.sum(a)

        args = ((jax.ShapeDtypeStruct((4, 4), jnp.int32),
                 jax.ShapeDtypeStruct((8,), jnp.int32)),
                jax.ShapeDtypeStruct((), jnp.int32))
        assert audit_donation(clean, args, 2, path="t.py", symbol="c",
                              label="clean") == []

    def test_alias_parser_handles_nested_braces(self):
        txt = ("HloModule jit_f, is_scheduled=true, input_output_alias="
               "{ {0}: (0, {}, may-alias), {1}: (3, {}, may-alias) }, "
               "entry_computation_layout={(s32[4]{0})->s32[4]{0}}")
        assert aliased_params_of(txt) == {0, 3}
        assert aliased_params_of("HloModule jit_g") == set()

    def test_real_pipeline_executable_aliases_every_carry_leaf(self):
        """JXP403 on the ACTUAL make_chunk_fn product: the executable
        run_sim_pipelined dispatches must alias the full donated
        carry. (The repo-wide fixture covers both layouts + the mesh
        executor; this pins the single-device path directly.)"""
        assert audit_pipeline_donation(layouts=("lead",)) == []


# --- the cost gate ---------------------------------------------------------


def _fake_report(eqns=1000, hbm=500000):
    return cost_model.CostReport(eqns=eqns, hbm_bytes=hbm,
                                 phases={"node_phase": eqns // 2})


class TestCostGate:
    PATHS = {"echo/n=2/lead": ("maelstrom_tpu/models/echo.py",
                               "EchoModel")}

    def test_regression_trips_cost501(self):
        live = {"echo/n=2/lead": _fake_report(eqns=1200)}
        base = {"tolerance": 0.10,
                "entries": {"echo/n=2/lead": {"eqns": 1000,
                                              "hbm-bytes-per-tick":
                                                  500000}}}
        fs = compare_costs(live, base, self.PATHS)
        assert _rules(fs) == {"COST501"}
        assert fs[0].severity == "error"
        assert "+20%" in fs[0].message

    def test_within_tolerance_is_clean(self):
        live = {"echo/n=2/lead": _fake_report(eqns=1050)}
        base = {"tolerance": 0.10,
                "entries": {"echo/n=2/lead": {"eqns": 1000,
                                              "hbm-bytes-per-tick":
                                                  500000}}}
        assert compare_costs(live, base, self.PATHS) == []

    def test_missing_entry_trips_cost502(self):
        fs = compare_costs({"echo/n=2/lead": _fake_report()},
                           {"tolerance": 0.10, "entries": {}},
                           self.PATHS)
        assert _rules(fs) == {"COST502"}

    def test_stale_entry_trips_cost503_only_on_full_universe(self):
        base = {"tolerance": 0.10,
                "entries": {"gone/n=9/lead": {"eqns": 5,
                                              "hbm-bytes-per-tick": 5}}}
        fs = compare_costs({}, base, {}, full_universe=True)
        assert _rules(fs) == {"COST503"}
        assert fs[0].severity == "warning"
        assert compare_costs({}, base, {}, full_universe=False) == []

    def test_improvement_trips_cost504_info(self):
        live = {"echo/n=2/lead": _fake_report(eqns=700, hbm=400000)}
        base = {"tolerance": 0.10,
                "entries": {"echo/n=2/lead": {"eqns": 1000,
                                              "hbm-bytes-per-tick":
                                                  500000}}}
        fs = compare_costs(live, base, self.PATHS)
        assert _rules(fs) == {"COST504"}
        assert fs[0].severity == "info"

    def test_checked_in_baseline_covers_every_model_both_layouts(self):
        data = json.load(open(cost_model.DEFAULT_COST_BASELINE))
        want = {cost_model.entry_key(wl, n, layout)
                for wl, n in cost_model.cost_specs()
                for layout in cost_model.AUDIT_LAYOUTS}
        assert set(data["entries"]) == want
        for key, e in data["entries"].items():
            assert e["eqns"] > 0 and e["hbm-bytes-per-tick"] > 0, key
            assert e["phases"], key

    def test_update_baseline_roundtrip(self, tmp_path):
        """--update-baseline writes a baseline the very next cost run
        is clean against (drift detection pinned end-to-end on a real
        trace)."""
        path = str(tmp_path / "cost_baseline.json")
        fs = run_ir_lint(hazards=False, cost=True,
                         workloads=[("echo", 2)], layouts=("lead",),
                         cost_baseline_path=path, update_baseline=True)
        assert _rules(fs) == {"COST500"}
        assert os.path.exists(path)
        fs = run_ir_lint(hazards=False, cost=True,
                         workloads=[("echo", 2)], layouts=("lead",),
                         cost_baseline_path=path)
        assert fs == [], [f.to_dict() for f in fs]
        # ...and a synthetic 2x bloat against that same baseline fails
        data = json.load(open(path))
        key = "echo/n=2/lead"
        data["entries"][key]["eqns"] //= 2
        json.dump(data, open(path, "w"))
        fs = run_ir_lint(hazards=False, cost=True,
                         workloads=[("echo", 2)], layouts=("lead",),
                         cost_baseline_path=path)
        assert _rules(fs) == {"COST501"}


# --- the cost model itself -------------------------------------------------


class TestCostModel:
    def test_tick_cost_is_deterministic_and_layout_aware(self):
        from maelstrom_tpu.models import get_model
        model = get_model("echo", 2)
        sim_l = cost_model.audit_sim(model, 2, "lead")
        sim_m = cost_model.audit_sim(model, 2, "minor")
        a = cost_model.tick_cost(model, sim_l)
        b = cost_model.tick_cost(model, sim_l)
        assert (a.eqns, a.hbm_bytes, a.phases) == \
            (b.eqns, b.hbm_bytes, b.phases)
        c = cost_model.tick_cost(model, sim_m)
        # the two layouts lower to (slightly) different graphs — both
        # are budgeted separately
        assert c.eqns != a.eqns
        assert sum(a.phases.values()) == a.eqns

    def test_scan_body_bytes_are_trip_weighted(self):
        """A scan body's intermediates are charged per trip — the HBM
        estimate must scale with the trip count."""
        def f(x):
            return jax.lax.scan(lambda c, _: (c * 2 + 1, None), x,
                                None, length=10)[0]

        def g(x):
            return jax.lax.scan(lambda c, _: (c * 2 + 1, None), x,
                                None, length=100)[0]

        x = jax.ShapeDtypeStruct((128,), jnp.int32)
        cf = cost_model.cost_of_jaxpr(jax.make_jaxpr(f)(x))
        cg = cost_model.cost_of_jaxpr(jax.make_jaxpr(g)(x))
        assert cf.eqns == cg.eqns          # static graph size is equal
        assert cg.hbm_bytes > cf.hbm_bytes * 5

    def test_loops_count_only_surviving_whiles(self):
        """The ``loops`` (fusion-breakers) metric: a plain scan and a
        while_loop each count once; a fully unrolled scan lowers
        while-free and counts zero."""
        def scanned(x):
            return jax.lax.scan(lambda c, _: (c + 1, None), x, None,
                                length=8)[0]

        def unrolled(x):
            return jax.lax.scan(lambda c, _: (c + 1, None), x, None,
                                length=8, unroll=True)[0]

        def whiled(x):
            return jax.lax.while_loop(lambda c: c[0] < 8,
                                      lambda c: (c[0] + 1, c[1] * 2), x)

        x = jax.ShapeDtypeStruct((), jnp.int32)
        assert cost_model.cost_of_jaxpr(
            jax.make_jaxpr(scanned)(x)).loops == 1
        assert cost_model.cost_of_jaxpr(
            jax.make_jaxpr(unrolled)(x)).loops == 0
        assert cost_model.cost_of_jaxpr(
            jax.make_jaxpr(whiled)((x, x))).loops == 1

    def test_hlo_exec_stats_parses_entry_and_while_bodies(self):
        """ir_thunks = entry instructions + while body/condition
        instructions, with while regions resolved from the while op's
        attributes (names are XLA-version noise), fusion-internal
        instructions excluded."""
        hlo = "\n".join([
            "HloModule m",
            "",
            "%fused_computation.1 (p: s32[4]) -> s32[4] {",
            "  %p = s32[4]{0} parameter(0)",
            "  ROOT %a = s32[4]{0} add(%p, %p)",
            "}",
            "",
            "%region_7.12 (c: (s32[], s32[4])) -> (s32[], s32[4]) {",
            "  %c = (s32[], s32[4]{0}) parameter(0)",
            "  %i = s32[] get-tuple-element(%c), index=0",
            "  ROOT %t = (s32[], s32[4]{0}) tuple(%i, %i)",
            "}",
            "",
            "%region_8.13 (c: (s32[], s32[4])) -> pred[] {",
            "  %c = (s32[], s32[4]{0}) parameter(0)",
            "  ROOT %lt = pred[] compare(%c, %c), direction=LT",
            "}",
            "",
            "ENTRY %main.20 (a: s32[4]) -> s32[4] {",
            "  %a = s32[4]{0} parameter(0)",
            "  %f = s32[4]{0} fusion(%a), kind=kLoop, "
            "calls=%fused_computation.1",
            "  %w = (s32[], s32[4]{0}) while((s32[], s32[4]{0}) %f), "
            "condition=%region_8.13, body=%region_7.12",
            "  ROOT %r = s32[4]{0} get-tuple-element(%w), index=1",
            "}",
        ])
        st = cost_model.hlo_exec_stats(hlo)
        # entry: 4 instrs; while body: 3; while cond: 2; the fusion's
        # 2 internal instrs excluded from thunks, included in the total
        assert st == {"ir_thunks": 9, "hlo_instructions": 11,
                      "while_loops": 1}


# --- repo-wide gate --------------------------------------------------------


class TestRepoWideGate:
    def test_ir_cost_gate_green_modulo_expected_fixtures(self,
                                                         repo_report):
        """The acceptance bar: `maelstrom lint --ir --cost --strict`
        repo-wide finds no unsuppressed errors, every fixture finding
        is suppressed as expected, and no stale entries surface."""
        assert repo_report.errors() == [], [
            f.to_dict() for f in repo_report.errors()]
        suppressed_rules = {f.rule for f, _ in repo_report.suppressed}
        assert {"JXP401", "JXP402", "JXP404",
                "JXP405"} <= suppressed_rules
        assert all(e.status == "expected"
                   for f, e in repo_report.suppressed
                   if f.rule.startswith("JXP"))
        assert repo_report.passes_run == ("ir", "cost")

    def test_gate_saw_the_compiled_executors(self, repo_report):
        """JXP403 verdicts come from the compiled pipeline/mesh
        executables; a clean gate means the audit ran and aliased —
        the rule must not appear as a finding OR a suppression."""
        all_rules = (_rules(repo_report.findings)
                     | {f.rule for f, _ in repo_report.suppressed})
        assert "JXP403" not in all_rules
        assert "JXP400" not in all_rules      # every model lowered
