"""EDN history export — the Elle/Knossos adjudication escape hatch
(SURVEY §7 / VERDICT r2 next #5): every stored history must round-trip
through the Jepsen-compatible EDN op-map form losslessly, including
mutant-generated anomaly histories, so a disputed in-repo verdict can be
re-checked by the stock JVM checkers outside this image."""

import glob
import json
import os

import pytest

from maelstrom_tpu.cli import main as cli_main
from maelstrom_tpu.utils.edn import (Keyword, dumps, edn_map_to_op,
                                     history_to_edn_lines, loads,
                                     op_to_edn_map)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_edn_scalar_roundtrip():
    for v in [None, True, False, 0, -7, 3.5, "plain",
              'quo"te\\back\nnl', Keyword("append"),
              [1, [2, None], {"k": Keyword("r")}],
              {Keyword("f"): Keyword("txn"), "s": [1, 2]}]:
        assert loads(dumps(v)) == v


def test_edn_emits_jepsen_shapes():
    op = {"process": 7, "type": "invoke", "f": "txn",
          "value": [["append", 4, 1], ["r", 5, None]],
          "index": 0, "time": 123}
    line = dumps(op_to_edn_map(op, "txn-list-append"))
    assert line == ('{:process 7, :type :invoke, :f :txn, '
                    ':value [[:append 4 1] [:r 5 nil]], '
                    ':index 0, :time 123}'), line


def _roundtrip(records, workload):
    for op in records:
        line = dumps(op_to_edn_map(op, workload))
        back = edn_map_to_op(loads(line))
        # strict equality after JSON normalization (tuples/keywords out)
        assert json.loads(json.dumps(back)) == op, (op, line)


@pytest.mark.parametrize("run_dir", sorted(
    glob.glob(os.path.join(REPO, "store", "*", "latest"))))
def test_stored_histories_roundtrip(run_dir):
    workload = os.path.basename(os.path.dirname(run_dir))
    if workload.endswith("-tpu"):
        workload = workload[:-len("-tpu")]
    for p in sorted(glob.glob(os.path.join(run_dir, "history*.jsonl"))):
        records = [json.loads(l) for l in open(p) if l.strip()]
        assert records, p
        _roundtrip(records, workload)


@pytest.mark.slow
def test_mutant_anomaly_history_roundtrips(tmp_path):
    """An anomaly history from the bug-injection corpus (stale-read
    mutant under partitions) exports and round-trips; the checker's
    verdict on the re-imported history is unchanged."""
    from maelstrom_tpu.models.raft_buggy import RaftStaleRead
    from maelstrom_tpu.tpu.harness import run_tpu_test

    res = run_tpu_test(RaftStaleRead(n_nodes_hint=3), dict(
        node_count=3, concurrency=3, n_instances=24,
        record_instances=24, time_limit=2.5, rate=40.0, latency=10.0,
        rpc_timeout=0.8, nemesis=["partition"], nemesis_interval=0.25,
        p_loss=0.05, recovery_time=0.3, seed=2,
        store_root=str(tmp_path)))
    assert res["valid?"] is False   # the mutant is caught
    run_dir = os.path.join(str(tmp_path), "lin-kv-bug-stale-read-tpu",
                           "latest")
    paths = sorted(glob.glob(os.path.join(run_dir, "history*.jsonl")))
    assert paths
    total = 0
    for p in paths:
        records = [json.loads(l) for l in open(p) if l.strip()]
        total += len(records)
        _roundtrip(records, "lin-kv-bug-stale-read")
    assert total > 50


def test_cli_export_roundtrip(tmp_path, capsys):
    """Default export is one EDN vector per file (the history.edn shape
    — ADVICE r3 #1: a stock read-string must see the whole history, not
    just the first op). Self-provisions its store run (a quick TPU
    txn-list-append sim) instead of assuming a pre-existing artifact —
    the seed tree shipped without one and the test failed on fresh
    checkouts."""
    from maelstrom_tpu.models.txn_raft import TxnListAppendModel
    from maelstrom_tpu.tpu.harness import run_tpu_test

    store_root = str(tmp_path / "store")
    run_tpu_test(TxnListAppendModel(n_nodes_hint=1),
                 dict(node_count=1, concurrency=2, time_limit=1.0,
                      rate=50.0, latency=2.0, n_instances=2,
                      record_instances=2, seed=7,
                      store_root=store_root))
    src = os.path.join(store_root, "txn-list-append-tpu", "latest")
    out = str(tmp_path / "out")
    rc = cli_main(["export", src, "-o", out])
    assert rc == 0
    edn_files = sorted(glob.glob(os.path.join(out, "history*.edn")))
    assert edn_files
    jsonl = sorted(glob.glob(os.path.join(src, "history*.jsonl")))
    for ep, jp in zip(edn_files, jsonl):
        records = [json.loads(l) for l in open(jp) if l.strip()]
        whole = loads(open(ep).read())     # single read of the file
        assert isinstance(whole, list)
        assert len(whole) == len(records)
        for m, op in zip(whole, records):
            assert m[Keyword("type")] in ("invoke", "ok", "fail", "info")
            assert json.loads(json.dumps(edn_map_to_op(m))) == op


@pytest.fixture(scope="module")
def lin_kv_store(tmp_path_factory):
    """Self-provisioned store/lin-kv-tpu run for the stdout export
    tests (ROADMAP residual fragility from PR 1: these used to read the
    untracked store/lin-kv/latest artifact and failed on any checkout
    where it was never generated)."""
    from maelstrom_tpu.models import get_model
    from maelstrom_tpu.tpu.harness import run_tpu_test

    root = str(tmp_path_factory.mktemp("edn-store"))
    # ONE recorded instance: the stdout-vector export refuses multi-shard
    # runs (concatenated vectors are not one readable EDN form)
    run_tpu_test(get_model("lin-kv", 3, "grid"), dict(
        node_count=3, concurrency=2, time_limit=0.6, rate=60.0,
        latency=5.0, n_instances=2, record_instances=1, seed=11,
        store_root=root))
    return os.path.join(root, "lin-kv-tpu", "latest")


def test_cli_export_stdout_maps(lin_kv_store, capsys):
    rc = cli_main(["export", lin_kv_store, "-o", "-", "--maps"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.strip()]
    assert lines and all(l.startswith("{:") for l in lines)


def test_cli_export_stdout_vector(lin_kv_store, capsys):
    rc = cli_main(["export", lin_kv_store, "-o", "-"])
    assert rc == 0
    out = capsys.readouterr().out
    whole = loads(out)
    assert isinstance(whole, list) and whole
    assert all(Keyword("type") in m for m in whole)


# --- golden fixtures: genuine Jepsen-produced history.edn lines ----------
#
# Literal op maps printed by real Jepsen runs in the reference's guide
# (so the bridge is validated against actual JVM output, not just its
# own writer): /root/reference/doc/05-datomic/02-shared-state.md:385-386
# (grep of store/latest/history.edn) and the :last-op map of
# /root/reference/doc/06-raft/01-key-value.md:148-152.
JEPSEN_GOLDEN = [
    ('{:type :info, :f :txn, :value [[:append 9 11] [:append 6 3]], '
     ':time 5246977350, :process 0, :error :net-timeout, :index 1043}',
     "txn-list-append"),
    ('{:type :info, :f :txn, :value [[:r 40 nil] [:append 40 13]], '
     ':time 10293060397, :process 1, :error :net-timeout, :index 2025}',
     "txn-list-append"),
    ('{:process 1, :type :ok, :f :cas, :value [2 3], :index 85, '
     ':time 9787361454}',
     "lin-kv"),
]


@pytest.mark.parametrize("line,workload", JEPSEN_GOLDEN)
def test_genuine_jepsen_history_roundtrips(line, workload):
    """Parse a genuine Jepsen history.edn op, convert through the JSON
    bridge both ways, and require the re-exported EDN to parse to the
    IDENTICAL structure — a silent format mismatch here would void the
    stock-Elle/Knossos adjudication story (VERDICT r3 missing #6)."""
    parsed = loads(line)
    op = edn_map_to_op(parsed)
    # the JSON form is plain-JSON serializable (what history.jsonl holds)
    op = json.loads(json.dumps(op))
    re_exported = dumps(op_to_edn_map(op, workload))
    assert loads(re_exported) == parsed
    # keyword positions survived: micro-op tags and error tags
    for k in (Keyword("type"), Keyword("f")):
        assert isinstance(loads(re_exported)[k], Keyword)


def test_golden_nonfinite_floats():
    assert dumps(float("inf")) == "##Inf"
    assert dumps(float("-inf")) == "##-Inf"
    assert dumps(float("nan")) == "##NaN"
    assert loads("##Inf") == float("inf")
    assert loads("[##NaN]")[0] != loads("[##NaN]")[0]


def test_null_f_stays_nil():
    m = op_to_edn_map({"type": "info", "f": None, "value": None}, "lin-kv")
    assert m[Keyword("f")] is None
    assert dumps(m) == "{:type :info, :f nil, :value nil}"
