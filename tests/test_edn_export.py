"""EDN history export — the Elle/Knossos adjudication escape hatch
(SURVEY §7 / VERDICT r2 next #5): every stored history must round-trip
through the Jepsen-compatible EDN op-map form losslessly, including
mutant-generated anomaly histories, so a disputed in-repo verdict can be
re-checked by the stock JVM checkers outside this image."""

import glob
import json
import os

import pytest

from maelstrom_tpu.cli import main as cli_main
from maelstrom_tpu.utils.edn import (Keyword, dumps, edn_map_to_op,
                                     history_to_edn_lines, loads,
                                     op_to_edn_map)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_edn_scalar_roundtrip():
    for v in [None, True, False, 0, -7, 3.5, "plain",
              'quo"te\\back\nnl', Keyword("append"),
              [1, [2, None], {"k": Keyword("r")}],
              {Keyword("f"): Keyword("txn"), "s": [1, 2]}]:
        assert loads(dumps(v)) == v


def test_edn_emits_jepsen_shapes():
    op = {"process": 7, "type": "invoke", "f": "txn",
          "value": [["append", 4, 1], ["r", 5, None]],
          "index": 0, "time": 123}
    line = dumps(op_to_edn_map(op, "txn-list-append"))
    assert line == ('{:process 7, :type :invoke, :f :txn, '
                    ':value [[:append 4 1] [:r 5 nil]], '
                    ':index 0, :time 123}'), line


def _roundtrip(records, workload):
    for op in records:
        line = dumps(op_to_edn_map(op, workload))
        back = edn_map_to_op(loads(line))
        # strict equality after JSON normalization (tuples/keywords out)
        assert json.loads(json.dumps(back)) == op, (op, line)


@pytest.mark.parametrize("run_dir", sorted(
    glob.glob(os.path.join(REPO, "store", "*", "latest"))))
def test_stored_histories_roundtrip(run_dir):
    workload = os.path.basename(os.path.dirname(run_dir))
    if workload.endswith("-tpu"):
        workload = workload[:-len("-tpu")]
    for p in sorted(glob.glob(os.path.join(run_dir, "history*.jsonl"))):
        records = [json.loads(l) for l in open(p) if l.strip()]
        assert records, p
        _roundtrip(records, workload)


def test_mutant_anomaly_history_roundtrips(tmp_path):
    """An anomaly history from the bug-injection corpus (stale-read
    mutant under partitions) exports and round-trips; the checker's
    verdict on the re-imported history is unchanged."""
    from maelstrom_tpu.models.raft_buggy import RaftStaleRead
    from maelstrom_tpu.tpu.harness import run_tpu_test

    res = run_tpu_test(RaftStaleRead(n_nodes_hint=3), dict(
        node_count=3, concurrency=3, n_instances=24,
        record_instances=24, time_limit=2.5, rate=40.0, latency=10.0,
        rpc_timeout=0.8, nemesis=["partition"], nemesis_interval=0.25,
        p_loss=0.05, recovery_time=0.3, seed=2,
        store_root=str(tmp_path)))
    assert res["valid?"] is False   # the mutant is caught
    run_dir = os.path.join(str(tmp_path), "lin-kv-bug-stale-read-tpu",
                           "latest")
    paths = sorted(glob.glob(os.path.join(run_dir, "history*.jsonl")))
    assert paths
    total = 0
    for p in paths:
        records = [json.loads(l) for l in open(p) if l.strip()]
        total += len(records)
        _roundtrip(records, "lin-kv-bug-stale-read")
    assert total > 50


def test_cli_export_roundtrip(tmp_path, capsys):
    src = os.path.join(REPO, "store", "txn-list-append", "latest")
    out = str(tmp_path / "out")
    rc = cli_main(["export", src, "-o", out])
    assert rc == 0
    edn_files = sorted(glob.glob(os.path.join(out, "history*.edn")))
    assert edn_files
    jsonl = sorted(glob.glob(os.path.join(src, "history*.jsonl")))
    for ep, jp in zip(edn_files, jsonl):
        records = [json.loads(l) for l in open(jp) if l.strip()]
        lines = [l for l in open(ep).read().splitlines() if l.strip()]
        assert len(lines) == len(records)
        for line, op in zip(lines, records):
            m = loads(line)
            assert m[Keyword("type")] in ("invoke", "ok", "fail", "info")
            assert json.loads(json.dumps(edn_map_to_op(m))) == op


def test_cli_export_stdout(capsys):
    src = os.path.join(REPO, "store", "lin-kv", "latest")
    rc = cli_main(["export", src, "-o", "-"])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.strip()]
    assert lines and all(l.startswith("{:") for l in lines)
