"""End-to-end runs of the Ruby example nodes through the process
runtime. Skips cleanly when no `ruby` interpreter is present (this
image ships none — the static wire conformance in
test_ruby_wire_conformance.py still runs)."""

import os
import shutil

import pytest

from maelstrom_tpu import run_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RB = os.path.join(REPO, "examples", "ruby")

pytestmark = pytest.mark.skipif(
    shutil.which("ruby") is None, reason="no Ruby interpreter in image")


def _bin(name):
    return dict(bin="ruby", bin_args=[os.path.join(RB, name)])


def test_ruby_echo_e2e(tmp_path):
    res = run_test("echo", dict(
        **_bin("echo.rb"), node_count=2, time_limit=3.0, rate=20.0,
        concurrency=4, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_ruby_broadcast_partition_e2e(tmp_path):
    res = run_test("broadcast", dict(
        **_bin("broadcast.rb"), node_count=3, time_limit=6.0,
        rate=20.0, concurrency=4, nemesis=["partition"],
        nemesis_interval=2.0, recovery_time=3.0,
        store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_ruby_counter_seq_kv_e2e(tmp_path):
    res = run_test("g-counter", dict(
        **_bin("counter.rb"), node_count=2, time_limit=5.0, rate=10.0,
        concurrency=4, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True
