"""Sequential-wrapper staleness semantics, pinned directly in memory
(VERDICT r4 next #7).

The reference pins these properties purely in-memory
(test/maelstrom/service_test.clj:6-53): a FRESH client may read a stale
state; a write forces recency for the writer; repeated reads converge
to (and never leave) the newest state; and every client observes a
per-client-monotonic sequence. The seq-kv counter demo exercises the
wrapper end-to-end, but only a unit test can *prove* an actually-stale
read happened — the assertion here fails if no seed in the search
window produces one.
"""

import pytest

from maelstrom_tpu.core.errors import RPCError
from maelstrom_tpu.runtime.services import PersistentKV, Sequential


def _read(svc, client, key="x"):
    return svc.handle(client, {"type": "read", "key": key,
                               "msg_id": 1})["value"]


def _write(svc, client, value, key="x"):
    svc.handle(client, {"type": "write", "key": key, "value": value,
                        "msg_id": 1})


def _loaded_service(seed, n_writes=10):
    """A wrapper whose ring holds states x=0..n_writes-1, all written by
    one writer client."""
    svc = Sequential(PersistentKV(), seed=seed)
    for v in range(n_writes):
        _write(svc, "writer", v)
    return svc


def test_fresh_client_reads_actually_stale_state():
    # a fresh client's watermark starts at the ring base, so its first
    # read may land on ANY retained state. Demand a seed that serves a
    # genuinely stale value — if the wrapper always returned the newest
    # state (i.e. degenerated into linearizable), this loop exhausts.
    for seed in range(50):
        svc = _loaded_service(seed)
        v = _read(svc, "fresh-reader")
        assert 0 <= v <= 9
        if v < 9:
            return  # actually-stale read observed
    pytest.fail("no seed in 0..49 produced a stale read — Sequential "
                "is serving only the newest state")


def test_fresh_client_can_see_pre_key_state():
    # the oldest retained state predates the key entirely; a fresh
    # client landing there gets key-does-not-exist — legal staleness
    # (the reference's fresh-client semantics, service.clj:161-177)
    hit = False
    for seed in range(200):
        svc = Sequential(PersistentKV(), seed=seed)
        _write(svc, "writer", 1)
        try:
            _read(svc, f"fresh-{seed}")
        except RPCError as e:
            assert e.code == 20  # key-does-not-exist
            hit = True
            break
    assert hit, "no fresh client ever saw the pre-write state"


def test_reads_are_per_client_monotonic():
    # watermarks only advance: the value sequence one client observes
    # never goes backwards, across interleaved writer progress
    svc = _loaded_service(seed=3, n_writes=5)
    seen = []
    for v in range(5, 10):
        seen.append(_read(svc, "reader"))
        _write(svc, "writer", v)
    seen.append(_read(svc, "reader"))
    assert seen == sorted(seen), seen


def test_write_forces_recency_for_writer():
    # after a client writes, its watermark is the newest state: its own
    # read MUST observe its write (read-your-writes), every seed
    for seed in range(20):
        svc = _loaded_service(seed)
        _write(svc, "c2", 99)
        assert _read(svc, "c2") == 99


def test_repeated_reads_converge_and_stay():
    # reads advance the watermark toward newest and never regress: once
    # a client has seen the newest state it can't see anything older
    svc = _loaded_service(seed=11)
    vals = [_read(svc, "r") for _ in range(200)]
    assert vals == sorted(vals)
    assert vals[-1] == 9, "200 reads never converged to the newest state"
    at_newest = vals.index(9)
    assert all(v == 9 for v in vals[at_newest:])


def test_ring_eviction_clamps_watermark():
    # more writes than RING retains: a stale watermark (or a fresh
    # client) must clamp to the ring base instead of indexing out
    svc = Sequential(PersistentKV(), seed=0)
    _write(svc, "reader", -1)           # watermark pinned early
    for v in range(3 * Sequential.RING):
        _write(svc, "writer", v)
    v = _read(svc, "reader")            # old watermark < base now
    assert v >= 3 * Sequential.RING - Sequential.RING - 1
