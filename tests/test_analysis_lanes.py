"""Lane-liveness dataflow tests (analysis/lane_liveness.py).

Pins the PR's acceptance bars: each planted lane fixture trips its
LNE6xx rule, the conservative fallback (LNE605) fires on genuinely
unresolvable lane indices, manifest drift/missing/stale detection works
(including the jax-version staleness downgrade), Baseline.stale_entries
scopes LNE entries to the lanes pass, and — the safety proof the
specialization PR leans on — narrowing a fixture model's ``body_lanes``
to its recorded live set leaves tick trajectories bit-identical in both
carry layouts.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu.analysis import cost_model, run_lint
from maelstrom_tpu.analysis.findings import (Baseline, BaselineEntry,
                                             fingerprint_pass)
from maelstrom_tpu.analysis.lane_liveness import (DEFAULT_LANE_MANIFEST,
                                                  LaneReport,
                                                  analyze_model,
                                                  compare_manifest,
                                                  findings_of_report,
                                                  load_lane_manifest,
                                                  run_lane_lint,
                                                  save_lane_manifest)
from maelstrom_tpu.models.ir_hazards import (LANE_FIXTURE_MODELS,
                                             IrDeadLane, IrDeadStore,
                                             IrLaneOverread)
from maelstrom_tpu.tpu import wire

pytestmark = pytest.mark.lanes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


# --- the planted fixtures trip their rules ---------------------------------


class TestFixturesTrip:
    def test_dead_lane_trips_lne601_and_602(self):
        rep = analyze_model(IrDeadLane(), 2, "lead")
        fs = findings_of_report(IrDeadLane(), rep)
        assert {"LNE601", "LNE602"} <= _rules(fs)
        assert not rep.conservative
        # the declared-but-unread lanes are exactly the recorded
        # headroom the fixture plants
        assert rep.live_body_lanes == [0]
        assert rep.dead_body_lanes == [1, 2, 3]
        assert rep.dead_bytes_est > 0
        # both planted carry leaves classify dead
        dead = set(rep.dead_carry_leaves)
        assert any("seen" in p for p in dead)
        assert any("ballast" in p for p in dead)

    def test_dead_store_trips_lne603(self):
        rep = analyze_model(IrDeadStore(), 2, "lead")
        fs = findings_of_report(IrDeadStore(), rep)
        assert "LNE603" in _rules(fs)
        # the stamped-but-never-read lane is body lane 1
        assert wire.BODY + 1 in {lane for lane, _ in rep.dead_stores}
        assert 1 in rep.dead_body_lanes

    def test_lane_overread_trips_lne604_as_error(self):
        rep = analyze_model(IrLaneOverread(), 2, "lead")
        fs = findings_of_report(IrLaneOverread(), rep)
        overreads = [f for f in fs if f.rule == "LNE604"]
        assert overreads and all(f.severity == "error"
                                 for f in overreads)
        # the fixture aims one past the row end
        assert rep.lanes in {lane for lane, _ in rep.overreads}

    def test_fixtures_trip_in_both_layouts(self):
        for layout in ("lead", "minor"):
            for kind, cls in sorted(LANE_FIXTURE_MODELS.items()):
                rep = analyze_model(cls(), 2, layout)
                fs = findings_of_report(cls(), rep)
                assert fs, (kind, layout)

    def test_unresolvable_index_falls_back_conservative(self):
        """LNE605: a lane index computed from message DATA cannot be
        resolved statically — the model must widen to all-live (no
        dead-lane credit), not silently under-approximate."""
        from maelstrom_tpu.models.echo import EchoModel

        class DataIndexed(EchoModel):
            name = "echo-data-indexed"

            def handle(self, row, node_idx, msg, t, key, cfg, params):
                row, out = super().handle(row, node_idx, msg, t, key,
                                          cfg, params)
                # index depends on traced payload: unresolvable
                lane = msg[wire.BODY] % cfg.lanes
                ghost = jax.lax.dynamic_index_in_dim(
                    msg, lane, axis=-1, keepdims=False)
                out = out.at[0, wire.BODY].add(ghost * 0)
                return row, out

        rep = analyze_model(DataIndexed(), 2, "lead")
        assert rep.conservative
        assert rep.live_lanes == set(range(rep.lanes))
        fs = findings_of_report(DataIndexed(), rep)
        assert _rules(fs) == {"LNE605"}
        assert not rep.dead_body_lanes   # no credit taken

    def test_honest_echo_is_exact(self):
        """False-positive guard: the registered echo model resolves
        exactly (no LNE604/605) and its one payload lane is live."""
        from maelstrom_tpu.models import get_model
        for layout in ("lead", "minor"):
            rep = analyze_model(get_model("echo", 2), 2, layout)
            assert not rep.conservative, rep.notes
            assert not rep.overreads
            assert 0 in rep.live_body_lanes


# --- manifest io + drift gate ----------------------------------------------


def _fake_report(**kw):
    defaults = dict(label="echo/n=2/lead", lanes=11, body_lanes=2,
                    live_lanes=set(range(9)) | {wire.BODY})
    defaults.update(kw)
    return LaneReport(**defaults)


class TestManifestGate:
    def test_roundtrip_and_entry_contract(self, tmp_path):
        rep = _fake_report()
        path = str(tmp_path / "m.json")
        save_lane_manifest({"echo/n=2/lead": rep.to_entry()}, path)
        man = load_lane_manifest(path)
        e = man["entries"]["echo/n=2/lead"]
        # the specialization contract: the three keys ROADMAP item 2's
        # refactor consumes
        assert e["live_body_lanes"] == [0]
        assert "dead_bytes_per_tick_est" in e
        assert e["projected_narrow_ir_bytes_est"] == \
            e["ir_bytes_est"] - e["dead_bytes_per_tick_est"]
        assert man["jax-version"] == jax.__version__

    def test_drift_is_an_error_same_toolchain(self):
        rep = _fake_report()
        entry = rep.to_entry()
        entry["live_body_lanes"] = [0, 1]   # manifest claims lane 1 live
        manifest = {"jax-version": jax.__version__,
                    "entries": {"echo/n=2/lead": entry}}
        fs = compare_manifest({"echo/n=2/lead": rep}, manifest,
                              {"echo/n=2/lead": ("p.py", "Echo")})
        (f,) = [f for f in fs if f.rule == "LNE606"]
        assert f.severity == "error"
        assert "live_body_lanes" in f.message

    def test_drift_downgrades_under_toolchain_skew(self):
        """The self-explaining staleness downgrade: recorded under a
        different jax, drift is a re-record warning, not a failure."""
        rep = _fake_report()
        entry = rep.to_entry()
        entry["live_body_lanes"] = [0, 1]
        manifest = {"jax-version": "0.0.0",
                    "entries": {"echo/n=2/lead": entry}}
        fs = compare_manifest({"echo/n=2/lead": rep}, manifest,
                              {"echo/n=2/lead": ("p.py", "Echo")})
        (f,) = [f for f in fs if f.rule == "LNE606"]
        assert f.severity == "warning"
        assert "--update-manifest" in f.message
        assert "0.0.0" in f.message

    def test_missing_and_stale_entries(self):
        rep = _fake_report()
        manifest = {"jax-version": jax.__version__,
                    "entries": {"ghost/n=9/lead": rep.to_entry()}}
        fs = compare_manifest({"echo/n=2/lead": rep}, manifest,
                              {"echo/n=2/lead": ("p.py", "Echo")})
        assert _rules(fs) == {"LNE607", "LNE608"}
        missing = [f for f in fs if f.rule == "LNE607"]
        assert missing[0].severity == "error"

    def test_errored_keys_are_not_stale(self):
        """A model whose analysis crashed already carries LNE609; its
        manifest entries must NOT also be called stale (LNE608 would
        advise deleting perfectly valid entries)."""
        rep = _fake_report()
        manifest = {"jax-version": jax.__version__,
                    "entries": {"ghost/n=9/lead": rep.to_entry()}}
        fs = compare_manifest({}, manifest, {},
                              errored={"ghost/n=9/lead"})
        assert "LNE608" not in _rules(fs)

    def test_analysis_failure_trips_lne609(self):
        """get_model crashing is a total audit failure (error-severity
        LNE609), distinct from LNE605's documented warning-severity
        conservative widening."""
        fs = run_lane_lint(workloads=[("no-such-workload", 3)])
        hits = [f for f in fs if f.rule == "LNE609"]
        assert hits and all(f.severity == "error" for f in hits)
        assert not [f for f in fs if f.rule == "LNE605"]

    def test_cost_toolchain_note_matches_contract(self):
        assert cost_model.toolchain_note(jax.__version__, "x") is None
        assert cost_model.toolchain_note(None, "x") is None
        note = cost_model.toolchain_note("0.0.0", "the cost baseline")
        assert "--update-baseline" in note and "0.0.0" in note

    def test_checked_in_manifest_covers_registry_with_headroom(self):
        """Acceptance bar: the committed manifest has one entry per
        registered model x layout, and at least one family records
        nonzero dead bytes — the measured ROADMAP item 2 headroom."""
        man = load_lane_manifest(DEFAULT_LANE_MANIFEST)
        want = {cost_model.entry_key(wl, n, layout)
                for wl, n in cost_model.cost_specs()
                for layout in cost_model.AUDIT_LAYOUTS}
        assert set(man["entries"]) == want
        assert any(e["dead_bytes_per_tick_est"] > 0
                   for e in man["entries"].values())
        assert man.get("jax-version")

    def test_restricted_run_gates_against_checked_in_manifest(self):
        """One model x both layouts against the committed manifest:
        clean, and with a tampered copy the same run raises LNE606."""
        fs = run_lane_lint(REPO, workloads=[("echo", 2)])
        assert not [f for f in fs if f.severity == "error"], \
            [f.to_dict() for f in fs if f.severity == "error"]

    def test_restricted_run_flags_tampered_manifest(self, tmp_path):
        man = load_lane_manifest(DEFAULT_LANE_MANIFEST)
        key = cost_model.entry_key("echo", 2, "lead")
        man["entries"][key]["live_body_lanes"] = []
        tampered = tmp_path / "tampered.json"
        tampered.write_text(json.dumps(man))
        fs = run_lane_lint(REPO, manifest_path=str(tampered),
                           workloads=[("echo", 2)])
        drifts = [f for f in fs if f.rule == "LNE606"]
        assert drifts and drifts[0].severity == "error"

    def test_update_manifest_records_and_regates_clean(self, tmp_path):
        """record → immediately re-gate: the freshly recorded manifest
        must be drift-free (the --update-manifest workflow)."""
        path = str(tmp_path / "m.json")
        fs = run_lane_lint(REPO, manifest_path=path,
                           update_manifest=True,
                           workloads=[("echo", 2)])
        assert "LNE600" in _rules(fs)
        fs2 = run_lane_lint(REPO, manifest_path=path,
                            workloads=[("echo", 2)])
        assert not [f for f in fs2
                    if f.rule in ("LNE606", "LNE607", "LNE608")]


# --- baseline pass-scoping -------------------------------------------------


class TestBaselineScoping:
    def test_lne_fingerprints_map_to_lanes_pass(self):
        assert fingerprint_pass("LNE601:maelstrom_tpu/models/"
                                "ir_hazards.py:IrDeadLane") == "lanes"
        assert fingerprint_pass("COST501:x:y") == "cost"
        assert fingerprint_pass("TRC101:x:y") == "trace"

    def test_stale_entries_scoped_to_ran_passes(self):
        """An unmatched LNE entry is stale ONLY when the lanes pass
        ran — a default trace/contract/schema sweep must not call the
        lane baseline stale (the third opt-in pass joins the PR 5
        prefix map)."""
        b = Baseline(entries=[
            BaselineEntry(fingerprint="LNE601:p.py:Ghost",
                          status="expected", reason="t"),
            BaselineEntry(fingerprint="TRC101:p.py:Ghost",
                          status="expected", reason="t"),
        ])
        stale_default = b.stale_entries(
            passes=("trace", "contract", "schema"))
        assert [e.fingerprint for e in stale_default] == \
            ["TRC101:p.py:Ghost"]
        stale_lanes = b.stale_entries(passes=("lanes",))
        assert [e.fingerprint for e in stale_lanes] == \
            ["LNE601:p.py:Ghost"]
        assert len(b.stale_entries(passes=None)) == 2

    def test_repo_baseline_has_no_orphan_lane_entries(self):
        """Every LNE entry in the checked-in baseline names a fixture
        class (or accepted model) that still exists."""
        b = Baseline.load(os.path.join(
            REPO, "maelstrom_tpu", "analysis", "baseline.json"))
        import importlib
        for fp in b.entries:
            if not fp.startswith("LNE"):
                continue
            _, path, symbol = fp.split(":")
            mod = importlib.import_module(
                path[:-3].replace(os.sep, ".").replace("/", "."))
            assert hasattr(mod, symbol), fp


# --- the narrow-layout safety proof ----------------------------------------


def _run_echo_fixture(model, layout, opts=None):
    from maelstrom_tpu.tpu.harness import make_sim_config
    from maelstrom_tpu.tpu.runtime import canonical_carry, run_sim
    base = dict(node_count=2, concurrency=4, n_instances=16,
                record_instances=4, inbox_k=1, pool_slots=12,
                time_limit=0.1, rate=200.0, latency=5.0,
                rpc_timeout=1.0, nemesis=[], seed=11, layout=layout)
    base.update(opts or {})
    sim = make_sim_config(model, base)
    params = model.make_params(sim.net.n_nodes)
    carry, ys = run_sim(model, sim, base["seed"], params)
    return canonical_carry(carry, sim), ys, sim


class TestNarrowLayoutRoundTrip:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_narrowing_to_live_set_is_trajectory_preserving(self,
                                                            layout):
        """The end-to-end safety proof: record the fixture's live set,
        rebuild it with ``body_lanes`` narrowed to exactly that set,
        and the tick trajectories are bit-identical — same decoded
        events, same stats/violations, same live pool lanes. This is
        the check the ROADMAP item 2 specialization PR re-runs per
        family before shrinking the real Msg."""
        wide = IrDeadLane()
        rep = analyze_model(wide, 2, layout)
        assert not rep.conservative
        live = rep.live_body_lanes
        assert live == [0]          # the manifest's recorded live set
        narrow_width = max(live) + 1

        narrow_cls = type("IrDeadLaneNarrow", (IrDeadLane,),
                          {"body_lanes": narrow_width})
        wide_c, wide_ys, wide_sim = _run_echo_fixture(wide, layout)
        nar_c, nar_ys, nar_sim = _run_echo_fixture(narrow_cls(), layout)

        # decoded observables: bit-identical
        np.testing.assert_array_equal(np.asarray(wide_ys.events),
                                      np.asarray(nar_ys.events))
        # fleet stats + violations: bit-identical, leaf by leaf
        for leaf_name in ("stats", "violations"):
            wl = jax.tree_util.tree_leaves(getattr(wide_c, leaf_name))
            nl = jax.tree_util.tree_leaves(getattr(nar_c, leaf_name))
            assert len(wl) == len(nl)
            for a, b in zip(wl, nl):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b),
                                              err_msg=leaf_name)
        # the surviving lanes of the pool carry the same bits: header
        # lanes + the live body lanes (dead lanes are the only thing
        # the narrow layout dropped)
        keep = list(range(wire.BODY)) + [wire.BODY + l for l in live]
        np.testing.assert_array_equal(
            np.asarray(wide_c.pool)[..., keep],
            np.asarray(nar_c.pool)[..., keep])
        # the run exercised real traffic
        assert int(np.asarray(wide_c.stats.delivered)) > 10

    def test_dead_bytes_shrink_when_narrowed(self):
        """The projection is honest: the narrow rebuild's ir_bytes_est
        lands at or below the wide model's projected figure."""
        wide_rep = analyze_model(IrDeadLane(), 2, "lead")
        narrow_cls = type("IrDeadLaneNarrow", (IrDeadLane,),
                          {"body_lanes": 1})
        narrow_rep = analyze_model(narrow_cls(), 2, "lead")
        assert narrow_rep.ir_bytes_est < wide_rep.ir_bytes_est
        assert narrow_rep.dead_bytes_est < wide_rep.dead_bytes_est


# --- wire-format guard (the make_msg satellite) ----------------------------


class TestMakeMsgGuard:
    def test_body_overflow_raises_at_trace_time(self):
        with pytest.raises(ValueError, match="body_lanes"):
            wire.make_msg(src=0, dest=1, type_=1, body=(1, 2, 3),
                          body_lanes=2)

    def test_body_overflow_raises_under_jit(self):
        def build():
            return wire.make_msg(src=0, dest=1, type_=1,
                                 body=(1, 2, 3, 4), body_lanes=3)
        with pytest.raises(ValueError, match="body_lanes"):
            jax.jit(build)()

    def test_full_body_still_fits(self):
        m = wire.make_msg(src=0, dest=1, type_=1, body=(7, 8),
                          body_lanes=2)
        assert m.shape == (wire.lanes(2),)
        assert int(m[wire.BODY]) == 7 and int(m[wire.BODY + 1]) == 8


# --- repo-wide gate (exhaustive sweep: slow) -------------------------------


@pytest.mark.slow
class TestRepoGate:
    def test_repo_wide_lanes_gate_is_green(self):
        """Every registered model x both layouts + the fixtures, gated
        against the committed manifest and baseline: zero unsuppressed
        findings, and every expected fixture entry HIT (none stale)."""
        report = run_lint(repo_root=REPO, passes=("lanes",))
        assert report.findings == [], \
            [f.to_dict() for f in report.findings]
        stale = [e.fingerprint for e in report.stale
                 if e.fingerprint.startswith("LNE")]
        assert stale == []
        hit = {e.fingerprint for _, e in report.suppressed}
        assert any(fp.startswith("LNE604") for fp in hit)
        assert any(fp.startswith("LNE603") for fp in hit)
