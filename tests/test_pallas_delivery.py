"""Pallas delivery kernel cross-validation: bit-identical to the XLA
reference implementation (netsim.deliver) on random pools, partitions,
and clock values — the divergence-debugging discipline of SURVEY §7
(host oracle cross-validation), applied to the hand-written kernel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


from maelstrom_tpu.ops.delivery import deliver_pallas
from maelstrom_tpu.tpu import netsim, wire
from maelstrom_tpu.tpu.netsim import NetConfig


def _random_pool(rng, cfg, fill=0.6):
    S, L = cfg.pool_slots, cfg.lanes
    pool = np.zeros((S, L), dtype=np.int32)
    for s in range(S):
        if rng.random() < fill:
            pool[s, wire.VALID] = 1
            pool[s, wire.SRC] = rng.randrange(cfg.n_total)
            pool[s, wire.DEST] = rng.randrange(cfg.n_total)
            pool[s, wire.ORIGIN] = rng.randrange(cfg.n_total)
            pool[s, wire.DTICK] = rng.randrange(0, 30)
            pool[s, wire.TYPE] = rng.randrange(1, 9)
            pool[s, wire.BODY] = rng.randrange(100)
    return pool


@pytest.mark.parametrize(
    "seed", [0, pytest.param(1, marks=pytest.mark.slow),
             pytest.param(2, marks=pytest.mark.slow)])
def test_pallas_deliver_matches_xla_reference(seed):
    import random
    rng = random.Random(seed)
    cfg = NetConfig(n_nodes=3, n_clients=3, pool_slots=32, inbox_k=4,
                    body_lanes=6, latency_mean=5.0, latency_dist=2,
                    p_loss=0.0)
    I = 8
    pools = np.stack([_random_pool(rng, cfg) for _ in range(I)])
    parts = (np.random.RandomState(seed).rand(
        I, cfg.n_total, cfg.n_total) < 0.25)
    np.einsum("ijj->ij", parts)[:] = False   # no self-partitions
    t = jnp.int32(15)

    ref_pool, ref_inbox, ref_ndel, ref_ndrop = jax.vmap(
        lambda p, pa: netsim.deliver(p, pa, t, cfg))(
        jnp.asarray(pools), jnp.asarray(parts))
    pal_pool, pal_inbox, pal_ndel, pal_ndrop = deliver_pallas(
        jnp.asarray(pools), jnp.asarray(parts), t, cfg, interpret=True)

    np.testing.assert_array_equal(np.asarray(ref_pool),
                                  np.asarray(pal_pool))
    np.testing.assert_array_equal(np.asarray(ref_inbox),
                                  np.asarray(pal_inbox))
    np.testing.assert_array_equal(np.asarray(ref_ndel),
                                  np.asarray(pal_ndel))
    np.testing.assert_array_equal(np.asarray(ref_ndrop),
                                  np.asarray(pal_ndrop))
