"""Device-side transactional + kafka workloads over the TPU runtime
(VERDICT r1 items 2 and 6: the north-star txn-list-append config and the
kafka model, each with a caught bug mutant)."""

import pytest

from maelstrom_tpu.models.kafka import KafkaModel, KafkaOffsetReuse
from maelstrom_tpu.models.txn_raft import (TxnDirtyApply,
                                           TxnListAppendModel,
                                           TxnRwRegisterModel)
from maelstrom_tpu.tpu.harness import run_tpu_test
from maelstrom_tpu.tpu.runtime import scripted_isolate_groups

TXN_OPTS = dict(node_count=3, concurrency=3, n_instances=4,
                record_instances=4, time_limit=3.0, rate=15.0,
                latency=5.0, rpc_timeout=1.0, recovery_time=0.3, seed=1)


@pytest.mark.slow
@pytest.mark.parametrize("model_cls", [TxnListAppendModel,
                                       TxnRwRegisterModel])
def test_txn_over_raft_clean(model_cls):
    res = run_tpu_test(model_cls(n_nodes_hint=3), TXN_OPTS)
    assert res["valid?"] is True, res["instances"]
    assert res["net"]["delivered"] > 500


def _leader_isolation_schedule(cycles=2):
    """Deterministically isolate each node in turn (400-tick phases with
    100-tick heal gaps) — whoever is leader gets cut off from the
    majority at some point, which is what makes dirty-apply observable."""
    sched = []
    t = 200
    for _ in range(cycles):
        for iso in range(3):
            others = tuple(sorted({0, 1, 2} - {iso}))
            sched.append(scripted_isolate_groups(t + 400,
                                                 [(iso,), others], 3))
            t += 400
            sched.append((t + 100, ()))
            t += 100
    return tuple(sched), (t + 600) / 1000


@pytest.mark.slow
def test_txn_dirty_apply_caught_by_elle():
    """Acked-at-append txns get truncated on leader change: Elle must
    flag lost-append / incompatible-order; the correct model must pass
    the identical schedule."""
    sched, horizon = _leader_isolation_schedule()
    opts = dict(node_count=3, concurrency=4, n_instances=8,
                record_instances=8, time_limit=horizon, rate=60.0,
                latency=5.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_kind="scripted", nemesis_schedule=sched,
                recovery_time=0.5, seed=3)
    res = run_tpu_test(TxnDirtyApply(n_nodes_hint=3, log_cap=96), opts)
    assert res["valid?"] is False, "dirty-apply mutant not caught"
    bad = [i for i in res["instances"] if i.get("valid?") is False]
    kinds = set()
    for b in bad:
        kinds.update(b.get("anomaly-types") or [])
    assert "lost-append" in kinds or "incompatible-order" in kinds, kinds

    res_ok = run_tpu_test(TxnListAppendModel(n_nodes_hint=3, log_cap=96),
                          opts)
    assert res_ok["valid?"] is True, res_ok["instances"]


KAFKA_OPTS = dict(node_count=1, concurrency=4, n_instances=8,
                  record_instances=8, time_limit=3.0, rate=40.0,
                  latency=5.0, rpc_timeout=0.8, p_loss=0.05,
                  recovery_time=0.3, seed=4)


def test_kafka_clean():
    res = run_tpu_test(KafkaModel(), KAFKA_OPTS)
    assert res["valid?"] is True, res["instances"]
    assert res["net"]["delivered"] > 300


@pytest.mark.slow
def test_kafka_offset_reuse_caught():
    res = run_tpu_test(KafkaOffsetReuse(), KAFKA_OPTS)
    assert res["valid?"] is False, "offset-reuse mutant not caught"
    bad = [i for i in res["instances"] if i.get("valid?") is False]
    kinds = set()
    for b in bad:
        kinds.update(b.get("anomaly-types") or [])
    assert "duplicate-offset" in kinds, kinds


@pytest.mark.slow
def test_txn_rw_dirty_apply_caught():
    """rw-register dirty-apply mutant: stale reads of truncated acked
    writes surface as G-single cycles through the checker's
    wfr/initial-version order inference; correct model clean on the
    identical schedule."""
    from maelstrom_tpu.models.txn_raft import TxnRwDirtyApply
    sched, horizon = _leader_isolation_schedule()
    opts = dict(node_count=3, concurrency=4, n_instances=8,
                record_instances=8, time_limit=horizon, rate=60.0,
                latency=5.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_kind="scripted", nemesis_schedule=sched,
                recovery_time=0.5, seed=3)
    res = run_tpu_test(TxnRwDirtyApply(n_nodes_hint=3, log_cap=96), opts)
    assert res["valid?"] is False, "rw dirty-apply mutant not caught"

    res_ok = run_tpu_test(TxnRwRegisterModel(n_nodes_hint=3, log_cap=96),
                          opts)
    assert res_ok["valid?"] is True, res_ok["instances"]


@pytest.mark.slow
def test_kafka_commit_regression_caught():
    from maelstrom_tpu.models.kafka import KafkaCommitRegression
    # needs a wider fleet than the other mutants: the regression only
    # surfaces when a lagging consumer's blind overwrite is OBSERVED by
    # later list ops — 32 instances catches it on every seed tried,
    # where 8 is schedule-lottery (more instances = more schedules, the
    # product's whole thesis)
    res = run_tpu_test(KafkaCommitRegression(),
                       dict(KAFKA_OPTS, n_instances=32,
                            record_instances=32))
    assert res["valid?"] is False, "commit-regression mutant not caught"
    kinds = set()
    for b in res["instances"]:
        kinds.update(b.get("anomaly-types") or [])
    assert "commit-regression" in kinds, kinds
