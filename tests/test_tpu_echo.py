"""TPU runtime MVP: vectorized echo instances end-to-end on the virtual
CPU mesh (SURVEY §7 step 5)."""

import os

import numpy as np

from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.tpu.harness import run_tpu_test


def test_tpu_echo_e2e():
    res = run_tpu_test(EchoModel(), dict(
        node_count=2, concurrency=2, n_instances=16, record_instances=4,
        time_limit=1.0, rate=100.0, latency=5.0, seed=3))
    assert res["valid?"] is True, res
    assert res["checked-instances"] == 4
    # every checked instance saw real traffic
    for inst in res["instances"]:
        assert inst["ok-count"] > 10, inst
    assert res["net"]["delivered"] > 100
    assert res["net"]["dropped-overflow"] == 0


def test_tpu_echo_loss_and_timeouts():
    res = run_tpu_test(EchoModel(), dict(
        node_count=1, concurrency=2, n_instances=8, record_instances=4,
        time_limit=1.0, rate=50.0, latency=5.0, p_loss=0.5,
        rpc_timeout=0.2, seed=3))
    # loss must be observed and echo payloads still correct when ok
    assert res["net"]["dropped-loss"] > 0
    assert res["valid?"] is True, res


def test_tpu_echo_deterministic():
    opts = dict(node_count=2, concurrency=2, n_instances=4,
                record_instances=2, time_limit=0.5, rate=100.0,
                latency=5.0, seed=11)
    r1 = run_tpu_test(EchoModel(), opts)
    r2 = run_tpu_test(EchoModel(), opts)
    assert r1["net"] == r2["net"]


def test_tpu_unique_ids():
    from maelstrom_tpu.models.unique_ids import UniqueIdsModel
    res = run_tpu_test(UniqueIdsModel(), dict(
        node_count=3, concurrency=2, n_instances=8, record_instances=4,
        time_limit=1.0, rate=100.0, latency=5.0, seed=9))
    assert res["valid?"] is True, res["instances"]
    assert res["instances"][0]["acknowledged-count"] > 10


def test_tpu_journal_and_lamport_svg(tmp_path):
    """VERDICT r1 missing #5: TPU runs get per-message journals —
    send/recv pairing, all/clients/servers stats, messages.svg."""
    from maelstrom_tpu.models.echo import EchoModel
    from maelstrom_tpu.tpu.harness import run_tpu_test

    res = run_tpu_test(EchoModel(), dict(
        node_count=2, concurrency=2, n_instances=4, record_instances=2,
        journal_instances=1, time_limit=1.0, rate=30.0, latency=5.0,
        rpc_timeout=0.5, recovery_time=0.2, seed=5,
        store_root=str(tmp_path)))
    assert res["valid?"] is True
    j = res["net"]["journal"]
    st = j["stats"]
    # every recv pairs with a send; some sends may be lost/in flight
    assert 0 < st["all"]["recv-count"] <= st["all"]["send-count"]
    assert st["all"]["msg-count"] > 0
    # echo is pure client<->server RPC: all traffic involves a client
    assert st["servers"]["msg-count"] == 0
    assert j["msgs-per-op"] is not None
    svg = os.path.join(res["store-dir"], "messages.svg")
    assert os.path.exists(svg) and os.path.getsize(svg) > 1000
