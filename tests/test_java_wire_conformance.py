"""Runtime-independent wire conformance for the Java SDK + nodes.

No JVM exists in this image, so — like the JS/Go/Ruby suites — the
sources are validated STATICALLY against the wire protocol and the
schema registry. The e2e suite (test_java_nodes.py) runs whenever a
`javac`/`java` toolchain appears."""

import os
import re

import pytest

from wire_conformance_common import (assert_error_codes_in_catalog,
                                     assert_node_reply_types)

J_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "java")

SDK = open(os.path.join(J_DIR, "Maelstrom.java")).read()

NODES = {
    "EchoServer.java": ("echo", set()),
    "BroadcastServer.java": ("broadcast", {"gossip"}),
    "CounterServer.java": ("g-counter", set()),
}


def _literal_types(src):
    """Every "type" -> "x" put into a reply/send body."""
    return set(re.findall(
        r'put\("type",\s*"([a-z_]+)"\)', src))


def test_sdk_envelope_shape():
    assert 'env.put("src", nodeId)' in SDK
    assert 'env.put("dest", dest)' in SDK
    assert 'env.put("body", body)' in SDK
    assert '"in_reply_to"' in SDK and '"msg_id"' in SDK


def test_sdk_init_handshake():
    assert '"init_ok"' in SDK
    assert '"node_id"' in SDK and '"node_ids"' in SDK


def test_sdk_error_codes_in_catalog():
    codes = {int(c) for c in re.findall(
        r"ERR_[A-Z_]+ = (\d+);", SDK)}
    assert_error_codes_in_catalog(codes)


def test_kv_client_speaks_service_schema():
    for field in ('put("type", "read")', 'put("type", "write")',
                  'put("type", "cas")', 'put("key", key)',
                  'put("value", value)', 'put("from", from)',
                  'put("to", to)', 'put("create_if_not_exists"'):
        assert field in SDK, field
    assert '"lin-kv"' in SDK and '"seq-kv"' in SDK and '"lww-kv"' in SDK


def test_sdk_json_codec_roundtrip_shape():
    # the embedded codec must at least cover the wire's value grammar
    for token in ("readObject", "readArray", "readString",
                  "Double.parseDouble", "Long.parseLong",
                  '"null"', '"true"', '"false"'):
        assert token in SDK, token


@pytest.mark.parametrize("name", sorted(NODES))
def test_node_reply_types_in_registry(name):
    namespace, internal = NODES[name]
    src = open(os.path.join(J_DIR, name)).read()
    emitted = _literal_types(src)
    assert_node_reply_types(namespace, internal, emitted, name)
