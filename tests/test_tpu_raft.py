"""Vectorized Raft (the TPU-runtime flagship): linearizability under
faults, and injected-bug detection (SURVEY §7 steps 7-8)."""

import pytest

from maelstrom_tpu.models.raft import RaftModel
from maelstrom_tpu.models.raft_buggy import (RaftDoubleVote,
                                             RaftEagerCommit,
                                             RaftNoTermGuard,
                                             RaftShortLogWins,
                                             RaftStaleRead)
from maelstrom_tpu.tpu.harness import run_tpu_test
from maelstrom_tpu.tpu.runtime import scripted_isolate_groups


def test_raft_linearizable_happy_path():
    res = run_tpu_test(RaftModel(n_nodes_hint=3), dict(
        node_count=3, concurrency=3, n_instances=4, record_instances=4,
        time_limit=3.0, rate=20.0, latency=5.0, rpc_timeout=1.0,
        recovery_time=0.3, seed=1))
    assert res["valid?"] is True, res["instances"]
    # clients actually get committed ops through (leader forwarding works)
    assert res["net"]["delivered"] > 1000


@pytest.mark.slow
def test_raft_linearizable_under_partitions_and_loss():
    res = run_tpu_test(RaftModel(n_nodes_hint=3), dict(
        node_count=3, concurrency=3, n_instances=4, record_instances=4,
        time_limit=4.0, rate=20.0, latency=5.0, rpc_timeout=1.0,
        nemesis=["partition"], nemesis_interval=0.4, p_loss=0.1,
        recovery_time=0.5, seed=1))
    assert res["valid?"] is True, res["instances"]
    assert res["net"]["dropped-partition"] > 0
    assert res["net"]["dropped-loss"] > 0


BUG_OPTS = dict(node_count=3, concurrency=3, n_instances=24,
                record_instances=24, time_limit=2.5, rate=40.0,
                latency=10.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_interval=0.25, p_loss=0.05, recovery_time=0.3,
                seed=2)


# RaftNoTermGuard needs the Figure-8 schedule — see
# test_raft_no_term_guard_caught_on_figure8 below; all three corpus
# mutants are now demonstrably caught.
@pytest.mark.parametrize("buggy", [RaftDoubleVote, RaftStaleRead])
@pytest.mark.slow
def test_raft_injected_bugs_are_caught(buggy):
    res = run_tpu_test(buggy(n_nodes_hint=3), BUG_OPTS)
    assert res["valid?"] is False, \
        f"{buggy.__name__}: checker failed to catch the injected bug"


def _rotating_majorities_schedule(n=5, phase_len=200, horizon_ticks=3500):
    """Scripted rotating 3-node majorities over a 5-node cluster: each
    phase only one majority group can talk, and the pivot node rotates —
    the repeated partial-replication / leader-change pattern that
    realizes the Raft §5.4.2 Figure-8 scenario across a fleet of seeds."""
    groups_cycle = [({0, 1, 2},), ({2, 3, 4},), ({4, 0, 1},),
                    ({1, 2, 3},), ({3, 4, 0},)]
    sched, t, i = [], 0, 0
    while t < horizon_ticks - 500:
        t += phase_len
        sched.append(scripted_isolate_groups(t, groups_cycle[i % 5], n))
        i += 1
    return tuple(sched)


FIGURE8_OPTS = dict(node_count=5, concurrency=4, n_instances=64,
                    record_instances=1, time_limit=3.5, rate=60.0,
                    latency=5.0, rpc_timeout=0.8, nemesis=["partition"],
                    nemesis_kind="scripted",
                    nemesis_schedule=_rotating_majorities_schedule(),
                    recovery_time=0.5, seed=11)


@pytest.mark.slow
def test_raft_no_term_guard_caught_on_figure8():
    """The §5.4.2 commit bug: an old-term entry committed on replication
    count alone gets overwritten after a leader change. The on-device
    truncated-committed witness (a node overwriting below its own commit
    index) catches it fleet-wide under the rotating-majorities schedule;
    correct Raft stays clean on the identical schedule."""
    res = run_tpu_test(RaftNoTermGuard(n_nodes_hint=5, log_cap=64),
                       FIGURE8_OPTS)
    inv = res["invariants"]
    assert inv["violating-instances"] >= 3, inv
    assert res["valid?"] is False

    res_ok = run_tpu_test(RaftModel(n_nodes_hint=5, log_cap=64),
                          FIGURE8_OPTS)
    assert res_ok["invariants"]["violating-instances"] == 0, \
        res_ok["invariants"]
    assert res_ok["valid?"] is True, res_ok["instances"]


@pytest.mark.slow
def test_raft_eager_commit_caught():
    """Max-match commit (no majority quorum): the leader acknowledges
    writes it alone holds; a failover to a node without them then
    truncates the acknowledged suffix. The rotating-majorities schedule
    forces exactly that partial-replication + leader-churn pattern;
    caught by the truncated-committed witness / committed-prefix
    invariant (or WGL on recorded instances). Correct Raft on the
    identical schedule is covered by
    test_raft_no_term_guard_caught_on_figure8."""
    res = run_tpu_test(RaftEagerCommit(n_nodes_hint=5, log_cap=64),
                       FIGURE8_OPTS)
    caught = (res["valid?"] is False
              or res["invariants"]["violating-instances"] > 0)
    assert caught, (res["instances"], res["invariants"])


@pytest.mark.slow
def test_raft_short_log_wins_caught():
    """Term-only vote recency: a same-term shorter-log candidate wins an
    election and truncates a committed suffix. Needs churn (partitions +
    loss force lagging followers into candidacy); the on-device
    truncated-committed witness / committed-prefix agreement flags it,
    while correct Raft stays clean under the identical config."""
    opts = dict(BUG_OPTS, n_instances=48, record_instances=8,
                time_limit=3.0, seed=5)
    res = run_tpu_test(RaftShortLogWins(n_nodes_hint=3), opts)
    caught = (res["valid?"] is False
              or res["invariants"]["violating-instances"] > 0)
    assert caught, (res["instances"], res["invariants"])

    res_ok = run_tpu_test(RaftModel(n_nodes_hint=3), opts)
    assert res_ok["invariants"]["violating-instances"] == 0
    assert res_ok["valid?"] is True, res_ok["instances"]


@pytest.mark.slow
def test_raft_correct_same_config_as_bug_hunt():
    """The correct model must pass the exact config that trips the
    mutants — otherwise the bug tests prove nothing."""
    res = run_tpu_test(RaftModel(n_nodes_hint=3), BUG_OPTS)
    assert res["valid?"] is True, res["instances"]


@pytest.mark.slow
def test_on_device_invariants_catch_double_vote_fleet_wide():
    """Election-safety + committed-log-agreement run on EVERY instance
    on-device; detection rate beats history sampling by an order of
    magnitude (SURVEY §7: cheap vectorized invariants everywhere)."""
    opts = dict(BUG_OPTS, n_instances=32, record_instances=4)
    res = run_tpu_test(RaftDoubleVote(n_nodes_hint=3), opts)
    inv = res["invariants"]
    assert inv["violating-instances"] >= 3, inv
    assert res["valid?"] is False

    res_ok = run_tpu_test(RaftModel(n_nodes_hint=3), opts)
    assert res_ok["invariants"]["violating-instances"] == 0
    assert res_ok["valid?"] is True, res_ok["instances"]


@pytest.mark.slow
def test_raft_majorities_ring_nemesis():
    res = run_tpu_test(RaftModel(n_nodes_hint=5), dict(
        node_count=5, concurrency=3, n_instances=4, record_instances=4,
        time_limit=3.0, rate=20.0, latency=5.0, rpc_timeout=1.0,
        nemesis=["partition"], nemesis_kind="majorities-ring",
        nemesis_interval=0.4, recovery_time=0.5, seed=3))
    assert res["net"]["dropped-partition"] > 0
    assert res["valid?"] is True, res["instances"]
