"""Vectorized Raft (the TPU-runtime flagship): linearizability under
faults, and injected-bug detection (SURVEY §7 steps 7-8)."""

import pytest

from maelstrom_tpu.models.raft import RaftModel
from maelstrom_tpu.models.raft_buggy import RaftDoubleVote, RaftStaleRead
from maelstrom_tpu.tpu.harness import run_tpu_test


def test_raft_linearizable_happy_path():
    res = run_tpu_test(RaftModel(n_nodes_hint=3), dict(
        node_count=3, concurrency=3, n_instances=4, record_instances=4,
        time_limit=3.0, rate=20.0, latency=5.0, rpc_timeout=1.0,
        recovery_time=0.3, seed=1))
    assert res["valid?"] is True, res["instances"]
    # clients actually get committed ops through (leader forwarding works)
    assert res["net"]["delivered"] > 1000


def test_raft_linearizable_under_partitions_and_loss():
    res = run_tpu_test(RaftModel(n_nodes_hint=3), dict(
        node_count=3, concurrency=3, n_instances=4, record_instances=4,
        time_limit=4.0, rate=20.0, latency=5.0, rpc_timeout=1.0,
        nemesis=["partition"], nemesis_interval=0.4, p_loss=0.1,
        recovery_time=0.5, seed=1))
    assert res["valid?"] is True, res["instances"]
    assert res["net"]["dropped-partition"] > 0
    assert res["net"]["dropped-loss"] > 0


BUG_OPTS = dict(node_count=3, concurrency=3, n_instances=24,
                record_instances=24, time_limit=2.5, rate=40.0,
                latency=10.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_interval=0.25, p_loss=0.05, recovery_time=0.3,
                seed=2)


# RaftNoTermGuard is deliberately absent: the §5.4.2 commit bug needs the
# Figure-8 schedule, which these shapes don't reliably produce (see
# models/raft_buggy.py) — asserting it's caught here would be a lie.
@pytest.mark.parametrize("buggy", [RaftDoubleVote, RaftStaleRead])
def test_raft_injected_bugs_are_caught(buggy):
    res = run_tpu_test(buggy(n_nodes_hint=3), BUG_OPTS)
    assert res["valid?"] is False, \
        f"{buggy.__name__}: checker failed to catch the injected bug"


def test_raft_correct_same_config_as_bug_hunt():
    """The correct model must pass the exact config that trips the
    mutants — otherwise the bug tests prove nothing."""
    res = run_tpu_test(RaftModel(n_nodes_hint=3), BUG_OPTS)
    assert res["valid?"] is True, res["instances"]


def test_on_device_invariants_catch_double_vote_fleet_wide():
    """Election-safety + committed-log-agreement run on EVERY instance
    on-device; detection rate beats history sampling by an order of
    magnitude (SURVEY §7: cheap vectorized invariants everywhere)."""
    opts = dict(BUG_OPTS, n_instances=32, record_instances=4)
    res = run_tpu_test(RaftDoubleVote(n_nodes_hint=3), opts)
    inv = res["invariants"]
    assert inv["violating-instances"] >= 3, inv
    assert res["valid?"] is False

    res_ok = run_tpu_test(RaftModel(n_nodes_hint=3), opts)
    assert res_ok["invariants"]["violating-instances"] == 0
    assert res_ok["valid?"] is True, res_ok["instances"]


def test_raft_majorities_ring_nemesis():
    res = run_tpu_test(RaftModel(n_nodes_hint=5), dict(
        node_count=5, concurrency=3, n_instances=4, record_instances=4,
        time_limit=3.0, rate=20.0, latency=5.0, rpc_timeout=1.0,
        nemesis=["partition"], nemesis_kind="majorities-ring",
        nemesis_interval=0.4, recovery_time=0.5, seed=3))
    assert res["net"]["dropped-partition"] > 0
    assert res["valid?"] is True, res["instances"]
