"""Broadcast gossip efficiency: the fire-and-forget discipline must hit
the reference's published msgs-per-op numbers (VERDICT r1 weak #5;
reference doc/03-broadcast/02-performance.md:22-28 naive 5.01 on 5-node
grid, :249-254 tree4 12.0 on 25 nodes)."""

import os
import sys

from maelstrom_tpu import run_test
import pytest

pytestmark = pytest.mark.slow

BIN = sys.executable
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FF = [os.path.join(REPO, "examples", "python", "broadcast.py"), "--ff"]


def test_ff_grid5_beats_naive_baseline():
    res = run_test("broadcast", dict(
        bin=BIN, bin_args=FF, node_count=5, topology="grid",
        time_limit=8.0, rate=50.0, concurrency=4, latency=0.0, seed=9))
    assert res["valid?"] is True
    mpo = res["net"]["msgs-per-op"]
    assert mpo <= 5.01, f"{mpo} msgs/op exceeds the 5.01 naive baseline"


def test_ff_tree4_25n_near_optimal():
    res = run_test("broadcast", dict(
        bin=BIN, bin_args=FF, node_count=25, topology="tree4",
        time_limit=10.0, rate=100.0, concurrency=8, latency=0.0, seed=9))
    assert res["valid?"] is True
    mpo = res["net"]["msgs-per-op"]
    # reference: 12.0 (optimal 24 msgs/broadcast over 50/50 op mix)
    assert mpo <= 13.0, f"{mpo} msgs/op vs reference 12.0 on tree4"
