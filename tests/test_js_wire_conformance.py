"""Runtime-independent wire conformance for the JS SDK + nodes.

No JS engine exists in this image (the e2e tests in test_js_nodes.py
skip), so the JS sources are validated STATICALLY against the wire
protocol and the schema registry: envelope shape, init handshake,
in_reply_to plumbing, error-code catalog membership, and every
client-facing reply type + field set a node emits. This catches the
protocol-drift class of bug (renamed fields, wrong reply types, codes
outside the catalog) without executing a line of JS; behavioral testing
still needs a runtime (VERDICT r2 weak #5 — the skips stop being a
blind spot for the wire vocabulary).
"""

import os
import re

import pytest

import maelstrom_tpu.workloads  # noqa: F401 — populate the registry
from maelstrom_tpu.core.errors import ERRORS_BY_CODE
from maelstrom_tpu.core.schema import REGISTRY, Opt

JS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "js")

SDK = open(os.path.join(JS_DIR, "node.js")).read()

# which registry namespace each JS node serves, plus the node-internal
# RPC types it exchanges with peers (not client-facing, so not in the
# registry; they must still be handled symmetrically)
NODES = {
    "echo.js": ("echo", set()),
    "broadcast.js": ("broadcast", {"gossip"}),
    "g_set.js": ("g-set", {"merge"}),
    "lin_kv_proxy.js": ("lin-kv", set()),
}


def _reply_bodies(src):
    """Yield (type, field set) for every object literal passed to
    node.reply(msg, { ... }) — top-level keys only."""
    for m in re.finditer(r"node\.reply\(\s*\w+\s*,\s*\{", src):
        depth, i = 1, m.end()
        while depth and i < len(src):
            depth += {"{": 1, "}": -1}.get(src[i], 0)
            i += 1
        body = src[m.end():i - 1]
        # strip nested literals so only top-level keys survive
        flat, depth = [], 0
        for ch in body:
            depth += {"{": 1, "[": 1, "}": -1, "]": -1}.get(ch, 0)
            if depth == 0 and ch not in "}]":
                flat.append(ch)
        flat = "".join(flat)
        tm = re.search(r'type:\s*"([^"]+)"', flat)
        if not tm:
            continue   # e.g. node.reply(msg, err.body()) passthroughs
        keys = set()
        for part in flat.split(","):
            # `key: expr`, or ES6 shorthand `key` alone
            km = re.match(r"\s*(\w+)\s*(?::|$)", part)
            if km:
                keys.add(km.group(1))
        yield tm.group(1), keys


def test_sdk_envelope_shape():
    """send() must write the {src, dest, body} envelope as one JSON
    line (resources/protocol-intro.md wire format)."""
    assert re.search(
        r"JSON\.stringify\(\{\s*src:\s*this\.nodeId,\s*dest,\s*body\s*\}"
        r"\)\s*\+\s*\"\\n\"", SDK), "envelope is not {src, dest, body}"


def test_sdk_init_handshake():
    """init must capture node_id/node_ids and reply init_ok."""
    assert 'body.type === "init"' in SDK
    assert "this.nodeId = body.node_id" in SDK
    assert "this.nodeIds = body.node_ids" in SDK
    assert re.search(r'reply\(msg,\s*\{\s*type:\s*"init_ok"\s*\}', SDK)


def test_sdk_reply_and_rpc_plumbing():
    """reply() correlates via in_reply_to = req.body.msg_id; rpc()
    allocates msg_id and dispatches responses on in_reply_to; error
    bodies carry type 'error' + code (errors.edn semantics)."""
    assert re.search(
        r"in_reply_to:\s*req\.body\.msg_id", SDK)
    assert re.search(r"\{\s*\.\.\.body,\s*msg_id:\s*msgId\s*\}", SDK)
    assert "body.in_reply_to" in SDK
    assert re.search(r'body\.type === "error"', SDK)
    assert re.search(
        r'\{\s*type:\s*"error",\s*code:\s*this\.code,\s*'
        r"text:\s*this\.text\s*\}", SDK)


def test_sdk_error_codes_in_catalog():
    """Every numeric code the SDK constructs must exist in the error
    catalog (core/errors.py mirrors resources/errors.edn)."""
    codes = {int(c) for c in
             re.findall(r"new RPCError\((\d+)", SDK)}
    assert codes, "no RPCError constructions found"
    unknown = codes - set(ERRORS_BY_CODE)
    assert not unknown, f"codes outside the catalog: {unknown}"


def test_sdk_kv_client_matches_service_schema():
    """The KV client's request bodies must use the service RPC field
    names (read key / write key value / cas key from to
    create_if_not_exists)."""
    assert re.search(r'\{\s*type:\s*"read",\s*key\s*\}', SDK)
    assert re.search(r'\{\s*type:\s*"write",\s*key,\s*value\s*\}', SDK)
    cas = re.search(r'\{\s*type:\s*"cas",\s*key,\s*from,\s*to,\s*'
                    r"create_if_not_exists:", SDK)
    assert cas, "cas body drifted from the service schema"


@pytest.mark.parametrize("fname", sorted(NODES))
def test_node_reply_vocabulary(fname):
    """Every client-facing reply a JS node emits must be the registered
    response type of its workload's RPC, carrying at least the
    schema-required response fields; internal peer RPCs must have a
    matching handler registered in the same file."""
    ns, internal = NODES[fname]
    src = open(os.path.join(JS_DIR, fname)).read()
    rpcs = REGISTRY[ns]
    expected = {d.response_type: d for d in rpcs.values()}
    handled = set(re.findall(r'node\.on\("(\w+)"', src))

    replies = list(_reply_bodies(src))
    assert replies, f"{fname}: no reply literals found"
    seen_types = set()
    for rtype, keys in replies:
        if rtype.endswith("_ok") and rtype[:-3] in internal:
            assert rtype[:-3] in handled, \
                f"{fname}: internal RPC {rtype[:-3]} acked but not handled"
            continue
        assert rtype in expected, \
            f"{fname}: reply type {rtype!r} not in the {ns} schema"
        d = expected[rtype]
        required = {k for k in d.response
                    if isinstance(k, str) and not isinstance(k, Opt)}
        missing = required - keys
        assert not missing, \
            f"{fname}: {rtype} reply missing fields {missing}"
        seen_types.add(rtype)

    # node must answer every client RPC of its workload
    unanswered = {n for n, d in rpcs.items()
                  if d.response_type not in seen_types and n in handled}
    covered = {n for n in rpcs if n in handled}
    assert covered, f"{fname}: handles none of the {ns} RPCs"
    assert not unanswered, \
        f"{fname}: handles {unanswered} but never sends the ok reply"
