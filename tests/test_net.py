"""Unit tests for the host simulated network (SURVEY §2.1 net.clj
semantics: deadline ordering, client zero-latency, receiver-side partition
drop, loss)."""

import time

from maelstrom_tpu.net.net import Latency, Net


def make_net(**kw):
    net = Net(**kw)
    for n in ("n0", "n1", "c0"):
        net.add_node(n)
    return net


def test_send_recv_roundtrip():
    net = make_net(seed=0)
    net.send("n0", "n1", {"type": "hi", "msg_id": 1})
    m = net.recv("n1", timeout=1.0)
    assert m is not None
    assert m.src == "n0" and m.dest == "n1" and m.body["type"] == "hi"


def test_recv_timeout_returns_none():
    net = make_net(seed=0)
    t0 = time.monotonic()
    assert net.recv("n1", timeout=0.05) is None
    assert time.monotonic() - t0 >= 0.04


def test_latency_delays_server_traffic_but_not_clients():
    net = make_net(latency=Latency(50, "constant"), seed=0)
    # server->server takes ~50ms
    t0 = time.monotonic()
    net.send("n0", "n1", {"type": "x"})
    assert net.recv("n1", timeout=1.0) is not None
    assert time.monotonic() - t0 >= 0.045
    # client traffic is always zero-latency (net.clj:178-187)
    t0 = time.monotonic()
    net.send("c0", "n1", {"type": "x"})
    assert net.recv("n1", timeout=1.0) is not None
    assert time.monotonic() - t0 < 0.04


def test_deadline_ordering_not_fifo():
    net = make_net(seed=0)
    # manually enqueue with distinct latencies by toggling the latency dist
    net.latency = Latency(100, "constant")
    net.send("n0", "n1", {"type": "slow"})
    net.latency = Latency(0, "constant")
    net.send("n0", "n1", {"type": "fast"})
    m1 = net.recv("n1", timeout=1.0)
    m2 = net.recv("n1", timeout=1.0)
    assert m1.body["type"] == "fast"
    assert m2.body["type"] == "slow"


def test_partition_drops_at_delivery():
    net = make_net(seed=0)
    net.drop("n0", "n1")  # n1 refuses messages from n0
    net.send("n0", "n1", {"type": "x"})
    assert net.recv("n1", timeout=0.1) is None
    # other direction unaffected
    net.send("n1", "n0", {"type": "y"})
    assert net.recv("n0", timeout=1.0) is not None
    net.heal()
    net.send("n0", "n1", {"type": "z"})
    assert net.recv("n1", timeout=1.0) is not None


def test_loss():
    net = make_net(p_loss=1.0, seed=0)
    net.send("n0", "n1", {"type": "x"})
    assert net.recv("n1", timeout=0.1) is None


def test_journal_counts():
    net = make_net(seed=0)
    net.send("n0", "n1", {"type": "x"})
    net.recv("n1", timeout=1.0)
    net.send("c0", "n0", {"type": "y"})
    net.recv("n0", timeout=1.0)
    s = net.journal.stats()
    assert s["all"]["send-count"] == 2
    assert s["all"]["recv-count"] == 2
    assert s["servers"]["msg-count"] == 1
    assert s["clients"]["msg-count"] == 1


def test_flaky_and_slow_adapters():
    net = make_net(seed=0)
    net.flaky()
    assert net.p_loss == 0.5
    net.reliable()
    assert net.p_loss == 0.0
    net.slow()
    assert net.latency.mean == 0.0  # base was 0
    net.fast()
