"""TPU-runtime CRDT models: broadcast / g-set / pn-counter end-to-end on
the virtual CPU mesh, including partition-nemesis runs (SURVEY §7 step 6)."""
import pytest

from maelstrom_tpu.models.crdt import (BroadcastModel, GCounterModel,
                                       GossipSetModel, PNCounterModel)
from maelstrom_tpu.tpu.harness import run_tpu_test


def test_tpu_g_set():
    res = run_tpu_test(GossipSetModel("grid"), dict(
        node_count=5, concurrency=2, n_instances=8, record_instances=4,
        time_limit=2.0, rate=20.0, latency=5.0, rpc_timeout=0.5, seed=7))
    assert res["valid?"] is True, res["instances"]
    inst = res["instances"][0]
    assert inst["acknowledged-count"] > 0
    assert inst["lost-count"] == 0


@pytest.mark.slow
def test_tpu_broadcast_partition():
    res = run_tpu_test(BroadcastModel("grid"), dict(
        node_count=5, concurrency=2, n_instances=8, record_instances=4,
        time_limit=3.0, rate=20.0, latency=5.0, rpc_timeout=0.5,
        nemesis=["partition"], nemesis_interval=0.3, seed=9))
    # partitions must actually bite (server gossip dropped)...
    assert res["net"]["dropped-partition"] > 0
    # ...and anti-entropy must still deliver every acknowledged broadcast
    assert res["valid?"] is True, res["instances"]


@pytest.mark.slow
def test_tpu_pn_counter():
    res = run_tpu_test(PNCounterModel(n_nodes_hint=3, topology="total"),
                       dict(node_count=3, concurrency=2, n_instances=8,
                            record_instances=4, time_limit=2.0, rate=20.0,
                            latency=5.0, rpc_timeout=0.5, seed=11))
    assert res["valid?"] is True, res["instances"]
    inst = res["instances"][0]
    assert inst["final-reads"], inst


@pytest.mark.slow
def test_tpu_g_counter():
    res = run_tpu_test(GCounterModel(n_nodes_hint=3, topology="total"),
                       dict(node_count=3, concurrency=2, n_instances=4,
                            record_instances=2, time_limit=1.5, rate=20.0,
                            latency=5.0, rpc_timeout=0.5, seed=13))
    assert res["valid?"] is True, res["instances"]
