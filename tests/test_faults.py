"""Device-resident fault-plan engine: spec/compile units, the
all-healthy bit-identity guarantee, and the planted-bug anomaly matrix.

The engine's contract (doc/guide/10-faults.md) has three legs, each
pinned here:

1. **Bit-identity** — a fault plan whose lanes are present but
   value-neutral (zero delay/loss, rate-1.0 skew, crash phases beyond
   the horizon) produces trajectories BIT-IDENTICAL to a fault-free
   run, in BOTH carry layouts; and an active plan produces identical
   trajectories across layouts (the engine rides the same vmapped
   per-instance code both ways). Combined with the frozen pre-refactor
   goldens (tests/test_node_fusion.py), this proves fault-free runs
   are bit-identical to pre-fault-engine history.
2. **Anomaly matrix** — for each fault lane, a planted-bug model trips
   its checker while the CORRECT model stays valid under the SAME
   plan: crash-restart vs RaftForgetsSnapshot (amnesiac recovery →
   committed-prefix/election-safety invariants + WGL), clock skew vs
   RaftFixedTimeout (lockstep livelock → availability), link
   degradation vs RaftStaleRead (lagging replicas served locally →
   WGL).
3. **Observatory integration** — the funnel replays violating
   instances bit-exactly under a fault plan (instance-stable RNG holds
   with the new restart lane), and fault epochs ride the heartbeat.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest

from maelstrom_tpu.faults import (FAULT_KINDS, FaultConfig, SpecError,
                                  compile_fault_plan,
                                  generate_fault_plan,
                                  validate_fault_plan)
from maelstrom_tpu.faults.engine import phase_at, phase_summary
from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu.harness import (make_sim_config, replay_instances,
                                       run_tpu_test)
from maelstrom_tpu.tpu.runtime import canonical_carry, run_sim

pytestmark = pytest.mark.faults


# --- shared fixtures -------------------------------------------------------

# crash-lane matrix plan: commit writes on a healthy cluster, crash a
# MAJORITY {0, 1}, then isolate the full-log survivor so the restarted
# pair must form a quorum from whatever their recovery preserved.
# Correct Raft recovers its durable term/vote/log from the snapshot
# slab and elects safely; the forget-snapshot mutant reboots amnesiac
# and commits fresh entries over slots the survivor holds committed —
# the on-device committed-prefix invariant trips.
_ISOLATE_2 = [{"dst": 2, "src": 0, "block": True},
              {"dst": 2, "src": 1, "block": True},
              {"dst": 0, "src": 2, "block": True},
              {"dst": 1, "src": 2, "block": True}]
CRASH_PLAN = {"phases": [{"until": 220},
                         {"until": 280, "crash": [0, 1]},
                         {"until": 520, "links": _ISOLATE_2},
                         {"until": 700}]}
# inbox_k=2 / pool_slots=24 throughout: an ~8x smaller unrolled inbox
# graph per compile (the suite's dominant cost), anomaly rates verified
# across seeds at exactly these shapes
CRASH_OPTS = dict(node_count=3, concurrency=4, n_instances=32,
                  record_instances=4, time_limit=0.7, rate=300.0,
                  latency=5.0, rpc_timeout=0.08, recovery_time=0.1,
                  fault_plan=CRASH_PLAN, heartbeat=False, seed=7,
                  funnel_max=6, inbox_k=2, pool_slots=24)

# skew-lane matrix plan: a uniformly 2x-fast cluster — elections fire
# twice as often relative to network latency. Jittered timeouts break
# the symmetry; the fixed-timeout mutant's deadlines collide in
# lockstep forever (no leader, zero acks).
SKEW_PLAN = {"phases": [{"until": 10_000,
                         "skew": {"0": 2.0, "1": 2.0, "2": 2.0}}]}
SKEW_OPTS = dict(node_count=3, concurrency=4, n_instances=8,
                 record_instances=4, time_limit=0.6, rate=300.0,
                 latency=5.0, latency_dist="constant", rpc_timeout=0.08,
                 recovery_time=0.1, availability=0.15, funnel=False,
                 heartbeat=False, fault_plan=SKEW_PLAN, seed=7,
                 inbox_k=2, pool_slots=24)

# link-lane matrix plan: every server-server edge slow AND lossy —
# replication lags hard, so locally-served reads are stale.
_DEGRADE_ALL = [{"dst": d, "src": s, "delay": 45, "loss": 0.35}
                for d in range(3) for s in range(3) if d != s]
LINK_PLAN = {"phases": [{"until": 120},
                        {"until": 800, "links": _DEGRADE_ALL}]}
LINK_OPTS = dict(node_count=3, concurrency=8, n_instances=16,
                 record_instances=8, time_limit=0.8, rate=500.0,
                 latency=5.0, rpc_timeout=0.08, recovery_time=0.1,
                 fault_plan=LINK_PLAN, funnel=False, heartbeat=False,
                 seed=7, inbox_k=2, pool_slots=24)


def _run_carry(workload, opts, layout="lead"):
    model = get_model(workload, opts["node_count"])
    sim = make_sim_config(model, {**opts, "layout": layout})
    return model, sim, run_sim(model, sim, opts["seed"],
                               model.make_params(opts["node_count"]))


# --- spec / compile units --------------------------------------------------


class TestSpec:
    def test_compile_roundtrip(self):
        fx = compile_fault_plan(CRASH_PLAN, 3, stop_tick=600)
        assert fx.enabled and fx.has_crash and fx.has_links
        assert not fx.has_skew
        assert fx.untils == (220, 280, 520, 700)
        assert fx.crash[1] == (0, 1)
        assert len(fx.links[2]) == 4
        # phases index correctly, and stop_tick heals
        assert phase_at(fx, 0) == 0
        assert phase_at(fx, 250) == 1
        assert phase_at(fx, 280) == 2
        assert phase_at(fx, 599) == 3
        assert phase_at(fx, 600) == 4      # healed row
        s = phase_summary(fx, 250)
        assert s["crashed"] == [0, 1]

    def test_none_plan_is_disabled(self):
        fx = compile_fault_plan(None, 3, stop_tick=600)
        assert fx == FaultConfig()
        assert not fx.active

    def test_loss_stored_per_mille_and_skew_in_64ths(self):
        fx = compile_fault_plan(
            {"phases": [{"until": 10,
                         "links": [{"dst": 0, "src": 1, "loss": 0.25}],
                         "skew": {"2": 1.5}}]}, 3, stop_tick=600)
        assert fx.links[0][0][4] == 250
        assert fx.skew[0] == ((2, 96),)

    @pytest.mark.parametrize("plan,msg", [
        ({}, "phases"),
        ({"phases": [{"until": 0}]}, "until"),
        ({"phases": [{"until": 10}, {"until": 5}]}, "until"),
        ({"phases": [{"until": 10, "crash": [7]}]}, "out of range"),
        ({"phases": [{"until": 10,
                      "links": [{"dst": 0, "src": 1, "loss": 2.0}]}]},
         "loss"),
        ({"phases": [{"until": 10, "skew": {"0": 100.0}}]}, "rate"),
        ({"snapshot_every": 0, "phases": [{"until": 10}]},
         "snapshot_every"),
    ])
    def test_validation_rejects(self, plan, msg):
        with pytest.raises(SpecError, match=msg):
            validate_fault_plan(plan, 3)

    def test_dash_keys_tolerated(self):
        fx = compile_fault_plan(
            {"snapshot-every": 2,
             "phases": [{"until": 10, "crash": [0]}]}, 3, stop_tick=600)
        assert fx.snapshot_every == 2 and fx.crash[0] == (0,)

    def test_generators_compose(self):
        plan = generate_fault_plan(list(FAULT_KINDS), 3, 600, 50, 500)
        fx = compile_fault_plan(plan, 3, stop_tick=500)
        assert fx.has_crash and fx.has_links and fx.has_skew
        # crash victims are always a minority (correct models must
        # survive the generated plan)
        for victims in fx.crash:
            assert len(victims) <= 1
        # skew alone produces a single whole-run phase
        solo = compile_fault_plan(
            generate_fault_plan(["clock-skew"], 3, 600, 50, 500),
            3, stop_tick=500)
        assert solo.has_skew and not solo.has_crash
        assert len(solo.untils) == 1

    def test_duplicate_edge_entries_merge(self):
        """Two entries for one directed edge combine (the documented
        'one edge may combine delay and loss') instead of the second
        zeroing the first's fields."""
        from maelstrom_tpu.faults.engine import _planes_np
        fx = compile_fault_plan(
            {"phases": [{"until": 50, "links": [
                {"dst": 0, "src": 1, "delay": 20},
                {"dst": 0, "src": 1, "loss": 0.25},
                {"dst": 0, "src": 1, "block": True}]}]},
            3, stop_tick=600)
        _, _, block, delay, loss, _, _ = _planes_np(fx, 3, 2)
        assert delay[0, 0, 1] == 20
        assert loss[0, 0, 1] == 250
        assert block[0, 0, 1]

    def test_single_node_fault_kinds_rejected(self):
        """crash-restart/link-degrade cannot target a 1-node cluster:
        asking for them must be a hard error, not a silently fault-free
        'valid' run; clock-skew (which can) still works."""
        kafka = get_model("kafka", 1)
        with pytest.raises(ValueError, match="no fault lanes"):
            make_sim_config(kafka, dict(node_count=1,
                                        nemesis=["crash-restart"]))
        sim = make_sim_config(kafka, dict(node_count=1,
                                          nemesis=["clock-skew"]))
        assert sim.faults.has_skew

    def test_generator_clamps_oversized_interval(self):
        """A nemesis interval longer than the horizon must still yield
        an ACTIVE plan (at least one fault phase) — asking for faults
        and silently running fault-free would be a lie. This is the
        default 10s interval vs a 2-3s run."""
        for kinds in (["crash-restart"], ["crash-restart",
                                          "clock-skew"]):
            plan = generate_fault_plan(kinds, 3, n_ticks=2500,
                                       interval=10_000, stop_tick=2400)
            fx = compile_fault_plan(plan, 3, stop_tick=2400)
            assert fx.active, (kinds, plan)
            assert fx.has_crash
            if "clock-skew" in kinds:
                assert fx.has_skew


# --- bit-identity ----------------------------------------------------------

# lanes PRESENT but value-neutral: zero delay/loss edges, rate-1.0 skew
# on every node, and a crash phase parked beyond stop_tick — the full
# engine machinery (snapshot slab, wipe select, edge planes, local
# clocks) is in the graph, with values identical to the healthy path
_NEUTRAL_PLAN = {"phases": [
    {"until": 250,
     "links": [{"dst": 0, "src": 1, "delay": 0, "loss": 0.0}],
     "skew": {str(i): 1.0 for i in range(3)}},
    {"until": 100_000, "crash": [0]}]}

_IDENTITY_OPTS = dict(node_count=3, concurrency=2, n_instances=4,
                      record_instances=2, time_limit=0.3, rate=200.0,
                      latency=5.0, p_loss=0.05, nemesis=["partition"],
                      nemesis_interval=0.05, seed=0, inbox_k=2,
                      pool_slots=24)


class TestBitIdentity:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_all_healthy_plan_bit_identical(self, layout):
        """A value-neutral plan (every lane exercised) reproduces the
        fault-free trajectory bit-for-bit — composed with the partition
        nemesis, which must keep working unchanged."""
        model = get_model("lin-kv", 3)
        sim = make_sim_config(model, {**_IDENTITY_OPTS,
                                      "layout": layout})
        fx = compile_fault_plan(_NEUTRAL_PLAN, 3,
                                stop_tick=sim.nemesis.stop_tick)
        params = model.make_params(3)
        base_c, base_y = run_sim(model, sim, 0, params)
        neut_c, neut_y = run_sim(model, sim._replace(faults=fx), 0,
                                 params)
        assert neut_c.snapshots is not None   # the machinery really ran
        for a, b in zip(
                jax.tree.leaves((base_c.pool, base_c.node_state,
                                 base_c.client_state, base_c.stats,
                                 base_c.violations)),
                jax.tree.leaves((neut_c.pool, neut_c.node_state,
                                 neut_c.client_state, neut_c.stats,
                                 neut_c.violations))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(base_y.events),
                                      np.asarray(neut_y.events))

    def test_active_plan_layout_independent(self):
        """An ACTIVE plan (crash + links + skew all firing) produces
        bit-identical trajectories in both carry layouts."""
        opts = dict(_IDENTITY_OPTS, fault_plan=None, nemesis=[])
        plan = {"phases": [{"until": 80},
                           {"until": 140, "crash": [0, 1]},
                           {"until": 220,
                            "links": [{"dst": 0, "src": 2, "delay": 10},
                                      {"dst": 2, "src": 0, "loss": 0.3},
                                      {"dst": 1, "src": 2,
                                       "block": True}]},
                           {"until": 280, "skew": {"0": 2.0,
                                                   "1": 0.5}}]}
        out = {}
        for layout in ("lead", "minor"):
            model = get_model("lin-kv", 3)
            sim = make_sim_config(model, {**opts, "layout": layout})
            fx = compile_fault_plan(plan, 3,
                                    stop_tick=sim.nemesis.stop_tick)
            sim = sim._replace(faults=fx)
            c, y = run_sim(model, sim, 0, model.make_params(3))
            canon = canonical_carry(c, sim)
            out[layout] = (jax.tree.leaves(
                (canon.pool, canon.node_state, canon.client_state,
                 canon.stats, canon.violations, canon.snapshots)),
                np.asarray(y.events))
        for a, b in zip(out["lead"][0], out["minor"][0]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(out["lead"][1], out["minor"][1])


# --- the anomaly matrix ----------------------------------------------------


class TestCrashRestartLane:
    def test_forget_snapshot_caught_correct_model_survives(self):
        """The crash lane's planted bug: amnesiac recovery commits over
        the survivor's committed prefix — the on-device invariant
        trips across most of the fleet and the funnel's bit-exact
        replay confirms every tripper; correct Raft under the SAME
        plan recovers from its snapshots and stays fully valid."""
        bug = run_tpu_test(get_model("lin-kv-bug-forget-snapshot", 3),
                           dict(CRASH_OPTS))
        assert bug["valid?"] is False
        tripped = bug["invariants"]["violating-instances"]
        assert tripped >= 8, bug["invariants"]
        # the funnel replayed the flagged subset into violation again
        # — instance-stable RNG holds across the restart lane (the
        # bit-exact-replay contract under an active fault plan)
        funnel = bug["funnel"]
        assert funnel["replayed-violating"] == len(funnel["ids"]) > 0

        ok = run_tpu_test(get_model("lin-kv", 3), dict(CRASH_OPTS))
        assert ok["valid?"] is True
        assert ok["invariants"]["violating-instances"] == 0

    @pytest.mark.slow
    def test_crash_actually_perturbs_the_trajectory(self):
        """Guard against a silently inert lane: the crash plan must
        change the correct model's trajectory vs a fault-free run."""
        _, _, (c_fault, _) = _run_carry("lin-kv", CRASH_OPTS)
        _, _, (c_plain, _) = _run_carry(
            "lin-kv", {**CRASH_OPTS, "fault_plan": None})
        assert not np.array_equal(
            np.asarray(c_fault.node_state.commit_idx),
            np.asarray(c_plain.node_state.commit_idx))


class TestClockSkewLane:
    def test_fixed_timeout_livelocks_correct_model_elects(self):
        """The skew lane's planted bug: deterministic election
        deadlines collide in lockstep — no leader, zero acks, the
        availability checker flags the livelock. Correct Raft's
        randomized timeouts elect fine under the SAME 2x-fast plan."""
        bug = run_tpu_test(get_model("lin-kv-bug-fixed-timeout", 3),
                           dict(SKEW_OPTS))
        assert bug["valid?"] is False
        assert bug["availability"]["valid?"] is False
        assert bug["availability"]["ok-count"] == 0

        ok = run_tpu_test(get_model("lin-kv", 3), dict(SKEW_OPTS))
        assert ok["valid?"] is True
        assert ok["availability"]["ok-count"] > 0


class TestLinkDegradationLane:
    def test_stale_read_caught_correct_model_survives(self):
        """The link lane vs the stale-read mutant: slow lossy
        replication makes locally-served reads stale (WGL catches the
        linearizability violation); correct Raft reads through the log
        and stays valid under the SAME degraded edges."""
        bug = run_tpu_test(get_model("lin-kv-bug-stale-read", 3),
                           dict(LINK_OPTS))
        assert bug["valid?"] is False
        assert bug["valid-instances"] < bug["checked-instances"]

        ok = run_tpu_test(get_model("lin-kv", 3), dict(LINK_OPTS))
        assert ok["valid?"] is True
        assert ok["valid-instances"] == ok["checked-instances"]


@pytest.mark.slow
class TestAnomalyMatrixSweep:
    """The full matrix across extra seeds — the cheap representatives
    above keep one pinned seed per lane inside the tier-1 budget."""

    @pytest.mark.parametrize("seed", [11, 13])
    def test_crash_lane(self, seed):
        bug = run_tpu_test(get_model("lin-kv-bug-forget-snapshot", 3),
                           dict(CRASH_OPTS, seed=seed))
        ok = run_tpu_test(get_model("lin-kv", 3),
                          dict(CRASH_OPTS, seed=seed))
        assert bug["valid?"] is False and ok["valid?"] is True

    @pytest.mark.parametrize("seed", [11, 13])
    def test_link_lane(self, seed):
        bug = run_tpu_test(get_model("lin-kv-bug-stale-read", 3),
                           dict(LINK_OPTS, seed=seed))
        ok = run_tpu_test(get_model("lin-kv", 3),
                          dict(LINK_OPTS, seed=seed))
        assert bug["valid?"] is False and ok["valid?"] is True

    @pytest.mark.parametrize("seed", [11, 13])
    def test_skew_lane(self, seed):
        bug = run_tpu_test(get_model("lin-kv-bug-fixed-timeout", 3),
                           dict(SKEW_OPTS, seed=seed))
        ok = run_tpu_test(get_model("lin-kv", 3),
                          dict(SKEW_OPTS, seed=seed))
        assert bug["valid?"] is False and ok["valid?"] is True

    def test_generated_minority_crash_plan_is_survivable(self):
        """The CLI's generated crash-restart plan (one rotating victim
        at a time) must be survivable by correct Raft — the safety bar
        for the composable --nemesis vocabulary."""
        opts = dict(node_count=3, concurrency=4, n_instances=16,
                    record_instances=4, time_limit=0.8, rate=200.0,
                    latency=5.0, rpc_timeout=0.08, recovery_time=0.15,
                    nemesis=["crash-restart"], nemesis_interval=0.08,
                    heartbeat=False, seed=7)
        res = run_tpu_test(get_model("lin-kv", 3), opts)
        assert res["valid?"] is True
        assert res["invariants"]["violating-instances"] == 0


# --- observatory integration ----------------------------------------------


class TestObservatory:
    def test_fault_epochs_ride_the_heartbeat(self, tmp_path):
        """Chunked fault runs stream their fault epoch per chunk, and
        the run-start header labels the plan's lanes (model-agnostic —
        a cheap echo fleet exercises the whole path)."""
        plan = {"phases": [{"until": 100},
                           {"until": 140, "crash": [1]},
                           {"until": 220,
                            "links": [{"dst": 0, "src": 1,
                                       "delay": 5}]}]}
        opts = dict(node_count=2, concurrency=2, n_instances=8,
                    record_instances=2, time_limit=0.3, rate=100.0,
                    latency=5.0, recovery_time=0.05, seed=3,
                    fault_plan=plan, funnel=False,
                    store_root=str(tmp_path), pipeline="on",
                    chunk_ticks=50)
        run_tpu_test(get_model("echo", 2), opts)
        from maelstrom_tpu.telemetry.stream import read_heartbeat
        run_dir = os.path.realpath(
            os.path.join(str(tmp_path), "echo-tpu", "latest"))
        hb = read_heartbeat(run_dir)
        assert hb["header"]["faults"]["lanes"] == [
            "crash-restart", "link-degradation"]
        faults = [rec.get("fault") for rec in hb["chunks"]]
        assert all(f is not None for f in faults)
        # the crash phase [100, 140) lands inside the 100..150 chunk
        crashed = [f for f in faults if f.get("crashed")]
        assert crashed and crashed[0]["crashed"] == [1]
        assert faults[-1].get("healthy") is True

    @pytest.mark.slow
    def test_replay_is_bit_exact_under_fault_plan(self):
        """replay_instances on specific ids reproduces the violating
        trajectories (the triage/funnel contract) with fault lanes
        active — the standalone form of the funnel self-check the fast
        crash-lane test already pins."""
        model = get_model("lin-kv-bug-forget-snapshot", 3)
        _, _, (carry, _) = _run_carry("lin-kv-bug-forget-snapshot",
                                      CRASH_OPTS)
        viol = np.nonzero(np.asarray(carry.violations))[0]
        ids = [int(i) for i in viol[:3]]
        assert ids
        rep = replay_instances(model, dict(CRASH_OPTS), ids)
        assert rep["replayed-violating"] == len(ids)


# --- kafka crash-clients (TPU/native vocabulary parity) --------------------


KAFKA_OPTS = dict(node_count=1, concurrency=4, n_instances=8,
                  record_instances=4, time_limit=1.0, rate=300.0,
                  latency=5.0, seed=3, funnel=False, heartbeat=False)


@pytest.fixture(scope="module")
def kafka_crash_histories():
    """One shared replay of the crash-clients fleet — every kafka
    parity assertion reads these histories instead of re-simulating."""
    model = get_model("kafka", 1, opts={"crash_clients": True})
    rep = replay_instances(model, dict(KAFKA_OPTS), list(range(8)))
    return rep["histories"]


class TestKafkaCrashClients:
    def test_crash_clients_valid_end_to_end(self):
        model = get_model("kafka", 1, opts={"crash_clients": True})
        assert model.crash_clients
        res = run_tpu_test(model, dict(KAFKA_OPTS))
        assert res["valid?"] is True

    def test_crashes_fired(self, kafka_crash_histories):
        crashes = sum(1 for h in kafka_crash_histories.values()
                      for r in h if r.get("f") == "crash"
                      and r["type"] == "invoke")
        assert crashes >= 3, "crash injection never fired"

    def test_reassigned_marking_is_load_bearing(self,
                                                kafka_crash_histories):
        """Run the raw checker WITHOUT the reassigned tagging and it
        must see the backward jumps (external nonmonotonic) the tag
        legalizes — proving the committed-offset resume actually
        rewinds consumers; with the tagging, every history is clean."""
        from maelstrom_tpu.checkers.kafka import (
            kafka_checker, mark_reassigned_after_crashes)
        union_hit = False
        for h in kafka_crash_histories.values():
            naked = kafka_checker(h)
            marked = kafka_checker(mark_reassigned_after_crashes(h))
            assert marked["valid?"] is True, marked["anomaly-types"]
            if "external-nonmonotonic" in naked["anomaly-types"]:
                union_hit = True
        assert union_hit, ("no consumer ever rewound — the crash "
                           "lane is inert")

    def test_default_kafka_never_crashes(self):
        model = get_model("kafka", 1)
        assert not model.crash_clients
        rep = replay_instances(model, dict(KAFKA_OPTS), [0, 1])
        assert not any(r.get("f") == "crash"
                       for h in rep["histories"].values() for r in h)


class TestModelSelectionParity:
    def test_dirty_apply_flag_selects_mutant(self):
        for wl in ("txn-list-append", "txn-rw-register"):
            m = get_model(wl, 3, opts={"txn_dirty_apply": True})
            assert m.name == f"{wl}-bug-dirty-apply"
            assert get_model(wl, 3).name == wl

    def test_resolve_model_honors_parity_flags(self):
        from maelstrom_tpu.checkers.triage import resolve_model
        m = resolve_model({"workload": "kafka",
                           "opts": {"node_count": 1,
                                    "crash_clients": True},
                           "model-config": {}})
        assert m.crash_clients

    def test_new_mutants_registered(self):
        assert get_model("lin-kv-bug-forget-snapshot", 3).name \
            == "lin-kv-bug-forget-snapshot"
        m = get_model("lin-kv-bug-fixed-timeout", 3)
        assert m.elect_jitter == 1
