"""Cross-validation: the device netsim against the host simulated network
(the oracle). The two implement the same network semantics — latency
distributions, loss rates, partition behavior — so their observable
statistics must agree within sampling error (SURVEY §7 hard parts:
"same-seed cross-validation is the race-detector for the TPU runtime
itself")."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from maelstrom_tpu.net.net import Latency
from maelstrom_tpu.tpu import netsim, wire
from maelstrom_tpu.tpu.netsim import NetConfig


def _device_latency_samples(dist: int, mean: float, n: int) -> np.ndarray:
    cfg = NetConfig(n_nodes=2, n_clients=0, pool_slots=4, inbox_k=1,
                    body_lanes=1, latency_mean=mean, latency_dist=dist,
                    p_loss=0.0)
    keys = jax.random.split(jax.random.PRNGKey(0), n)
    msg = wire.make_msg(src=0, dest=1, type_=1, body_lanes=1)[None]

    def one(key):
        pool = netsim.empty_pool(cfg)
        pool, *_ = netsim.enqueue(pool, msg, jnp.int32(0), key, cfg)
        return pool[0, wire.DTICK] - 1   # deadline = t + 1 + latency

    return np.asarray(jax.vmap(one)(keys))


def _host_latency_samples(dist: str, mean: float, n: int) -> np.ndarray:
    lat = Latency(mean, dist)
    rng = random.Random(0)
    return np.array([lat.draw(rng) for _ in range(n)])


def test_latency_distributions_match_host_oracle():
    n = 4000
    for dist_name, dist_id in (("constant", 0), ("uniform", 1),
                               ("exponential", 2)):
        host = _host_latency_samples(dist_name, 50.0, n)
        dev = _device_latency_samples(dist_id, 50.0, n)
        # device quantizes to integer ticks (floor): mean shifts ~-0.5
        assert abs(host.mean() - dev.mean()) < 3.0, \
            (dist_name, host.mean(), dev.mean())
        if dist_name != "constant":
            assert abs(np.percentile(host, 90)
                       - np.percentile(dev, 90)) < 10.0, dist_name


def test_loss_rate_matches_host_oracle():
    n = 4000
    p = 0.3
    cfg = NetConfig(n_nodes=2, n_clients=0, pool_slots=4, inbox_k=1,
                    body_lanes=1, latency_mean=0, latency_dist=0,
                    p_loss=p)
    keys = jax.random.split(jax.random.PRNGKey(1), n)
    msg = wire.make_msg(src=0, dest=1, type_=1, body_lanes=1)[None]

    def one(key):
        pool = netsim.empty_pool(cfg)
        _, _, lost, _ = netsim.enqueue(pool, msg, jnp.int32(0), key, cfg)
        return lost

    losses = float(np.asarray(jax.vmap(one)(keys)).sum()) / n
    assert abs(losses - p) < 0.03, losses


def test_client_links_zero_latency_both_runtimes():
    # host behavior is asserted in test_net.py; the device side must
    # agree: client-edge messages deliver on the next tick regardless of
    # the configured latency
    cfg = NetConfig(n_nodes=2, n_clients=1, pool_slots=4, inbox_k=1,
                    body_lanes=1, latency_mean=500.0, latency_dist=2,
                    p_loss=0.0)
    msg = wire.make_msg(src=2, dest=0, type_=1, body_lanes=1)[None]
    pool = netsim.empty_pool(cfg)
    pool, *_ = netsim.enqueue(pool, msg, jnp.int32(0),
                              jax.random.PRNGKey(0), cfg)
    assert int(pool[0, wire.DTICK]) == 1
