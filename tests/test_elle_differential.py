"""Differential test: the Elle-style checker vs a brute-force
serialization oracle (VERDICT r4 next #6).

The oracle decides serializability EXACTLY for small histories: try
every permutation of the committed transactions, simulate list-append /
register semantics, and accept iff some permutation explains every
committed read (for strict serializability, only permutations that are
linear extensions of the real-time interval order count). Histories are
generated from a simulated correct DB (always valid by construction),
then corrupted with targeted mutations (lost append, stale read,
aborted read, intermediate read, phantom value, reordered read); the
ground truth on mutants comes from the oracle, not from the mutation's
intent — a "stale read" of a concurrent txn can still be serializable.

Checked both ways, per consistency model:
- soundness: oracle-valid histories must pass the checker;
- completeness: oracle-invalid histories must fail it (for these
  generators every version order is observable — each key ends with a
  full final read — which is the regime where Elle-style inference is
  complete).
"""

import itertools
import random

import pytest

from maelstrom_tpu.checkers.elle import check_list_append, check_rw_register

MODELS = ("serializable", "strict-serializable")


# --- brute-force oracle ---------------------------------------------------

def _txns(history):
    """(committed, failed_values) from a history; committed txns carry
    (invoke_index, end_index, ops)."""
    committed, open_by_proc = [], {}
    for r in history:
        p = r["process"]
        if r["type"] == "invoke":
            open_by_proc[p] = r
        elif r["type"] in ("ok", "fail"):
            inv = open_by_proc.pop(p)
            if r["type"] == "ok":
                committed.append({"invoke": inv["index"],
                                  "end": r["index"],
                                  "ops": r["value"]})
    return committed


def _replay_ok(perm, kind):
    """Does executing ``perm`` (list of op-lists) in order explain every
    read? kind: 'append' (state = list per key) or 'w' (register)."""
    state = {}
    for ops in perm:
        for f, k, v in ops:
            if f == "append":
                state.setdefault(k, [])
                state[k] = state[k] + [v]
            elif f == "w":
                state[k] = v
            elif f == "r":
                if kind == "append":
                    if list(v or []) != state.get(k, []):
                        return False
                else:
                    if v != state.get(k):
                        return False
    return True


def oracle(history, model, kind="append"):
    """True iff the committed txns have a (real-time-respecting, when
    strict) serialization explaining all reads. Exponential — callers
    keep histories <= 6 committed txns."""
    committed = _txns(history)
    n = len(committed)
    order = range(n)
    for perm in itertools.permutations(order):
        if model == "strict-serializable":
            pos = {t: i for i, t in enumerate(perm)}
            if any(committed[a]["end"] < committed[b]["invoke"]
                   and pos[a] > pos[b]
                   for a in order for b in order if a != b):
                continue
        if _replay_ok([committed[t]["ops"] for t in perm], kind):
            return True
    return False


# --- valid-history generator ----------------------------------------------

def gen_history(rng, kind="append", n_txns=5, n_keys=2):
    """Simulate a correct sequential DB, emitting overlapping intervals
    (adjacent txns on distinct processes sometimes overlap — the
    serialization order still respects real time). Every key gets a
    final full read so version orders are fully observable. Some runs
    include a definitely-failed txn whose writes never apply."""
    state = {}
    next_val = itertools.count(1)
    execs = []          # ops per txn, in true execution order
    for _ in range(n_txns):
        ops = []
        for _ in range(rng.randint(1, 3)):
            k = rng.randrange(n_keys)
            if rng.random() < 0.55:
                v = next(next_val)
                ops.append(["append", k, v] if kind == "append"
                           else ["w", k, v])
                if kind == "append":
                    state.setdefault(k, [])
                    state[k] = state[k] + [v]
                else:
                    state[k] = v
            else:
                ops.append(["r", k, list(state.get(k, []))
                            if kind == "append" else state.get(k)])
        execs.append(ops)
    # final reads pin the complete version order of every key
    execs.append([["r", k, list(state.get(k, []))
                   if kind == "append" else state.get(k)]
                  for k in range(n_keys)])

    hist, idx = [], itertools.count()
    i = 0
    while i < len(execs):
        overlap = i + 1 < len(execs) and rng.random() < 0.4
        group = execs[i:i + 2] if overlap else execs[i:i + 1]
        for j, ops in enumerate(group):
            inv = [[f, k, None if f == "r" else v] for f, k, v in ops]
            hist.append({"process": i + j, "type": "invoke", "f": "txn",
                         "value": inv, "index": next(idx)})
        for j, ops in enumerate(group):
            hist.append({"process": i + j, "type": "ok", "f": "txn",
                         "value": ops, "index": next(idx)})
        i += len(group)
    if rng.random() < 0.4:
        # a definitely-failed append: its value must never be observed
        k = rng.randrange(n_keys)
        v = next(next_val)
        op = [["append", k, v] if kind == "append" else ["w", k, v]]
        hist.append({"process": 90, "type": "invoke", "f": "txn",
                     "value": op, "index": next(idx)})
        hist.append({"process": 90, "type": "fail", "f": "txn",
                     "value": op, "index": next(idx)})
    for n, r in enumerate(hist):
        r["index"] = n
        r["time"] = n
    return hist


# --- mutations -------------------------------------------------------------

def _ok_reads(hist):
    return [(ri, oi) for ri, r in enumerate(hist) if r["type"] == "ok"
            for oi, op in enumerate(r["value"]) if op[0] == "r"]


def mutate(hist, rng, kind="append"):
    """Corrupt a committed read/write; returns None when the chosen
    mutation has no applicable site."""
    h = [dict(r, value=[list(op) for op in r["value"]]) for r in hist]
    reads = _ok_reads(h)
    if not reads:
        return None
    which = rng.choice(["lost", "stale", "aborted", "phantom", "reorder"]
                       if kind == "append" else
                       ["lost", "stale", "aborted", "phantom"])
    if which == "lost":
        # an acked append/write vanishes from every read
        writes = [op for r in h if r["type"] == "ok"
                  for op in r["value"] if op[0] != "r"]
        if not writes:
            return None
        _, k, v = rng.choice(writes)
        for r in h:
            if r["type"] != "ok":
                continue
            for op in r["value"]:
                if op[0] == "r" and op[1] == k:
                    if kind == "append" and op[2] and v in op[2]:
                        op[2] = [x for x in op[2] if x != v]
                    elif kind != "append" and op[2] == v:
                        op[2] = None
        return h
    ri, oi = rng.choice(reads)
    op = h[ri]["value"][oi]
    if which == "stale":
        if kind == "append":
            if not op[2]:
                return None
            op[2] = op[2][:rng.randrange(len(op[2]))]
        else:
            if op[2] is None:
                return None
            op[2] = None if op[2] == 1 else op[2] - 1
    elif which == "aborted":
        failed = [o for r in h if r["type"] == "fail"
                  for o in r["value"] if o[0] != "r" and o[1] == op[1]]
        if not failed:
            return None
        if kind == "append":
            op[2] = (op[2] or []) + [failed[0][2]]
        else:
            op[2] = failed[0][2]
    elif which == "phantom":
        if kind == "append":
            op[2] = (op[2] or []) + [7777]
        else:
            op[2] = 7777
    elif which == "reorder":
        if not op[2] or len(op[2]) < 2:
            return None
        op[2] = list(op[2])
        op[2][0], op[2][1] = op[2][1], op[2][0]
    return h


# --- the differential property --------------------------------------------

def _check(kind):
    return check_list_append if kind == "append" else check_rw_register


@pytest.mark.parametrize("kind", ["append", "w"])
@pytest.mark.parametrize("seed", range(40))
def test_valid_histories_pass(kind, seed):
    rng = random.Random(seed)
    hist = gen_history(rng, kind, n_txns=rng.randint(2, 5))
    for model in MODELS:
        assert oracle(hist, model, kind) is True, \
            "generator produced an oracle-invalid history"
        r = _check(kind)(hist, consistency_model=model)
        assert r["valid?"] is True, (model, r)


@pytest.mark.slow
def test_wide_sweep_soundness_and_bounded_incompleteness():
    """1000-seed sweep per workload. The checker must NEVER flag an
    oracle-valid history (soundness, zero tolerance). For completeness:
    list-append must catch every oracle-invalid mutant (version orders
    are fully observable here — Elle-complete regime); rw-register may
    miss the few mutants whose refutation needs a case split over
    UNOBSERVED version orders — deciding register serializability is
    NP-hard in general (Papadimitriou 1979), and the checker is
    documented as sound-inference-only. The miss budget pins today's
    count; improving inference may lower it, never raise it."""
    false_pos, append_miss, register_miss = [], [], []
    for kind in ("append", "w"):
        chk = _check(kind)
        for seed in range(1000):
            rng = random.Random(5000 + seed)
            hist = gen_history(rng, kind, n_txns=rng.randint(2, 6))
            mut = mutate(hist, rng, kind)
            for model in MODELS:
                for h in (hist, mut):
                    if h is None:
                        continue
                    truth = oracle(h, model, kind)
                    ok = chk(h, consistency_model=model)["valid?"] is True
                    if truth and not ok:
                        false_pos.append((kind, seed, model))
                    elif not truth and ok:
                        (append_miss if kind == "append"
                         else register_miss).append((seed, model))
    assert not false_pos, f"checker flagged valid histories: {false_pos}"
    assert not append_miss, f"list-append missed: {append_miss}"
    assert len(register_miss) <= 4, \
        f"register misses grew past the pinned budget: {register_miss}"


@pytest.mark.parametrize("kind", ["append", "w"])
@pytest.mark.parametrize("seed", range(60))
def test_mutants_agree_with_oracle(kind, seed):
    rng = random.Random(1000 + seed)
    hist = gen_history(rng, kind, n_txns=rng.randint(2, 5))
    mut = mutate(hist, rng, kind)
    if mut is None:
        pytest.skip("mutation had no applicable site")
    for model in MODELS:
        truth = oracle(mut, model, kind)
        r = _check(kind)(mut, consistency_model=model)
        if truth:
            # soundness: the checker must not cry wolf on a history the
            # oracle can serialize
            assert r["valid?"] is True, (model, "false positive", r)
        else:
            assert r["valid?"] is False, (model, "missed anomaly", r)
