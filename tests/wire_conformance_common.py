"""Shared core of the per-language static wire-conformance suites
(Go / Ruby / Java / Clojure; the older JS suite predates this helper
and additionally drives body-literal extraction differently).

Each language file keeps only what is language-specific — the regexes
that extract emitted "type" literals and error-code constants, and the
node -> (registry namespace, internal RPC types) map — and delegates
the registry/catalog logic here so the five suites cannot drift."""

import maelstrom_tpu.workloads  # noqa: F401 — populate the registry
from maelstrom_tpu.core.errors import ERRORS_BY_CODE
from maelstrom_tpu.core.schema import REGISTRY

# types every SDK may emit regardless of workload: protocol plumbing
# plus the KV-service client verbs
_ALWAYS_ALLOWED = {"error", "init_ok", "topology_ok", "topology",
                   "read", "write", "cas"}


def assert_error_codes_in_catalog(codes):
    """Every error constant an SDK defines must be a catalog code."""
    assert codes, "no error constants found"
    assert codes <= set(ERRORS_BY_CODE), codes - set(ERRORS_BY_CODE)


def assert_node_reply_types(namespace, internal, emitted, label):
    """The "type" literals a node emits must be its workload's request/
    reply vocabulary (plus node-internal RPCs and plumbing), and the
    node must actually serve at least one workload reply."""
    rpcs = REGISTRY.get(namespace)
    assert rpcs, f"no registry namespace {namespace}"
    known = set()
    for rpc in rpcs.values():
        known.add(rpc.name)
        known.add(rpc.response_type)
    unknown = emitted - (known | set(internal) | _ALWAYS_ALLOWED)
    assert not unknown, (label, unknown)
    reply_types = {r.response_type for r in rpcs.values()}
    assert emitted & reply_types, (label, "serves no workload reply",
                                   emitted, reply_types)
