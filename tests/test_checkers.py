"""Checker unit tests on literal histories — the reference's test pattern
(SURVEY §4: checkers are history->verdict functions)."""

from maelstrom_tpu.checkers.linearizable import (
    linearizable_kv_checker)
from maelstrom_tpu.checkers.pn_counter import pn_counter_checker
from maelstrom_tpu.checkers.set_full import set_full_checker
from maelstrom_tpu.checkers.unique_ids import unique_ids_checker
from maelstrom_tpu.checkers.availability import availability_checker
from maelstrom_tpu.gen.history import History


def H(*recs):
    """Build a history from (process, type, f, value[, extra]) tuples."""
    out = []
    for i, r in enumerate(recs):
        rec = {"process": r[0], "type": r[1], "f": r[2], "value": r[3],
               "index": i, "time": i}
        if len(r) > 4:
            rec.update(r[4])
        out.append(rec)
    return out


def test_set_full_ok():
    h = H((0, "invoke", "add", 1), (0, "ok", "add", 1),
          (1, "invoke", "add", 2), (1, "ok", "add", 2),
          (0, "invoke", "read", None), (0, "ok", "read", [1, 2]))
    r = set_full_checker(h)
    assert r["valid?"] is True
    assert r["lost-count"] == 0
    assert r["stable-count"] == 2


def test_set_full_lost():
    h = H((0, "invoke", "add", 1), (0, "ok", "add", 1),
          (0, "invoke", "read", None), (0, "ok", "read", []))
    r = set_full_checker(h)
    assert r["valid?"] is False
    assert r["lost"] == [1]


def test_set_full_indeterminate_add_never_lost():
    h = H((0, "invoke", "add", 1), (0, "info", "add", 1),
          (0, "invoke", "read", None), (0, "ok", "read", []))
    assert set_full_checker(h)["valid?"] is True


def test_unique_ids():
    ok = H((0, "invoke", "generate", None), (0, "ok", "generate", "a"),
           (1, "invoke", "generate", None), (1, "ok", "generate", "b"))
    assert unique_ids_checker(ok)["valid?"] is True
    dup = H((0, "invoke", "generate", None), (0, "ok", "generate", "a"),
            (1, "invoke", "generate", None), (1, "ok", "generate", "a"))
    r = unique_ids_checker(dup)
    assert r["valid?"] is False and r["duplicated-count"] == 1


def test_pn_counter_definite_only():
    h = H((0, "invoke", "add", 3), (0, "ok", "add", 3),
          (1, "invoke", "add", -1), (1, "ok", "add", -1),
          (0, "invoke", "read", None), (0, "ok", "read", 2))
    assert pn_counter_checker(h)["valid?"] is True


def test_pn_counter_indeterminate_subset():
    # definite +3; indeterminate +5 -> reads of 3 or 8 both fine, 5 is not
    h = H((0, "invoke", "add", 3), (0, "ok", "add", 3),
          (1, "invoke", "add", 5), (1, "info", "add", 5),
          (0, "invoke", "read", None), (0, "ok", "read", 8))
    assert pn_counter_checker(h)["valid?"] is True
    h_bad = H((0, "invoke", "add", 3), (0, "ok", "add", 3),
              (1, "invoke", "add", 5), (1, "info", "add", 5),
              (0, "invoke", "read", None), (0, "ok", "read", 5))
    assert pn_counter_checker(h_bad)["valid?"] is False


def test_availability():
    h = H((0, "invoke", "read", None), (0, "ok", "read", 1),
          (1, "invoke", "read", None), (1, "info", "read", None))
    assert availability_checker(h, None)["valid?"] is True
    assert availability_checker(h, "total")["valid?"] is False
    assert availability_checker(h, 0.5)["valid?"] is True
    assert availability_checker(h, 0.9)["valid?"] is False


def test_linearizable_ok():
    h = H((0, "invoke", "write", [0, 1]), (0, "ok", "write", [0, 1]),
          (1, "invoke", "read", [0, None]), (1, "ok", "read", [0, 1]),
          (0, "invoke", "cas", [0, [1, 2]]), (0, "ok", "cas", [0, [1, 2]]),
          (1, "invoke", "read", [0, None]), (1, "ok", "read", [0, 2]))
    assert linearizable_kv_checker(h)["valid?"] is True


def test_linearizable_violation():
    # read returns a value that was never written
    h = H((0, "invoke", "write", [0, 1]), (0, "ok", "write", [0, 1]),
          (1, "invoke", "read", [0, None]), (1, "ok", "read", [0, 7]))
    r = linearizable_kv_checker(h)
    assert r["valid?"] is False and r["bad-keys"] == [0]


def test_linearizable_stale_read_violation():
    # sequential writes 1 then 2 (non-overlapping), then a read of 1: stale
    h = H((0, "invoke", "write", [0, 1]), (0, "ok", "write", [0, 1]),
          (0, "invoke", "write", [0, 2]), (0, "ok", "write", [0, 2]),
          (1, "invoke", "read", [0, None]), (1, "ok", "read", [0, 1]))
    assert linearizable_kv_checker(h)["valid?"] is False


def test_linearizable_concurrent_ok():
    # concurrent write may linearize before or after the read
    h = [
        {"process": 0, "type": "invoke", "f": "write", "value": [0, 1],
         "index": 0, "time": 0},
        {"process": 1, "type": "invoke", "f": "read", "value": [0, None],
         "index": 1, "time": 1},
        {"process": 1, "type": "ok", "f": "read", "value": [0, None],
         "index": 2, "time": 2},
        {"process": 0, "type": "ok", "f": "write", "value": [0, 1],
         "index": 3, "time": 3},
    ]
    assert linearizable_kv_checker(h)["valid?"] is True


def test_linearizable_info_op_may_or_may_not_apply():
    # an info write may have taken effect: read of its value is legal...
    h = H((0, "invoke", "write", [0, 1]), (0, "info", "write", [0, 1]),
          (1, "invoke", "read", [0, None]), (1, "ok", "read", [0, 1]))
    assert linearizable_kv_checker(h)["valid?"] is True
    # ...and so is never seeing it
    h2 = H((0, "invoke", "write", [0, 1]), (0, "info", "write", [0, 1]),
           (1, "invoke", "read", [0, None]), (1, "ok", "read", [0, None]))
    assert linearizable_kv_checker(h2)["valid?"] is True


def test_set_full_vanished_element_is_lost():
    # element seen once, then permanently missing from later reads -> lost
    h = H((0, "invoke", "add", 5), (0, "ok", "add", 5),
          (0, "invoke", "read", None), (0, "ok", "read", [5]),
          (1, "invoke", "read", None), (1, "ok", "read", []),
          (1, "invoke", "read", None), (1, "ok", "read", []))
    r = set_full_checker(h)
    assert r["valid?"] is False
    assert r["lost"] == [5]


def test_pn_counter_prefers_final_tagged_reads():
    # mid-test stale read of 3 would be wrong vs the end state, but the
    # tagged final read of 10 is the one that's judged
    h = H((0, "invoke", "read", None), (0, "ok", "read", 3),
          (1, "invoke", "add", 10), (1, "ok", "add", 10),
          (0, "invoke", "read", None, {"final": True}),
          (0, "ok", "read", 10))
    assert pn_counter_checker(h)["valid?"] is True


def test_linearizable_large_key_planted_violation_fails():
    # VERDICT r1 weak #4: a busy key (>400 ops) used to be silently
    # skipped with valid? true. 600 sequential ops with one stale read
    # planted in the middle must now FAIL.
    recs = []
    for i in range(150):
        recs.append((0, "invoke", "write", [0, i]))
        recs.append((0, "ok", "write", [0, i]))
        recs.append((1, "invoke", "read", [0, None]))
        recs.append((1, "ok", "read", [0, i]))
    # planted: read of long-gone value 3 after write of 149
    recs.append((1, "invoke", "read", [0, None]))
    recs.append((1, "ok", "read", [0, 3]))
    h = H(*recs)
    assert len([r for r in h if r["type"] == "invoke"]) > 250
    r = linearizable_kv_checker(h)
    assert r["valid?"] is False and r["bad-keys"] == [0]


def test_linearizable_over_cap_is_unknown_not_valid():
    recs = []
    for i in range(20):
        recs.append((0, "invoke", "write", [0, i]))
        recs.append((0, "ok", "write", [0, i]))
    h = H(*recs)
    r = linearizable_kv_checker(h, max_ops_per_key=10)
    assert r["valid?"] == "unknown"
    assert r["unknown-keys"] == [0]


def test_linearizable_budget_exhaustion_is_unknown():
    # fully-concurrent writes (all invoked before any completes) blow up
    # the WGL search; a tiny budget must yield unknown, never true.
    import random
    rng = random.Random(0)
    n = 14
    h = []
    for i in range(n):
        h.append({"process": i, "type": "invoke", "f": "write",
                  "value": [0, i], "index": i, "time": 0})
    for i in range(n):
        h.append({"process": i, "type": "ok", "f": "write",
                  "value": [0, i], "index": n + i, "time": 1000 + i})
    r = linearizable_kv_checker(h, budget_states=50)
    assert r["valid?"] == "unknown"


def test_linearizable_segmented_deep_history_fast():
    # 2000 non-overlapping ops on one key: quiescent-cut segmentation
    # must keep this near-instant (was exponential risk pre-r2).
    import time as _t
    recs = []
    for i in range(500):
        recs.append((0, "invoke", "write", [0, i]))
        recs.append((0, "ok", "write", [0, i]))
        recs.append((1, "invoke", "read", [0, None]))
        recs.append((1, "ok", "read", [0, i]))
    h = H(*recs)
    t0 = _t.monotonic()
    r = linearizable_kv_checker(h)
    assert r["valid?"] is True
    assert _t.monotonic() - t0 < 5.0
