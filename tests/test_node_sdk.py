"""Unit tests for the bundled node SDKs with injected pipes — the
reference's demo-library test pattern (demo/go/node_test.go:19-37 injects
fake Stdin/Stdout; SURVEY §4)."""

import json
import os
import subprocess
import sys

from conftest import REPO

PY_DIR = os.path.join(REPO, "examples", "python")


def drive(script: str, messages):
    """Run a node script, feed it JSON messages, return its stdout
    replies keyed by in_reply_to (dispatch is threaded, so stdout order
    is nondeterministic)."""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(PY_DIR, script)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    stdin = "\n".join(json.dumps(m) for m in messages) + "\n"
    try:
        out, err = proc.communicate(stdin, timeout=10)
    finally:
        proc.kill()
    replies = {}
    for line in out.splitlines():
        if line.strip():
            m = json.loads(line)
            replies[m["body"].get("in_reply_to")] = m
    return replies


def msg(src, dest, body):
    return {"id": 0, "src": src, "dest": dest, "body": body}


INIT = msg("c0", "n0", {"type": "init", "msg_id": 1, "node_id": "n0",
                        "node_ids": ["n0", "n1"]})


def test_sdk_init_handshake():
    out = drive("echo.py", [INIT])
    m = out[1]
    assert m["body"]["type"] == "init_ok"
    assert m["src"] == "n0" and m["dest"] == "c0"


def test_sdk_echo_roundtrip():
    out = drive("echo.py", [
        INIT,
        msg("c0", "n0", {"type": "echo", "msg_id": 2,
                         "echo": {"nested": [1, None, "x"]}}),
    ])
    body = out[2]["body"]
    assert body["type"] == "echo_ok"
    assert body["echo"] == {"nested": [1, None, "x"]}


def test_sdk_unknown_type_replies_not_supported():
    out = drive("echo.py", [
        INIT,
        msg("c0", "n0", {"type": "zorp", "msg_id": 3}),
    ])
    body = out[3]["body"]
    assert body["type"] == "error"
    assert body["code"] == 10


def test_sdk_handler_exception_becomes_crash_error():
    # broadcast with a missing field forces a handler error
    out = drive("broadcast.py", [
        INIT,
        msg("c0", "n0", {"type": "broadcast", "msg_id": 4}),  # no message
    ])
    body = out[4]["body"]
    assert body["type"] == "error"
    assert body["code"] == 13
