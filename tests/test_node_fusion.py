"""Fusion-first node runtime: bit-identity + cost acceptance gates.

The compartmentalized node step (``models/raft_core.py``, driven by
``runtime.node_phase`` for ``fused_node`` models) promises two things:

1. **Bit-identity** — trajectories are EXACTLY the pre-refactor
   runtime's, in both carry layouts, pinned by frozen golden digests
   recorded from the pre-refactor code
   (``tests/data/node_fusion_golden.json`` — these can never be
   regenerated from this tree, so they pin history). The legacy
   ``handle()``/``tick()`` formulation itself (PR 6's live oracle) was
   DELETED after its soak window — the goldens are the remaining, and
   sufficient, identity anchor.
2. **Cost** — the node phase of every raft-family model drops >= 2x in
   jaxpr equation count vs the PR-5 baseline, with ZERO fusion-breaking
   loops (the unrolled scans must keep lowering while-free), enforced
   forever by the per-model ``fusion-breakers`` budgets in
   ``analysis/cost_baseline.json``.

The planted-bug corpus rides the same kernel (the bug knobs are static
branches in raft_core), so the golden set includes every buggy variant:
dirty-apply / double-vote / stale-read must keep planting EXACTLY the
same bugs — their digests are pinned too, and the double-vote mutant
must still trip the on-device invariant (the full Elle-checker trips
stay pinned by tests/test_tpu_txn.py and the triage fixtures).
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import numpy as np
import pytest

from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu.harness import make_sim_config
from maelstrom_tpu.tpu.runtime import canonical_carry, run_sim

pytestmark = pytest.mark.fusion

GOLDEN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "data", "node_fusion_golden.json")
with open(GOLDEN_PATH) as f:
    GOLDEN = json.load(f)

# the exact recording config of the frozen digests (pre-refactor code,
# tests/data/node_fusion_golden.json) — every knob matters: a changed
# horizon or rate is a different trajectory, not a failed identity
GOLDEN_OPTS = dict(node_count=3, concurrency=4, n_instances=2,
                   record_instances=2, time_limit=1.2, rate=300.0,
                   latency=4.0, rpc_timeout=0.5, nemesis=["partition"],
                   nemesis_interval=0.25, p_loss=0.05,
                   recovery_time=0.3, pool_slots=32, seed=0,
                   telemetry=False)
GOLDEN_SEED = 11

RAFT_FAMILY = [
    "lin-kv",
    "lin-kv-bug-double-vote", "lin-kv-bug-stale-read",
    "lin-kv-bug-no-term-guard", "lin-kv-bug-short-log-wins",
    "lin-kv-bug-eager-commit",
    "txn-list-append", "txn-rw-register",
    "txn-list-append-bug-dirty-apply", "txn-rw-register-bug-dirty-apply",
]

# the PR-5 node-phase eqn figures this PR halves (the acceptance bar's
# "before" column — frozen history, doc/results.md scoreboard)
PR5_NODE_EQNS = {"lin-kv": 1083, "txn-rw-register": 1175,
                 "txn-list-append": 1499}
AUDIT_N = {"lin-kv": 5, "txn-rw-register": 3, "txn-list-append": 3}


def _traj_digest(model, layout):
    """sha256 over the canonicalized end-of-run carry + the dense event
    tensor — the exact recipe of the frozen recording script (canonical
    orientation makes the digest layout-independent by construction).

    The digests were recorded under the pre-specialization wire format
    (9-lane header with NETID at lane 8, always stamped). The run
    therefore forces ``netid=True`` — value-identical to the recording
    config, today's opt-in spelling of the always-on lane — and maps the
    pool back to the legacy lane ORDER (NETID moved from the trailing
    lane to lane 8) before hashing; every other leaf is untouched by
    the format change."""
    sim = make_sim_config(model, {**GOLDEN_OPTS, "layout": layout,
                                  "netid": True})
    carry, ys = run_sim(model, sim, GOLDEN_SEED,
                        model.make_params(sim.net.n_nodes))
    canon = canonical_carry(carry, sim)
    legacy_pool = np.concatenate(
        [np.asarray(canon.pool[..., :8]),      # VALID..ORIGIN
         np.asarray(canon.pool[..., -1:]),     # NETID (legacy lane 8)
         np.asarray(canon.pool[..., 8:-1])],   # body lanes
        axis=-1)
    # The membership lane (joint-consensus reconfiguration) appended
    # two provisioning leaves to RaftRow — cfg_boot / caught_up —
    # AFTER the digests were frozen. Under the golden config (no
    # membership lane) they are inert constants and every
    # pre-existing leaf must still be bit-identical, so the digest
    # hashes exactly the recorded field set in its recorded order
    # (the new fields were appended, so stripping them preserves it)
    # — the same move as the legacy-lane-order pool remap above.
    node_state = canon.node_state
    if hasattr(node_state, "_fields"):
        node_state = tuple(
            getattr(node_state, f) for f in node_state._fields
            if f not in ("cfg_boot", "caught_up"))
    h = hashlib.sha256()
    for leaf in jax.tree.leaves((legacy_pool, node_state,
                                 canon.client_state, canon.violations,
                                 canon.stats)):
        h.update(np.asarray(leaf).tobytes())
    h.update(np.asarray(ys.events).tobytes())
    return h.hexdigest()


# --- frozen pre-refactor oracle -------------------------------------------


# tier-1 pins the three headline models (lin-kv in BOTH layouts; the
# txn models split one layout each — the golden file itself pins
# lead==minor) plus one bug variant per bug family; the full 10x2
# sweep (identical assertion, the remaining variants) is the slow
# re-measure, budgeted out of the 870s tier-1 window
TIER1_GOLDEN = [("lin-kv", "lead"), ("lin-kv", "minor"),
                ("txn-rw-register", "lead"),
                ("txn-list-append", "minor"),
                ("lin-kv-bug-double-vote", "lead"),
                ("txn-list-append-bug-dirty-apply", "lead")]
SLOW_GOLDEN = [(wl, layout) for wl in RAFT_FAMILY
               for layout in ("lead", "minor")
               if (wl, layout) not in TIER1_GOLDEN]


@pytest.mark.parametrize("workload,layout", TIER1_GOLDEN)
def test_golden_digest(workload, layout):
    """The fused runtime reproduces the pre-refactor trajectory
    bit-for-bit (frozen digest, recorded before the refactor)."""
    model = get_model(workload, GOLDEN_OPTS["node_count"])
    assert _traj_digest(model, layout) == GOLDEN[f"{workload}/{layout}"]


@pytest.mark.slow
@pytest.mark.parametrize("workload,layout", SLOW_GOLDEN)
def test_golden_digest_full_sweep(workload, layout):
    model = get_model(workload, GOLDEN_OPTS["node_count"])
    assert _traj_digest(model, layout) == GOLDEN[f"{workload}/{layout}"]


def test_golden_set_is_complete_and_layout_independent():
    """Every raft-family model x both layouts is pinned, and each
    lead/minor pair recorded the SAME digest (canonical_carry is a pure
    transpose — a layout-dependent digest would mean the recording
    itself caught a layout bug)."""
    assert set(GOLDEN) == {f"{wl}/{layout}" for wl in RAFT_FAMILY
                           for layout in ("lead", "minor")}
    for wl in RAFT_FAMILY:
        assert GOLDEN[f"{wl}/lead"] == GOLDEN[f"{wl}/minor"], wl


def test_golden_pins_the_planted_bugs():
    """The recorded trajectories PROVE the bug corpus stayed planted:
    a mutant whose bug manifests inside the recording horizon digests
    differently from its correct base model."""
    for wl in ("lin-kv-bug-double-vote", "lin-kv-bug-stale-read",
               "lin-kv-bug-eager-commit"):
        assert GOLDEN[f"{wl}/lead"] != GOLDEN["lin-kv/lead"], wl
    assert (GOLDEN["txn-list-append-bug-dirty-apply/lead"]
            != GOLDEN["txn-list-append/lead"])
    assert (GOLDEN["txn-rw-register-bug-dirty-apply/lead"]
            != GOLDEN["txn-rw-register/lead"])


# --- the legacy path is gone ----------------------------------------------


def test_raft_family_has_no_legacy_node_path():
    """ROADMAP item 1 residual: the legacy ``handle()``/``tick()``
    formulation (and its helpers) was deleted from the raft family
    after the soak window — the fused protocol is the only node step.
    A reintroduced override would silently fork the semantics away
    from what the frozen goldens pin, so its absence is asserted."""
    from maelstrom_tpu.tpu.runtime import Model
    for wl in RAFT_FAMILY:
        model = get_model(wl, GOLDEN_OPTS["node_count"])
        assert type(model).fused_node, wl
        # handle/tick resolve to the abstract Model defaults only
        assert type(model).handle is Model.handle, wl
        assert type(model).tick is Model.tick, wl
        for helper in ("_apply_one", "_peer_sends", "_apply_frontier",
                       "_step_down", "_reset_election"):
            assert not hasattr(model, helper), (wl, helper)


# --- the planted bugs still fire ------------------------------------------


def test_double_vote_still_trips_on_device_invariant():
    """The fused double-vote mutant still elects two leaders in one
    term under partitions — the on-device invariant lane must light up
    (the config is test_stream_triage's forensics fixture)."""
    opts = dict(node_count=3, concurrency=6, n_instances=16,
                record_instances=4, inbox_k=1, pool_slots=16,
                time_limit=0.3, rate=200.0, latency=5.0,
                rpc_timeout=1.0, nemesis=["partition"],
                nemesis_interval=0.04, p_loss=0.05, recovery_time=0.0)
    model = get_model("lin-kv-bug-double-vote", 3)
    sim = make_sim_config(model, opts)
    carry, _ = run_sim(model, sim, 7, model.make_params(3))
    assert int(np.asarray(carry.violations).sum()) > 0

    # the correct model stays clean under the identical schedule
    ok_model = get_model("lin-kv", 3)
    ok_carry, _ = run_sim(ok_model, sim, 7, ok_model.make_params(3))
    assert int(np.asarray(ok_carry.violations).sum()) == 0


# --- the cost acceptance bar ----------------------------------------------


# the value-range analyzer PR added 4 trust-boundary clamp equations
# to inbox_step (vote-bitmask shift cap, match_ack/r_match caps —
# doc/lint.md pass-7 soundness notes): value-identical on every honest
# trace (the frozen goldens pin that) but they ride the node phase, so
# the PR-6 2x bar is asserted net of exactly that named overhead
TRUST_CLAMP_EQNS = 4

# the membership fault lane added Raft JOINT CONSENSUS to the shared
# kernel (models/raft_core.py): two config-view derivations (the
# latest C entry in the log), dual-quorum election + commit math,
# catch-up gating, and the leader's reconfiguration driver — measured
# at 244-265 eqns across the raft family x layouts. NEW protocol, not
# compression regression: value-identical to the pre-membership tick
# everywhere the lane is off (the frozen goldens above pin that), zero
# fusion-breaking loops (asserted below), and the cost baseline gates
# the re-recorded totals. The PR-6 2x bar nets it out BY NAME, exactly
# like the trust clamps.
JOINT_CONSENSUS_EQNS = 270


def test_node_phase_eqns_halved_vs_pr5():
    """ISSUE-6 acceptance: node-phase eqn count >= 2x down vs the PR-5
    baseline for the three headline models, in BOTH layouts, with zero
    fusion-breaking loops in the whole tick (net of the later
    range-analyzer trust clamps and the joint-consensus machinery —
    see TRUST_CLAMP_EQNS / JOINT_CONSENSUS_EQNS)."""
    from maelstrom_tpu.analysis.cost_model import audit_sim, tick_cost
    for wl, before in PR5_NODE_EQNS.items():
        n = AUDIT_N[wl]
        model = get_model(wl, n)
        for layout in ("lead", "minor"):
            cost = tick_cost(model, audit_sim(model, n, layout))
            now = (cost.phases["node_phase"] - TRUST_CLAMP_EQNS
                   - JOINT_CONSENSUS_EQNS)
            assert now * 2 <= before, (wl, layout, now, before)
            assert cost.loops == 0, (wl, layout)


def test_raft_family_budgets_pinned_at_zero():
    """The re-recorded cost baseline carries a zero fusion-breaker
    budget for every raft-family entry — the JXP404 per-model gate that
    makes a re-introduced per-slot scan a pre-merge ERROR."""
    from maelstrom_tpu.analysis.cost_model import load_cost_baseline
    entries = load_cost_baseline()["entries"]
    raft_keys = [k for k in entries
                 if k.split("/")[0] in RAFT_FAMILY]
    assert len(raft_keys) == 20          # 10 models x 2 layouts
    for k in raft_keys:
        assert entries[k]["fusion-breakers"] == 0, k
        # same by-name netting as test_node_phase_eqns_halved_vs_pr5:
        # the trust clamps and the joint-consensus machinery are later
        # NAMED additions, not compression regressions
        assert (entries[k]["phases"]["node_phase"] - TRUST_CLAMP_EQNS
                - JOINT_CONSENSUS_EQNS) * 2 <= max(
            PR5_NODE_EQNS.values()), k
