"""Multi-device sharding: the instance axis over a virtual 8-device CPU
mesh via shard_map, with psum'd fleet stats (SURVEY §7 step 8)."""
import pytest

import jax
import numpy as np

from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.models.raft import RaftModel
from maelstrom_tpu.parallel.mesh import make_mesh, run_sim_sharded
from maelstrom_tpu.tpu.harness import (events_to_histories,
                                       make_sim_config)


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_echo_sharded_over_8_devices():
    model = EchoModel()
    opts = dict(node_count=2, concurrency=2, n_instances=4,
                record_instances=2, time_limit=0.5, rate=100.0,
                latency=5.0, seed=3)
    sim = make_sim_config(model, opts)
    mesh = make_mesh()
    stats, violations, events = run_sim_sharded(model, sim, seed=3, mesh=mesh)
    # events gathered across shards: R_total = 2 * 8
    assert events.shape[1] == 16
    # violations cover ALL instances (4 per shard x 8), not just recorded
    assert violations.shape == (32,) and int(violations.sum()) == 0
    assert int(stats.delivered) > 0
    # every shard produced distinct traffic (decorrelated seeds)
    hists = events_to_histories(model, np.asarray(events))
    payload_sets = [frozenset(r["value"] for r in h
                              if r["type"] == "invoke") for h in hists]
    assert len(set(payload_sets)) > 1


@pytest.mark.slow
def test_raft_sharded_runs_and_checks():
    model = RaftModel(n_nodes_hint=3, log_cap=48)
    opts = dict(node_count=3, concurrency=2, n_instances=2,
                record_instances=1, time_limit=1.5, rate=20.0,
                latency=5.0, rpc_timeout=0.8, recovery_time=0.2, seed=5)
    sim = make_sim_config(model, opts)
    stats, violations, events = run_sim_sharded(model, sim, seed=5)
    hists = events_to_histories(model, np.asarray(events),
                                sim.client.final_start)
    assert len(hists) == 8
    checker = model.checker()
    for h in hists:
        if h:
            assert checker(h, opts)["valid?"] is True


@pytest.mark.slow
def test_sharded_equals_unsharded_bitwise():
    """Behavioral equivalence, not just execution (VERDICT r2 #4): the
    same per-shard seeds run unsharded on one device reproduce the
    8-way shard_map run bit-for-bit — stats, violation counters, and
    recorded event streams."""
    from maelstrom_tpu.parallel.mesh import run_sim_unsharded

    model = RaftModel(n_nodes_hint=3, log_cap=16)
    opts = dict(node_count=3, concurrency=2, n_instances=2,
                record_instances=2, time_limit=0.5, rate=50.0,
                latency=5.0, rpc_timeout=0.4, nemesis=["partition"],
                nemesis_interval=0.1, p_loss=0.05, recovery_time=0.1,
                seed=9)
    sim = make_sim_config(model, opts)._replace(n_ticks=40)
    stats, violations, events = run_sim_sharded(model, sim, seed=9)
    u_stats, u_viol, u_events = run_sim_unsharded(model, sim, seed=9,
                                                  n_shards=8)
    assert tuple(jax.tree.map(int, stats)) == tuple(u_stats)
    assert np.array_equal(np.asarray(violations), u_viol)
    assert np.array_equal(np.asarray(events), u_events)


@pytest.mark.slow
def test_hybrid_mesh_single_host_degenerate():
    """run_sim_sharded over the (1, 8) degenerate DCN x ICI hybrid mesh:
    the two-axis sharding compiles and runs; only the axis sizes change
    on a real pod."""
    from maelstrom_tpu.models.raft import RaftModel
    from maelstrom_tpu.parallel import multihost
    from maelstrom_tpu.tpu.harness import make_sim_config

    model = RaftModel(n_nodes_hint=3, log_cap=16)
    opts = dict(node_count=3, concurrency=2, n_instances=4,
                record_instances=2, time_limit=0.5, rate=30.0,
                latency=5.0, rpc_timeout=0.4, recovery_time=0.1, seed=2)
    sim = make_sim_config(model, opts)._replace(n_ticks=40)
    mesh = multihost.make_hybrid_mesh()
    assert mesh.devices.shape == (1, 8)
    assert mesh.axis_names == (multihost.DCN_AXIS, multihost.ICI_AXIS)
    stats, violations, events = run_sim_sharded(
        model, sim, seed=4, mesh=mesh)
    assert violations.shape[0] == 4 * 8
    assert events.shape[1] == 2 * 8
    assert int(stats.sent) > 0
