"""Time-to-first-anomaly scales with instance parallelism (SURVEY §7
step 8: the bug-injection corpus exists to measure exactly this — the
product value of fuzzing 10^3-10^5 protocol seeds per chip is that rare
bugs surface in wall-clock minutes instead of days).

The double-vote mutant's violation tick is recorded on-device per
instance; the FLEET's time-to-first-anomaly is the minimum violation
tick across instances, which can only improve as the fleet grows (more
seeds explore more schedules per simulated second)."""

import numpy as np

from maelstrom_tpu.models.raft_buggy import RaftDoubleVote
from maelstrom_tpu.tpu.harness import make_sim_config
from maelstrom_tpu.tpu.runtime import run_sim
import pytest

pytestmark = pytest.mark.slow


def _first_anomaly_tick(n_instances: int, seed: int = 9) -> int:
    """Earliest tick at which any instance's on-device invariant trips
    (violations counts violation ticks; we re-run streaming the
    violation vector per tick via the recorded carry — cheaper: run the
    sim and binary-search is overkill, the violation count after T
    ticks is monotone, so run a short horizon and check who tripped)."""
    model = RaftDoubleVote(n_nodes_hint=3)
    opts = dict(node_count=3, concurrency=3, n_instances=n_instances,
                record_instances=1, time_limit=2.0, rate=40.0,
                latency=10.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_interval=0.25, p_loss=0.05, recovery_time=0.3,
                seed=seed)
    sim = make_sim_config(model, opts)
    carry, _ = run_sim(model, sim, seed, model.make_params(3))
    v = np.asarray(carry.violations)
    if not (v > 0).any():
        return 1 << 30
    # violations[i] = number of ticks instance i spent in violation; the
    # first anomaly tick for an instance that stayed violated once
    # tripped is n_ticks - violations[i]
    return int((sim.n_ticks - v[v > 0].max()))


def _violating_count(n_instances: int, seed: int = 9) -> int:
    model = RaftDoubleVote(n_nodes_hint=3)
    opts = dict(node_count=3, concurrency=3, n_instances=n_instances,
                record_instances=1, time_limit=2.0, rate=40.0,
                latency=10.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_interval=0.25, p_loss=0.05, recovery_time=0.3,
                seed=seed)
    sim = make_sim_config(model, opts)
    carry, _ = run_sim(model, sim, seed, model.make_params(3))
    return int((np.asarray(carry.violations) > 0).sum())


def test_time_to_first_anomaly_improves_with_fleet_size():
    # both fleet sizes catch the mutant within the horizon, and the
    # larger fleet catches it on strictly more instances — each seed
    # explores an independent schedule, which is what converts instance
    # parallelism into shorter wall-clock time-to-anomaly
    small_tick = _first_anomaly_tick(4)
    assert small_tick < 1 << 30
    small_n = _violating_count(4)
    large_n = _violating_count(64)
    assert large_n > small_n, (small_n, large_n)
    assert large_n >= 8, large_n
