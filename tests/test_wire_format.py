"""Per-family wire-format specialization: narrowing safety proofs.

The narrow default wire format (8-lane header, no NETID lane — see
``tpu/wire.py``) must be TRAJECTORY-PRESERVING against the wide
(netid/journaling) format for every registered production model, in
both carry layouts — the PR-7 ``IrDeadLane`` fixture proof extended to
the whole registry. The pool is compared on the shared lanes (the wide
pool minus its trailing NETID lane); every other leaf must be
bit-identical outright.

Also pinned here: the checkpoint width-mismatch refusal names the
lane-width change, journaling refuses to run without the pairing lane,
and the native engine's width-templated instantiations (narrow vs
``wide=True``) produce identical histories and checker verdicts.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from maelstrom_tpu.analysis.cost_model import cost_specs
from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu import wire
from maelstrom_tpu.tpu.harness import make_sim_config
from maelstrom_tpu.tpu.runtime import canonical_carry, run_sim

pytestmark = pytest.mark.lanes

# small but non-degenerate: partitions + loss exercise every header
# lane, the horizon covers elections/commits for the raft family
AB_OPTS = dict(node_count=3, concurrency=4, n_instances=2,
               record_instances=2, time_limit=0.4, rate=300.0,
               latency=4.0, rpc_timeout=0.3, nemesis=["partition"],
               nemesis_interval=0.1, p_loss=0.05, recovery_time=0.1,
               pool_slots=32, seed=3)


def _run(model, layout, netid, n=3):
    sim = make_sim_config(model, {**AB_OPTS, "node_count": n,
                                  "layout": layout, "netid": netid})
    params = model.make_params(sim.net.n_nodes)
    carry, ys = run_sim(model, sim, 11, params)
    return canonical_carry(carry, sim), ys, sim


def _assert_narrow_equals_wide(workload, n, layout):
    model = get_model(workload, n)
    narrow, ys_n, sim_n = _run(model, layout, netid=False, n=n)
    wide, ys_w, sim_w = _run(model, layout, netid=True, n=n)
    assert sim_n.net.lanes + 1 == sim_w.net.lanes
    # shared pool lanes: the wide format appends exactly one NETID lane
    np.testing.assert_array_equal(np.asarray(narrow.pool),
                                  np.asarray(wide.pool[..., :-1]))
    for a, b in zip(jax.tree.leaves((narrow.node_state,
                                     narrow.client_state,
                                     narrow.stats, narrow.violations,
                                     narrow.telemetry)),
                    jax.tree.leaves((wide.node_state,
                                     wide.client_state, wide.stats,
                                     wide.violations, wide.telemetry))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(ys_n.events),
                                  np.asarray(ys_w.events))


# tier-1 pins the acceptance-critical combo (the widest family's row
# in the batch-minor layout — the ~4MB/tick worst offender the ISSUE
# names); the full registry x layouts sweep is the slow re-measure.
# Budget note: each combo compiles two full tick graphs, so breadth
# lives in the slow sweep to keep the tier-1 window honest.
TIER1_AB = [("txn-list-append", 3, "minor")]
SLOW_AB = [(wl, n, layout) for wl, n in cost_specs()
           for layout in ("lead", "minor")
           if (wl, n, layout) not in TIER1_AB]


@pytest.mark.parametrize("workload,n,layout", TIER1_AB)
def test_narrow_equals_wide(workload, n, layout):
    """The narrow default format is bit-identical to the wide
    (journaling) format on every shared lane."""
    _assert_narrow_equals_wide(workload, n, layout)


@pytest.mark.slow
@pytest.mark.parametrize("workload,n,layout", SLOW_AB)
def test_narrow_equals_wide_full_sweep(workload, n, layout):
    _assert_narrow_equals_wide(workload, n, layout)


@pytest.mark.slow
def test_wide_pool_trailing_lane_is_the_netid_stamp():
    """In the wide format the trailing lane of every occupied pool row
    carries the runtime's send-time NETID stamp (nonnegative and
    unique within an instance's in-flight set)."""
    model = get_model("lin-kv", 3)
    wide, _, _ = _run(model, "lead", netid=True)
    pool = np.asarray(wide.pool)
    for i in range(pool.shape[0]):
        rows = pool[i][pool[i][:, wire.VALID] == 1]
        if len(rows) == 0:
            continue
        ids = rows[:, -1]
        assert (ids >= 0).all()
        assert len(set(ids.tolist())) == len(ids)


def test_journaling_requires_netid_lane():
    model = get_model("echo", 1)
    with pytest.raises(ValueError, match="NETID"):
        make_sim_config(model, {**AB_OPTS, "journal_instances": 1,
                                "netid": False})
    # auto (None) resolves netid from journaling
    sim = make_sim_config(model, {**AB_OPTS, "journal_instances": 1})
    assert sim.net.netid
    assert make_sim_config(model, AB_OPTS).net.netid is False


def test_make_msg_width_follows_format():
    m = wire.make_msg(src=0, dest=1, type_=1, body=(5,), body_lanes=2)
    assert m.shape == (wire.HDR_LANES + 2,)
    mw = wire.make_msg(src=0, dest=1, type_=1, body=(5,), body_lanes=2,
                       netid=True)
    assert mw.shape == (wire.HDR_LANES + 3,)
    np.testing.assert_array_equal(np.asarray(mw[:-1]), np.asarray(m))
    assert int(mw[-1]) == 0   # the runtime stamps it at send time


def test_heartbeat_meta_records_resolved_wire_format():
    from maelstrom_tpu.tpu.harness import heartbeat_meta
    model = get_model("txn-list-append", 3)
    sim = make_sim_config(model, AB_OPTS)
    meta = heartbeat_meta(model, sim, AB_OPTS)
    wf = meta["wire-format"]
    assert wf == {"header_lanes": 8, "body_lanes": model.body_lanes,
                  "netid": False, "lanes": 8 + model.body_lanes,
                  "bytes_per_msg_row": 4 * (8 + model.body_lanes)}
    wide = make_sim_config(model, {**AB_OPTS, "netid": True})
    assert heartbeat_meta(model, wide, AB_OPTS)["wire-format"][
        "lanes"] == 9 + model.body_lanes


def test_checkpoint_width_mismatch_refusal_names_the_lane_change():
    """Resuming a wide-format checkpoint under the narrow format (or
    vice versa) must be refused with a message that NAMES the
    lane-width change — not a bare shape dump."""
    from maelstrom_tpu.campaign.checkpoint import (CheckpointError,
                                                   restore_carry)
    from maelstrom_tpu.tpu.runtime import init_carry
    model = get_model("lin-kv", 3)
    sim_w = make_sim_config(model, {**AB_OPTS, "netid": True})
    sim_n = make_sim_config(model, AB_OPTS)
    params = model.make_params(3)
    wide_t = jax.eval_shape(lambda: init_carry(model, sim_w, 0, params))
    narrow = jax.tree.map(
        lambda s: np.zeros(s.shape, s.dtype),
        jax.eval_shape(lambda: init_carry(model, sim_n, 0, params)))
    with pytest.raises(CheckpointError,
                       match="LANE-WIDTH change.*wire format"):
        restore_carry(wide_t, jax.tree.leaves(narrow))


@pytest.mark.slow
def test_checkpoint_resume_roundtrip_under_narrow_format(tmp_path):
    """Checkpoint + resume of a narrowed (default-format) run is
    bit-identical to the uninterrupted run — the PR-8 proof re-pinned
    under the specialized wire format (the cheap width-refusal pin
    above stays tier-1; this full roundtrip compiles the pipelined
    executor twice, so it rides the slow lane)."""
    from maelstrom_tpu.tpu.harness import run_tpu_test
    model = get_model("lin-kv", 3)
    opts = {**AB_OPTS, "time_limit": 0.3, "chunk_ticks": 50,
            "pipeline": "on", "telemetry": False, "heartbeat": True,
            "store_root": str(tmp_path), "checkpoint_every": 1}
    res_a = run_tpu_test(model, opts)
    run_dir = res_a["store-dir"]
    # resume the finished run in place: the checkpointed carry must
    # rebuild under the SAME narrow format and finish identically
    res_b = run_tpu_test(model, {**opts, "store_dir": run_dir},
                         resume_from=run_dir)
    assert res_a["valid?"] == res_b["valid?"]
    assert res_a["net"] == res_b["net"]
    assert res_a["invariants"] == res_b["invariants"]


def test_native_narrow_equals_wide():
    """The width-templated native instantiations (per-family class vs
    force-wide W_TXN) run identical trajectories: same histories, same
    stats, same violations, same checker verdicts."""
    from maelstrom_tpu.native.engine import (native_available,
                                             native_msg_lanes,
                                             run_native_sim)
    if not native_available():
        pytest.skip("native engine unavailable")
    from maelstrom_tpu.checkers.linearizable import \
        linearizable_kv_checker
    assert native_msg_lanes("lin-kv") == 13
    assert native_msg_lanes("g-set") == 6
    assert native_msg_lanes("txn-list-append") == 21
    assert native_msg_lanes("lin-kv", wide=True) == 21
    for wl in ("lin-kv", "txn-list-append", "g-set"):
        o = dict(workload=wl, n_instances=128, time_limit=1.0,
                 record_instances=4, threads=1, seed=5)
        a = run_native_sim(o)
        b = run_native_sim({**o, "wide": True})
        assert a["histories"] == b["histories"], wl
        assert a["stats"] == b["stats"], wl
        np.testing.assert_array_equal(a["violations"], b["violations"])
        if wl == "lin-kv":
            va = [linearizable_kv_checker(h)["valid?"]
                  for h in a["histories"]]
            vb = [linearizable_kv_checker(h)["valid?"]
                  for h in b["histories"]]
            assert va == vb
        assert (a["perf"]["bytes-per-msg-row"]
                <= b["perf"]["bytes-per-msg-row"])


def test_native_width_table_conformance_clean():
    """LNE610 on the real tree: C++ constants, the Python table, and
    the registry agree (the divergence path is pinned by the fixture
    + the lint-gate tamper canary)."""
    from maelstrom_tpu.analysis.lane_liveness import \
        native_width_findings
    real = [f for f in native_width_findings(include_fixture=False)]
    assert real == [], [f.message for f in real]
    fx = [f for f in native_width_findings()
          if f.symbol == "FIXTURE_DIVERGENT_WIDTHS"]
    assert fx and all(f.rule == "LNE610" for f in fx)
