"""JS node SDK end-to-end (third demo language; reference ships 8 — we
bundle Python, C++, JS). Skipped when no `node` runtime exists in the
image; the SDK is exercised the same way as the Python/C++ ones."""

import os
import shutil

import pytest

from maelstrom_tpu import run_test

NODE_BIN = shutil.which("node") or shutil.which("nodejs")
pytestmark = pytest.mark.skipif(NODE_BIN is None,
                                reason="no JS runtime in image")

JS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples", "js")


def test_js_echo():
    res = run_test("echo", dict(
        bin=NODE_BIN, bin_args=[f"{JS}/echo.js"], node_count=2,
        time_limit=3.0, rate=20.0, concurrency=4, seed=7))
    assert res["valid?"] is True


def test_js_broadcast_grid():
    res = run_test("broadcast", dict(
        bin=NODE_BIN, bin_args=[f"{JS}/broadcast.js"], node_count=5,
        topology="grid", time_limit=5.0, rate=20.0, concurrency=4,
        seed=7))
    assert res["valid?"] is True


def test_js_g_set():
    res = run_test("g-set", dict(
        bin=NODE_BIN, bin_args=[f"{JS}/g_set.js"], node_count=3,
        time_limit=5.0, rate=20.0, concurrency=4, seed=7))
    assert res["valid?"] is True


def test_js_lin_kv_proxy():
    res = run_test("lin-kv", dict(
        bin=NODE_BIN, bin_args=[f"{JS}/lin_kv_proxy.js"], node_count=2,
        time_limit=4.0, rate=15.0, concurrency=4, seed=7))
    assert res["valid?"] is True
