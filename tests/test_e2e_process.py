"""End-to-end tests of the process runtime against bundled example nodes —
the equivalent of the reference's `demo` self-test (core.clj:104-126)."""

import pytest

from conftest import example_bin
from maelstrom_tpu.runner import run_test


def run(workload, node, **opts):
    bin_cmd = example_bin(node)
    base = dict(bin=bin_cmd[0], bin_args=bin_cmd[1:], snapshot_store=False,
                time_limit=2.0, rate=30.0, concurrency=4, recovery_time=0.5,
                seed=42)
    base.update(opts)
    return run_test(workload, base)


def test_echo_e2e():
    res = run("echo", "echo.py", node_count=1)
    assert res["workload"]["valid?"] is True
    assert res["workload"]["ok-count"] > 10
    assert res["valid?"] is True
    assert res["net"]["stats"]["all"]["send-count"] > 0


def test_echo_availability_total():
    res = run("echo", "echo.py", node_count=2, availability="total")
    assert res["availability"]["valid?"] is True
    assert res["availability"]["ok-fraction"] == 1.0
