"""End-to-end tests of the process runtime against bundled example nodes —
the equivalent of the reference's `demo` self-test (core.clj:104-126)."""

import pytest

from conftest import example_bin
from maelstrom_tpu.runner import run_test


def run(workload, node, **opts):
    bin_cmd = example_bin(node)
    base = dict(bin=bin_cmd[0], bin_args=bin_cmd[1:], snapshot_store=False,
                time_limit=2.0, rate=30.0, concurrency=4, recovery_time=0.5,
                seed=42)
    base.update(opts)
    return run_test(workload, base)


def test_echo_e2e():
    res = run("echo", "echo.py", node_count=1)
    assert res["workload"]["valid?"] is True
    assert res["workload"]["ok-count"] > 10
    assert res["valid?"] is True
    assert res["net"]["stats"]["all"]["send-count"] > 0


def test_echo_availability_total():
    res = run("echo", "echo.py", node_count=2, availability="total")
    assert res["availability"]["valid?"] is True
    assert res["availability"]["ok-fraction"] == 1.0


def test_broadcast_e2e():
    res = run("broadcast", "broadcast.py", node_count=5, topology="grid",
              time_limit=3.0, recovery_time=1.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["acknowledged-count"] > 0
    assert w["lost-count"] == 0
    assert res["net"]["msgs-per-op"] > 0


@pytest.mark.slow
def test_broadcast_partition_e2e():
    res = run("broadcast", "broadcast.py", node_count=5, topology="tree4",
              time_limit=4.0, recovery_time=2.0,
              nemesis=["partition"], nemesis_interval=1.0)
    w = res["workload"]
    assert w["lost-count"] == 0, w


@pytest.mark.slow
def test_g_set_partition_e2e():
    res = run("g-set", "g_set.py", node_count=3, time_limit=3.0,
              recovery_time=1.5, nemesis=["partition"],
              nemesis_interval=1.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["lost-count"] == 0


@pytest.mark.slow
def test_pn_counter_e2e():
    res = run("pn-counter", "pn_counter.py", node_count=3, time_limit=3.0,
              recovery_time=1.0)
    assert res["workload"]["valid?"] is True, res["workload"]


def test_unique_ids_e2e():
    res = run("unique-ids", "unique_ids.py", node_count=3, time_limit=2.0)
    w = res["workload"]
    assert w["valid?"] is True
    assert w["acknowledged-count"] > 10


def test_lin_kv_proxy_e2e():
    res = run("lin-kv", "lin_kv_proxy.py", node_count=2, time_limit=3.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["key-count"] > 0


def test_txn_list_append_single_node_e2e():
    res = run("txn-list-append", "txn_single.py", node_count=1,
              time_limit=3.0, rate=30.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["txn-count"] > 20


@pytest.mark.slow
def test_txn_rw_register_single_node_e2e():
    res = run("txn-rw-register", "txn_single.py", node_count=1,
              time_limit=3.0, rate=30.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["txn-count"] > 20


def test_datomic_txn_multi_node_e2e():
    res = run("txn-list-append", "datomic_txn.py", node_count=3,
              time_limit=4.0, rate=20.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["txn-count"] > 10


@pytest.mark.slow
def test_txn_thunks_multi_node_e2e():
    """Per-key-thunk transactor (reference demo/clojure/
    multi_key_txn.clj as spec): immutable thunks in lww-kv + root map
    CAS in lin-kv stays strict-serializable."""
    res = run("txn-list-append", "txn_thunks.py", node_count=3,
              time_limit=4.0, rate=20.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["txn-count"] > 10


@pytest.mark.slow
def test_hat_isolation_tradeoff():
    """The HAT teaching point (reference demo/clojure/
    txn_rw_register_hat.clj as spec): total availability under
    partitions at read-uncommitted, but serializable checking flags the
    missing isolation on the SAME design under load."""
    res = run("txn-rw-register", "txn_rw_hat.py", node_count=3,
              concurrency=6, time_limit=5.0, rate=15.0,
              nemesis=["partition"], nemesis_interval=2.0,
              recovery_time=2.0, availability="total",
              consistency_models="read-uncommitted", seed=7)
    assert res["workload"]["valid?"] is True, res["workload"]
    assert res["availability"]["valid?"] is True, res["availability"]

    # anomaly production depends on real subprocess scheduling — retry a
    # couple of seeds so a lightly-loaded host can't yield a spuriously
    # clean history (ADVICE r3 #4)
    verdicts = []
    for seed in (5, 11, 23):
        res2 = run("txn-rw-register", "txn_rw_hat.py", node_count=3,
                   concurrency=9, time_limit=6.0, rate=60.0, key_count=4,
                   nemesis=["partition"], nemesis_interval=1.5,
                   recovery_time=2.0, consistency_models="serializable",
                   seed=seed)
        verdicts.append(res2["workload"]["valid?"])
        if verdicts[-1] is False:
            break
    assert False in verdicts, \
        f"HAT should not pass serializable checking under load: {verdicts}"


@pytest.mark.slow
def test_no_isolation_node_caught():
    """The un-isolated single-node transactor (reference demo/clojure/
    txn_rw_register_no_isolation.clj as spec) interleaves mid-txn; the
    Elle rw-register checker must flag intermediate reads / cycles with
    zero network faults."""
    # retried across seeds: anomalies need real scheduling interleaves,
    # which a lightly-loaded host may not produce first try (ADVICE r3 #4)
    last = None
    for seed in (3, 17, 29):
        res = run("txn-rw-register", "txn_rw_no_isolation.py",
                  node_count=1, concurrency=16, time_limit=6.0,
                  rate=120.0, key_count=4, seed=seed)
        last = w = res["workload"]
        if w["valid?"] is False and set(w.get("anomaly-types") or []) & {
                "G1b", "G1c", "G-single", "G2-item", "internal"}:
            return
    assert False, f"no-isolation anomalies not caught: {last}"


@pytest.mark.slow
def test_raft_node_lin_kv_with_partitions_e2e():
    """The canonical Raft demo config (reference doc/06-raft): lin-kv
    over the bundled raft.py, partitions during the run."""
    res = run("lin-kv", "raft.py", node_count=3, concurrency=6,
              rate=20.0, time_limit=10.0, nemesis=["partition"],
              nemesis_interval=2.5, recovery_time=2.0, seed=7)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert res["stats"]["ok-count"] > 30


@pytest.mark.slow
def test_counter_over_seq_kv_service_e2e():
    """Exercises the Sequential consistency wrapper end-to-end: CAS retry
    adds + the write-to-force-recency read trick (reference doc/04-crdts
    seq-kv counter)."""
    res = run("g-counter", "counter_seq_kv.py", node_count=3,
              time_limit=3.0, recovery_time=1.0)
    assert res["workload"]["valid?"] is True, res["workload"]
