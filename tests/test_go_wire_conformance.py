"""Runtime-independent wire conformance for the Go SDK + nodes
(VERDICT r4 next #8).

No Go toolchain exists in this image, so — like the JS suite
(test_js_wire_conformance.py) — the sources are validated STATICALLY
against the wire protocol and the schema registry: envelope shape,
init handshake, in_reply_to plumbing, error-code catalog membership,
and every client-facing reply type a node emits. Behavioral testing
runs in test_go_nodes.py whenever a `go` binary is present (and the
SDK carries its own fake-stdio `go test` suite, the reference
node_test.go pattern)."""

import os
import re

import pytest

from wire_conformance_common import (assert_error_codes_in_catalog,
                                     assert_node_reply_types)

GO_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "go")

SDK = open(os.path.join(GO_DIR, "maelstrom", "maelstrom.go")).read()
KV = open(os.path.join(GO_DIR, "maelstrom", "kv.go")).read()

# each Go node program -> (registry namespace, peer-internal RPC types)
NODES = {
    "echo": ("echo", set()),
    "broadcast": ("broadcast", {"gossip"}),
    "g_set": ("g-set", {"merge"}),
    "counter": ("g-counter", set()),
}


def _node_src(name):
    return open(os.path.join(GO_DIR, "cmd", name, "main.go")).read()


def _literal_types(src):
    """Every "type": "x" value in map[string]any literals."""
    return set(re.findall(r'"type":\s*"([a-z_]+)"', src))


def test_sdk_envelope_shape():
    # envelopes are {src, dest, body}; replies stamp in_reply_to from
    # the request's msg_id
    assert '"src": n.id' in SDK and '"dest": dest' in SDK \
        and '"body": body' in SDK
    assert '"in_reply_to"' in SDK and '"msg_id"' in SDK


def test_sdk_init_handshake():
    # init -> init_ok, node_id + node_ids captured
    assert '"init_ok"' in SDK
    assert '"node_id"' in SDK and '"node_ids"' in SDK


def test_sdk_error_codes_in_catalog():
    codes = {int(c) for c in re.findall(
        r"Err[A-Za-z]+\s*=\s*(\d+)", SDK)}
    assert_error_codes_in_catalog(codes)


def test_kv_client_speaks_service_schema():
    # the KV client's request bodies carry the service op vocabulary
    for field in ('"type": "read"', '"type": "write"', '"type": "cas"',
                  '"key"', '"value"', '"from"', '"to"',
                  '"create_if_not_exists"'):
        assert field in KV, field
    assert '"lin-kv"' in KV and '"seq-kv"' in KV and '"lww-kv"' in KV


@pytest.mark.parametrize("name", sorted(NODES))
def test_node_reply_types_in_registry(name):
    namespace, internal = NODES[name]
    src = _node_src(name)
    emitted = _literal_types(src)
    assert_node_reply_types(namespace, internal, emitted, name)
