"""End-to-end tests of the C++ node SDK: build the example nodes with
make, then run them through the full harness (SURVEY §2.3 native
components #1/#2)."""

import os

import pytest

from conftest import REPO
from maelstrom_tpu.runner import run_test

# cpp_bins fixture: session-scoped, in conftest.py


def run(workload, binary, cpp_bins, **opts):
    base = dict(bin=os.path.join(cpp_bins, binary), bin_args=[],
                snapshot_store=False, time_limit=2.0, rate=30.0,
                concurrency=4, recovery_time=0.5, seed=42)
    base.update(opts)
    return run_test(workload, base)


def test_cpp_echo(cpp_bins):
    res = run("echo", "echo", cpp_bins, node_count=2)
    assert res["valid?"] is True, res["workload"]
    assert res["workload"]["ok-count"] > 10


@pytest.mark.slow
def test_cpp_g_set_with_partitions(cpp_bins):
    res = run("g-set", "g_set", cpp_bins, node_count=3, time_limit=3.0,
              recovery_time=1.5, nemesis=["partition"],
              nemesis_interval=1.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["lost-count"] == 0


@pytest.mark.slow
def test_cpp_lin_kv_proxy(cpp_bins):
    res = run("lin-kv", "lin_kv_proxy", cpp_bins, node_count=2,
              time_limit=3.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["key-count"] > 0


@pytest.mark.slow
def test_cpp_broadcast_with_partitions(cpp_bins):
    res = run("broadcast", "broadcast", cpp_bins, node_count=5,
              topology="grid", time_limit=3.0, recovery_time=1.5,
              nemesis=["partition"], nemesis_interval=1.0)
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["lost-count"] == 0
    assert w["acknowledged-count"] > 0


@pytest.mark.slow
def test_cpp_pn_counter(cpp_bins):
    res = run("pn-counter", "pn_counter", cpp_bins, node_count=3,
              time_limit=4.0, recovery_time=1.0)
    assert res["valid?"] is True, res["workload"]
    assert res["stats"]["ok-count"] > 30


@pytest.mark.slow
def test_cpp_pn_counter_as_g_counter(cpp_bins):
    res = run("g-counter", "pn_counter", cpp_bins, node_count=3,
              time_limit=4.0, recovery_time=1.0)
    assert res["valid?"] is True, res["workload"]
