"""The device-time observatory (telemetry/profiler.py): profiling must
be purely observational — trajectories bit-identical with it on or off
in both carry layouts and under the sharded driver — while the captured
records keep their schema contracts: heartbeat ``device-ms`` lanes,
the ``results.perf.phases.device`` roll-up, the ``maelstrom profile``
report, timed-fallback attribution that sums to the measured dispatch
wall, and the trace-teardown guarantee (an exception mid-capture must
never leave the process-wide ``jax.profiler`` trace open).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu import cli
from maelstrom_tpu.campaign.checkpoint import (load_checkpoint,
                                               restore_carry,
                                               save_checkpoint)
from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.telemetry import profiler as profiler_mod
from maelstrom_tpu.telemetry.profiler import (PHASE_LABELS,
                                              DeviceProfiler, hot_scope,
                                              phase_weights,
                                              render_profile_report)
from maelstrom_tpu.telemetry.stream import read_heartbeat, render_chunk_line
from maelstrom_tpu.tpu.harness import (make_sim_config, run_tpu_test)
from maelstrom_tpu.tpu.pipeline import (ResumeState, _init_pipelined,
                                        make_chunk_fn, run_sim_pipelined)

pytestmark = pytest.mark.profiler

# the shared tiny echo config: 300 ticks / chunk 50 = 6 chunks
ECHO_OPTS = dict(node_count=2, concurrency=2, n_instances=8,
                 record_instances=2, time_limit=0.3, rate=100.0,
                 latency=5.0, seed=3, funnel=False, pipeline="on",
                 chunk_ticks=50)


class Killed(Exception):
    pass


def _trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# --- observational purity --------------------------------------------------

@pytest.mark.parametrize("layout", ["lead", "minor"])
def test_pipelined_bit_identity_on_off(layout):
    model = EchoModel()
    sim = make_sim_config(model, {**ECHO_OPTS, "layout": layout})
    params = model.make_params(sim.net.n_nodes)
    off = run_sim_pipelined(model, sim, 3, params, chunk=50)
    prof = DeviceProfiler("on", model=model, sim=sim, params=params)
    on = run_sim_pipelined(model, sim, 3, params, chunk=50,
                           profiler=prof)
    _trees_equal(off.carry, on.carry)
    assert np.array_equal(off.events, on.events)
    # and it really profiled: every chunk captured in "on" mode
    assert len(prof.records) == on.perf["chunks"]
    assert on.perf["device"]["captured-chunks"] == len(prof.records)


def test_sharded_bit_identity_on_off():
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked)
    model = EchoModel()
    opts = dict(ECHO_OPTS, n_instances=4, time_limit=0.12)
    sim = make_sim_config(model, opts)
    mesh = make_mesh(2)
    off = run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                  chunk=40)
    prof = DeviceProfiler("on", model=model, sim=sim)
    perf = {}
    on = run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                 chunk=40, perf=perf, profiler=prof)
    assert off[0] == on[0]                      # psum'd NetStats
    assert np.array_equal(off[1], on[1])        # violations
    assert np.array_equal(off[2], on[2])        # events
    assert prof.records and perf["device"]["captured-chunks"] > 0


def test_auto_mode_samples_not_every_chunk():
    p = DeviceProfiler("auto")
    expect = [i < DeviceProfiler.AUTO_FIRST_K
              or i % DeviceProfiler.AUTO_EVERY_N == 0 for i in range(40)]
    assert [p.should_capture(i) for i in range(40)] == expect
    assert sum(expect) < 40                     # auto really skips
    with pytest.raises(ValueError):
        DeviceProfiler("sometimes")


# --- the streamed schema ---------------------------------------------------

@pytest.fixture(scope="module")
def profiled_run(tmp_path_factory):
    """One stored chunked echo run with --device-profile on."""
    store = str(tmp_path_factory.mktemp("prof-store"))
    results = run_tpu_test(EchoModel(),
                           dict(ECHO_OPTS, store_root=store,
                                device_profile="on"))
    return results, results["store-dir"]


def test_heartbeat_device_ms_schema(profiled_run):
    _, run_dir = profiled_run
    hb = read_heartbeat(os.path.join(run_dir, "heartbeat.jsonl"))
    dev_chunks = [c for c in hb["chunks"] if c.get("device-ms")]
    assert len(dev_chunks) == len(hb["chunks"])   # "on" = every chunk
    for rec in dev_chunks:
        assert set(rec["device-ms"]) <= set(PHASE_LABELS)
        assert all(isinstance(v, float) and v >= 0.0
                   for v in rec["device-ms"].values())
        assert rec["device-source"] in ("timed", "trace")
        assert rec["device-s"] > 0.0
        # the watch lane renders from exactly these keys
        assert "dev[" in render_chunk_line(rec)


def test_results_device_rollup_schema(profiled_run):
    results, run_dir = profiled_run
    dev = results["perf"]["phases"]["device"]
    assert dev["mode"] == "on"
    assert dev["source"] in ("timed", "trace")
    assert dev["captured-chunks"] == 6            # 300 ticks / chunk 50
    assert dev["ms-per-tick"] > 0.0
    per = dev["per-phase-ms-per-tick"]
    assert per and set(per) <= set(PHASE_LABELS)
    assert abs(sum(per.values()) - dev["ms-per-tick"]) \
        <= 0.05 * dev["ms-per-tick"] + 1e-3
    # the stored results.json carries the same roll-up
    with open(os.path.join(run_dir, "results.json")) as f:
        stored = json.load(f)
    assert stored["perf"]["phases"]["device"] == json.loads(
        json.dumps(dev))


def test_profile_cli_smoke(profiled_run, capsys):
    _, run_dir = profiled_run
    assert cli.main(["profile", run_dir]) == 0
    out = capsys.readouterr().out
    assert "hot scope:" in out
    assert "ms/tick" in out
    # a dir with no device time exits 2, never crashes
    assert cli.main(["profile", os.path.dirname(run_dir)]) == 2


def test_profile_off_leaves_no_lanes(tmp_path):
    results = run_tpu_test(EchoModel(),
                           dict(ECHO_OPTS, store_root=str(tmp_path),
                                device_profile="off"))
    assert "device" not in results["perf"]["phases"]
    hb = read_heartbeat(os.path.join(results["store-dir"],
                                     "heartbeat.jsonl"))
    assert not any(c.get("device-ms") for c in hb["chunks"])
    assert render_profile_report(results["store-dir"]) is None


# --- timed-fallback attribution --------------------------------------------

def test_fallback_attribution_sums_to_measured_wall():
    """Each timed capture splits the measured dispatch wall across the
    cost model's phase weights: the per-phase sum must equal the
    recorded device wall (by construction, modulo rounding), and the
    recorded wall must be within tolerance of an external measurement
    of the same warm dispatch."""
    import time

    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    params = model.make_params(sim.net.n_nodes)
    chunk_fn = make_chunk_fn(model, sim, params,
                             np.arange(8, dtype=np.int32), 64, 1)
    st = _init_pipelined(model, sim, jnp.int32(3), params,
                         jnp.arange(8, dtype=jnp.int32))
    st = jax.tree.map(lambda x: x.copy(), st)
    prof = DeviceProfiler("on", model=model, sim=sim, params=params)
    # warm-up capture: compile happens inside the dispatch call, which
    # the profiler's post-return stamp excludes from device time
    (st, *_), warm = prof.capture(chunk_fn,
                                  (st, jnp.int32(0), 50), 50)
    t0 = time.monotonic()
    (st, *_), rec = prof.capture(chunk_fn, (st, jnp.int32(50), 50), 50)
    external_wall_ms = (time.monotonic() - t0) * 1000.0
    assert rec["source"] == "timed"
    phase_sum = sum(rec["per-phase-ms"].values())
    measured = rec["device-s"] * 1000.0
    assert measured > 0
    assert abs(phase_sum - measured) <= 0.25 * measured + 1e-3
    # the recorded device wall is a real measurement of this dispatch,
    # not a constant: it cannot exceed the external wall around it
    assert measured <= external_wall_ms + 1e-6


def test_phase_weights_cover_known_scopes():
    """The fallback attributes against the cost model's named scopes —
    the vocabulary COST505 audits — and the weights are a partition."""
    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    w = phase_weights(model, sim)
    assert abs(sum(w.values()) - 1.0) < 1e-9
    assert set(w) <= set(PHASE_LABELS)
    assert "client_step" in w and "node_phase" in w
    assert hot_scope(w) is not None


# --- checkpoint/resume -----------------------------------------------------

def test_resume_with_profiling_bit_exact(tmp_path):
    """Kill mid-run, resume WITH profiling on: the concatenated
    segments equal the uninterrupted unprofiled run, and the resumed
    profiler's capture schedule continues at the absolute chunk index
    (no re-burst of the auto mode's first-K chunks)."""
    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    params = model.make_params(sim.net.n_nodes)
    base = run_sim_pipelined(model, sim, 3, params, chunk=50)

    d = str(tmp_path)

    def cb(state, ticks, host):
        save_checkpoint(d, kind="pipelined", state=state, ticks=ticks,
                        chunks=host["chunks"],
                        compact=tuple(host["compact"]),
                        journal=tuple(host["journal"]))
        raise Killed

    prof1 = DeviceProfiler("on", model=model, sim=sim, params=params)
    with pytest.raises(Killed):
        run_sim_pipelined(model, sim, 3, params, chunk=50,
                          checkpoint_cb=cb, checkpoint_every=2,
                          profiler=prof1)
    ck = load_checkpoint(d)
    template = _init_pipelined(model, sim, 3, params,
                               np.arange(8, dtype=np.int32))
    resume = ResumeState(carry=restore_carry(template, ck["carry"]),
                         ticks=ck["ticks"], chunks=ck["chunks"],
                         compact=tuple(ck["compact"]),
                         journal=tuple(ck["journal"]))
    prof2 = DeviceProfiler("on", model=model, sim=sim, params=params)
    res = run_sim_pipelined(model, sim, 3, params, chunk=50,
                            resume=resume, profiler=prof2)
    _trees_equal(base.carry, res.carry)
    assert np.array_equal(base.events, res.events)
    # the resumed segment captured exactly its own chunks
    assert len(prof2.records) == 6 - ck["chunks"]


# --- trace teardown --------------------------------------------------------

def test_capture_teardown_on_exception(monkeypatch, tmp_path):
    """An fn blow-up mid-capture must propagate AND stop the
    process-wide trace — a later ``jax.profiler.start_trace`` must not
    fail with 'already active' (the regression this pins)."""
    monkeypatch.setenv("MAELSTROM_DEVICE_TRACE", "1")
    monkeypatch.setattr(profiler_mod, "_TRACE_FAILED", [False])
    prof = DeviceProfiler("on")
    assert prof._try_trace

    class Boom(Exception):
        pass

    def bad_fn():
        raise Boom

    with pytest.raises(Boom):
        prof.capture(bad_fn, (), 1)
    # the trace was torn down: a fresh window opens and closes cleanly
    jax.profiler.start_trace(str(tmp_path))
    jax.profiler.stop_trace()


def test_trace_failure_latches_to_timed(monkeypatch):
    """On this backend the forced trace attempt yields no parseable
    trace-viewer JSON: the first capture must fall back to timed,
    latch the process-wide flag, and still record real numbers."""
    monkeypatch.setenv("MAELSTROM_DEVICE_TRACE", "1")
    monkeypatch.setattr(profiler_mod, "_TRACE_FAILED", [False])
    prof = DeviceProfiler("on")

    def fn(x):
        return jnp.sum(x * 2.0)

    out, rec = prof.capture(fn, (jnp.ones(64),), 4)
    assert float(out) == 128.0
    assert rec["source"] == "timed" and rec["device-s"] >= 0.0
