"""The parallel host verdict pipeline (tpu/decode.py + checkers/pool.py).

Three contracts, all byte-level:

1. **Vectorized decode identity** — the NumPy column decoder produces
   dict histories ``json.dumps``-identical to the original per-event
   loop (kept as ``decode.reference_histories``, the pinned oracle),
   on the dense tensor AND straight from the compacted chunk buffers.
2. **Pool-vs-serial verdict identity** — every registered workload, in
   both carry layouts, checked through the worker farm at 1/2/4
   workers, yields exactly the serial path's verdicts and stored
   histories (tier-1 runs a representative slice; the full matrix is
   the slow sweep).
3. **Resilience** — killing every pool worker mid-run still yields the
   serial verdicts (auto-fallback), and a checker that raises becomes
   a structured invalid-with-reason verdict (instance id, checker
   name, truncated traceback), never a crash.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu import decode
from maelstrom_tpu.tpu.harness import (events_to_histories,
                                       make_sim_config, run_tpu_test)
from maelstrom_tpu.tpu.runtime import run_sim

pytestmark = pytest.mark.pool

# one short, dense config every workload decodes real traffic from
DECODE_OPTS = dict(node_count=3, concurrency=4, n_instances=8,
                   record_instances=8, time_limit=0.5, rate=300.0,
                   latency=4.0, rpc_timeout=0.25,
                   nemesis=["partition"], nemesis_interval=0.1,
                   p_loss=0.05, recovery_time=0.1, pool_slots=32,
                   seed=11, telemetry=False, ms_per_tick=1)

ALL_WORKLOADS = ["echo", "unique-ids", "broadcast", "g-set",
                 "pn-counter", "g-counter", "lin-kv", "kafka",
                 "txn-list-append", "txn-rw-register"]

# tier-1 covers every distinct checker family once, alternating carry
# layouts; the full workload x layout x worker-count matrix is slow
TIER1_MATRIX = [("echo", "lead"), ("unique-ids", "minor"),
                ("g-set", "lead"), ("pn-counter", "minor"),
                ("lin-kv", "minor"), ("kafka", "lead"),
                ("txn-list-append", "lead"),
                ("txn-rw-register", "minor")]
SLOW_MATRIX = [(wl, layout) for wl in ALL_WORKLOADS
               for layout in ("lead", "minor")
               if (wl, layout) not in TIER1_MATRIX]


def _workload_opts(workload):
    opts = dict(DECODE_OPTS)
    if workload == "kafka":
        opts.update(node_count=1, nemesis=[], nemesis_interval=0.5)
    return opts


def _run_events(workload, layout):
    opts = {**_workload_opts(workload), "layout": layout}
    model = get_model(workload, opts["node_count"])
    sim = make_sim_config(model, opts)
    carry, ys = run_sim(model, sim, opts["seed"],
                        model.make_params(sim.net.n_nodes))
    return model, sim, opts, np.asarray(ys.events)


def _dump(histories):
    return [json.dumps(h) for h in histories]


# --- 1. vectorized decode identity ----------------------------------------


@pytest.mark.parametrize("workload,layout",
                         [("echo", "lead"), ("unique-ids", "lead"),
                          ("lin-kv", "minor"),
                          ("txn-list-append", "lead"),
                          ("kafka", "minor")])
def test_vectorized_decode_matches_reference(workload, layout):
    """events_to_histories (the column decoder) == the original
    per-event loop, json-byte-for-byte, wide ev_vals included."""
    model, sim, opts, events = _run_events(workload, layout)
    ref = decode.reference_histories(
        model, events, final_start=sim.client.final_start,
        ms_per_tick=opts["ms_per_tick"])
    vec = events_to_histories(model, events,
                              final_start=sim.client.final_start,
                              ms_per_tick=opts["ms_per_tick"])
    assert sum(len(h) for h in ref) > 20, "fixture decoded no traffic"
    assert _dump(vec) == _dump(ref)


def test_compact_decode_matches_dense():
    """Slabs decoded straight from the compacted chunk stream equal
    the dense-tensor decode — the pipelined path never rebuilds the
    dense tensor, so this IS its history correctness proof."""
    from maelstrom_tpu.tpu.pipeline import run_sim_pipelined
    model, sim, opts, events = _run_events("lin-kv", "lead")
    res = run_sim_pipelined(model, sim, opts["seed"],
                            model.make_params(sim.net.n_nodes),
                            chunk=50, keep_compact=True,
                            dense_events=False)
    assert res.events is None
    slabs = decode.decode_compact(model, sim.client.n_clients,
                                  sim.record_instances, res.compact)
    lazy = decode.LazyHistories(model, slabs, sim.record_instances,
                                sim.client.final_start,
                                opts["ms_per_tick"])
    ref = decode.reference_histories(
        model, events, final_start=sim.client.final_start,
        ms_per_tick=opts["ms_per_tick"])
    assert _dump(lazy.materialize()) == _dump(ref)


def test_stream_decoder_chunked_equals_one_shot():
    """Feeding the StreamDecoder chunk-by-chunk (the run_chunked
    consume-side hookup) equals decoding all chunks at once — index
    counters and record order survive the incremental path."""
    from maelstrom_tpu.tpu.pipeline import run_sim_pipelined
    model, sim, opts, events = _run_events("echo", "lead")
    res = run_sim_pipelined(model, sim, opts["seed"],
                            model.make_params(sim.net.n_nodes),
                            chunk=50, keep_compact=True)
    sd = decode.StreamDecoder(model, sim.client.n_clients,
                              sim.record_instances,
                              sim.client.final_start,
                              opts["ms_per_tick"])
    for rows, count in res.compact:
        sd.feed(rows, count)
    ref = decode.reference_histories(
        model, events, final_start=sim.client.final_start,
        ms_per_tick=opts["ms_per_tick"])
    assert _dump(sd.finish().materialize()) == _dump(ref)


def test_final_tag_and_ms_per_tick():
    """final-read tagging and the virtual-clock time stamps survive
    vectorization (the two non-trivial per-record branches)."""
    model, sim, opts, events = _run_events("g-set", "lead")
    ref = decode.reference_histories(model, events,
                                     final_start=sim.client.final_start,
                                     ms_per_tick=2.5)
    vec = events_to_histories(model, events,
                              final_start=sim.client.final_start,
                              ms_per_tick=2.5)
    assert _dump(vec) == _dump(ref)
    assert any(r.get("final") for h in ref for r in h), \
        "fixture produced no final-phase ops"


# --- 2. pool-vs-serial verdict identity -----------------------------------


def _identity_case(workload, layout, workers_list=(2,)):
    opts = {**_workload_opts(workload), "layout": layout,
            "store_root": None, "funnel": False}
    model = get_model(workload, opts["node_count"])
    serial = run_tpu_test(model, dict(opts, check_workers=0))
    for workers in workers_list:
        pooled = run_tpu_test(get_model(workload, opts["node_count"]),
                              dict(opts, check_workers=workers))
        assert pooled["instances"] == serial["instances"], \
            (workload, layout, workers)
        assert pooled["valid?"] == serial["valid?"]
        assert pooled["net"] == serial["net"]
        rec = pooled["perf"]["phases"]["check"]
        assert rec["mode"] in ("pooled", "pooled-fallback-serial")
    return serial


@pytest.mark.parametrize("workload,layout", TIER1_MATRIX)
def test_pool_verdicts_identical_tier1(workload, layout):
    _identity_case(workload, layout, workers_list=(2,))


@pytest.mark.slow
@pytest.mark.parametrize("workload,layout", SLOW_MATRIX)
def test_pool_verdicts_identical_full(workload, layout):
    _identity_case(workload, layout, workers_list=(1, 2, 4))


@pytest.mark.slow
@pytest.mark.parametrize("workload,layout", TIER1_MATRIX)
def test_pool_verdicts_identical_tier1_all_workers(workload, layout):
    _identity_case(workload, layout, workers_list=(1, 4))


def test_pooled_stored_histories_byte_identical(tmp_path):
    """Store artifacts (history-i.jsonl) from a pooled run equal the
    serial run's, byte for byte."""
    opts = {**_workload_opts("lin-kv"), "funnel": False}
    s_root, p_root = str(tmp_path / "s"), str(tmp_path / "p")
    run_tpu_test(get_model("lin-kv", 3),
                 dict(opts, check_workers=0, store_root=s_root))
    run_tpu_test(get_model("lin-kv", 3),
                 dict(opts, check_workers=2, store_root=p_root))
    for i in range(opts["record_instances"]):
        a = open(os.path.join(s_root, "lin-kv-tpu", "latest",
                              f"history-{i}.jsonl")).read()
        b = open(os.path.join(p_root, "lin-kv-tpu", "latest",
                              f"history-{i}.jsonl")).read()
        assert a == b, f"history-{i} diverged"
        assert a.strip(), f"history-{i} is empty"


def test_incremental_unique_ids_matches_batch():
    """The streaming unique-ids twin produces the batch checker's
    exact dict (first-seen order, repr tie-breaks) fed in chunks."""
    from maelstrom_tpu.checkers.pool import _IncrementalUniqueIds
    from maelstrom_tpu.checkers.unique_ids import unique_ids_checker
    history = []
    for i, val in enumerate([7, 3, 7, 12, 3, 3, 99]):
        history.append({"f": "generate", "value": None,
                        "type": "invoke", "index": 2 * i})
        history.append({"f": "generate", "value": val, "type": "ok",
                        "index": 2 * i + 1})
    history.append({"f": "generate", "value": None, "type": "invoke",
                    "index": len(history)})   # unacknowledged tail
    inc = _IncrementalUniqueIds(None, {})
    for lo in range(0, len(history), 3):      # ragged chunking
        inc.feed(history[lo:lo + 3])
    assert inc.result() == unique_ids_checker(history)
    assert inc.result()["valid?"] is False


# --- 3. resilience ---------------------------------------------------------


def test_pool_killed_mid_run_falls_back_to_serial(monkeypatch):
    """SIGKILL every checker worker right after the pool spawns: the
    run must complete with the serial path's exact verdicts and say so
    (mode=pooled-fallback-serial)."""
    from maelstrom_tpu.checkers import pool as pool_mod

    opts = {**_workload_opts("lin-kv"), "funnel": False}
    serial = run_tpu_test(get_model("lin-kv", 3),
                          dict(opts, check_workers=0))

    real_feed = pool_mod.CheckerPool.feed
    state = {"killed": False}

    def kill_then_feed(self, slabs):
        if not state["killed"]:
            self.kill()          # every worker dies mid-run
            state["killed"] = True
        return real_feed(self, slabs)

    monkeypatch.setattr(pool_mod.CheckerPool, "feed", kill_then_feed)
    pooled = run_tpu_test(get_model("lin-kv", 3),
                          dict(opts, check_workers=2))
    assert state["killed"], "pool was never exercised"
    rec = pooled["perf"]["phases"]["check"]
    assert rec["mode"] == "pooled-fallback-serial", rec
    assert pooled["instances"] == serial["instances"]
    assert pooled["valid?"] == serial["valid?"]


def test_checker_blowup_is_structured_invalid():
    """Satellite pin: a checker exception becomes invalid-with-reason —
    instance id, checker name, truncated traceback — and the composed
    verdict counts it as a definite False (results.checker-errors)."""

    from maelstrom_tpu.models.echo import EchoModel

    class BlowupEcho(EchoModel):
        checker_name = "blowup-echo"

        def checker(self):
            def chk(history, opts):
                raise RuntimeError("checker exploded on purpose")
            return chk

    res = run_tpu_test(BlowupEcho(), dict(
        node_count=2, concurrency=2, n_instances=8, record_instances=2,
        time_limit=0.5, rate=100.0, latency=5.0, seed=3,
        check_workers=0, funnel=False))
    assert res["valid?"] is False
    assert res["checker-errors"] == 2
    inst = res["instances"][0]
    assert inst["valid?"] is False
    assert inst["checker"] == "blowup-echo"
    assert inst["instance"] == 0
    assert "RuntimeError" in inst["traceback"]
    assert "checker exploded on purpose" in inst["error"]


def test_worker_side_blowup_is_structured_too():
    """The worker main loop wraps checker exceptions with the same
    checker_failure dict (exercised via the worker internals — pooled
    e2e blow-ups need a registry model, which test models are not)."""
    from maelstrom_tpu.checkers import checker_failure
    try:
        raise ValueError("boom")
    except ValueError as e:
        v = checker_failure(e, checker="elle-list-append", instance=5)
    assert v["valid?"] is False
    assert v["checker"] == "elle-list-append"
    assert v["instance"] == 5
    assert v["traceback"].endswith("ValueError: boom\n")
    from maelstrom_tpu.checkers import compose_valid
    assert compose_valid([v["valid?"], True]) is False


def test_checker_failure_identical_across_call_sites():
    """The byte-identity contract extends to BLOW-UP verdicts: the
    formatted traceback drops its first frame (the harness/pool call
    site), so the same checker exception produces the same dict
    whether a farm worker or the serial loop caught it."""
    from maelstrom_tpu.checkers import checker_failure

    def exploding_checker(history, opts):
        raise RuntimeError("same explosion")

    def worker_like_call_site():
        try:
            exploding_checker([], {})
        except Exception as e:
            return checker_failure(e, checker="c", instance=3)

    def serial_like_call_site():
        try:
            exploding_checker([], {})
        except Exception as e:
            return checker_failure(e, checker="c", instance=3)

    assert worker_like_call_site() == serial_like_call_site()


def test_resolve_check_workers_auto():
    from maelstrom_tpu.checkers.pool import resolve_check_workers
    assert resolve_check_workers(0, 512) == 0
    assert resolve_check_workers(3, 512) == 3
    auto = resolve_check_workers(None, 512)
    if (os.cpu_count() or 1) >= 2:
        assert 1 <= auto <= 4
    else:
        assert auto == 0
    # tiny fleets never pay pool spawn
    assert resolve_check_workers(None, 4) == 0


# --- decode speedup (the >=5x acceptance, measured) ------------------------


@pytest.mark.slow
def test_vectorized_decode_speedup():
    """Acceptance: the event -> per-instance-op-array decode (the
    column pass that feeds the checker farm) beats the per-event
    reference loop >= 5x on a bench-shaped tensor (measured ~9x on the
    1-vCPU dev box, doc/results.md scoreboard). The remaining cost —
    dict materialization — moved to the checker boundary, where the
    pool spreads it across workers; the lazily-materialized dicts stay
    byte-identical."""
    import time

    model, sim, opts, events = _run_events("lin-kv", "lead")
    # tile the recorded instances to bench scale (identical per-copy
    # content; the decoder treats copies as distinct instances)
    reps = 16
    events = np.tile(events, (1, reps, 1, 1, 1))
    t0 = time.monotonic()
    ref = decode.reference_histories(
        model, events, final_start=sim.client.final_start)
    ref_s = time.monotonic() - t0
    t0 = time.monotonic()
    slabs = decode.decode_dense(model, events)
    col_s = time.monotonic() - t0
    lazy = decode.LazyHistories(model, slabs, events.shape[1],
                                sim.client.final_start, 1)
    assert _dump(lazy.materialize()) == _dump(ref)
    assert col_s * 5 <= ref_s, (col_s, ref_s)


@pytest.mark.slow
def test_pool_check_speedup_at_4_workers():
    """Acceptance: 512-instance lin-kv verdict wall-clock >= 2.5x
    faster through 4 checker workers than serial. Needs real cores —
    skipped below 4 (the 1-vCPU dev box runs the identity half of the
    contract; this half is the multi-core window's to hold)."""
    import time

    if (os.cpu_count() or 1) < 4:
        pytest.skip("needs >= 4 cores to demonstrate pool scaling")
    from maelstrom_tpu.checkers.pool import (CheckerPool, pool_spec,
                                             checker_name)
    from maelstrom_tpu.checkers import checker_failure

    opts = {**_workload_opts("lin-kv"), "time_limit": 2.0,
            "record_instances": 8, "n_instances": 8}
    model = get_model("lin-kv", 3)
    sim = make_sim_config(model, opts)
    carry, ys = run_sim(model, sim, opts["seed"],
                        model.make_params(3))
    base = decode.decode_dense(model, np.asarray(ys.events))
    # tile the 8 recorded instances to a 512-instance verdict load
    slabs = {i: base[i % 8] for i in range(512) if (i % 8) in base}
    spec = pool_spec(model, opts, sim.client.final_start, 1)

    def pooled(workers):
        farm = CheckerPool(spec, workers)
        try:
            t0 = time.monotonic()
            farm.feed(slabs)
            out = farm.finalize(list(range(512)))
            dt = time.monotonic() - t0
            assert out is not None, "pool broke"
            return out, dt
        finally:
            farm.close()
    # warm the forkserver so worker startup is not billed to the run
    pooled(1)
    checker = model.checker()
    lazy = decode.LazyHistories(model, slabs, 512,
                                sim.client.final_start, 1)
    t0 = time.monotonic()
    serial = {}
    for inst in range(512):
        try:
            serial[inst] = checker(lazy[inst], opts)
        except Exception as e:
            serial[inst] = checker_failure(e, checker_name(model),
                                           inst)
    serial_s = time.monotonic() - t0
    got, pooled_s = pooled(4)
    assert got == serial
    assert pooled_s * 2.5 <= serial_s, (pooled_s, serial_s)
