"""End-to-end runs of the Clojure (babashka) example nodes through the
process runtime. Skips cleanly when no `bb` interpreter is present
(this image ships none — the static wire conformance in
test_clojure_wire_conformance.py still runs)."""

import os
import shutil

import pytest

from maelstrom_tpu import run_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLJ = os.path.join(REPO, "examples", "clojure")

pytestmark = pytest.mark.skipif(
    shutil.which("bb") is None, reason="no babashka in image")


def _bin(name):
    return dict(bin="bb", bin_args=[os.path.join(CLJ, name)])


def test_clojure_echo_e2e(tmp_path):
    res = run_test("echo", dict(
        **_bin("echo.clj"), node_count=2, time_limit=3.0, rate=20.0,
        concurrency=4, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_clojure_broadcast_partition_e2e(tmp_path):
    res = run_test("broadcast", dict(
        **_bin("broadcast.clj"), node_count=3, time_limit=6.0,
        rate=20.0, concurrency=4, nemesis=["partition"],
        nemesis_interval=2.0, recovery_time=3.0,
        store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_clojure_counter_seq_kv_e2e(tmp_path):
    res = run_test("g-counter", dict(
        **_bin("counter.clj"), node_count=2, time_limit=5.0,
        rate=10.0, concurrency=4, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True
