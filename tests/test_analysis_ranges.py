"""Value-range abstract-interpreter tests (analysis/absint.py).

Pins the PR's acceptance bars: each planted range fixture trips its
ABS7xx rule in BOTH carry layouts, real models prove overflow-free to
(at least) the production horizon with the netsim scatter path
certified race-free, the manifest round-trips / gates drift / is
re-recordable, the scan widener terminates (and refuses to "prove" a
super-linear recurrence), the combined gate reuses the shared
trace_cache (no duplicate traces), and ``make_sim_config`` refuses a
horizon above a model's proven bound BY NAME.
"""

import json
import os

import jax.numpy as jnp
import pytest

from maelstrom_tpu.analysis import absint, cost_model, run_lint
from maelstrom_tpu.analysis.absint import (DEFAULT_RANGE_MANIFEST,
                                           PRODUCTION_LOG2, RangeReport,
                                           analyze_model,
                                           compare_manifest,
                                           findings_of_report,
                                           load_range_manifest,
                                           proven_horizon_log2,
                                           run_range_lint,
                                           save_range_manifest,
                                           tick_range_stats)
from maelstrom_tpu.analysis.findings import fingerprint_pass
from maelstrom_tpu.models import get_model
from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.models.ir_hazards import (RANGE_FIXTURE_MODELS,
                                             IrCounterOverflow,
                                             IrOobGather, IrScatterRace)

pytestmark = pytest.mark.ranges

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


# --- the planted fixtures trip their rules ---------------------------------


class TestFixturesTrip:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_counter_overflow_trips_abs701(self, layout):
        rep = analyze_model(IrCounterOverflow(), 2, layout)
        fs = findings_of_report(IrCounterOverflow(), rep)
        assert "ABS701" in _rules(fs)
        # 2048/tick crosses int32 max just past the production horizon:
        # proven safe only below 2^20, minimal overflowing T named
        assert rep.max_safe_horizon_log2 == PRODUCTION_LOG2 - 1
        assert rep.min_overflow_t is not None
        assert 0 < (1 << 20) - rep.min_overflow_t <= 64
        msg = next(f for f in fs if f.rule == "ABS701").message
        assert str(rep.min_overflow_t) in msg
        assert "leaf" in msg or "node_state" in msg

    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_scatter_race_trips_abs702(self, layout):
        rep = analyze_model(IrScatterRace(), 2, layout)
        fs = findings_of_report(IrScatterRace(), rep)
        assert "ABS702" in _rules(fs)
        assert rep.race_status == "racing"
        assert any("duplicates" in s["why"] for s in rep.race_sites)
        # the race is the ONLY defect: the counter side still proves
        assert rep.max_safe_horizon_log2 >= PRODUCTION_LOG2

    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_oob_gather_trips_abs703(self, layout):
        rep = analyze_model(IrOobGather(), 2, layout)
        fs = findings_of_report(IrOobGather(), rep)
        assert "ABS703" in _rules(fs)
        site = rep.oob_sites[0]
        # the interval domain resolves 8 + (t % 4) to a range starting
        # at 8 — provably past the whole 8-entry table (the hi may
        # over-approximate under the vmap plumbing's joins)
        assert site["lo"] == 8 and site["hi"] >= 11
        assert site["axis_size"] == 8
        assert "ABS701" not in _rules(fs)   # orthogonal verdicts

    def test_fixture_rules_are_disjoint(self):
        """Each fixture trips exactly its own rule family."""
        for kind, cls in RANGE_FIXTURE_MODELS.items():
            rep = analyze_model(cls(), 2, "lead", label=kind)
            rules = _rules(findings_of_report(cls(), rep))
            want = {"counter-overflow": "ABS701",
                    "scatter-race": "ABS702",
                    "oob-gather": "ABS703"}[kind]
            assert want in rules, (kind, rules)


# --- widening semantics ----------------------------------------------------


class _DoublingCounter(EchoModel):
    """Inline (never-registered) super-linear recurrence: the affine
    widener must refuse to 'prove' it and widen instead (ABS704)."""
    name = "echo-test-doubling"

    def tick(self, row, node_idx, t, key, cfg, params):
        return row * 2 + 1, jnp.zeros((self.tick_out, cfg.lanes),
                                      dtype=jnp.int32)


class TestWidening:
    def test_widening_terminates_on_scan_fixed_point(self):
        """The tick-level fixed point terminates on a real model whose
        tick carries inner scans (the non-fused kafka path has
        recorded fusion-breaker loops) and yields a proof."""
        rep = analyze_model(get_model("kafka", 1, "grid"), 1, "lead")
        assert rep.proven
        assert rep.max_safe_horizon_log2 >= PRODUCTION_LOG2

    def test_super_linear_growth_is_not_proven(self):
        """A doubling counter must come out unproven (ABS704) or as an
        overflow at a tiny horizon — never as a clean proof."""
        m = _DoublingCounter()
        rep = analyze_model(m, 2, "lead")
        fs = findings_of_report(m, rep)
        assert (not rep.proven) or \
            rep.max_safe_horizon_log2 < PRODUCTION_LOG2
        assert {"ABS701", "ABS704"} & _rules(fs)

    def test_real_models_prove_clean_with_headroom(self):
        """The acceptance bar, on the tier-1 budget slice: echo and
        lin-kv (the raft family representative) prove overflow-free at
        the production horizon in both layouts, race-free, with
        nonzero counter headroom; the netsim deliver/enqueue composed-
        gather path carries zero scatter sites."""
        for wl, n in (("echo", 2), ("lin-kv", 5)):
            model = get_model(wl, n, "grid")
            for layout in ("lead", "minor"):
                rep = analyze_model(model, n, layout)
                assert rep.proven, (wl, layout, rep.notes,
                                    rep.unproven_leaves)
                assert rep.max_safe_horizon_log2 >= PRODUCTION_LOG2, \
                    (wl, layout, rep.overflow_sites)
                assert rep.race_status == "race-free"
                assert rep.ovf_margin_bits >= 1
                # the netsim certification: the composed-gather deliver
                # path carries NO scatter, and enqueue's only scatter
                # is the single-row deadline-column stitch — proven
                # race-free with everything else above
                assert rep.scatter_census.get("deliver", 0) == 0
                assert rep.scatter_census.get("enqueue", 0) <= 1
                fs = findings_of_report(model, rep)
                assert not [f for f in fs if f.severity == "error"], \
                    [f.message for f in fs]

    def test_flake_split_is_proven(self):
        """The retired ROADMAP waiver: unique-ids' id-space split is a
        PROVEN bound now — the counter's reachable ceiling fits the
        declared field with margin (the old 20-bit split did NOT; the
        analyzer found the margin thinner than the hand analysis
        claimed, and the split was widened)."""
        m = get_model("unique-ids", 3, "grid")
        rep = analyze_model(m, 3, "lead")
        assert rep.flake is not None
        assert rep.flake["fits"] is True
        assert rep.flake["bits"] == m.flake_counter_bits
        # the proof would have REJECTED the old hand-waved split
        assert rep.flake["proven_counter_max"] > (1 << 20)
        assert rep.flake["proven_counter_max"] < (1 << rep.flake["bits"])


# --- manifest gate ---------------------------------------------------------


def _report(label="echo/n=2/lead", **kw):
    rep = RangeReport(label=label, probe_log2=24, proven=True,
                      max_safe_horizon_log2=21)
    rep.counters = {".stats.sent": 4}
    for k, v in kw.items():
        setattr(rep, k, v)
    return rep


class TestManifestGate:
    def test_roundtrip_and_entry_contract(self, tmp_path):
        path = str(tmp_path / "ranges.json")
        rep = _report()
        save_range_manifest({"echo/n=2/lead": rep.to_entry()}, path)
        man = load_range_manifest(path)
        e = man["entries"]["echo/n=2/lead"]
        assert e["proven"] is True
        assert e["max_safe_horizon_log2"] == 21
        assert e["scatter_race"] == "race-free"
        assert e["netsim_scatters"] == 0
        assert e["counters"] == {".stats.sent": 4}
        import jax
        assert man["jax-version"] == jax.__version__
        fs = compare_manifest({"echo/n=2/lead": rep}, man,
                              {"echo/n=2/lead": ("p.py", "E")})
        assert fs == []

    def test_drift_is_an_error_same_toolchain(self):
        import jax
        rep = _report()
        man = {"jax-version": jax.__version__,
               "entries": {"echo/n=2/lead": {
                   **rep.to_entry(), "max_safe_horizon_log2": 24}}}
        fs = compare_manifest({"echo/n=2/lead": rep}, man,
                              {"echo/n=2/lead": ("p.py", "E")})
        assert [f.rule for f in fs] == ["ABS705"]
        assert fs[0].severity == "error"

    def test_drift_downgrades_under_toolchain_skew(self):
        rep = _report()
        man = {"jax-version": "0.0.0-not-this-one",
               "entries": {"echo/n=2/lead": {
                   **rep.to_entry(), "ovf_margin_bits": 30}}}
        fs = compare_manifest({"echo/n=2/lead": rep}, man,
                              {"echo/n=2/lead": ("p.py", "E")})
        assert [f.rule for f in fs] == ["ABS705"]
        assert fs[0].severity == "warning"
        assert "--update-ranges" in fs[0].message

    def test_missing_and_stale_entries(self):
        import jax
        rep = _report()
        man = {"jax-version": jax.__version__,
               "entries": {"gone/n=9/lead": _report().to_entry()}}
        fs = compare_manifest({"echo/n=2/lead": rep}, man,
                              {"echo/n=2/lead": ("p.py", "E")})
        assert {f.rule for f in fs} == {"ABS706", "ABS707"}

    def test_errored_keys_are_not_stale(self):
        import jax
        man = {"jax-version": jax.__version__,
               "entries": {"broken/n=2/lead": _report().to_entry()}}
        fs = compare_manifest({}, man, {}, errored={"broken/n=2/lead"})
        assert fs == []

    def test_update_records_and_regates_clean(self, tmp_path):
        path = str(tmp_path / "ranges.json")
        fs = run_range_lint(workloads=[("echo", 2)],
                            manifest_path=path, update_manifest=True)
        assert "ABS700" in _rules(fs)
        assert not [f for f in fs if f.severity == "error"]
        fs2 = run_range_lint(workloads=[("echo", 2)],
                             manifest_path=path)
        assert not [f for f in fs2 if f.severity == "error"], \
            [f.message for f in fs2]

    def test_tampered_manifest_trips_abs705(self, tmp_path):
        path = str(tmp_path / "ranges.json")
        run_range_lint(workloads=[("echo", 2)], manifest_path=path,
                       update_manifest=True)
        man = json.load(open(path))
        key = sorted(man["entries"])[0]
        man["entries"][key]["ovf_margin_bits"] += 7
        json.dump(man, open(path, "w"))
        fs = run_range_lint(workloads=[("echo", 2)],
                            manifest_path=path)
        errs = [f for f in fs if f.rule == "ABS705"]
        assert errs and errs[0].severity == "error"

    def test_checked_in_manifest_covers_registry(self):
        """Every registered model x layout has a PROVEN entry at (or
        above) the production horizon in the checked-in manifest —
        the acceptance criterion, read off the committed artifact."""
        man = load_range_manifest(DEFAULT_RANGE_MANIFEST)
        keys = {cost_model.entry_key(wl, n, lay)
                for wl, n in cost_model.cost_specs()
                for lay in ("lead", "minor")}
        missing = keys - set(man["entries"])
        assert not missing, sorted(missing)
        for k in sorted(keys):
            e = man["entries"][k]
            assert e["proven"] is True, k
            assert e["max_safe_horizon_log2"] >= PRODUCTION_LOG2, \
                (k, e["max_safe_horizon_log2"])
            assert e["scatter_race"] == "race-free", k
            # ABS702's netsim certification: the composed-gather
            # deliver path carries no scatter; enqueue's single-row
            # deadline stitch is the only netsim scatter site
            assert e["netsim_scatters"] <= 1, k
            assert e["ovf_margin_bits"] >= 1, k

    def test_synthetic_horizon_trips_abs701(self):
        """The lint_gate canary's synthetic overflow budget: probing
        at 2^31 makes every cumulative fleet counter trip ABS701."""
        fs = run_range_lint(workloads=[("echo", 2)],
                            layouts=("lead",), probe_log2=31)
        assert any(f.rule == "ABS701" and f.severity == "error"
                   for f in fs)


# --- baseline scoping + pass plumbing --------------------------------------


class TestPassPlumbing:
    def test_abs_fingerprints_map_to_ranges_pass(self):
        assert fingerprint_pass("ABS701:x:y") == "ranges"

    def test_trace_cache_is_shared(self):
        """The combined --ir --cost --lanes --ranges gate must trace
        each model x layout ONCE: a restricted multi-pass run through
        the shared cache ends with exactly one trace per entry and the
        ranges pass sees cache hits, not fresh traces."""
        from maelstrom_tpu.analysis.ir_lint import run_ir_lint
        from maelstrom_tpu.analysis.lane_liveness import run_lane_lint
        cache: dict = {}
        calls = []
        orig = cost_model.trace_tick

        def counting(model, sim, params=None, cache=None):
            key = cost_model.entry_key(
                getattr(model, "name", "?"), sim.net.n_nodes,
                sim.layout)
            hit = cache is not None and key in cache
            calls.append((key, hit))
            return orig(model, sim, params, cache)

        cost_model.trace_tick = counting
        try:
            run_ir_lint(workloads=[("echo", 2)], trace_cache=cache,
                        donation=False, include_fixtures=False)
            run_lane_lint(workloads=[("echo", 2)], trace_cache=cache,
                          include_fixtures=False)
            run_range_lint(workloads=[("echo", 2)], trace_cache=cache,
                           include_fixtures=False)
        finally:
            cost_model.trace_tick = orig
        per_key: dict = {}
        for key, hit in calls:
            per_key.setdefault(key, []).append(hit)
        for key, hits in per_key.items():
            assert hits[0] is False and all(hits[1:]), (key, hits)
        # the ranges pass (3rd) saw only cache hits
        assert all(hit for key, hit in calls[-2:]), calls

    def test_bench_stats_surface(self):
        sim = cost_model.audit_sim(get_model("echo", 2, "grid"), 2,
                                   "lead")
        st = cost_model.tick_range_stats(get_model("echo", 2, "grid"),
                                         sim)
        assert st["ovf_margin_bits"] >= 1


# --- make_sim_config cross-check -------------------------------------------


class TestHorizonRefusal:
    def test_refuses_above_proven_bound_by_name(self, tmp_path,
                                                monkeypatch):
        """A model whose manifest proves a bound BELOW the global 2^20
        cap is refused above it, and the refusal names the model and
        the re-prove command."""
        from maelstrom_tpu.tpu.harness import make_sim_config
        path = str(tmp_path / "ranges.json")
        rep = _report(label="echo/n=2/lead")
        rep.max_safe_horizon_log2 = 12
        save_range_manifest({"echo/n=2/lead": rep.to_entry()}, path)
        monkeypatch.setattr(absint, "DEFAULT_RANGE_MANIFEST", path)
        absint._MANIFEST_CACHE.clear()
        model = get_model("echo", 2, "grid")
        with pytest.raises(ValueError) as ei:
            make_sim_config(model, dict(node_count=2,
                                        time_limit=5.0,
                                        ms_per_tick=1.0))
        msg = str(ei.value)
        assert "'echo'" in msg and "2^12" in msg
        assert "--update-ranges" in msg
        # below the proven bound the same config family is accepted
        sim = make_sim_config(model, dict(node_count=2,
                                          time_limit=3.0,
                                          ms_per_tick=1.0))
        assert sim.n_ticks == 3000
        absint._MANIFEST_CACHE.clear()

    def test_unproven_entry_does_not_cap(self, tmp_path, monkeypatch):
        from maelstrom_tpu.tpu.harness import make_sim_config
        path = str(tmp_path / "ranges.json")
        rep = _report(label="echo/n=2/lead")
        rep.max_safe_horizon_log2 = 3
        rep.proven = False
        save_range_manifest({"echo/n=2/lead": rep.to_entry()}, path)
        monkeypatch.setattr(absint, "DEFAULT_RANGE_MANIFEST", path)
        absint._MANIFEST_CACHE.clear()
        sim = make_sim_config(get_model("echo", 2, "grid"),
                              dict(node_count=2, time_limit=5.0,
                                   ms_per_tick=1.0))
        assert sim.n_ticks == 5000     # only the global cap applies
        absint._MANIFEST_CACHE.clear()

    def test_proven_horizon_reads_min_across_layouts(self, tmp_path):
        path = str(tmp_path / "ranges.json")
        a = _report(label="echo/n=2/lead")
        b = _report(label="echo/n=2/minor")
        b.max_safe_horizon_log2 = 20
        save_range_manifest({"echo/n=2/lead": a.to_entry(),
                             "echo/n=2/minor": b.to_entry()}, path)
        assert proven_horizon_log2("echo", path) == 20
        assert proven_horizon_log2("not-a-model", path) is None


# --- the repo-wide gate ----------------------------------------------------


@pytest.mark.slow
class TestRepoGate:
    def test_repo_wide_ranges_gate_is_green(self):
        """The full `--ranges` sweep (every registered model x both
        layouts + the range fixtures) is clean modulo the expected-
        status fixture entries in analysis/baseline.json."""
        report = run_lint(repo_root=REPO, passes=("ranges",),
                          baseline_path=os.path.join(
                              REPO, "maelstrom_tpu", "analysis",
                              "baseline.json"))
        assert report.errors() == [], [f.to_dict()
                                       for f in report.errors()]
        expected = {f.rule for f, e in report.suppressed
                    if e.status == "expected"}
        assert {"ABS701", "ABS702", "ABS703"} <= expected
