"""Mid-run membership change: the joint-consensus reconfiguration lane.

The fourth fault lane (``maelstrom_tpu/faults/`` membership) changes
WHO is in the cluster mid-run, and Raft answers with real joint
consensus (``models/raft_core.py``: C_old,new / C_new log entries,
dual-quorum election and commit, catch-up-gated joiners). Four legs,
each pinned here:

1. **Spec** — the inheriting ``members``/``add``/``remove`` dialect
   resolves to absolute per-phase sets; plans that would EMPTY the
   cluster or name a node past ``n_nodes`` capacity are refused at
   compile time (so by ``make_sim_config``) with the offending phase
   NAMED.
2. **Bit-identity** — an all-member membership lane (plan AND fuzz) is
   bit-identical to a fault-free run in both carry layouts; an ACTIVE
   plan is layout-identical and shard-identical.
3. **Anomaly matrix** — ``RaftSingleQuorumReconfig`` (joint-phase
   quorums consult only the new config) trips committed-prefix under
   the remove-majority-then-partition plan, and
   ``RaftVotesBeforeCatchup`` (blank joiners vote immediately) trips
   under the add-majority-behind-a-partition plan — while CORRECT
   joint-consensus Raft stays checker-valid under the SAME plans
   across seeds and demonstrably COMPLETES the C_old,new -> C_new
   round.
4. **Durability/triage** — checkpoint/resume under an active
   membership plan is bit-identical across the seam (taken mid-joint-
   phase), and the funnel's bit-exact replay reproduces the violating
   instances.

Plus the shrinker's ddmin upgrade (complement-halving rounds beat the
greedy-only pass on a >= 4-phase planted schedule) and the observatory
integration (membership fault epochs per chunk, fuzz coverage
counters, ``watch`` rendering).
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np
import pytest

from maelstrom_tpu.faults import (SpecError, compile_fault_fuzz,
                                  compile_fault_plan,
                                  generate_fault_plan, membership_walk,
                                  validate_fault_plan)
from maelstrom_tpu.faults import fuzz as fz
from maelstrom_tpu.faults.engine import span_summary
from maelstrom_tpu.models import get_model
from maelstrom_tpu.models.raft_core import F_CONFIG
from maelstrom_tpu.tpu.harness import make_sim_config, run_tpu_test
from maelstrom_tpu.tpu.runtime import canonical_carry, run_sim

pytestmark = pytest.mark.membership


# --- shared fixtures -------------------------------------------------------

# remove-majority-then-partition (n=3): commit writes healthy, then
# target members=[0] while links cut {0} | {1,2}, then restore
# membership with the partition still up. The single-quorum mutant's
# joint-phase leader at 0 commits the change (and client writes) alone;
# the restored {1,2} majority — which never heard of it — elects and
# commits a DIFFERENT history at the same indices. Correct Raft stalls
# the change (old-majority veto): unavailable for the window, never
# unsafe.
_SPLIT_0 = [{"dst": d, "src": s, "block": True}
            for d, s in ((0, 1), (1, 0), (0, 2), (2, 0))]
SQ_PLAN = {"phases": [{"until": 220},
                      {"until": 400, "members": [0], "links": _SPLIT_0},
                      {"until": 640, "members": [0, 1, 2],
                       "links": _SPLIT_0}]}
SQ_OPTS = dict(node_count=3, concurrency=4, n_instances=16,
               record_instances=4, time_limit=0.7, rate=300.0,
               latency=5.0, rpc_timeout=0.08, recovery_time=0.05,
               fault_plan=SQ_PLAN, heartbeat=False, seed=7,
               funnel_max=4, inbox_k=2, pool_slots=24)

# add-majority-of-blank-joiners behind a partition (n=5): the 2-of-5
# initial cluster {0,1} commits writes, then {2,3,4} join while
# partitioned from {0,1}; buggy joiners vote with empty logs and elect
# one of themselves over the committed history. When the partition
# heals, correct Raft catches the learners up and completes the full
# joint round.
_SPLIT_01 = ([{"dst": d, "src": s, "block": True}
              for d in (0, 1) for s in (2, 3, 4)]
             + [{"dst": d, "src": s, "block": True}
                for d in (2, 3, 4) for s in (0, 1)])
VBC_PLAN = {"phases": [{"until": 200, "members": [0, 1]},
                       {"until": 480, "add": [2, 3, 4],
                        "links": _SPLIT_01},
                       {"until": 700}]}
VBC_OPTS = dict(node_count=5, concurrency=4, n_instances=12,
                record_instances=4, time_limit=0.75, rate=300.0,
                latency=5.0, rpc_timeout=0.08, recovery_time=0.05,
                fault_plan=VBC_PLAN, heartbeat=False, seed=7,
                funnel_max=4, inbox_k=2, pool_slots=24)

_IDENTITY_OPTS = dict(node_count=3, concurrency=2, n_instances=4,
                      record_instances=2, time_limit=0.3, rate=200.0,
                      latency=5.0, p_loss=0.05, nemesis=["partition"],
                      nemesis_interval=0.05, seed=0, inbox_k=2,
                      pool_slots=24)

# membership configured but value-neutral: every phase keeps everyone
# in — the full lane machinery (slab, park select, target threading,
# client retarget, dual-quorum masks) traces, with values identical to
# the membership-free path
_NEUTRAL_PLAN = {"phases": [{"until": 100_000,
                             "members": [0, 1, 2]}]}

# fuzz distribution with a rate-0 membership lane: present, all draws
# healthy
_HEALTHY_DIST = {"windows": [1, 2], "gap": [20, 60],
                 "duration": [20, 50],
                 "membership": {"rate": 0.0, "victims": [1, 2]}}
_ACTIVE_DIST = {"windows": [2, 2], "gap": [60, 160],
                "duration": [40, 90],
                "membership": {"rate": 0.8, "victims": [1, 2]}}


# --- spec / compile units --------------------------------------------------


class TestSpec:
    def test_walk_resolves_inheritance(self):
        phases = [{"until": 50, "members": [0, 1]},
                  {"until": 100},                    # inherits {0,1}
                  {"until": 150, "add": [2]},
                  {"until": 200, "remove": [1]}]
        assert membership_walk(phases, 3) == [
            (0, 1), (0, 1), (0, 1, 2), (0, 2)]

    def test_walk_none_when_lane_absent(self):
        assert membership_walk([{"until": 10, "crash": [0]}], 3) is None

    def test_compile_carries_members_and_universe(self):
        fxx = compile_fault_plan(SQ_PLAN, 3, stop_tick=640)
        assert fxx.has_members and fxx.active
        assert fxx.members == ((0, 1, 2), (0,), (0, 1, 2))
        assert fxx.n_nodes == 3

    @pytest.mark.parametrize("plan,msg", [
        # emptying the cluster names the phase
        ({"phases": [{"until": 10, "members": []}]},
         "phase 0 membership would EMPTY"),
        ({"phases": [{"until": 10, "members": [0, 1]},
                     {"until": 20, "remove": [0, 1]}]},
         "phase 1 membership would EMPTY"),
        # capacity overflow names the phase
        ({"phases": [{"until": 10, "add": [7]}]},
         "phase 0 added node 7 out of range"),
        ({"phases": [{"until": 10}, {"until": 20, "members": [0, 5]}]},
         "phase 1 member 5 out of range"),
        # absolute + relative in one phase is ambiguous
        ({"phases": [{"until": 10, "members": [0], "add": [1]}]},
         "mixes 'members' with 'add'/'remove'"),
    ])
    def test_validation_rejects_naming_the_phase(self, plan, msg):
        with pytest.raises(SpecError, match=msg):
            validate_fault_plan(plan, 3)

    def test_make_sim_config_refuses_bad_membership_plans(self):
        """The satellite contract: make_sim_config is where the CLI's
        plan lands, and the refusal must name the offending phase."""
        model = get_model("lin-kv", 3)
        bad_empty = {"phases": [{"until": 10, "members": [0]},
                                {"until": 20, "remove": [0]}]}
        with pytest.raises(SpecError, match="phase 1 membership would "
                                            "EMPTY the cluster"):
            make_sim_config(model, dict(node_count=3,
                                        fault_plan=bad_empty))
        bad_cap = {"phases": [{"until": 10, "add": [3]}]}
        with pytest.raises(SpecError,
                           match="phase 0 added node 3 out of range"):
            make_sim_config(model, dict(node_count=3,
                                        fault_plan=bad_cap))

    def test_fuzz_victims_capped_below_cluster_size(self):
        with pytest.raises(SpecError, match="membership victims"):
            compile_fault_fuzz(
                {"membership": {"rate": 1.0, "victims": [1, 3]}}, 3,
                stop_tick=100)
        fxx = compile_fault_fuzz(
            {"membership": {"rate": 1.0, "victims": [1, 2]}}, 3,
            stop_tick=100)
        assert fxx.has_members and fxx.fuzz.has_membership

    def test_generated_membership_kind(self):
        """--nemesis membership: rotating single-node removal with an
        explicit all-member restore each heal phase (membership
        INHERITS, so heals must say so)."""
        plan = generate_fault_plan(["membership"], 3, 600, 50, 500)
        fxx = compile_fault_plan(plan, 3, stop_tick=500)
        assert fxx.has_members
        for p, members in enumerate(fxx.members):
            assert len(members) >= 2   # always a minority removed
            if p % 2 == 1:
                assert len(members) == 2
            else:
                assert members == (0, 1, 2)

    def test_span_summary_membership_epoch(self):
        fxx = compile_fault_plan(SQ_PLAN, 3, stop_tick=640)
        mid = span_summary(fxx, 250, 100)      # inside the removal
        assert mid["membership"]["removed"] == [1, 2]
        assert mid["membership"]["members"] == [0]
        rejoin = span_summary(fxx, 380, 100)   # spans the restore edge
        assert rejoin["membership"]["joined"] == [1, 2]
        healthy = span_summary(fxx, 660, 40)   # final heal
        assert healthy.get("healthy") is True

    def test_watch_renders_membership_epoch(self):
        from maelstrom_tpu.telemetry.stream import render_chunk_line
        line = render_chunk_line(
            {"chunk": 3, "t0": 300, "ticks": 100,
             "fault": {"phase": 2, "phases": 3,
                       "membership": {"members": [0],
                                      "joined": [0],
                                      "removed": [1, 2]}}})
        assert "membership +1/-2" in line
        fuzz_line = render_chunk_line(
            {"chunk": 1, "t0": 0, "ticks": 50,
             "fault-fuzz": {"schedules-active": 3, "membership": 2}})
        assert "membership 2" in fuzz_line


# --- bit-identity ----------------------------------------------------------


class TestBitIdentity:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_all_member_plan_bit_identical(self, layout):
        """A membership lane that keeps everyone in reproduces the
        fault-free trajectory bit-for-bit (the machinery — slab, park
        select, dual-quorum masks, client retarget — is all in the
        graph)."""
        model = get_model("lin-kv", 3)
        sim = make_sim_config(model, {**_IDENTITY_OPTS,
                                      "layout": layout})
        fxx = compile_fault_plan(_NEUTRAL_PLAN, 3,
                                 stop_tick=sim.nemesis.stop_tick)
        params = model.make_params(3)
        base_c, base_y = run_sim(model, sim, 0, params)
        neut_c, neut_y = run_sim(model, sim._replace(faults=fxx), 0,
                                 params)
        assert neut_c.snapshots is not None   # the slab really exists
        for a, b in zip(
                jax.tree.leaves((base_c.pool, base_c.node_state,
                                 base_c.client_state, base_c.stats,
                                 base_c.violations)),
                jax.tree.leaves((neut_c.pool, neut_c.node_state,
                                 neut_c.client_state, neut_c.stats,
                                 neut_c.violations))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(base_y.events),
                                      np.asarray(neut_y.events))

    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_all_healthy_membership_fuzz_bit_identical(self, layout):
        """A rate-0 membership DISTRIBUTION (schedule lanes drawn and
        selected per instance every tick) is bit-identical to
        fault-free."""
        model = get_model("lin-kv", 3)
        opts = {**_IDENTITY_OPTS, "nemesis": [], "p_loss": 0.0,
                "layout": layout}
        sim = make_sim_config(model, dict(opts))
        simf = make_sim_config(model, {**opts,
                                       "fault_fuzz": _HEALTHY_DIST})
        params = model.make_params(3)
        bc, by = run_sim(model, sim, 0, params)
        nc, ny = run_sim(model, simf, 0, params)
        for a, b in zip(
                jax.tree.leaves((bc.pool, bc.node_state,
                                 bc.client_state, bc.stats,
                                 bc.violations)),
                jax.tree.leaves((nc.pool, nc.node_state,
                                 nc.client_state, nc.stats,
                                 nc.violations))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(by.events),
                                      np.asarray(ny.events))

    def test_active_plan_layout_independent(self):
        """The remove-majority plan produces bit-identical trajectories
        in both carry layouts (park wipes, joins, retargeting and the
        dual-quorum math all ride the shared per-instance code)."""
        out = {}
        for layout in ("lead", "minor"):
            model = get_model("lin-kv", 3)
            sim = make_sim_config(model, {**SQ_OPTS, "layout": layout})
            c, y = run_sim(model, sim, 7, model.make_params(3))
            canon = canonical_carry(c, sim)
            out[layout] = (jax.tree.leaves(
                (canon.pool, canon.node_state, canon.client_state,
                 canon.stats, canon.violations, canon.snapshots)),
                np.asarray(y.events))
        for a, b in zip(out["lead"][0], out["minor"][0]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(out["lead"][1], out["minor"][1])

    def test_all_member_plan_sharded_bit_identical(self):
        """Across the shard_map wire: an all-member membership fleet's
        (stats, violations, events) equal the fault-free sharded run
        bit-for-bit."""
        from maelstrom_tpu.parallel.mesh import (make_mesh,
                                                 run_sim_sharded)
        model = get_model("lin-kv", 3)
        opts = dict(node_count=3, concurrency=2, n_instances=4,
                    record_instances=2, time_limit=0.2, rate=200.0,
                    latency=5.0, seed=3, inbox_k=2, pool_slots=16)
        params = model.make_params(3)
        mesh = make_mesh(2)
        base = make_sim_config(model, dict(opts))
        neut = base._replace(faults=compile_fault_plan(
            _NEUTRAL_PLAN, 3, stop_tick=base.nemesis.stop_tick))
        s0, v0, e0 = run_sim_sharded(model, base, 3, params, mesh=mesh)
        s1, v1, e1 = run_sim_sharded(model, neut, 3, params, mesh=mesh)
        assert jax.tree.map(int, s0) == jax.tree.map(int, s1)
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(e0), np.asarray(e1))

    @pytest.mark.slow
    def test_active_plan_sharded_chunked_matches_oracle(self):
        """An ACTIVE membership plan through the chunked sharded driver
        equals the unsharded oracle — the lane survives the shard_map
        wire and the chunked executor together."""
        from maelstrom_tpu.parallel.mesh import (make_mesh,
                                                 run_sim_sharded_chunked,
                                                 run_sim_unsharded)
        model = get_model("lin-kv", 3)
        opts = dict(SQ_OPTS, n_instances=4, record_instances=2,
                    funnel=False)
        sim = make_sim_config(model, opts)
        params = model.make_params(3)
        mesh = make_mesh(2)
        s_sh, v_sh, e_sh = run_sim_sharded_chunked(
            model, sim, 7, params, mesh=mesh, chunk=100)
        s_un, v_un, e_un = run_sim_unsharded(model, sim, 7, 2, params)
        assert jax.tree.map(int, s_sh) == jax.tree.map(int, s_un)
        np.testing.assert_array_equal(np.asarray(v_sh), v_un)
        np.testing.assert_array_equal(np.asarray(e_sh), e_un)


# --- the anomaly matrix ----------------------------------------------------


class TestSingleQuorumLane:
    def test_single_quorum_reconfig_caught_correct_model_survives(self):
        """The membership lane's planted bug #1 end-to-end: the
        joint-phase single-quorum commit diverges the two sides of the
        partition, the on-device committed-prefix invariant trips, and
        the funnel's bit-exact replay confirms every flagged instance;
        correct joint-consensus Raft under the SAME plan stalls the
        change and stays fully valid."""
        bug = run_tpu_test(
            get_model("lin-kv-bug-single-quorum-reconfig", 3),
            dict(SQ_OPTS))
        assert bug["valid?"] is False
        assert bug["invariants"]["violating-instances"] >= 4, \
            bug["invariants"]
        funnel = bug["funnel"]
        assert funnel["replayed-violating"] == len(funnel["ids"]) > 0

        ok = run_tpu_test(get_model("lin-kv", 3), dict(SQ_OPTS))
        assert ok["valid?"] is True
        assert ok["invariants"]["violating-instances"] == 0


class TestVotesBeforeCatchupLane:
    def test_votes_before_catchup_caught_correct_model_completes(self):
        """The membership lane's planted bug #2: blank joiners elect an
        empty-log leader over the committed history — every instance
        trips. The CORRECT model under the SAME plan keeps the joiners
        mute until caught up and then COMPLETES the reconfiguration:
        both config entries (C_old,new with old != new, then C_new with
        old == new == all) land in every instance's log."""
        bug = run_tpu_test(
            get_model("lin-kv-bug-votes-before-catchup", 5),
            dict(VBC_OPTS))
        assert bug["valid?"] is False
        assert bug["invariants"]["violating-instances"] >= 8, \
            bug["invariants"]

        model = get_model("lin-kv", 5)
        ok = run_tpu_test(model, dict(VBC_OPTS))
        assert ok["valid?"] is True
        assert ok["invariants"]["violating-instances"] == 0

        # the joint-consensus happy path: C_old,new ({0,1} -> all5)
        # then C_new, on every instance's node-0 log
        sim = make_sim_config(model, dict(VBC_OPTS))
        carry, _ = run_sim(model, sim, 7, model.make_params(5))
        lb = np.asarray(canonical_carry(carry, sim).node_state.log_body)
        ll = np.asarray(canonical_carry(carry, sim).node_state.log_len)
        all5 = (1 << 5) - 1
        for i in range(lb.shape[0]):
            cfgs = [(int(lb[i, 0, k, 1]), int(lb[i, 0, k, 2]))
                    for k in range(lb.shape[2])
                    if k < ll[i, 0] and lb[i, 0, k, 0] == F_CONFIG]
            assert (0b11, all5) in cfgs, (i, cfgs)     # C_old,new
            assert (all5, all5) in cfgs, (i, cfgs)     # C_new
        # joiners came out of learner mode (a single node may still be
        # mid-catch-up at the horizon — e.g. re-parked by a last
        # election race — but every instance ends with at least a full
        # quorum of caught-up voters)
        caught = np.asarray(canonical_carry(carry,
                                            sim).node_state.caught_up)
        assert (caught.sum(axis=1) >= 4).all(), caught


class TestWideClusterMask:
    def test_full_member_mask_no_overflow(self):
        """Membership-free runs wider than the int32 value bits must
        still trace: the all-members mask collapses to -1 (every bit
        set — 'member' for every index under the arithmetic-shift
        tests) instead of raising OverflowError at ``(1 << n) - 1``.
        The membership LANE stays capped at MAX_MEMBER_NODES=30 by the
        spec walk."""
        import jax.numpy as jnp
        from maelstrom_tpu.models.raft_core import full_member_mask
        assert full_member_mask(3) == 0b111
        assert full_member_mask(31) == (1 << 31) - 1
        assert full_member_mask(32) == -1
        assert full_member_mask(64) == -1
        model = get_model("lin-kv", 33)
        row = model.init_row(33, jnp.int32(0), jax.random.PRNGKey(0),
                             model.make_params(33))
        assert int(row.cfg_boot) == -1


class TestLearnerGateDurability:
    def test_crash_restart_preserves_caught_up(self):
        """``caught_up`` is DURABLE, so the crash and membership lanes
        COMPOSE: a joining learner that crashes before its first
        fitting AppendEntries accept must restart with caught_up=0.
        init_row's fresh row says 1, and restoring every durable lane
        BUT the gate would let a blank joiner vote after any crash
        window — the VotesBeforeCatchup anomaly in the CORRECT
        model."""
        import jax.numpy as jnp
        model = get_model("lin-kv", 3)
        params = model.make_params(3)
        key = jax.random.PRNGKey(0)
        fresh = model.init_row(3, jnp.int32(2), key, params)
        assert "caught_up" in model.DURABLE_LANES
        # blank joiner: empty durable log -> non-voting learner
        joined = model.join_row(3, jnp.int32(2), key, params,
                                model.snapshot_row(fresh),
                                jnp.int32(100), jnp.int32(0b111))
        assert int(joined.caught_up) == 0
        # crash it before catch-up: the gate survives the reboot
        rebooted = model.restart_row(3, jnp.int32(2), key, params,
                                     model.snapshot_row(joined),
                                     jnp.int32(200))
        assert int(rebooted.caught_up) == 0
        # and a caught-up voter stays a voter across a crash
        voter = joined._replace(caught_up=jnp.int32(1))
        rebooted = model.restart_row(3, jnp.int32(2), key, params,
                                     model.snapshot_row(voter),
                                     jnp.int32(300))
        assert int(rebooted.caught_up) == 1


@pytest.mark.slow
class TestAnomalyMatrixSweep:
    """The matrix across extra seeds (>= 3 total with the pinned
    seed-7 representatives above)."""

    @pytest.mark.parametrize("seed", [11, 13])
    def test_single_quorum_lane(self, seed):
        bug = run_tpu_test(
            get_model("lin-kv-bug-single-quorum-reconfig", 3),
            dict(SQ_OPTS, seed=seed))
        ok = run_tpu_test(get_model("lin-kv", 3),
                          dict(SQ_OPTS, seed=seed))
        assert bug["valid?"] is False and ok["valid?"] is True

    @pytest.mark.parametrize("seed", [11, 13])
    def test_votes_before_catchup_lane(self, seed):
        bug = run_tpu_test(
            get_model("lin-kv-bug-votes-before-catchup", 5),
            dict(VBC_OPTS, seed=seed))
        ok = run_tpu_test(get_model("lin-kv", 5),
                          dict(VBC_OPTS, seed=seed))
        assert bug["valid?"] is False and ok["valid?"] is True

    def test_generated_membership_churn_is_survivable(self):
        """The CLI's generated membership plan (one rotating node
        removed at a time) must be survivable AND completable by
        correct Raft — every window drives a full joint round."""
        opts = dict(node_count=3, concurrency=4, n_instances=8,
                    record_instances=4, time_limit=0.8, rate=200.0,
                    latency=5.0, rpc_timeout=0.08, recovery_time=0.15,
                    nemesis=["membership"], nemesis_interval=0.1,
                    heartbeat=False, seed=7, inbox_k=2, pool_slots=24)
        res = run_tpu_test(get_model("lin-kv", 3), opts)
        assert res["valid?"] is True
        assert res["invariants"]["violating-instances"] == 0


# --- membership fuzz lane --------------------------------------------------


class TestMembershipFuzz:
    def test_distinct_schedules_and_coverage(self):
        fxx = compile_fault_fuzz(_ACTIVE_DIST, 3, stop_tick=600)
        win = fz.fleet_windows(fxx, 3, 7, np.arange(16, dtype=np.int32))
        cov = fz.fleet_coverage(win)
        assert cov["membership-windows"] >= 4
        assert cov["distinct-schedules"] >= 4
        span = fz.span_counters(win, 0, 600)
        assert span["membership"] >= 4

    def test_reconstructed_plan_rejoins_on_time(self):
        """The seed -> schedule -> plan path: membership windows lower
        to remove/add event phases whose compiled planes are
        value-identical to the drawn schedule at every tick."""
        import jax.numpy as jnp
        from maelstrom_tpu.faults.engine import tick_planes
        fxx = compile_fault_fuzz(_ACTIVE_DIST, 3, stop_tick=600)
        cfg = make_sim_config(get_model("lin-kv", 3),
                              dict(node_count=3, time_limit=0.6,
                                   recovery_time=0.0)).net
        hits = 0
        for inst in range(4):
            sched = fz.reconstruct_schedule(fxx, 3, 7, inst)
            plan = fz.schedule_to_plan(sched, fxx)
            pfx = (compile_fault_plan(plan, 3, stop_tick=600)
                   if plan else None)
            sched_j = jax.tree.map(jnp.asarray, sched)
            for t in range(0, 600, 5):
                fp = fz.schedule_planes(sched_j, fxx, cfg,
                                        jnp.int32(t))
                fm = np.asarray(fp.member)
                if pfx is None or not pfx.has_members:
                    pm = np.ones(3, bool)
                else:
                    pp = tick_planes(pfx, cfg, jnp.int32(t))
                    pm = np.asarray(pp.member)
                np.testing.assert_array_equal(fm, pm, err_msg=f"t={t}")
                hits += int((~fm).any())
        assert hits > 0    # the sweep actually removed somebody

    def test_membership_fuzz_runs_and_replays(self):
        """An active membership distribution over correct Raft: runs
        valid (remove-then-rejoin churn is survivable), and the drawn
        schedules ride the carry through both layouts identically."""
        opts = dict(node_count=3, concurrency=2, n_instances=8,
                    record_instances=2, time_limit=0.5, rate=200.0,
                    latency=5.0, rpc_timeout=0.08, recovery_time=0.1,
                    seed=7, inbox_k=2, pool_slots=24, funnel=False,
                    heartbeat=False, fault_fuzz=_ACTIVE_DIST)
        out = {}
        for layout in ("lead", "minor"):
            model = get_model("lin-kv", 3)
            sim = make_sim_config(model, {**opts, "layout": layout})
            c, y = run_sim(model, sim, 7, model.make_params(3))
            canon = canonical_carry(c, sim)
            out[layout] = (jax.tree.leaves(
                (canon.pool, canon.node_state, canon.client_state,
                 canon.stats, canon.violations, canon.fault_sched)),
                np.asarray(y.events))
            assert int(np.asarray(c.violations).sum()) == 0
        for a, b in zip(out["lead"][0], out["minor"][0]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(out["lead"][1], out["minor"][1])


# --- checkpoint/resume + triage under an active membership plan ------------


class TestDurability:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_checkpoint_resume_mid_joint_phase_bit_identical(
            self, tmp_path, layout):
        """Kill at a checkpoint taken INSIDE the membership phase (the
        joint round is in flight: C_old,new appended, parked nodes
        held), resume, and the result equals the uninterrupted run."""
        from maelstrom_tpu.campaign.checkpoint import (load_checkpoint,
                                                       restore_carry,
                                                       save_checkpoint)
        from maelstrom_tpu.tpu.pipeline import (ResumeState,
                                                _init_pipelined,
                                                run_sim_pipelined)
        model = get_model("lin-kv", 3)
        opts = dict(SQ_OPTS, n_instances=4, record_instances=2,
                    funnel=False, layout=layout)
        sim = make_sim_config(model, opts)
        assert sim.faults.has_members
        params = model.make_params(3)
        base = run_sim_pipelined(model, sim, 7, params, chunk=100)

        d = str(tmp_path) + f"-{layout}"
        os.makedirs(d, exist_ok=True)

        class Killed(Exception):
            pass

        def cb(state, ticks, host):
            save_checkpoint(d, kind="pipelined", state=state,
                            ticks=ticks, chunks=host["chunks"],
                            compact=tuple(host["compact"]),
                            journal=tuple(host["journal"]))
            raise Killed

        with pytest.raises(Killed):
            # checkpoint_every=3 -> the seam lands at tick 300: inside
            # the members=[0] phase (220..400), mid-joint-round
            run_sim_pipelined(model, sim, 7, params, chunk=100,
                              checkpoint_cb=cb, checkpoint_every=3)
        ck = load_checkpoint(d)
        assert 220 < ck["ticks"] < 400     # genuinely mid-phase
        template = _init_pipelined(model, sim, 7, params,
                                   np.arange(4, dtype=np.int32))
        resume = ResumeState(
            carry=restore_carry(template, ck["carry"]),
            ticks=ck["ticks"], chunks=ck["chunks"],
            compact=tuple(ck["compact"]),
            journal=tuple(ck["journal"]))
        res = run_sim_pipelined(model, sim, 7, params, chunk=100,
                                resume=resume)
        np.testing.assert_array_equal(base.events, res.events)
        for a, b in zip(jax.tree.leaves(base.carry),
                        jax.tree.leaves(res.carry)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))

    def test_membership_epochs_ride_the_heartbeat(self, tmp_path):
        """Chunked membership runs stream their membership epoch per
        chunk and the run-start header lists the lane — model-agnostic
        (echo nodes park and cold-boot through the default hooks)."""
        plan = {"phases": [{"until": 100},
                           {"until": 160, "remove": [1]},
                           {"until": 220, "add": [1]}]}
        opts = dict(node_count=2, concurrency=2, n_instances=8,
                    record_instances=2, time_limit=0.3, rate=100.0,
                    latency=5.0, recovery_time=0.05, seed=3,
                    fault_plan=plan, funnel=False,
                    store_root=str(tmp_path), pipeline="on",
                    chunk_ticks=50)
        run_tpu_test(get_model("echo", 2), opts)
        from maelstrom_tpu.telemetry.stream import read_heartbeat
        run_dir = os.path.realpath(
            os.path.join(str(tmp_path), "echo-tpu", "latest"))
        hb = read_heartbeat(run_dir)
        assert "membership" in hb["header"]["faults"]["lanes"]
        epochs = [rec["fault"].get("membership")
                  for rec in hb["chunks"] if rec.get("fault")]
        removed = [m for m in epochs if m and m.get("removed") == [1]]
        assert removed, epochs
        joined = [m for m in epochs if m and m.get("joined") == [1]]
        assert joined, epochs

    def test_triage_repro_opts_carry_the_plan(self):
        """fault_plan is a repro opt: heartbeat_meta's opts block (what
        triage/campaign-resume rebuild from) round-trips the membership
        plan verbatim."""
        from maelstrom_tpu.tpu.harness import heartbeat_meta
        model = get_model("lin-kv", 3)
        sim = make_sim_config(model, dict(SQ_OPTS))
        meta = heartbeat_meta(model, sim, dict(SQ_OPTS))
        assert meta["opts"]["fault_plan"] == SQ_PLAN
        assert "membership" in meta["faults"]["lanes"]


# --- ddmin shrinker upgrade ------------------------------------------------


def _planted_replay(needed_phase_crash):
    """Synthetic replay predicate: the plan still 'fails' iff SOME
    phase still crashes the planted victim set."""
    def replay(plan):
        if not plan:
            return False
        return any(sorted(ph.get("crash") or []) ==
                   sorted(needed_phase_crash)
                   for ph in plan.get("phases", ()))
    return replay


def _wide_plan(n_phases=8, victim=2):
    """n fault phases, only one of which (index 5) carries the
    trigger."""
    phases = []
    t = 0
    for i in range(n_phases):
        t += 50
        phases.append({"until": t,
                       "crash": [victim] if i == 5 else [0]})
    return {"phases": phases}


class TestDdminShrink:
    def test_ddmin_beats_greedy_on_multi_phase_schedule(self):
        """The satellite's convergence bar: on a >= 4-phase planted
        schedule (8 phases, one trigger) the complement-halving rounds
        reach the same-or-smaller minimum in strictly fewer verified
        replays than the greedy-only pass."""
        from maelstrom_tpu.faults.fuzz import plan_weight
        from maelstrom_tpu.faults.shrink import shrink_plan
        plan = _wide_plan()
        res_dd = shrink_plan(plan, _planted_replay([2]),
                             max_attempts=64, ddmin=True)
        res_gr = shrink_plan(plan, _planted_replay([2]),
                             max_attempts=64, ddmin=False)
        assert plan_weight(res_dd["plan"]) <= plan_weight(res_gr["plan"])
        assert plan_weight(res_dd["plan"]) == (1, 1)
        assert res_dd["attempts"] < res_gr["attempts"], \
            (res_dd["attempts"], res_gr["attempts"])
        assert any(k.startswith("ddmin-drop-phases-")
                   for k in res_dd["kept"])

    def test_every_kept_reduction_was_verified(self):
        """The ddmin pass replays every candidate it keeps: the replay
        log length equals the attempt count, and each kept reduction
        corresponds to a replay that returned True."""
        from maelstrom_tpu.faults.shrink import shrink_plan
        calls = []
        inner = _planted_replay([2])

        def logging_replay(plan):
            ok = inner(plan)
            calls.append(ok)
            return ok

        res = shrink_plan(_wide_plan(), logging_replay,
                          max_attempts=64)
        assert len(calls) == res["attempts"]
        assert sum(calls) == len(res["kept"])

    def test_membership_candidates_drop_removals_not_heals(self):
        """The greedy pass targets membership REMOVALS (and absolute
        members keys) but never rejoin 'add' events — dropping a heal
        would enlarge the fault."""
        from maelstrom_tpu.faults.shrink import _candidates
        plan = {"phases": [{"until": 50, "remove": [1, 2]},
                           {"until": 100, "add": [1, 2]}]}
        labels = [label for label, _ in _candidates(plan)]
        assert "phase-0-drop-remove-1" in labels
        assert "phase-0-drop-remove-2" in labels
        assert not any("add" in lb for lb in labels)
        # drop-phase keeps the heal
        for label, cand in _candidates(plan):
            if label == "drop-phase-0":
                assert "remove" not in cand["phases"][0]
        # and the heal phase itself is never a drop target
        assert "drop-phase-1" not in labels

    def test_members_restore_is_heal_not_fault(self):
        """A ``members`` key that RESTORES (or merely restates) the
        previous phase's set is HEAL content, like rejoin 'add'
        events: the shrinker never offers it as a drop candidate
        (membership INHERITS, so dropping a restore would EXTEND the
        outage for the rest of the run), drop-phase and the ddmin
        complement drops keep it, and plan_weight does not count
        it."""
        from maelstrom_tpu.faults.fuzz import plan_weight
        from maelstrom_tpu.faults.shrink import (_candidates,
                                                 _drop_phase_set)
        from maelstrom_tpu.faults.spec import membership_heal_phases
        plan = {"phases": [{"until": 50, "members": [0]},
                           {"until": 100, "members": [0, 1, 2],
                            "crash": [1]}]}
        assert membership_heal_phases(plan, 3) == {1}
        labels = [label for label, _ in _candidates(plan, n_nodes=3)]
        assert "phase-0-drop-members" in labels      # the removal
        assert "phase-1-drop-members" not in labels  # the restore
        for label, cand in _candidates(plan, n_nodes=3):
            if label == "drop-phase-1":
                assert cand["phases"][1]["members"] == [0, 1, 2]
        stripped = _drop_phase_set(plan, [0, 1],
                                   membership_heal_phases(plan, 3))
        assert "members" not in stripped["phases"][0]
        assert stripped["phases"][1]["members"] == [0, 1, 2]
        # the minimality metric: the restore weighs nothing
        assert plan_weight(plan, 3) == (2, 2)
        assert plan_weight(SQ_PLAN, 3) == (2, 9)   # not (2, 10)

    @pytest.mark.slow
    def test_shrinks_the_deterministic_single_quorum_plan(self):
        """shrink generalized to deterministic ``--fault-plan`` runs
        (the membership smoke's path — tools/lint_gate.sh runs the
        same loop end-to-end through the CLI): the hand-built
        remove-majority plan is over-specified — 8 link-edge entries
        where fewer suffice — and shrink_instance minimizes it to a
        verified still-failing plan that keeps the membership
        change. Slow: each candidate replay recompiles the tick."""
        from maelstrom_tpu.faults.shrink import shrink_instance
        model = get_model("lin-kv-bug-single-quorum-reconfig", 3)
        opts = dict(SQ_OPTS, funnel=False, n_instances=16)
        sim = make_sim_config(model, dict(opts))
        carry, _ = run_sim(model, sim, 7, model.make_params(3))
        viol = np.nonzero(np.asarray(carry.violations))[0]
        assert viol.size > 0
        rec = shrink_instance(model, dict(opts), int(viol[0]),
                              max_attempts=8)
        assert rec["verified"]
        assert rec["reduced"], rec
        assert (rec["shrunk-phases"], rec["shrunk-victims"]) \
            < (rec["original-phases"], rec["original-victims"])
        # the minimal plan still reconfigures (the trigger is the
        # membership change, not the decoration around it)
        assert any(ph.get("members") is not None
                   or ph.get("remove") or ph.get("add")
                   for ph in rec["shrunk-plan"]["phases"])
        assert json.dumps(rec["shrunk-plan"])   # JSON-serializable
        validate_fault_plan(rec["shrunk-plan"], 3)
