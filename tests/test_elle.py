"""Elle-style checker unit tests on literal histories (the reference's
checker-test pattern, SURVEY §4)."""

from maelstrom_tpu.checkers.elle import check_list_append, check_rw_register


def H(*recs):
    out = []
    for i, r in enumerate(recs):
        out.append({"process": r[0], "type": r[1], "f": "txn",
                    "value": r[2], "index": i, "time": i})
    return out


def test_list_append_clean_serial():
    h = H((0, "invoke", [["append", 1, 1]]),
          (0, "ok",     [["append", 1, 1]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, [1]]]),
          (0, "invoke", [["append", 1, 2]]),
          (0, "ok",     [["append", 1, 2]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, [1, 2]]]))
    r = check_list_append(h)
    assert r["valid?"] is True, r


def test_list_append_lost_append():
    h = H((0, "invoke", [["append", 1, 1]]),
          (0, "ok",     [["append", 1, 1]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, []]]))
    r = check_list_append(h)
    assert r["valid?"] is False
    assert "lost-append" in r["anomalies"]


def test_list_append_g1a_aborted_read():
    h = H((0, "invoke", [["append", 1, 9]]),
          (0, "fail",   [["append", 1, 9]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, [9]]]))
    r = check_list_append(h)
    assert r["valid?"] is False
    assert "G1a" in r["anomalies"]


def test_list_append_incompatible_order():
    h = H((0, "invoke", [["r", 1, None]]),
          (0, "ok",     [["r", 1, [1, 2]]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, [2, 1]]]))
    r = check_list_append(h)
    assert r["valid?"] is False
    assert "incompatible-order" in r["anomalies"]


def test_list_append_wr_cycle_g1c():
    # T1 reads T2's append; T2 reads T1's append: wr cycle
    h = [
        {"process": 0, "type": "invoke", "f": "txn",
         "value": [["append", 1, 1], ["r", 2, None]], "index": 0,
         "time": 0},
        {"process": 1, "type": "invoke", "f": "txn",
         "value": [["append", 2, 1], ["r", 1, None]], "index": 1,
         "time": 1},
        {"process": 0, "type": "ok", "f": "txn",
         "value": [["append", 1, 1], ["r", 2, [1]]], "index": 2,
         "time": 2},
        {"process": 1, "type": "ok", "f": "txn",
         "value": [["append", 2, 1], ["r", 1, [1]]], "index": 3,
         "time": 3},
    ]
    r = check_list_append(h, "serializable")
    assert r["valid?"] is False
    assert any(k in r["anomalies"] for k in ("G1c", "G2-item")), r


def test_list_append_realtime_stale_read():
    # append completes, then a later txn reads the old state: under
    # strict serializability that's an rw/realtime cycle; serializable
    # alone accepts it
    h = H((0, "invoke", [["append", 1, 1]]),
          (0, "ok",     [["append", 1, 1]]),
          (0, "invoke", [["append", 1, 2]]),
          (0, "ok",     [["append", 1, 2]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, [1]]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, [1, 2]]]))
    assert check_list_append(h, "strict-serializable")["valid?"] is False
    assert check_list_append(h, "serializable")["valid?"] is True


def test_rw_register_clean():
    h = H((0, "invoke", [["w", 1, 1]]),
          (0, "ok",     [["w", 1, 1]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, 1]]))
    assert check_rw_register(h)["valid?"] is True


def test_rw_register_g1a():
    h = H((0, "invoke", [["w", 1, 5]]),
          (0, "fail",   [["w", 1, 5]]),
          (1, "invoke", [["r", 1, None]]),
          (1, "ok",     [["r", 1, 5]]))
    r = check_rw_register(h)
    assert r["valid?"] is False
    assert "G1a" in r["anomalies"]


def test_rw_register_wr_cycle():
    h = [
        {"process": 0, "type": "invoke", "f": "txn",
         "value": [["w", 1, 1], ["r", 2, None]], "index": 0, "time": 0},
        {"process": 1, "type": "invoke", "f": "txn",
         "value": [["w", 2, 1], ["r", 1, None]], "index": 1, "time": 1},
        {"process": 0, "type": "ok", "f": "txn",
         "value": [["w", 1, 1], ["r", 2, 1]], "index": 2, "time": 2},
        {"process": 1, "type": "ok", "f": "txn",
         "value": [["w", 2, 1], ["r", 1, 1]], "index": 3, "time": 3},
    ]
    r = check_rw_register(h, "serializable")
    assert r["valid?"] is False


def test_list_append_g_single_label():
    # classic fractured read: T1 sees T2's append to key B but misses
    # T2's append to key A (whose version order a third read pins) —
    # wr T2->T1 plus rw T1->T2, a single-rw cycle -> G-single
    h = [
        {"process": 0, "type": "invoke", "f": "txn",
         "value": [["append", 1, 1], ["append", 2, 1]], "index": 0,
         "time": 0},
        {"process": 1, "type": "invoke", "f": "txn",
         "value": [["r", 1, None], ["r", 2, None]], "index": 1,
         "time": 1},
        {"process": 0, "type": "ok", "f": "txn",
         "value": [["append", 1, 1], ["append", 2, 1]], "index": 2,
         "time": 2},
        {"process": 1, "type": "ok", "f": "txn",
         "value": [["r", 1, []], ["r", 2, [1]]], "index": 3,
         "time": 3},
        {"process": 2, "type": "invoke", "f": "txn",
         "value": [["r", 1, None]], "index": 4, "time": 4},
        {"process": 2, "type": "ok", "f": "txn",
         "value": [["r", 1, [1]]], "index": 5, "time": 5},
    ]
    r = check_list_append(h, "serializable")
    assert r["valid?"] is False
    assert "G-single" in r["anomalies"], r["anomaly-types"]


def test_minimal_cycle_steps_reported():
    """r2: anomalies carry a minimal explanatory cycle with per-edge
    reasons (Elle's explanation discipline), not a whole-SCC dump."""
    from maelstrom_tpu.checkers.elle import check_list_append
    # classic G0: two txns that ww-conflict in both orders on two keys
    h = []
    i = 0

    def rec(p, t, f, v, tm):
        nonlocal i
        r = {"process": p, "type": t, "f": f, "value": v, "index": i,
             "time": tm}
        i += 1
        return r

    h.append(rec(0, "invoke", "txn", [["append", 0, 1], ["append", 1, 2]], 0))
    h.append(rec(1, "invoke", "txn", [["append", 1, 1], ["append", 0, 2]], 0))
    h.append(rec(0, "ok", "txn", [["append", 0, 1], ["append", 1, 2]], 5))
    h.append(rec(1, "ok", "txn", [["append", 1, 1], ["append", 0, 2]], 5))
    # reads fixing the version orders: key0 = [1, 2] puts txn0 before
    # txn1; key1 = [1, 2] puts txn1 (which appended 1) before txn0
    # (which appended 2) -> ww cycle
    h.append(rec(2, "invoke", "txn", [["r", 0, None], ["r", 1, None]], 6))
    h.append(rec(2, "ok", "txn", [["r", 0, [1, 2]], ["r", 1, [1, 2]]], 7))
    res = check_list_append(h, "serializable")
    assert res["valid?"] is False
    g0 = res["anomalies"].get("G0") or res["anomalies"].get("G1c")
    assert g0, res["anomalies"]
    cyc = g0[0]
    assert cyc["cycle-length"] >= 2
    assert all("because" in s and s["because"] for s in cyc["steps"])


def _rw_rec(recs):
    h = []
    for i, (p, t, f, v, tm) in enumerate(recs):
        h.append({"process": p, "type": t, "f": f, "value": v,
                  "index": i, "time": tm})
    return h


def test_rw_register_write_skew_caught():
    """Classic write skew: T1 reads x, writes y; T2 reads y, writes x —
    both read the initial state. Two generalized anti-dependencies form
    a G2-item cycle (r2: the rw-register checker now infers version
    orders from wfr + initial-version facts, not wr edges alone)."""
    from maelstrom_tpu.checkers.elle import check_rw_register
    h = _rw_rec([
        (0, "invoke", "txn", [["r", "x", None], ["w", "y", 1]], 0),
        (1, "invoke", "txn", [["r", "y", None], ["w", "x", 2]], 0),
        (0, "ok", "txn", [["r", "x", None], ["w", "y", 1]], 5),
        (1, "ok", "txn", [["r", "y", None], ["w", "x", 2]], 5),
    ])
    res = check_rw_register(h, "serializable")
    assert res["valid?"] is False
    assert "G2-item" in res["anomaly-types"], res["anomaly-types"]


def test_rw_register_internal_anomaly():
    from maelstrom_tpu.checkers.elle import check_rw_register
    h = _rw_rec([
        (0, "invoke", "txn", [["w", "x", 1], ["r", "x", None]], 0),
        (0, "ok", "txn", [["w", "x", 1], ["r", "x", 7]], 2),
    ])
    res = check_rw_register(h, "read-atomic")
    assert res["valid?"] is False
    assert "internal" in res["anomaly-types"]


def test_rw_register_serializable_history_clean():
    from maelstrom_tpu.checkers.elle import check_rw_register
    # sequential: T1 writes x=1; T2 reads x=1 writes x=2; T3 reads x=2
    h = _rw_rec([
        (0, "invoke", "txn", [["w", "x", 1]], 0),
        (0, "ok", "txn", [["w", "x", 1]], 1),
        (1, "invoke", "txn", [["r", "x", None], ["w", "x", 2]], 2),
        (1, "ok", "txn", [["r", "x", 1], ["w", "x", 2]], 3),
        (2, "invoke", "txn", [["r", "x", None]], 4),
        (2, "ok", "txn", [["r", "x", 2]], 5),
    ])
    res = check_rw_register(h, "strict-serializable")
    assert res["valid?"] is True, res


def test_rw_register_fractured_read_second_observation():
    """A txn that externally observes TWO versions of one key must
    contribute anti-dependency edges for each observation (r2 review
    fix: readers records every observed version, not just the first)."""
    from maelstrom_tpu.checkers.elle import check_rw_register
    h = _rw_rec([
        (0, "invoke", "txn", [["r", "x", None], ["r", "x", None]], 0),
        (0, "ok", "txn", [["r", "x", None], ["r", "x", 1]], 1),
        (1, "invoke", "txn", [["w", "x", 1]], 2),
        (1, "ok", "txn", [["w", "x", 1]], 3),
        (2, "invoke", "txn", [["r", "x", None], ["w", "x", 2]], 4),
        (2, "ok", "txn", [["r", "x", 1], ["w", "x", 2]], 5),
    ])
    res = check_rw_register(h, "serializable")
    assert res["valid?"] is False
    assert any(k in res["anomaly-types"]
               for k in ("G-single", "G2-item")), res["anomaly-types"]


def test_cycle_search_timeout_is_unknown_for_list_append():
    # wr cycle SCC, but a zero budget means it can't be searched: the
    # verdict must be "unknown" (a skipped search proves nothing), with
    # the pseudo-anomaly reported (Elle's cycle-search-timeout)
    h = H((0, "invoke", [["append", 1, 1], ["r", 2, None]]),
          (0, "ok",     [["append", 1, 1], ["r", 2, [2]]]),
          (1, "invoke", [["append", 2, 2], ["r", 1, None]]),
          (1, "ok",     [["append", 2, 2], ["r", 1, [1]]]))
    full = check_list_append(h)
    assert full["valid?"] is False   # searchable: a real G1c
    r = check_list_append(h, cycle_search_budget=0)
    assert r["valid?"] == "unknown"
    assert "cycle-search-timeout" in r["anomaly-types"]


def test_cycle_search_timeout_filtered_for_rw_register():
    # reference parity (txn_rw_register.clj:138-150): the rw-register
    # workload DROPS cycle-search timeouts entirely
    h = H((0, "invoke", [["w", 1, 1], ["r", 2, None]]),
          (0, "ok",     [["w", 1, 1], ["r", 2, 2]]),
          (1, "invoke", [["w", 2, 2], ["r", 1, None]]),
          (1, "ok",     [["w", 2, 2], ["r", 1, 1]]))
    assert check_rw_register(h)["valid?"] is False
    r = check_rw_register(h, cycle_search_budget=0)
    assert r["valid?"] is True
    assert "cycle-search-timeout" not in r["anomaly-types"]


def test_list_append_internal_and_unwritten():
    # a txn missing its OWN append is internally inconsistent
    h = H((0, "invoke", [["append", 1, 5], ["r", 1, None]]),
          (0, "ok",     [["append", 1, 5], ["r", 1, []]]))
    r = check_list_append(h, consistency_model="read-atomic")
    assert r["valid?"] is False and "internal" in r["anomalies"]
    # reading a value nobody ever wrote is corruption at any model
    h2 = H((0, "invoke", [["r", 1, None]]),
           (0, "ok",     [["r", 1, [31337]]]))
    r2 = check_list_append(h2, consistency_model="read-uncommitted")
    assert r2["valid?"] is False and "unwritten-read" in r2["anomalies"]


def test_rw_register_fractured_read():
    # two external reads of one key in one txn disagree: fine at
    # read-committed (non-repeatable reads allowed), fractured at
    # read-atomic and up
    h = H((0, "invoke", [["w", 1, 1]]),
          (0, "ok",     [["w", 1, 1]]),
          (1, "invoke", [["w", 1, 2]]),
          (1, "ok",     [["w", 1, 2]]),
          (2, "invoke", [["r", 1, None], ["r", 1, None]]),
          (2, "ok",     [["r", 1, 1], ["r", 1, 2]]))
    assert check_rw_register(
        h, consistency_model="read-committed")["valid?"] is True
    r = check_rw_register(h, consistency_model="read-atomic")
    assert r["valid?"] is False and "fractured-read" in r["anomalies"]


def test_nil_reader_inference_gated_below_serializable():
    # two txns each read nil then write the same key: legal at
    # read-committed (stale nil reads are permitted); the serializable
    # "nil-reader writes the first version" inference must not leak ww
    # edges into weaker models and fabricate a G0 there
    h = H((0, "invoke", [["r", 1, None], ["w", 1, 1]]),
          (0, "ok",     [["r", 1, None], ["w", 1, 1]]),
          (1, "invoke", [["r", 1, None], ["w", 1, 2]]),
          (1, "ok",     [["r", 1, None], ["w", 1, 2]]))
    assert check_rw_register(
        h, consistency_model="read-committed")["valid?"] is True
    # at serializable the two nil reads are mutually impossible
    assert check_rw_register(
        h, consistency_model="serializable")["valid?"] is False


def test_write_skew_si_legal_but_not_serializable():
    # classic write skew: T1 reads y writes x, T2 reads x writes y —
    # a 2-cycle of two ADJACENT rw edges. Snapshot isolation admits it
    # (Fekete et al.); serializable does not.
    h = H((0, "invoke", [["r", 2, None], ["w", 1, 1]]),
          (1, "invoke", [["r", 1, None], ["w", 2, 2]]),
          (0, "ok",     [["r", 2, None], ["w", 1, 1]]),
          (1, "ok",     [["r", 1, None], ["w", 2, 2]]))
    assert check_rw_register(
        h, consistency_model="snapshot-isolation")["valid?"] is True
    r = check_rw_register(h, consistency_model="serializable")
    assert r["valid?"] is False
    assert "G2-item" in r["anomalies"], r


def test_g_nonadjacent_refutes_snapshot_isolation():
    # 4-cycle alternating rw / wr edges, all txns concurrent:
    #   T0 -rw-> T1 -wr-> T2 -rw-> T3 -wr-> T0
    # (T0 read k0=[] missing T1's append; T2 read T1's k1 append; T2
    # read k2=[] missing T3's append; T0 read T3's k3 append.) The two
    # rw edges sit at opposite corners — never adjacent — so even
    # snapshot isolation forbids the cycle (G-nonadjacent).
    h = H(
        (0, "invoke", [["r", 0, None], ["r", 3, None]]),
        (1, "invoke", [["append", 0, 1], ["append", 1, 2]]),
        (2, "invoke", [["r", 1, None], ["r", 2, None]]),
        (3, "invoke", [["append", 2, 3], ["append", 3, 4]]),
        (0, "ok",     [["r", 0, []], ["r", 3, [4]]]),
        (1, "ok",     [["append", 0, 1], ["append", 1, 2]]),
        (2, "ok",     [["r", 1, [2]], ["r", 2, []]]),
        (3, "ok",     [["append", 2, 3], ["append", 3, 4]]),
    )
    r = check_list_append(h, consistency_model="snapshot-isolation")
    assert r["valid?"] is False, r
    assert "G-nonadjacent" in r["anomalies"], r
    # the same witness still fails serializable, and write-skew-style
    # adjacent-rw cycles would not have been flagged at SI
    assert check_list_append(
        h, consistency_model="serializable")["valid?"] is False
