"""Kafka workload: checker unit tests + single-node e2e."""
import pytest

from maelstrom_tpu.checkers.kafka import kafka_checker
from conftest import example_bin
from maelstrom_tpu.runner import run_test


def H(*recs):
    out = []
    for i, r in enumerate(recs):
        out.append({"process": r[0], "type": r[1], "f": r[2],
                    "value": r[3], "index": i, "time": i})
    return out


def test_kafka_clean():
    h = H((0, "invoke", "send", ["k", 1]),
          (0, "ok", "send", ["k", 1, 0]),
          (1, "invoke", "poll", None),
          (1, "ok", "poll", {"k": [[0, 1]]}))
    assert kafka_checker(h)["valid?"] is True


def test_kafka_lost_write():
    h = H((0, "invoke", "send", ["k", 1]),
          (0, "ok", "send", ["k", 1, 0]),
          (0, "invoke", "send", ["k", 2]),
          (0, "ok", "send", ["k", 2, 1]),
          (1, "invoke", "poll", None),
          (1, "ok", "poll", {"k": [[1, 2]]}))
    r = kafka_checker(h)
    assert r["valid?"] is False
    assert "lost-write" in r["anomalies"]


def test_kafka_internal_nonmonotonic():
    h = H((1, "invoke", "poll", None),
          (1, "ok", "poll", {"k": [[3, "a"], [2, "b"]]}))
    r = kafka_checker(h)
    assert "internal-nonmonotonic" in r["anomalies"]


def test_kafka_inconsistent_offset():
    h = H((0, "invoke", "poll", None),
          (0, "ok", "poll", {"k": [[0, "a"]]}),
          (1, "invoke", "poll", None),
          (1, "ok", "poll", {"k": [[0, "b"]]}))
    r = kafka_checker(h)
    assert "inconsistent-offset" in r["anomalies"]


@pytest.mark.slow
def test_kafka_single_node_e2e():
    bin_cmd = example_bin("kafka_single.py")
    res = run_test("kafka", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:], node_count=1,
        snapshot_store=False, time_limit=3.0, rate=40.0, concurrency=4,
        recovery_time=0.5, seed=42))
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["send-count"] > 10
    assert w["poll-count"] > 10


@pytest.mark.slow
def test_kafka_multi_node_over_lin_kv_e2e():
    bin_cmd = example_bin("kafka_lin_kv.py")
    res = run_test("kafka", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:], node_count=3,
        snapshot_store=False, time_limit=3.0, rate=20.0, concurrency=4,
        recovery_time=0.5, seed=11))
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["send-count"] > 5


# --- txn mode (--txn: multi-mop send/poll transactions) -------------------

def test_kafka_txn_mops_feed_anomaly_machinery():
    # lost write planted inside txn mops: send off 0 and 1, later txn
    # poll observes only offset 1
    h = H((0, "invoke", "txn", [["send", "k", 1], ["send", "k", 2]]),
          (0, "ok", "txn", [["send", "k", [0, 1]],
                            ["send", "k", [1, 2]]]),
          (1, "invoke", "txn", [["poll"]]),
          (1, "ok", "txn", [["poll", {"k": [[1, 2]]}]]))
    r = kafka_checker(h)
    assert r["valid?"] is False
    assert "lost-write" in r["anomalies"]
    assert r["send-count"] == 2 and r["poll-count"] == 1


def test_kafka_txn_external_nonmonotonic_and_reassignment():
    # same process polls backwards across txns -> anomaly ...
    h = H((0, "invoke", "txn", [["poll"]]),
          (0, "ok", "txn", [["poll", {"k": [[0, "a"], [1, "b"]]}]]),
          (0, "invoke", "txn", [["poll"]]),
          (0, "ok", "txn", [["poll", {"k": [[0, "a"]]}]]))
    r = kafka_checker(h)
    assert "external-nonmonotonic" in r["anomalies"]
    # ... unless the op carries the reassignment marker (fresh client)
    h2 = h[:3] + [{"process": 0, "type": "ok", "f": "txn",
                   "value": [["poll", {"k": [[0, "a"]]}]],
                   "reassigned": True, "index": 3, "time": 3}]
    assert "external-nonmonotonic" not in kafka_checker(h2)["anomalies"]


@pytest.mark.slow
def test_kafka_txn_e2e():
    bin_cmd = example_bin("kafka_single.py")
    res = run_test("kafka", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:], node_count=1,
        snapshot_store=False, time_limit=6.0, rate=15.0, concurrency=4,
        txn=True, max_txn_length=4, seed=5))
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["send-count"] > 20

def test_kafka_aborted_read_unit():
    """A poll observing a value whose atomic txn definitively failed is
    the aborted-read anomaly; non-atomic (sequential-fallback) failures
    are exempt — their durable prefix is documented semantics."""
    h = H((0, "invoke", "txn", [["send", "k", 7]]),
          (0, "fail", "txn", [["send", "k", 7]]),
          (1, "invoke", "poll", None),
          (1, "ok", "poll", {"k": [[0, 7]]}))
    r = kafka_checker(h)
    assert r["valid?"] is False and "aborted-read" in r["anomaly-types"]

    # identical history, but the failed op is tagged non-atomic
    h2 = [dict(rec) for rec in h]
    h2[0]["non-atomic"] = True
    h2[1]["non-atomic"] = True
    r2 = kafka_checker(h2)
    assert "aborted-read" not in r2["anomaly-types"], r2


@pytest.mark.slow
def test_kafka_atomic_txn_node_e2e():
    """The single-root transactor under multi-mop --txn load: atomic,
    clean; its --no-atomic mutant (durable sends from aborted txns) is
    caught via aborted-read (VERDICT r3 next #4)."""
    bin_cmd = example_bin("kafka_txn.py")
    res = run_test("kafka", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:], node_count=3,
        snapshot_store=False, time_limit=5.0, rate=25.0, concurrency=6,
        txn=True, key_count=4, seed=7))
    w = res["workload"]
    assert w["valid?"] is True, w
    assert w["send-count"] > 20

    res2 = run_test("kafka", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:] + ["--no-atomic"],
        node_count=3, snapshot_store=False, time_limit=5.0, rate=25.0,
        concurrency=6, txn=True, key_count=4, seed=7))
    w2 = res2["workload"]
    assert w2["valid?"] is False, "non-atomic mutant not caught"
    assert "aborted-read" in w2["anomaly-types"], w2
