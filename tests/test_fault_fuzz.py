"""Randomized per-instance fault fuzzer: the schedule-RNG lane.

Four contracts, each pinned here (doc/guide/10-faults.md "Randomized
schedules & shrinking"):

1. **Distribution spec** — validate/compile units for the ``--fault-
   fuzz`` JSON (per-lane rates, victim/duration/phase-count ranges),
   and the mutual exclusions with ``--fault-plan`` / fault nemesis
   kinds.
2. **Per-instance randomization + bit-identity** — a fuzzed sweep
   draws ≥2 DISTINCT schedules per lane across instances (the whole
   point: one instance = one scenario); an all-healthy distribution
   (lanes configured, rate 0 — full machinery in the graph) is
   bit-identical to a fault-free run in BOTH carry layouts and through
   the sharded driver; an active distribution is layout-independent.
3. **Seed-stable reconstruction** — any instance's schedule rebuilds
   host-side from ``(seed, instance_id)`` alone, lowers to a
   deterministic ``--fault-plan`` dict, and the single-instance replay
   under that plan is BIT-EXACT against the instance's slice of the
   fuzzed fleet (the foundation of ``maelstrom shrink``).
4. **Shrinking** — on a planted ``RaftForgetsSnapshot`` fuzz hit, the
   delta-debugger converges to a plan with strictly fewer
   phases/victims whose replay still trips the committed-prefix
   invariant; checkpoint/resume under an active fuzz stays
   bit-identical (the schedule lanes ride the carry).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu.faults import (SpecError, compile_fault_fuzz,
                                  validate_fault_fuzz)
from maelstrom_tpu.faults import fuzz as fz
from maelstrom_tpu.models import get_model
from maelstrom_tpu.tpu.harness import make_sim_config, run_tpu_test
from maelstrom_tpu.tpu.pipeline import run_sim_pipelined
from maelstrom_tpu.tpu.runtime import canonical_carry, run_sim

pytestmark = pytest.mark.fuzz


# --- shared fixtures -------------------------------------------------------

# an ACTIVE distribution exercising all three lanes (kept identical to
# the doc walkthrough so the guide's config is tested)
ACTIVE_DIST = {"windows": [2, 2], "gap": [40, 120],
               "duration": [30, 80],
               "crash": {"rate": 0.8, "victims": [1, 2]},
               "links": {"rate": 0.6, "edges": [1, 3], "block": 0.5,
                         "delay": [0, 20], "loss": [0.0, 0.3]},
               "skew": {"rate": 0.5, "victims": [1, 2],
                        "range": [0.5, 2.0]}}

# lanes CONFIGURED but rate 0: the schedule machinery is fully in the
# traced graph (planes computed per instance every tick) while every
# draw is healthy — the bit-identity probe
HEALTHY_DIST = {"windows": [1, 2], "gap": [20, 60],
                "duration": [20, 50],
                "crash": {"rate": 0.0, "victims": [1, 2]},
                "links": {"rate": 0.0, "edges": [1, 2]},
                "skew": {"rate": 0.0, "victims": [1, 1]}}

# the shrinker's quarry: first gap long enough for Raft to commit
# entries, then majority crashes — the forget-snapshot mutant reboots
# amnesiac pairs that elect each other and commit over the survivor's
# committed prefix; links/skew ride along as shrinkable decoys
HIT_DIST = {"windows": [2, 2], "gap": [150, 260],
            "duration": [50, 90],
            "crash": {"rate": 1.0, "victims": [2, 2]},
            "links": {"rate": 0.6, "edges": [1, 3], "block": 0.5,
                      "delay": [0, 20], "loss": [0.0, 0.2]},
            "skew": {"rate": 0.4, "victims": [1, 1],
                     "range": [0.75, 1.5]}}
HIT_OPTS = dict(node_count=3, concurrency=4, n_instances=16,
                record_instances=2, time_limit=0.8, rate=300.0,
                latency=5.0, rpc_timeout=0.08, recovery_time=0.1,
                seed=7, inbox_k=2, pool_slots=24, fault_fuzz=HIT_DIST,
                funnel=False, heartbeat=False)

SMALL_OPTS = dict(node_count=3, concurrency=2, n_instances=8,
                  record_instances=2, time_limit=0.4, rate=200.0,
                  latency=5.0, rpc_timeout=0.08, recovery_time=0.1,
                  seed=7, inbox_k=2, pool_slots=24)


# --- spec / compile units --------------------------------------------------


class TestSpec:
    def test_compile_roundtrip(self):
        fx = compile_fault_fuzz(ACTIVE_DIST, 3, stop_tick=600)
        assert fx.enabled and fx.has_fuzz and fx.active
        assert fx.has_crash and fx.has_links and fx.has_skew
        f = fx.fuzz
        assert (f.windows_min, f.windows_max) == (2, 2)
        assert f.crash.rate_pm == 800
        assert f.links.loss_pm_max == 300
        assert f.skew.rate64_min == 32 and f.skew.rate64_max == 128
        assert fx.untils == ()   # no shared timeline: fuzz is per-inst

    def test_healthy_rates_keep_lanes_present(self):
        """rate 0 keeps a configured lane STATICALLY present (the
        all-healthy machinery probe) — presence is configuration, not
        drawn content."""
        fx = compile_fault_fuzz(HEALTHY_DIST, 3, stop_tick=600)
        assert fx.has_crash and fx.has_links and fx.has_skew

    def test_none_is_disabled(self):
        fx = compile_fault_fuzz(None, 3, stop_tick=600)
        assert not fx.active and not fx.has_fuzz

    @pytest.mark.parametrize("dist,msg", [
        ({}, "at least one lane"),
        ({"windows": [3, 1], "crash": {"victims": 1}}, "lo > hi"),
        ({"crash": {"rate": 2.0, "victims": 1}}, "rate"),
        ({"crash": {"victims": [1, 7]}}, "victims"),
        ({"links": {"edges": [1, 2]}, "windows": 99}, "windows"),
        ({"skew": {"victims": 1, "range": [0.01, 1.0]}}, "range"),
        ({"snapshot_every": 0, "crash": {"victims": 1}},
         "snapshot_every"),
    ])
    def test_validation_rejects(self, dist, msg):
        with pytest.raises(SpecError, match=msg):
            validate_fault_fuzz(dist, 3)

    def test_links_need_two_nodes(self):
        with pytest.raises(SpecError, match="2 server nodes"):
            validate_fault_fuzz({"links": {"edges": 1}}, 1)

    def test_dash_keys_tolerated(self):
        fx = compile_fault_fuzz(
            {"snapshot-every": 2, "crash": {"victims": [1, 2]}},
            3, stop_tick=600)
        assert fx.snapshot_every == 2 and fx.has_crash

    def test_mutually_exclusive_with_plan_and_kinds(self):
        model = get_model("echo", 3)
        plan = {"phases": [{"until": 10, "crash": [0]}]}
        with pytest.raises(ValueError, match="mutually exclusive"):
            make_sim_config(model, dict(SMALL_OPTS,
                                        fault_fuzz=HEALTHY_DIST,
                                        fault_plan=plan))
        with pytest.raises(ValueError, match="mutually exclusive"):
            make_sim_config(model, dict(SMALL_OPTS,
                                        fault_fuzz=HEALTHY_DIST,
                                        nemesis=["crash-restart"]))
        # composes with the partition nemesis
        sim = make_sim_config(model, dict(SMALL_OPTS,
                                          fault_fuzz=HEALTHY_DIST,
                                          nemesis=["partition"]))
        assert sim.faults.has_fuzz and sim.nemesis.enabled


# --- schedule draws --------------------------------------------------------


class TestScheduleDraw:
    def _draws(self, n=16):
        fx = compile_fault_fuzz(ACTIVE_DIST, 3, stop_tick=500)
        return fx, [fz.reconstruct_schedule(fx, 3, 7, i)
                    for i in range(n)]

    def test_schedules_differ_per_lane(self):
        """The acceptance bar: a fuzzed sweep holds >= 2 DISTINCT
        per-instance schedules PER LANE — the fleet explores many
        fault-space points per run, not one."""
        _, scheds = self._draws()
        untils = {tuple(np.asarray(s.untils).tolist()) for s in scheds}
        crash = {np.asarray(s.crash).astype(np.int8).tobytes()
                 for s in scheds}
        links = {np.concatenate(
            [np.asarray(s.edge_dst), np.asarray(s.edge_src),
             np.asarray(s.edge_block), np.asarray(s.edge_delay),
             np.asarray(s.edge_loss_pm)], axis=None).tobytes()
            for s in scheds}
        skew = {np.asarray(s.skew).tobytes() for s in scheds}
        assert len(untils) >= 2
        assert len(crash) >= 2
        assert len(links) >= 2
        assert len(skew) >= 2

    def test_draw_shapes_and_bounds(self):
        fx, scheds = self._draws()
        f = fx.fuzz
        for s in scheds:
            u = np.asarray(s.untils)
            assert u.shape == (2 * f.windows_max,)
            assert (np.diff(u) >= 0).all()
            crash = np.asarray(s.crash)
            assert ((crash.sum(axis=1) == 0)
                    | ((crash.sum(axis=1) >= f.crash.victims_min)
                       & (crash.sum(axis=1)
                          <= f.crash.victims_max))).all()
            dst, src = np.asarray(s.edge_dst), np.asarray(s.edge_src)
            assert (dst != src).all()        # never a self edge
            assert (dst >= 0).all() and (dst < 3).all()
            assert (src >= 0).all() and (src < 3).all()
            assert (np.asarray(s.edge_delay) <= f.links.delay_max).all()
            skew = np.asarray(s.skew)
            neutral = skew == 64
            assert (neutral | ((skew >= f.skew.rate64_min)
                               & (skew <= f.skew.rate64_max))).all()

    def test_draw_is_seed_stable(self):
        fx = compile_fault_fuzz(ACTIVE_DIST, 3, stop_tick=500)
        a = fz.reconstruct_schedule(fx, 3, 7, 5)
        b = fz.reconstruct_schedule(fx, 3, 7, 5)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_fleet_windows_and_coverage(self):
        fx = compile_fault_fuzz(ACTIVE_DIST, 3, stop_tick=500)
        win = fz.fleet_windows(fx, 3, 7, np.arange(32))
        cov = fz.fleet_coverage(win)
        assert cov["instances"] == 32
        assert cov["distinct-schedules"] >= 2
        assert cov["crash-windows"] > 0
        counters = fz.span_counters(win, 0, 500)
        assert counters["schedules-active"] > 0
        # a span past every window is quiet (membership joined the
        # lane roster in PR 15)
        assert fz.span_counters(win, 10_000, 100) == {
            "schedules-active": 0, "crash": 0, "links": 0, "skew": 0,
            "membership": 0}


# --- bit-identity ----------------------------------------------------------


class TestBitIdentity:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_all_healthy_fuzz_bit_identical(self, layout):
        """An all-healthy distribution (every lane configured, rate 0)
        reproduces the fault-free trajectory bit-for-bit in both
        layouts — the fuzz analog of the PR 9 neutral-plan probe."""
        model = get_model("lin-kv", 3)
        params = model.make_params(3)
        base = make_sim_config(model, {**SMALL_OPTS, "layout": layout})
        fzd = make_sim_config(model, {**SMALL_OPTS, "layout": layout,
                                      "fault_fuzz": HEALTHY_DIST})
        assert fzd.faults.has_fuzz
        c0, y0 = run_sim(model, base, 7, params)
        c1, y1 = run_sim(model, fzd, 7, params)
        assert c1.fault_sched is not None    # machinery really ran
        assert c1.snapshots is not None
        for a, b in zip(
                jax.tree.leaves((c0.pool, c0.node_state,
                                 c0.client_state, c0.stats,
                                 c0.violations)),
                jax.tree.leaves((c1.pool, c1.node_state,
                                 c1.client_state, c1.stats,
                                 c1.violations))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(y0.events),
                                      np.asarray(y1.events))

    def test_active_fuzz_layout_independent(self):
        """An ACTIVE distribution produces bit-identical trajectories
        in both carry layouts (per-instance planes ride the same
        vmapped code either way)."""
        model = get_model("lin-kv", 3)
        params = model.make_params(3)
        out = {}
        for layout in ("lead", "minor"):
            sim = make_sim_config(model, {**SMALL_OPTS,
                                          "layout": layout,
                                          "fault_fuzz": ACTIVE_DIST})
            c, y = run_sim(model, sim, 7, params)
            canon = canonical_carry(c, sim)
            out[layout] = (jax.tree.leaves(
                (canon.pool, canon.node_state, canon.client_state,
                 canon.stats, canon.violations, canon.snapshots,
                 canon.fault_sched)), np.asarray(y.events))
        for a, b in zip(out["lead"][0], out["minor"][0]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(out["lead"][1], out["minor"][1])

    def test_all_healthy_fuzz_sharded_bit_identical(self):
        """Through the sharded driver: an all-healthy fuzzed fleet's
        (stats, violations, events) equal the fault-free sharded run
        bit-for-bit; the schedule lanes cross the shard_map wire."""
        from maelstrom_tpu.parallel.mesh import (make_mesh,
                                                 run_sim_sharded)
        model = get_model("echo", 2)
        opts = dict(node_count=2, concurrency=2, n_instances=4,
                    record_instances=2, time_limit=0.2, rate=200.0,
                    latency=5.0, seed=3, inbox_k=2, pool_slots=16)
        params = model.make_params(2)
        mesh = make_mesh(2)
        base = make_sim_config(model, dict(opts))
        fzd = make_sim_config(model, {**opts,
                                      "fault_fuzz": HEALTHY_DIST})
        s0, v0, e0 = run_sim_sharded(model, base, 3, params, mesh=mesh)
        s1, v1, e1 = run_sim_sharded(model, fzd, 3, params, mesh=mesh)
        assert jax.tree.map(int, s0) == jax.tree.map(int, s1)
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(e0), np.asarray(e1))

    def test_active_fuzz_sharded_chunked_matches_oracle(self):
        """An ACTIVE fuzzed fleet through the chunked sharded driver
        equals the serial unsharded oracle — randomized schedules do
        not break the shard-equivalence contract."""
        from maelstrom_tpu.parallel.mesh import (make_mesh,
                                                 run_sim_sharded_chunked,
                                                 run_sim_unsharded)
        model = get_model("echo", 2)
        opts = dict(node_count=2, concurrency=2, n_instances=4,
                    record_instances=2, time_limit=0.2, rate=200.0,
                    latency=5.0, seed=3, inbox_k=2, pool_slots=16,
                    fault_fuzz=dict(ACTIVE_DIST,
                                    links=None, skew=None))
        sim = make_sim_config(model, opts)
        params = model.make_params(2)
        mesh = make_mesh(2)
        s_sh, v_sh, e_sh = run_sim_sharded_chunked(
            model, sim, 3, params, mesh=mesh, chunk=50)
        s_un, v_un, e_un = run_sim_unsharded(model, sim, 3, 2, params)
        assert jax.tree.map(int, s_sh) == jax.tree.map(int, s_un)
        np.testing.assert_array_equal(np.asarray(v_sh), v_un)
        np.testing.assert_array_equal(np.asarray(e_sh), e_un)


# --- seed-stable reconstruction --------------------------------------------


class TestReconstruction:
    def test_fuzz_instance_equals_plan_replay_bit_exact(self):
        """Instance ``i`` of a fuzzed sweep and the single-instance
        deterministic replay of its reconstructed plan are the SAME
        trajectory, bit for bit — the contract `maelstrom shrink`'s
        delta-debugging rests on."""
        model = get_model("lin-kv", 3)
        params = model.make_params(3)
        sim = make_sim_config(model, {**SMALL_OPTS, "layout": "lead",
                                      "fault_fuzz": ACTIVE_DIST})
        c, _ = run_sim(model, sim, 7, params)
        cc = canonical_carry(c, sim)
        gid = 3
        plan = fz.reconstruct_plan(sim.faults, 3, 7, gid)
        assert plan and plan["phases"]   # instance 3 drew real faults
        sub = make_sim_config(model, {**SMALL_OPTS, "layout": "lead",
                                      "fault_plan": plan,
                                      "n_instances": 1,
                                      "record_instances": 1})
        c1, _ = run_sim(model, sub, 7, params,
                        jnp.asarray([gid], jnp.int32))
        cc1 = canonical_carry(c1, sub)
        a = jax.tree.map(lambda x: np.asarray(x)[gid],
                         (cc.pool, cc.node_state, cc.client_state))
        b = jax.tree.map(lambda x: np.asarray(x)[0],
                         (cc1.pool, cc1.node_state, cc1.client_state))
        for x, z in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(x, z)
        assert int(np.asarray(c.violations)[gid]) \
            == int(np.asarray(c1.violations)[0])

    def test_all_healthy_draw_reconstructs_to_empty_plan(self):
        fx = compile_fault_fuzz(HEALTHY_DIST, 3, stop_tick=500)
        for i in range(4):
            assert fz.reconstruct_plan(fx, 3, 7, i) == {}

    def test_plan_validates_and_compiles(self):
        """Reconstructed plans are legal ``--fault-plan`` inputs: they
        pass the PR 9 validator and compile to matching lanes."""
        from maelstrom_tpu.faults import (compile_fault_plan,
                                          validate_fault_plan)
        fx = compile_fault_fuzz(ACTIVE_DIST, 3, stop_tick=500)
        seen_lane = False
        for i in range(6):
            plan = fz.reconstruct_plan(fx, 3, 7, i)
            if not plan:
                continue
            validate_fault_plan(plan, 3)
            det = compile_fault_plan(plan, 3, stop_tick=500)
            assert det.active
            seen_lane = True
        assert seen_lane


# --- the shrinker ----------------------------------------------------------


class TestShrinker:
    def test_shrinker_converges_on_forget_snapshot_hit(self):
        """The acceptance bar end-to-end: the fuzzed sweep flags the
        amnesia mutant, and the shrinker reduces the flagged
        instance's drawn schedule to a plan with STRICTLY fewer
        phases/victims whose deterministic replay still trips the
        committed-prefix invariant (every kept reduction re-verified
        by replay, the final plan by construction)."""
        from maelstrom_tpu.faults.shrink import shrink_instance
        model = get_model("lin-kv-bug-forget-snapshot", 3)
        params = model.make_params(3)
        sim = make_sim_config(model, dict(HIT_OPTS))
        res = run_sim_pipelined(model, sim, HIT_OPTS["seed"], params,
                                chunk=100)
        viol = np.nonzero(np.asarray(res.carry.violations))[0]
        assert viol.size > 0, "fuzz sweep produced no amnesia hit"
        gid = int(viol[0])
        rec = shrink_instance(model, dict(HIT_OPTS), gid,
                              params=params, max_attempts=6)
        assert rec["verified"]
        assert rec["reduced"], rec
        assert (rec["shrunk-phases"], rec["shrunk-victims"]) \
            < (rec["original-phases"], rec["original-victims"])
        # the artifact is a legal plan file
        from maelstrom_tpu.faults import validate_fault_plan
        validate_fault_plan(rec["shrunk-plan"], 3)

    def test_shrink_rejects_fault_free_runs(self):
        """No fuzz distribution AND no deterministic plan -> nothing
        to shrink (plan runs became shrinkable with the membership
        lane — tests/test_membership.py covers that path)."""
        from maelstrom_tpu.faults.shrink import (ShrinkError,
                                                 shrink_instance)
        model = get_model("lin-kv", 3)
        with pytest.raises(ShrinkError, match="not a fault run"):
            shrink_instance(model, dict(SMALL_OPTS), 0)

    @pytest.mark.slow
    def test_shrink_run_writes_bundles(self, tmp_path):
        """The run-dir face: a stored fuzz run of the mutant shrinks
        into triage/instance-<id>/shrunk-plan.json + shrink.json, and
        the summary reports the reduction."""
        from maelstrom_tpu.faults.shrink import shrink_run
        model = get_model("lin-kv-bug-forget-snapshot", 3)
        opts = dict(HIT_OPTS, store_root=str(tmp_path),
                    heartbeat=True, pipeline="on", chunk_ticks=100)
        res = run_tpu_test(model, opts)
        assert res["valid?"] is False
        run_dir = os.path.realpath(os.path.join(
            str(tmp_path), "lin-kv-bug-forget-snapshot-tpu", "latest"))
        summary = shrink_run(run_dir, max_instances=1, max_attempts=6)
        assert summary["shrunk"], summary
        rec = summary["shrunk"][0]
        assert rec["verified"] and rec["reduced"]
        plan_path = os.path.join(run_dir, "triage",
                                 f"instance-{rec['instance']}",
                                 "shrunk-plan.json")
        with open(plan_path) as f:
            plan = json.load(f)
        assert plan["phases"]


# --- checkpoint/resume + observability -------------------------------------


class TestDurabilityAndObservability:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_checkpoint_resume_under_fuzz_bit_identical(self, tmp_path,
                                                        layout):
        """Kill after a mid-run checkpoint under an ACTIVE fuzz,
        resume, and the result equals the uninterrupted run — the
        schedule lanes ride the carry through save/restore."""
        from maelstrom_tpu.campaign.checkpoint import (load_checkpoint,
                                                       restore_carry,
                                                       save_checkpoint)
        from maelstrom_tpu.tpu.pipeline import (ResumeState,
                                                _init_pipelined)
        model = get_model("echo", 2)
        opts = dict(node_count=2, concurrency=2, n_instances=8,
                    record_instances=2, time_limit=0.3, rate=200.0,
                    latency=5.0, seed=3, inbox_k=2, pool_slots=16,
                    layout=layout,
                    fault_fuzz=dict(ACTIVE_DIST, links=None,
                                    skew=None))
        sim = make_sim_config(model, opts)
        assert sim.faults.has_fuzz
        params = model.make_params(2)
        base = run_sim_pipelined(model, sim, 3, params, chunk=50)

        d = str(tmp_path)

        class Killed(Exception):
            pass

        def cb(state, ticks, host):
            save_checkpoint(d, kind="pipelined", state=state,
                            ticks=ticks, chunks=host["chunks"],
                            compact=tuple(host["compact"]),
                            journal=tuple(host["journal"]))
            raise Killed

        with pytest.raises(Killed):
            run_sim_pipelined(model, sim, 3, params, chunk=50,
                              checkpoint_cb=cb, checkpoint_every=2)
        ck = load_checkpoint(d)
        assert 0 < ck["ticks"] < sim.n_ticks
        template = _init_pipelined(model, sim, 3, params,
                                   np.arange(8, dtype=np.int32))
        resume = ResumeState(
            carry=restore_carry(template, ck["carry"]),
            ticks=ck["ticks"], chunks=ck["chunks"],
            compact=tuple(ck["compact"]),
            journal=tuple(ck["journal"]))
        res = run_sim_pipelined(model, sim, 3, params, chunk=50,
                                resume=resume)
        np.testing.assert_array_equal(base.events, res.events)
        for a, b in zip(jax.tree.leaves(base.carry),
                        jax.tree.leaves(res.carry)):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))

    def test_fuzz_lane_rides_the_heartbeat(self, tmp_path):
        """Chunked fuzz runs stream schedules-active counters per
        chunk, the run-start header carries the distribution + fleet
        coverage, `watch` renders the lane, and triage bundles gain
        the instance's reconstructed schedule."""
        from maelstrom_tpu.telemetry.stream import (read_heartbeat,
                                                    render_chunk_line)
        model = get_model("echo", 2)
        opts = dict(node_count=2, concurrency=2, n_instances=8,
                    record_instances=2, time_limit=0.3, rate=100.0,
                    latency=5.0, recovery_time=0.05, seed=3,
                    fault_fuzz=dict(ACTIVE_DIST, gap=[20, 80],
                                    links=None, skew=None),
                    funnel=False, store_root=str(tmp_path),
                    pipeline="on", chunk_ticks=50)
        run_tpu_test(model, opts)
        run_dir = os.path.realpath(
            os.path.join(str(tmp_path), "echo-tpu", "latest"))
        hb = read_heartbeat(run_dir)
        header = hb["header"]
        assert header["faults"]["fuzz"]["lanes"] == ["crash-restart"]
        cov = header["fault-fuzz"]
        assert cov["instances"] == 8
        assert cov["distinct-schedules"] >= 2
        lanes = [rec.get("fault-fuzz") for rec in hb["chunks"]]
        assert all(x is not None for x in lanes)
        assert any(x["schedules-active"] > 0 for x in lanes)
        rendered = [render_chunk_line(rec) for rec in hb["chunks"]]
        assert any("fuzz[" in line for line in rendered)

    @pytest.mark.slow
    def test_triage_bundle_carries_schedule(self, tmp_path):
        from maelstrom_tpu.checkers.triage import triage_run
        model = get_model("lin-kv-bug-forget-snapshot", 3)
        opts = dict(HIT_OPTS, store_root=str(tmp_path),
                    heartbeat=True, pipeline="on", chunk_ticks=100)
        res = run_tpu_test(model, opts)
        assert res["valid?"] is False
        run_dir = os.path.realpath(os.path.join(
            str(tmp_path), "lin-kv-bug-forget-snapshot-tpu", "latest"))
        summary = triage_run(run_dir, max_instances=1)
        inst_dir = summary["triaged"][0]["dir"]
        with open(os.path.join(inst_dir, "schedule.json")) as f:
            plan = json.load(f)
        assert plan["phases"]
        with open(os.path.join(inst_dir, "repro.json")) as f:
            repro = json.load(f)
        assert "shrink-command" in repro

    def test_host_runtimes_reject_fault_fuzz(self, capsys):
        """The PR 9 rejection pattern extends to --fault-fuzz: host
        runtimes have one real cluster and no schedule-RNG lane
        (nemesis.py parity note, PARITY.md)."""
        from maelstrom_tpu.cli import main
        rc = main(["test", "-w", "echo", "--runtime", "process",
                   "--fault-fuzz", "nonexistent.json"])
        assert rc == 2
        err = capsys.readouterr().err
        assert "--runtime tpu only" in err


# --- overhead --------------------------------------------------------------


@pytest.mark.slow
def test_fuzz_overhead_within_noise():
    """The bench A/B bar (BENCH_FUZZ=0): an all-healthy distribution's
    schedule draw + per-tick plane select stay within the telemetry-
    style noise allowance of the bare pipelined path, with identical
    trajectories."""
    import time

    model = get_model("echo", 2)
    opts = dict(node_count=2, concurrency=4, n_instances=256,
                record_instances=1, time_limit=0.5, rate=200.0,
                latency=5.0, seed=7, funnel=False)
    params = model.make_params(2)

    def run_one(with_fuzz):
        sim = make_sim_config(
            model, dict(opts, **({"fault_fuzz": HEALTHY_DIST}
                                 if with_fuzz else {})))
        best = float("inf")
        delivered = None
        for i in range(3):
            t0 = time.monotonic()
            res = run_sim_pipelined(model, sim, 7, params, chunk=100)
            dt = time.monotonic() - t0
            if i > 0:   # skip the compile-inclusive first pass
                best = min(best, dt)
            delivered = int(res.carry.stats.delivered)
        return best, delivered

    base_s, base_d = run_one(False)
    fuzz_s, fuzz_d = run_one(True)
    assert base_d == fuzz_d   # identical trajectories
    ratio = fuzz_s / base_s
    print(f"fuzz overhead: {base_s:.3f}s -> {fuzz_s:.3f}s "
          f"(x{ratio:.3f})")
    assert ratio < 1.25, (base_s, fuzz_s)
