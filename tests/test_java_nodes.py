"""End-to-end runs of the Java example nodes through the process
runtime. Skips cleanly when no JVM toolchain is present (this image
ships none — the static wire conformance in
test_java_wire_conformance.py still runs)."""

import os
import shutil
import subprocess

import pytest

from maelstrom_tpu import run_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
J_DIR = os.path.join(REPO, "examples", "java")

pytestmark = pytest.mark.skipif(
    shutil.which("javac") is None or shutil.which("java") is None,
    reason="no JVM toolchain in image")


@pytest.fixture(scope="session")
def java_classes(tmp_path_factory):
    out = tmp_path_factory.mktemp("java-classes")
    srcs = [os.path.join(J_DIR, f) for f in os.listdir(J_DIR)
            if f.endswith(".java")]
    subprocess.run(["javac", "-d", str(out)] + srcs, check=True,
                   capture_output=True)
    return out


def _bin(classes, main):
    return dict(bin="java",
                bin_args=["-cp", str(classes), f"maelstrom.{main}"])


def test_java_echo_e2e(java_classes, tmp_path):
    res = run_test("echo", dict(
        **_bin(java_classes, "EchoServer"), node_count=2,
        time_limit=3.0, rate=20.0, concurrency=4,
        store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_java_broadcast_partition_e2e(java_classes, tmp_path):
    res = run_test("broadcast", dict(
        **_bin(java_classes, "BroadcastServer"), node_count=3,
        time_limit=6.0, rate=20.0, concurrency=4,
        nemesis=["partition"], nemesis_interval=2.0,
        recovery_time=3.0, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_java_counter_seq_kv_e2e(java_classes, tmp_path):
    res = run_test("g-counter", dict(
        **_bin(java_classes, "CounterServer"), node_count=2,
        time_limit=5.0, rate=10.0, concurrency=4,
        store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True
