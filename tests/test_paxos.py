"""Single-decree-Paxos lin-kv node (BASELINE.json config #4): per-key
multi-slot Paxos with full two-phase rounds per op, linearizable with
and without partitions."""

import os
import sys

from maelstrom_tpu import run_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN_ARGS = [os.path.join(REPO, "examples", "python", "paxos.py")]


def test_paxos_lin_kv_5n():
    res = run_test("lin-kv", dict(
        bin=sys.executable, bin_args=BIN_ARGS, node_count=5,
        time_limit=8.0, rate=10.0, concurrency=4, recovery_time=1.0,
        seed=21))
    assert res["valid?"] is True, res["workload"]
    assert res["stats"]["ok-count"] > 30


def test_paxos_lin_kv_partitions():
    res = run_test("lin-kv", dict(
        bin=sys.executable, bin_args=BIN_ARGS, node_count=5,
        time_limit=12.0, rate=10.0, concurrency=4, latency=5.0,
        nemesis=["partition"], nemesis_interval=3.0, recovery_time=2.0,
        seed=22))
    assert res["valid?"] is True, res["workload"]
    assert res["workload"]["bad-keys"] == []
    assert res["stats"]["ok-count"] > 10
