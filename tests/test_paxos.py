"""Single-decree-Paxos lin-kv node (BASELINE.json config #4): per-key
multi-slot Paxos with full two-phase rounds per op, linearizable with
and without partitions."""

import os
import sys

from maelstrom_tpu import run_test
import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BIN_ARGS = [os.path.join(REPO, "examples", "python", "paxos.py")]


def test_paxos_lin_kv_5n():
    res = run_test("lin-kv", dict(
        bin=sys.executable, bin_args=BIN_ARGS, node_count=5,
        time_limit=8.0, rate=10.0, concurrency=4, recovery_time=1.0,
        seed=21))
    assert res["valid?"] is True, res["workload"]
    assert res["stats"]["ok-count"] > 30


@pytest.mark.slow
def test_paxos_lin_kv_partitions(tmp_path):
    """Regression for the cross-round closure-poisoning bug: under dense
    contention + partitions, a late promise reply from round k used to
    write into round k+1's adoption cell (shared closure variable),
    making the proposer accept the wrong value — same-slot conflicting
    decides, divergent logs, WGL violation. This config reproduced it
    2/2 before the fix; we assert both the checker verdict AND zero
    conflicting decides in the wire journal."""
    res = run_test("lin-kv", dict(
        bin=sys.executable, bin_args=BIN_ARGS, node_count=5,
        time_limit=12.0, rate=25.0, concurrency=8, latency=5.0,
        nemesis=["partition"], nemesis_interval=2.0, recovery_time=2.0,
        seed=22, snapshot_store=True, store_root=str(tmp_path)))
    assert res["valid?"] is True, res["workload"]
    assert res["workload"]["bad-keys"] == []
    assert res["stats"]["ok-count"] > 10

    # Paxos safety, checked at the wire: one decided value per slot.
    import collections
    import glob
    import json
    decided = collections.defaultdict(set)
    for f in glob.glob(str(tmp_path / "lin-kv" / "latest"
                           / "net-journal" / "*.jsonl")):
        for line in open(f):
            e = json.loads(line)
            b = e["message"]["body"]
            if e["type"] == "send" and b.get("type") == "decide":
                decided[(b["key"], b["slot"])].add(
                    json.dumps(b["value"], sort_keys=True))
    conflicts = {ks: vs for ks, vs in decided.items() if len(vs) > 1}
    assert not conflicts, conflicts
