"""Live observatory tests: streaming heartbeat (telemetry/stream.py),
fail-fast dispatch, and violation forensics (checkers/triage.py).

Pins the PR's acceptance bars: >=1 heartbeat record per chunk in both
the single-device and sharded chunk drivers, trajectories bit-identical
with the heartbeat on/off, `--fail-fast` stopping dispatch within one
chunk of the device-detected violation, and `maelstrom triage` naming
the violating instance and emitting its spacetime SVG + repro bundle —
including on a partial run dir that never got a results.json (the
crash/kill semantics: heartbeat.jsonl is valid as a prefix).
"""

import json
import os

import jax
import numpy as np
import pytest

from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.models.raft_buggy import RaftDoubleVote
from maelstrom_tpu.telemetry.stream import (HeartbeatWriter,
                                            combine_shard_scans,
                                            first_violation_of,
                                            flagged_instances,
                                            read_heartbeat,
                                            render_watch_report)
from maelstrom_tpu.tpu.harness import make_sim_config, run_tpu_test
from maelstrom_tpu.tpu.pipeline import (expand_compact_events,
                                        plan_chunks, run_sim_pipelined)

pytestmark = pytest.mark.triage

# the planted violating model: double-vote raft under partitions trips
# the on-device two-leaders invariant at tick 82 of this exact config
# (instances 6 and 13 by tick 150) — the forensics fixture every test
# here shares (models/raft_buggy.py bug-injection corpus)
BUGGY_OPTS = dict(node_count=3, concurrency=6, n_instances=16,
                  record_instances=4, inbox_k=1, pool_slots=16,
                  time_limit=0.3, rate=200.0, latency=5.0,
                  rpc_timeout=1.0, nemesis=["partition"],
                  nemesis_interval=0.04, p_loss=0.05, recovery_time=0.0,
                  seed=7, funnel=False, pipeline="on", chunk_ticks=50)

ECHO_OPTS = dict(node_count=2, concurrency=2, n_instances=8,
                 record_instances=2, time_limit=0.3, rate=100.0,
                 latency=5.0, seed=3, funnel=False, pipeline="on",
                 chunk_ticks=100)


def _buggy_model():
    return RaftDoubleVote(n_nodes_hint=3, log_cap=64, heartbeat=8)


@pytest.fixture(scope="module")
def failfast_run(tmp_path_factory):
    """One stored fail-fast run of the planted mutant, shared by the
    heartbeat/triage tests below."""
    store = str(tmp_path_factory.mktemp("failfast-store"))
    results = run_tpu_test(_buggy_model(),
                           {**BUGGY_OPTS, "fail_fast": True,
                            "store_root": store})
    return results, results["store-dir"]


@pytest.fixture(scope="module")
def echo_run(tmp_path_factory):
    store = str(tmp_path_factory.mktemp("echo-store"))
    results = run_tpu_test(EchoModel(),
                           {**ECHO_OPTS, "store_root": store})
    return results, results["store-dir"]


# --- heartbeat streaming ---------------------------------------------------


def test_heartbeat_streams_one_record_per_chunk(echo_run):
    results, run_dir = echo_run
    hb = read_heartbeat(run_dir)
    assert hb["skipped"] == 0
    header, end = hb["header"], hb["end"]
    assert header is not None and end is not None
    n_chunks = len(plan_chunks(300, ECHO_OPTS["chunk_ticks"]))
    assert n_chunks >= 2   # the bar is defined over multi-chunk runs
    assert len(hb["chunks"]) == n_chunks
    # schema: every chunk record is self-contained
    for i, rec in enumerate(hb["chunks"]):
        assert rec["chunk"] == i
        assert rec["ticks"] > 0
        assert set(rec["net"]) == {"sent", "delivered",
                                   "dropped-partition", "dropped-loss",
                                   "dropped-overflow"}
        assert rec["first-violation"] is None   # echo is clean
        assert rec["events-overflowed"] is False
    # net counters are cumulative: the last record equals the final
    # fleet NetStats the results.json reports
    last = hb["chunks"][-1]["net"]
    assert last["sent"] == results["net"]["sent"]
    assert last["delivered"] == results["net"]["delivered"]
    assert end["status"] == "complete"
    assert end["valid?"] is True
    assert header["workload"] == "echo"
    assert header["opts"]["seed"] == ECHO_OPTS["seed"]


@pytest.mark.parametrize("layout", ["lead", "minor"])
def test_heartbeat_bit_identity_unsharded(tmp_path, layout):
    """Heartbeat + violation scan are observational: carry and decoded
    histories are bit-identical with the writer on or off, in both
    carry layouts."""
    model = EchoModel()
    sim = make_sim_config(model, {**ECHO_OPTS, "layout": layout})
    params = model.make_params(sim.net.n_nodes)
    base = run_sim_pipelined(model, sim, 3, params, chunk=100)
    hb = HeartbeatWriter(str(tmp_path), meta={"workload": "echo"})
    with_hb = run_sim_pipelined(model, sim, 3, params, chunk=100,
                                heartbeat=hb)
    hb.finish()
    for a, b in zip(jax.tree.leaves(base.carry),
                    jax.tree.leaves(with_hb.carry)):
        assert (np.asarray(a) == np.asarray(b)).all()
    assert (base.events == with_hb.events).all()
    rec = read_heartbeat(str(tmp_path))
    assert len(rec["chunks"]) == len(plan_chunks(sim.n_ticks, 100))


def test_heartbeat_sharded_chunked(tmp_path):
    """The sharded chunk driver streams the same heartbeat — one record
    per chunk, net summed over shards — and stays bit-identical to the
    no-heartbeat run."""
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked)
    if len(jax.devices()) < 4:
        pytest.skip("needs a >=4-device virtual mesh")
    model = EchoModel()
    opts = {**ECHO_OPTS, "n_instances": 4, "time_limit": 0.12}
    sim = make_sim_config(model, opts)
    mesh = make_mesh(4)
    stats0, viol0, ev0 = run_sim_sharded_chunked(
        model, sim, seed=3, mesh=mesh, chunk=40)
    hb = HeartbeatWriter(str(tmp_path), meta={"workload": "echo"})
    perf = {}
    stats1, viol1, ev1 = run_sim_sharded_chunked(
        model, sim, seed=3, mesh=mesh, chunk=40, heartbeat=hb,
        perf=perf)
    hb.finish()
    assert tuple(jax.tree.map(int, stats0)) == \
        tuple(jax.tree.map(int, stats1))
    assert (viol0 == viol1).all() and (ev0 == ev1).all()
    rec = read_heartbeat(str(tmp_path))
    assert len(rec["chunks"]) == len(plan_chunks(sim.n_ticks, 40))
    assert rec["chunks"][-1]["net"]["delivered"] == int(stats1.delivered)
    assert all(r["first-violation"] is None for r in rec["chunks"])


class TickBombModel(EchoModel):
    """Echo with a per-node tick counter whose invariant trips at a
    KNOWN tick on every instance — the cheapest deterministic planted
    violation for exercising the sharded fail-fast path."""
    name = "echo-tick-bomb"
    BOOM = 60

    def tick(self, row, node_idx, t, key, cfg, params):
        import jax.numpy as jnp
        return row + 1, jnp.zeros((self.tick_out, cfg.lanes),
                                  dtype=jnp.int32)

    def invariants(self, node_state, cfg, params):
        import jax.numpy as jnp
        return jnp.any(node_state >= self.BOOM)


def test_fail_fast_sharded(tmp_path):
    """The sharded driver's fail-fast: the psum'd/merged violation scan
    stops dispatch within one chunk, and the heartbeat names the
    (globally-indexed) tripping instance."""
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked)
    if len(jax.devices()) < 4:
        pytest.skip("needs a >=4-device virtual mesh")
    model = TickBombModel()
    opts = {**ECHO_OPTS, "n_instances": 4, "time_limit": 0.2}
    sim = make_sim_config(model, opts)
    mesh = make_mesh(4)
    hb = HeartbeatWriter(str(tmp_path), meta={"workload": model.name})
    perf = {}
    stats, viol, ev = run_sim_sharded_chunked(
        model, sim, seed=3, mesh=mesh, chunk=40, heartbeat=hb,
        fail_fast=True, perf=perf)
    hb.finish(status="stopped")
    # trip at tick 60 -> inside chunk 1 (40..79); its consume happens
    # with chunk 2 already in flight; nothing is dispatched after it
    assert perf["stopped-early"] is True
    assert perf["ticks-dispatched"] == 120 < sim.n_ticks
    assert ev.shape[0] == 120
    assert (viol > 0).all()   # every instance's counter hit BOOM
    rec = read_heartbeat(str(tmp_path))
    v = first_violation_of(rec)
    # all 16 merged instances tripped; the counter reaches BOOM after
    # the tick-59 update (row == t + 1), and the cross-shard merge
    # breaks the all-shards tie toward the lowest global id
    assert v["instances"] == 16
    assert v["instance"] == 0
    assert v["tick"] == 59


def test_combine_shard_scans_globalizes_instances():
    I = 8   # instances per shard
    # legacy [n_shards, 3] single-lane wire format reads as K=1
    scans = np.array([[0, -1, -1],       # clean shard
                      [2, 90, 3],        # shard 1: first trip t=90 @ 3
                      [1, 82, 5],        # shard 2: earliest, local 5
                      [0, -1, -1]], np.int32)
    out = combine_shard_scans(scans, I)
    assert out.shape == (1, 3)
    assert out[0].tolist() == [3, 82, 2 * I + 5]
    # telemetry-off runs report tick -1: lowest global id wins
    out = combine_shard_scans(np.array([[0, -1, -1], [1, -1, 6],
                                        [2, -1, 1]], np.int32), I)
    assert out[0].tolist() == [3, -1, 1 * I + 6]
    out = combine_shard_scans(np.zeros((3, 3), np.int32), I)
    assert out[0].tolist() == [0, -1, -1]


def test_combine_shard_scans_top_k_merge():
    """[n_shards, K, 3] scans merge into one globally-ranked top-K
    block: rows ordered by earliest tick across shards, padding rows
    dropped, count lane = fleet-wide sum."""
    I = 8
    pad = [2, -1, -1]
    scans = np.array([
        [[0, -1, -1], [0, -1, -1]],          # clean shard
        [[2, 90, 3], [2, 95, 0]],            # shard 1: two trippers
        [[1, 82, 5], [1, -1, -1]],           # shard 2: earliest, 1 lane
    ], np.int32)
    scans[2, 1] = pad                        # padding row semantics
    out = combine_shard_scans(scans, I)
    assert out.shape == (2, 3)
    assert out[0].tolist() == [3, 82, 2 * I + 5]
    assert out[1].tolist() == [3, 90, 1 * I + 3]
    # k widens/narrows the merged block independently of the shard K
    out4 = combine_shard_scans(scans, I, k=4)
    assert out4.shape == (4, 3)
    assert out4[2].tolist() == [3, 95, 1 * I + 0]
    assert out4[3].tolist() == [3, -1, -1]   # padding past the trippers


def test_violation_scan_top_k_device():
    """violation_scan(k) names the K earliest trippers in tick order
    (row 0 == the PR-4 argmin), padding unused rows with instance -1."""
    import jax.numpy as jnp
    from maelstrom_tpu.telemetry.recorder import (TelemetryConfig,
                                                  init_telemetry)
    from maelstrom_tpu.tpu.pipeline import violation_scan
    I = 6
    violations = jnp.asarray([0, 2, 1, 0, 3, 1], jnp.int32)
    tel = init_telemetry(I, TelemetryConfig(enabled=True, n_windows=1))
    tel = tel._replace(first_violation=jnp.asarray(
        [-1, 40, 95, -1, 12, 95], jnp.int32))
    ids = jnp.arange(I, dtype=jnp.int32)
    out = np.asarray(violation_scan(violations, tel, ids, k=3))
    assert out.shape == (3, 3)
    assert out[0].tolist() == [4, 12, 4]
    assert out[1].tolist() == [4, 40, 1]
    assert out[2].tolist() == [4, 95, 2]    # tick tie -> lowest id
    # k past the tripper count pads with instance -1
    out = np.asarray(violation_scan(violations, tel, ids, k=6))
    assert out[4].tolist() == [4, -1, -1]
    # telemetry-off: lowest-id trippers, tick unknown
    out = np.asarray(violation_scan(violations, None, ids, k=2))
    assert out[0].tolist() == [4, -1, 1]
    assert out[1].tolist() == [4, -1, 2]
    # k=1 degenerates to the original argmin vector (as a [1, 3] block)
    out = np.asarray(violation_scan(violations, tel, ids))
    assert out.tolist() == [[4, 12, 4]]


# --- fail-fast -------------------------------------------------------------


def test_fail_fast_stops_within_one_chunk(failfast_run):
    results, run_dir = failfast_run
    assert results["valid?"] is False
    ff = results["fail-fast"]
    assert ff["stopped"] is True
    v = ff["first-violation"]
    assert v is not None and v["instances"] >= 1
    # the device scan named the earliest tripper of this seeded run
    assert v["tick"] == 82 and v["instance"] in (6, 13)
    # within one chunk of detection: the violation lands in the chunk
    # covering tick 82; one more chunk was already in flight when that
    # chunk's payload was consumed, and nothing was dispatched after it
    chunk = BUGGY_OPTS["chunk_ticks"]
    detect_chunk_end = (v["tick"] // chunk + 1) * chunk
    assert ff["ticks-dispatched"] <= detect_chunk_end + chunk
    assert ff["ticks-dispatched"] == 150   # deterministic for this seed
    assert ff["ticks-planned"] == 300
    # perf reports the ticks that actually EXECUTED, not the plan —
    # throughput figures on stopped runs must not be inflated
    assert results["perf"]["ticks"] == 150
    # the heartbeat agrees record-for-record
    hb = read_heartbeat(run_dir)
    assert hb["end"]["status"] == "stopped"
    assert len(hb["chunks"]) == ff["ticks-dispatched"] // chunk
    assert first_violation_of(hb)["tick"] == 82


def test_fail_fast_off_runs_full_horizon():
    results = run_tpu_test(_buggy_model(), BUGGY_OPTS)
    assert "fail-fast" not in results
    assert results["perf"]["ticks"] == 300
    assert results["valid?"] is False


# --- triage ----------------------------------------------------------------


def test_triage_names_violator_and_emits_bundle(failfast_run):
    from maelstrom_tpu.checkers.triage import triage_run
    from maelstrom_tpu.utils import edn

    results, run_dir = failfast_run
    summary = triage_run(run_dir)
    flagged = results["invariants"]["violating-instance-ids"]
    assert summary["flagged"] == flagged == [6, 13]
    assert len(summary["triaged"]) == 2
    # the bit-exactness self-check: every replayed instance re-tripped
    assert summary["replayed-violating"] == 2
    assert summary["ticks"] == 150   # the dispatched prefix, not 300
    for entry in summary["triaged"]:
        d = entry["dir"]
        assert entry["violation-ticks"] > 0
        svg = open(os.path.join(d, "messages.svg")).read()
        assert svg.startswith("<svg") or "<svg" in svg
        assert entry["journal-events"] > 0
        # journal.edn is line-delimited EDN the in-repo reader round-trips
        with open(os.path.join(d, "journal.edn")) as f:
            first = f.readline().strip()
        rec = edn.loads(first)
        assert rec["type"] in ("send", "recv")
        repro = json.load(open(os.path.join(d, "repro.json")))
        assert repro["workload"] == "lin-kv-bug-double-vote"
        assert repro["instance"] == entry["instance"]
        assert repro["opts"]["seed"] == 7
        assert repro["replay"]["args"]["instance_ids"] == \
            [entry["instance"]]
    # the replay restored the run's non-default model knobs: instance
    # 13's first trip matches the original device scan exactly
    by_id = {e["instance"]: e for e in summary["triaged"]}
    assert by_id[13]["first-violation-tick"] == 82
    assert os.path.exists(os.path.join(run_dir, "triage",
                                       "summary.json"))


def test_triage_partial_run_without_results(failfast_run, tmp_path):
    """Crash semantics: a run dir with only a heartbeat prefix (no
    results.json, no run-end record, torn final line) still watches and
    triages."""
    from maelstrom_tpu.checkers.triage import triage_run

    _, run_dir = failfast_run
    partial = str(tmp_path / "partial-run")
    os.makedirs(partial)
    # keep ONLY the heartbeat, as a killed run would: drop the run-end
    # record and tear the final chunk line mid-write
    lines = open(os.path.join(run_dir, "heartbeat.jsonl")).readlines()
    assert json.loads(lines[-1])["type"] == "run-end"
    with open(os.path.join(partial, "heartbeat.jsonl"), "w") as f:
        f.writelines(lines[:-2])
        f.write(lines[-2][:37])   # torn tail
    hb = read_heartbeat(partial)
    assert hb["end"] is None and hb["skipped"] == 1
    report = render_watch_report(hb, path=partial)
    assert "no run-end record" in report
    assert "instance 13" in report
    # triage falls back to the heartbeat's scan-named instances — the
    # top-K lanes name BOTH trippers of this run (13 first: the
    # earliest-tick row leads each chunk's scan), where the PR-4
    # argmin-only scan saw just 13
    assert flagged_instances(hb) == [13, 6]
    summary = triage_run(partial)
    assert [e["instance"] for e in summary["triaged"]] == [13, 6]
    assert summary["replayed-violating"] == 2
    d = summary["triaged"][0]["dir"]
    for name in ("messages.svg", "journal.edn", "repro.json",
                 "history.jsonl"):
        assert os.path.getsize(os.path.join(d, name)) > 0


def test_watch_and_triage_cli(failfast_run, capsys):
    from maelstrom_tpu.cli import main

    _, run_dir = failfast_run
    assert main(["watch", run_dir]) == 0
    out = capsys.readouterr().out
    assert "chunk" in out and "first violation" in out
    assert "status: stopped" in out
    assert main(["triage", run_dir, "--instance", "13"]) == 0
    out = capsys.readouterr().out
    assert "instance 13" in out
    # a dir with no heartbeat: clean error, not a traceback
    assert main(["watch", str(run_dir) + "/triage"]) == 2
    assert main(["triage", str(run_dir) + "/triage"]) == 2


def test_expand_compact_events_instance_subset():
    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    params = model.make_params(sim.net.n_nodes)
    res = run_sim_pipelined(model, sim, 3, params, chunk=100,
                            keep_compact=True)
    assert res.compact is not None
    full = expand_compact_events(model, sim, res.compact)
    assert (full == res.events).all()
    for k in range(sim.record_instances):
        sub = expand_compact_events(model, sim, res.compact,
                                    instances=[k])
        assert sub.shape[1] == 1
        assert (sub[:, 0] == full[:, k]).all()
    # reordering the subset reorders the output
    both = expand_compact_events(model, sim, res.compact,
                                 instances=[1, 0])
    assert (both[:, 0] == full[:, 1]).all()
    assert (both[:, 1] == full[:, 0]).all()


# --- crash/partial-write unit coverage -------------------------------------


def test_heartbeat_writer_crash_leaves_valid_prefix(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), meta={"workload": "w"})
    hb.record_chunk(chunk=0, t0=0, ticks=50,
                    net={"sent": 1, "delivered": 1,
                         "dropped-partition": 0, "dropped-loss": 0,
                         "dropped-overflow": 0})
    hb.record_chunk(chunk=1, t0=50, ticks=50,
                    violation={"instances": 1, "tick": 60,
                               "instance": 4})
    hb.close()   # crash path: NO run-end record
    with open(hb.path, "a") as f:
        f.write('{"type": "chunk", "chu')   # torn write
    rec = read_heartbeat(str(tmp_path))
    assert rec["header"]["workload"] == "w"
    assert len(rec["chunks"]) == 2 and rec["end"] is None
    assert rec["skipped"] == 1
    assert first_violation_of(rec) == {"instances": 1, "tick": 60,
                                       "instance": 4}


@pytest.mark.slow
def test_heartbeat_overhead_within_noise(tmp_path):
    """The bench A/B bar (BENCH_HEARTBEAT=0): the per-chunk violation
    scan + JSONL append stay within noise of the bare pipelined path on
    the bench-style echo scan. Same noise allowance as the telemetry
    overhead bar (test_telemetry.py)."""
    import time

    model = EchoModel()
    opts = dict(node_count=2, concurrency=4, n_instances=256,
                record_instances=1, time_limit=0.5, rate=200.0,
                latency=5.0, seed=7, funnel=False)
    sim = make_sim_config(model, opts)
    params = model.make_params(sim.net.n_nodes)

    def run_one(with_hb):
        best = float("inf")
        delivered = None
        for i in range(3):
            hb = None
            if with_hb:
                hb = HeartbeatWriter(path=str(tmp_path /
                                              f"hb-{i}.jsonl"),
                                     meta={"workload": "echo"})
            t0 = time.monotonic()
            res = run_sim_pipelined(model, sim, 7, params, chunk=100,
                                    heartbeat=hb)
            dt = time.monotonic() - t0
            if hb is not None:
                hb.finish()
            if i > 0:   # skip the compile-inclusive first pass
                best = min(best, dt)
            delivered = int(res.carry.stats.delivered)
        return best, delivered

    base_s, base_d = run_one(False)
    hb_s, hb_d = run_one(True)
    assert base_d == hb_d   # identical trajectories
    ratio = hb_s / base_s
    print(f"heartbeat overhead: {base_s:.3f}s -> {hb_s:.3f}s "
          f"(x{ratio:.3f})")
    assert ratio < 1.25, (base_s, hb_s)


# --- satellite regressions -------------------------------------------------


@pytest.mark.telemetry
def test_fleet_stats_degrades_without_record_or_journal(tmp_path,
                                                        capsys):
    """record_instances == 0 / journal_instances == 0 runs (whose ys
    buffers are None since the pipeline PR) must store, fleet-stat, and
    journal-report without raising on the absent leaves."""
    from maelstrom_tpu.cli import main

    store = str(tmp_path / "store")
    opts = {**ECHO_OPTS, "record_instances": 0, "journal_instances": 0,
            "store_root": store}
    results = run_tpu_test(EchoModel(), opts)
    assert results["checked-instances"] == 0
    assert "telemetry" in results
    run_dir = results["store-dir"]
    assert main(["fleet-stats", run_dir, "--no-svg"]) == 0
    out = capsys.readouterr().out
    assert "fleet: 8 instances" in out
    # journal block with zero recorded instances (J > 0, R == 0)
    r2 = run_tpu_test(EchoModel(), {**ECHO_OPTS, "record_instances": 0,
                                    "journal_instances": 2})
    assert r2["net"]["journal"]["msgs-per-op"] == 0.0
    # and the monolithic executor path degrades the same way
    r3 = run_tpu_test(EchoModel(), {**ECHO_OPTS, "record_instances": 0,
                                    "journal_instances": 2,
                                    "pipeline": "off"})
    assert r3["net"]["journal"]["stats"] == r2["net"]["journal"]["stats"]


def test_fleet_summary_empty_leaves():
    """fleet_summary on a zero-instance telemetry pytree (every leaf
    empty) degrades to zeros instead of raising on empty reductions."""
    from maelstrom_tpu.telemetry.fleet import fleet_summary
    from maelstrom_tpu.telemetry.recorder import init_telemetry

    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    tel = jax.tree.map(np.asarray, init_telemetry(0, sim.telemetry))
    m = fleet_summary(tel._replace(), sim._replace(n_instances=0))
    assert m["high-water"]["pool-occupancy"] == 0
    assert m["nemesis"]["epochs-max"] == 0
    assert m["invariants"]["tripped-instances"] == 0


def test_plot_lamport_caps_events(tmp_path):
    """Satellite: the Lamport renderer bounds its output with an
    explicit '+N elided' annotation instead of an unbounded SVG."""
    from maelstrom_tpu.net.viz import plot_lamport

    class FakeJournal:
        def events(self):
            for i in range(500):
                yield {"time": i, "type": "send" if i % 2 == 0
                       else "recv",
                       "message": {"id": i // 2, "src": "n0",
                                   "dest": "n1",
                                   "body": {"type": 1, "b": [i]}}}

    p = str(tmp_path / "m.svg")
    plot_lamport(FakeJournal(), p, max_events=100)
    svg = open(p).read()
    assert "+400 elided" in svg
    capped = svg
    plot_lamport(FakeJournal(), p)   # default cap: nothing elided here
    assert "elided" not in open(p).read()
    # the capped render is strictly bounded in rows -> in bytes
    assert len(capped) < len(open(p).read())
