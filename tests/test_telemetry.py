"""Flight-recorder telemetry: device histograms vs numpy oracles, fleet
aggregation, the fleet-stats CLI, and the lint-gate guarantee that the
telemetry carry is itself TRC/CON-clean (the first consumer-scale test
of the PR 1 contract audit)."""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.telemetry.fleet import (bucket_upper_ticks,
                                           fleet_summary, hist_quantile)
from maelstrom_tpu.telemetry.recorder import (TelemetryConfig,
                                              latency_bucket)
from maelstrom_tpu.tpu.harness import make_sim_config, run_tpu_test
from maelstrom_tpu.tpu.runtime import run_sim

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ECHO_OPTS = dict(node_count=2, concurrency=2, n_instances=8,
                 record_instances=8, time_limit=1.0, rate=100.0,
                 latency=5.0, p_loss=0.2, rpc_timeout=0.2,
                 nemesis=["partition"], nemesis_interval=0.2,
                 recovery_time=0.2, seed=5)


def np_bucket(lat, buckets):
    """Independent numpy restatement of recorder.latency_bucket."""
    lat = max(int(lat), 0)
    b = 0
    for k in range(1, buckets):
        if lat + 1 >= 2 ** k:
            b += 1
    return b


def test_latency_bucket_exact_vs_oracle():
    cfg = TelemetryConfig(hist_buckets=8)
    lats = jnp.asarray([0, 1, 2, 3, 4, 6, 7, 14, 15, 62, 126, 127,
                        1000, 10 ** 6, -3], jnp.int32)
    got = np.asarray(latency_bucket(lats, cfg))
    want = [np_bucket(int(x), 8) for x in np.asarray(lats)]
    assert got.tolist() == want
    # bucket k's inclusive range is [2^k - 1, 2^(k+1) - 2]
    uppers = bucket_upper_ticks(8)
    for k in range(7):
        assert np_bucket(uppers[k], 8) == k
        assert np_bucket(uppers[k] + 1, 8) == k + 1


def test_hist_quantile_vs_numpy_oracle():
    rng = np.random.default_rng(0)
    for _ in range(20):
        counts = rng.integers(0, 9, size=12)
        if counts.sum() == 0:
            assert hist_quantile(counts, 0.5) is None
            continue
        expanded = np.repeat(np.arange(12), counts)
        n = len(expanded)
        for q in (0.5, 0.95, 0.99, 1.0):
            i = min(n - 1, int(q * n))
            assert hist_quantile(counts, q) == int(np.sort(expanded)[i])


def test_telemetry_pytree_round_trips_scan_and_eval_shape():
    """The telemetry carry is a shape fixed point of the tick — through
    jax.eval_shape AND a real (tiny) lax.scan — and vanishes entirely
    when disabled."""
    from maelstrom_tpu.tpu.runtime import init_carry, make_tick_fn

    model = EchoModel()
    sim = make_sim_config(model, dict(
        node_count=2, concurrency=2, n_instances=4, record_instances=2,
        time_limit=0.05, rate=100.0, latency=2.0, layout="lead"))
    params = model.make_params(sim.net.n_nodes)
    c0 = init_carry(model, sim, 0, params)
    assert c0.telemetry is not None
    tick = make_tick_fn(model, sim, params)
    c1, _ = jax.eval_shape(tick, c0, jax.ShapeDtypeStruct((), jnp.int32))
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_flatten_with_path(c0.telemetry)[0],
            jax.tree_util.tree_flatten_with_path(c1.telemetry)[0]):
        assert a.shape == b.shape and a.dtype == b.dtype, pa
    cN, _ = jax.lax.scan(tick, c0, jnp.arange(10, dtype=jnp.int32))
    assert int(jnp.sum(cN.telemetry.sent)) >= 0

    sim_off = make_sim_config(model, dict(
        node_count=2, concurrency=2, n_instances=4, record_instances=2,
        time_limit=0.05, rate=100.0, latency=2.0, layout="lead",
        telemetry=False))
    assert init_carry(model, sim_off, 0, params).telemetry is None


@pytest.fixture(scope="module")
def echo_run(tmp_path_factory):
    """One echo fleet with EVERY instance recorded, so device telemetry
    is checkable against the decoded journal, plus its store artifacts."""
    store = str(tmp_path_factory.mktemp("telemetry-store"))
    res = run_tpu_test(EchoModel(), dict(ECHO_OPTS, store_root=store))
    run_dir = res["store-dir"]
    histories = []
    for p in sorted(glob.glob(os.path.join(run_dir, "history-*.jsonl"))):
        histories.append([json.loads(l) for l in open(p) if l.strip()])
    with open(os.path.join(run_dir, "fleet-metrics.json")) as f:
        metrics = json.load(f)
    return res, histories, metrics, run_dir


def test_fleet_totals_match_device_counters(echo_run):
    res, histories, metrics, _ = echo_run
    t = metrics["totals"]
    assert t["sent"] == res["net"]["sent"]
    assert t["delivered"] == res["net"]["delivered"]
    assert t["dropped-partition"] == res["net"]["dropped-partition"]
    assert t["dropped-loss"] == res["net"]["dropped-loss"]
    assert t["dropped-overflow"] == res["net"]["dropped-overflow"]
    assert t["dropped-loss"] > 0          # the config exercises loss
    assert metrics["nemesis"]["epochs-max"] >= 1


def test_fleet_counts_and_quantiles_match_journal_oracle(echo_run):
    """The acceptance bar: per-fleet invoke/ack counts and the
    ticks-to-ack histogram + quantiles in fleet-metrics.json must match
    a pure-numpy recomputation from the decoded histories (every
    instance is recorded here, so the journal covers the fleet)."""
    from maelstrom_tpu.gen.history import pairs

    res, histories, metrics, _ = echo_run
    mpt = metrics["ms-per-tick"]
    buckets = len(metrics["latency-hist"]["fleet-counts"])
    all_lats = []
    n_invokes = n_acks = 0
    oracle_hist = np.zeros(buckets, dtype=np.int64)
    for h in histories:
        for p in pairs(h):
            inv, comp = p["invoke"], p["complete"]
            n_invokes += 1
            if comp is None or comp["type"] != "ok":
                continue
            n_acks += 1
            lat = round((comp["time"] - inv["time"]) / (mpt * 1e6))
            all_lats.append(lat)
            oracle_hist[np_bucket(lat, buckets)] += 1
    assert n_invokes == metrics["totals"]["invokes"] > 0
    assert n_acks == metrics["totals"]["acks"] > 0
    assert oracle_hist.tolist() == metrics["latency-hist"]["fleet-counts"]
    uppers = bucket_upper_ticks(buckets)
    srt = sorted(all_lats)
    for q in (0.5, 0.95, 0.99, 1.0):
        i = min(len(srt) - 1, int(q * len(srt)))
        assert metrics["latency-ticks"][str(q)] \
            == uppers[np_bucket(srt[i], buckets)], q


def test_per_instance_histograms_match_each_history(echo_run):
    """Stronger than the fleet check: instance i's device histogram is
    exactly the bucketed ok-latencies of instance i's own history."""
    from maelstrom_tpu.gen.history import pairs
    from maelstrom_tpu.models.echo import EchoModel as _E

    res, histories, metrics, _ = echo_run
    sim = make_sim_config(_E(), ECHO_OPTS)
    carry, _ys = run_sim(_E(), sim, ECHO_OPTS["seed"],
                         _E().make_params(sim.net.n_nodes))
    hist = np.asarray(carry.telemetry.rpc_hist)
    buckets = hist.shape[1]
    for i, h in enumerate(histories):
        oracle = np.zeros(buckets, dtype=np.int64)
        for p in pairs(h):
            comp = p["complete"]
            if comp is None or comp["type"] != "ok":
                continue
            lat = round((comp["time"] - p["invoke"]["time"]) / 1e6)
            oracle[np_bucket(lat, buckets)] += 1
        assert hist[i].tolist() == oracle.tolist(), f"instance {i}"


def test_series_windows_sum_to_totals(echo_run):
    res, histories, metrics, _ = echo_run
    ser = metrics["series"]
    windows = np.asarray(ser["windows"], dtype=np.int64)
    lanes = {n: i for i, n in enumerate(ser["lanes"])}
    for name in ("delivered", "sent", "invokes", "acks"):
        assert int(windows[:, lanes[name]].sum()) \
            == metrics["totals"][name], name


def test_fleet_stats_cli_smoke(echo_run, capsys):
    from maelstrom_tpu.cli import main as cli_main

    _res, _h, metrics, run_dir = echo_run
    rc = cli_main(["fleet-stats", run_dir])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ticks-to-ack" in out and "dropped" in out
    for name in ("fleet-rate.svg", "fleet-drops.svg",
                 "fleet-latency.svg", "fleet-metrics.json"):
        p = os.path.join(run_dir, name)
        assert os.path.exists(p) and os.path.getsize(p) > 100, name
    # a bogus path is a clean error, not a traceback
    assert cli_main(["fleet-stats", os.path.join(run_dir, "nope")]) == 2


def test_no_telemetry_run_has_no_artifacts(tmp_path):
    res = run_tpu_test(EchoModel(), dict(
        node_count=2, concurrency=2, n_instances=4, record_instances=2,
        time_limit=0.3, rate=100.0, latency=5.0, seed=3,
        telemetry=False, store_root=str(tmp_path)))
    assert "telemetry" not in res
    assert not os.path.exists(os.path.join(res["store-dir"],
                                           "fleet-metrics.json"))


def test_telemetry_carry_is_lint_clean():
    """The lint-gate satellite: the flight recorder is a traced surface
    and must be TRC-clean by the PR 1 rules, and the telemetry-bearing
    tick carry must audit CON-clean (fixed point, lane contracts)."""
    from maelstrom_tpu.analysis.contract_audit import audit_model
    from maelstrom_tpu.analysis.trace_lint import run_trace_lint

    findings = run_trace_lint(
        REPO, ["maelstrom_tpu/telemetry/recorder.py"])
    assert findings == [], [f.message for f in findings]
    audit = audit_model(EchoModel(), 2)
    assert audit == [], [f.message for f in audit]


@pytest.mark.slow
def test_telemetry_overhead_bounded():
    """Steady-state tick-loop overhead of the flight recorder, measured
    compile-free on a bench-like echo config. The acceptance bar is 10%;
    the assert allows CI scheduling noise on top (the measured ratio is
    printed and recorded in doc/observability.md)."""
    import time

    from maelstrom_tpu.tpu.runtime import init_carry, make_tick_fn

    model = EchoModel()
    opts = dict(node_count=2, concurrency=4, n_instances=256,
                record_instances=1, time_limit=0.5, rate=200.0,
                latency=5.0, seed=7)

    def run_one(telemetry):
        sim = make_sim_config(model, dict(opts, telemetry=telemetry))
        params = model.make_params(sim.net.n_nodes)
        tick = make_tick_fn(model, sim, params)

        @jax.jit
        def scan(c):
            return jax.lax.scan(
                tick, c, jnp.arange(sim.n_ticks, dtype=jnp.int32))[0]

        carry = init_carry(model, sim, 7, params)
        jax.block_until_ready(scan(carry))        # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.monotonic()
            jax.block_until_ready(scan(carry))
            best = min(best, time.monotonic() - t0)
        return best

    base = run_one(False)
    with_tel = run_one(True)
    ratio = with_tel / base
    print(f"telemetry overhead: {base:.3f}s -> {with_tel:.3f}s "
          f"(x{ratio:.3f})")
    assert ratio < 1.25, (base, with_tel)
