"""Docs-as-tests for the teaching guide (VERDICT r3 next #5).

Every chapter under doc/guide/ embeds its measured example runs as
``<!-- guide-test {...} -->`` markers (config + expectations). This
suite re-runs each embedded config and asserts the chapter's claims
still hold — the reference's golden-walkthrough pattern
(/root/reference/doc/03-broadcast/02-performance.md:22-28), where stats
printed in the guide double as regression fixtures. Expectations are
ranges, not exact counts: subprocess scheduling makes process-runtime
numbers wobble; a chapter claiming ~2.9 msgs/op must stay in [2.2, 3.9],
not reproduce 2.93.
"""

import glob
import json
import os
import re

import pytest

from conftest import REPO, example_bin

MARKER = re.compile(r"<!--\s*guide-test\s*(\{.*?\})\s*-->", re.S)


def collect_specs():
    specs = []
    for path in sorted(glob.glob(os.path.join(REPO, "doc", "guide",
                                              "*.md"))):
        text = open(path).read()
        for m in MARKER.finditer(text):
            try:
                spec = json.loads(m.group(1))
            except json.JSONDecodeError as e:
                raise AssertionError(
                    f"unparseable guide-test marker in {path}: {e}")
            spec["_file"] = os.path.basename(path)
            specs.append(spec)
    return specs


SPECS = collect_specs()


def test_guide_has_chapters_with_tests():
    """>=5 chapters exist and >=6 of them carry embedded tested stats."""
    chapters = glob.glob(os.path.join(REPO, "doc", "guide", "*.md"))
    assert len(chapters) >= 5, chapters
    assert len(SPECS) >= 6
    assert len({s["_file"] for s in SPECS}) >= 5


def _check_range(actual, bound, label):
    if isinstance(bound, list):
        lo, hi = bound
        assert lo <= actual <= hi, f"{label}: {actual} not in [{lo},{hi}]"
    elif isinstance(bound, dict) and "min" in bound:
        assert actual >= bound["min"], f"{label}: {actual} < {bound['min']}"
    else:
        assert actual == bound, f"{label}: {actual} != {bound}"


@pytest.mark.slow
@pytest.mark.parametrize("spec", SPECS, ids=lambda s: s["id"])
def test_guide_embedded_config(spec):
    expect = spec["expect"]
    if spec.get("runtime") in ("tpu", "native"):
        # the vectorized runtimes share a results shape; only the
        # harness call differs
        if spec["runtime"] == "tpu":
            from maelstrom_tpu.models import get_model
            from maelstrom_tpu.tpu.harness import run_tpu_test
            model = get_model(spec["workload"],
                              spec["opts"].get("node_count", 1), "grid")
            res = run_tpu_test(model, dict(spec["opts"]))
        else:
            from maelstrom_tpu.native import native_available
            if not native_available():
                pytest.skip("native engine unavailable "
                            "(no C++ toolchain)")
            from maelstrom_tpu.native.harness import run_native_test
            res = run_native_test(dict(spec["opts"],
                                       workload=spec["workload"]))
        if "delivered_min" in expect:
            assert res["net"]["delivered"] >= expect["delivered_min"], \
                res["net"]
        if "violating" in expect:
            assert (res["invariants"]["violating-instances"]
                    == expect["violating"]), res["invariants"]
        if "invalid_instances_min" in expect:
            n_bad = sum(1 for i in res["instances"]
                        if i.get("valid?") is False)
            assert n_bad >= expect["invalid_instances_min"], \
                res["instances"]
    else:
        from maelstrom_tpu.runner import run_test
        bin_cmd = example_bin(spec["node"])
        res = run_test(spec["workload"], dict(
            bin=bin_cmd[0],
            bin_args=bin_cmd[1:] + spec.get("node_args", []),
            snapshot_store=False, **spec["opts"]))
        if "ok_min" in expect:
            assert res["stats"]["ok-count"] >= expect["ok_min"], \
                res["stats"]
        if "msgs_per_op" in expect:
            _check_range(res["net"]["msgs-per-op"],
                         expect["msgs_per_op"], "msgs-per-op")
        for key, bound in (expect.get("w") or {}).items():
            _check_range(res["workload"].get(key), bound, f"workload.{key}")
    if "valid" in expect:
        assert res["valid?"] is expect["valid"], \
            (res.get("workload"), res.get("invariants"))
