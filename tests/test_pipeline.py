"""The chunked donated executor (tpu/pipeline.py): bit-identity against
the monolithic scan, compacted-event correctness, donation safety, and
the sharded-telemetry surfacing that rides the same PR.

The pipeline's contract is that chunking, donation, and event
compaction are pure execution-strategy changes: final carry and decoded
histories must match the single-dispatch ``run_sim`` bit-for-bit in
BOTH carry layouts, compacted events must expand to the dense oracle's
nonempty rows exactly, and capacity overflow must be *flagged* rather
than silently truncating a "valid" verdict.
"""

import jax
import jax.numpy as jnp
import jax.tree_util as tu
import numpy as np
import pytest

from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.models.raft import RaftModel
from maelstrom_tpu.tpu.harness import (events_to_histories,
                                       make_sim_config, resolve_pipeline,
                                       run_tpu_test)
from maelstrom_tpu.tpu.pipeline import (event_capacity,
                                        expand_compact_events,
                                        plan_chunks, run_sim_pipelined,
                                        _make_chunk_fn)
from maelstrom_tpu.tpu.runtime import EV_NONE, canonical_carry, run_sim

pytestmark = pytest.mark.pipeline

BASE_OPTS = dict(node_count=3, concurrency=6, n_instances=16,
                 record_instances=4, inbox_k=1, pool_slots=16,
                 time_limit=0.12, rate=200.0, latency=5.0,
                 rpc_timeout=1.0, nemesis=["partition"],
                 nemesis_interval=0.04, p_loss=0.05, recovery_time=0.0,
                 seed=7)


def _model():
    return RaftModel(n_nodes_hint=3, log_cap=64, heartbeat=8)


def _assert_trees_equal(a, b):
    for (path, x), (_, y) in zip(tu.tree_flatten_with_path(a)[0],
                                 tu.tree_flatten_with_path(b)[0]):
        name = "/".join(str(p) for p in path)
        assert x.shape == y.shape, (name, x.shape, y.shape)
        assert (np.asarray(x) == np.asarray(y)).all(), name


def _dense_oracle(events):
    """Dense events with the lanes the compact stream does not carry
    nulled: the msg-id lane (never read by the history decoder) and the
    stale value lanes of EV_NONE rows (client_step writes value lanes
    unconditionally and gates only the type lane)."""
    oracle = np.asarray(events).copy()
    oracle[..., -1] = 0
    oracle[oracle[..., 0] == EV_NONE] = 0
    return oracle


def test_plan_chunks_prefers_divisor():
    # 120 ticks at chunk=100 -> one 100 + one 20 would double-compile;
    # the planner drops to the divisor 60
    assert plan_chunks(120, 100) == [(0, 60), (60, 60)]
    assert plan_chunks(200, 100) == [(0, 100), (100, 100)]
    # no divisor in [50, 100] for 101 (prime): tail chunk accepted
    assert plan_chunks(101, 100) == [(0, 100), (100, 1)]
    assert plan_chunks(40, 100) == [(0, 40)]


@pytest.mark.parametrize("layout", ["lead", "minor"])
def test_pipelined_bit_identity(layout):
    model = _model()
    opts = {**BASE_OPTS, "layout": layout}
    sim = make_sim_config(model, opts)
    params = model.make_params(sim.net.n_nodes)
    carry_m, ys = run_sim(model, sim, opts["seed"], params)
    res = run_sim_pipelined(model, sim, opts["seed"], params, chunk=40)
    _assert_trees_equal(canonical_carry(carry_m, sim),
                        canonical_carry(res.carry, sim))
    # decoded histories — the checker input — are identical
    hm = events_to_histories(model, np.asarray(ys.events),
                             final_start=sim.client.final_start)
    hp = events_to_histories(model, res.events,
                             final_start=sim.client.final_start)
    assert hm == hp
    # the run exercised real traffic, so the equality is meaningful
    assert int(res.carry.stats.delivered) > 100


def test_compact_events_match_dense_oracle():
    model = _model()
    sim = make_sim_config(model, BASE_OPTS)
    params = model.make_params(sim.net.n_nodes)
    _, ys = run_sim(model, sim, 7, params)
    res = run_sim_pipelined(model, sim, 7, params, chunk=40)
    assert res.perf["overflowed-chunks"] == 0
    assert (res.events == _dense_oracle(ys.events)).all()
    # and the stream actually compacted: fewer bytes than the dense
    # tensor (the >=10x bar at default record/rate settings is held by
    # test_default_settings_fetch_reduction below)
    assert res.perf["event-bytes-fetched"] < res.perf["event-bytes-dense"]


def test_compaction_overflow_flagged():
    model = _model()
    sim = make_sim_config(model, BASE_OPTS)
    params = model.make_params(sim.net.n_nodes)
    res = run_sim_pipelined(model, sim, 7, params, chunk=40, event_cap=8)
    # 8 rows per 40-tick chunk is far under the real event volume:
    # every chunk must flag, and the run must not crash or mis-shape
    assert res.perf["overflowed-chunks"] >= 1
    assert res.events.shape[0] == sim.n_ticks
    # the flagged truncation surfaces on run_tpu_test results too
    results = run_tpu_test(model, {**BASE_OPTS, "pipeline": "on",
                                   "chunk_ticks": 40,
                                   "event_capacity": 8,
                                   "funnel": False})
    assert results["events-truncated"] is True


def test_use_after_donate_regression():
    """The chunk dispatch donates the carry: the executor must never
    touch a consumed buffer again, and a caller reusing one must get a
    loud error, not stale data."""
    model = _model()
    sim = make_sim_config(model, BASE_OPTS)
    params = model.make_params(sim.net.n_nodes)
    from maelstrom_tpu.tpu.runtime import init_carry
    chunk_fn = _make_chunk_fn(model, sim, params, None, 64, 1)
    carry0 = jax.tree.map(lambda x: x.copy(),
                          init_carry(model, sim, 7, params))
    pool0 = carry0.pool
    carry1, svec, scan, buf, _ = chunk_fn(carry0, jnp.int32(0), 40)
    if not pool0.is_deleted():
        pytest.skip("backend did not donate the carry buffer")
    # the donated input is gone — reuse must raise, not return garbage
    with pytest.raises(RuntimeError):
        np.asarray(pool0)
    # the detached stats + violation-scan snapshots stay readable after
    # the NEXT chunk donates carry1 away (the overlapped bench loop and
    # the run heartbeat both depend on this)
    carry2, svec2, scan2, _, _ = chunk_fn(carry1, jnp.int32(40), 40)
    # top-K violation lanes ([K, 3], default K=8; row 0 = the argmin)
    from maelstrom_tpu.tpu.pipeline import DEFAULT_SCAN_TOP_K
    assert np.asarray(scan).shape == (DEFAULT_SCAN_TOP_K, 3)
    assert np.asarray(scan2).shape == (DEFAULT_SCAN_TOP_K, 3)
    assert carry1.pool.is_deleted()
    d1 = int(np.asarray(svec)[1])
    d2 = int(np.asarray(svec2)[1])
    assert d2 >= d1 >= 0
    assert int(jax.block_until_ready(carry2).stats.delivered) == d2
    # and the full executor runs the same horizon without ever touching
    # a donated buffer (a use-after-donate inside would raise here)
    res = run_sim_pipelined(model, sim, 7, params, chunk=40)
    assert int(res.carry.stats.delivered) > 0


def test_record_zero_skips_event_buffers():
    """Fleet-stats-only runs (record_instances == 0) materialize no
    event or journal ys at all — not even zero-size arrays."""
    model = _model()
    sim = make_sim_config(model, {**BASE_OPTS, "record_instances": 0})
    params = model.make_params(sim.net.n_nodes)
    _, ys = run_sim(model, sim, 7, params)
    assert ys.events is None
    assert ys.journal_sends is None and ys.journal_recvs is None
    res = run_sim_pipelined(model, sim, 7, params, chunk=40)
    assert res.perf["event-bytes-fetched"] == 0
    assert res.events.shape[1] == 0
    # harness end-to-end: telemetry still ships, histories are empty
    results = run_tpu_test(model, {**BASE_OPTS, "record_instances": 0,
                                   "pipeline": "on", "chunk_ticks": 40,
                                   "funnel": False})
    assert results["checked-instances"] == 0
    assert "telemetry" in results


def test_default_settings_fetch_reduction():
    """The acceptance bar: at the harness's default record/rate
    settings the reported event fetch bytes drop >= 10x vs the dense
    tensor the monolithic path ships."""
    model = EchoModel()
    from maelstrom_tpu.tpu.harness import TPU_DEFAULTS
    opts = dict(node_count=2, time_limit=1.0, n_instances=16, seed=3,
                pipeline="on", funnel=False)
    # rate/concurrency/record_instances/chunk_ticks stay at defaults —
    # that is what the bar is defined over
    assert TPU_DEFAULTS["rate"] == 100.0
    assert TPU_DEFAULTS["record_instances"] == 8
    results = run_tpu_test(model, opts)
    pipe = results["perf"]["phases"]["pipeline"]
    assert pipe["overflowed-chunks"] == 0
    assert pipe["fetch-reduction-x"] >= 10.0
    assert results["valid?"] is True


def test_run_tpu_test_pipeline_off_on_agree():
    """The harness-level A/B: identical verdicts, net counters, and
    per-instance results whichever executor runs."""
    model = _model()
    opts = {**BASE_OPTS, "funnel": False}
    r_off = run_tpu_test(model, {**opts, "pipeline": "off"})
    r_on = run_tpu_test(model, {**opts, "pipeline": "on",
                                "chunk_ticks": 40})
    assert r_off["net"] == r_on["net"]
    assert r_off["instances"] == r_on["instances"]
    assert r_off["valid?"] == r_on["valid?"]
    assert r_off["invariants"] == r_on["invariants"]
    assert "pipeline" in r_on["perf"]["phases"]
    assert "pipeline" not in r_off["perf"]["phases"]


def test_resolve_pipeline_auto():
    model = _model()
    short = make_sim_config(model, {**BASE_OPTS, "time_limit": 0.1})
    long = make_sim_config(model, {**BASE_OPTS, "time_limit": 0.4})
    assert not resolve_pipeline(short, {"chunk_ticks": 100,
                                        "pipeline": "auto"})
    assert resolve_pipeline(long, {"chunk_ticks": 100,
                                   "pipeline": "auto"})
    assert resolve_pipeline(short, {"pipeline": "on"})
    assert not resolve_pipeline(long, {"pipeline": "off"})


def test_event_capacity_auto_bounds():
    model = _model()
    sim = make_sim_config(model, BASE_OPTS)
    cap = event_capacity(sim, model, 100)
    dense_rows = 100 * sim.record_instances * sim.client.n_clients * 2
    assert 0 < cap <= dense_rows
    # degenerate rate-1 config: capacity clamps at the dense row count
    sim_hot = sim._replace(client=sim.client._replace(rate=1.0))
    assert event_capacity(sim_hot, model, 100) == \
        100 * sim.record_instances * sim.client.n_clients * 2


def test_expand_compact_events_roundtrip_empty():
    model = _model()
    sim = make_sim_config(model, BASE_OPTS)
    dense = expand_compact_events(model, sim, [])
    assert dense.shape == (sim.n_ticks, sim.record_instances,
                           sim.client.n_clients, 2, 2 + model.ev_vals)
    assert not dense.any()


# --- sharded-runner telemetry surfacing (ROADMAP open item, PR 2) ----------

def test_sharded_runners_surface_merged_telemetry():
    from maelstrom_tpu.parallel.mesh import (make_mesh, run_sim_sharded,
                                             run_sim_sharded_chunked,
                                             run_sim_unsharded)
    if len(jax.devices()) < 4:
        pytest.skip("needs a >=4-device virtual mesh")
    model = _model()
    opts = {**BASE_OPTS, "n_instances": 4, "record_instances": 2}
    sim = make_sim_config(model, opts)
    mesh = make_mesh(4)
    stats_u, viol_u, ev_u, tel_u = run_sim_unsharded(
        model, sim, seed=7, n_shards=4, return_telemetry=True)
    assert tel_u is not None
    # single-dispatch sharded runner
    stats_s, viol_s, ev_s, tel_s = run_sim_sharded(
        model, sim, seed=7, mesh=mesh, return_telemetry=True)
    assert tuple(jax.tree.map(int, stats_s)) == \
        tuple(jax.tree.map(int, stats_u))
    assert tel_s.sent.shape == (16,)   # 4 shards x 4 instances, merged
    _assert_trees_equal(jax.tree.map(np.asarray, tel_s), tel_u)
    # chunked sharded runner (unified executor)
    perf = {}
    stats_c, viol_c, ev_c, tel_c = run_sim_sharded_chunked(
        model, sim, seed=7, mesh=mesh, chunk=40,
        return_telemetry=True, perf=perf)
    assert (ev_c == ev_u).all() and (viol_c == viol_u).all()
    _assert_trees_equal(tel_c, tel_u)
    # the shared chunk driver reported its dispatch stats
    assert perf["chunks"] == len(plan_chunks(sim.n_ticks, 40))
    # telemetry totals agree with the psum'd NetStats the runners
    # always returned (same per-tick deltas, different reductions)
    assert int(tel_u.delivered.sum()) == int(stats_u.delivered)
    # legacy 3-tuple call signatures are unchanged
    assert len(run_sim_sharded(model, sim, seed=7, mesh=mesh)) == 3
    assert len(run_sim_unsharded(model, sim, seed=7, n_shards=4)) == 3
