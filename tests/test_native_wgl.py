"""Native WGL core (cpp/checker/libwgl.so, ctypes) differential-tested
against the pure-Python search on randomized register histories — same
cross-validation discipline as the device netsim vs host oracle."""

import random

import pytest

from maelstrom_tpu.checkers import native
from maelstrom_tpu.checkers.linearizable import (
    _collect_ops, check_register_history)

pytestmark = pytest.mark.skipif(native._load() is None,
                                reason="no C++ toolchain")


def _random_history(rng, n_ops=14, n_procs=4, n_vals=3,
                    corrupt=False):
    h, i, t = [], 0, 0
    pending = {}
    for _ in range(n_ops):
        t += 1
        p = rng.randrange(n_procs)
        if p in pending:
            f, v = pending.pop(p)
            ctype = rng.choice(["ok", "ok", "ok", "info", "fail"])
            if f == "read" and ctype == "ok":
                v = [v[0], rng.randrange(n_vals) if corrupt or
                     rng.random() < 0.7 else None]
            h.append({"process": p, "type": ctype, "f": f, "value": v,
                      "index": i, "time": t})
        else:
            f = rng.choice(["read", "write", "cas"])
            if f == "read":
                v = [0, None]
            elif f == "write":
                v = [0, rng.randrange(n_vals)]
            else:
                v = [0, [rng.randrange(n_vals), rng.randrange(n_vals)]]
            h.append({"process": p, "type": "invoke", "f": f, "value": v,
                      "index": i, "time": t})
            pending[p] = (f, v)
        i += 1
    return h


@pytest.mark.parametrize("seed", range(40))
def test_native_matches_python_verdict(seed):
    rng = random.Random(seed)
    h = _random_history(rng, corrupt=(seed % 2 == 0))
    ops = _collect_ops(h, 0)
    py = check_register_history(ops, budget_states=10_000_000)
    nat = native.check_register_history_native(ops, 10_000_000)
    assert nat is not None, "native path unexpectedly unavailable"
    assert nat == py, (seed, nat, py)
