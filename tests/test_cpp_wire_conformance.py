"""Executing wire conformance for the C++ SDK + nodes.

Unlike the JS SDK (no runtime in this image — statically analyzed in
test_js_wire_conformance.py), the C++ nodes COMPILE AND RUN here, so
they get the stronger treatment: each binary is spawned directly and
driven over its real STDIN/STDOUT — the injected-fake-stdio unit-test
pattern of the reference's Go SDK tests
(/root/reference/demo/go/node_test.go:19-37), with this harness playing
BOTH the client and the built-in services a node calls. Replies are
validated against the schema registry (reply type + field sets), plus
the protocol edges: init handshake, in_reply_to plumbing, error 10 for
unsupported types (VERDICT r3 next #10).

No Go toolchain exists in this image (`which go` is empty), so the
conditional Go-SDK half of that item does not apply.
"""

import json
import os
import queue
import subprocess
import threading

import pytest

import maelstrom_tpu.workloads  # noqa: F401 — populate the registry
from maelstrom_tpu.core.schema import REGISTRY, Opt

TIMEOUT = 10.0


class FakeNet:
    """Drive one node binary over its pipes: send messages as any src,
    receive whatever the node emits (to us or to peers/services)."""

    def __init__(self, path):
        self.proc = subprocess.Popen(
            [path], stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True, bufsize=1)
        self.q = queue.Queue()
        self.next_id = 100
        t = threading.Thread(target=self._pump, daemon=True)
        t.start()

    def _pump(self):
        for line in self.proc.stdout:
            line = line.strip()
            if line:
                self.q.put(json.loads(line))

    def send(self, src, dest, body):
        msg = {"src": src, "dest": dest, "body": body}
        self.proc.stdin.write(json.dumps(msg) + "\n")
        self.proc.stdin.flush()

    def rpc(self, src, dest, body):
        body = dict(body)
        self.next_id += 1
        body["msg_id"] = self.next_id
        self.send(src, dest, body)
        return self.next_id

    def recv(self, timeout=TIMEOUT):
        return self.q.get(timeout=timeout)

    def recv_reply(self, msg_id, service=None, timeout=TIMEOUT):
        """Wait for the reply to ``msg_id``; meanwhile, answer any
        service traffic the node emits via ``service(msg) -> body``."""
        import time
        deadline = time.monotonic() + timeout
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no reply to msg_id {msg_id} within {timeout}s")
            msg = self.recv(max(0.01, deadline - time.monotonic()))
            if msg["body"].get("in_reply_to") == msg_id:
                return msg
            if service is not None:
                reply = service(msg)
                if reply is not None:
                    reply = dict(reply)
                    reply["in_reply_to"] = msg["body"]["msg_id"]
                    self.send(msg["dest"], msg["src"], reply)

    def pump(self, service, until, timeout=TIMEOUT):
        """Answer node-emitted traffic via ``service`` until ``until()``
        is true (e.g. gossip sent on a retry timer has shown up)."""
        import time
        deadline = time.monotonic() + timeout
        while not until() and time.monotonic() < deadline:
            try:
                msg = self.recv(0.25)
            except queue.Empty:
                continue
            reply = service(msg)
            if reply is not None:
                reply = dict(reply)
                reply["in_reply_to"] = msg["body"]["msg_id"]
                self.send(msg["dest"], msg["src"], reply)
        assert until(), "pump timed out"

    def init(self, node_id="n0", node_ids=("n0",)):
        mid = self.rpc("c0", node_id, {
            "type": "init", "node_id": node_id,
            "node_ids": list(node_ids)})
        reply = self.recv_reply(mid)
        assert reply["body"]["type"] == "init_ok", reply
        assert reply["src"] == node_id and reply["dest"] == "c0"
        return reply

    def close(self):
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        self.proc.terminate()
        self.proc.wait(timeout=5)


def check_against_registry(namespace, rpc_type, body):
    """Reply body must be the registry's reply type and carry every
    required response field (extra unknown fields are allowed only if
    the schema says so — here: flag them)."""
    spec = REGISTRY[namespace][rpc_type]
    assert body["type"] == f"{rpc_type}_ok", body
    required = {k for k in spec.response
                if not isinstance(k, Opt)}
    allowed = ({k.key if isinstance(k, Opt) else k
                for k in spec.response}
               | {"type", "in_reply_to", "msg_id"})
    got = set(body)
    assert required <= got, f"missing {required - got} in {body}"
    assert got <= allowed, f"unexpected {got - allowed} in {body}"


@pytest.fixture
def net(request, cpp_bins):
    nets = []

    def make(binary, node_ids=("n0",)):
        n = FakeNet(os.path.join(cpp_bins, binary))
        nets.append(n)
        n.init("n0", node_ids)
        return n
    yield make
    for n in nets:
        n.close()


def test_cpp_echo_conformance(net):
    n = net("echo")
    mid = n.rpc("c1", "n0", {"type": "echo", "echo": "hello 42"})
    reply = n.recv_reply(mid)
    assert reply["body"]["echo"] == "hello 42"
    check_against_registry("echo", "echo", reply["body"])


def test_cpp_unsupported_type_is_error_10(net):
    n = net("echo")
    mid = n.rpc("c1", "n0", {"type": "frobnicate"})
    reply = n.recv_reply(mid)
    assert reply["body"]["type"] == "error", reply
    assert reply["body"]["code"] == 10, reply


def test_cpp_g_set_conformance(net):
    n = net("g_set")
    mid = n.rpc("c1", "n0", {"type": "add", "element": 7})
    check_against_registry("g-set", "add", n.recv_reply(mid)["body"])
    mid = n.rpc("c1", "n0", {"type": "read"})
    body = n.recv_reply(mid)["body"]
    check_against_registry("g-set", "read", body)
    assert 7 in body["value"]


def test_cpp_pn_counter_conformance(net):
    n = net("pn_counter")
    for delta in (5, -2):
        mid = n.rpc("c1", "n0", {"type": "add", "delta": delta})
        check_against_registry("pn-counter", "add",
                               n.recv_reply(mid)["body"])
    mid = n.rpc("c1", "n0", {"type": "read"})
    body = n.recv_reply(mid)["body"]
    check_against_registry("pn-counter", "read", body)
    assert body["value"] == 3


def test_cpp_broadcast_conformance(net):
    n = net("broadcast", node_ids=("n0", "n1"))
    mid = n.rpc("c1", "n0", {"type": "topology",
                             "topology": {"n0": ["n1"], "n1": ["n0"]}})
    check_against_registry("broadcast", "topology",
                           n.recv_reply(mid)["body"])

    peer_traffic = []

    def peer_service(msg):
        # n1: ack whatever gossip/broadcast arrives so retries stop
        peer_traffic.append(msg)
        t = msg["body"]["type"]
        if "msg_id" in msg["body"]:
            return {"type": f"{t}_ok"}
        return None

    mid = n.rpc("c1", "n0", {"type": "broadcast", "message": 123})
    check_against_registry(
        "broadcast", "broadcast",
        n.recv_reply(mid, service=peer_service)["body"])
    # gossip toward the peer rides the node's retry timer — pump until
    # it shows up (and gets acked, stopping the retries)
    n.pump(peer_service,
           until=lambda: any(m["dest"] == "n1" for m in peer_traffic))
    mid = n.rpc("c1", "n0", {"type": "read"})
    body = n.recv_reply(mid, service=peer_service)["body"]
    check_against_registry("broadcast", "read", body)
    assert 123 in body["messages"]
    gossip = [m for m in peer_traffic if m["dest"] == "n1"]
    assert gossip and gossip[0]["body"]["message"] == 123


def test_cpp_lin_kv_proxy_conformance(net):
    """The SDK's service-KV client (the Rust crate's kv role): the proxy
    must translate client read/write/cas into lin-kv service RPCs; the
    fake service answers them."""
    store = {}

    def lin_kv(msg):
        if msg["dest"] != "lin-kv":
            return None
        b = msg["body"]
        if b["type"] == "read":
            if b["key"] in store:
                return {"type": "read_ok", "value": store[b["key"]]}
            return {"type": "error", "code": 20,
                    "text": "key does not exist"}
        if b["type"] == "write":
            store[b["key"]] = b["value"]
            return {"type": "write_ok"}
        if b["type"] == "cas":
            cur = store.get(b["key"])
            if cur is None and not b.get("create_if_not_exists"):
                return {"type": "error", "code": 20, "text": "nope"}
            if cur is not None and cur != b["from"]:
                return {"type": "error", "code": 22,
                        "text": f"expected {b['from']}, had {cur}"}
            store[b["key"]] = b["to"]
            return {"type": "cas_ok"}
        return None

    n = net("lin_kv_proxy")
    mid = n.rpc("c1", "n0", {"type": "write", "key": 1, "value": 9})
    check_against_registry(
        "lin-kv", "write", n.recv_reply(mid, service=lin_kv)["body"])
    mid = n.rpc("c1", "n0", {"type": "read", "key": 1})
    body = n.recv_reply(mid, service=lin_kv)["body"]
    check_against_registry("lin-kv", "read", body)
    assert body["value"] == 9
    mid = n.rpc("c1", "n0", {"type": "cas", "key": 1, "from": 9, "to": 10})
    check_against_registry(
        "lin-kv", "cas", n.recv_reply(mid, service=lin_kv)["body"])
    assert store[1] == 10
    # failing CAS surfaces the service's definite error to the client
    mid = n.rpc("c1", "n0", {"type": "cas", "key": 1, "from": 9, "to": 11})
    body = n.recv_reply(mid, service=lin_kv)["body"]
    assert body["type"] == "error" and body["code"] == 22, body
