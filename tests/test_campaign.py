"""Durable campaign control-plane tests (maelstrom_tpu/campaign/).

Pins the PR's acceptance bars:

- **checkpoint durability** — the write-temp-then-rename pivot means a
  writer killed at ANY point leaves the previous checkpoint or the new
  one, never a torn file;
- **bit-exact resume** — a chunked run killed mid-horizon resumes from
  its last checkpoint and produces decoded histories, fleet metrics,
  and checker verdicts identical to the same run executed
  uninterrupted, in BOTH carry layouts and through the sharded driver;
  double-resume is idempotent;
- **queue semantics** — file-lock claims are exclusive, a dead worker's
  item is detected stale and re-claimed, and the item then resumes from
  its recorded run dir's checkpoint;
- **triage over segments** — `maelstrom triage` on a resumed run
  replays the FULL dispatched horizon across the kill seam;
- the `latest` symlink survives concurrent runs (atomic repoint) and
  campaign items get collision-free run dirs.
"""

import copy
import glob
import json
import os
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

import maelstrom_tpu.campaign.checkpoint as ckpt
from maelstrom_tpu.campaign import queue as cqueue
from maelstrom_tpu.campaign.checkpoint import (CheckpointError,
                                               checkpoint_path,
                                               load_checkpoint,
                                               restore_carry,
                                               save_checkpoint)
from maelstrom_tpu.campaign.runner import resume_run, run_campaign
from maelstrom_tpu.campaign.spec import SpecError, expand_items
from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.telemetry.stream import read_heartbeat
from maelstrom_tpu.tpu.harness import (make_sim_config,
                                       prepare_store_dir, run_tpu_test)
from maelstrom_tpu.tpu.pipeline import (ResumeState, _init_pipelined,
                                        resume_plans, run_sim_pipelined)

pytestmark = pytest.mark.campaign

# the shared tiny echo config: 300 ticks / chunk 50 = 6 chunks, small
# enough that a handful of runs stays inside the tier-1 budget
ECHO_OPTS = dict(node_count=2, concurrency=2, n_instances=8,
                 record_instances=2, time_limit=0.3, rate=100.0,
                 latency=5.0, seed=3, funnel=False, pipeline="on",
                 chunk_ticks=50)

# the planted violating model of test_stream_triage — resumed-run
# triage must name its instances across the kill seam
BUGGY_OPTS = dict(node_count=3, concurrency=6, n_instances=16,
                  record_instances=4, inbox_k=1, pool_slots=16,
                  time_limit=0.3, rate=200.0, latency=5.0,
                  rpc_timeout=1.0, nemesis=["partition"],
                  nemesis_interval=0.04, p_loss=0.05, recovery_time=0.0,
                  seed=7, funnel=False, pipeline="on", chunk_ticks=50)


class Killed(BaseException):
    """Simulated SIGKILL: raised from the checkpoint sink so the run
    dies immediately after a checkpoint lands (BaseException so no
    well-meaning except-Exception path can swallow the 'kill')."""


def _kill_after(n_saves):
    """Patch campaign.checkpoint.save_checkpoint to die after the n-th
    save; returns the restore thunk."""
    orig = ckpt.save_checkpoint
    calls = [0]

    def dying(*a, **k):
        path = orig(*a, **k)
        calls[0] += 1
        if calls[0] >= n_saves:
            raise Killed
        return path

    ckpt.save_checkpoint = dying
    return lambda: setattr(ckpt, "save_checkpoint", orig)


def _strip(results):
    """Everything that must be bit-identical across kill/resume: the
    full results dict minus wall-clock perf and the store path."""
    r = copy.deepcopy(results)
    r.pop("perf", None)
    r.pop("store-dir", None)
    return json.loads(json.dumps(r, default=repr))


# --- checkpoint durability -------------------------------------------------


def test_checkpoint_roundtrip(tmp_path):
    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    params = model.make_params(sim.net.n_nodes)
    res = run_sim_pipelined(model, sim, 3, params, chunk=50,
                            keep_compact=True)
    d = str(tmp_path)
    save_checkpoint(d, kind="pipelined", state=res.carry, ticks=300,
                    chunks=6, compact=tuple(res.compact),
                    meta={"workload": "echo"})
    ck = load_checkpoint(d)
    assert ck["kind"] == "pipelined"
    assert ck["ticks"] == 300 and ck["chunks"] == 6
    assert len(ck["compact"]) == len(res.compact)
    for (a, na), (b, nb) in zip(ck["compact"], res.compact):
        assert na == nb and np.array_equal(a, np.asarray(b))
    assert ck["meta"]["workload"] == "echo"
    for a, b in zip(ck["carry"], jax.tree.leaves(res.carry)):
        assert np.array_equal(a, np.asarray(b))


def test_checkpoint_kill_mid_write_leaves_old_or_none(tmp_path,
                                                      monkeypatch):
    """Atomicity: a writer that dies mid-write (before the rename
    pivot) leaves the PREVIOUS checkpoint fully intact — and a first
    write that dies leaves no checkpoint, not a torn one."""
    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    carry = _init_pipelined(model, sim, 3,
                            model.make_params(sim.net.n_nodes),
                            np.arange(8, dtype=np.int32))
    d = str(tmp_path)

    def torn_savez(f, **arrays):
        f.write(b"\x00" * 37)   # partial garbage, then the "kill"
        raise Killed

    # first-ever write dies: no checkpoint must exist
    monkeypatch.setattr(np, "savez", torn_savez)
    with pytest.raises(Killed):
        save_checkpoint(d, kind="pipelined", state=carry, ticks=50,
                        chunks=1)
    monkeypatch.undo()
    assert load_checkpoint(d) is None
    assert not glob.glob(checkpoint_path(d) + ".tmp-*")

    # a good checkpoint, then a dying overwrite: the old one survives
    save_checkpoint(d, kind="pipelined", state=carry, ticks=50,
                    chunks=1)
    monkeypatch.setattr(np, "savez", torn_savez)
    with pytest.raises(Killed):
        save_checkpoint(d, kind="pipelined", state=carry, ticks=100,
                        chunks=2)
    monkeypatch.undo()
    ck = load_checkpoint(d)
    assert ck is not None and ck["ticks"] == 50


def test_restore_refuses_config_mismatch(tmp_path):
    model = EchoModel()
    sim = make_sim_config(model, ECHO_OPTS)
    params = model.make_params(sim.net.n_nodes)
    carry = _init_pipelined(model, sim, 3, params,
                            np.arange(8, dtype=np.int32))
    d = str(tmp_path)
    save_checkpoint(d, kind="pipelined", state=carry, ticks=50,
                    chunks=1)
    ck = load_checkpoint(d)
    other = make_sim_config(model, {**ECHO_OPTS, "n_instances": 16})
    template = _init_pipelined(model, other, 3, params,
                               np.arange(16, dtype=np.int32))
    with pytest.raises(CheckpointError):
        restore_carry(template, ck["carry"])


def test_resume_plans_boundary_check():
    assert resume_plans(300, 50, None) == [(0, 50), (50, 50), (100, 50),
                                           (150, 50), (200, 50),
                                           (250, 50)]
    rs = ResumeState(carry=None, ticks=100)
    assert resume_plans(300, 50, rs) == [(100, 50), (150, 50),
                                         (200, 50), (250, 50)]
    with pytest.raises(ValueError):
        resume_plans(300, 50, ResumeState(carry=None, ticks=70))
    assert resume_plans(300, 50, ResumeState(carry=None,
                                             ticks=300)) == []


# --- bit-exact resume ------------------------------------------------------


@pytest.mark.parametrize("layout", ["lead", "minor"])
def test_resume_bit_identical(tmp_path, layout):
    """Kill after a mid-run checkpoint, resume, and the concatenated
    segments equal the uninterrupted run — carry, decoded events,
    telemetry leaves — in BOTH carry layouts."""
    model = EchoModel()
    sim = make_sim_config(model, {**ECHO_OPTS, "layout": layout})
    params = model.make_params(sim.net.n_nodes)
    base = run_sim_pipelined(model, sim, 3, params, chunk=50)

    d = str(tmp_path)

    def cb(state, ticks, host):
        save_checkpoint(d, kind="pipelined", state=state, ticks=ticks,
                        chunks=host["chunks"],
                        compact=tuple(host["compact"]),
                        journal=tuple(host["journal"]))
        raise Killed

    with pytest.raises(Killed):
        run_sim_pipelined(model, sim, 3, params, chunk=50,
                          checkpoint_cb=cb, checkpoint_every=2)
    ck = load_checkpoint(d)
    assert 0 < ck["ticks"] < sim.n_ticks
    template = _init_pipelined(model, sim, 3, params,
                               np.arange(8, dtype=np.int32))
    resume = ResumeState(carry=restore_carry(template, ck["carry"]),
                         ticks=ck["ticks"], chunks=ck["chunks"],
                         compact=tuple(ck["compact"]),
                         journal=tuple(ck["journal"]))
    res = run_sim_pipelined(model, sim, 3, params, chunk=50,
                            resume=resume)
    assert res.perf["resumed-from-ticks"] == ck["ticks"]
    assert res.perf["ticks-dispatched"] == sim.n_ticks
    assert np.array_equal(base.events, res.events)
    for a, b in zip(jax.tree.leaves(base.carry),
                    jax.tree.leaves(res.carry)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope="module")
def oracle_and_resumed(tmp_path_factory):
    """One uninterrupted oracle run + one killed-then-resumed run of
    the identical config, shared by the e2e equality tests below."""
    oracle_store = str(tmp_path_factory.mktemp("oracle-store"))
    killed_store = str(tmp_path_factory.mktemp("killed-store"))
    opts = dict(ECHO_OPTS, checkpoint_every=2)
    oracle = run_tpu_test(EchoModel(),
                          dict(opts, store_root=oracle_store))
    restore = _kill_after(1)
    try:
        with pytest.raises(Killed):
            run_tpu_test(EchoModel(),
                         dict(opts, store_root=killed_store))
    finally:
        restore()
    (run_dir,) = glob.glob(os.path.join(killed_store, "echo-tpu", "2*"))
    # the kill left checkpoint + heartbeat prefix, but no results
    assert not os.path.exists(os.path.join(run_dir, "results.json"))
    assert load_checkpoint(run_dir) is not None
    resumed = resume_run(run_dir)
    return oracle, resumed, run_dir


def test_resume_run_matches_uninterrupted_oracle(oracle_and_resumed):
    oracle, resumed, _ = oracle_and_resumed
    assert _strip(oracle) == _strip(resumed)
    assert resumed["valid?"] is True


def test_resumed_store_artifacts_match(oracle_and_resumed):
    """Decoded histories and fleet metrics on disk are byte-identical
    to the uninterrupted run's."""
    oracle, resumed, run_dir = oracle_and_resumed
    odir = oracle["store-dir"]
    for name in ("history-0.jsonl", "history-1.jsonl"):
        with open(os.path.join(odir, name)) as a, \
                open(os.path.join(run_dir, name)) as b:
            assert a.read() == b.read()
    with open(os.path.join(odir, "fleet-metrics.json")) as a, \
            open(os.path.join(run_dir, "fleet-metrics.json")) as b:
        assert json.load(a) == json.load(b)


def test_resumed_heartbeat_has_seam_and_end(oracle_and_resumed):
    _, _, run_dir = oracle_and_resumed
    hb = read_heartbeat(run_dir)
    assert len(hb["resumes"]) == 1
    assert hb["resumes"][0]["from-ticks"] > 0
    assert hb["end"] is not None
    assert hb["end"]["status"] == "complete"
    assert hb["end"]["ticks"] == 300


def test_double_resume_idempotent(oracle_and_resumed):
    """Resuming an already-finished run re-runs its tail segment from
    the (still present) checkpoint and lands on the same results."""
    oracle, _, run_dir = oracle_and_resumed
    again = resume_run(run_dir)
    assert _strip(again) == _strip(oracle)
    hb = read_heartbeat(run_dir)
    assert len(hb["resumes"]) == 2
    assert hb["end"] is not None


def test_resume_without_checkpoint_refused(tmp_path):
    with pytest.raises(CheckpointError):
        resume_run(str(tmp_path))


@pytest.mark.slow
def test_resume_sharded_bit_identical(tmp_path):
    """The sharded driver checkpoints its wire carry and resumes
    bit-identically (same mesh shape enforced by the restore check)."""
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked,
                                             wire_template)
    model = EchoModel()
    opts = dict(ECHO_OPTS, n_instances=4, time_limit=0.12)
    sim = make_sim_config(model, opts)
    mesh = make_mesh(2)
    base = run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                   chunk=40)
    d = str(tmp_path)

    def cb(state, ticks, host):
        save_checkpoint(d, kind="sharded", state=state, ticks=ticks,
                        chunks=host["chunks"],
                        events=tuple(host["events"]))
        raise Killed

    with pytest.raises(Killed):
        run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                chunk=40, checkpoint_cb=cb,
                                checkpoint_every=1)
    ck = load_checkpoint(d)
    assert ck["kind"] == "sharded" and 0 < ck["ticks"] < sim.n_ticks
    tmpl = wire_template(model, sim, mesh)
    resume = ResumeState(carry=restore_carry(tmpl, ck["carry"]),
                         ticks=ck["ticks"], chunks=ck["chunks"],
                         events=tuple(ck["events"]))
    res = run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                  chunk=40, resume=resume)
    assert base[0] == res[0]
    assert np.array_equal(base[1], res[1])
    assert np.array_equal(base[2], res[2])
    # without the checkpoint's recorded shard metadata a wrong-size
    # mesh is still refused, never silently mis-sharded (WITH it the
    # mismatch reshards — test_cross_mesh_resume_bit_identical)
    with pytest.raises(CheckpointError):
        restore_carry(wire_template(model, sim, make_mesh(4)),
                      ck["carry"])


@pytest.mark.shard
def test_restore_carry_names_shard_counts_on_mismatch():
    """The reshard route's refusal is actionable: it names both shard
    counts and the reshard path, not a bare leaf-count complaint."""
    from maelstrom_tpu.parallel.mesh import (make_mesh, wire_leaf_kinds,
                                             wire_template)
    model = EchoModel()
    sim4 = make_sim_config(model, dict(ECHO_OPTS, n_instances=2))
    tmpl4 = wire_template(model, sim4, make_mesh(4))
    leaves = [np.zeros(l.shape, l.dtype)
              for l in jax.tree.leaves(tmpl4)]
    shard = {"n-shards": 4, "instances-per-shard": 2,
             "interleaved": True,
             "leaf-kinds": wire_leaf_kinds(model, sim4)}
    # the resume config expects a DIFFERENT global fleet (3 x 2 = 6
    # instances vs the checkpoint's 4 x 2 = 8): not a pure shard-count
    # change, so the reshard route must refuse by name
    sim2 = make_sim_config(model, dict(ECHO_OPTS, n_instances=3))
    with pytest.raises(CheckpointError) as e:
        restore_carry(wire_template(model, sim2, make_mesh(2)),
                      leaves, shard=shard)
    msg = str(e.value)
    assert "carry saved at 4 shards, mesh has 2" in msg
    assert "resharding via reshard_carry" in msg
    assert "8 instances (4 x 2)" in msg


@pytest.mark.shard
@pytest.mark.slow
@pytest.mark.parametrize("new_shards", [2, 1])
def test_cross_mesh_resume_bit_identical(tmp_path, new_shards):
    """ROADMAP item 1's elastic-resume residual: a checkpoint written
    at 4 shards resumes at 2 and at 1 shards with fleet stats,
    per-instance violations, event streams, decoded histories, and
    checker verdicts all bit-identical to an uninterrupted run at the
    NEW shard count (global-instance-id RNG + per-leaf reshard kinds;
    statically verified by `maelstrom lint --shard` SHD809)."""
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked,
                                             wire_template)
    from maelstrom_tpu.tpu.harness import events_to_histories
    model = EchoModel()

    def sim_at(shards):
        # the same 8-instance global fleet however it is chunked —
        # recording ALL of it, so the recorded set (round-robin global
        # ids) is identical at every shard count
        return make_sim_config(model, dict(
            ECHO_OPTS, n_instances=8 // shards,
            record_instances=8 // shards, time_limit=0.12))

    # uninterrupted oracle at the NEW shard count
    sim_new = sim_at(new_shards)
    mesh_new = make_mesh(new_shards)
    base = run_sim_sharded_chunked(model, sim_new, seed=3,
                                   mesh=mesh_new, chunk=40)

    # the killed run writes its checkpoint at 4 shards
    sim4 = sim_at(4)
    d = str(tmp_path)

    def cb(state, ticks, host):
        save_checkpoint(d, kind="sharded", state=state, ticks=ticks,
                        chunks=host["chunks"],
                        events=tuple(host["events"]),
                        meta={"shard": host["shard"]})
        raise Killed

    with pytest.raises(Killed):
        run_sim_sharded_chunked(model, sim4, seed=3, mesh=make_mesh(4),
                                chunk=40, checkpoint_cb=cb,
                                checkpoint_every=1)
    ck = load_checkpoint(d)
    assert ck["meta"]["shard"]["n-shards"] == 4
    assert 0 < ck["ticks"] < sim4.n_ticks

    # resume on the smaller mesh: restore_carry routes the pure
    # shard-count mismatch through reshard_carry
    tmpl = wire_template(model, sim_new, mesh_new)
    resume = ResumeState(
        carry=restore_carry(tmpl, ck["carry"],
                            shard=ck["meta"]["shard"]),
        ticks=ck["ticks"], chunks=ck["chunks"],
        events=tuple(ck["events"]))
    res = run_sim_sharded_chunked(model, sim_new, seed=3,
                                  mesh=mesh_new, chunk=40,
                                  resume=resume)
    assert base[0] == res[0]
    assert np.array_equal(base[1], res[1])
    assert np.array_equal(base[2], res[2])
    # the bit-identity carries through decode + checking: same
    # histories, same verdicts
    h_base = events_to_histories(model, np.asarray(base[2]))
    h_res = events_to_histories(model, np.asarray(res[2]))
    assert h_base == h_res
    checker = model.checker()
    opts = dict(ECHO_OPTS, n_instances=8 // new_shards)
    for hb, hr in zip(h_base, h_res):
        if hb:
            assert checker(hb, opts) == checker(hr, opts)


def test_triage_on_resumed_run_covers_full_horizon(tmp_path):
    """`maelstrom triage` on a killed-then-resumed run of the planted
    double-vote mutant: the flagged instances replay over the FULL
    dispatched horizon across both segments and re-trip."""
    from maelstrom_tpu.checkers.triage import triage_run
    from maelstrom_tpu.models.raft_buggy import RaftDoubleVote

    def buggy():
        return RaftDoubleVote(n_nodes_hint=3, log_cap=64, heartbeat=8)

    store = str(tmp_path / "store")
    opts = dict(BUGGY_OPTS, checkpoint_every=2, store_root=store)
    oracle = run_tpu_test(buggy(), opts)
    assert oracle["valid?"] is False
    restore = _kill_after(1)
    try:
        with pytest.raises(Killed):
            run_tpu_test(buggy(), dict(opts, store_root=str(
                tmp_path / "killed")))
    finally:
        restore()
    (run_dir,) = glob.glob(str(tmp_path / "killed" /
                               "lin-kv-bug-double-vote-tpu" / "2*"))
    resumed = resume_run(run_dir)
    assert _strip(resumed) == _strip(oracle)
    summary = triage_run(run_dir, max_instances=2)
    assert summary["flagged"] == oracle["invariants"][
        "violating-instance-ids"]
    assert summary["ticks"] == 300   # full horizon, not the tail
    assert summary["replayed-violating"] == len(summary["triaged"])


# --- compile cache ---------------------------------------------------------


def test_compile_cache_recorded_in_phases(tmp_path, monkeypatch):
    cache = str(tmp_path / "cache")
    try:
        res = run_tpu_test(EchoModel(),
                           dict(ECHO_OPTS, compile_cache=cache))
        rec = res["perf"]["phases"]["compile-cache"]
        assert rec["dir"] == os.path.abspath(cache)
        assert rec["hits"] >= 0 and rec["misses"] >= 0
        # disabled via env: no record, no cache writes
        monkeypatch.setenv("MAELSTROM_COMPILE_CACHE", "0")
        res2 = run_tpu_test(EchoModel(), dict(ECHO_OPTS))
        assert "compile-cache" not in res2["perf"]["phases"]
    finally:
        # restore the suite-wide cache dir (tests/conftest.py)
        jax.config.update("jax_compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache"))


# --- campaign spec + queue -------------------------------------------------


def test_spec_matrix_expansion():
    items = expand_items({
        "name": "m",
        "defaults": {"time_limit": 1.0},
        "matrix": {"workload": ["echo", "g-set"], "seed": [0, 1],
                   "rate": 50.0},
        "items": [{"workload": "echo", "seed": 9}],
    })
    assert len(items) == 5
    assert all(i["time_limit"] == 1.0 for i in items)
    assert all(i.get("rate", 50.0) == 50.0 for i in items[:4])
    combos = {(i["workload"], i["seed"]) for i in items[:4]}
    assert combos == {("echo", 0), ("echo", 1), ("g-set", 0),
                      ("g-set", 1)}
    assert items[4] == {"time_limit": 1.0, "workload": "echo",
                        "seed": 9}
    with pytest.raises(SpecError):
        expand_items({"name": "empty"})
    with pytest.raises(SpecError):
        expand_items({"matrix": {"seed": [1]}})   # no workload


def _tiny_campaign(store, n=2):
    return cqueue.submit_campaign(
        {"name": "t", "items": [dict(ECHO_OPTS, workload="echo",
                                     seed=s) for s in range(n)]},
        store)


def test_queue_claim_exclusive_and_ordered(tmp_path):
    cdir = _tiny_campaign(str(tmp_path), n=3)
    c0 = cqueue.claim_next(cdir, worker="w0")
    assert c0.item["id"] == 0 and c0.item["status"] == "running"
    c1 = cqueue.claim_next(cdir, worker="w1")
    assert c1.item["id"] == 1   # the running item 0 is skipped
    cqueue.finish_item(c0, cqueue.DONE, **{"valid?": True})
    cqueue.finish_item(c1, cqueue.FAILED, error="boom")
    c2 = cqueue.claim_next(cdir)
    assert c2.item["id"] == 2
    cqueue.finish_item(c2, cqueue.DONE, **{"valid?": True})
    assert cqueue.claim_next(cdir) is None
    statuses = [i["status"] for i in cqueue.list_items(cdir)]
    assert statuses == ["done", "failed", "done"]


def test_queue_stale_lock_reclaim(tmp_path):
    """A worker that died holding an item: its lock pid is dead, the
    item flips to preempted and the next claimer takes it over."""
    import socket
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="doomed")
    # forge the lock as a dead process on this host (pid 2**22+1 is
    # beyond default pid_max)
    with open(claim.lock, "w") as f:
        json.dump({"pid": (1 << 22) + 1,
                   "host": socket.gethostname()}, f)
    again = cqueue.claim_next(cdir, worker="rescuer")
    assert again is not None and again.item["id"] == 0
    assert again.item["previous-status"] == "preempted"
    assert again.item["attempts"] == 2
    cqueue.finish_item(again, cqueue.DONE, **{"valid?": True})
    # a live lock is NEVER stolen
    cdir2 = _tiny_campaign(str(tmp_path / "c2"), n=1)
    live = cqueue.claim_next(cdir2, worker="alive")
    assert cqueue.claim_next(cdir2, worker="thief") is None
    cqueue.finish_item(live, cqueue.DONE)


def test_requeue_stale_flips_dead_running_items(tmp_path):
    import socket
    cdir = _tiny_campaign(str(tmp_path), n=2)
    claim = cqueue.claim_next(cdir)
    with open(claim.lock, "w") as f:
        json.dump({"pid": (1 << 22) + 1,
                   "host": socket.gethostname()}, f)
    assert cqueue.requeue_stale(cdir) == [0]
    assert cqueue.list_items(cdir)[0]["status"] == "preempted"


def test_requeue_force_never_steals_live_same_host_lock(tmp_path):
    """--force is for lock-less / cross-host items; a live same-host
    lock means the worker is demonstrably running — never stolen."""
    cdir = _tiny_campaign(str(tmp_path / "live"), n=1)
    live = cqueue.claim_next(cdir)
    assert cqueue.requeue_stale(cdir, force=True) == []
    assert cqueue.list_items(cdir)[0]["status"] == "running"
    cqueue.finish_item(live, cqueue.DONE)
    # a lock-LESS running item is reclaimed only under force
    cdir2 = _tiny_campaign(str(tmp_path / "lockless"), n=1)
    c = cqueue.claim_next(cdir2)
    os.unlink(c.lock)
    assert cqueue.requeue_stale(cdir2) == []
    assert cqueue.requeue_stale(cdir2, force=True) == [0]
    # a cross-host lock (liveness unprobeable) also needs force
    cdir3 = _tiny_campaign(str(tmp_path / "remote"), n=1)
    c3 = cqueue.claim_next(cdir3)
    with open(c3.lock, "w") as f:
        json.dump({"pid": os.getpid(), "host": "some-other-host"}, f)
    assert cqueue.requeue_stale(cdir3) == []
    assert cqueue.requeue_stale(cdir3, force=True) == [0]


def test_lease_expiry_requeues_lost_remote_worker(tmp_path):
    """Lease-file TTLs (PR-8 residual): a cross-host lock whose lease
    expired is stale WITHOUT --force — the lost-remote-worker case
    that used to need `requeue_stale --force`."""
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="remote:1")
    # a fresh claim writes a lease
    with open(claim.lock) as f:
        lock = json.load(f)
    assert lock["lease-expires"] > time.time()
    # forge it as a remote worker whose lease ran out
    with open(claim.lock, "w") as f:
        json.dump({"pid": os.getpid(), "host": "some-other-host",
                   "worker": "remote:1", "claimed": time.time() - 900,
                   "lease-expires": time.time() - 300}, f)
    assert cqueue.requeue_stale(cdir) == [0]
    again = cqueue.claim_next(cdir, worker="rescuer")
    assert again is not None and again.item["previous-status"] \
        == "preempted"
    cqueue.finish_item(again, cqueue.DONE, **{"valid?": True})


def test_fresh_remote_lease_not_auto_stolen_force_overrides(tmp_path):
    """An UNexpired remote lease presumes its worker alive — never
    auto-stolen; --force is the operator asserting the remote worker
    is lost and overrides the TTL. A lapsed-lease SAME-HOST lock with
    a live pid stays held either way (the pid probe is authoritative
    locally)."""
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="remote:1")
    with open(claim.lock, "w") as f:
        json.dump({"pid": os.getpid(), "host": "some-other-host",
                   "worker": "remote:1", "claimed": time.time(),
                   "lease-expires": time.time() + 600}, f)
    assert cqueue.requeue_stale(cdir) == []
    assert cqueue.claim_next(cdir, worker="thief") is None
    assert cqueue.requeue_stale(cdir, force=True) == [0]

    # same-host live pid with a LAPSED lease: still running (stopped/
    # swapping workers miss renewals) — never stolen, force or not
    import socket
    cdir2 = _tiny_campaign(str(tmp_path / "local"), n=1)
    c2 = cqueue.claim_next(cdir2, worker="slow:1")
    with open(c2.lock, "w") as f:
        json.dump({"pid": os.getpid(), "host": socket.gethostname(),
                   "worker": "slow:1", "claimed": time.time() - 900,
                   "lease-expires": time.time() - 300}, f)
    assert cqueue.requeue_stale(cdir2) == []
    assert cqueue.requeue_stale(cdir2, force=True) == []
    assert cqueue.claim_next(cdir2, worker="thief") is None


def test_expired_lease_claimed_directly(tmp_path):
    """claim_next itself steals an expired lease (no separate requeue
    pass needed): the dead remote worker's item re-runs."""
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="remote:1")
    with open(claim.lock, "w") as f:
        json.dump({"pid": os.getpid(), "host": "some-other-host",
                   "worker": "remote:1", "claimed": time.time() - 900,
                   "lease-expires": time.time() - 1}, f)
    again = cqueue.claim_next(cdir, worker="rescuer")
    assert again is not None
    assert again.item["claimed-by"] == "rescuer"
    cqueue.finish_item(again, cqueue.DONE)


def test_renew_lease_extends_and_stops_after_finish(tmp_path):
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="w")
    with open(claim.lock) as f:
        before = json.load(f)["lease-expires"]
    time.sleep(0.05)
    assert cqueue.renew_lease(claim.lock, worker="w")
    with open(claim.lock) as f:
        after = json.load(f)["lease-expires"]
    assert after > before
    cqueue.finish_item(claim, cqueue.DONE)
    # lock gone: renewal reports False (the LeaseKeeper's stop signal)
    assert cqueue.renew_lease(claim.lock, worker="w") is False


def test_renew_lease_forfeits_when_stolen_or_lapsed(tmp_path):
    """A renewer that finds its lock held by someone else — or its own
    lease already expired — must NOT write: the steal/claim path owns
    the lock now, and a clobbering renewal would double-claim."""
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="w1")
    # stolen and re-claimed by another worker
    with open(claim.lock, "w") as f:
        json.dump(cqueue._lease_body("w2"), f)
    assert cqueue.renew_lease(claim.lock, worker="w1") is False
    with open(claim.lock) as f:
        assert json.load(f)["worker"] == "w2"   # untouched
    # own lease lapsed: forfeited, not refreshed
    with open(claim.lock, "w") as f:
        json.dump(dict(cqueue._lease_body("w1"),
                       **{"lease-expires": time.time() - 5}), f)
    assert cqueue.renew_lease(claim.lock, worker="w1") is False
    cqueue.finish_item(claim, cqueue.DONE)


def test_lease_is_ours_distinguishes_terminal_from_transient(tmp_path):
    """The LeaseKeeper's stop test: a failed renewal only terminates
    the keeper when the lease is genuinely lost — ours-and-fresh means
    the failure was transient and renewal must keep retrying."""
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir, worker="w1")
    assert cqueue.lease_is_ours(claim.lock, worker="w1")
    assert not cqueue.lease_is_ours(claim.lock, worker="w2")
    with open(claim.lock, "w") as f:
        json.dump(dict(cqueue._lease_body("w1"),
                       **{"lease-expires": time.time() - 5}), f)
    assert not cqueue.lease_is_ours(claim.lock, worker="w1")
    cqueue.finish_item(claim, cqueue.DONE)
    assert not cqueue.lease_is_ours(claim.lock, worker="w1")


def test_lease_keeper_renews_while_item_runs(tmp_path):
    from maelstrom_tpu.campaign.runner import LeaseKeeper
    cdir = _tiny_campaign(str(tmp_path), n=1)
    # default worker id: the keeper renews as _worker_id() and the
    # ownership check must match (the campaign runner's arrangement)
    claim = cqueue.claim_next(cdir)
    with open(claim.lock) as f:
        before = json.load(f)["claimed"]
    with LeaseKeeper(claim.lock, ttl=0.3):
        time.sleep(0.5)
    # the keeper re-stamped the lease at ttl/3 cadence: the write time
    # advanced and the expiry still covers now + a fresh ttl window
    with open(claim.lock) as f:
        lock = json.load(f)
    assert lock["claimed"] > before
    assert lock["lease-expires"] > time.time()
    cqueue.finish_item(claim, cqueue.DONE)


def test_campaign_end_to_end_with_planted_bug(tmp_path):
    """A 2-item campaign — clean echo + the planted double-vote mutant
    — drains to done with the mutant flagged invalid, and the trend
    report aggregates both."""
    from maelstrom_tpu.campaign.report import (campaign_report,
                                               campaign_status)
    store = str(tmp_path)
    cdir = cqueue.submit_campaign(
        {"name": "e2e", "items": [
            dict(ECHO_OPTS, workload="echo"),
            dict(BUGGY_OPTS, workload="lin-kv-bug-double-vote"),
        ]}, store)
    summary = run_campaign(cdir, log=lambda *a, **k: None)
    assert summary["ran"] == 2 and summary["done"] == 2
    assert summary["failed"] == 0 and summary["invalid"] == 1
    status = campaign_status(cdir)
    assert status["counts"] == {"done": 2}
    rep = campaign_report(cdir, static_cost=False)
    assert rep["valid?"] is False
    by_wl = rep["trends"]
    assert by_wl["echo"]["valid"] == 1
    assert by_wl["lin-kv-bug-double-vote"]["invalid"] == 1
    assert os.path.exists(os.path.join(cdir, "summary.json"))
    # items landed in the store with collision-free tagged dirs
    runs = glob.glob(os.path.join(store, "*-tpu", "*item*"))
    assert len(runs) == 2
    # serve renders the campaign page with the trend table
    from maelstrom_tpu.serve import _run_page
    page = _run_page(store, cqueue.CAMPAIGNS_SUBDIR,
                     os.path.basename(cdir)).decode()
    assert "Trends (per workload)" in page
    assert "lin-kv-bug-double-vote" in page


# --- store-dir bugfix ------------------------------------------------------


def test_prepare_store_dir_concurrent_collision_free(tmp_path):
    """Two runs sharing a test name: distinct dirs, and `latest` always
    resolves to an existing run dir mid-churn (atomic repoint)."""
    store = str(tmp_path)
    dirs, errors = [], []

    def spin(k):
        try:
            for _ in range(8):
                dirs.append(prepare_store_dir("echo", store))
        except Exception as e:   # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=spin, args=(k,))
               for k in range(4)]
    stop = [False]
    seen_bad = []

    def reader():
        latest = os.path.join(store, "echo-tpu", "latest")
        while not stop[0]:
            if os.path.lexists(latest) and not os.path.exists(latest):
                seen_bad.append("dangling")   # pragma: no cover
    watcher = threading.Thread(target=reader)
    watcher.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop[0] = True
    watcher.join()
    assert not errors
    assert len(dirs) == len(set(dirs)) == 32
    assert not seen_bad
    latest = os.path.join(store, "echo-tpu", "latest")
    assert os.path.isdir(os.path.realpath(latest))
    # campaign items: human-readable tagged names
    d = prepare_store_dir("echo", store, tag="item7")
    assert d.endswith("-item7")


# --- watch -----------------------------------------------------------------


def _spawn_watch(args, cwd):
    env = dict(os.environ,
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    return subprocess.Popen(
        [sys.executable, "-m", "maelstrom_tpu", "watch"] + args,
        cwd=cwd, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def test_watch_follow_terminates_on_run_end(tmp_path):
    """--follow exits 0 by itself once the run-end record lands (the
    regression this satellite pins), and reports the resume seam."""
    run = tmp_path / "run"
    run.mkdir()
    hb = open(run / "heartbeat.jsonl", "w")

    def rec(obj):
        hb.write(json.dumps(obj) + "\n")
        hb.flush()

    rec({"type": "run-start", "schema": 1, "workload": "echo",
         "instances": 4, "ticks": 200, "chunk-ticks": 100})
    proc = _spawn_watch(["run", "--follow", "--interval", "0.1"],
                        str(tmp_path))
    time.sleep(0.4)
    rec({"type": "chunk", "chunk": 0, "t0": 0, "ticks": 100,
         "wall-s": 0.1, "net": {"sent": 5, "delivered": 5},
         "first-violation": None, "events-overflowed": False})
    rec({"type": "resume", "schema": 1, "from-ticks": 100})
    rec({"type": "chunk", "chunk": 1, "t0": 100, "ticks": 100,
         "wall-s": 0.2, "net": {"sent": 9, "delivered": 9},
         "first-violation": None, "events-overflowed": False})
    rec({"type": "run-end", "status": "complete", "chunks": 2,
         "ticks": 200, "wall-s": 0.5, "first-violation": None,
         "valid?": True})
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0, out
    assert "status: complete" in out
    assert "chunk   1" in out


def test_watch_campaign_mode(tmp_path):
    cdir = _tiny_campaign(str(tmp_path), n=2)
    c0 = cqueue.claim_next(cdir)
    cqueue.finish_item(c0, cqueue.DONE, **{"valid?": True})
    proc = _spawn_watch([os.path.relpath(cdir, str(tmp_path)),
                         "--campaign"], str(tmp_path))
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 3, out   # not settled: item 1 pending
    assert "done 1" in out and "pending 1" in out
    c1 = cqueue.claim_next(cdir)
    cqueue.finish_item(c1, cqueue.FAILED, error="x")
    proc = _spawn_watch([os.path.relpath(cdir, str(tmp_path)),
                         "--campaign"], str(tmp_path))
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0, out   # settled


# --- retries-with-backoff (spec `retries`/`backoff-s` keys) ----------------


def test_failed_item_requeues_with_backoff(tmp_path):
    """A FAILED (crashed, not invalid) item with `retries` re-queues
    with exponential backoff recorded on the item JSON, is skipped
    while its window runs, and lands FAILED only after the budget is
    spent — with `failures`/`backoff-history` on the record."""
    cdir = cqueue.submit_campaign(
        {"name": "retry",
         "items": [{"workload": "no-such-workload", "retries": 2,
                    "backoff_s": 0.2}]}, str(tmp_path))
    item = json.load(open(cqueue.item_path(cdir, 0)))
    # policy keys lifted off opts onto the item record
    assert item["retries"] == 2 and item["backoff-s"] == 0.2
    assert "retries" not in item["opts"]

    summary = run_campaign(cdir, store_root=str(tmp_path),
                           log=lambda *a: None)
    # 3 attempts ran (1 + 2 retries): two re-queues, one terminal fail
    assert summary["ran"] == 3
    assert summary["retried"] == 2 and summary["failed"] == 1
    item = json.load(open(cqueue.item_path(cdir, 0)))
    assert item["status"] == cqueue.FAILED
    assert item["failures"] == 3 and item["attempts"] == 3
    # exponential: each recorded wait doubles the previous
    hist = item["backoff-history"]
    assert hist == [0.2, 0.4]
    assert "no-such-workload" in (item.get("error") or "") \
        or item.get("error")


def test_backoff_window_blocks_claims(tmp_path):
    cdir = _tiny_campaign(str(tmp_path), n=1)
    claim = cqueue.claim_next(cdir)
    # simulate the runner's retry re-queue: pending, but not before
    # a future instant
    cqueue.finish_item(claim, cqueue.PENDING, failures=1,
                       **{"not-before": time.time() + 30.0})
    assert cqueue.claim_next(cdir) is None   # window still running
    eta = cqueue.next_retry_eta(cdir)
    assert eta is not None and eta > time.time()
    # an elapsed window is claimable again
    item = json.load(open(cqueue.item_path(cdir, 0)))
    item["not-before"] = time.time() - 1.0
    cqueue.write_json_atomic(cqueue.item_path(cdir, 0), item)
    assert cqueue.next_retry_eta(cdir) is None
    claim = cqueue.claim_next(cdir)
    assert claim is not None and claim.item["id"] == 0


def test_status_and_report_show_attempt_counts(tmp_path):
    from maelstrom_tpu.campaign.report import (campaign_report,
                                               campaign_status,
                                               render_status)
    cdir = cqueue.submit_campaign(
        {"name": "retry2",
         "items": [{"workload": "no-such-workload", "retries": 1,
                    "backoff_s": 0.05}]}, str(tmp_path))
    run_campaign(cdir, store_root=str(tmp_path), log=lambda *a: None)
    status = campaign_status(cdir)
    row = status["items"][0]
    assert row["attempts"] == 2 and row["failures"] == 2
    assert "failures 2/1" in render_status(status)
    report = campaign_report(cdir, static_cost=False, write=False)
    assert report["items"][0]["failures"] == 2
