"""Datomic-style transactors: the hash-tree page version must pass
strict serializability AND abort >=2x less than the single-root version
under CAS contention (VERDICT r1 missing #4; reference
demo/ruby/datomic_list_append.rb:3-40)."""

import os
import sys

from maelstrom_tpu import run_test
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

OPTS = dict(bin=sys.executable, node_count=3, time_limit=10.0,
            rate=15.0, concurrency=8, latency=15.0, seed=12)


def _run(node):
    return run_test("txn-list-append", dict(
        OPTS, bin_args=[os.path.join(REPO, "examples", "python", node)]))


def test_hash_tree_transactor_fewer_aborts_than_single_root():
    tree = _run("datomic_list_append.py")
    single = _run("datomic_txn.py")
    assert tree["valid?"] is True, tree.get("workload")
    assert single["valid?"] is True, single.get("workload")
    tree_aborts = tree["stats"]["fail-count"]
    single_aborts = single["stats"]["fail-count"]
    assert single_aborts >= 2 * max(tree_aborts, 1) or tree_aborts == 0, \
        (tree_aborts, single_aborts)
    assert tree["stats"]["ok-count"] > 30
