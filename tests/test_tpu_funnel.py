"""The invariant-trip -> full-check funnel (SURVEY §7: "full checkers on
samples + any instance whose invariants trip"; VERDICT r3 next #3).

Rests on instance-stable RNG: an instance's trajectory is a pure
function of (seed, instance id), so any subset of a big batch can be
re-simulated bit-exactly with recording enabled. These tests pin that
property first, then the funnel built on it — a buggy-Raft fleet where
every tripped instance yields a checkable history and a per-instance
checker verdict, matching the reference's explainable-anomaly bar
(Knossos witnesses, /root/reference/src/maelstrom/workload/lin_kv.clj:78-85).
"""

import numpy as np
import pytest

from maelstrom_tpu.models.raft import RaftModel
from maelstrom_tpu.models.raft_buggy import RaftNoTermGuard
from maelstrom_tpu.tpu.harness import (make_sim_config, replay_instances,
                                       run_tpu_test)
from maelstrom_tpu.tpu.runtime import scripted_isolate_groups

BASE = dict(node_count=3, concurrency=3, time_limit=2.0, rate=40.0,
            latency=10.0, rpc_timeout=0.8, nemesis=["partition"],
            nemesis_interval=0.25, p_loss=0.05, recovery_time=0.3,
            seed=11)


def _rotating_majorities_schedule(n=5, phase_len=200, horizon_ticks=3500):
    groups_cycle = [({0, 1, 2},), ({2, 3, 4},), ({4, 0, 1},),
                    ({1, 2, 3},), ({3, 4, 0},)]
    sched, t, i = [], 0, 0
    while t < horizon_ticks - 500:
        t += phase_len
        sched.append(scripted_isolate_groups(t, groups_cycle[i % 5], n))
        i += 1
    return tuple(sched)


# the Figure-8 recipe (see test_tpu_raft.py): rotating 3-node majorities
# make RaftNoTermGuard's §5.4.2 commit bug trip the on-device
# truncated-committed witness on a sizable fraction of instances
FIGURE8 = dict(node_count=5, concurrency=4, time_limit=3.5, rate=60.0,
               latency=5.0, rpc_timeout=0.8, nemesis=["partition"],
               nemesis_kind="scripted",
               nemesis_schedule=_rotating_majorities_schedule(),
               recovery_time=0.5, seed=11)


@pytest.mark.slow
def test_instance_trajectory_independent_of_batch():
    """Instance k's history must be identical whether it runs in a batch
    of 16 or alone via replay_instances — the bit-exactness the whole
    funnel rests on."""
    model = RaftModel(n_nodes_hint=3)
    opts = {**BASE, "n_instances": 16, "record_instances": 16,
            "funnel": False}
    res = run_tpu_test(model, opts)

    # replay a scattered subset of the batch; histories must match the
    # full run's recordings bit-for-bit
    ids = [3, 7, 12]
    import jax.numpy as jnp
    from maelstrom_tpu.tpu.harness import events_to_histories
    from maelstrom_tpu.tpu.runtime import run_sim

    sim_full = make_sim_config(model, opts)
    params = model.make_params(sim_full.net.n_nodes)
    _, ys_full = run_sim(model, sim_full, opts["seed"], params)
    full_events = np.asarray(ys_full.events)

    rep = replay_instances(model, opts, ids)
    sub_opts = {**opts, "n_instances": len(ids),
                "record_instances": len(ids)}
    sim_sub = make_sim_config(model, sub_opts)
    _, ys_sub = run_sim(model, sim_sub, opts["seed"], params,
                        jnp.asarray(ids, dtype=jnp.int32))
    sub_events = np.asarray(ys_sub.events)
    for j, iid in enumerate(ids):
        assert np.array_equal(full_events[:, iid], sub_events[:, j]), \
            f"instance {iid} diverged between batch-of-16 and replay"
    # and the decoded histories in the replay helper agree too
    full_hists = events_to_histories(
        model, full_events, final_start=sim_full.client.final_start)
    for iid in ids:
        assert rep["histories"][iid] == full_hists[iid]


@pytest.mark.slow
def test_funnel_explains_tripped_instances(tmp_path):
    """A buggy-Raft fleet at scale: instances whose on-device invariants
    trip land OUTSIDE the recorded window, yet the funnel still yields a
    checkable history + checker verdict for each (up to funnel_max) —
    and the store gets one funnel-history-<id>.jsonl per tripped
    instance, named by its ORIGINAL batch index."""
    import glob
    import json
    import os

    res = run_tpu_test(RaftNoTermGuard(n_nodes_hint=5, log_cap=64), dict(
        **FIGURE8, n_instances=96, record_instances=2, funnel_max=6,
        store_root=str(tmp_path)))
    inv = res["invariants"]
    assert inv["violating-instances"] > 0, \
        "mutant produced no invariant trips at this config/seed"
    # trips must exist beyond the recorded window for the test to mean
    # anything (otherwise plain recording would have covered them)
    assert any(i >= 2 for i in inv["violating-instance-ids"])
    fun = res["funnel"]
    assert fun["ids"] == inv["violating-instance-ids"][:len(fun["ids"])]
    # the replay must re-trip the SAME instances' invariants — the
    # self-check that the replay really was bit-exact
    assert fun["replayed-violating"] == len(fun["ids"])
    assert len(fun["verdicts"]) == len(fun["ids"])
    for v in fun["verdicts"]:
        assert "valid?" in v and "instance" in v
        assert v["ops"] > 0, "funnel history is empty - not checkable"

    run_dir = os.path.join(
        str(tmp_path), "lin-kv-bug-no-term-guard-tpu", "latest")
    stored = sorted(glob.glob(os.path.join(run_dir,
                                           "funnel-history-*.jsonl")))
    assert stored
    ids = {int(os.path.basename(p).split("-")[-1].split(".")[0])
           for p in stored}
    assert ids == set(fun["ids"])
    for p in stored:
        records = [json.loads(l) for l in open(p) if l.strip()]
        assert any(r["type"] == "invoke" for r in records)
    # results.json carries the verdicts without the raw histories
    results = json.load(open(os.path.join(run_dir, "results.json")))
    assert "histories" not in results["funnel"]
    assert results["funnel"]["verdicts"]


def test_replay_instances_smoke():
    """Fast path proof that subset replay works at all: replayed
    histories exist, are non-empty, and re-running the same ids gives
    identical histories (determinism at the API boundary)."""
    model = RaftModel(n_nodes_hint=3)
    opts = {**BASE, "n_instances": 6, "time_limit": 0.6, "funnel": False}
    a = replay_instances(model, opts, [1, 4])
    b = replay_instances(model, opts, [1, 4])
    assert set(a["histories"]) == {1, 4}
    assert all(len(h) > 0 for h in a["histories"].values())
    assert a["histories"] == b["histories"]
