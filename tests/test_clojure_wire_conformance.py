"""Runtime-independent wire conformance for the Clojure (babashka) SDK
+ nodes — the seventh SDK language (the reference's broadest demo set,
demo/clojure/, 2k LoC). No babashka/JVM ships in this image, so the
sources are validated statically like the JS/Go/Ruby/Java suites; the
e2e suite (test_clojure_nodes.py) runs when a `bb` binary appears."""

import os
import re

import pytest

from wire_conformance_common import (assert_error_codes_in_catalog,
                                     assert_node_reply_types)

CLJ_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples", "clojure")

SDK = open(os.path.join(CLJ_DIR, "maelstrom.clj")).read()

NODES = {
    "echo.clj": ("echo", set()),
    "broadcast.clj": ("broadcast", {"gossip"}),
    "counter.clj": ("g-counter", set()),
}


def _literal_types(src):
    return set(re.findall(r':type\s+"([a-z_]+)"', src))


def test_sdk_envelope_shape():
    assert ":src @node-id :dest dest :body body" in SDK
    assert ":in_reply_to" in SDK and ":msg_id" in SDK


def test_sdk_init_handshake():
    assert '"init_ok"' in SDK
    assert ":node_id" in SDK and ":node_ids" in SDK


def test_sdk_error_codes_in_catalog():
    codes = {int(c) for c in re.findall(
        r"\(def err-[a-z-]+ (\d+)\)", SDK)}
    assert_error_codes_in_catalog(codes)


def test_kv_client_speaks_service_schema():
    for field in (':type "read" :key', ':type "write" :key',
                  ':type "cas" :key', ":value v", ":from from",
                  ":to to", ":create_if_not_exists"):
        assert field in SDK, field


@pytest.mark.parametrize("name", sorted(NODES))
def test_node_reply_types_in_registry(name):
    namespace, internal = NODES[name]
    src = open(os.path.join(CLJ_DIR, name)).read()
    emitted = _literal_types(src)
    assert_node_reply_types(namespace, internal, emitted, name)
