"""SPMD partition-auditor tests (analysis/shard_audit.py).

Pins the PR's acceptance bars: each planted shard fixture trips its
SHD8xx rule in BOTH carry layouts, the collective census classifies
tick-hot-loop vs per-dispatch collectives with scan-trip weighting,
the ICI ring formulas are exact, manifest drift/missing/stale/update
detection works (including the jax-version skew downgrade), the
combined gate pays one trace per model x layout through the shared
cache, the static reshardability proof (SHD809) passes on real models
and fires on broken metadata, and the checked-in manifest covers the
whole registry at every audited mesh size.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from maelstrom_tpu.analysis import cost_model, shard_audit
from maelstrom_tpu.analysis.findings import fingerprint_pass
from maelstrom_tpu.analysis.shard_audit import (DEFAULT_SHARD_MANIFEST,
                                                MESH_SIZES,
                                                census_of_jaxpr,
                                                compare_manifest,
                                                entry_of_census,
                                                ici_bytes_of,
                                                load_shard_manifest,
                                                reshard_findings,
                                                run_shard_lint,
                                                save_shard_manifest,
                                                shard_stats, size_key,
                                                trace_sharded_chunk,
                                                trace_sharded_run)
from maelstrom_tpu.models.echo import EchoModel
from maelstrom_tpu.models.ir_hazards import (SHARD_FIXTURE_MODELS,
                                             IrShardCrossTalk,
                                             IrShardReplicatedLeaf)

pytestmark = pytest.mark.shard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


def _echo_census(layout="lead"):
    model = EchoModel()
    sim = cost_model.audit_sim(model, 2, layout)
    closed, _ = trace_sharded_chunk(model, sim)
    return model, sim, census_of_jaxpr(closed)


# --- the planted fixtures trip their rules ---------------------------------


class TestFixturesTrip:
    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_cross_talk_trips_shd801_and_803(self, layout):
        model = IrShardCrossTalk()
        sim = cost_model.audit_sim(model, 2, layout)
        closed, _ = trace_sharded_chunk(model, sim)
        census = census_of_jaxpr(closed)
        fs = shard_audit.hot_loop_findings(model, census, layout,
                                           "shard-cross-talk")
        assert {"SHD801", "SHD803"} <= _rules(fs)
        assert all(f.severity == "error" for f in fs)
        # the gather and the psum both live in the TICK bucket —
        # scan-trip-weighted to per-tick, not per-dispatch
        assert census["tick"]["all_gather"]["count"] == 1
        assert census["tick"]["psum"]["count"] == 1

    @pytest.mark.parametrize("layout", ["lead", "minor"])
    def test_replicated_leaf_trips_shd802(self, layout):
        model = IrShardReplicatedLeaf()
        sim = cost_model.audit_sim(model, 2, layout)
        fs = shard_audit.replicated_leaf_findings(model, sim, layout)
        assert _rules(fs) == {"SHD802"}
        assert "per_instance_cache" in fs[0].message

    def test_honest_echo_is_clean(self):
        model, sim, census = _echo_census()
        assert census["tick"] == {}
        assert shard_audit.hot_loop_findings(model, census, "lead",
                                             "echo") == []
        assert shard_audit.replicated_leaf_findings(model, sim,
                                                    "lead") == []

    def test_small_per_instance_leaf_is_under_the_floor(self):
        """A tiny table whose leading dim happens to equal the
        instance count must NOT flag — the 16 KiB floor."""
        class SmallLeaf(EchoModel):
            name = "echo-shard-small-leaf"

            def make_params(self, n_nodes):
                return {"t": jax.numpy.zeros((4, 8), jax.numpy.int32)}

        model = SmallLeaf()
        sim = cost_model.audit_sim(model, 2, "lead")
        assert shard_audit.replicated_leaf_findings(model, sim,
                                                    "lead") == []


# --- census mechanics + ICI formulas ---------------------------------------


class TestCensus:
    def test_run_subject_merges_stats_at_dispatch_not_tick(self):
        """The fleet-stats psums of the single-dispatch runner sit
        OUTSIDE the scanned tick body: dispatch-level plumbing, not
        per-tick ICI traffic (their exact count is pinned by the
        manifest, not hardcoded here)."""
        model = EchoModel()
        sim = cost_model.audit_sim(model, 2, "lead")
        census = census_of_jaxpr(trace_sharded_run(model, sim))
        assert census["tick"] == {}
        assert census["dispatch"]["psum"]["count"] >= 5

    def test_census_is_mesh_size_invariant(self):
        model = EchoModel()
        sim = cost_model.audit_sim(model, 2, "lead")
        a = census_of_jaxpr(trace_sharded_chunk(model, sim, 2)[0])
        b = census_of_jaxpr(trace_sharded_chunk(model, sim, 8)[0])
        assert a == b

    def test_ici_ring_formulas(self):
        b = 1000
        assert ici_bytes_of("psum", b, 1) == 0
        assert ici_bytes_of("all_gather", b, 1) == 0
        assert ici_bytes_of("psum", b, 4) == 2 * b * 3 // 4
        assert ici_bytes_of("pmax", b, 8) == 2 * b * 7 // 8
        assert ici_bytes_of("all_gather", b, 4) == 3 * b
        assert ici_bytes_of("psum_scatter", b, 4) == b * 3 // 4
        assert ici_bytes_of("all_to_all", b, 8) == b * 7 // 8
        assert ici_bytes_of("ppermute", b, 4) == b

    def test_entry_of_census_scales_with_mesh_size(self):
        model = IrShardCrossTalk()
        sim = cost_model.audit_sim(model, 2, "lead")
        census = census_of_jaxpr(trace_sharded_chunk(model, sim)[0])
        e1 = entry_of_census(census, 1)
        e8 = entry_of_census(census, 8)
        # counts are size-invariant; the ICI estimate is not
        assert e1["tick-collectives"] == e8["tick-collectives"]
        assert e1["ici-bytes-per-tick"] == 0
        assert e8["ici-bytes-per-tick"] > 0

    def test_shard_stats_surface(self):
        model = IrShardCrossTalk()
        sim = cost_model.audit_sim(model, 2, "lead")
        cache = {}
        st = shard_stats(model, sim, cache=cache)
        assert st["collectives_per_tick"] == 2
        assert st["ici_bytes_est"] > 0
        # the census rode the shared cache under a shard: key, and a
        # second call serves from it (no retrace)
        assert any(k.startswith("shard:") for k in cache)
        assert shard_stats(model, sim, cache=cache) == st
        # the cost_model delegation returns the same figures
        assert cost_model.tick_shard_stats(model, sim,
                                           cache=cache) == st


# --- the manifest gate -----------------------------------------------------


def _echo_live():
    model, sim, census = _echo_census()
    live, paths = {}, {}
    for s in MESH_SIZES:
        key = size_key("echo", 2, "lead", s)
        live[key] = entry_of_census(census, s)
        paths[key] = ("maelstrom_tpu/models/echo.py", "EchoModel")
    return live, paths


class TestManifestGate:
    def test_roundtrip_and_entry_contract(self, tmp_path):
        live, _ = _echo_live()
        p = str(tmp_path / "m.json")
        save_shard_manifest(live, p)
        data = load_shard_manifest(p)
        assert data["jax-version"] == jax.__version__
        assert data["entries"] == live
        for ent in data["entries"].values():
            assert set(ent) == {"tick-collectives",
                                "tick-collective-bytes",
                                "dispatch-collectives",
                                "ici-bytes-per-tick",
                                "ici-bytes-per-dispatch"}

    def test_clean_compare_is_silent(self):
        live, paths = _echo_live()
        manifest = {"jax-version": jax.__version__,
                    "entries": dict(live)}
        assert compare_manifest(live, manifest, paths) == []

    def test_tampered_ici_bytes_trip_shd807_error(self):
        live, paths = _echo_live()
        entries = {k: dict(v) for k, v in live.items()}
        key = size_key("echo", 2, "lead", 8)
        entries[key]["ici-bytes-per-dispatch"] = (
            entries[key]["ici-bytes-per-dispatch"] * 2 + 4096)
        manifest = {"jax-version": jax.__version__, "entries": entries}
        fs = compare_manifest(live, manifest, paths)
        assert [f.rule for f in fs] == ["SHD807"]
        assert fs[0].severity == "error"
        assert key in fs[0].message

    def test_count_change_trips_shd807_exactly(self):
        """Collective COUNTS compare exactly — a new collective is
        never 'within tolerance'."""
        live, paths = _echo_live()
        entries = {k: dict(v) for k, v in live.items()}
        key = size_key("echo", 2, "lead", 2)
        entries[key]["tick-collectives"] = {"all_gather": 1}
        manifest = {"jax-version": jax.__version__, "entries": entries}
        fs = compare_manifest(live, manifest, paths)
        assert [f.rule for f in fs] == ["SHD807"]
        assert "tick-collectives" in fs[0].message

    def test_drift_downgrades_under_toolchain_skew(self):
        live, paths = _echo_live()
        entries = {k: dict(v) for k, v in live.items()}
        key = size_key("echo", 2, "lead", 8)
        entries[key]["ici-bytes-per-dispatch"] += 10 ** 9
        manifest = {"jax-version": "0.0.1-not-this-toolchain",
                    "entries": entries}
        fs = compare_manifest(live, manifest, paths)
        assert [f.rule for f in fs] == ["SHD807"]
        assert fs[0].severity == "warning"
        assert "--update-shard-manifest" in fs[0].message

    def test_missing_and_stale_entries(self):
        live, paths = _echo_live()
        manifest = {"jax-version": jax.__version__,
                    "entries": {"ghost/n=9/lead/s=2": {}}}
        fs = compare_manifest(live, manifest, paths,
                              full_universe=True)
        assert _rules(fs) == {"SHD805", "SHD806"}
        # restricted runs never report staleness
        fs = compare_manifest(live, manifest, paths,
                              full_universe=False)
        assert _rules(fs) == {"SHD805"}

    def test_errored_keys_are_not_stale(self):
        live, paths = _echo_live()
        key = size_key("echo", 2, "minor", 2)
        manifest = {"jax-version": jax.__version__,
                    "entries": {**live, key: {}}}
        fs = compare_manifest(live, manifest, paths,
                              full_universe=True, errored={key})
        assert fs == []

    def test_checked_in_manifest_covers_registry(self):
        data = load_shard_manifest(DEFAULT_SHARD_MANIFEST)
        entries = data["entries"]
        for wl, n in cost_model.cost_specs():
            for layout in cost_model.AUDIT_LAYOUTS:
                for s in MESH_SIZES:
                    assert size_key(wl, n, layout, s) in entries
        # plus the single-dispatch runner subject
        assert any(k.startswith("run:") for k in entries)

    def test_restricted_run_gates_clean_against_checked_in(self):
        fs = run_shard_lint(workloads=[("echo", 2)])
        assert [f for f in fs if f.severity == "error"] == []

    def test_update_records_and_regates_clean(self, tmp_path):
        p = str(tmp_path / "m.json")
        fs = run_shard_lint(workloads=[("echo", 2)], manifest_path=p,
                            update_manifest=True)
        assert _rules(fs) == {"SHD808"}
        fs = run_shard_lint(workloads=[("echo", 2)], manifest_path=p)
        assert fs == []
        # tamper: the gate must notice
        data = json.load(open(p))
        key = size_key("echo", 2, "lead", 8)
        data["entries"][key]["ici-bytes-per-dispatch"] += 10 ** 9
        json.dump(data, open(p, "w"))
        fs = run_shard_lint(workloads=[("echo", 2)], manifest_path=p)
        assert "SHD807" in _rules(fs)


# --- shared-cache economy + pass wiring ------------------------------------


class TestWiring:
    def test_single_trace_per_model_via_shared_cache(self, monkeypatch):
        cache = {}
        run_shard_lint(workloads=[("echo", 2)], trace_cache=cache)
        # both the plain tick trace and the sharded census landed in
        # the shared cache, one per layout
        assert {k for k in cache if k.startswith("shard:")} == {
            "shard:echo/n=2/lead", "shard:echo/n=2/minor"}
        assert "echo/n=2/lead" in cache and "echo/n=2/minor" in cache

        def boom(*a, **k):                       # pragma: no cover
            raise AssertionError("retraced despite warm cache")
        monkeypatch.setattr(shard_audit, "trace_sharded_chunk", boom)
        fs = run_shard_lint(workloads=[("echo", 2)],
                            trace_cache=cache)
        assert [f for f in fs if f.rule == "SHD800"] == []

    def test_shd_fingerprints_map_to_shard_pass(self):
        assert fingerprint_pass(
            "SHD801:maelstrom_tpu/models/ir_hazards.py:"
            "IrShardCrossTalk") == "shard"

    def test_shard_is_an_extra_pass(self):
        from maelstrom_tpu.analysis.runner import (ALL_PASSES,
                                                   EXTRA_PASSES)
        assert "shard" in EXTRA_PASSES
        assert "shard" not in ALL_PASSES

    def test_model_failure_trips_shd800(self):
        fs = run_shard_lint(workloads=[("no-such-workload", 2)])
        assert "SHD800" in _rules(fs)
        # its manifest keys are excluded from staleness via `errored`,
        # and a failed model contributes no live entries
        assert not any(f.rule == "SHD806" for f in fs)


# --- SHD809: static reshardability -----------------------------------------


class TestReshardProof:
    def test_echo_carry_is_reshardable(self):
        model = EchoModel()
        sim = cost_model.audit_sim(model, 2, "lead")
        assert reshard_findings(model, sim, "lead") == []

    def test_broken_kind_metadata_trips_shd809(self, monkeypatch):
        from maelstrom_tpu.parallel import mesh as mesh_mod
        model = EchoModel()
        sim = cost_model.audit_sim(model, 2, "lead")
        real = mesh_mod.wire_leaf_kinds
        monkeypatch.setattr(
            mesh_mod, "wire_leaf_kinds",
            lambda *a, **k: real(*a, **k)[:-1])
        fs = reshard_findings(model, sim, "lead")
        assert _rules(fs) == {"SHD809"}
        assert "cannot be resharded" in fs[0].message


# --- the tunnel-down probe artifact ----------------------------------------


class TestMultichipProbe:
    def test_unreachable_is_a_distinct_status(self, tmp_path):
        """On a CPU-only host the probe must write an artifact that
        SAYS the tunnel is down — never a stale healthy-looking one."""
        out = str(tmp_path / "MULTICHIP_rtest.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "multichip_probe"),
             "--round", "test", "--out", out, "--probe-s", "60"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 2, proc.stderr
        rec = json.load(open(out))
        assert rec["status"] == "unreachable"
        assert "probe_rc" in rec and "ts" in rec
