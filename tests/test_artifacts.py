"""Store-dir artifact tests: history/results/journal/plots land on disk
(reference doc/results.md store layout)."""

import json
import os

from conftest import example_bin
from maelstrom_tpu.runner import run_test


def test_store_artifacts(tmp_path):
    bin_cmd = example_bin("echo.py")
    res = run_test("echo", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:], node_count=1,
        time_limit=1.0, rate=20.0, concurrency=2, seed=1,
        store_root=str(tmp_path), snapshot_store=True))
    assert res["valid?"] is True
    d = os.path.join(str(tmp_path), "echo")
    runs = [p for p in os.listdir(d) if p != "latest"]
    assert len(runs) == 1
    run_dir = os.path.join(d, runs[0])
    for artifact in ("history.jsonl", "history.txt", "results.json",
                     "messages.svg", "timeline.html",
                     "latency-raw.svg", "latency-quantiles.svg",
                     "rate.svg", "net-journal",
                     "node-logs"):
        assert os.path.exists(os.path.join(run_dir, artifact)), artifact
    assert os.path.islink(os.path.join(d, "latest"))
    with open(os.path.join(run_dir, "results.json")) as f:
        assert json.load(f)["valid?"] is True
    with open(os.path.join(run_dir, "history.jsonl")) as f:
        lines = [json.loads(l) for l in f]
    assert lines and lines[0]["index"] == 0
    assert {"invoke", "ok"} <= {l["type"] for l in lines}


def test_cli_test_command(tmp_path):
    from maelstrom_tpu.cli import main
    import conftest
    bin_cmd = conftest.example_bin("echo.py")
    rc = main(["test", "-w", "echo", "--bin", bin_cmd[1],
               "--node-count", "1", "--time-limit", "1", "--rate", "20",
               "--store", str(tmp_path)])
    assert rc == 0


def test_cli_doc_command(tmp_path):
    from maelstrom_tpu.cli import main
    rc = main(["doc", "--out", str(tmp_path)])
    assert rc == 0
    text = (tmp_path / "workloads.md").read_text()
    assert "## lin-kv" in text and "### cas" in text
    proto = (tmp_path / "protocol.md").read_text()
    assert "precondition-failed" in proto


def test_cli_concurrency_parsing():
    from maelstrom_tpu.cli import parse_concurrency
    assert parse_concurrency("10", 5) == 10
    assert parse_concurrency("4n", 5) == 20


def test_offline_check_command(tmp_path):
    """`check` re-runs checkers on a stored history: a clean run
    re-checks valid (rc 0); a history with a planted safety violation
    fails (rc 1)."""
    from maelstrom_tpu.cli import main

    bin_cmd = example_bin("echo.py")
    run_test("echo", dict(
        bin=bin_cmd[0], bin_args=bin_cmd[1:], node_count=1,
        time_limit=1.0, rate=20.0, concurrency=2, seed=1,
        store_root=str(tmp_path), snapshot_store=True))
    run_dir = os.path.join(str(tmp_path), "echo", "latest")
    assert main(["check", run_dir]) == 0
    # workload inference from the store path: no -w needed above; a bare
    # file needs it
    hist = os.path.join(run_dir, "history.jsonl")
    assert main(["check", hist]) == 2  # no workload inferable
    assert main(["check", hist, "-w", "echo"]) == 0

    # planted violation: a broadcast value acknowledged but never read
    bad = tmp_path / "bad.jsonl"
    records = [
        {"index": 0, "time": 0, "process": 0, "type": "invoke",
         "f": "broadcast", "value": 7},
        {"index": 1, "time": 1, "process": 0, "type": "ok",
         "f": "broadcast", "value": 7},
        {"index": 2, "time": 2, "process": 1, "type": "invoke",
         "f": "read", "value": None},
        {"index": 3, "time": 3, "process": 1, "type": "ok",
         "f": "read", "value": []},
    ]
    with open(bad, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    assert main(["check", str(bad), "-w", "broadcast"]) == 1
