import os
import sys

# TPU-runtime tests run on a virtual 8-device CPU mesh. A sitecustomize
# hook may have imported jax (pointing at a real accelerator) before this
# file runs, so updating os.environ alone is not enough — override the
# already-imported config too. Backends are initialized lazily, so this
# works as long as no device was touched yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def example_bin(name: str) -> list:
    """Command line for a bundled example node."""
    return [sys.executable, os.path.join(REPO, "examples", "python", name)]
