import os
import sys

import pytest

# TPU-runtime tests run on a virtual 8-device CPU mesh. A sitecustomize
# hook may have imported jax (pointing at a real accelerator) before this
# file runs, so updating os.environ alone is not enough — override the
# already-imported config too. Backends are initialized lazily, so this
# works as long as no device was touched yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: identical sim configs recompile in every
# pytest process otherwise (the suite's dominant cost — VERDICT r3 weak
# #7). Cached executables are keyed on HLO + compile options, so this is
# purely a wall-clock lever.
_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

# The certified AOT executable store (tpu/aot_store.py) is OFF for the
# suite: populating it is deliberately expensive (a populate compile
# bypasses the persistent XLA cache above, so every store miss is a
# REAL compile), and any source edit re-keys the whole store — letting
# the ~700 incidental run_tpu_test calls repopulate it would blow the
# tier-1 wall-clock budget on every first run after a change.
# tests/test_aot.py re-enables it per-module and exercises the store
# deliberately with explicit store dirs.
os.environ.setdefault("MAELSTROM_AOT", "0")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavyweight test (big sims / long e2e runs); "
                   "deselected by -m fast")
    config.addinivalue_line(
        "markers", "fast: auto-applied to every non-slow test; "
                   "`pytest -m fast` is the <2-minute sweep — every "
                   "component keeps at least one fast representative "
                   "(meta-tests like time-to-anomaly are slow-only)")
    config.addinivalue_line(
        "markers", "telemetry: flight-recorder / fleet-stats "
                   "observability tests (doc/observability.md)")
    config.addinivalue_line(
        "markers", "pipeline: chunked donated executor / event "
                   "compaction tests (tpu/pipeline.py)")
    config.addinivalue_line(
        "markers", "triage: streaming heartbeat / watch / triage "
                   "forensics tests (telemetry/stream.py, "
                   "checkers/triage.py)")
    config.addinivalue_line(
        "markers", "ir: IR-level lint / cost-model tests "
                   "(analysis/ir_lint.py, analysis/cost_model.py)")
    config.addinivalue_line(
        "markers", "fusion: compartmentalized node-step bit-identity "
                   "/ cost tests (models/raft_core.py)")
    config.addinivalue_line(
        "markers", "lanes: lane-liveness dataflow / manifest tests "
                   "(analysis/lane_liveness.py)")
    config.addinivalue_line(
        "markers", "ranges: value-range abstract-interpreter / "
                   "range-manifest tests (analysis/absint.py)")
    config.addinivalue_line(
        "markers", "campaign: durable control-plane tests — "
                   "checkpoint/resume, run queue, trend store "
                   "(maelstrom_tpu/campaign/)")
    config.addinivalue_line(
        "markers", "faults: device-resident fault-plan engine tests — "
                   "crash-restart, link degradation, clock skew, "
                   "planted-bug anomaly matrix (maelstrom_tpu/faults/)")
    config.addinivalue_line(
        "markers", "fuzz: randomized per-instance fault-schedule "
                   "fuzzer tests — schedule-RNG lane, seed-stable "
                   "reconstruction, shrinking "
                   "(maelstrom_tpu/faults/fuzz.py, shrink.py)")
    config.addinivalue_line(
        "markers", "pool: parallel host verdict pipeline tests — "
                   "vectorized decode identity, checker-farm "
                   "pool-vs-serial identity, kill-fallback "
                   "(tpu/decode.py, checkers/pool.py)")
    config.addinivalue_line(
        "markers", "membership: mid-run membership-change fault lane "
                   "tests — joint-consensus Raft reconfiguration, "
                   "parked-node semantics, planted reconfig bugs "
                   "(maelstrom_tpu/faults/, models/raft_core.py)")
    config.addinivalue_line(
        "markers", "shard: SPMD partition auditor / shard-manifest / "
                   "cross-mesh resume tests (analysis/shard_audit.py, "
                   "campaign/checkpoint.py reshard path)")
    config.addinivalue_line(
        "markers", "device_check: device verdict-lane tests — "
                   "summary-lane layout identity, device-vs-farm "
                   "verdict identity, flagged-set routing, "
                   "checkpoint/cross-mesh lane stability "
                   "(checkers/device_summary.py)")
    config.addinivalue_line(
        "markers", "profiler: device-time observatory tests — "
                   "profiling on/off bit-identity, heartbeat device-ms "
                   "schema, trace teardown, fallback attribution "
                   "(telemetry/profiler.py)")
    config.addinivalue_line(
        "markers", "aot: certified AOT executable-store tests — "
                   "store-key stability, cold/warm bit-identity, "
                   "prewarm key-compat, EXE9xx audit rules "
                   "(tpu/aot_store.py, analysis/aot_audit.py)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def example_bin(name: str) -> list:
    """Command line for a bundled example node."""
    return [sys.executable, os.path.join(REPO, "examples", "python", name)]


@pytest.fixture(scope="session")
def cpp_bins():
    """Build the C++ example nodes once per session; shared by the e2e
    and wire-conformance suites."""
    import shutil
    import subprocess
    if shutil.which("g++") is None:
        pytest.skip("no g++ toolchain")
    cpp_dir = os.path.join(REPO, "examples", "cpp")
    subprocess.run(["make", "-C", cpp_dir], check=True,
                   capture_output=True)
    return os.path.join(cpp_dir, "bin")
