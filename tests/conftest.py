import os
import sys

# TPU-runtime tests run on a virtual 8-device CPU mesh; must be set before
# jax import anywhere in the test session.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def example_bin(name: str) -> list:
    """Command line for a bundled example node."""
    return [sys.executable, os.path.join(REPO, "examples", "python", name)]
