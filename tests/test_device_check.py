"""Device verdict lanes (checkers/device_summary.py, ``--check-mode``).

The screening contract, pinned at byte level:

1. **Device-vs-farm verdict identity** — per-instance ``valid?`` fields
   agree across ``farm``/``device``/``both`` on every workload in both
   carry layouts (tier-1 runs a representative slice; the full matrix
   is the slow sweep), and a flagged instance's device-mode verdict is
   byte-identical to farm mode's (same farm path by construction).
2. **Planted-mutant routing** — the double-vote mutant's device-flagged
   set covers the farm-invalid oracle set (no screening gap), the
   ``both``-mode audit reports complete, and the farm receives exactly
   the flagged instances.
3. **Layout identity** — summary lane blocks are bit-identical between
   the lead and minor carry layouts, like the trajectories they
   summarize.
4. **Checkpoint stability** — lanes survive kill/resume bit-identically
   on the sharded driver, including a cross-mesh 4 -> 2 -> 1 resume.
5. **Fault composition** — every fault lane (crash/links/skew/
   membership, plan and fuzz engines) composes with
   ``--check-mode device``: flagged instances confirm through the farm
   and verdicts still match the all-instances oracle.
6. **Clean sweep** — a clean run routes ZERO instances into the farm
   (``farm_load_fraction=0``), the headline O(chips) property.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from maelstrom_tpu.checkers import device_summary
from maelstrom_tpu.models import get_model
from maelstrom_tpu.models.raft_buggy import RaftDoubleVote
from maelstrom_tpu.tpu.harness import make_sim_config, run_tpu_test
from maelstrom_tpu.tpu.runtime import run_sim

pytestmark = pytest.mark.device_check

# dense partition-nemesis config: real traffic, real leader churn — the
# inbox_k=2 / pool_slots=24 shapes of the fault suite (small compiles)
BASE_OPTS = dict(node_count=3, concurrency=4, n_instances=16,
                 record_instances=8, inbox_k=2, pool_slots=24,
                 time_limit=0.4, rate=200.0, latency=5.0,
                 rpc_timeout=0.2, recovery_time=0.1, seed=7,
                 nemesis=["partition"], nemesis_interval=0.05,
                 p_loss=0.05, telemetry=False, funnel=False)

ALL_WORKLOADS = ["echo", "unique-ids", "broadcast", "g-set",
                 "pn-counter", "g-counter", "lin-kv", "kafka",
                 "txn-list-append", "txn-rw-register"]

# tier-1 covers every summary_step implementation (raft family, kafka,
# g-set family, counter family) plus one default-hook workload,
# alternating layouts; the rest is the slow sweep
TIER1_MATRIX = [("lin-kv", "lead"), ("g-set", "minor"),
                ("kafka", "lead"), ("pn-counter", "minor"),
                ("unique-ids", "lead")]
SLOW_MATRIX = [(wl, layout) for wl in ALL_WORKLOADS
               for layout in ("lead", "minor")
               if (wl, layout) not in TIER1_MATRIX]


def _workload_opts(workload):
    opts = dict(BASE_OPTS)
    if workload == "kafka":
        # single-node, nemesis-free (the pool suite's kafka shape) —
        # a partitioned cold restart wipes volatile committed offsets,
        # a known acceptable false-positive source this identity test
        # keeps out of scope
        opts.update(node_count=1, nemesis=[], nemesis_interval=0.5)
    return opts


# --- 1. device-vs-farm verdict identity ------------------------------------


def _identity_case(workload, layout):
    opts = {**_workload_opts(workload), "layout": layout}

    def mk():
        return get_model(workload, opts["node_count"])

    farm = run_tpu_test(mk(), dict(opts, check_mode="farm"))
    dev = run_tpu_test(mk(), dict(opts, check_mode="device"))
    both = run_tpu_test(mk(), dict(opts, check_mode="both"))

    # both-mode farms everything: verdicts byte-identical to farm mode,
    # and the A/B audit must report the screen complete
    assert both["instances"] == farm["instances"], (workload, layout)
    assert both["valid?"] == farm["valid?"]
    assert both["check"]["device-vs-farm"]["complete"], \
        both["check"]["device-vs-farm"]

    # device mode: same per-instance valid? everywhere; flagged
    # instances ran the SAME farm path, so their verdicts are
    # byte-identical; unflagged ones carry the synthesized screen tag
    flagged = set(dev["check"]["flagged-instance-ids"])
    assert dev["valid?"] == farm["valid?"]
    for fv, dv in zip(farm["instances"], dev["instances"]):
        i = fv["instance"]
        assert dv["instance"] == i
        assert dv.get("valid?") == fv.get("valid?"), \
            (workload, layout, i)
        if i in flagged:
            assert dv == fv, (workload, layout, i)
        else:
            assert dv.get("checked-by") == "device-summary", \
                (workload, layout, i)


@pytest.mark.parametrize("workload,layout", TIER1_MATRIX)
def test_device_vs_farm_identity_tier1(workload, layout):
    _identity_case(workload, layout)


@pytest.mark.slow
@pytest.mark.parametrize("workload,layout", SLOW_MATRIX)
def test_device_vs_farm_identity_full(workload, layout):
    _identity_case(workload, layout)


# --- 2. planted-mutant routing ---------------------------------------------


# the forensics fixture (test_stream_triage / test_node_fusion): dense
# partitions + generous rpc_timeout make the double-vote mutant elect
# two leaders in one term within the 300-tick horizon
MUTANT_OPTS = dict(node_count=3, concurrency=6, n_instances=32,
                   record_instances=32, inbox_k=1, pool_slots=16,
                   time_limit=0.3, rate=200.0, latency=5.0,
                   rpc_timeout=1.0, nemesis=["partition"],
                   nemesis_interval=0.04, p_loss=0.05,
                   recovery_time=0.0, seed=7, telemetry=False,
                   funnel=False)


@pytest.mark.parametrize("layout", ["lead", "minor"])
def test_double_vote_mutant_flagged_and_routed(layout):
    """The double-vote mutant diverges committed prefixes; the device
    lanes must flag instances, every farm-invalid instance must be
    flagged (screen completeness — the ``both`` audit), the farm must
    receive exactly the flagged recorded set, and per-instance verdicts
    must equal the all-instances oracle's byte for byte."""
    opts = dict(MUTANT_OPTS, layout=layout)

    def mk():
        return RaftDoubleVote(n_nodes_hint=3, log_cap=64, heartbeat=8)

    dev = run_tpu_test(mk(), dict(opts, check_mode="device"))
    both = run_tpu_test(mk(), dict(opts, check_mode="both"))

    assert dev["valid?"] is False and both["valid?"] is False
    flagged = set(dev["check"]["flagged-instance-ids"])
    assert flagged, "mutant raised no device flags"
    oracle = {v["instance"] for v in both["instances"]
              if v.get("valid?") is False}
    assert oracle <= flagged, f"screen missed {sorted(oracle - flagged)}"
    assert both["check"]["device-vs-farm"]["complete"], \
        both["check"]["device-vs-farm"]
    # the farm checked exactly the flagged recorded instances
    assert dev["check"]["farm-instances"] == \
        len([i for i in flagged if i < opts["record_instances"]])
    by_inst = {v["instance"]: v for v in both["instances"]}
    for v in dev["instances"]:
        if v["instance"] in flagged:
            assert v == by_inst[v["instance"]], v["instance"]
        else:
            assert v.get("checked-by") == "device-summary", v
    assert all(isinstance(i, int) and 0 <= i < 32 for i in flagged)
    assert dev["check"]["flagged-instances"] == len(flagged)
    assert dev["check"]["summary-bytes-per-tick"] == \
        device_summary.summary_bytes_per_tick(32)


@pytest.mark.slow
def test_dirty_apply_farm_invalid_instances_routed():
    """The strongest routing oracle: the txn dirty-apply mutant under
    scripted leader isolation produces instances the HOST checker
    (Elle) rejects — device mode must flag every one of them and hand
    back byte-identical invalid verdicts (txn models inherit the raft
    lane, whose applied-truncation witness — log end below
    ``last_applied`` — is exactly the dirty-apply lost acked txn)."""
    from maelstrom_tpu.models.txn_raft import TxnDirtyApply
    from maelstrom_tpu.tpu.runtime import scripted_isolate_groups
    # test_tpu_txn's leader-isolation schedule, 2 cycles: isolate each
    # node in turn (400-tick phases, 100-tick heal gaps) so whichever
    # node is leader gets cut from the majority at some point
    sched, t = [], 200
    for _ in range(2):
        for iso in range(3):
            others = tuple(sorted({0, 1, 2} - {iso}))
            sched.append(scripted_isolate_groups(
                t + 400, [(iso,), others], 3))
            t += 400
            sched.append((t + 100, ()))
            t += 100
    opts = dict(node_count=3, concurrency=4, n_instances=8,
                record_instances=8, time_limit=(t + 600) / 1000,
                rate=60.0, latency=5.0, rpc_timeout=0.8,
                nemesis=["partition"], nemesis_kind="scripted",
                nemesis_schedule=tuple(sched), recovery_time=0.5,
                seed=3, telemetry=False, funnel=False)

    def mk():
        return TxnDirtyApply(n_nodes_hint=3, log_cap=96)

    farm = run_tpu_test(mk(), dict(opts, check_mode="farm"))
    dev = run_tpu_test(mk(), dict(opts, check_mode="device"))
    oracle = {v["instance"] for v in farm["instances"]
              if v.get("valid?") is False}
    assert oracle, "mutant failed to trip the host checker"
    flagged = set(dev["check"]["flagged-instance-ids"])
    assert oracle <= flagged, f"screen missed {sorted(oracle - flagged)}"
    by_inst = {v["instance"]: v for v in farm["instances"]}
    for v in dev["instances"]:
        if v["instance"] in flagged:
            assert v == by_inst[v["instance"]], v["instance"]
    assert dev["valid?"] is False


# --- 3. layout identity ----------------------------------------------------


@pytest.mark.parametrize("workload", ["lin-kv", "g-set"])
def test_summary_lanes_layout_bit_identical(workload):
    """The lane block is folded from the per-instance trace, which is
    layout-invariant — lead and minor runs must agree bit for bit."""
    opts = _workload_opts(workload)
    blocks = {}
    for layout in ("lead", "minor"):
        model = get_model(workload, opts["node_count"])
        sim = make_sim_config(model, {**opts, "layout": layout,
                                      "check_mode": "device"})
        carry, _ = run_sim(model, sim, opts["seed"],
                           model.make_params(sim.net.n_nodes))
        blocks[layout] = np.asarray(carry.check_summary)
    assert blocks["lead"].shape == (opts["n_instances"],
                                    device_summary.N_LANES)
    assert np.array_equal(blocks["lead"], blocks["minor"])


# --- 4. checkpoint / cross-mesh stability ----------------------------------

ECHO_OPTS = dict(node_count=2, concurrency=2, n_instances=8,
                 record_instances=2, time_limit=0.3, rate=100.0,
                 latency=5.0, seed=3, funnel=False, pipeline="on",
                 chunk_ticks=50, check_mode="device")


class Killed(BaseException):
    """Simulated SIGKILL from the checkpoint sink."""


def test_summary_lanes_checkpoint_resume_bit_identical(tmp_path):
    from maelstrom_tpu.campaign.checkpoint import (load_checkpoint,
                                                   restore_carry,
                                                   save_checkpoint)
    from maelstrom_tpu.models.echo import EchoModel
    from maelstrom_tpu.tpu.pipeline import ResumeState
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked,
                                             wire_template)
    model = EchoModel()
    opts = dict(ECHO_OPTS, n_instances=4, time_limit=0.12)
    sim = make_sim_config(model, opts)
    assert sim.check_summary
    mesh = make_mesh(2)
    base = run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                   chunk=40, return_check_summary=True)
    d = str(tmp_path)

    def cb(state, ticks, host):
        save_checkpoint(d, kind="sharded", state=state, ticks=ticks,
                        chunks=host["chunks"],
                        events=tuple(host["events"]))
        raise Killed

    with pytest.raises(Killed):
        run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                chunk=40, checkpoint_cb=cb,
                                checkpoint_every=1)
    ck = load_checkpoint(d)
    tmpl = wire_template(model, sim, mesh)
    resume = ResumeState(carry=restore_carry(tmpl, ck["carry"]),
                         ticks=ck["ticks"], chunks=ck["chunks"],
                         events=tuple(ck["events"]))
    res = run_sim_sharded_chunked(model, sim, seed=3, mesh=mesh,
                                  chunk=40, resume=resume,
                                  return_check_summary=True)
    assert base[0] == res[0]
    assert np.array_equal(base[1], res[1])
    assert np.array_equal(base[2], res[2])
    assert base[3] is not None
    assert np.array_equal(base[3], res[3])


@pytest.mark.parametrize("new_shards", [2, 1])
def test_summary_lanes_cross_mesh_resume(tmp_path, new_shards):
    """A checkpoint written at 4 shards resumes at 2 and at 1 with the
    summary lane block bit-identical to an uninterrupted run at the new
    shard count — the lanes ride the reshard as ordinary
    instance-sharded leaves."""
    from maelstrom_tpu.campaign.checkpoint import (load_checkpoint,
                                                   restore_carry,
                                                   save_checkpoint)
    from maelstrom_tpu.models.echo import EchoModel
    from maelstrom_tpu.tpu.pipeline import ResumeState
    from maelstrom_tpu.parallel.mesh import (make_mesh,
                                             run_sim_sharded_chunked,
                                             wire_template)
    model = EchoModel()

    def sim_at(shards):
        return make_sim_config(model, dict(
            ECHO_OPTS, n_instances=8 // shards,
            record_instances=8 // shards, time_limit=0.12))

    sim_new = sim_at(new_shards)
    mesh_new = make_mesh(new_shards)
    base = run_sim_sharded_chunked(model, sim_new, seed=3,
                                   mesh=mesh_new, chunk=40,
                                   return_check_summary=True)
    sim4 = sim_at(4)
    d = str(tmp_path)

    def cb(state, ticks, host):
        save_checkpoint(d, kind="sharded", state=state, ticks=ticks,
                        chunks=host["chunks"],
                        events=tuple(host["events"]),
                        meta={"shard": host["shard"]})
        raise Killed

    with pytest.raises(Killed):
        run_sim_sharded_chunked(model, sim4, seed=3, mesh=make_mesh(4),
                                chunk=40, checkpoint_cb=cb,
                                checkpoint_every=1)
    ck = load_checkpoint(d)
    tmpl = wire_template(model, sim_new, mesh_new)
    resume = ResumeState(
        carry=restore_carry(tmpl, ck["carry"],
                            shard=ck["meta"]["shard"]),
        ticks=ck["ticks"], chunks=ck["chunks"],
        events=tuple(ck["events"]))
    res = run_sim_sharded_chunked(model, sim_new, seed=3,
                                  mesh=mesh_new, chunk=40,
                                  resume=resume,
                                  return_check_summary=True)
    assert base[0] == res[0]
    assert np.array_equal(base[1], res[1])
    assert np.array_equal(base[2], res[2])
    assert base[3] is not None and base[3].shape == \
        (8, device_summary.N_LANES)
    assert np.array_equal(base[3], res[3])


# --- 5. fault-lane composition ---------------------------------------------

# one plan per lane, each short enough for tier-1's representative case
_ISOLATE_2 = [{"dst": 2, "src": 0, "block": True},
              {"dst": 2, "src": 1, "block": True},
              {"dst": 0, "src": 2, "block": True},
              {"dst": 1, "src": 2, "block": True}]
FAULT_PLANS = {
    "crash": {"phases": [{"until": 120},
                         {"until": 180, "crash": [2]},
                         {"until": 400}]},
    "links": {"phases": [{"until": 120},
                         {"until": 260, "links": _ISOLATE_2},
                         {"until": 400}]},
    "skew": {"phases": [{"until": 400,
                         "skew": {"0": 1.5, "1": 1.0, "2": 1.0}}]},
    "membership": {"phases": [{"until": 150, "members": [0, 1]},
                              {"until": 400,
                               "members": [0, 1, 2]}]},
}
FUZZ_DIST = {"windows": [1, 2], "gap": [60, 160], "duration": [20, 60],
             "crash": {"rate": 0.5, "victims": [1, 1]},
             "links": {"rate": 0.5, "edges": [1, 2], "block": 0.5,
                       "delay": [0, 10], "loss": [0.0, 0.2]},
             "skew": {"rate": 0.3, "victims": [1, 1],
                      "range": [0.75, 1.5]}}


def _fault_compose_case(fault_opts):
    opts = dict(BASE_OPTS, nemesis=[], nemesis_interval=0.5,
                rpc_timeout=0.08, **fault_opts)

    def mk():
        return get_model("lin-kv", opts["node_count"])

    dev = run_tpu_test(mk(), dict(opts, check_mode="device"))
    both = run_tpu_test(mk(), dict(opts, check_mode="both"))
    assert "check" in dev and "check" in both
    assert both["check"]["device-vs-farm"]["complete"], \
        both["check"]["device-vs-farm"]
    assert dev["valid?"] == both["valid?"]
    for bv, dv in zip(both["instances"], dev["instances"]):
        assert dv.get("valid?") == bv.get("valid?"), bv["instance"]


@pytest.mark.parametrize("lane", ["links"])
def test_fault_lane_composes_with_device_mode_tier1(lane):
    _fault_compose_case({"fault_plan": FAULT_PLANS[lane]})


@pytest.mark.slow
@pytest.mark.parametrize("lane", ["crash", "skew", "membership"])
def test_fault_lane_composes_with_device_mode_full(lane):
    _fault_compose_case({"fault_plan": FAULT_PLANS[lane]})


@pytest.mark.slow
def test_fault_fuzz_composes_with_device_mode():
    _fault_compose_case({"fault_fuzz": FUZZ_DIST})


# --- 6. clean sweep --------------------------------------------------------


def test_clean_sweep_routes_zero_instances_to_farm():
    """The headline property: a clean echo fleet proves itself on
    device and the farm receives NOTHING."""
    opts = dict(node_count=2, concurrency=2, n_instances=16,
                record_instances=8, time_limit=0.3, rate=100.0,
                latency=5.0, seed=3, telemetry=False, funnel=False,
                check_mode="device")
    r = run_tpu_test(get_model("echo", 2), opts)
    assert r["valid?"] is True
    assert r["check"]["mode"] == "device"
    assert r["check"]["flagged-instances"] == 0
    assert r["check"]["farm-instances"] == 0
    assert r["check"]["farm-load-fraction"] == 0.0
    assert all(v.get("checked-by") == "device-summary"
               for v in r["instances"])


@pytest.mark.slow
def test_summary_lane_overhead_bounded():
    """The lane fold must stay a small fraction of tick cost: warm-run
    wall with lanes on vs off at 512 instances, generous 75% bound
    (typical is single-digit percent — this pins regressions, not
    noise)."""
    walls = {}
    for mode in ("farm", "device"):
        model = get_model("lin-kv", 3)
        opts = dict(BASE_OPTS, n_instances=512, record_instances=1,
                    time_limit=0.5, check_mode=mode)
        sim = make_sim_config(model, opts)
        params = model.make_params(sim.net.n_nodes)
        run_sim(model, sim, opts["seed"], params)       # compile warm
        t0 = time.monotonic()
        carry, _ = run_sim(model, sim, opts["seed"], params)
        np.asarray(carry.violations)                    # block
        walls[mode] = time.monotonic() - t0
    assert walls["device"] <= walls["farm"] * 1.75, walls
