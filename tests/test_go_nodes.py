"""End-to-end runs of the Go example nodes through the process
runtime, plus the SDK's own fake-stdio `go test` suite. Skips cleanly
when no Go toolchain is present (this image ships none — the static
wire conformance in test_go_wire_conformance.py still runs)."""

import os
import shutil
import subprocess

import pytest

from maelstrom_tpu import run_test

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GO_DIR = os.path.join(REPO, "examples", "go")

pytestmark = pytest.mark.skipif(
    shutil.which("go") is None, reason="no Go toolchain in image")


@pytest.fixture(scope="session")
def go_bins(tmp_path_factory):
    out = tmp_path_factory.mktemp("go-bins")
    for name in ("echo", "broadcast", "g_set", "counter"):
        subprocess.run(
            ["go", "build", "-o", str(out / name), f"./cmd/{name}"],
            cwd=GO_DIR, check=True, capture_output=True)
    return out


def test_go_sdk_unit_suite():
    # the SDK's fake-stdio tests (reference node_test.go:19-37 pattern)
    subprocess.run(["go", "test", "./maelstrom/..."], cwd=GO_DIR,
                   check=True, capture_output=True)


def test_go_echo_e2e(go_bins, tmp_path):
    res = run_test("echo", dict(
        bin=str(go_bins / "echo"), node_count=2, time_limit=3.0,
        rate=20.0, concurrency=4, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_go_broadcast_partition_e2e(go_bins, tmp_path):
    res = run_test("broadcast", dict(
        bin=str(go_bins / "broadcast"), node_count=3, time_limit=6.0,
        rate=20.0, concurrency=4, nemesis=["partition"],
        nemesis_interval=2.0, recovery_time=3.0,
        store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True


def test_go_counter_seq_kv_e2e(go_bins, tmp_path):
    res = run_test("g-counter", dict(
        bin=str(go_bins / "counter"), node_count=2, time_limit=5.0,
        rate=10.0, concurrency=4, store_root=str(tmp_path), seed=7))
    assert res["valid?"] is True
