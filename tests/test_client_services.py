"""Unit tests for the client/RPC layer and built-in services (SURVEY §2.1
client.clj / service.clj semantics)."""

import pytest

from maelstrom_tpu.core.errors import RPCError
from maelstrom_tpu.net.net import Net
from maelstrom_tpu.runtime.client import Client, with_errors
from maelstrom_tpu.runtime.services import (
    Eventual, Linearizable, LWWKV, PersistentKV, PersistentTSO, Sequential,
    Service, default_services, start_services, stop_services)


def test_client_rpc_roundtrip_with_service():
    net = Net(seed=0)
    svc = Service("lin-kv", Linearizable(PersistentKV()), net)
    svc.start()
    try:
        c = Client.open(net)
        assert c.node_id == "c0"
        c2 = Client.open(net)
        assert c2.node_id == "c1"
        resp = c.rpc("lin-kv", {"type": "write", "key": "x", "value": 5})
        assert resp["type"] == "write_ok"
        resp = c.rpc("lin-kv", {"type": "read", "key": "x"})
        assert resp["value"] == 5
        with pytest.raises(RPCError) as ei:
            c.rpc("lin-kv", {"type": "read", "key": "nope"})
        assert ei.value.code == 20
        with pytest.raises(RPCError) as ei:
            c.rpc("lin-kv", {"type": "cas", "key": "x", "from": 9, "to": 1})
        assert ei.value.code == 22
        resp = c.rpc("lin-kv", {"type": "cas", "key": "x", "from": 5,
                                "to": 6})
        assert resp["type"] == "cas_ok"
        resp = c.rpc("lin-kv", {"type": "cas", "key": "new", "from": 0,
                                "to": 1, "create_if_not_exists": True})
        assert resp["type"] == "cas_ok"
    finally:
        svc.stop()


def test_tso_monotonic():
    net = Net(seed=0)
    svc = Service("lin-tso", Linearizable(PersistentTSO()), net)
    svc.start()
    try:
        c = Client.open(net)
        ts = [c.rpc("lin-tso", {"type": "ts"})["ts"] for _ in range(5)]
        assert ts == sorted(ts) and len(set(ts)) == 5
    finally:
        svc.stop()


def test_with_errors_mapping():
    op = {"f": "read", "value": None}

    def boom_timeout():
        raise RPCError(0, "timed out")

    # timeout on idempotent op -> fail; non-idempotent -> info
    assert with_errors(dict(op), {"read"}, boom_timeout)["type"] == "fail"
    assert with_errors(dict(op), set(), boom_timeout)["type"] == "info"

    def boom_definite():
        raise RPCError(22, "nope")

    out = with_errors(dict(op), set(), boom_definite)
    assert out["type"] == "fail"
    assert out["error"][0] == "precondition-failed"

    def boom_indefinite():
        raise RPCError(13, "crash")

    assert with_errors(dict(op), set(), boom_indefinite)["type"] == "info"


def test_sequential_wrapper_per_client_monotonic():
    """Mirrors the reference's service_test.clj: a fresh client may read a
    stale state, a write forces recency, repeated reads converge."""
    seq = Sequential(PersistentKV(), seed=7)
    # build up some history via one client
    for i in range(10):
        seq.handle("c1", {"type": "write", "key": "x", "value": i})
    # a fresh client may see any historical state; values must be
    # non-decreasing per client across repeated reads
    last = -1
    for _ in range(50):
        v = seq.handle("c2", {"type": "read", "key": "x"})["value"]
        assert v >= last
        last = v
    # after the client writes, its reads must reflect at least that state
    seq.handle("c2", {"type": "write", "key": "x", "value": 99})
    assert seq.handle("c2", {"type": "read", "key": "x"})["value"] == 99


def test_lww_merge():
    kv = LWWKV()
    a = kv.initial()
    b = kv.initial()
    a, _ = kv.handle(a, {"type": "write", "key": "k", "value": "a"})
    b, _ = kv.handle(b, {"type": "write", "key": "k", "value": "b"})
    b, _ = kv.handle(b, {"type": "write", "key": "k", "value": "b2"})
    m = kv.merge(a, b)
    # b2 has the higher clock
    _, reply = kv.handle(m, {"type": "read", "key": "k"})
    assert reply["value"] == "b2"


def test_eventual_wrapper_converges_on_merge():
    ev = Eventual(LWWKV(), n=3, merge_prob=1.0, seed=3)
    ev.handle("c1", {"type": "write", "key": "x", "value": 1})
    # eventually every replica should learn x via merges
    seen = 0
    for _ in range(200):
        try:
            ev.handle("c1", {"type": "read", "key": "x"})
            seen += 1
        except RPCError:
            pass
    assert seen > 150


def test_default_services_start_stop():
    net = Net(seed=0)
    svcs = start_services(default_services(net, seed=0))
    assert set(net.nodes()) == {"lww-kv", "seq-kv", "lin-kv", "lin-tso"}
    stop_services(svcs)
    assert net.nodes() == []
