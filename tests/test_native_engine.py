"""The native CPU engine (cpp/engine): the C++ scalar backend with the
JAX runtime's simulated-cluster semantics. Compatibility is semantic,
not bit-level (different RNG): clean configs must be invariant-clean
and WGL-valid, the bug-injection mutants must be caught by the SAME
checkers, and the CLI `--runtime native` path must produce the full
results/store shape."""

import shutil

import pytest

from maelstrom_tpu.checkers.linearizable import linearizable_kv_checker
from maelstrom_tpu.native import native_available, run_native_sim
from maelstrom_tpu.native.harness import run_native_test

pytestmark = pytest.mark.skipif(
    not (native_available() or shutil.which("g++")),
    reason="no native engine and no toolchain to build it")

BASE = dict(node_count=3, concurrency=6, n_instances=128,
            record_instances=16, time_limit=2.0, rate=100.0,
            latency=5.0, rpc_timeout=1.0, nemesis=["partition"],
            nemesis_interval=0.4, p_loss=0.05, recovery_time=0.3,
            seed=7)


def test_native_clean_and_checkable():
    res = run_native_sim(BASE)
    assert res is not None
    assert res["violating-instances"] == 0
    assert res["stats"]["delivered"] > 10_000
    assert res["stats"]["dropped-partition"] > 0    # nemesis really ran
    assert res["stats"]["dropped-loss"] > 0
    for h in res["histories"]:
        assert len(h) > 5
        assert linearizable_kv_checker(h)["valid?"] is True, h[:20]


def test_native_deterministic():
    a = run_native_sim(BASE)
    b = run_native_sim(BASE)
    assert a["stats"] == b["stats"]
    assert a["histories"] == b["histories"]


def test_native_thread_count_invariance():
    """Worker threads own disjoint instance blocks and per-instance RNG
    is a pure function of (seed, id): results must be IDENTICAL at any
    thread count — stats, violations, and recorded histories."""
    a = run_native_sim(dict(BASE, threads=1))
    b = run_native_sim(dict(BASE, threads=4))
    assert a["stats"] == b["stats"]
    assert a["histories"] == b["histories"]
    assert (a["violations"] == b["violations"]).all()


@pytest.mark.parametrize("flag,invariant_caught", [
    ("stale_read", False),    # linearizability bug: checker-caught
    ("eager_commit", True),   # lost committed entries: invariant-caught
])
def test_native_mutants_caught(flag, invariant_caught):
    opts = dict(BASE, n_instances=256, record_instances=64,
                time_limit=3.0, seed=3, **{flag: True})
    res = run_native_sim(opts)
    bad = sum(1 for h in res["histories"]
              if linearizable_kv_checker(h)["valid?"] is False)
    caught = bad > 0 or res["violating-instances"] > 0
    assert caught, f"{flag} mutant not caught"
    if invariant_caught:
        assert res["violating-instances"] > 0

    # the correct engine stays clean on the identical config
    res_ok = run_native_sim(dict(BASE, n_instances=256,
                                 record_instances=64, time_limit=3.0,
                                 seed=3))
    assert res_ok["violating-instances"] == 0
    assert all(linearizable_kv_checker(h)["valid?"] is True
               for h in res_ok["histories"])


def test_native_harness_and_store(tmp_path):
    res = run_native_test(dict(BASE, store_root=str(tmp_path)))
    assert res["valid?"] is True
    assert res["engine"] == "native-cpp"
    assert res["checked-instances"] == 16
    assert res["perf"]["msgs-per-sec"] > 0
    import glob
    import os
    run_dir = os.path.join(str(tmp_path), "lin-kv-native", "latest")
    assert len(glob.glob(os.path.join(run_dir, "history-*.jsonl"))) == 16
    assert os.path.exists(os.path.join(run_dir, "results.json"))


@pytest.mark.slow
def test_native_throughput_beats_reference_baseline():
    """The native engine on ONE CPU core (threads=1, explicitly) must
    beat the reference's whole-48-way-Xeon figure (60k msgs/s,
    README.md:39-42) — the CPU-fallback bench story."""
    res = run_native_sim(dict(threads=1, node_count=3, concurrency=6,
                              n_instances=2048, record_instances=2,
                              time_limit=2.0, rate=200.0, latency=5.0,
                              rpc_timeout=1.0, nemesis=["partition"],
                              nemesis_interval=0.4, p_loss=0.05,
                              recovery_time=0.3, seed=7))
    assert res["perf"]["msgs-per-sec"] > 60_000, res["perf"]
    for h in res["histories"]:
        assert linearizable_kv_checker(h)["valid?"] is True


def test_native_funnel_replays_tripped_instances(tmp_path):
    """The eager-commit mutant trips invariants across the fleet; the
    funnel must replay each tripped id bit-exactly (re-tripping its
    invariants), produce checkable histories, and store them."""
    import glob
    import os

    opts = dict(BASE, n_instances=256, record_instances=2,
                time_limit=3.0, seed=3, eager_commit=True,
                funnel_max=6, store_root=str(tmp_path))
    res = run_native_test(opts)
    assert res["invariants"]["violating-instances"] > 0
    assert any(i >= 2 for i in
               res["invariants"]["violating-instance-ids"])
    fun = res["funnel"]
    assert fun["replayed-violating"] == len(fun["ids"]), fun
    assert len(fun["verdicts"]) == len(fun["ids"])
    for v in fun["verdicts"]:
        assert v["ops"] > 0
    run_dir = os.path.join(str(tmp_path), "lin-kv-native", "latest")
    stored = glob.glob(os.path.join(run_dir, "funnel-history-*.jsonl"))
    assert {int(os.path.basename(p).split("-")[-1].split(".")[0])
            for p in stored} == set(fun["ids"])


def test_native_instance_base_bit_exact():
    """A single-instance replay at instance_base=k must reproduce the
    batch run's instance k exactly (stats and recorded history)."""
    batch = run_native_sim(dict(BASE, n_instances=16,
                                record_instances=16))
    for k in (3, 11):
        solo = run_native_sim(dict(BASE, n_instances=1,
                                   record_instances=1,
                                   instance_base=k))
        assert solo["histories"][0] == batch["histories"][k], k


@pytest.mark.slow
def test_native_no_term_guard_caught_on_figure8():
    """The Raft §5.4.2 commit bug needs the constructed
    rotating-majorities schedule (as on the device runtime): the native
    scripted nemesis must trip the truncated-committed witness on a
    sizable fraction of instances, with correct Raft clean on the
    IDENTICAL schedule."""
    from maelstrom_tpu.tpu.runtime import scripted_isolate_groups

    cycle = [({0, 1, 2},), ({2, 3, 4},), ({4, 0, 1},),
             ({1, 2, 3},), ({3, 4, 0},)]
    sched, t, i = [], 0, 0
    while t < 3000:
        t += 200
        sched.append(scripted_isolate_groups(t, cycle[i % 5], 5))
        i += 1
    base = dict(node_count=5, concurrency=4, n_instances=96,
                record_instances=4, time_limit=3.5, rate=60.0,
                latency=5.0, rpc_timeout=0.8, nemesis=["partition"],
                nemesis_schedule=tuple(sched), recovery_time=0.5,
                seed=11)
    bug = run_native_sim(dict(base, no_term_guard=True))
    assert bug["violating-instances"] >= 5, bug["violating-instances"]
    ok = run_native_sim(base)
    assert ok["violating-instances"] == 0
    assert all(linearizable_kv_checker(h)["valid?"] is True
               for h in ok["histories"])


@pytest.mark.slow
def test_native_vs_jax_engine_statistics_agree():
    """The two engines are not bit-compatible (different RNG), but on
    the identical config their AGGREGATE behavior must agree: similar
    delivery ratios, loss fractions near p_loss, both invariant-clean,
    both WGL-valid — the guard against semantic drift between backends."""
    from maelstrom_tpu.models.raft import RaftModel
    from maelstrom_tpu.tpu.harness import run_tpu_test

    opts = dict(node_count=3, concurrency=6, n_instances=64,
                record_instances=4, time_limit=1.0, rate=100.0,
                latency=5.0, rpc_timeout=1.0, nemesis=["partition"],
                nemesis_interval=0.4, p_loss=0.05, recovery_time=0.3,
                seed=7)
    nat = run_native_sim(opts)
    jx = run_tpu_test(RaftModel(n_nodes_hint=3, log_cap=64, heartbeat=8),
                      dict(opts, funnel=False))

    assert nat["violating-instances"] == 0
    assert jx["invariants"]["violating-instances"] == 0
    assert jx["valid?"] is True
    for h in nat["histories"]:
        assert linearizable_kv_checker(h)["valid?"] is True

    def ratios(sent, delivered, lost):
        return delivered / sent, lost / sent

    n_del, n_loss = ratios(nat["stats"]["sent"],
                           nat["stats"]["delivered"],
                           nat["stats"]["dropped-loss"])
    j_del, j_loss = ratios(jx["net"]["sent"], jx["net"]["delivered"],
                           jx["net"]["dropped-loss"])
    # loss fraction must sit near p_loss * inter-node share on both
    assert 0.01 < n_loss < 0.06 and 0.01 < j_loss < 0.06, \
        (n_loss, j_loss)
    # delivery ratios within 15 points of each other (protocol mixes
    # differ slightly: heartbeat cadence vs elect timing constants)
    assert abs(n_del - j_del) < 0.15, (n_del, j_del)


# --- txn-list-append workload (VERDICT r4 next #4: a second native
# workload family — transactions over the Raft log, Elle-checked) -----

def _txn_opts(**kw):
    o = dict(workload="txn-list-append", n_instances=64,
             record_instances=8, time_limit=3.0, nemesis=["partition"],
             nemesis_interval=0.3, p_loss=0.05, recovery_time=0.3,
             seed=7, threads=1)
    o.update(kw)
    return o


def test_native_txn_clean_elle_valid():
    from maelstrom_tpu.checkers.elle import check_list_append
    res = run_native_sim(_txn_opts())
    assert res is not None
    assert res["violating-instances"] == 0
    n_txns = 0
    for h in res["histories"]:
        r = check_list_append(h)
        assert r["valid?"] is True, r
        n_txns += r["txn-count"]
    # the runs must carry real transactional load for the verdict to
    # mean anything
    assert n_txns > 100
    # atomicity sanity: some committed txn mixes appends and reads
    assert any(
        {op[0] for op in rec["value"]} == {"append", "r"}
        for h in res["histories"] for rec in h if rec["type"] == "ok")


def test_native_txn_dirty_apply_caught_by_elle():
    # the native twin of models/txn_raft.py's TxnDirtyApply mutant:
    # apply + reply at append time — leader churn truncates acked
    # txns; Elle must catch it on the recorded instances
    from maelstrom_tpu.checkers.elle import check_list_append
    res = run_native_sim(_txn_opts(txn_dirty_apply=True))
    anomalies = set()
    flagged = 0
    for h in res["histories"]:
        r = check_list_append(h)
        if r["valid?"] is False:
            flagged += 1
            anomalies |= set(r["anomalies"].keys())
    assert flagged >= 2, "dirty-apply went undetected"
    assert anomalies & {"lost-append", "G-single", "G2-item", "G1c",
                        "incompatible-order", "G1a"}, anomalies


def test_native_txn_harness_verdicts(tmp_path):
    # run_native_test dispatches the Elle checker for the txn workload
    # and writes the store under the workload's name
    res = run_native_test(_txn_opts(store_root=str(tmp_path)))
    assert res["valid?"] in (True, "unknown")
    assert res["checked-instances"] == 8
    assert (tmp_path / "txn-list-append-native").exists()


def test_native_txn_instance_base_bit_exact():
    # the funnel contract holds for the txn workload too: global-id
    # keyed RNG makes any single instance replay bit-exactly
    res = run_native_sim(_txn_opts())
    target = 5
    solo = run_native_sim(_txn_opts(n_instances=1, record_instances=1,
                                    instance_base=target))
    assert solo["histories"][0] == res["histories"][target]


# --- g-set workload (third native family: gossip CRDT + set-full) ----

def _gset_opts(**kw):
    o = dict(workload="g-set", n_instances=64, record_instances=4,
             time_limit=2.0, nemesis=["partition"],
             nemesis_interval=0.3, p_loss=0.05, recovery_time=0.4,
             seed=7, read_prob=0.1, threads=1)
    o.update(kw)
    return o


def test_native_gset_clean_set_full_valid():
    res = run_native_test(_gset_opts())
    assert res["valid?"] is True
    for inst in res["instances"][:4]:
        assert inst.get("lost-count", 0) == 0, inst
    # real load: elements actually stabilized across the fleet
    assert sum(i.get("stable-count", 0)
               for i in res["instances"]) > 100


def test_native_gset_no_gossip_caught():
    # adds stay on the receiving node; reads from other nodes miss
    # them — set-full must report lost elements
    res = run_native_test(_gset_opts(gset_no_gossip=True))
    assert res["valid?"] is False
    assert any(i.get("lost-count", 0) > 5 for i in res["instances"])


def test_native_gset_instance_base_bit_exact():
    from maelstrom_tpu.native import run_native_sim
    res = run_native_sim(_gset_opts())
    solo = run_native_sim(_gset_opts(n_instances=1, record_instances=1,
                                     instance_base=2))
    assert solo["histories"][0] == res["histories"][2]


def test_native_gset_truncation_decodes_cleanly():
    # a saturated recorder leaves zero padding rows; the decoder must
    # stop at them (events-truncated reports it), never crash
    import numpy as np
    from maelstrom_tpu.native.engine import _decode_gset_history
    ev = np.zeros((4, 7), dtype=np.int32)
    ev[0] = [5, 0, 1, 1, 0, 42, 0]
    h = _decode_gset_history(ev, 1, 1 << 30)
    assert len(h) == 1 and h[0]["value"] == 42


# --- broadcast workload (fourth native family: topology flooding +
# anti-entropy, set-full with stable latency) ------------------------

def _bcast_opts(**kw):
    o = dict(workload="broadcast", n_instances=48, record_instances=4,
             time_limit=2.0, nemesis=["partition"],
             nemesis_interval=0.3, p_loss=0.05, recovery_time=0.4,
             seed=7, read_prob=0.1, node_count=5, topology="grid",
             threads=1)
    o.update(kw)
    return o


@pytest.mark.parametrize("topo", ["grid", "line", "tree2", "total"])
def test_native_broadcast_topologies_clean(topo):
    res = run_native_test(_bcast_opts(topology=topo))
    assert res["valid?"] is True, res["instances"][:2]
    for inst in res["instances"]:
        assert inst.get("lost-count", 0) == 0, (topo, inst)
    assert sum(i.get("stable-count", 0)
               for i in res["instances"]) > 100


def test_native_broadcast_no_gossip_caught():
    res = run_native_test(_bcast_opts(gset_no_gossip=True))
    assert res["valid?"] is False
    assert any(i.get("lost-count", 0) > 5 for i in res["instances"])


def test_native_broadcast_instance_base_bit_exact():
    from maelstrom_tpu.native import run_native_sim
    res = run_native_sim(_bcast_opts())
    solo = run_native_sim(_bcast_opts(n_instances=1,
                                      record_instances=1,
                                      instance_base=3))
    assert solo["histories"][0] == res["histories"][3]


# --- unique-ids + pn/g-counter (families five through seven) --------

def _small_opts(**kw):
    o = dict(n_instances=48, record_instances=4, time_limit=2.0,
             nemesis=["partition"], nemesis_interval=0.3, p_loss=0.05,
             recovery_time=0.4, seed=7, read_prob=0.15, threads=1)
    o.update(kw)
    return o


def test_native_unique_ids_clean_and_collision_caught():
    res = run_native_test(_small_opts(workload="unique-ids"))
    assert res["valid?"] is True
    assert sum(i.get("acknowledged-count", 0)
               for i in res["instances"]) > 200
    # the family bug flag drops node striping: bare counters collide
    bad = run_native_test(_small_opts(workload="unique-ids",
                                      gset_no_gossip=True))
    assert bad["valid?"] is False
    assert any(i.get("duplicated-count", 0) > 0
               for i in bad["instances"])


@pytest.mark.parametrize("wl", ["pn-counter", "g-counter"])
def test_native_counters_interval_clean(wl):
    res = run_native_test(_small_opts(workload=wl))
    assert res["valid?"] is True, res["instances"][:2]
    if wl == "g-counter":
        # non-negative deltas: sums never go below zero
        for inst in res["instances"]:
            for v in inst.get("final-reads") or []:
                assert v >= 0, inst


def test_native_pn_counter_no_gossip_caught():
    res = run_native_test(_small_opts(workload="pn-counter",
                                      gset_no_gossip=True))
    assert res["valid?"] is False


def test_native_unique_ids_instance_base_bit_exact():
    from maelstrom_tpu.native import run_native_sim
    res = run_native_sim(_small_opts(workload="unique-ids"))
    solo = run_native_sim(_small_opts(workload="unique-ids",
                                      n_instances=1,
                                      record_instances=1,
                                      instance_base=2))
    assert solo["histories"][0] == res["histories"][2]


# --- txn-rw-register + echo (families eight and nine) ---------------

def test_native_rw_register_clean_elle_valid():
    from maelstrom_tpu.checkers.elle import check_rw_register
    res = run_native_sim(_txn_opts(workload="txn-rw-register"))
    assert res["violating-instances"] == 0
    n_txns = 0
    for h in res["histories"]:
        r = check_rw_register(h)
        assert r["valid?"] is True, r
        n_txns += r["txn-count"]
    assert n_txns > 100


def test_native_rw_register_dirty_apply_caught():
    from maelstrom_tpu.checkers.elle import check_rw_register
    res = run_native_sim(_txn_opts(workload="txn-rw-register",
                                   txn_dirty_apply=True))
    flagged = 0
    anomalies = set()
    for h in res["histories"]:
        r = check_rw_register(h)
        if r["valid?"] is False:
            flagged += 1
            anomalies |= set(r["anomalies"].keys())
    assert flagged >= 2, "dirty-apply went undetected on registers"
    assert anomalies & {"G0", "G1a", "G1c", "G-single", "G2-item",
                        "unwritten-read"}, anomalies


def test_native_echo_clean():
    res = run_native_test(_small_opts(workload="echo"))
    assert res["valid?"] is True
    assert sum(i.get("ok-count", 0) for i in res["instances"]) > 200


def test_native_rw_register_instance_base_bit_exact():
    res = run_native_sim(_txn_opts(workload="txn-rw-register"))
    solo = run_native_sim(_txn_opts(workload="txn-rw-register",
                                    n_instances=1, record_instances=1,
                                    instance_base=6))
    assert solo["histories"][0] == res["histories"][6]


# --- kafka (family ten: the full workload table runs natively) ------

def _kafka_opts(**kw):
    o = dict(workload="kafka", n_instances=48, record_instances=4,
             time_limit=2.0, node_count=1, nemesis=[], p_loss=0.05,
             recovery_time=0.3, seed=7, threads=1)
    o.update(kw)
    return o


def test_native_kafka_clean():
    res = run_native_test(_kafka_opts())
    assert res["valid?"] is True, res["instances"][:2]
    assert sum(i.get("send-count", 0) for i in res["instances"]) > 200
    assert sum(i.get("poll-count", 0) for i in res["instances"]) > 200


def test_native_kafka_poll_skip_caught():
    # the family bug flag makes the broker skip the first pending
    # message per key on every poll — consumers advance past values
    # nobody observes, which the checker reports as lost writes
    res = run_native_test(_kafka_opts(gset_no_gossip=True))
    assert res["valid?"] is False
    anoms = set()
    for i in res["instances"]:
        anoms |= set((i.get("anomalies") or {}).keys())
    assert "lost-write" in anoms, anoms


def test_native_kafka_instance_base_bit_exact():
    from maelstrom_tpu.native import run_native_sim
    res = run_native_sim(_kafka_opts())
    solo = run_native_sim(_kafka_opts(n_instances=1,
                                      record_instances=1,
                                      instance_base=1))
    assert solo["histories"][0] == res["histories"][1]


def test_native_kafka_crash_clients_resume_from_committed():
    # crashed clients refetch committed offsets and resume; the first
    # poll after carries the reassigned flag the checker honors. The
    # flag is load-bearing: stripped histories must show the backward
    # jumps as external-nonmonotonic.
    from maelstrom_tpu.native import run_native_sim
    from maelstrom_tpu.checkers.kafka import kafka_checker
    raw = run_native_sim(_kafka_opts(time_limit=3.0, n_instances=64,
                                     record_instances=8,
                                     crash_clients=True))
    crashes = stripped_caught = 0
    for h in raw["histories"]:
        crashes += sum(1 for r in h if r["f"] == "crash"
                       and r["type"] == "invoke")
        assert kafka_checker(h)["valid?"] is True
        bare = [{k: v for k, v in r.items() if k != "reassigned"}
                for r in h]
        r2 = kafka_checker(bare)
        if r2["valid?"] is False and \
                "external-nonmonotonic" in r2["anomalies"]:
            stripped_caught += 1
    assert crashes >= 3, "crash injection never fired"
    assert stripped_caught >= 1, \
        "no crash produced an actual backward jump"


def test_native_kafka_txn_atomic_and_mutant_caught():
    # multi-mop send/poll transactions: atomic on the broker (~8%
    # aborts, error 30, definite); clean runs pass the checker with
    # real txn load. The dirty-apply family flag leaves an aborted
    # txn's sends durable — aborted-read, caught.
    from maelstrom_tpu.native import run_native_sim
    from maelstrom_tpu.checkers.kafka import kafka_checker
    raw = run_native_sim(_kafka_opts(time_limit=3.0, n_instances=64,
                                     record_instances=8, txn=True))
    txns = aborts = 0
    for h in raw["histories"]:
        assert kafka_checker(h)["valid?"] is True
        txns += sum(1 for r in h if r["f"] == "txn"
                    and r["type"] == "ok")
        aborts += sum(1 for r in h if r["f"] == "txn"
                      and r["type"] == "fail")
    assert txns > 100, "no committed transactions"
    assert aborts > 3, "the abort path never fired"
    bad = run_native_sim(_kafka_opts(time_limit=3.0, n_instances=64,
                                     record_instances=8, txn=True,
                                     txn_dirty_apply=True))
    anoms = set()
    for h in bad["histories"]:
        r = kafka_checker(h)
        if r["valid?"] is False:
            anoms |= set(r["anomalies"].keys())
    assert "aborted-read" in anoms, anoms


def test_native_kafka_txn_with_crash_clients_clean():
    # the combo the reassigned-flag plumbing exists for: crashed txn
    # clients reset to committed offsets (usually 0 — txn clients
    # never commit) and their next polling txn legally jumps backward;
    # the flag must ride the txn invoke or the checker would flag a
    # correct broker as external-nonmonotonic
    from maelstrom_tpu.native import run_native_sim
    from maelstrom_tpu.checkers.kafka import kafka_checker
    crashes = 0
    for seed in (7, 11, 19):
        raw = run_native_sim(_kafka_opts(time_limit=3.0,
                                         n_instances=64,
                                         record_instances=8, txn=True,
                                         crash_clients=True,
                                         seed=seed))
        for h in raw["histories"]:
            assert kafka_checker(h)["valid?"] is True, seed
            crashes += sum(1 for r in h if r["f"] == "crash"
                           and r["type"] == "invoke")
    assert crashes >= 5, "crash injection never fired under txn mode"
