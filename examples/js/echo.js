#!/usr/bin/env node
// Echo node (JS): the smallest complete workload node.
"use strict";
const { Node } = require(require("path").join(__dirname, "node"));

const node = new Node();
node.on("echo", (msg) =>
  node.reply(msg, { type: "echo_ok", echo: msg.body.echo }));
node.run();
