// Node SDK for the maelstrom-tpu process runtime (JavaScript edition).
//
// Line-delimited JSON over STDIN/STDOUT, handler registry, async RPC
// with promises, periodic tasks, and a KV client for the built-in
// services — the same node-framework role as examples/python/node.py
// and cpp/maelstrom/node.hpp (reference counterpart: the demo-language
// node libraries surveyed in SURVEY.md §2.3).
//
// Usage:
//   const { Node } = require("./node");
//   const node = new Node();
//   node.on("echo", (msg) => node.reply(msg, { type: "echo_ok",
//                                              echo: msg.body.echo }));
//   node.run();
"use strict";

const readline = require("readline");

class RPCError extends Error {
  constructor(code, text) {
    super(`RPC error ${code}: ${text}`);
    this.code = code;
    this.text = text;
  }
  body() {
    return { type: "error", code: this.code, text: this.text };
  }
  static timeout(t) { return new RPCError(0, t); }
  static notSupported(t) { return new RPCError(10, t); }
  static tempUnavailable(t) { return new RPCError(11, t); }
  static malformed(t) { return new RPCError(12, t); }
  static abort(t) { return new RPCError(14, t); }
  static keyDoesNotExist(t) { return new RPCError(20, t); }
  static preconditionFailed(t) { return new RPCError(22, t); }
  static txnConflict(t) { return new RPCError(30, t); }
}

class Node {
  constructor() {
    this.nodeId = null;
    this.nodeIds = [];
    this.handlers = new Map();     // type -> fn(msg)
    this.callbacks = new Map();    // msg_id -> {resolve, reject, timer}
    this.nextMsgId = 0;
    this.initCallbacks = [];
    this.timers = [];
  }

  log(...args) {
    process.stderr.write(args.join(" ") + "\n");
  }

  send(dest, body) {
    process.stdout.write(
      JSON.stringify({ src: this.nodeId, dest, body }) + "\n");
  }

  reply(req, body) {
    this.send(req.src, { ...body, in_reply_to: req.body.msg_id });
  }

  // Promise-based RPC; rejects with RPCError on error replies/timeouts.
  rpc(dest, body, timeoutMs = 5000) {
    const msgId = this.nextMsgId++;
    return new Promise((resolve, reject) => {
      const timer = setTimeout(() => {
        this.callbacks.delete(msgId);
        reject(RPCError.timeout(`no reply to ${body.type} within ` +
                                `${timeoutMs}ms`));
      }, timeoutMs);
      this.callbacks.set(msgId, { resolve, reject, timer });
      this.send(dest, { ...body, msg_id: msgId });
    });
  }

  on(type, fn) {
    this.handlers.set(type, fn);
    return this;
  }

  every(intervalMs, fn) {
    this.timers.push([intervalMs, fn]);
  }

  _dispatch(msg) {
    const body = msg.body || {};
    if (body.in_reply_to !== undefined && body.in_reply_to !== null) {
      const cb = this.callbacks.get(body.in_reply_to);
      if (cb) {
        this.callbacks.delete(body.in_reply_to);
        clearTimeout(cb.timer);
        if (body.type === "error") {
          cb.reject(new RPCError(body.code, body.text));
        } else {
          cb.resolve(body);
        }
      }
      return;
    }
    if (body.type === "init") {
      this.nodeId = body.node_id;
      this.nodeIds = body.node_ids;
      this.log(`node ${this.nodeId} initialized`);
      this.reply(msg, { type: "init_ok" });
      for (const [interval, fn] of this.timers) setInterval(fn, interval);
      for (const fn of this.initCallbacks) fn();
      return;
    }
    const handler = this.handlers.get(body.type);
    if (!handler) {
      this.reply(msg, RPCError.notSupported(
        `unknown message type ${body.type}`).body());
      return;
    }
    Promise.resolve()
      .then(() => handler(msg))
      .catch((e) => {
        const err = e instanceof RPCError
          ? e : new RPCError(13, String(e && e.stack || e));
        this.reply(msg, err.body());
      });
  }

  run() {
    const rl = readline.createInterface({ input: process.stdin });
    rl.on("line", (line) => {
      line = line.trim();
      if (!line) return;
      let msg;
      try {
        msg = JSON.parse(line);
      } catch (e) {
        this.log(`malformed input line: ${line}`);
        return;
      }
      this._dispatch(msg);
    });
  }
}

// Client for the built-in KV services (lin-kv / seq-kv / lww-kv).
class KV {
  constructor(node, service = "lin-kv", timeoutMs = 1000) {
    this.node = node;
    this.service = service;
    this.timeoutMs = timeoutMs;
  }

  async read(key, dflt) {
    try {
      const body = await this.node.rpc(
        this.service, { type: "read", key }, this.timeoutMs);
      return body.value;
    } catch (e) {
      if (e instanceof RPCError && e.code === 20 && dflt !== undefined) {
        return dflt;
      }
      throw e;
    }
  }

  async write(key, value) {
    await this.node.rpc(this.service,
                        { type: "write", key, value }, this.timeoutMs);
  }

  async cas(key, from, to, createIfNotExists = false) {
    await this.node.rpc(this.service, {
      type: "cas", key, from, to,
      create_if_not_exists: createIfNotExists,
    }, this.timeoutMs);
  }
}

module.exports = { Node, KV, RPCError };
