#!/usr/bin/env node
// Grow-only set CRDT node (JS): periodic full-state gossip merge.
"use strict";
const { Node } = require(require("path").join(__dirname, "node"));

const node = new Node();
const elements = new Set();

node.on("add", (msg) => {
  elements.add(msg.body.element);
  node.reply(msg, { type: "add_ok" });
});

node.on("read", (msg) =>
  node.reply(msg, { type: "read_ok", value: [...elements].sort() }));

node.on("merge", (msg) => {
  for (const e of msg.body.value || []) elements.add(e);
  node.reply(msg, { type: "merge_ok" });
});

node.every(300, () => {
  const peers = node.nodeIds.filter((n) => n !== node.nodeId);
  if (!peers.length) return;
  const peer = peers[Math.floor(Math.random() * peers.length)];
  node.rpc(peer, { type: "merge", value: [...elements] }, 1000)
    .catch(() => {});
});

node.run();
