#!/usr/bin/env node
// Broadcast node (JS): fire-and-forget gossip along the topology with a
// periodic anti-entropy retry of unacked values (partition tolerant).
"use strict";
const { Node } = require(require("path").join(__dirname, "node"));

const node = new Node();
const messages = new Set();
let neighbors = [];
const pending = new Map();   // peer -> Set of unacked values

node.on("topology", (msg) => {
  neighbors = (msg.body.topology || {})[node.nodeId] || [];
  for (const n of neighbors) if (!pending.has(n)) pending.set(n, new Set());
  node.reply(msg, { type: "topology_ok" });
});

function gossipTo(dest) {
  const vals = [...(pending.get(dest) || [])];
  if (!vals.length) return;
  node.rpc(dest, { type: "gossip", messages: vals, ack: true }, 1000)
    .then(() => {
      const p = pending.get(dest);
      if (p) for (const v of vals) p.delete(v);
    })
    .catch(() => {});   // retry timer re-sends
}

function propagate(vals, exclude) {
  for (const nbr of neighbors) {
    if (nbr === exclude) continue;
    const p = pending.get(nbr) || new Set();
    for (const v of vals) p.add(v);
    pending.set(nbr, p);
    gossipTo(nbr);
  }
}

node.on("broadcast", (msg) => {
  const m = msg.body.message;
  if (!messages.has(m)) {
    messages.add(m);
    propagate([m], msg.src);
  }
  node.reply(msg, { type: "broadcast_ok" });
});

node.on("gossip", (msg) => {
  const fresh = (msg.body.messages || []).filter((m) => !messages.has(m));
  for (const m of fresh) messages.add(m);
  if (fresh.length) propagate(fresh, msg.src);
  if (msg.body.ack) node.reply(msg, { type: "gossip_ok" });
});

node.on("read", (msg) =>
  node.reply(msg, { type: "read_ok",
                    messages: [...messages].sort((a, b) => a - b) }));

node.every(200, () => { for (const n of neighbors) gossipTo(n); });

node.run();
