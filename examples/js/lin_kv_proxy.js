#!/usr/bin/env node
// lin-kv proxy node (JS): serves read/write/cas by delegating to the
// built-in linearizable lin-kv service (the service-client demo).
"use strict";
const path = require("path");
const { Node, KV, RPCError } = require(path.join(__dirname, "node"));

const node = new Node();
const kv = new KV(node, "lin-kv", 2000);

node.on("read", async (msg) => {
  const value = await kv.read(msg.body.key, null);
  node.reply(msg, { type: "read_ok", value });
});

node.on("write", async (msg) => {
  await kv.write(msg.body.key, msg.body.value);
  node.reply(msg, { type: "write_ok" });
});

node.on("cas", async (msg) => {
  await kv.cas(msg.body.key, msg.body.from, msg.body.to,
               !!msg.body.create_if_not_exists);
  node.reply(msg, { type: "cas_ok" });
});

node.run();
