// Grow-only set CRDT node (workload: g-set): merge-on-gossip.
package main

import (
	"encoding/json"
	"log"
	"sync"
	"time"

	maelstrom "maelstrom-tpu/examples/go/maelstrom"
)

func main() {
	n := maelstrom.New()
	var mu sync.Mutex
	set := map[string]any{}   // canonical-JSON key -> value

	add := func(v any) {
		key, _ := json.Marshal(v)
		mu.Lock()
		set[string(key)] = v
		mu.Unlock()
	}
	elements := func() []any {
		mu.Lock()
		defer mu.Unlock()
		out := make([]any, 0, len(set))
		for _, v := range set {
			out = append(out, v)
		}
		return out
	}

	n.Handle("add", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		add(body["element"])
		return map[string]any{"type": "add_ok"}, nil
	})
	n.Handle("read", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		return map[string]any{"type": "read_ok",
			"value": elements()}, nil
	})
	n.Handle("merge", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		if vals, ok := body["value"].([]any); ok {
			for _, v := range vals {
				add(v)
			}
		}
		return nil, nil
	})

	n.OnInit(func() {
		go func() {
			for range time.Tick(500 * time.Millisecond) {
				for _, peer := range n.Peers() {
					if peer != n.ID() {
						n.Send(peer, map[string]any{
							"type": "merge", "value": elements()})
					}
				}
			}
		}()
	})

	if err := n.Run(); err != nil {
		log.Fatal(err)
	}
}
