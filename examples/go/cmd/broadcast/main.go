// Broadcast node (workload: broadcast): gossip-on-receive with
// periodic anti-entropy toward topology neighbors, so partitions heal.
package main

import (
	"log"
	"sync"
	"time"

	maelstrom "maelstrom-tpu/examples/go/maelstrom"
)

func main() {
	n := maelstrom.New()
	var mu sync.Mutex
	seen := map[float64]bool{}
	var neighbors []string

	values := func() []float64 {
		mu.Lock()
		defer mu.Unlock()
		out := make([]float64, 0, len(seen))
		for v := range seen {
			out = append(out, v)
		}
		return out
	}

	merge := func(vals any) []float64 {
		mu.Lock()
		defer mu.Unlock()
		var fresh []float64
		list, _ := vals.([]any)
		for _, raw := range list {
			if v, ok := raw.(float64); ok && !seen[v] {
				seen[v] = true
				fresh = append(fresh, v)
			}
		}
		return fresh
	}

	gossip := func(vals []float64, except string) {
		if len(vals) == 0 {
			return
		}
		mu.Lock()
		targets := append([]string(nil), neighbors...)
		mu.Unlock()
		for _, peer := range targets {
			if peer != except {
				n.Send(peer, map[string]any{
					"type": "gossip", "values": vals})
			}
		}
	}

	n.Handle("topology", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		mu.Lock()
		neighbors = neighbors[:0]
		if topo, ok := body["topology"].(map[string]any); ok {
			if mine, ok := topo[n.ID()].([]any); ok {
				for _, p := range mine {
					if s, ok := p.(string); ok {
						neighbors = append(neighbors, s)
					}
				}
			}
		}
		mu.Unlock()
		return map[string]any{"type": "topology_ok"}, nil
	})

	n.Handle("broadcast", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		fresh := merge([]any{body["message"]})
		gossip(fresh, "")
		return map[string]any{"type": "broadcast_ok"}, nil
	})

	n.Handle("gossip", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		gossip(merge(body["values"]), req.Src)
		return nil, nil
	})

	n.Handle("read", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		return map[string]any{"type": "read_ok",
			"messages": values()}, nil
	})

	// anti-entropy: full-state gossip on a timer heals partitions the
	// receive-time gossip missed
	n.OnInit(func() {
		go func() {
			for range time.Tick(500 * time.Millisecond) {
				gossip(values(), "")
			}
		}()
	})

	if err := n.Run(); err != nil {
		log.Fatal(err)
	}
}
