// Grow-only counter over seq-kv (workload: g-counter): CAS-increment
// a per-node key, sum all keys on read — exercises the KV client
// (kv.go) against the harness's Sequential service.
package main

import (
	"log"

	maelstrom "maelstrom-tpu/examples/go/maelstrom"
)

func main() {
	n := maelstrom.New()
	kv := maelstrom.NewSeqKV(n)

	n.Handle("add", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		delta, _ := body["delta"].(float64)
		key := "counter-" + n.ID()
		for {
			cur, err := kv.ReadInt(key, 0)
			if err != nil {
				return nil, err
			}
			err = kv.CAS(key, cur, cur+int(delta), true)
			if err == nil {
				return map[string]any{"type": "add_ok"}, nil
			}
			var rpcErr *maelstrom.RPCError
			if !maelstrom.AsRPCError(err, &rpcErr) ||
				rpcErr.Code != maelstrom.ErrPreconditionFailed {
				return nil, err
			}
		}
	})

	n.Handle("read", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		// sum every node's counter key; seq-kv staleness is within the
		// g-counter checker's interval tolerance
		total := 0
		for _, peer := range n.Peers() {
			v, err := kv.ReadInt("counter-"+peer, 0)
			if err != nil {
				return nil, err
			}
			total += v
		}
		return map[string]any{"type": "read_ok", "value": total}, nil
	})

	if err := n.Run(); err != nil {
		log.Fatal(err)
	}
}
