// Echo node (workload: echo).
package main

import (
	"log"

	maelstrom "maelstrom-tpu/examples/go/maelstrom"
)

func main() {
	n := maelstrom.New()
	n.Handle("echo", func(req maelstrom.Message,
		body map[string]any) (map[string]any, error) {
		return map[string]any{"type": "echo_ok",
			"echo": body["echo"]}, nil
	})
	if err := n.Run(); err != nil {
		log.Fatal(err)
	}
}
