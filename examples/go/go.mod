module maelstrom-tpu/examples/go

go 1.21
