// Package maelstrom is the Go node SDK for the maelstrom_tpu process
// runtime: newline-delimited JSON envelopes {src, dest, body} on
// stdin/stdout, an init handshake, handler dispatch by body type, and
// request/reply RPC with msg_id / in_reply_to correlation.
//
// Counterpart of the reference's Go library (demo/go/node.go:339),
// re-designed rather than ported: handlers RETURN their reply body
// (nil = no reply) instead of calling reply themselves, error replies
// fall out of returning *RPCError, and synchronous RPC is a plain
// blocking call with a timeout instead of a context/callback pair.
// Wire-compatible with every other SDK in examples/ (the runtime's
// schema registry is the contract; tests/test_go_wire_conformance.py
// holds this file to it).
package maelstrom

import (
	"bufio"
	"encoding/json"
	"errors"
	"fmt"
	"io"
	"os"
	"sync"
	"time"
)

// Message is one wire envelope.
type Message struct {
	Src  string          `json:"src"`
	Dest string          `json:"dest"`
	Body json.RawMessage `json:"body"`
}

// Handler processes one decoded request body and returns the reply
// body (nil for no reply). Returning *RPCError sends an error reply;
// any other error becomes a crash (code 13).
type Handler func(req Message, body map[string]any) (map[string]any, error)

// RPCError is the typed error of doc/protocol.md's error catalog.
type RPCError struct {
	Code int
	Text string
}

func (e *RPCError) Error() string {
	return fmt.Sprintf("rpc error %d: %s", e.Code, e.Text)
}

// Catalog codes used by SDK helpers (the full table lives in the
// runtime's core/errors.py).
const (
	ErrTimeout            = 0
	ErrNotSupported       = 10
	ErrTemporarilyUnavail = 11
	ErrCrash              = 13
	ErrKeyDoesNotExist    = 20
	ErrPreconditionFailed = 22
	ErrTxnConflict        = 30
)

// Node runs the message loop for one simulated process.
type Node struct {
	mu       sync.Mutex // guards writes, pending, nextID, id, peers
	r        io.Reader
	w        io.Writer
	id       string
	peers    []string
	handlers map[string]Handler
	onInit   func()
	pending  map[int]chan map[string]any
	nextID   int
	wg       sync.WaitGroup
}

// New returns a Node bound to stdin/stdout.
func New() *Node { return NewWithIO(os.Stdin, os.Stdout) }

// NewWithIO binds the node to explicit streams — the fake-stdio seam
// the unit tests drive (reference node_test.go:19-37 pattern).
func NewWithIO(r io.Reader, w io.Writer) *Node {
	return &Node{
		r:        r,
		w:        w,
		handlers: map[string]Handler{},
		pending:  map[int]chan map[string]any{},
	}
}

// ID is this node's identifier (valid once init has been handled).
func (n *Node) ID() string {
	n.mu.Lock()
	defer n.mu.Unlock()
	return n.id
}

// Peers is every node id in the cluster, this node included.
func (n *Node) Peers() []string {
	n.mu.Lock()
	defer n.mu.Unlock()
	return append([]string(nil), n.peers...)
}

// Handle registers the handler for one body type.
func (n *Node) Handle(typ string, h Handler) {
	if _, dup := n.handlers[typ]; dup {
		panic("duplicate handler for " + typ)
	}
	n.handlers[typ] = h
}

// OnInit registers a hook run after the init handshake completes.
func (n *Node) OnInit(f func()) { n.onInit = f }

func (n *Node) writeEnvelope(dest string, body map[string]any) error {
	n.mu.Lock()
	defer n.mu.Unlock()
	env := map[string]any{"src": n.id, "dest": dest, "body": body}
	buf, err := json.Marshal(env)
	if err != nil {
		return err
	}
	_, err = n.w.Write(append(buf, '\n'))
	return err
}

// Send ships a fire-and-forget body to dest.
func (n *Node) Send(dest string, body map[string]any) error {
	return n.writeEnvelope(dest, body)
}

// Reply answers req with body, stamping in_reply_to from the request's
// msg_id.
func (n *Node) Reply(req Message, body map[string]any) error {
	var reqBody map[string]any
	if err := json.Unmarshal(req.Body, &reqBody); err != nil {
		return err
	}
	if id, ok := reqBody["msg_id"]; ok {
		body["in_reply_to"] = id
	}
	return n.writeEnvelope(req.Src, body)
}

// RPC sends body to dest with a fresh msg_id and blocks for the reply
// body or the timeout (ErrTimeout as an *RPCError).
func (n *Node) RPC(dest string, body map[string]any,
	timeout time.Duration) (map[string]any, error) {
	n.mu.Lock()
	n.nextID++
	id := n.nextID
	ch := make(chan map[string]any, 1)
	n.pending[id] = ch
	n.mu.Unlock()
	defer func() {
		n.mu.Lock()
		delete(n.pending, id)
		n.mu.Unlock()
	}()
	body["msg_id"] = id
	if err := n.writeEnvelope(dest, body); err != nil {
		return nil, err
	}
	select {
	case reply := <-ch:
		if reply["type"] == "error" {
			code, _ := reply["code"].(float64)
			text, _ := reply["text"].(string)
			return nil, &RPCError{Code: int(code), Text: text}
		}
		return reply, nil
	case <-time.After(timeout):
		return nil, &RPCError{Code: ErrTimeout, Text: "RPC timeout"}
	}
}

// Run is the main loop: decode envelopes, route replies to waiting
// RPCs, dispatch requests to handlers (each on its own goroutine so a
// handler may itself issue RPCs). Returns when stdin closes.
func (n *Node) Run() error {
	scanner := bufio.NewScanner(n.r)
	scanner.Buffer(make([]byte, 0, 1<<20), 1<<24)
	for scanner.Scan() {
		line := scanner.Bytes()
		if len(line) == 0 {
			continue
		}
		var msg Message
		if err := json.Unmarshal(line, &msg); err != nil {
			fmt.Fprintf(os.Stderr, "bad envelope: %v\n", err)
			continue
		}
		var body map[string]any
		if err := json.Unmarshal(msg.Body, &body); err != nil {
			fmt.Fprintf(os.Stderr, "bad body: %v\n", err)
			continue
		}
		if irt, ok := body["in_reply_to"].(float64); ok {
			n.mu.Lock()
			ch := n.pending[int(irt)]
			n.mu.Unlock()
			if ch != nil {
				ch <- body
			}
			continue
		}
		typ, _ := body["type"].(string)
		if typ == "init" {
			n.handleInit(msg, body)
			continue
		}
		h, ok := n.handlers[typ]
		if !ok {
			n.Reply(msg, map[string]any{
				"type": "error", "code": ErrNotSupported,
				"text": "unknown type " + typ})
			continue
		}
		n.wg.Add(1)
		go func(msg Message, body map[string]any) {
			defer n.wg.Done()
			n.dispatch(h, msg, body)
		}(msg, body)
	}
	n.wg.Wait()
	return scanner.Err()
}

func (n *Node) dispatch(h Handler, msg Message, body map[string]any) {
	reply, err := h(msg, body)
	if err != nil {
		var rpcErr *RPCError
		if !errors.As(err, &rpcErr) {
			rpcErr = &RPCError{Code: ErrCrash, Text: err.Error()}
		}
		n.Reply(msg, map[string]any{
			"type": "error", "code": rpcErr.Code, "text": rpcErr.Text})
		return
	}
	if reply != nil {
		n.Reply(msg, reply)
	}
}

func (n *Node) handleInit(msg Message, body map[string]any) {
	n.mu.Lock()
	n.id, _ = body["node_id"].(string)
	n.peers = n.peers[:0]
	if ids, ok := body["node_ids"].([]any); ok {
		for _, v := range ids {
			if s, ok := v.(string); ok {
				n.peers = append(n.peers, s)
			}
		}
	}
	n.mu.Unlock()
	n.Reply(msg, map[string]any{"type": "init_ok"})
	if n.onInit != nil {
		n.onInit()
	}
}
