// Fake-stdio unit tests: drive the SDK through injected reader/writer
// pairs, no harness process needed — the reference Go library's
// testing pattern (demo/go/node_test.go:19-37), exercised here against
// this SDK's handler-returns-reply design.
package maelstrom

import (
	"bytes"
	"encoding/json"
	"strings"
	"testing"
)

func runNode(t *testing.T, setup func(*Node), lines ...string) []map[string]any {
	t.Helper()
	in := strings.NewReader(strings.Join(lines, "\n") + "\n")
	var out bytes.Buffer
	n := NewWithIO(in, &out)
	setup(n)
	if err := n.Run(); err != nil {
		t.Fatalf("run: %v", err)
	}
	var envs []map[string]any
	for _, l := range strings.Split(strings.TrimSpace(out.String()), "\n") {
		if l == "" {
			continue
		}
		var env map[string]any
		if err := json.Unmarshal([]byte(l), &env); err != nil {
			t.Fatalf("bad output line %q: %v", l, err)
		}
		envs = append(envs, env)
	}
	return envs
}

const initLine = `{"src":"c0","dest":"n1","body":{"type":"init",` +
	`"msg_id":1,"node_id":"n1","node_ids":["n1","n2"]}}`

func body(env map[string]any) map[string]any {
	return env["body"].(map[string]any)
}

func TestInitHandshake(t *testing.T) {
	envs := runNode(t, func(n *Node) {}, initLine)
	if len(envs) != 1 {
		t.Fatalf("want 1 reply, got %d", len(envs))
	}
	b := body(envs[0])
	if b["type"] != "init_ok" || b["in_reply_to"] != float64(1) {
		t.Fatalf("bad init reply: %v", b)
	}
	if envs[0]["dest"] != "c0" || envs[0]["src"] != "n1" {
		t.Fatalf("bad envelope: %v", envs[0])
	}
}

func TestHandlerReplyAndPeers(t *testing.T) {
	var peers []string
	envs := runNode(t, func(n *Node) {
		n.Handle("echo", func(req Message, b map[string]any) (map[string]any, error) {
			peers = n.Peers()
			return map[string]any{"type": "echo_ok", "echo": b["echo"]}, nil
		})
	}, initLine,
		`{"src":"c0","dest":"n1","body":{"type":"echo","msg_id":2,"echo":"hi"}}`)
	if len(envs) != 2 {
		t.Fatalf("want 2 replies, got %d", len(envs))
	}
	b := body(envs[1])
	if b["type"] != "echo_ok" || b["echo"] != "hi" ||
		b["in_reply_to"] != float64(2) {
		t.Fatalf("bad echo reply: %v", b)
	}
	if len(peers) != 2 || peers[0] != "n1" {
		t.Fatalf("bad peers: %v", peers)
	}
}

func TestErrorReplies(t *testing.T) {
	envs := runNode(t, func(n *Node) {
		n.Handle("boom", func(Message, map[string]any) (map[string]any, error) {
			return nil, &RPCError{Code: ErrTxnConflict, Text: "nope"}
		})
	}, initLine,
		`{"src":"c0","dest":"n1","body":{"type":"boom","msg_id":2}}`,
		`{"src":"c0","dest":"n1","body":{"type":"nosuch","msg_id":3}}`)
	if len(envs) != 3 {
		t.Fatalf("want 3 replies, got %d", len(envs))
	}
	// handler replies come off a dispatch goroutine while unknown-type
	// errors are written inline, so output ORDER is unspecified — match
	// replies to requests by in_reply_to
	codes := map[float64]float64{}
	for _, env := range envs[1:] {
		b := body(env)
		if b["type"] != "error" {
			t.Fatalf("want error reply, got %v", b)
		}
		codes[b["in_reply_to"].(float64)] = b["code"].(float64)
	}
	if codes[2] != 30 || codes[3] != 10 {
		t.Fatalf("bad error codes by request: %v", codes)
	}
}
