// KV client for the harness services (lin-kv / seq-kv / lww-kv /
// lin-tso) — the role of the reference's demo/go/kv.go:144, on this
// SDK's blocking-RPC surface instead of context callbacks.
package maelstrom

import "time"

// KV speaks read/write/cas to one harness KV service node.
type KV struct {
	service string
	node    *Node
	Timeout time.Duration
}

func NewLinKV(n *Node) *KV { return &KV{"lin-kv", n, 5 * time.Second} }
func NewSeqKV(n *Node) *KV { return &KV{"seq-kv", n, 5 * time.Second} }
func NewLWWKV(n *Node) *KV { return &KV{"lww-kv", n, 5 * time.Second} }

// Read returns the value of key (ErrKeyDoesNotExist as *RPCError when
// absent).
func (kv *KV) Read(key any) (any, error) {
	reply, err := kv.node.RPC(kv.service,
		map[string]any{"type": "read", "key": key}, kv.Timeout)
	if err != nil {
		return nil, err
	}
	return reply["value"], nil
}

// ReadInt reads key as an int, defaulting absent keys to dflt.
func (kv *KV) ReadInt(key any, dflt int) (int, error) {
	v, err := kv.Read(key)
	if err != nil {
		var rpcErr *RPCError
		if AsRPCError(err, &rpcErr) && rpcErr.Code == ErrKeyDoesNotExist {
			return dflt, nil
		}
		return 0, err
	}
	f, ok := v.(float64)
	if !ok {
		return 0, &RPCError{Code: ErrCrash, Text: "non-numeric value"}
	}
	return int(f), nil
}

// Write sets key to value.
func (kv *KV) Write(key, value any) error {
	_, err := kv.node.RPC(kv.service,
		map[string]any{"type": "write", "key": key, "value": value},
		kv.Timeout)
	return err
}

// CAS swaps key from -> to; createIfNotExists initializes absent keys.
// ErrPreconditionFailed (as *RPCError) reports a lost race.
func (kv *KV) CAS(key, from, to any, createIfNotExists bool) error {
	_, err := kv.node.RPC(kv.service, map[string]any{
		"type": "cas", "key": key, "from": from, "to": to,
		"create_if_not_exists": createIfNotExists}, kv.Timeout)
	return err
}

// AsRPCError extracts an *RPCError from err (errors.As without the
// interface dance for this concrete type).
func AsRPCError(err error, target **RPCError) bool {
	e, ok := err.(*RPCError)
	if ok {
		*target = e
	}
	return ok
}
