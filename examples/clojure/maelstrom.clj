;; Clojure (babashka) node SDK for the maelstrom_tpu process runtime:
;; JSON envelopes {src, dest, body} per line on stdin/stdout, init
;; handshake, handler dispatch by body type, request/reply RPC via
;; msg_id / in_reply_to.
;;
;; Counterpart of the reference's babashka library (demo/clojure/
;; node.clj), re-designed rather than ported: one namespace holding an
;; atom of node state, handlers as pure-ish fns RETURNING the reply
;; body (nil = no reply), error maps thrown via ex-info, and blocking
;; RPC on promises. Runs under babashka or JVM clojure (only
;; cheshire/clojure.data.json-free: bb ships cheshire).
;;
;; No Clojure runtime ships in this image —
;; tests/test_clojure_wire_conformance.py holds these sources to the
;; schema registry statically; the e2e suite runs when `bb` appears.

(ns maelstrom
  (:require [cheshire.core :as json]))

(def node-id (atom nil))
(def node-ids (atom []))
(def handlers (atom {}))
(def init-hooks (atom []))
(def pending (atom {}))          ; msg-id -> promise
(def next-msg-id (atom 0))
(def write-lock (Object.))

;; error catalog codes used by SDK helpers (core/errors.py)
(def err-timeout 0)
(def err-not-supported 10)
(def err-temporarily-unavailable 11)
(def err-crash 13)
(def err-key-does-not-exist 20)
(def err-precondition-failed 22)
(def err-txn-conflict 30)

(defn rpc-error
  "An ex-info a handler throws to send a typed error reply."
  [code text]
  (ex-info text {:maelstrom/code code}))

(defn- write-envelope! [dest body]
  (locking write-lock
    (println (json/generate-string
              {:src @node-id :dest dest :body body}))
    (flush)))

(defn send!
  "Fire-and-forget a body to dest."
  [dest body]
  (write-envelope! dest body))

(defn reply!
  "Answer msg with body, stamping in_reply_to from its msg_id."
  [msg body]
  (write-envelope! (:src msg)
                   (assoc body :in_reply_to (get-in msg [:body :msg_id]))))

(defn rpc!
  "Blocking RPC: returns the reply body, throws (rpc-error ...) on an
  error reply or timeout."
  ([dest body] (rpc! dest body 5000))
  ([dest body timeout-ms]
   (let [id (swap! next-msg-id inc)
         p (promise)]
     (swap! pending assoc id p)
     (write-envelope! dest (assoc body :msg_id id))
     (let [rep (deref p timeout-ms ::timeout)]
       (swap! pending dissoc id)
       (cond
         (= rep ::timeout)
         (throw (rpc-error err-timeout "RPC timeout"))
         (= (:type rep) "error")
         (throw (rpc-error (:code rep) (str (:text rep))))
         :else rep)))))

(defn on
  "Register a handler: (on \"echo\" (fn [msg body] {:type \"echo_ok\"}))"
  [type f]
  (swap! handlers assoc type f))

(defn on-init [f]
  (swap! init-hooks conj f))

;; --- KV client for the harness services (lin-kv / seq-kv / lww-kv) --

(defn kv-read [service k]
  (:value (rpc! service {:type "read" :key k})))

(defn kv-read-default [service k default]
  (try (kv-read service k)
       (catch clojure.lang.ExceptionInfo e
         (if (= (:maelstrom/code (ex-data e)) err-key-does-not-exist)
           default
           (throw e)))))

(defn kv-write [service k v]
  (rpc! service {:type "write" :key k :value v})
  nil)

(defn kv-cas
  ([service k from to] (kv-cas service k from to false))
  ([service k from to create?]
   (rpc! service {:type "cas" :key k :from from :to to
                  :create_if_not_exists create?})
   nil))

;; --- main loop ------------------------------------------------------

(defn- dispatch [msg body]
  (if-let [h (get @handlers (:type body))]
    (try
      (when-let [rep (h msg body)]
        (reply! msg rep))
      (catch clojure.lang.ExceptionInfo e
        (reply! msg {:type "error"
                     :code (or (:maelstrom/code (ex-data e)) err-crash)
                     :text (ex-message e)}))
      (catch Exception e
        (reply! msg {:type "error" :code err-crash
                     :text (str e)})))
    (reply! msg {:type "error" :code err-not-supported
                 :text (str "unknown type " (:type body))})))

(defn run!
  "Main loop: route replies to waiting RPCs, handle init, dispatch
  requests on futures (handlers may themselves block in rpc!)."
  []
  (doseq [line (line-seq (java.io.BufferedReader. *in*))]
    (when-not (empty? line)
      (let [msg (json/parse-string line true)
            body (:body msg)]
        (cond
          (:in_reply_to body)
          (when-let [p (get @pending (:in_reply_to body))]
            (deliver p body))

          (= (:type body) "init")
          (do (reset! node-id (:node_id body))
              (reset! node-ids (vec (:node_ids body)))
              (reply! msg {:type "init_ok"})
              (doseq [f @init-hooks] (f)))

          :else
          (future (dispatch msg body)))))))
