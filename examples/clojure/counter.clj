#!/usr/bin/env bb
;; Grow-only counter over seq-kv (workload: g-counter): CAS-increment
;; a per-node key, sum every node's key on read — exercises the KV
;; client against the harness's Sequential service.
(load-file (str (or (-> *file* java.io.File. .getParent) ".")
                "/maelstrom.clj"))

(defn my-key [] (str "counter-" @maelstrom/node-id))

(maelstrom/on "add"
  (fn [_msg body]
    (loop []
      (let [cur (maelstrom/kv-read-default "seq-kv" (my-key) 0)
            ok? (try
                  (maelstrom/kv-cas "seq-kv" (my-key) cur
                                    (+ cur (:delta body)) true)
                  true
                  (catch clojure.lang.ExceptionInfo e
                    (if (= (:maelstrom/code (ex-data e))
                           maelstrom/err-precondition-failed)
                      false
                      (throw e))))]
        (if ok? {:type "add_ok"} (recur))))))

(maelstrom/on "read"
  (fn [_msg body]
    ;; force recency: a write bumps this session's seq-kv watermark to
    ;; the newest state before summing (the Sequential service may
    ;; otherwise serve a stale snapshot — examples/python/
    ;; counter_seq_kv.py documents the same fix)
    (maelstrom/kv-write "seq-kv" (str "sync-" @maelstrom/node-id)
                        (:msg_id body 0))
    {:type "read_ok"
     :value (reduce + 0
                    (map #(maelstrom/kv-read-default
                           "seq-kv" (str "counter-" %) 0)
                         @maelstrom/node-ids))}))

(maelstrom/run!)
