#!/usr/bin/env bb
;; Echo node (workload: echo).
(load-file (str (or (-> *file* java.io.File. .getParent) ".")
                "/maelstrom.clj"))

(maelstrom/on "echo"
  (fn [_msg body]
    {:type "echo_ok" :echo (:echo body)}))

(maelstrom/run!)
