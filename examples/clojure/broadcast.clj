#!/usr/bin/env bb
;; Broadcast node (workload: broadcast): gossip-on-receive plus timed
;; anti-entropy toward topology neighbors so partitions heal.
(load-file (str (or (-> *file* java.io.File. .getParent) ".")
                "/maelstrom.clj"))

(def seen (atom #{}))
(def neighbors (atom []))

(defn gossip! [values except]
  (when (seq values)
    (doseq [peer @neighbors
            :when (not= peer except)]
      (maelstrom/send! peer {:type "gossip" :values (vec values)}))))

(maelstrom/on "topology"
  (fn [_msg body]
    (reset! neighbors
            (vec (get-in body [:topology (keyword @maelstrom/node-id)]
                          [])))
    {:type "topology_ok"}))

(maelstrom/on "broadcast"
  (fn [_msg body]
    (let [v (:message body)
          fresh? (not (contains? @seen v))]
      (swap! seen conj v)
      (when fresh? (gossip! [v] nil))
      {:type "broadcast_ok"})))

(maelstrom/on "gossip"
  (fn [msg body]
    (let [fresh (remove @seen (:values body))]
      (swap! seen into fresh)
      (gossip! fresh (:src msg)))
    nil))

(maelstrom/on "read"
  (fn [_msg _body]
    {:type "read_ok" :messages (vec @seen)}))

(maelstrom/on-init
  (fn []
    (future
      (loop []
        (Thread/sleep 500)
        (gossip! @seen nil)
        (recur)))))

(maelstrom/run!)
