#!/usr/bin/env python3
"""Trivially linearizable KV node: proxies every op to the built-in lin-kv
service. The role of the reference's demo/ruby/lin_kv_proxy.rb — exercises
the service path end-to-end."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
kv = KV(node, KV.LIN, timeout=2.0)


@node.on("read")
def read(msg):
    try:
        value = kv.read(msg["body"]["key"])
    except RPCError as e:
        node.reply_error(msg, e)
        return
    node.reply(msg, {"type": "read_ok", "value": value})


@node.on("write")
def write(msg):
    kv.write(msg["body"]["key"], msg["body"]["value"])
    node.reply(msg, {"type": "write_ok"})


@node.on("cas")
def cas(msg):
    b = msg["body"]
    try:
        kv.cas(b["key"], b["from"], b["to"], create_if_not_exists=False)
    except RPCError as e:
        node.reply_error(msg, e)
        return
    node.reply(msg, {"type": "cas_ok"})


if __name__ == "__main__":
    node.run()
