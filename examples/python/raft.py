#!/usr/bin/env python3
"""Raft consensus node for the process runtime: a linearizable KV store
behind the lin-kv workload, written against the bundled node SDK.

The canonical process-runtime reference implementation — the role of the
reference's demo/python/raft.py (elections :274-343, log replication
:391-445, commit via median match index :382-389, leader proxying
:552-571). Written from scratch on this SDK's threading model: all state
mutations run under node.lock (handlers and timers hold it; RPC
callbacks take it explicitly).

Usage: --bin examples/python/raft.py with the lin-kv workload.
"""

import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

ELECTION_MIN_S = 0.30
ELECTION_JITTER_S = 0.30
HEARTBEAT_S = 0.08
STEP_DOWN_S = 1.0   # leader steps down without majority contact this long

node = Node()


class Log:
    """1-indexed log of entries {term, op} (op None for the init entry),
    like the reference's 1-indexed Log (raft.py:114-156)."""

    def __init__(self):
        self.entries = [{"term": 0, "op": None}]

    def __len__(self):
        return len(self.entries)

    def get(self, i):
        if i < 1:
            raise IndexError(f"log indices are 1-based, got {i}")
        return self.entries[i - 1]

    def append(self, *entries):
        self.entries.extend(entries)

    def last_term(self):
        return self.entries[-1]["term"]

    def from_index(self, i):
        return self.entries[i - 1:]

    def truncate(self, length):
        del self.entries[length:]


class Raft:
    def __init__(self):
        self.term = 0
        self.voted_for = None
        self.role = "follower"
        self.log = Log()
        self.commit_index = 1
        self.last_applied = 1
        self.leader = None          # leader hint for proxying
        self.kv = {}
        self.votes = set()
        self.next_index = {}
        self.match_index = {}
        self.election_deadline = time.monotonic() + self._timeout()
        self.last_acks = {}         # peer -> last reply time (any kind)
        self.last_replication = 0.0
        # client requests waiting for their log entry to commit:
        # log index -> (term, original message)
        self.waiting = {}

    @staticmethod
    def _timeout():
        return ELECTION_MIN_S + random.random() * ELECTION_JITTER_S

    def reset_election_deadline(self):
        self.election_deadline = time.monotonic() + self._timeout()

    # --- role transitions -------------------------------------------------

    def advance_term(self, term):
        if term < self.term:
            raise RuntimeError("terms never go backwards")
        self.term = term
        self.voted_for = None

    def become_follower(self):
        self.role = "follower"
        self.votes = set()
        self.fail_waiting()
        self.reset_election_deadline()
        node.log(f"became follower in term {self.term}")

    def become_candidate(self):
        self.role = "candidate"
        self.advance_term(self.term + 1)
        self.voted_for = node.node_id
        self.votes = {node.node_id}
        self.leader = None
        self.reset_election_deadline()
        node.log(f"became candidate in term {self.term}")
        self.request_votes()

    def become_leader(self):
        self.role = "leader"
        self.leader = None
        self.next_index = {p: len(self.log) + 1
                           for p in node.other_node_ids()}
        self.match_index = {p: 0 for p in node.other_node_ids()}
        self.last_acks = {p: time.monotonic()
                          for p in node.other_node_ids()}
        self.last_replication = 0.0
        node.log(f"became leader in term {self.term}")

    def fail_waiting(self):
        """A deposed leader fails its in-flight client requests with an
        indefinite error (they may still commit later)."""
        for idx, (term, msg) in list(self.waiting.items()):
            node.reply_error(msg, RPCError(13,
                                           "leadership lost; outcome "
                                           "unknown"))
        self.waiting = {}

    # --- elections --------------------------------------------------------

    def request_votes(self):
        term = self.term

        def on_reply(body):
            with node.lock:
                self.maybe_step_down(body["term"])
                if (self.role == "candidate" and self.term == term
                        and body["term"] == term
                        and body.get("vote_granted")):
                    self.votes.add(body["__src"])
                    if len(self.votes) * 2 > len(node.node_ids):
                        self.become_leader()

        for peer in node.other_node_ids():
            self._rpc_with_src(peer, {
                "type": "request_vote",
                "term": term,
                "candidate_id": node.node_id,
                "last_log_index": len(self.log),
                "last_log_term": self.log.last_term(),
            }, on_reply)

    def _rpc_with_src(self, dest, body, cb):
        def wrapped(reply):
            reply = dict(reply)
            reply["__src"] = dest
            cb(reply)
        node.rpc(dest, body, wrapped)

    def maybe_step_down(self, remote_term):
        if remote_term > self.term:
            self.advance_term(remote_term)
            if self.role != "follower":
                self.become_follower()

    # --- replication ------------------------------------------------------

    def replicate(self, force=False):
        if self.role != "leader":
            return
        now = time.monotonic()
        if not force and now - self.last_replication < HEARTBEAT_S:
            return
        self.last_replication = now
        term = self.term
        for peer in node.other_node_ids():
            ni = self.next_index[peer]
            entries = self.log.from_index(ni)[:16]

            def on_reply(body, peer=peer, ni=ni, n=len(entries)):
                with node.lock:
                    self.last_acks[peer] = time.monotonic()
                    self.maybe_step_down(body["term"])
                    if self.role != "leader" or self.term != term:
                        return
                    if body.get("success"):
                        self.next_index[peer] = max(
                            self.next_index[peer], ni + n)
                        self.match_index[peer] = max(
                            self.match_index[peer], ni + n - 1)
                        self.advance_commit()
                    else:
                        self.next_index[peer] = max(1,
                                                    self.next_index[peer]
                                                    - 1)

            self._rpc_with_src(peer, {
                "type": "append_entries",
                "term": term,
                "leader_id": node.node_id,
                "prev_log_index": ni - 1,
                "prev_log_term": (self.log.get(ni - 1)["term"]
                                  if ni > 1 else 0),
                "entries": entries,
                "leader_commit": self.commit_index,
            }, on_reply)

    def advance_commit(self):
        """Median match index, current term only (raft.py:382-389)."""
        if self.role != "leader":
            return
        matches = sorted(list(self.match_index.values())
                         + [len(self.log)])
        n = matches[(len(matches) - 1) // 2]
        if n > self.commit_index and self.log.get(n)["term"] == self.term:
            self.commit_index = n
            self.apply_committed()

    def apply_committed(self):
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.log.get(self.last_applied)
            op = entry["op"]
            if op is None:
                continue
            reply = self.apply_op(op)
            waiter = self.waiting.pop(self.last_applied, None)
            if waiter is not None and self.role == "leader":
                term, msg = waiter
                if isinstance(reply, RPCError):
                    node.reply_error(msg, reply)
                else:
                    node.reply(msg, reply)

    def apply_op(self, op):
        t = op["type"]
        k = str(op["key"])
        if t == "read":
            if k not in self.kv:
                return RPCError.key_does_not_exist(f"key {k!r} not found")
            return {"type": "read_ok", "value": self.kv[k]}
        if t == "write":
            self.kv[k] = op["value"]
            return {"type": "write_ok"}
        if t == "cas":
            if k not in self.kv:
                return RPCError.key_does_not_exist(f"key {k!r} not found")
            if self.kv[k] != op["from"]:
                return RPCError.precondition_failed(
                    f"expected {op['from']!r} but had {self.kv[k]!r}")
            self.kv[k] = op["to"]
            return {"type": "cas_ok"}
        return RPCError(12, f"unknown op type {t!r}")


raft = Raft()


# --- message handlers -----------------------------------------------------

@node.on("request_vote")
def request_vote(msg):
    b = msg["body"]
    raft.maybe_step_down(b["term"])
    grant = False
    if (b["term"] == raft.term
            and raft.voted_for in (None, b["candidate_id"])
            and (b["last_log_term"] > raft.log.last_term()
                 or (b["last_log_term"] == raft.log.last_term()
                     and b["last_log_index"] >= len(raft.log)))):
        grant = True
        raft.voted_for = b["candidate_id"]
        raft.reset_election_deadline()
    node.reply(msg, {"type": "request_vote_res", "term": raft.term,
                     "vote_granted": grant})


@node.on("append_entries")
def append_entries(msg):
    b = msg["body"]
    raft.maybe_step_down(b["term"])
    res = {"type": "append_entries_res", "term": raft.term,
           "success": False}
    if b["term"] < raft.term:
        node.reply(msg, res)
        return
    # a current-term AppendEntries is from the legitimate leader
    raft.leader = b["leader_id"]
    if raft.role == "candidate":
        raft.become_follower()
    raft.reset_election_deadline()
    prev_i = b["prev_log_index"]
    if prev_i > 0 and (prev_i > len(raft.log)
                       or raft.log.get(prev_i)["term"]
                       != b["prev_log_term"]):
        node.reply(msg, res)
        return
    # truncate conflicts, append new entries
    for j, e in enumerate(b["entries"]):
        i = prev_i + 1 + j
        if i <= len(raft.log):
            if raft.log.get(i)["term"] != e["term"]:
                raft.log.truncate(i - 1)
                raft.log.append(e)
        else:
            raft.log.append(e)
    if b["leader_commit"] > raft.commit_index:
        # Raft §5.3: bound by the last entry this AppendEntries verified,
        # not the local log length (which may hold an unverified tail)
        bound = prev_i + len(b["entries"])
        raft.commit_index = max(raft.commit_index,
                                min(b["leader_commit"], bound))
        raft.apply_committed()
    res["success"] = True
    node.reply(msg, res)


def client_op(msg):
    if raft.role == "leader":
        raft.log.append({"term": raft.term, "op": msg["body"]})
        raft.waiting[len(raft.log)] = (raft.term, msg)
        raft.replicate(force=True)
    elif raft.leader is not None:
        # proxy to the current leader (raft.py:552-571): re-send the
        # client's body; the leader replies to us and we relay back
        body = dict(msg["body"])

        def relay(reply):
            out = dict(reply)
            out.pop("in_reply_to", None)
            out["in_reply_to"] = msg["body"]["msg_id"]
            node.send(msg["src"], out)

        node.rpc(raft.leader, body, relay)
    else:
        node.reply_error(msg, RPCError.temporarily_unavailable(
            "not a leader, and no known leader"))


for t in ("read", "write", "cas"):
    node.on(t, client_op)


# --- timers ----------------------------------------------------------------

@node.every(0.05)
def election_tick():
    now = time.monotonic()
    if raft.role != "leader" and now >= raft.election_deadline:
        raft.become_candidate()
    elif raft.role == "leader":
        # step down if we've lost contact with a majority (a stale
        # leader in a minority partition must stop stringing clients
        # along; the reference's step-down deadline plays this role)
        recent = sum(1 for t in raft.last_acks.values()
                     if now - t < STEP_DOWN_S)
        if (recent + 1) * 2 <= len(node.node_ids):
            node.log("stepping down: lost contact with majority")
            raft.become_follower()


@node.every(HEARTBEAT_S / 2)
def replication_tick():
    raft.replicate()


if __name__ == "__main__":
    node.run()
