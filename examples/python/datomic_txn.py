#!/usr/bin/env python3
"""Multi-node strict-serializable transactor over the lin-kv service.

Every node executes transactions optimistically against a database value
stored under a single key in the built-in lin-kv service: read the root,
apply the micro-ops, compare-and-set the root. A CAS conflict aborts the
transaction with error 30 (txn-conflict), which is definite — the client
may safely retry. Strict serializability follows from the linearizable
root pointer.

The role of the reference's demo/ruby/datomic_list_append.rb (root CAS in
lin-kv, :3-40), simplified to a whole-database value instead of
persistent hash-tree pages.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
kv = KV(node, KV.LIN, timeout=2.0)

ROOT = "datomic-root"


def init_root():
    """The first node creates the root before any client op runs — a
    concurrent cas-create race between nodes would lose a transaction."""
    if node.node_ids and node.node_id == node.node_ids[0]:
        try:
            kv.write(ROOT, {"__init__": True})
        except RPCError as e:
            node.log(f"root init failed: {e}")


node.init_callbacks.append(init_root)


@node.on("txn")
def txn(msg):
    ops = msg["body"]["txn"]
    db = kv.read(ROOT, default=None) or {}
    new_db = dict(db)
    out = []
    for f, k, v in ops:
        k = str(k)
        kk = int(k) if k.isdigit() else k
        if f == "r":
            out.append(["r", kk, new_db.get(k)])
        elif f == "append":
            new_db[k] = list(new_db.get(k) or []) + [v]
            out.append(["append", kk, v])
        elif f == "w":
            new_db[k] = v
            out.append(["w", kk, v])
        else:
            raise RPCError(12, f"unknown micro-op {f!r}")
    if new_db != db:
        try:
            kv.cas(ROOT, db or None, new_db,
                   create_if_not_exists=(not db))
        except RPCError as e:
            if e.code in (20, 22):
                raise RPCError.txn_conflict(
                    "root CAS failed; transaction aborted") from None
            raise
    node.reply(msg, {"type": "txn_ok", "txn": out})


if __name__ == "__main__":
    node.run()
