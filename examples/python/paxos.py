#!/usr/bin/env python3
"""lin-kv node driven by per-key multi-slot single-decree Paxos.

Every client operation (read / write / cas — reads included, for
linearizability) is decided into the next free slot of its key's log by
a full two-phase single-decree Paxos round (prepare/promise with
accepted-value adoption, then accept/accepted on a majority, then a
decide broadcast). No stable leader, no leases: competing proposers
collide, adopt each other's values, and retry with higher ballots —
the classic teaching construction (BASELINE.json config #4's
"single-decree Paxos demo node"; protocol-equivalent role to the
reference's Raft chapter nodes, built on the plain node SDK).

Partition-tolerant: ops proposed on the majority side commit; minority
proposers exhaust their ballot budget and fail definite (error 11), so
clients retry safely.
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()

MAX_ROUNDS = 10          # ballot retries before giving up (definite 11)
PHASE_TIMEOUT = 0.6      # seconds to wait for a quorum per phase

state = threading.RLock()
# acceptor: (key, slot) -> {"promised": ballot, "accepted": (ballot, v)}
acceptor = {}
# learner: key -> list of chosen ops (the key's command log)
chosen = {}
# applied kv: key -> register value, and how many slots are applied
kv = {}
applied = {}
_ballot_counter = [0]


def _majority():
    return len(node.node_ids) // 2 + 1


def _next_ballot():
    with state:
        _ballot_counter[0] += 1
        return [_ballot_counter[0], node.node_id]


def _bump_ballot(seen):
    with state:
        _ballot_counter[0] = max(_ballot_counter[0], seen[0])


def _acc(key, slot):
    return acceptor.setdefault((key, slot), {"promised": None,
                                             "accepted": None})


def _quorum_call(body, collect):
    """Send ``body`` to every node (self included, via loopback call),
    wait for a majority of positive replies within PHASE_TIMEOUT.
    ``collect(reply_body)`` returns True if the reply counts toward the
    quorum. Returns True on quorum."""
    need = _majority()
    got = [0]
    done = threading.Event()

    def on_reply(reply):
        if collect(reply):
            with state:
                got[0] += 1
                if got[0] >= need:
                    done.set()

    for peer in node.node_ids:
        if peer == node.node_id:
            on_reply(_handle_local(dict(body)))
        else:
            node.rpc(peer, dict(body), on_reply, timeout=PHASE_TIMEOUT)
    done.wait(PHASE_TIMEOUT)
    return got[0] >= need


def _handle_local(body):
    """Run our own acceptor for a loopback phase message."""
    if body["type"] == "prepare":
        return _prepare(body)
    return _accept(body)


def _prepare(b):
    with state:
        a = _acc(b["key"], b["slot"])
        if a["promised"] is None or b["ballot"] >= a["promised"]:
            a["promised"] = list(b["ballot"])
            return {"type": "promise", "ok": True,
                    "accepted": a["accepted"]}
        return {"type": "promise", "ok": False,
                "promised": a["promised"]}


def _accept(b):
    with state:
        a = _acc(b["key"], b["slot"])
        if a["promised"] is None or b["ballot"] >= a["promised"]:
            a["promised"] = list(b["ballot"])
            a["accepted"] = [list(b["ballot"]), b["value"]]
            return {"type": "accepted", "ok": True}
        return {"type": "accepted", "ok": False,
                "promised": a["promised"]}


@node.on("prepare")
def on_prepare(msg):
    node.reply(msg, _prepare(msg["body"]))


@node.on("accept")
def on_accept(msg):
    node.reply(msg, _accept(msg["body"]))


@node.on("decide")
def on_decide(msg):
    b = msg["body"]
    _learn(b["key"], b["slot"], b["value"])
    node.reply(msg, {"type": "decide_ok"})


def _learn(key, slot, value):
    with state:
        log = chosen.setdefault(key, {})
        log[slot] = value
        # apply any now-contiguous prefix
        kv.setdefault(key, None)
        n = applied.setdefault(key, 0)
        while n in log:
            op = log[n]
            if op["f"] == "write":
                kv[key] = op["value"]
            elif op["f"] == "cas" and kv[key] == op["from"]:
                kv[key] = op["to"]
            # reads leave state untouched
            n += 1
        applied[key] = n


def _decide_all(key, slot, value):
    _learn(key, slot, value)
    for peer in node.node_ids:
        if peer != node.node_id:
            node.rpc(peer, {"type": "decide", "key": key, "slot": slot,
                            "value": value}, lambda r: None,
                     timeout=PHASE_TIMEOUT)


def _paxos_round(key, slot, my_op):
    """One full prepare+accept round for (key, slot).

    Returns ``(decided, value)``. ``value`` is the value this round
    carried as far as it got: None when the PREPARE phase failed (our
    op never left this node), the accepted-phase value when the ACCEPT
    quorum failed (it may have reached a minority — the caller MUST
    treat a matching op id as exposed/indefinite, never definite-fail),
    and the decided value on success. Defined as a FUNCTION (not inline in the
    retry loop) so every round gets fresh closure cells: a late promise
    reply from round k — its callback survives in the SDK's table after
    the phase timeout — must never write into round k+1's ``adopted``.
    (With loop-local closures, rebinding ``adopted`` each iteration
    shares one cell across all rounds; a delayed high-ballot promise
    from the previous slot's round then overwrites the current round's
    adoption and the proposer accepts the WRONG value — an actual
    linearizability violation this framework's own WGL checker + net
    journal caught: same-slot conflicting decides, divergent logs.)"""
    ballot = _next_ballot()
    adopted = [None]   # highest-ballot accepted value seen THIS round

    def on_promise(r):
        if r.get("type") != "promise":
            return False
        if not r.get("ok"):
            if r.get("promised"):
                _bump_ballot(r["promised"])
            return False
        acc = r.get("accepted")
        if acc:
            with state:
                if adopted[0] is None or acc[0] > adopted[0][0]:
                    adopted[0] = acc
        return True

    if not _quorum_call({"type": "prepare", "key": key,
                         "slot": slot, "ballot": ballot},
                        on_promise):
        return False, None
    value = adopted[0][1] if adopted[0] else my_op

    def on_accepted(r):
        if r.get("type") != "accepted" or not r.get("ok"):
            if r.get("promised"):
                _bump_ballot(r["promised"])
            return False
        return True

    if not _quorum_call({"type": "accept", "key": key, "slot": slot,
                         "ballot": ballot, "value": value},
                        on_accepted):
        return False, value
    _decide_all(key, slot, value)
    return True, value


def _propose(key, my_op):
    """Decide ``my_op`` into some slot of ``key``; returns the slot it
    was chosen in (driving competing values to completion on the way)."""
    exposed = False   # once our value reached ANY acceptor, a later
                      # proposer may adopt and commit it, so giving up
                      # must be INDEFINITE (the op may still happen)
    for _ in range(MAX_ROUNDS):
        with state:
            log = chosen.get(key, {})
            # adoption dedupe: a competing proposer may have adopted and
            # committed OUR value after a partial accept — proposing it
            # again would apply the op twice (linearizability violation)
            for s_done, v_done in log.items():
                if v_done.get("id") == my_op["id"]:
                    return s_done
            slot = applied.get(key, 0)
            while slot in log:
                slot += 1
        decided, value = _paxos_round(key, slot, my_op)
        if value is not None and value.get("id") == my_op["id"]:
            exposed = True
        if not decided:
            time.sleep(0.02)
            continue
        if value.get("id") == my_op["id"]:
            return slot
        # our slot was taken by an adopted value; drive on to the next
    if exposed:
        # indefinite: an accepted copy of our value may yet be chosen
        raise RPCError.timeout("gave up mid-accept; op may still apply")
    raise RPCError(11, "could not reach consensus (partitioned?)")


_op_counter = [0]


def _run_client_op(msg, f, extra):
    key = str(msg["body"]["key"])
    with state:
        _op_counter[0] += 1
        op_id = f"{node.node_id}-{_op_counter[0]}"
    my_op = {"f": f, "id": op_id, **extra}
    slot = _propose(key, my_op)
    with state:
        # compute the op's result from the log prefix (_propose returns
        # only once every slot <= ours is chosen and learned locally)
        val = None
        for s in range(slot + 1):
            op = chosen[key][s]
            if op["f"] == "write":
                val = op["value"]
            elif op["f"] == "cas" and val == op["from"]:
                val = op["to"]
        if f == "read":
            node.reply(msg, {"type": "read_ok", "value": val})
        elif f == "write":
            node.reply(msg, {"type": "write_ok"})
        else:
            # recompute whether OUR cas succeeded: state just before it
            pre = None
            for s in range(slot):
                op = chosen[key][s]
                if op["f"] == "write":
                    pre = op["value"]
                elif op["f"] == "cas" and pre == op["from"]:
                    pre = op["to"]
            if pre == my_op["from"]:
                node.reply(msg, {"type": "cas_ok"})
            elif pre is None:
                node.reply_error(msg, RPCError(20, "key does not exist"))
            else:
                node.reply_error(msg, RPCError(
                    22, f"expected {my_op['from']!r}, had {pre!r}"))


def _client_op_async(msg, f, extra):
    """The SDK dispatches handlers under node.lock; a multi-round Paxos
    proposal blocks for seconds, which would stall this node's acceptor
    (prepare/accept queue behind the lock) and livelock competing
    proposers. Run the proposal on a worker thread instead — the
    acceptor handlers stay quick — and map errors to replies ourselves
    (the SDK's auto-reply only covers in-handler exceptions)."""
    def work():
        try:
            _run_client_op(msg, f, extra)
        except RPCError as e:
            node.reply_error(msg, e)
        except Exception as e:  # noqa: BLE001
            node.reply_error(msg, RPCError(13, repr(e)))
    threading.Thread(target=work, daemon=True).start()


@node.on("read")
def on_read(msg):
    _client_op_async(msg, "read", {})


@node.on("write")
def on_write(msg):
    _client_op_async(msg, "write", {"value": msg["body"]["value"]})


@node.on("cas")
def on_cas(msg):
    _client_op_async(msg, "cas", {"from": msg["body"]["from"],
                                  "to": msg["body"]["to"]})


if __name__ == "__main__":
    node.run()
