#!/usr/bin/env python3
"""Datomic-style transactor: immutable hash-tree pages + root-pointer CAS.

The database is split into ``B`` hash buckets. Each bucket's contents
live in an IMMUTABLE page (a fresh unique id per version) stored in the
eventually-consistent lww-kv service — safe because immutable values
never conflict under last-write-wins. The only mutable cell is the root
(bucket -> page id map) in lin-kv, advanced by compare-and-set; strict
serializability follows from the linearizable root pointer.

The role of the reference's demo/ruby/datomic_list_append.rb (persistent
pages in lww-kv, root CAS in lin-kv, :3-40) — plus an OCC rebase loop:
on a root CAS conflict, if no concurrent commit touched this txn's
read/write buckets, the txn re-CASes a rebased root instead of
re-executing or aborting, so transactions on disjoint keys never abort
(VERDICT r1 missing #4). Read-only transactions never CAS at all.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
root_kv = KV(node, KV.LIN, timeout=2.0)
page_kv = KV(node, KV.LWW, timeout=2.0)

ROOT = "datomic-root"
BUCKETS = 8
MAX_ATTEMPTS = 8

_page_counter = [0]


def _new_page_id() -> str:
    _page_counter[0] += 1
    return f"{node.node_id}-{_page_counter[0]}"


def _bucket(k) -> str:
    # deterministic across processes (python's hash() is per-process
    # randomized, which would make nodes disagree on bucket layout)
    s = str(k)
    if s.isdigit():
        return str(int(s) % BUCKETS)
    return str(sum(s.encode()) % BUCKETS)


# pages are immutable, so a node-local cache is perfectly coherent and
# absorbs both re-reads and this node's own writes
_page_cache = {}


def _read_page(page_id):
    """lww-kv is eventually consistent: a freshly committed page may not
    have reached the replica we hit, so retry briefly before giving up."""
    cached = _page_cache.get(page_id)
    if cached is not None:
        return cached
    for attempt in range(12):
        try:
            value = page_kv.read(page_id)
            _page_cache[page_id] = value
            return value
        except RPCError as e:
            if e.code != 20:
                raise
            time.sleep(0.01 * (attempt + 1))
    raise RPCError(11, f"page {page_id} not yet visible")


def init_root():
    if node.node_ids and node.node_id == node.node_ids[0]:
        try:
            root_kv.write(ROOT, {})
        except RPCError as e:
            node.log(f"root init failed: {e}")


node.init_callbacks.append(init_root)


def _execute(ops, root):
    """Run micro-ops against the snapshot ``root``. Returns
    (results, new_pages {page_id: value}, dirty {bucket: page_id},
    read_set buckets)."""
    pages = {}      # bucket -> page dict (loaded or being built)
    dirty = {}      # bucket -> new page id
    read_set = set()
    out = []
    for f, k, v in ops:
        b = _bucket(k)
        read_set.add(b)
        if b not in pages:
            pid = root.get(b)
            pages[b] = dict(_read_page(pid)) if pid else {}
        page = pages[b]
        kk = int(k) if str(k).isdigit() else k
        key = str(k)
        if f == "r":
            out.append(["r", kk, page.get(key)])
        elif f == "append":
            page[key] = list(page.get(key) or []) + [v]
            dirty[b] = None
            out.append(["append", kk, v])
        elif f == "w":
            page[key] = v
            dirty[b] = None
            out.append(["w", kk, v])
        else:
            raise RPCError(12, f"unknown micro-op {f!r}")
    new_pages = {}
    for b in dirty:
        pid = _new_page_id()
        dirty[b] = pid
        new_pages[pid] = pages[b]
    return out, new_pages, dirty, read_set


MISSING = object()


@node.on("txn")
def txn(msg):
    ops = msg["body"]["txn"]
    stored = root_kv.read(ROOT, default=MISSING)
    root = {} if stored is MISSING else stored
    out, new_pages, dirty, read_set = _execute(ops, root)

    if not dirty:   # read-only: serializes at the root read, no CAS
        node.reply(msg, {"type": "txn_ok", "txn": out})
        return

    for pid, value in new_pages.items():
        _page_cache[pid] = value
        page_kv.write(pid, value)

    attempt = 0
    while True:
        new_root = dict(root)
        new_root.update(dirty)
        try:
            root_kv.cas(ROOT, None if stored is MISSING else stored,
                        new_root,
                        create_if_not_exists=stored is MISSING)
            node.reply(msg, {"type": "txn_ok", "txn": out})
            return
        except RPCError as e:
            if e.code not in (20, 22):
                raise
        attempt += 1
        if attempt >= MAX_ATTEMPTS:
            raise RPCError.txn_conflict(
                "root CAS contention; transaction aborted") from None
        stored = root_kv.read(ROOT, default=MISSING)
        latest = {} if stored is MISSING else stored
        touched = read_set | set(dirty)
        if all(latest.get(b) == root.get(b) for b in touched):
            # disjoint concurrent commit: rebase our entries onto the
            # new root without re-executing
            root = latest
            continue
        # our data moved under us: re-execute against the new snapshot
        root = latest
        out, new_pages, dirty, read_set = _execute(ops, root)
        if not dirty:
            node.reply(msg, {"type": "txn_ok", "txn": out})
            return
        for pid, value in new_pages.items():
            _page_cache[pid] = value
            page_kv.write(pid, value)


if __name__ == "__main__":
    node.run()
