#!/usr/bin/env python3
"""G-counter/PN-counter node over the built-in seq-kv service.

Adds are CAS retry loops against a single counter key. seq-kv is only
sequentially consistent, so a plain read may be stale; before reading we
write a per-node sync key, which forces our session's watermark to the
newest state (mutations always apply to the freshest state in the
Sequential wrapper) — the classic recency trick from the reference's
CRDT chapter (doc/04-crdts, seq-kv counter).

The role of the reference's demo/clojure/gcounter.clj.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
kv = KV(node, KV.SEQ, timeout=2.0)

KEY = "counter"


def init_counter():
    """First node seeds the key before any client op: a concurrent
    cas-create race between nodes could lose an add."""
    if node.node_ids and node.node_id == node.node_ids[0]:
        kv.write(KEY, 0)


node.init_callbacks.append(init_counter)


@node.on("add")
def add(msg):
    delta = msg["body"]["delta"]
    while True:
        cur = kv.read(KEY, default=None)
        if cur is None:
            try:
                kv.cas(KEY, None, delta, create_if_not_exists=True)
                break
            except RPCError as e:
                if e.code not in (20, 22):
                    raise
        else:
            try:
                kv.cas(KEY, cur, cur + delta)
                break
            except RPCError as e:
                if e.code not in (20, 22):
                    raise
    node.reply(msg, {"type": "add_ok"})


@node.on("read")
def read(msg):
    # force recency: a write bumps this session to the newest state
    kv.write(f"sync-{node.node_id}", msg["body"].get("msg_id", 0))
    value = kv.read(KEY, default=0)
    node.reply(msg, {"type": "read_ok", "value": value})


if __name__ == "__main__":
    node.run()
