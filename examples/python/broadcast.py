#!/usr/bin/env python3
"""Broadcast node with two gossip disciplines:

- default (acked): batched, acknowledged retries — broadcasts survive
  partitions (the retry-until-ack design the reference's performance
  chapter builds for fault tolerance,
  doc/03-broadcast/02-performance.md:513-545), at the cost of an ack per
  gossip.
- ``--ff`` (fire-and-forget): each new value crosses every topology edge
  exactly once, no acks, no retries — the minimal-traffic discipline the
  chapter's efficiency sections measure (2.94 msgs/op on 5 nodes,
  ~12.0 on 25-node tree4, doc/03-broadcast/02-performance.md:71-76,
  249-254). Not partition-tolerant; pair with a healed network.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

FIRE_AND_FORGET = "--ff" in sys.argv[1:]

node = Node()
messages = set()
neighbors = []
# peer -> set of values not yet acknowledged by that peer (acked mode)
pending = {}


@node.on("topology")
def topology(msg):
    global neighbors
    neighbors = msg["body"]["topology"].get(node.node_id, [])
    for nbr in neighbors:
        pending.setdefault(nbr, set())
    node.log(f"topology: neighbors = {neighbors} "
             f"({'ff' if FIRE_AND_FORGET else 'acked'} gossip)")
    node.reply(msg, {"type": "topology_ok"})


def propagate(new_vals, exclude):
    """Hand new values to the active gossip discipline."""
    if FIRE_AND_FORGET:
        batch = sorted(new_vals)
        for nbr in neighbors:
            if nbr != exclude:
                node.send(nbr, {"type": "gossip", "messages": batch})
        return
    for nbr in neighbors:
        if nbr != exclude:
            pending.setdefault(nbr, set()).update(new_vals)
    flush()


def flush():
    """One batched acked gossip per peer with everything it hasn't acked."""
    for dest, vals in pending.items():
        if not vals:
            continue
        batch = sorted(vals)

        def on_ack(reply, dest=dest, batch=batch):
            with node.lock:
                pending.get(dest, set()).difference_update(batch)

        node.rpc(dest, {"type": "gossip", "messages": batch, "ack": True},
                 on_ack)


@node.on("broadcast")
def broadcast(msg):
    m = msg["body"]["message"]
    if m not in messages:
        messages.add(m)
        propagate({m}, exclude=msg["src"])
    node.reply(msg, {"type": "broadcast_ok"})


@node.on("gossip")
def handle_gossip(msg):
    new = set(msg["body"]["messages"]) - messages
    messages.update(new)
    if new:
        propagate(new, exclude=msg["src"])
    if msg["body"].get("ack"):
        node.reply(msg, {"type": "gossip_ok"})


@node.on("read")
def read(msg):
    node.reply(msg, {"type": "read_ok", "messages": sorted(messages)})


@node.every(0.2)
def retry():
    if not FIRE_AND_FORGET:
        flush()


if __name__ == "__main__":
    node.run()
