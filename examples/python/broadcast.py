#!/usr/bin/env python3
"""Broadcast node: gossips messages along the topology with batched,
acknowledged retries, so broadcasts survive partitions while keeping
msgs-per-op low (one gossip message per peer per retry tick carries ALL
unacked values). The role of the reference's demo/ruby/broadcast.rb
retry loop, plus the batching optimization its performance chapter works
toward (doc/03-broadcast/02-performance.md)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
messages = set()
neighbors = []
# peer -> set of values not yet acknowledged by that peer
pending = {}


@node.on("topology")
def topology(msg):
    global neighbors
    neighbors = msg["body"]["topology"].get(node.node_id, [])
    for nbr in neighbors:
        pending.setdefault(nbr, set())
    node.log(f"topology: neighbors = {neighbors}")
    node.reply(msg, {"type": "topology_ok"})


def gossip(m, exclude):
    for nbr in neighbors:
        if nbr != exclude:
            pending.setdefault(nbr, set()).add(m)


def flush():
    """One batched gossip per peer carrying everything it hasn't acked."""
    for dest, vals in pending.items():
        if not vals:
            continue
        batch = sorted(vals)

        def on_ack(reply, dest=dest, batch=batch):
            with node.lock:
                pending.get(dest, set()).difference_update(batch)

        node.rpc(dest, {"type": "gossip", "messages": batch}, on_ack)


@node.on("broadcast")
def broadcast(msg):
    m = msg["body"]["message"]
    if m not in messages:
        messages.add(m)
        gossip(m, exclude=msg["src"])
        flush()   # propagate immediately; the timer only covers losses
    node.reply(msg, {"type": "broadcast_ok"})


@node.on("gossip")
def handle_gossip(msg):
    new = set(msg["body"]["messages"]) - messages
    messages.update(new)
    for m in new:
        gossip(m, exclude=msg["src"])
    if new:
        flush()
    node.reply(msg, {"type": "gossip_ok"})


@node.on("read")
def read(msg):
    node.reply(msg, {"type": "read_ok", "messages": sorted(messages)})


@node.every(0.2)
def retry():
    flush()


if __name__ == "__main__":
    node.run()
