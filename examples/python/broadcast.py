#!/usr/bin/env python3
"""Broadcast node: gossips messages along the topology with retries, so
broadcasts survive partitions. The role of the reference's
demo/ruby/broadcast.rb (retry loop) for the broadcast workload."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
messages = set()
neighbors = []
# pending[(dest, msg)] until acked
pending = set()


@node.on("topology")
def topology(msg):
    global neighbors
    neighbors = msg["body"]["topology"].get(node.node_id, [])
    node.log(f"topology: neighbors = {neighbors}")
    node.reply(msg, {"type": "topology_ok"})


def gossip(m, exclude):
    for nbr in neighbors:
        if nbr == exclude:
            continue
        pending.add((nbr, m))


@node.on("broadcast")
def broadcast(msg):
    m = msg["body"]["message"]
    if m not in messages:
        messages.add(m)
        gossip(m, exclude=msg["src"])
    node.reply(msg, {"type": "broadcast_ok"})


@node.on("gossip")
def handle_gossip(msg):
    m = msg["body"]["message"]
    if m not in messages:
        messages.add(m)
        gossip(m, exclude=msg["src"])
    node.reply(msg, {"type": "gossip_ok"})


@node.on("read")
def read(msg):
    node.reply(msg, {"type": "read_ok", "messages": sorted(messages)})


@node.every(0.2)
def retry():
    # re-send every unacked gossip; acks prune the pending set
    for dest, m in list(pending):
        def on_ack(reply, key=(dest, m)):
            pending.discard(key)
        node.rpc(dest, {"type": "gossip", "message": m}, on_ack)


if __name__ == "__main__":
    node.run()
