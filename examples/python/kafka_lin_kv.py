#!/usr/bin/env python3
"""Multi-node kafka-style log over the built-in lin-kv service.

Each key's log lives under ``log-<k>`` in lin-kv; appends are CAS retry
loops, so offsets are consistent across nodes. Committed offsets live
under ``commit-<k>`` with monotonic CAS. Linearizable storage makes the
whole thing trivially free of lost/reordered writes — the multi-node
counterpart of kafka_single.py (the role of the reference's
demo/clojure/kafka.clj).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
kv = KV(node, KV.LIN, timeout=2.0)


def log_key(k):
    return f"log-{k}"


def register_key(k):
    """Track the known key set so polls can discover keys a client has
    never read (CAS retry on a shared registry key)."""
    while True:
        cur = kv.read("all-keys", default=None)
        if cur is not None and k in cur:
            return
        new = sorted(set(cur or []) | {k})
        try:
            if cur is None:
                kv.cas("all-keys", None, new, create_if_not_exists=True)
            else:
                kv.cas("all-keys", cur, new)
            return
        except RPCError as e:
            if e.code not in (20, 22):
                raise


@node.on("send")
def send(msg):
    k = msg["body"]["key"]
    v = msg["body"]["msg"]
    register_key(k)
    while True:
        cur = kv.read(log_key(k), default=None)
        new = (cur or []) + [v]
        try:
            if cur is None:
                kv.cas(log_key(k), None, new, create_if_not_exists=True)
            else:
                kv.cas(log_key(k), cur, new)
            break
        except RPCError as e:
            if e.code not in (20, 22):
                raise
    node.reply(msg, {"type": "send_ok", "offset": len(new) - 1})


@node.on("poll")
def poll(msg):
    offsets = msg["body"].get("offsets") or {}
    out = {}
    for k in kv.read("all-keys", default=[]):
        start = offsets.get(k, 0)
        log = kv.read(log_key(k), default=[])
        msgs = [[i, v] for i, v in
                enumerate(log[start:start + 16], start)]
        if msgs:
            out[k] = msgs
    node.reply(msg, {"type": "poll_ok", "msgs": out})


@node.on("commit_offsets")
def commit_offsets(msg):
    for k, off in (msg["body"].get("offsets") or {}).items():
        ck = f"commit-{k}"
        while True:
            cur = kv.read(ck, default=None)
            if cur is not None and cur >= off:
                break
            try:
                if cur is None:
                    kv.cas(ck, None, off, create_if_not_exists=True)
                else:
                    kv.cas(ck, cur, off)
                break
            except RPCError as e:
                if e.code not in (20, 22):
                    raise
    node.reply(msg, {"type": "commit_offsets_ok"})


@node.on("list_committed_offsets")
def list_committed_offsets(msg):
    out = {}
    for k in msg["body"].get("keys") or []:
        v = kv.read(f"commit-{k}", default=None)
        if v is not None:
            out[k] = v
    node.reply(msg, {"type": "list_committed_offsets_ok", "offsets": out})


if __name__ == "__main__":
    node.run()
