#!/usr/bin/env python3
"""Multi-node kafka-style log over the built-in lin-kv service.

Each key's log lives under ``log-<k>`` in lin-kv; appends are CAS retry
loops, so offsets are consistent across nodes. Committed offsets live
under ``commit-<k>`` with monotonic CAS. Linearizable storage makes the
whole thing trivially free of lost/reordered writes — the multi-node
counterpart of kafka_single.py (the role of the reference's
demo/clojure/kafka.clj).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
kv = KV(node, KV.LIN, timeout=2.0)


def cas_update(key, update, done=lambda cur: False):
    """Linearizable read-modify-write retry loop: read key, stop if
    ``done(cur)``, else CAS to ``update(cur)`` (creating if missing).
    Returns the new value."""
    while True:
        cur = kv.read(key, default=None)
        if done(cur):
            return cur
        new = update(cur)
        try:
            if cur is None:
                kv.cas(key, None, new, create_if_not_exists=True)
            else:
                kv.cas(key, cur, new)
            return new
        except RPCError as e:
            if e.code not in (20, 22):
                raise


def log_key(k):
    return f"log-{k}"


# keys this node already registered (registry entries are never removed,
# so a local hit skips a linearizable round trip on the send hot path)
registered = set()


def register_key(k):
    if k in registered:
        return
    cas_update("all-keys",
               update=lambda cur: sorted(set(cur or []) | {k}),
               done=lambda cur: cur is not None and k in cur)
    registered.add(k)


@node.on("send")
def send(msg):
    k = msg["body"]["key"]
    v = msg["body"]["msg"]
    register_key(k)
    new = cas_update(log_key(k), update=lambda cur: (cur or []) + [v])
    node.reply(msg, {"type": "send_ok", "offset": len(new) - 1})


@node.on("poll")
def poll(msg):
    offsets = msg["body"].get("offsets") or {}
    out = {}
    for k in kv.read("all-keys", default=[]):
        start = offsets.get(k, 0)
        log = kv.read(log_key(k), default=[])
        msgs = [[i, v] for i, v in
                enumerate(log[start:start + 16], start)]
        if msgs:
            out[k] = msgs
    node.reply(msg, {"type": "poll_ok", "msgs": out})


@node.on("commit_offsets")
def commit_offsets(msg):
    for k, off in (msg["body"].get("offsets") or {}).items():
        cas_update(f"commit-{k}",
                   update=lambda cur, off=off: off,
                   done=lambda cur, off=off: (cur is not None
                                              and cur >= off))
    node.reply(msg, {"type": "commit_offsets_ok"})


@node.on("list_committed_offsets")
def list_committed_offsets(msg):
    out = {}
    for k in msg["body"].get("keys") or []:
        v = kv.read(f"commit-{k}", default=None)
        if v is not None:
            out[k] = v
    node.reply(msg, {"type": "list_committed_offsets_ok", "offsets": out})


if __name__ == "__main__":
    node.run()
