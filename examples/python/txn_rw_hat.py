#!/usr/bin/env python3
"""Highly Available Transactions over read-write registers.

An adaptation of Bailis et al.'s HAT design (the reference's teaching
variant demo/clojure/txn_rw_register_hat.clj:1-171, used here as the
behavioral spec): every node executes transactions IMMEDIATELY against
its local state — no coordination, total availability, even under full
partitions — and asynchronously anti-entropies them to its peers.

- Each transaction gets a globally unique timestamp ``[lamport, node]``.
- Writes install ``(ts, value)`` per key, last-writer-wins by timestamp,
  so replicas converge to the same versions regardless of arrival order.
- An anti-entropy timer re-sends unacked transactions to the peers that
  still need them; ``replicate_ack`` clears them. Re-delivery is safe:
  applying a timestamped txn twice is idempotent under LWW.

The teaching point (why this sits in the demo matrix next to the
serializable transactors): total availability costs isolation. Per-key
LWW makes the write order acyclic — ``read-uncommitted`` (G0) passes —
but nothing orders reads with writes across keys, so long-fork /
fractured-read shapes appear and ``serializable`` checking rightly
fails it. Compare doc/05-txn chapter.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()

lamport = 0          # this node's Lamport clock
kv = {}              # key -> (ts, value); ts = (lamport, node_id)
unreplicated = {}    # ts -> {"ts":, "txn":, "nodes": set of peer ids}


def apply_txn(txn, ts=None):
    """Apply a txn at a timestamp (assigning one if None) against the
    local state; returns (ts, completed txn). Caller holds node.lock
    (the SDK serializes handlers)."""
    global lamport
    if ts is None:
        ts = (lamport, node.node_id)
        lamport += 1
    else:
        ts = tuple(ts)
        lamport = max(lamport, ts[0] + 1)
    out = []
    for f, k, v in txn:
        k = str(k)
        kk = int(k) if k.isdigit() else k
        if f == "r":
            cur = kv.get(k)
            out.append(["r", kk, cur[1] if cur else None])
        else:  # "w"
            cur = kv.get(k)
            if cur is None or cur[0] < ts:
                kv[k] = (ts, v)       # LWW install
            out.append(["w", kk, v])
    return ts, out


@node.on("txn")
def txn(msg):
    ts, out = apply_txn(msg["body"]["txn"])
    peers = set(node.other_node_ids())
    if peers:
        unreplicated[ts] = {"ts": list(ts), "txn": out, "nodes": peers}
    node.reply(msg, {"type": "txn_ok", "txn": out})


@node.on("replicate")
def replicate(msg):
    acked = []
    for t in msg["body"]["txns"]:
        ts = tuple(t["ts"])
        apply_txn(t["txn"], ts)
        acked.append(list(ts))
        # help forward to peers the sender still lists (minus ourselves)
        nodes = set(t["nodes"]) - {node.node_id}
        if nodes and ts not in unreplicated:
            unreplicated[ts] = {"ts": list(ts), "txn": t["txn"],
                                "nodes": nodes}
    # fire-and-forget: no reply — the ack broadcast below is what clears
    # pending sets on every holder (incl. the original sender)
    for peer in node.other_node_ids():
        node.send(peer, {"type": "replicate_ack",
                         "node": node.node_id, "tss": acked})


@node.on("replicate_ack")
def replicate_ack(msg):
    who = msg["body"]["node"]
    for ts in map(tuple, msg["body"]["tss"]):
        ent = unreplicated.get(ts)
        if ent is None:
            continue
        ent["nodes"].discard(who)
        if not ent["nodes"]:
            del unreplicated[ts]


@node.every(0.1)
def anti_entropy():
    # the SDK's timer loop already holds node.lock here
    if not unreplicated:
        return
    # pick the first pending peer, send it everything it's missing
    peer = next(iter(next(iter(unreplicated.values()))["nodes"]))
    txns = [{"ts": e["ts"], "txn": e["txn"], "nodes": sorted(e["nodes"])}
            for e in unreplicated.values() if peer in e["nodes"]]
    if txns:
        node.send(peer, {"type": "replicate", "txns": txns})


node.run()
