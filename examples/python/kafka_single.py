#!/usr/bin/env python3
"""Single-node kafka-style log server: per-key append-only logs with
offsets, client poll positions supplied by the client, committed offsets.
The role of the reference's demo/clojure/kafka_single_node.clj."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
logs = {}        # key -> list of values (offset = index)
committed = {}   # key -> offset


@node.on("send")
def send(msg):
    k = msg["body"]["key"]
    log = logs.setdefault(k, [])
    log.append(msg["body"]["msg"])
    node.reply(msg, {"type": "send_ok", "offset": len(log) - 1})


@node.on("poll")
def poll(msg):
    offsets = msg["body"].get("offsets") or {}
    out = {}
    for k, log in logs.items():
        start = offsets.get(k, 0)
        msgs = [[i, v] for i, v in enumerate(log[start:start + 16], start)]
        if msgs:
            out[k] = msgs
    node.reply(msg, {"type": "poll_ok", "msgs": out})


@node.on("commit_offsets")
def commit_offsets(msg):
    for k, off in (msg["body"].get("offsets") or {}).items():
        committed[k] = max(committed.get(k, -1), off)
    node.reply(msg, {"type": "commit_offsets_ok"})


@node.on("list_committed_offsets")
def list_committed_offsets(msg):
    keys = msg["body"].get("keys") or []
    node.reply(msg, {"type": "list_committed_offsets_ok",
                     "offsets": {k: committed[k] for k in keys
                                 if k in committed}})


if __name__ == "__main__":
    node.run()
