#!/usr/bin/env python3
"""G-set CRDT node: a grow-only set, periodically gossiping the full state
to all peers; merge = set union. The role of the reference's
demo/ruby/g_set.rb / demo/js/crdt_gset.js."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
elements = set()


@node.on("add")
def add(msg):
    elements.add(msg["body"]["element"])
    node.reply(msg, {"type": "add_ok"})


@node.on("read")
def read(msg):
    node.reply(msg, {"type": "read_ok", "value": sorted(elements)})


@node.on("replicate")
def replicate(msg):
    elements.update(msg["body"]["value"])


@node.every(0.2)
def gossip():
    for peer in node.other_node_ids():
        node.send(peer, {"type": "replicate", "value": sorted(elements)})


if __name__ == "__main__":
    node.run()
