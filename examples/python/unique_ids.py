#!/usr/bin/env python3
"""Flake-style unique-ID node: ids are [node_id, counter], unique without
coordination. The role of the reference's demo/clojure/flake_ids.clj."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
counter = 0


@node.on("generate")
def generate(msg):
    global counter
    counter += 1
    node.reply(msg, {"type": "generate_ok",
                     "id": [node.node_id, counter]})


if __name__ == "__main__":
    node.run()
