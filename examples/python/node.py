#!/usr/bin/env python3
"""A minimal Python node SDK for writing workload nodes.

Speaks the newline-delimited JSON protocol over STDIN/STDOUT, logs to
STDERR. Provides: handler registration per message type, automatic ``init``
handling, reply helpers, async RPC with callbacks/futures, periodic tasks,
and a client for the built-in KV services (lin-kv / seq-kv / lww-kv).

This fills the role of the reference's demo node libraries
(demo/python/maelstrom.py, demo/ruby/node.rb, demo/go/node.go +
demo/go/kv.go) with a thread-based design: one reader thread dispatches each
message to a worker thread; timers run on daemon threads.
"""

from __future__ import annotations

import json
import sys
import threading
import time


class RPCError(Exception):
    def __init__(self, code, text):
        self.code = code
        self.text = text
        super().__init__(f"RPC error {code}: {text}")

    @classmethod
    def timeout(cls, text="timed out"):
        return cls(0, text)

    @classmethod
    def not_supported(cls, text):
        return cls(10, text)

    @classmethod
    def temporarily_unavailable(cls, text):
        return cls(11, text)

    @classmethod
    def abort(cls, text):
        return cls(14, text)

    @classmethod
    def key_does_not_exist(cls, text):
        return cls(20, text)

    @classmethod
    def precondition_failed(cls, text):
        return cls(22, text)

    @classmethod
    def txn_conflict(cls, text):
        return cls(30, text)

    def to_body(self):
        return {"type": "error", "code": self.code, "text": self.text}


class Node:
    def __init__(self):
        self.node_id = None
        self.node_ids = []
        self.handlers = {}          # type -> fn(msg)
        self.callbacks = {}         # msg_id -> fn(body)
        self._next_msg_id = 0
        # lock ordering: `lock` serializes handler execution and is held
        # while a handler runs; callbacks + stdout use their own small
        # locks so the reply path never needs `lock` (otherwise a handler
        # blocking in sync_rpc would deadlock the reply dispatch).
        self.lock = threading.RLock()
        self._cb_lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._timers = []

        self.init_callbacks = []    # run after init, before init_ok

        def handle_init(msg):
            body = msg["body"]
            self.node_id = body["node_id"]
            self.node_ids = body["node_ids"]
            self.log(f"node {self.node_id} initialized")
            for fn in self.init_callbacks:
                fn()
            self.reply(msg, {"type": "init_ok"})
            for interval, fn in self._timers:
                t = threading.Thread(target=self._timer_loop,
                                     args=(interval, fn), daemon=True)
                t.start()

        self.handlers["init"] = handle_init

    # --- plumbing ---------------------------------------------------------

    def log(self, *args):
        print(*args, file=sys.stderr, flush=True)

    def send(self, dest, body):
        with self._io_lock:
            msg = {"src": self.node_id, "dest": dest, "body": body}
            print(json.dumps(msg), flush=True)

    def reply(self, req, body):
        body = dict(body)
        body["in_reply_to"] = req["body"]["msg_id"]
        self.send(req["src"], body)

    def reply_error(self, req, err: RPCError):
        self.reply(req, err.to_body())

    def new_msg_id(self):
        with self._cb_lock:
            self._next_msg_id += 1
            return self._next_msg_id

    def rpc(self, dest, body, callback, timeout=10.0):
        """Async RPC: callback(body) is invoked with the reply body on a
        dispatch thread WITHOUT the node lock held; callbacks that touch
        node state should take ``node.lock`` themselves. Callbacks whose
        reply never arrives (lost messages, partitions) are dropped after
        ``timeout`` seconds — otherwise every heartbeat into a partition
        would leak an entry forever."""
        msg_id = self.new_msg_id()
        now = time.monotonic()
        with self._cb_lock:
            self.callbacks[msg_id] = (callback, now + timeout)
            if len(self.callbacks) > 512:
                self.callbacks = {m: (cb, dl) for m, (cb, dl)
                                  in self.callbacks.items() if dl > now}
        body = dict(body)
        body["msg_id"] = msg_id
        self.send(dest, body)
        return msg_id

    def sync_rpc(self, dest, body, timeout=1.0):
        """Blocking RPC; raises RPCError on error reply or timeout."""
        event = threading.Event()
        result = {}

        def cb(reply):
            result["body"] = reply
            event.set()

        self.rpc(dest, body, cb)
        if not event.wait(timeout):
            raise RPCError.timeout(f"RPC to {dest} timed out")
        reply = result["body"]
        if reply.get("type") == "error":
            raise RPCError(reply.get("code", 13), reply.get("text", ""))
        return reply

    # --- API --------------------------------------------------------------

    def on(self, type_, fn=None):
        """Register a handler: decorator form ``@node.on("echo")`` or
        direct form ``node.on("echo", handler)``."""
        def register(f):
            self.handlers[type_] = f
            return f
        if fn is not None:
            return register(fn)
        return register

    def every(self, interval_s):
        """Decorator: run fn periodically once initialized."""
        def register(fn):
            self._timers.append((interval_s, fn))
            return fn
        return register

    def _timer_loop(self, interval, fn):
        while True:
            time.sleep(interval)
            try:
                with self.lock:
                    fn()
            except Exception as e:
                self.log(f"timer error: {e!r}")

    def other_node_ids(self):
        return [n for n in self.node_ids if n != self.node_id]

    def _dispatch(self, msg):
        body = msg["body"]
        in_reply_to = body.get("in_reply_to")
        if in_reply_to is not None:
            with self._cb_lock:
                entry = self.callbacks.pop(in_reply_to, None)
            if entry is not None:
                try:
                    entry[0](body)
                except Exception as e:
                    self.log(f"callback error: {e!r}")
            return
        handler = self.handlers.get(body.get("type"))
        if handler is None:
            self.reply_error(msg, RPCError.not_supported(
                f"no handler for {body.get('type')!r}"))
            return
        try:
            with self.lock:
                handler(msg)
        except RPCError as e:
            self.reply_error(msg, e)
        except Exception as e:
            self.log(f"handler error: {e!r}")
            self.reply_error(msg, RPCError(13, repr(e)))

    def run(self):
        """Main loop: one thread per incoming message keeps slow handlers
        from blocking the pipe; the node lock serializes state access.
        On stdin EOF, in-flight handlers get a brief grace period so
        their replies still reach stdout before the process exits."""
        threads = []
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            t = threading.Thread(target=self._dispatch, args=(msg,),
                                 daemon=True)
            t.start()
            threads.append(t)
            if len(threads) > 128:
                threads = [t for t in threads if t.is_alive()]
        # shared deadline: total grace is ~1s, not 1s per thread
        deadline = time.monotonic() + 1.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))


class KV:
    """Client for the built-in KV services, like demo/go/kv.go."""

    LIN = "lin-kv"
    SEQ = "seq-kv"
    LWW = "lww-kv"

    def __init__(self, node: Node, service: str = "lin-kv",
                 timeout: float = 1.0):
        self.node = node
        self.service = service
        self.timeout = timeout

    def read(self, key, default=KeyError):
        try:
            return self.node.sync_rpc(
                self.service, {"type": "read", "key": key},
                self.timeout)["value"]
        except RPCError as e:
            if e.code == 20 and default is not KeyError:
                return default
            raise

    def write(self, key, value):
        self.node.sync_rpc(self.service,
                           {"type": "write", "key": key, "value": value},
                           self.timeout)

    def cas(self, key, frm, to, create_if_not_exists=False):
        self.node.sync_rpc(
            self.service,
            {"type": "cas", "key": key, "from": frm, "to": to,
             "create_if_not_exists": create_if_not_exists},
            self.timeout)
