#!/usr/bin/env python3
"""Transactional kafka-style log: a single-root transactor over lin-kv.

The whole broker state — every key's log plus committed offsets — lives
as ONE value under the ``root`` key in the lin-kv service. A ``txn`` RPC
applies its entire mop batch to a copy and installs it with a single
root CAS, so either every send in the transaction becomes visible or
none does (the atomicity jepsen.tests.kafka's ``:txn?`` mode exists to
test — reference src/maelstrom/workload/kafka.clj:1-71). Polls inside a
txn read from the same snapshot the sends commit against. The plain
send/poll/commit RPCs route through the same root, so the node also
serves non-txn workloads.

Contention note: a single root serializes all writers (CAS retry loops)
— the deliberate trade for atomicity without a lock service; compare
datomic_list_append.py's hash-tree pages for the scalable variant.

``--no-atomic`` is the bug-injection mutant: each send in a txn is
installed with its OWN root CAS, and a multi-send txn then *aborts*
(error 30, definite) after its sends are already durable. The checker's
aborted-read anomaly (a poll observing a value whose send definitively
failed) catches it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

NO_ATOMIC = "--no-atomic" in sys.argv

node = Node()
kv = KV(node, KV.LIN, timeout=2.0)

ROOT = "root"
POLL_LIMIT = 16


def read_root():
    return kv.read(ROOT, default=None) or {"logs": {}, "commits": {}}


def cas_root(cur_raw, new):
    """Install ``new`` over the exact raw value read (None = absent).
    Returns True on success, False on conflict."""
    try:
        if cur_raw is None:
            kv.cas(ROOT, None, new, create_if_not_exists=True)
        else:
            kv.cas(ROOT, cur_raw, new)
        return True
    except RPCError as e:
        if e.code in (20, 22):
            return False
        raise


def with_root_retry(update):
    """Linearizable read-modify-write loop on the root. ``update(root)``
    returns (new_root_or_None, reply_payload); None = read-only."""
    while True:
        cur_raw = kv.read(ROOT, default=None)
        root = cur_raw or {"logs": {}, "commits": {}}
        new, payload = update(root)
        if new is None or cas_root(cur_raw, new):
            return payload


def apply_mops(root, mops):
    """Apply a txn's mops to (a copy of) root; returns
    (new_root, completed_mops, mutated?). Successive polls within one
    transaction consume FORWARD — the second poll resumes after what the
    first returned, like a client issuing them back-to-back — so a
    multi-poll txn never re-reads offsets (which the checker would flag
    as an external-nonmonotonic position jump)."""
    import copy
    # read-only batches (polls) work straight off root — a deepcopy of
    # every log ever sent on every poll would grow linearly with run
    # length
    mutated = any(m[0] == "send" for m in mops)
    new = copy.deepcopy(root) if mutated else root
    done = []
    next_pos = {}
    for mop in mops:
        if mop[0] == "send":
            _, k, v = mop
            log = new["logs"].setdefault(k, [])
            log.append(v)
            done.append(["send", k, [len(log) - 1, v]])
        else:  # ["poll", {key: from_offset}]
            offsets = mop[1] if len(mop) > 1 and mop[1] else {}
            out = {}
            for k, log in new["logs"].items():
                start = max(offsets.get(k, 0), next_pos.get(k, 0))
                msgs = [[i, v] for i, v in
                        enumerate(log[start:start + POLL_LIMIT], start)]
                if msgs:
                    out[k] = msgs
                    next_pos[k] = msgs[-1][0] + 1
            done.append(["poll", out])
    return new, done, mutated


@node.on("txn")
def txn(msg):
    mops = msg["body"]["txn"]
    if NO_ATOMIC:
        # MUTANT: per-send root CASes (each visible the moment it lands),
        # then a definite abort if the txn had more than one send — a
        # transactor that fails without rolling back its partial work
        n_sends = 0
        done = []
        for mop in mops:
            if mop[0] == "send":
                n_sends += 1
                _, k, v = mop

                def upd(root, k=k, v=v):
                    new, d, _ = apply_mops(root, [["send", k, v]])
                    return new, d[0]
                done.append(with_root_retry(upd))
            else:
                def upd(root, mop=mop):
                    _, d, _ = apply_mops(root, [mop])
                    return None, d[0]
                done.append(with_root_retry(upd))
        if n_sends >= 2:
            node.reply_error(msg, RPCError(30, "txn aborted (conflict)"))
            return
        node.reply(msg, {"type": "txn_ok", "txn": done})
        return

    def upd(root):
        new, done, mutated = apply_mops(root, mops)
        return (new if mutated else None), done

    done = with_root_retry(upd)
    node.reply(msg, {"type": "txn_ok", "txn": done})


@node.on("send")
def send(msg):
    k, v = msg["body"]["key"], msg["body"]["msg"]

    def upd(root):
        new, done, _ = apply_mops(root, [["send", k, v]])
        return new, done[0][2][0]
    off = with_root_retry(upd)
    node.reply(msg, {"type": "send_ok", "offset": off})


@node.on("poll")
def poll(msg):
    offsets = msg["body"].get("offsets") or {}
    root = read_root()
    _, done, _ = apply_mops(root, [["poll", offsets]])
    node.reply(msg, {"type": "poll_ok", "msgs": done[0][1]})


@node.on("commit_offsets")
def commit_offsets(msg):
    req = msg["body"].get("offsets") or {}

    def upd(root):
        commits = dict(root["commits"])
        changed = False
        for k, off in req.items():
            if commits.get(k, -1) < off:
                commits[k] = off
                changed = True
        if not changed:
            return None, None
        return {**root, "commits": commits}, None
    with_root_retry(upd)
    node.reply(msg, {"type": "commit_offsets_ok"})


@node.on("list_committed_offsets")
def list_committed_offsets(msg):
    root = read_root()
    out = {k: root["commits"][k]
           for k in msg["body"].get("keys") or []
           if k in root["commits"]}
    node.reply(msg, {"type": "list_committed_offsets_ok", "offsets": out})


if __name__ == "__main__":
    node.run()
