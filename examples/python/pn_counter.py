#!/usr/bin/env python3
"""PN-counter CRDT node: one [plus, minus] pair per node, gossiped and
merged pointwise-max; value = sum(plus) - sum(minus). The role of the
reference's demo/ruby/pn_counter.rb / demo/js/crdt_pn_counter.js."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
# node_id -> [plus, minus]
counters = {}


def merge(other):
    for n, (p, m) in other.items():
        cp, cm = counters.get(n, (0, 0))
        counters[n] = [max(cp, p), max(cm, m)]


@node.on("add")
def add(msg):
    delta = msg["body"]["delta"]
    p, m = counters.setdefault(node.node_id, [0, 0])
    if delta >= 0:
        counters[node.node_id] = [p + delta, m]
    else:
        counters[node.node_id] = [p, m - delta]
    node.reply(msg, {"type": "add_ok"})


@node.on("read")
def read(msg):
    value = sum(p for p, _ in counters.values()) - \
        sum(m for _, m in counters.values())
    node.reply(msg, {"type": "read_ok", "value": value})


@node.on("replicate")
def replicate(msg):
    merge({n: tuple(v) for n, v in msg["body"]["value"].items()})


@node.every(0.2)
def gossip():
    for peer in node.other_node_ids():
        node.send(peer, {"type": "replicate", "value": counters})


if __name__ == "__main__":
    node.run()
