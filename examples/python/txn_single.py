#!/usr/bin/env python3
"""Single-node transactional store: executes txn micro-ops (r / w /
append) atomically against local state. Trivially strict-serializable
with one node. The role of the reference's demo/clojure/single_key_txn /
datomic walk-up starting point."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()
store = {}


@node.on("txn")
def txn(msg):
    ops = msg["body"]["txn"]
    out = []
    for f, k, v in ops:
        k = str(k)
        if f == "r":
            out.append(["r", int(k) if k.isdigit() else k, store.get(k)])
        elif f == "append":
            store.setdefault(k, []).append(v)
            out.append(["append", int(k) if k.isdigit() else k, v])
        elif f == "w":
            store[k] = v
            out.append(["w", int(k) if k.isdigit() else k, v])
        else:
            raise ValueError(f"unknown micro-op {f!r}")
    node.reply(msg, {"type": "txn_ok", "txn": out})


if __name__ == "__main__":
    node.run()
