#!/usr/bin/env python3
"""A single-node, completely UN-isolated rw-register transaction system.

The reference's teaching foil (demo/clojure/
txn_rw_register_no_isolation.clj:1-35, used as the behavioral spec):
micro-ops apply directly to shared state with a deliberate sleep between
each one, so concurrent transactions interleave mid-flight. Useful for
demonstrating safety violations — the Elle rw-register checker flags
the resulting intermediate/fractured reads (G1b and friends) even on
one node with zero network faults, which is the whole lesson: isolation
is a property of the *transaction system*, not of the network being
healthy. tests/test_e2e_process.py asserts the checker catches it.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node, RPCError  # noqa: E402

node = Node()
state = {}

# Handlers normally run under node.lock; this node *deliberately*
# releases it around each micro-op so transactions interleave.


@node.on("txn")
def txn(msg):
    out = []
    for f, k, v in msg["body"]["txn"]:
        k = str(k)
        kk = int(k) if k.isdigit() else k
        node.lock.release()
        time.sleep(0.002)            # widen the interleaving window
        node.lock.acquire()
        if f == "r":
            out.append(["r", kk, state.get(k)])
        elif f == "w":
            state[k] = v
            out.append(["w", kk, v])
        else:
            raise RPCError(12, f"unknown micro-op {f!r}")
    node.reply(msg, {"type": "txn_ok", "txn": out})


node.run()
