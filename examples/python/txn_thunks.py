#!/usr/bin/env python3
"""Multi-key list-append transactor: per-key immutable thunks + root CAS.

The teaching midpoint between ``datomic_txn.py`` (whole database behind
ONE lin-kv value — simple, but every transaction conflicts with every
other) and ``datomic_list_append.py`` (persistent hash-tree pages).
Design follows the reference's demo/clojure/multi_key_txn.clj:1-307
(used as the behavioral spec):

- the ROOT, stored in lin-kv, is just a map ``key -> thunk id``
- each thunk is an IMMUTABLE value stored once in lww-kv under a fresh
  globally unique id (``<node>-<counter>``); immutability is what makes
  the eventually-consistent lww-kv service safe to read from — any copy
  a replica returns is the right one, and thunks can be cached forever
- a transaction reads the root, loads thunks for its read-set, applies
  its micro-ops, writes fresh thunks for its write-set, then CASes the
  root. Only the root CAS can conflict, and only on a real data race —
  transactions touching disjoint keys still conflict on the shared root
  map (the limitation the hash-tree transactor removes), but thunk
  writes themselves never do.
- a CAS mismatch aborts with error 30 (txn-conflict, definite — the
  client may retry safely since nothing observable happened: the
  orphaned thunks are garbage, not corruption).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import KV, Node, RPCError  # noqa: E402

node = Node()
root_kv = KV(node, KV.LIN, timeout=2.0)
thunk_kv = KV(node, KV.LWW, timeout=2.0)

ROOT = "thunks-root"

thunk_cache = {}     # thunk id -> value (immutable, so cache freely)
next_thunk = [0]


def new_thunk_id():
    next_thunk[0] += 1
    return f"{node.node_id}-{next_thunk[0]}"


def load_thunk(tid):
    if tid is None:
        return None
    if tid not in thunk_cache:
        # lww-kv is eventually consistent, but thunks are write-once:
        # retry until the replica that answers has seen the write
        for _ in range(20):
            try:
                thunk_cache[tid] = thunk_kv.read(tid)
                break
            except RPCError as e:
                if e.code != 20:
                    raise
        else:
            raise RPCError.txn_conflict(f"thunk {tid} never appeared")
    return thunk_cache[tid]


@node.on("txn")
def txn(msg):
    ops = msg["body"]["txn"]
    root = root_kv.read(ROOT, default=None) or {}
    new_root = dict(root)
    out = []
    dirty = {}                       # key -> new value (pending thunks)
    for f, k, v in ops:
        k = str(k)
        kk = int(k) if k.isdigit() else k
        if f == "r":
            val = (dirty[k] if k in dirty
                   else load_thunk(new_root.get(k)))
            out.append(["r", kk, val])
        elif f == "append":
            cur = (dirty[k] if k in dirty
                   else load_thunk(new_root.get(k))) or []
            dirty[k] = list(cur) + [v]
            out.append(["append", kk, v])
        else:
            raise RPCError(12, f"unknown micro-op {f!r}")
    if dirty:
        for k, val in dirty.items():
            tid = new_thunk_id()
            thunk_kv.write(tid, val)     # immutable, safe in lww-kv
            thunk_cache[tid] = val
            new_root[k] = tid
        try:
            root_kv.cas(ROOT, root or None, new_root,
                        create_if_not_exists=(not root))
        except RPCError as e:
            if e.code in (20, 22):
                raise RPCError.txn_conflict(
                    "root CAS failed; transaction aborted") from None
            raise
    node.reply(msg, {"type": "txn_ok", "txn": out})


node.run()
