#!/usr/bin/env python3
"""Echo node: replies to echo requests with the same payload.
The role of the reference's demo/python/echo.py."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from node import Node  # noqa: E402

node = Node()


@node.on("echo")
def echo(msg):
    node.reply(msg, {"type": "echo_ok", "echo": msg["body"]["echo"]})


if __name__ == "__main__":
    node.run()
