#!/usr/bin/env ruby
# Broadcast node (workload: broadcast): gossip-on-receive plus timed
# anti-entropy toward topology neighbors so partitions heal.
require_relative "maelstrom"
require "set"

node = Maelstrom::Node.new
lock = Mutex.new
seen = Set.new
neighbors = []

gossip = lambda do |values, except|
  targets = lock.synchronize { neighbors.dup }
  targets.each do |peer|
    next if peer == except
    node.send_msg(peer, { "type" => "gossip", "values" => values })
  end
end

node.on("topology") do |_msg, body|
  mine = (body["topology"] || {})[node.node_id] || []
  lock.synchronize { neighbors = mine }
  { "type" => "topology_ok" }
end

node.on("broadcast") do |_msg, body|
  fresh = lock.synchronize { seen.add?(body["message"]) }
  gossip.call([body["message"]], nil) if fresh
  { "type" => "broadcast_ok" }
end

node.on("gossip") do |msg, body|
  fresh = lock.synchronize do
    (body["values"] || []).select { |v| seen.add?(v) }
  end
  gossip.call(fresh, msg["src"]) unless fresh.empty?
  nil
end

node.on("read") do |_msg, _body|
  { "type" => "read_ok", "messages" => lock.synchronize { seen.to_a } }
end

node.on_init do
  Thread.new do
    loop do
      sleep 0.5
      gossip.call(lock.synchronize { seen.to_a }, nil)
    end
  end
end

node.run
