#!/usr/bin/env ruby
# Grow-only counter over seq-kv (workload: g-counter): CAS-increment a
# per-node key, sum every node's key on read — exercises the KV client
# against the harness's Sequential service.
require_relative "maelstrom"

node = Maelstrom::Node.new
kv = Maelstrom::KV.seq(node)

node.on("add") do |_msg, body|
  key = "counter-#{node.node_id}"
  loop do
    cur = kv.read_default(key, 0)
    begin
      kv.cas(key, cur, cur + body["delta"].to_i, create: true)
      break
    rescue Maelstrom::RPCError => e
      raise unless e.code == Maelstrom::RPCError::PRECONDITION_FAILED
    end
  end
  { "type" => "add_ok" }
end

node.on("read") do |_msg, _body|
  total = node.node_ids.sum { |peer| kv.read_default("counter-#{peer}", 0) }
  { "type" => "read_ok", "value" => total }
end

node.run
