#!/usr/bin/env ruby
# Grow-only set CRDT node (workload: g-set): merge-on-gossip.
require_relative "maelstrom"
require "set"

node = Maelstrom::Node.new
lock = Mutex.new
set = Set.new

node.on("add") do |_msg, body|
  lock.synchronize { set.add(body["element"]) }
  { "type" => "add_ok" }
end

node.on("read") do |_msg, _body|
  { "type" => "read_ok", "value" => lock.synchronize { set.to_a } }
end

node.on("merge") do |_msg, body|
  lock.synchronize { (body["value"] || []).each { |v| set.add(v) } }
  nil
end

node.on_init do
  Thread.new do
    loop do
      sleep 0.5
      snapshot = lock.synchronize { set.to_a }
      node.node_ids.each do |peer|
        next if peer == node.node_id
        node.send_msg(peer, { "type" => "merge", "value" => snapshot })
      end
    end
  end
end

node.run
