#!/usr/bin/env ruby
# Echo node (workload: echo).
require_relative "maelstrom"

node = Maelstrom::Node.new
node.on("echo") do |_msg, body|
  { "type" => "echo_ok", "echo" => body["echo"] }
end
node.run
