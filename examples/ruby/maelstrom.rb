# Ruby node SDK for the maelstrom_tpu process runtime: JSON envelopes
# {src, dest, body} per line on stdin/stdout, init handshake, handler
# dispatch by body type, request/reply RPC via msg_id / in_reply_to.
#
# Counterpart of the reference's Ruby library (demo/ruby/, what its own
# demo self-test runs — core.clj:104-126), re-designed rather than
# ported: handlers are blocks that RETURN the reply body (nil = no
# reply), raising RPCError sends the matching error reply, and
# synchronous RPC blocks on a ConditionVariable instead of promises.
# Wire-compatible with every other SDK in examples/;
# tests/test_ruby_wire_conformance.py holds this file to the schema
# registry without a Ruby runtime.

require "json"

module Maelstrom
  # Typed error of the harness catalog (core/errors.py).
  class RPCError < StandardError
    attr_reader :code

    TIMEOUT = 0
    NOT_SUPPORTED = 10
    TEMPORARILY_UNAVAILABLE = 11
    CRASH = 13
    KEY_DOES_NOT_EXIST = 20
    PRECONDITION_FAILED = 22
    TXN_CONFLICT = 30

    def initialize(code, text)
      @code = code
      super(text)
    end

    def body
      { "type" => "error", "code" => @code, "text" => message }
    end
  end

  class Node
    attr_reader :node_id, :node_ids

    def initialize(input: $stdin, output: $stdout)
      @in = input
      @out = output
      @lock = Mutex.new          # guards writes + rpc state
      @handlers = {}
      @init_hooks = []
      @pending = {}              # msg_id => reply body (filled by loop)
      @cv = ConditionVariable.new
      @next_msg_id = 0
      @node_id = nil
      @node_ids = []
    end

    # Register a handler: on("echo") { |msg, body| {"type" => "echo_ok"} }
    def on(type, &block)
      raise "duplicate handler for #{type}" if @handlers.key?(type)
      @handlers[type] = block
    end

    def on_init(&block)
      @init_hooks << block
    end

    def send_msg(dest, body)
      @lock.synchronize do
        env = { "src" => @node_id, "dest" => dest, "body" => body }
        @out.puts(JSON.generate(env))
        @out.flush
      end
    end

    def reply(msg, body)
      b = body.dup
      b["in_reply_to"] = msg["body"]["msg_id"]
      send_msg(msg["src"], b)
    end

    # Blocking RPC: returns the reply body, raises RPCError on an error
    # reply or timeout. Callable from handler threads (the main loop
    # routes in_reply_to bodies here).
    def rpc(dest, body, timeout = 5.0)
      id = nil
      @lock.synchronize do
        @next_msg_id += 1
        id = @next_msg_id
        @pending[id] = nil
      end
      send_msg(dest, body.merge("msg_id" => id))
      deadline = Time.now + timeout
      @lock.synchronize do
        while @pending[id].nil?
          remaining = deadline - Time.now
          if remaining <= 0
            @pending.delete(id)
            raise RPCError.new(RPCError::TIMEOUT, "RPC timeout")
          end
          @cv.wait(@lock, remaining)
        end
        reply_body = @pending.delete(id)
        if reply_body["type"] == "error"
          raise RPCError.new(reply_body["code"], reply_body["text"].to_s)
        end
        reply_body
      end
    end

    # Main loop: route replies to waiting RPCs, dispatch requests on
    # worker threads (handlers may themselves block in rpc).
    def run
      threads = []
      @in.each_line do |line|
        line = line.strip
        next if line.empty?
        msg = JSON.parse(line)
        body = msg["body"]
        if body["in_reply_to"]
          @lock.synchronize do
            id = body["in_reply_to"]
            @pending[id] = body if @pending.key?(id)
            @cv.broadcast
          end
          next
        end
        case body["type"]
        when "init"
          @node_id = body["node_id"]
          @node_ids = body["node_ids"] || []
          reply(msg, { "type" => "init_ok" })
          @init_hooks.each(&:call)
        else
          threads.reject! { |th| !th.alive? }   # keep O(in-flight)
          threads << Thread.new { dispatch(msg, body) }
        end
      end
      threads.each(&:join)
    end

    private

    def dispatch(msg, body)
      handler = @handlers[body["type"]]
      unless handler
        reply(msg, RPCError.new(RPCError::NOT_SUPPORTED,
                                "unknown type #{body['type']}").body)
        return
      end
      begin
        out = handler.call(msg, body)
        reply(msg, out) if out
      rescue RPCError => e
        reply(msg, e.body)
      rescue => e
        warn "handler crashed: #{e.class}: #{e.message}"
        reply(msg, RPCError.new(RPCError::CRASH, e.message).body)
      end
    end
  end

  # KV client for the harness services (demo/ruby kv role).
  class KV
    def initialize(node, service)
      @node = node
      @service = service
    end

    def self.lin(node) = new(node, "lin-kv")
    def self.seq(node) = new(node, "seq-kv")
    def self.lww(node) = new(node, "lww-kv")

    def read(key)
      @node.rpc(@service, { "type" => "read", "key" => key })["value"]
    end

    def read_default(key, default)
      read(key)
    rescue RPCError => e
      raise unless e.code == RPCError::KEY_DOES_NOT_EXIST
      default
    end

    def write(key, value)
      @node.rpc(@service,
                { "type" => "write", "key" => key, "value" => value })
      nil
    end

    def cas(key, from, to, create: false)
      @node.rpc(@service,
                { "type" => "cas", "key" => key, "from" => from,
                  "to" => to, "create_if_not_exists" => create })
      nil
    end
  end
end
