// Linearizable KV node in C++: proxies ops to the built-in lin-kv
// service — exercises the SDK's sync_rpc + KV client end-to-end (the
// role of the Rust crate's lin_kv Storage usage, demo/rust/src/bin/
// lin_kv.rs).
#include "maelstrom/node.hpp"

using maelstrom::KV;
using maelstrom::Message;
using maelstrom::Node;
using maelstrom::RPCError;
using maelstrom::Value;

int main() {
  Node node;
  KV kv(node, KV::LIN, 2.0);

  node.on("read", [&](const Message& msg) {
    Value b;
    b["type"] = "read_ok";
    b["value"] = kv.read(msg.body.at("key"));
    node.reply(msg, b);
  });

  node.on("write", [&](const Message& msg) {
    kv.write(msg.body.at("key"), msg.body.at("value"));
    Value b;
    b["type"] = "write_ok";
    node.reply(msg, b);
  });

  node.on("cas", [&](const Message& msg) {
    kv.cas(msg.body.at("key"), msg.body.at("from"), msg.body.at("to"));
    Value b;
    b["type"] = "cas_ok";
    node.reply(msg, b);
  });

  node.run();
  return 0;
}
