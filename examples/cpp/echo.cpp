// Echo node in C++ against the native SDK — the role of the reference's
// demo/c++/echo.cpp.
#include "maelstrom/node.hpp"

using maelstrom::Message;
using maelstrom::Node;
using maelstrom::Value;

int main() {
  Node node;
  node.on("echo", [&](const Message& msg) {
    Value b;
    b["type"] = "echo_ok";
    b["echo"] = msg.body.at("echo");
    node.reply(msg, b);
  });
  node.run();
  return 0;
}
