// PN-counter CRDT node in C++: per-node increment/decrement registers
// merged by pairwise max — the G-Counter pair construction. Periodic
// full-state gossip; reads sum both registers across all nodes.
// Exercises timers, numeric JSON, and nested-object merge in the native
// SDK (the role of the reference's pn_counter demo nodes).
#include <map>
#include <string>

#include "maelstrom/node.hpp"

using maelstrom::Message;
using maelstrom::Node;
using maelstrom::Value;

int main() {
  Node node;
  // node id -> accumulated positive / negative increments
  std::map<std::string, long> inc, dec;

  auto merge = [&](const Value& v, std::map<std::string, long>& into) {
    for (const auto& [k, amt] : v.as_object()) {
      long a = (long)amt.as_int();
      if (!into.count(k) || a > into[k]) into[k] = a;
    }
  };

  auto dump = [&](const std::map<std::string, long>& m) {
    Value v = Value(maelstrom::json::Object{});
    for (const auto& [k, a] : m) v[k] = (int64_t)a;
    return v;
  };

  node.on("add", [&](const Message& msg) {
    long delta = (long)msg.body.at("delta").as_int();
    if (delta >= 0)
      inc[node.node_id] += delta;
    else
      dec[node.node_id] += -delta;
    Value b;
    b["type"] = "add_ok";
    node.reply(msg, b);
  });

  node.on("read", [&](const Message& msg) {
    long total = 0;
    for (const auto& [k, a] : inc) total += a;
    for (const auto& [k, a] : dec) total -= a;
    Value b;
    b["type"] = "read_ok";
    b["value"] = (int64_t)total;
    node.reply(msg, b);
  });

  node.on("replicate", [&](const Message& msg) {
    merge(msg.body.at("inc"), inc);
    merge(msg.body.at("dec"), dec);
  });

  node.every(0.2, [&] {
    for (const auto& peer : node.node_ids) {
      if (peer == node.node_id) continue;
      Value b;
      b["type"] = "replicate";
      b["inc"] = dump(inc);
      b["dec"] = dump(dec);
      node.send(peer, b);
    }
  });

  node.run();
}
