// Broadcast node in C++: topology-aware gossip with retries, so
// broadcasts survive partitions (the role of demo/ruby/broadcast.rb's
// retry loop, in the native SDK).
#include <map>
#include <set>
#include <string>
#include <vector>

#include "maelstrom/node.hpp"

using maelstrom::Message;
using maelstrom::Node;
using maelstrom::Value;

int main() {
  Node node;
  std::set<int64_t> messages;
  std::vector<std::string> neighbors;
  // unacked gossip: (peer, message value)
  std::set<std::pair<std::string, int64_t>> pending;

  auto gossip = [&](int64_t m, const std::string& exclude) {
    for (const auto& nbr : neighbors)
      if (nbr != exclude) pending.insert({nbr, m});
  };

  node.on("topology", [&](const Message& msg) {
    neighbors.clear();
    const auto& topo = msg.body.at("topology").as_object();
    auto it = topo.find(node.node_id);
    if (it != topo.end())
      for (const auto& n : it->second.as_array())
        neighbors.push_back(n.as_string());
    Value b;
    b["type"] = "topology_ok";
    node.reply(msg, b);
  });

  auto accept = [&](const Message& msg, const char* ok_type) {
    int64_t m = msg.body.at("message").as_int();
    if (messages.insert(m).second) gossip(m, msg.src);
    Value b;
    b["type"] = ok_type;
    node.reply(msg, b);
  };

  node.on("broadcast",
          [&](const Message& msg) { accept(msg, "broadcast_ok"); });
  node.on("gossip",
          [&](const Message& msg) { accept(msg, "gossip_ok"); });

  node.on("read", [&](const Message& msg) {
    maelstrom::json::Array arr;
    for (int64_t m : messages) arr.push_back(Value(m));
    Value b;
    b["type"] = "read_ok";
    b["messages"] = Value(arr);
    node.reply(msg, b);
  });

  node.every(0.2, [&] {
    // re-send every unacked gossip; an ack erases the pending entry
    std::vector<std::pair<std::string, int64_t>> snapshot(
        pending.begin(), pending.end());
    for (const auto& pm : snapshot) {
      Value b;
      b["type"] = "gossip";
      b["message"] = pm.second;
      node.rpc(pm.first, b, [&node, &pending, pm](const Value&) {
        node.with_lock([&] { pending.erase(pm); });
      });
    }
  });

  node.run();
  return 0;
}
