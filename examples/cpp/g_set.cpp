// G-set CRDT node in C++: grow-only set with periodic full-state gossip
// (merge = union). Exercises timers, inter-node sends, and JSON arrays.
#include <set>

#include "maelstrom/node.hpp"

using maelstrom::Message;
using maelstrom::Node;
using maelstrom::Value;

int main() {
  Node node;
  // elements keyed by serialized form so arbitrary JSON values dedupe
  std::map<std::string, Value> elements;

  auto element_array = [&] {
    maelstrom::json::Array arr;
    for (const auto& [k, v] : elements) arr.push_back(v);
    return Value(arr);
  };

  node.on("add", [&](const Message& msg) {
    Value e = msg.body.at("element");
    elements[e.dump()] = e;
    Value b;
    b["type"] = "add_ok";
    node.reply(msg, b);
  });

  node.on("read", [&](const Message& msg) {
    Value b;
    b["type"] = "read_ok";
    b["value"] = element_array();
    node.reply(msg, b);
  });

  node.on("replicate", [&](const Message& msg) {
    for (const auto& e : msg.body.at("value").as_array())
      elements[e.dump()] = e;
  });

  node.every(0.2, [&] {
    for (const auto& peer : node.node_ids) {
      if (peer == node.node_id) continue;
      Value b;
      b["type"] = "replicate";
      b["value"] = element_array();
      node.send(peer, b);
    }
  });

  node.run();
  return 0;
}
