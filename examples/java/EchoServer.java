// Echo node (workload: echo).
package maelstrom;

import java.util.HashMap;
import java.util.Map;

public final class EchoServer {
    public static void main(String[] args) throws Exception {
        Maelstrom.Node node = new Maelstrom.Node();
        node.handle("echo", (msg, body) -> {
            Map<String, Object> rep = new HashMap<>();
            rep.put("type", "echo_ok");
            rep.put("echo", body.get("echo"));
            return rep;
        });
        node.run();
    }
}
