// Java node SDK for the maelstrom_tpu process runtime: JSON envelopes
// {src, dest, body} per line on stdin/stdout, init handshake, handler
// dispatch by body type, request/reply RPC via msg_id / in_reply_to.
//
// Counterpart of the reference's Java lab (demo/java/lab/Node.java),
// re-designed rather than ported: a single-file SDK with a tiny
// recursive-descent JSON codec (no Jackson/Gson on the classpath),
// handlers RETURN the reply body (null = no reply), RpcException
// becomes an error reply, and synchronous RPC blocks on a
// CompletableFuture with a timeout. Wire-compatible with every other
// SDK in examples/; tests/test_java_wire_conformance.py holds this
// file to the schema registry without a JVM.
package maelstrom;

import java.io.BufferedReader;
import java.io.InputStreamReader;
import java.util.ArrayList;
import java.util.HashMap;
import java.util.List;
import java.util.Map;
import java.util.concurrent.CompletableFuture;
import java.util.concurrent.ConcurrentHashMap;
import java.util.concurrent.ExecutorService;
import java.util.concurrent.Executors;
import java.util.concurrent.TimeUnit;
import java.util.concurrent.TimeoutException;

public final class Maelstrom {

    /** Error catalog codes used by SDK helpers (core/errors.py). */
    public static final int ERR_TIMEOUT = 0;
    public static final int ERR_NOT_SUPPORTED = 10;
    public static final int ERR_TEMPORARILY_UNAVAILABLE = 11;
    public static final int ERR_CRASH = 13;
    public static final int ERR_KEY_DOES_NOT_EXIST = 20;
    public static final int ERR_PRECONDITION_FAILED = 22;
    public static final int ERR_TXN_CONFLICT = 30;

    /** Typed RPC error: thrown by handlers to send an error reply. */
    public static final class RpcException extends Exception {
        public final int code;
        public RpcException(int code, String text) {
            super(text);
            this.code = code;
        }
    }

    /** A handler processes one request body and returns the reply
     *  body (null for no reply). */
    public interface Handler {
        Map<String, Object> handle(Map<String, Object> msg,
                                   Map<String, Object> body)
            throws Exception;
    }

    public static final class Node {
        private final Object writeLock = new Object();
        private final Map<String, Handler> handlers = new HashMap<>();
        private final Map<Long, CompletableFuture<Map<String, Object>>>
            pending = new ConcurrentHashMap<>();
        private final ExecutorService pool =
            Executors.newCachedThreadPool();
        private volatile String nodeId = "";
        private volatile List<String> nodeIds = new ArrayList<>();
        private final java.util.concurrent.atomic.AtomicLong nextMsgId =
            new java.util.concurrent.atomic.AtomicLong();
        private Runnable onInit = null;

        public String id() { return nodeId; }
        public List<String> peers() { return nodeIds; }

        public void handle(String type, Handler h) {
            if (handlers.putIfAbsent(type, h) != null)
                throw new IllegalStateException("duplicate handler " + type);
        }

        public void onInit(Runnable r) { onInit = r; }

        private void writeEnvelope(String dest, Map<String, Object> body) {
            Map<String, Object> env = new HashMap<>();
            env.put("src", nodeId);
            env.put("dest", dest);
            env.put("body", body);
            synchronized (writeLock) {
                System.out.println(Json.write(env));
                System.out.flush();
            }
        }

        public void send(String dest, Map<String, Object> body) {
            writeEnvelope(dest, body);
        }

        @SuppressWarnings("unchecked")
        public void reply(Map<String, Object> req,
                          Map<String, Object> body) {
            Map<String, Object> reqBody =
                (Map<String, Object>) req.get("body");
            Object msgId = reqBody.get("msg_id");
            if (msgId != null) body.put("in_reply_to", msgId);
            writeEnvelope((String) req.get("src"), body);
        }

        /** Blocking RPC with timeout; error replies and timeouts
         *  surface as RpcException. */
        public Map<String, Object> rpc(String dest,
                                       Map<String, Object> body,
                                       long timeoutMillis)
                throws RpcException {
            long id = nextMsgId.incrementAndGet();
            CompletableFuture<Map<String, Object>> fut =
                new CompletableFuture<>();
            pending.put(id, fut);
            body.put("msg_id", id);
            writeEnvelope(dest, body);
            try {
                Map<String, Object> rep =
                    fut.get(timeoutMillis, TimeUnit.MILLISECONDS);
                if ("error".equals(rep.get("type")))
                    throw new RpcException(
                        ((Number) rep.getOrDefault("code", 13)).intValue(),
                        String.valueOf(rep.get("text")));
                return rep;
            } catch (TimeoutException e) {
                throw new RpcException(ERR_TIMEOUT, "RPC timeout");
            } catch (InterruptedException | java.util.concurrent.ExecutionException e) {
                throw new RpcException(ERR_CRASH, e.toString());
            } finally {
                pending.remove(id);
            }
        }

        @SuppressWarnings("unchecked")
        public void run() throws Exception {
            BufferedReader in = new BufferedReader(
                new InputStreamReader(System.in));
            String line;
            while ((line = in.readLine()) != null) {
                if (line.isEmpty()) continue;
                Map<String, Object> msg =
                    (Map<String, Object>) Json.read(line);
                Map<String, Object> body =
                    (Map<String, Object>) msg.get("body");
                Object irt = body.get("in_reply_to");
                if (irt != null) {
                    CompletableFuture<Map<String, Object>> fut =
                        pending.get(((Number) irt).longValue());
                    if (fut != null) fut.complete(body);
                    continue;
                }
                String type = (String) body.get("type");
                if ("init".equals(type)) {
                    nodeId = (String) body.get("node_id");
                    List<String> ids = new ArrayList<>();
                    for (Object o : (List<Object>) body.get("node_ids"))
                        ids.add((String) o);
                    nodeIds = ids;
                    Map<String, Object> ok = new HashMap<>();
                    ok.put("type", "init_ok");
                    reply(msg, ok);
                    if (onInit != null) onInit.run();
                    continue;
                }
                Handler h = handlers.get(type);
                if (h == null) {
                    reply(msg, errorBody(ERR_NOT_SUPPORTED,
                                         "unknown type " + type));
                    continue;
                }
                pool.submit(() -> dispatch(h, msg, body));
            }
            pool.shutdown();
            pool.awaitTermination(5, TimeUnit.SECONDS);
        }

        private void dispatch(Handler h, Map<String, Object> msg,
                              Map<String, Object> body) {
            try {
                Map<String, Object> rep = h.handle(msg, body);
                if (rep != null) reply(msg, rep);
            } catch (RpcException e) {
                reply(msg, errorBody(e.code, e.getMessage()));
            } catch (Exception e) {
                reply(msg, errorBody(ERR_CRASH, e.toString()));
            }
        }

        private static Map<String, Object> errorBody(int code,
                                                     String text) {
            Map<String, Object> b = new HashMap<>();
            b.put("type", "error");
            b.put("code", code);
            b.put("text", text);
            return b;
        }
    }

    /** KV client for the harness services (lin-kv / seq-kv / lww-kv).
     *  The role of demo/go/kv.go on this SDK's blocking surface. */
    public static final class KV {
        private final Node node;
        private final String service;
        public long timeoutMillis = 5000;

        private KV(Node n, String s) { node = n; service = s; }
        public static KV lin(Node n) { return new KV(n, "lin-kv"); }
        public static KV seq(Node n) { return new KV(n, "seq-kv"); }
        public static KV lww(Node n) { return new KV(n, "lww-kv"); }

        public Object read(Object key) throws RpcException {
            Map<String, Object> b = new HashMap<>();
            b.put("type", "read");
            b.put("key", key);
            return node.rpc(service, b, timeoutMillis).get("value");
        }

        public long readLong(Object key, long dflt) throws RpcException {
            try {
                return ((Number) read(key)).longValue();
            } catch (RpcException e) {
                if (e.code == ERR_KEY_DOES_NOT_EXIST) return dflt;
                throw e;
            }
        }

        public void write(Object key, Object value) throws RpcException {
            Map<String, Object> b = new HashMap<>();
            b.put("type", "write");
            b.put("key", key);
            b.put("value", value);
            node.rpc(service, b, timeoutMillis);
        }

        public void cas(Object key, Object from, Object to,
                        boolean createIfNotExists) throws RpcException {
            Map<String, Object> b = new HashMap<>();
            b.put("type", "cas");
            b.put("key", key);
            b.put("from", from);
            b.put("to", to);
            b.put("create_if_not_exists", createIfNotExists);
            node.rpc(service, b, timeoutMillis);
        }
    }

    /** Minimal JSON codec: objects, arrays, strings, longs, doubles,
     *  booleans, null — the wire subset every SDK here speaks. */
    public static final class Json {
        public static String write(Object v) {
            StringBuilder sb = new StringBuilder();
            writeTo(sb, v);
            return sb.toString();
        }

        @SuppressWarnings("unchecked")
        private static void writeTo(StringBuilder sb, Object v) {
            if (v == null) { sb.append("null"); return; }
            if (v instanceof String) { writeString(sb, (String) v); return; }
            if (v instanceof Map) {
                sb.append('{');
                boolean first = true;
                for (Map.Entry<String, Object> e :
                         ((Map<String, Object>) v).entrySet()) {
                    if (!first) sb.append(',');
                    first = false;
                    writeString(sb, e.getKey());
                    sb.append(':');
                    writeTo(sb, e.getValue());
                }
                sb.append('}');
                return;
            }
            if (v instanceof List) {
                sb.append('[');
                boolean first = true;
                for (Object o : (List<Object>) v) {
                    if (!first) sb.append(',');
                    first = false;
                    writeTo(sb, o);
                }
                sb.append(']');
                return;
            }
            sb.append(v);   // Number / Boolean
        }

        private static void writeString(StringBuilder sb, String s) {
            sb.append('"');
            for (int i = 0; i < s.length(); i++) {
                char c = s.charAt(i);
                switch (c) {
                    case '"': sb.append("\\\""); break;
                    case '\\': sb.append("\\\\"); break;
                    case '\n': sb.append("\\n"); break;
                    case '\r': sb.append("\\r"); break;
                    case '\t': sb.append("\\t"); break;
                    default:
                        if (c < 0x20) sb.append(String.format("\\u%04x", (int) c));
                        else sb.append(c);
                }
            }
            sb.append('"');
        }

        public static Object read(String s) {
            int[] pos = {0};
            Object v = readValue(s, pos);
            return v;
        }

        private static void ws(String s, int[] p) {
            while (p[0] < s.length()
                   && Character.isWhitespace(s.charAt(p[0]))) p[0]++;
        }

        private static Object readValue(String s, int[] p) {
            ws(s, p);
            char c = s.charAt(p[0]);
            if (c == '{') return readObject(s, p);
            if (c == '[') return readArray(s, p);
            if (c == '"') return readString(s, p);
            if (s.startsWith("true", p[0])) { p[0] += 4; return Boolean.TRUE; }
            if (s.startsWith("false", p[0])) { p[0] += 5; return Boolean.FALSE; }
            if (s.startsWith("null", p[0])) { p[0] += 4; return null; }
            int start = p[0];
            boolean dbl = false;
            while (p[0] < s.length()
                   && "+-0123456789.eE".indexOf(s.charAt(p[0])) >= 0) {
                char d = s.charAt(p[0]);
                if (d == '.' || d == 'e' || d == 'E') dbl = true;
                p[0]++;
            }
            String num = s.substring(start, p[0]);
            return dbl ? (Object) Double.parseDouble(num)
                       : (Object) Long.parseLong(num);
        }

        private static Map<String, Object> readObject(String s, int[] p) {
            Map<String, Object> m = new HashMap<>();
            p[0]++;  // {
            ws(s, p);
            if (s.charAt(p[0]) == '}') { p[0]++; return m; }
            while (true) {
                ws(s, p);
                String k = readString(s, p);
                ws(s, p);
                p[0]++;  // :
                m.put(k, readValue(s, p));
                ws(s, p);
                char c = s.charAt(p[0]++);
                if (c == '}') return m;
                // else ',' — continue
            }
        }

        private static List<Object> readArray(String s, int[] p) {
            List<Object> l = new ArrayList<>();
            p[0]++;  // [
            ws(s, p);
            if (s.charAt(p[0]) == ']') { p[0]++; return l; }
            while (true) {
                l.add(readValue(s, p));
                ws(s, p);
                char c = s.charAt(p[0]++);
                if (c == ']') return l;
            }
        }

        private static String readString(String s, int[] p) {
            StringBuilder sb = new StringBuilder();
            p[0]++;  // "
            while (true) {
                char c = s.charAt(p[0]++);
                if (c == '"') return sb.toString();
                if (c == '\\') {
                    char e = s.charAt(p[0]++);
                    switch (e) {
                        case 'n': sb.append('\n'); break;
                        case 'r': sb.append('\r'); break;
                        case 't': sb.append('\t'); break;
                        case 'b': sb.append('\b'); break;
                        case 'f': sb.append('\f'); break;
                        case 'u':
                            sb.append((char) Integer.parseInt(
                                s.substring(p[0], p[0] + 4), 16));
                            p[0] += 4;
                            break;
                        default: sb.append(e);
                    }
                } else {
                    sb.append(c);
                }
            }
        }
    }

    private Maelstrom() {}
}
