// Broadcast node (workload: broadcast): gossip-on-receive plus timed
// anti-entropy toward topology neighbors so partitions heal.
package maelstrom;

import java.util.ArrayList;
import java.util.HashMap;
import java.util.HashSet;
import java.util.List;
import java.util.Map;
import java.util.Set;

public final class BroadcastServer {
    public static void main(String[] args) throws Exception {
        Maelstrom.Node node = new Maelstrom.Node();
        Set<Object> seen = new HashSet<>();
        List<String> neighbors = new ArrayList<>();
        Object lock = new Object();

        Runnable[] gossipAll = new Runnable[1];
        gossipAll[0] = () -> {
            List<Object> values;
            List<String> targets;
            synchronized (lock) {
                values = new ArrayList<>(seen);
                targets = new ArrayList<>(neighbors);
            }
            if (values.isEmpty()) return;
            for (String peer : targets) {
                Map<String, Object> g = new HashMap<>();
                g.put("type", "gossip");
                g.put("values", values);
                node.send(peer, g);
            }
        };

        node.handle("topology", (msg, body) -> {
            synchronized (lock) {
                neighbors.clear();
                @SuppressWarnings("unchecked")
                Map<String, Object> topo =
                    (Map<String, Object>) body.get("topology");
                if (topo != null && topo.get(node.id()) != null) {
                    for (Object p : (List<?>) topo.get(node.id()))
                        neighbors.add((String) p);
                }
            }
            Map<String, Object> rep = new HashMap<>();
            rep.put("type", "topology_ok");
            return rep;
        });

        node.handle("broadcast", (msg, body) -> {
            boolean fresh;
            synchronized (lock) { fresh = seen.add(body.get("message")); }
            if (fresh) gossipAll[0].run();
            Map<String, Object> rep = new HashMap<>();
            rep.put("type", "broadcast_ok");
            return rep;
        });

        node.handle("gossip", (msg, body) -> {
            List<Object> freshVals = new ArrayList<>();
            synchronized (lock) {
                for (Object v : (List<?>) body.get("values"))
                    if (seen.add(v)) freshVals.add(v);
            }
            if (!freshVals.isEmpty()) gossipAll[0].run();
            return null;
        });

        node.handle("read", (msg, body) -> {
            Map<String, Object> rep = new HashMap<>();
            rep.put("type", "read_ok");
            synchronized (lock) {
                rep.put("messages", new ArrayList<>(seen));
            }
            return rep;
        });

        node.onInit(() -> new Thread(() -> {
            while (true) {
                try { Thread.sleep(500); }
                catch (InterruptedException e) { return; }
                gossipAll[0].run();
            }
        }).start());

        node.run();
    }
}
