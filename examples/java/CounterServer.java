// Grow-only counter over seq-kv (workload: g-counter): CAS-increment a
// per-node key, sum every node's key on read — exercises the KV
// client against the harness's Sequential service.
package maelstrom;

import java.util.HashMap;
import java.util.Map;

public final class CounterServer {
    public static void main(String[] args) throws Exception {
        Maelstrom.Node node = new Maelstrom.Node();
        Maelstrom.KV kv = Maelstrom.KV.seq(node);

        node.handle("add", (msg, body) -> {
            long delta = ((Number) body.get("delta")).longValue();
            String key = "counter-" + node.id();
            while (true) {
                long cur = kv.readLong(key, 0);
                try {
                    kv.cas(key, cur, cur + delta, true);
                    break;
                } catch (Maelstrom.RpcException e) {
                    if (e.code != Maelstrom.ERR_PRECONDITION_FAILED)
                        throw e;
                }
            }
            Map<String, Object> rep = new HashMap<>();
            rep.put("type", "add_ok");
            return rep;
        });

        node.handle("read", (msg, body) -> {
            long total = 0;
            for (String peer : node.peers())
                total += kv.readLong("counter-" + peer, 0);
            Map<String, Object> rep = new HashMap<>();
            rep.put("type", "read_ok");
            rep.put("value", total);
            return rep;
        });

        node.run();
    }
}
