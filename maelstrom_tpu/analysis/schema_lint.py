"""Schema/wire conformance lint: registry vs wire encodings vs demo nodes.

The ``rpc()`` registry (core/schema.py) is the single source of truth
for message vocabularies — it drives validation, docs, and the TPU
runtime's fixed-width encodings. This pass cross-checks the three places
a vocabulary can drift apart:

- the registry itself,
- the TPU models' int32 lane encodings (``tpu/wire.py`` rows + each
  model's ``T_*`` constants / ``WIRE_TYPES`` map),
- the bundled demo nodes under ``examples/python/`` (via the demo
  matrix in ``cli.DEMOS``).

Rules (SCH3xx):

=======  =====================  ========  ==================================
rule     name                   severity  what it flags
=======  =====================  ========  ==================================
SCH301   response-type-drift    error /   a node emits ``<rpc>_ok`` that
                                warning   does not match the registry's
                                          declared response type (error), or
                                          an ``*_ok`` type whose stem is
                                          neither registered nor handled in
                                          the same node (warning)
SCH302   missing-handler        error     a demo-matrix node lacks a
                                          handler for one of its workload's
                                          registered request RPCs
SCH303   optional-field-access  error     a handler subscripts a request
                                          field the schema declares
                                          ``Opt`` — crashes on valid input
SCH304   unknown-error-code     error     an error code used in code is not
                                          in the core/errors registry; or
                                          the TPU runtime's definite-code
                                          table drifted from the registry
SCH305   no-wire-lane           error     a registered request RPC of a
                                          TPU-modeled workload has no int32
                                          wire TYPE (``WIRE_TYPES`` /
                                          ``T_<NAME>`` convention), or its
                                          required scalar fields exceed the
                                          model's body lanes
=======  =====================  ========  ==================================
"""

from __future__ import annotations

import ast
import glob
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding, SEV_ERROR, SEV_WARNING

PASS_NAME = "schema"

ENVELOPE_TYPES = {"init", "init_ok", "error"}

# registered request RPCs that are only exercised behind a CLI flag:
# (namespace, rpc) -> the opts key that turns them on
GATED_RPCS = {("kafka", "txn"): "txn"}

# workload namespace -> (model workload name, node_count) for the wire
# coverage rule; namespaces absent here have no TPU model
TPU_MODELED = {
    "echo": ("echo", 1),
    "unique-ids": ("unique-ids", 3),
    "broadcast": ("broadcast", 5),
    "g-set": ("g-set", 5),
    "pn-counter": ("pn-counter", 3),
    "g-counter": ("g-counter", 3),
    "lin-kv": ("lin-kv", 5),
    "txn-list-append": ("txn-list-append", 3),
    "txn-rw-register": ("txn-rw-register", 3),
    "kafka": ("kafka", 1),
}


def _finding(rule, name, severity, path, line, symbol, message):
    return Finding(rule=rule, name=name, severity=severity,
                   pass_name=PASS_NAME, path=path, line=line,
                   symbol=symbol, message=message)


# --- node-file scanning -----------------------------------------------------

def _string_calls(tree: ast.AST, func_name: str, attr: str
                  ) -> List[Tuple[str, int]]:
    """(literal, lineno) for calls shaped ``<func_name>.<attr>("lit")``."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == attr and \
                isinstance(node.func.value, ast.Name) and \
                node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno))
    return out


def _emitted_types(tree: ast.AST) -> List[Tuple[str, int]]:
    """String values of ``"type"`` keys in dict literals."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "type" \
                        and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    out.append((v.value, v.lineno))
    return out


def _loop_registered(tree: ast.AST) -> Set[str]:
    """Handler names registered via ``for t in ("a", "b"): node.on(t, f)``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.For) or \
                not isinstance(node.target, ast.Name) or \
                not isinstance(node.iter, (ast.Tuple, ast.List)):
            continue
        names = [e.value for e in node.iter.elts
                 if isinstance(e, ast.Constant) and isinstance(e.value, str)]
        if len(names) != len(node.iter.elts):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "on" and sub.args and \
                    isinstance(sub.args[0], ast.Name) and \
                    sub.args[0].id == node.target.id:
                out.update(names)
    return out


def _has_dynamic_on(tree: ast.AST) -> bool:
    """True when some ``node.on(expr, ...)`` registration could not be
    resolved to string literals — SCH302 cannot prove a handler missing
    then."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "on" and node.args and \
                not isinstance(node.args[0], ast.Constant):
            if isinstance(node.args[0], ast.Name):
                continue    # loop-variable form: _loop_registered saw it
            return True
    return False


def _handlers(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """rpc name -> handler FunctionDef for ``@node.on("x")`` decorators."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call) and \
                    isinstance(dec.func, ast.Attribute) and \
                    dec.func.attr == "on" and dec.args and \
                    isinstance(dec.args[0], ast.Constant) and \
                    isinstance(dec.args[0].value, str):
                out[dec.args[0].value] = node
    return out


def _registry():
    """The populated RPC registry (importing workloads registers all)."""
    import maelstrom_tpu.workloads  # noqa: F401  (side effect: rpc())
    from ..core.schema import REGISTRY
    return REGISTRY


def _opt_request_keys(rpcdef) -> Set[str]:
    from ..core.schema import Opt
    return {k.key for k in rpcdef.request if isinstance(k, Opt)}


def scan_node_source(rel_path: str, src: str, workload: Optional[str],
                     required_rpcs: Iterable[str],
                     registry=None) -> List[Finding]:
    """SCH301/302/303 over one demo node file (testable core).

    ``workload``: the node's workload namespace (None = not in the demo
    matrix; only the global SCH301 shape checks run then).
    """
    registry = registry if registry is not None else _registry()
    findings: List[Finding] = []
    try:
        tree = ast.parse(src, filename=rel_path)
    except SyntaxError as e:
        return [_finding("SCH300", "syntax-error", SEV_ERROR, rel_path,
                         e.lineno or 0, "", f"cannot parse: {e.msg}")]

    handlers = _handlers(tree)
    handled = set(handlers) | {n for n, _ in
                               _string_calls(tree, "node", "on")}
    handled |= _loop_registered(tree)
    dynamic_registration = _has_dynamic_on(tree)
    emitted = _emitted_types(tree)
    all_request_names = {n for rpcs in registry.values() for n in rpcs}
    all_response_types = {d.response_type for rpcs in registry.values()
                          for d in rpcs.values()} | ENVELOPE_TYPES
    ns_rpcs = registry.get(workload, {}) if workload else {}

    # SCH302: every required request RPC has a handler (skipped when the
    # node registers handlers through names we cannot resolve)
    for name in required_rpcs:
        if not dynamic_registration and name not in handled:
            findings.append(_finding(
                "SCH302", "missing-handler", SEV_ERROR, rel_path, 0,
                os.path.basename(rel_path),
                f"no handler for the {workload!r} workload's "
                f"registered RPC {name!r} (expected node.on({name!r}))"))

    # SCH301: emitted *_ok types
    for t, line in emitted:
        if not t.endswith("_ok") or t in ENVELOPE_TYPES:
            continue
        stem = t[: -len("_ok")]
        if stem in ns_rpcs:
            declared = ns_rpcs[stem].response_type
            if t != declared:
                findings.append(_finding(
                    "SCH301", "response-type-drift", SEV_ERROR, rel_path,
                    line, os.path.basename(rel_path),
                    f"replies to {stem!r} with type {t!r} but the "
                    f"registry declares {declared!r}"))
            continue
        if t in all_response_types:
            continue
        if stem in handled or stem in {e for e, _ in emitted}:
            continue    # internal node-to-node protocol message
        findings.append(_finding(
            "SCH301", "response-type-drift", SEV_WARNING, rel_path, line,
            os.path.basename(rel_path),
            f"emits reply type {t!r} whose request {stem!r} is neither "
            f"registered ({sorted(all_request_names)[:8]}...) nor "
            f"handled in this node"))

    # SCH303: handlers subscripting Opt request fields
    for rpc_name, fn in handlers.items():
        d = ns_rpcs.get(rpc_name)
        if d is None:
            continue
        opt_keys = _opt_request_keys(d)
        if not opt_keys:
            continue
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Subscript) and \
                    isinstance(sub.slice, ast.Constant) and \
                    sub.slice.value in opt_keys:
                findings.append(_finding(
                    "SCH303", "optional-field-access", SEV_ERROR,
                    rel_path, sub.lineno, f"{rpc_name} handler",
                    f"subscripts request field "
                    f"{sub.slice.value!r} which the schema declares "
                    f"optional — use .get(); a valid request without it "
                    f"crashes the handler"))
    return findings


# --- error codes ------------------------------------------------------------

def check_definite_codes() -> List[Finding]:
    """SCH304a: tpu/runtime.py's definite-code table == error registry."""
    from ..core.errors import _ERRORS
    from ..tpu.runtime import _DEFINITE_CODES
    registry_definite = tuple(sorted(e.code for e in _ERRORS if e.definite))
    runtime_definite = tuple(sorted(_DEFINITE_CODES))
    if registry_definite != runtime_definite:
        return [_finding(
            "SCH304", "unknown-error-code", SEV_ERROR,
            "maelstrom_tpu/tpu/runtime.py", 0, "_DEFINITE_CODES",
            f"TPU runtime definite-error table {runtime_definite} != "
            f"core.errors registry {registry_definite} — fail/info "
            f"verdicts drift between runtimes")]
    return []


def check_error_codes(sources: Dict[str, str],
                      valid_codes: Optional[Set[int]] = None
                      ) -> List[Finding]:
    """SCH304b: literal error codes must exist in the registry
    (codes >= 1000 are the documented user range)."""
    if valid_codes is None:
        from ..core.errors import ERRORS_BY_CODE
        valid_codes = set(ERRORS_BY_CODE)
    findings = []
    for rel_path, src in sources.items():
        try:
            tree = ast.parse(src, filename=rel_path)
        except SyntaxError:
            continue    # trace/schema passes report parse errors already
        for node in ast.walk(tree):
            code = None
            if isinstance(node, ast.Call):
                f = node.func
                name = f.id if isinstance(f, ast.Name) else (
                    f.attr if isinstance(f, ast.Attribute) else "")
                if name == "RPCError" and node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, int):
                    code = node.args[0].value
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == "code" \
                            and isinstance(v, ast.Constant) \
                            and isinstance(v.value, int):
                        code = v.value
            if code is not None and code not in valid_codes \
                    and code < 1000:
                findings.append(_finding(
                    "SCH304", "unknown-error-code", SEV_ERROR, rel_path,
                    node.lineno, "",
                    f"error code {code} is not in the core/errors.py "
                    f"registry (user codes start at 1000) — checkers "
                    f"will misclassify its definiteness"))
    return findings


# --- wire coverage ----------------------------------------------------------

def _scalar_required_fields(rpcdef) -> List[str]:
    from ..core import schema as S
    out = []
    for k, v in rpcdef.request.items():
        if k is Ellipsis or isinstance(k, S.Opt):
            continue
        if isinstance(v, (list, dict, S.MapOf)):
            continue    # structured payloads have bespoke encodings
        out.append(k)
    return out


def check_wire_coverage(registry=None) -> List[Finding]:
    """SCH305: every registered request RPC of a TPU-modeled workload
    resolves to a wire TYPE constant, and its required scalar fields fit
    the model's body lanes."""
    import importlib
    from ..models import get_model

    registry = registry if registry is not None else _registry()
    findings: List[Finding] = []
    for ns, (workload, n) in sorted(TPU_MODELED.items()):
        if ns not in registry:
            findings.append(_finding(
                "SCH305", "no-wire-lane", SEV_ERROR,
                "maelstrom_tpu/core/schema.py", 0, ns,
                f"workload {ns!r} has a TPU model but no registered "
                f"RPCs — docs and validation are blind to it"))
            continue
        model = get_model(workload, n, "grid")
        mod = importlib.import_module(type(model).__module__)
        path = type(model).__module__.replace(".", os.sep) + ".py"
        wire_types = getattr(model, "WIRE_TYPES", None)
        for name, d in registry[ns].items():
            if wire_types is not None and name in wire_types:
                continue    # explicit map (None = declared lane-free)
            const = name.upper().replace("-", "_")
            if hasattr(mod, f"T_{const}") or hasattr(mod, f"TYPE_{const}"):
                continue
            findings.append(_finding(
                "SCH305", "no-wire-lane", SEV_ERROR, path, 0,
                type(model).__name__,
                f"registered RPC {ns}/{name} has no wire TYPE "
                f"(expected T_{const}/TYPE_{const} or a WIRE_TYPES "
                f"entry) — the device runtime cannot carry it"))
        for name, d in registry[ns].items():
            fields = _scalar_required_fields(d)
            if len(fields) > model.body_lanes:
                findings.append(_finding(
                    "SCH305", "no-wire-lane", SEV_ERROR, path, 0,
                    type(model).__name__,
                    f"RPC {ns}/{name} needs {len(fields)} scalar "
                    f"request lanes {fields} but the model declares "
                    f"body_lanes={model.body_lanes}"))
    return findings


# --- orchestration ----------------------------------------------------------

def _demo_python_nodes() -> List[Tuple[str, str, dict]]:
    """(workload, node_file, opts) for the python demo-matrix entries."""
    from ..cli import DEMOS
    out = []
    for entry in DEMOS:
        workload, node, extra = entry[0], entry[1], entry[2]
        if extra.get("runtime") == "native":
            continue
        node_file = node.split()[0]
        out.append((workload, node_file, extra))
    return out


def run_schema_lint(repo_root: str = ".") -> List[Finding]:
    registry = _registry()
    findings: List[Finding] = []

    # demo nodes: one scan per unique (file, workload); required RPCs
    # are the union over the matrix entries that run that pairing
    required: Dict[Tuple[str, str], Set[str]] = {}
    for workload, node_file, extra in _demo_python_nodes():
        key = (node_file, workload)
        rpcs = required.setdefault(key, set())
        for name in registry.get(workload, {}):
            gate = GATED_RPCS.get((workload, name))
            if gate is not None and not extra.get(gate):
                continue
            rpcs.add(name)
    for (node_file, workload), rpcs in sorted(required.items()):
        rel = os.path.join("examples", "python", node_file)
        ap = os.path.join(repo_root, rel)
        if not os.path.exists(ap):
            findings.append(_finding(
                "SCH302", "missing-handler", SEV_ERROR, rel, 0, node_file,
                f"demo matrix references {node_file!r} for "
                f"{workload!r} but the file does not exist"))
            continue
        with open(ap) as f:
            src = f.read()
        findings.extend(scan_node_source(rel, src, workload,
                                         sorted(rpcs), registry))

    # error codes: demo nodes + the whole package
    sources = {}
    for pat in ("examples/python/*.py", "maelstrom_tpu/**/*.py"):
        for p in glob.glob(os.path.join(repo_root, pat), recursive=True):
            rel = os.path.relpath(p, repo_root)
            with open(p) as f:
                sources[rel] = f.read()
    findings.extend(check_error_codes(sources))
    findings.extend(check_definite_codes())
    findings.extend(check_wire_coverage(registry))
    return findings
