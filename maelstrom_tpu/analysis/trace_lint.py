"""Trace-hygiene lint: an AST pass over the traced surfaces.

The TPU runtime traces every :class:`~..tpu.runtime.Model` method and
the tick-loop helpers exactly once and replays the jitted graph for the
whole simulation. Python-level control flow on traced values, host
synchronizations, hidden mutable state, and bare-Python randomness all
either crash at trace time, silently freeze a "random" choice into the
graph, or force per-tick recompilation — the 100x-slowdown /
wrong-verdict bug class this pass exists to catch *before* a device run.

Mechanics: a file-local taint analysis. A function is **traced** when

- it is a known Model traced method (``handle``, ``tick``, ...), or
- one of its parameters has a conventional traced name (``row``,
  ``msg``, ``t``, ``key``, ``carry``, ``pool``, ... or ``*_ref`` for
  Pallas kernels), or
- it is (transitively) called from a traced function — by-name fixpoint
  over ``self.x(...)`` / bare-name calls across all scanned files, so
  helpers like ``RaftModel._apply_one`` inherit tracedness, or
- it is defined *inside* a traced function (scan/vmap bodies).

Inside a traced function, parameters are tainted (except a static-name
allowlist: ``self``, ``cfg``, ``n_nodes``, config objects), and taint
propagates through expressions. Host-side methods (``invoke_record``,
``checker``, the harness) never match and are skipped.

Rules (TRC1xx):

=======  ====================  ========  =====================================
rule     name                  severity  what it flags
=======  ====================  ========  =====================================
TRC101   traced-branch         error     python ``if`` on a traced value
TRC102   traced-while          error     python ``while`` on a traced value
TRC103   traced-assert         error     ``assert`` on a traced value
TRC104   host-sync             error     ``.item()`` / ``int()`` / ``float()``
                                         / ``bool()`` / ``np.asarray`` on a
                                         traced value inside a traced fn
TRC105   mutable-capture       error     mutating a list/dict/set captured
                                         from an enclosing scope (or module /
                                         ``self`` state) inside a traced fn
TRC106   data-dependent-shape  warning   ``jnp.nonzero`` / ``unique`` /
                                         ``argwhere`` / 1-arg ``where`` —
                                         value-dependent shapes break jit
                                         and differ across replicas
TRC107   bare-python-rng       error     ``random.*`` / ``np.random.*`` in a
                                         traced fn (a ``jax.random`` key is
                                         the only replay-stable source)
=======  ====================  ========  =====================================
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, SEV_ERROR, SEV_WARNING

PASS_NAME = "trace"

# Model methods that the runtime traces (tpu/runtime.py contract).
KNOWN_TRACED_METHODS = {
    "init_row", "handle", "tick", "invariants", "sample_op",
    "sample_final_op", "encode_request", "decode_reply",
    "decode_reply_wide",
}

# Conventional traced-argument names: presence of one marks a
# module-level function as traced (tick-loop helpers, netsim ops).
TRACED_PARAM_NAMES = {
    "row", "msg", "msgs", "t", "key", "keys", "carry", "pool",
    "node_state", "client_state", "inbox", "inbox_nodes",
    "inbox_clients", "op", "uniq", "msg_id", "client_idx", "node_idx",
    "partitions", "instance_key", "row_body", "tel",
}

# Parameters that are static (python-level) even inside traced functions.
STATIC_PARAM_NAMES = {
    "self", "cls", "cfg", "ccfg", "nem", "sim", "model", "params",
    "n_nodes", "node_count", "seed", "interpret", "length", "checker",
    "opts", "mesh", "axes", "gossip_prob", "body_lanes",
    # fault-plan engine (maelstrom_tpu/faults/): the compiled
    # FaultConfig and its snapshot stride are trace-time constants,
    # exactly like `nem`/`cfg`
    "fx", "every",
}

# Attribute reads on tainted values that yield static metadata.
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}

# Calls that launder taint into static python values (and are themselves
# host syncs when applied to a traced value).
_HOST_SYNC_BUILTINS = {"int", "float", "bool", "complex"}
_HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
_NP_SYNC_FUNCS = {"asarray", "array", "copyto"}

_DATA_DEP_FUNCS = {"nonzero", "flatnonzero", "argwhere", "unique",
                   "unique_values"}

_MUTATING_METHODS = {"append", "extend", "insert", "remove", "pop",
                     "clear", "add", "discard", "update", "setdefault",
                     "popitem"}

_RNG_MODULE_NAMES = {"random"}          # stdlib `import random`
_NP_NAMES = {"np", "numpy"}

# Builtins whose results are static regardless of argument taint (len of
# a traced array is its static shape; range/enumerate over statics).
_STATIC_RESULT_BUILTINS = {"len", "range", "enumerate", "zip", "isinstance",
                           "hasattr", "getattr", "type", "round", "repr",
                           "str", "print", "min", "max", "abs", "sorted"}
# note: min/max/abs on *tracers* would be host syncs via __bool__ only
# for min/max with multiple tracer args; kept static to avoid false
# positives on `min(python, python)` — TRC101 still catches the branchy
# patterns that matter.


def _func_params(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested attribute chains rooted at a Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FileIndex(ast.NodeVisitor):
    """First pass over one file: function defs, their called names, and
    which functions look traced by themselves."""

    def __init__(self):
        self.functions: Dict[str, List[Tuple[str, ast.AST]]] = {}
        # name -> list of (qualname, node); name collisions keep all
        self.calls_from: Dict[str, Set[str]] = {}   # qualname -> callee names
        self.self_traced: Set[str] = set()          # qualnames
        self._stack: List[str] = []
        self._class_stack: List[str] = []

    def _qual(self, name: str) -> str:
        return ".".join(self._class_stack + [name])

    def visit_ClassDef(self, node: ast.ClassDef):
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_fn(self, node):
        qual = self._qual(node.name)
        self.functions.setdefault(node.name, []).append((qual, node))
        params = _func_params(node)
        in_class = bool(self._class_stack)
        if in_class:
            # methods: only the runtime's known traced entry points (and
            # the call-graph fixpoint) — param names like `t`/`row` also
            # appear on host-side decoders (journal, history decoding)
            if node.name in KNOWN_TRACED_METHODS:
                self.self_traced.add(qual)
        elif any(p in TRACED_PARAM_NAMES or p.endswith("_ref")
                 for p in params if p not in STATIC_PARAM_NAMES):
            self.self_traced.add(qual)
        callees: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Name):
                    callees.add(f.id)
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id in ("self", "cls"):
                    callees.add(f.attr)
        self.calls_from[qual] = callees
        # nested defs are deliberately NOT indexed as separate functions:
        # the checker walks them inline with the parent's taint env

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


def _traced_qualnames(indexes: Dict[str, _FileIndex]) -> Set[str]:
    """Global fixpoint: traced roots + anything they call (by name)."""
    traced_names: Set[str] = set()      # bare function/method names
    traced_quals: Set[str] = set()
    for idx in indexes.values():
        for qual in idx.self_traced:
            traced_quals.add(qual)
            traced_names.add(qual.rsplit(".", 1)[-1])
    changed = True
    while changed:
        changed = False
        for idx in indexes.values():
            for name, defs in idx.functions.items():
                for qual, _node in defs:
                    is_traced = (qual in traced_quals
                                 or name in traced_names)
                    if not is_traced:
                        continue
                    if qual not in traced_quals:
                        traced_quals.add(qual)
                        changed = True
                    for callee in idx.calls_from.get(qual, ()):
                        if callee in traced_names:
                            continue
                        # only propagate to names actually defined
                        # somewhere in the scanned set
                        if any(callee in i.functions
                               for i in indexes.values()):
                            traced_names.add(callee)
                            changed = True
    return traced_quals


class _TraceChecker(ast.NodeVisitor):
    """Taint-tracking walk of ONE traced function (incl. nested defs)."""

    def __init__(self, path: str, symbol: str, module_mutables: Set[str],
                 findings: List[Finding]):
        self.path = path
        self.symbol = symbol
        self.module_mutables = module_mutables
        self.findings = findings
        self.tainted: Set[str] = set()
        self.local_names: Set[str] = set()
        self._flagged: Set[Tuple[str, int]] = set()

    # --- reporting --------------------------------------------------------

    def _flag(self, rule: str, name: str, severity: str, node: ast.AST,
              message: str):
        k = (rule, getattr(node, "lineno", 0))
        if k in self._flagged:
            return
        self._flagged.add(k)
        self.findings.append(Finding(
            rule=rule, name=name, severity=severity, pass_name=PASS_NAME,
            path=self.path, line=getattr(node, "lineno", 0),
            symbol=self.symbol, message=message))

    # --- taint evaluation -------------------------------------------------

    def _is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            base = node.value
            # self.x / cfg.x / module.CONST are static configuration
            if isinstance(base, ast.Name) and base.id not in self.tainted:
                return False
            return self._is_tainted(base)
        if isinstance(node, ast.Subscript):
            return (self._is_tainted(node.value)
                    or self._is_tainted(node.slice))
        if isinstance(node, ast.Call):
            return self._call_taint(node)
        if isinstance(node, ast.BinOp):
            return self._is_tainted(node.left) or self._is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a static python-level
            # structure check, legitimate on optional traced args
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return (self._is_tainted(node.left)
                    or any(self._is_tainted(c) for c in node.comparators))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self._is_tainted(v) for v in node.values
                       if v is not None)
        if isinstance(node, ast.IfExp):
            return (self._is_tainted(node.test)
                    or self._is_tainted(node.body)
                    or self._is_tainted(node.orelse))
        if isinstance(node, ast.Starred):
            return self._is_tainted(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return any(self._is_tainted(g.iter) for g in node.generators)
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, ast.JoinedStr):
            return False
        if isinstance(node, ast.Slice):
            return any(self._is_tainted(p) for p in
                       (node.lower, node.upper, node.step) if p is not None)
        return False

    def _call_taint(self, node: ast.Call) -> bool:
        f = node.func
        dotted = _dotted(f) or ""
        root = dotted.split(".", 1)[0]
        args_tainted = (any(self._is_tainted(a) for a in node.args)
                        or any(self._is_tainted(kw.value)
                               for kw in node.keywords))
        if isinstance(f, ast.Name) and f.id in _STATIC_RESULT_BUILTINS:
            return False
        if isinstance(f, ast.Name) and f.id in _HOST_SYNC_BUILTINS:
            return False        # flagged separately; result is host-static
        if root in ("jnp", "jax"):
            return True         # jax ops produce traced values
        if isinstance(f, ast.Attribute) and f.attr in _STATIC_ATTRS:
            return False
        if isinstance(f, ast.Attribute) and self._is_tainted(f.value):
            return True         # method on a traced value (.at[].set, ...)
        return args_tainted

    # --- binding ----------------------------------------------------------

    def _bind(self, target: ast.AST, tainted: bool):
        if isinstance(target, ast.Name):
            self.local_names.add(target.id)
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)
        # subscript/attribute targets: handled by mutation rule

    # --- statements -------------------------------------------------------

    def check_function(self, fn: ast.AST, extra_static: Set[str] = frozenset()):
        for p in _func_params(fn):
            self.local_names.add(p)
            if p not in STATIC_PARAM_NAMES and p not in extra_static:
                self.tainted.add(p)
        for stmt in fn.body:
            self.visit(stmt)

    def visit_Assign(self, node: ast.Assign):
        t = self._is_tainted(node.value)
        for target in node.targets:
            if isinstance(target, (ast.Subscript, ast.Attribute)):
                self._check_mutation_target(target, node)
            self._bind(target, t)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._bind(node.target, self._is_tainted(node.value))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        t = self._is_tainted(node.value) or self._is_tainted(node.target)
        if isinstance(node.target, (ast.Subscript, ast.Attribute)):
            self._check_mutation_target(node.target, node)
        self._bind(node.target, t)
        self.generic_visit(node)

    def visit_If(self, node: ast.If):
        if self._is_tainted(node.test):
            self._flag("TRC101", "traced-branch", SEV_ERROR, node,
                       "python `if` on a traced value — use jnp.where / "
                       "lax.cond; a tracer has no stable __bool__")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        if self._is_tainted(node.test):
            self._flag("TRC102", "traced-while", SEV_ERROR, node,
                       "python `while` on a traced value — use "
                       "lax.while_loop / lax.fori_loop")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        if self._is_tainted(node.test):
            self._flag("TRC103", "traced-assert", SEV_ERROR, node,
                       "assert on a traced value — crashes at trace time; "
                       "use checkify or an invariants() lane")
        self.generic_visit(node)

    def visit_For(self, node: ast.For):
        # iterating a traced array unrolls (legal); the target is traced.
        # The iterable expression itself still gets the call rules
        # (host-sync/RNG inside `for x in np.asarray(row)`).
        self.visit(node.iter)
        self._bind(node.target, self._is_tainted(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call):
        f = node.func
        dotted = _dotted(f) or ""
        root = dotted.split(".", 1)[0]
        args_tainted = (any(self._is_tainted(a) for a in node.args)
                        or any(self._is_tainted(kw.value)
                               for kw in node.keywords))

        # TRC104: host syncs
        if isinstance(f, ast.Name) and f.id in _HOST_SYNC_BUILTINS \
                and args_tainted:
            self._flag("TRC104", "host-sync", SEV_ERROR, node,
                       f"`{f.id}()` on a traced value forces a host sync "
                       f"(ConcretizationTypeError under jit)")
        if isinstance(f, ast.Attribute) and f.attr in _HOST_SYNC_ATTRS \
                and self._is_tainted(f.value):
            self._flag("TRC104", "host-sync", SEV_ERROR, node,
                       f"`.{f.attr}()` on a traced value forces a device "
                       f"round-trip inside a traced function")
        if root in _NP_NAMES and len(dotted.split(".")) == 2 \
                and dotted.split(".")[1] in _NP_SYNC_FUNCS and args_tainted:
            self._flag("TRC104", "host-sync", SEV_ERROR, node,
                       f"`{dotted}()` on a traced value materializes on "
                       f"host — use jnp inside traced code")

        # TRC106: data-dependent output shapes
        if root in {"jnp", "jax"} | _NP_NAMES:
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf in _DATA_DEP_FUNCS:
                self._flag("TRC106", "data-dependent-shape", SEV_WARNING,
                           node,
                           f"`{dotted}` has a value-dependent output "
                           f"shape — fails under jit/vmap and is not "
                           f"replica-deterministic; use fixed-size masks")
            if leaf == "where" and len(node.args) == 1 and not node.keywords:
                self._flag("TRC106", "data-dependent-shape", SEV_WARNING,
                           node,
                           "1-arg `where` returns value-dependent shapes "
                           "— use the 3-arg select form")

        # TRC107: bare python RNG
        if root in _RNG_MODULE_NAMES and "." in dotted:
            self._flag("TRC107", "bare-python-rng", SEV_ERROR, node,
                       f"`{dotted}()` (python RNG) inside a traced "
                       f"function freezes one sample into the compiled "
                       f"graph — thread a jax.random key instead")
        if root in _NP_NAMES and ".random." in "." + dotted + ".":
            self._flag("TRC107", "bare-python-rng", SEV_ERROR, node,
                       f"`{dotted}()` (numpy RNG) inside a traced "
                       f"function freezes one sample into the compiled "
                       f"graph — thread a jax.random key instead")

        # TRC105: mutating a captured container
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            self._check_mutation_target(f.value, node, method=f.attr)

        self.generic_visit(node)

    def _check_mutation_target(self, target: ast.AST, node: ast.AST,
                               method: Optional[str] = None):
        """Flag in-place mutation of state captured from outside the
        traced function (enclosing scope, module globals, or self)."""
        root = target
        while isinstance(root, (ast.Subscript, ast.Attribute)):
            root = root.value
        if not isinstance(root, ast.Name):
            return
        name = root.id
        is_self_state = (isinstance(target, ast.Attribute)
                         and name in ("self", "cls"))
        captured = (name not in self.local_names
                    and (name in self.module_mutables
                         or name in self.tainted))
        if is_self_state or captured:
            what = f".{method}()" if method else "assignment"
            self._flag("TRC105", "mutable-capture", SEV_ERROR, node,
                       f"in-place {what} on `{name}` captured from an "
                       f"enclosing scope — traced functions must be "
                       f"pure; mutation runs once at trace time, not "
                       f"per tick")

    def _visit_nested_fn(self, node):
        # nested defs (scan/vmap bodies) share the enclosing taint env;
        # their params are traced unless conventionally static
        self.local_names.add(node.name)
        saved = (set(self.tainted), set(self.local_names))
        for p in _func_params(node):
            self.local_names.add(p)
            if p not in STATIC_PARAM_NAMES:
                self.tainted.add(p)
        for stmt in node.body:
            self.visit(stmt)
        self.tainted, self.local_names = saved

    visit_FunctionDef = _visit_nested_fn
    visit_AsyncFunctionDef = _visit_nested_fn

    def visit_Lambda(self, node: ast.Lambda):
        saved = (set(self.tainted), set(self.local_names))
        for p in _func_params(node):
            self.local_names.add(p)
            if p not in STATIC_PARAM_NAMES:
                self.tainted.add(p)
        self.visit(node.body)
        self.tainted, self.local_names = saved

    def visit_ClassDef(self, node: ast.ClassDef):
        pass    # class defs inside traced fns: out of scope

    def visit_Import(self, node):
        for a in node.names:
            self.local_names.add(a.asname or a.name.split(".")[0])

    def visit_ImportFrom(self, node):
        for a in node.names:
            self.local_names.add(a.asname or a.name)


def _module_mutables(tree: ast.Module) -> Set[str]:
    """Module-level names bound to mutable literals (lists/dicts/sets)."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def default_trace_targets(repo_root: str) -> List[str]:
    """The traced surfaces named by the lint contract: every model, the
    tick-loop machinery, and the delivery kernel."""
    import glob
    pats = ["maelstrom_tpu/models/*.py", "maelstrom_tpu/tpu/*.py",
            "maelstrom_tpu/ops/delivery.py",
            "maelstrom_tpu/telemetry/recorder.py",
            "maelstrom_tpu/telemetry/stream.py",
            "maelstrom_tpu/telemetry/profiler.py",
            "maelstrom_tpu/checkers/triage.py",
            "maelstrom_tpu/checkers/pool.py",
            "maelstrom_tpu/campaign/*.py",
            "maelstrom_tpu/faults/*.py",
            # host-side analysis code, but its verdicts gate traced
            # code — keep the analyzer itself lint-clean
            "maelstrom_tpu/analysis/absint.py",
            "maelstrom_tpu/analysis/shard_audit.py",
            "maelstrom_tpu/analysis/aot_audit.py"]
    out = []
    for p in pats:
        out.extend(sorted(glob.glob(os.path.join(repo_root, p))))
    return [p for p in out if os.path.basename(p) != "__init__.py"
            or "models" not in p]


def lint_sources(sources: Dict[str, str]) -> List[Finding]:
    """Lint a {repo-relative-path: source} mapping (testable core)."""
    findings: List[Finding] = []
    indexes: Dict[str, _FileIndex] = {}
    trees: Dict[str, ast.Module] = {}
    for path, src in sources.items():
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(
                rule="TRC100", name="syntax-error", severity=SEV_ERROR,
                pass_name=PASS_NAME, path=path, line=e.lineno or 0,
                symbol="", message=f"cannot parse: {e.msg}"))
            continue
        trees[path] = tree
        idx = _FileIndex()
        idx.visit(tree)
        indexes[path] = idx

    traced_quals = _traced_qualnames(indexes)

    for path, idx in indexes.items():
        mutables = _module_mutables(trees[path])
        for name, defs in idx.functions.items():
            for qual, node in defs:
                if qual in traced_quals:
                    checker = _TraceChecker(path, qual, mutables,
                                            findings)
                    checker.check_function(node)
                    continue
                # host-side factories (make_tick_fn & co.) wrap traced
                # bodies in nested defs: check any nested def whose own
                # params look traced, with a fresh environment
                for sub in ast.walk(node):
                    if sub is node or not isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        continue
                    if any(p in TRACED_PARAM_NAMES or p.endswith("_ref")
                           for p in _func_params(sub)
                           if p not in STATIC_PARAM_NAMES):
                        checker = _TraceChecker(
                            path, f"{qual}.{sub.name}", mutables,
                            findings)
                        checker.check_function(sub)
    # nested-def scanning can visit a doubly-nested body twice — dedupe
    # on (rule, location)
    seen, out = set(), []
    for f in findings:
        k = (f.rule, f.path, f.line)
        if k not in seen:
            seen.add(k)
            out.append(f)
    return out


def run_trace_lint(repo_root: str,
                   paths: Optional[List[str]] = None) -> List[Finding]:
    targets = paths if paths else default_trace_targets(repo_root)
    sources = {}
    findings: List[Finding] = []
    for p in targets:
        ap = p if os.path.isabs(p) else os.path.join(repo_root, p)
        rel = os.path.relpath(ap, repo_root)
        try:
            with open(ap) as f:
                sources[rel] = f.read()
        except OSError as e:
            # surface unreadable targets as findings and keep scanning
            # the rest — one bad path must not hide real hazards
            findings.append(Finding(
                rule="TRC100", name="unreadable-file",
                severity=SEV_ERROR, pass_name=PASS_NAME, path=rel,
                line=0, symbol="", message=str(e)))
    findings.extend(lint_sources(sources))
    return findings
