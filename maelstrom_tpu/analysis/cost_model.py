"""Static per-tick cost model over the lowered JAX IR.

PR 1's AST lint and the abstract-eval contract audit stop at the Python
surface: they can say a model's *shapes* are right, but not what the
tick actually lowers to. The tick loop's honest throughput ceiling is
launch overhead — ~1000 XLA thunks per tick on the flagship config
(ROADMAP "pipelined executor" item) — so the quantity to budget is the
**lowered graph itself**: how many equations one fused tick compiles
to, how they split across the ``jax.named_scope`` phases the runtime
already annotates (nemesis / deliver / node_phase / client_step /
enqueue / telemetry), and how many intermediate HBM bytes they move.
This module computes those numbers *statically* — ``jax.make_jaxpr``
over the same tick closure the executor scans, no device, no FLOPs —
so they are deterministic, diffable, and cheap enough to gate every PR.

The numbers feed three consumers:

- ``maelstrom lint --cost`` (``analysis/ir_lint.py``): every registered
  model x both carry layouts is compared against the checked-in
  ``analysis/cost_baseline.json``; a >10% eqn or byte regression fails
  the gate pre-merge, and ``--update-baseline`` re-records after an
  intentional change.
- ``bench.py``: the metric line carries ``ir_eqns`` / ``ir_bytes_est``
  so the static cost trajectory lands in BENCH_*.json next to
  wall-clock.
- ``tools/tick_profile.py``: measured ms/tick is printed next to the
  static per-phase eqn counts, with the phase table defined HERE
  (:data:`PHASES`) instead of re-derived by hand.

Estimates, not measurements: ``hbm_bytes`` sums every equation's output
aval bytes (scan bodies weighted by trip count) — an upper-bound proxy
for HBM traffic that ignores fusion, which is exactly why it works as a
*regression* signal (fusion-friendlier IR lowers it; a new
fusion-breaking intermediate raises it).
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# The tick loop's jax.named_scope phase vocabulary (tpu/runtime.py,
# both carry layouts). Equations outside any named scope (stat
# accumulation, invariants, event assembly, scan plumbing) count under
# OTHER_PHASE.
PHASES = ("nemesis", "deliver", "node_phase", "client_step", "enqueue",
          "telemetry")
OTHER_PHASE = "other"

# The FULL known named-scope vocabulary — the phase table above plus
# the scopes that ride specific configs: the fault-engine lanes
# (``faults``, maelstrom_tpu/faults/) and the device verdict lanes
# (``check_summary``, checkers/device_summary.py). The device-time
# profiler (telemetry/profiler.py) attributes against THIS vocabulary;
# an equation under any other scope root — or under no scope the
# profiler can name — counts as unattributed, and the per-entry
# ``unattributed-eqns`` column gates it (COST505): a refactor that
# drops or renames a jax.named_scope can never silently blind the
# attribution.
KNOWN_SCOPES = PHASES + ("faults", "check_summary")

DEFAULT_COST_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "cost_baseline.json")

# cost-gate tolerance: a model's tick may drift this fraction above its
# baseline eqn/byte figures before COST501 fails the gate
DEFAULT_TOLERANCE = 0.10

# both carry layouts are first-class citizens of the cost baseline —
# the batch-minor tick lowers to a (slightly) different graph
AUDIT_LAYOUTS = ("lead", "minor")


@dataclass
class CostReport:
    """Static cost of ONE fused tick (one model, one layout)."""
    eqns: int                        # recursive equation count
    hbm_bytes: int                   # est. intermediate bytes per tick
    phases: Dict[str, int] = field(default_factory=dict)
    ops: Dict[str, int] = field(default_factory=dict)
    const_bytes: int = 0             # total baked-in constant bytes
    max_const_bytes: int = 0         # largest single baked-in constant
    carry_bytes: int = 0             # carry pytree bytes (audit config)
    max_broadcast_bytes: int = 0     # largest broadcast_in_dim output
    loops: int = 0                   # fusion-breaking loops in the tick
                                     # body: while_loops plus scans
                                     # whose bodies are NOT fully
                                     # unrolled at lowering (each one
                                     # survives as an XLA while — the
                                     # boundary fusion cannot cross)
    scopes: Dict[str, int] = field(default_factory=dict)
                                     # eqn count per RAW named-scope
                                     # root (KNOWN_SCOPES members plus
                                     # whatever else the tick carries;
                                     # scope-less eqns under "")
    unattributed_eqns: int = 0       # eqns outside every KNOWN_SCOPES
                                     # scope — the COST505 column
    unknown_scopes: Tuple[str, ...] = ()
                                     # scope roots seen but not in
                                     # KNOWN_SCOPES (a renamed scope
                                     # shows up here by name)

    def to_entry(self) -> Dict[str, Any]:
        """The checked-in baseline representation (stable keys only —
        the op histogram is too jax-version-volatile to pin).
        ``fusion-breakers`` doubles as the model's JXP404 loop budget
        (analysis/ir_lint.py): the refactored raft-family ticks pin 0,
        legacy-scan models keep their recorded count.
        ``unattributed-eqns`` is the COST505 scope-coverage budget —
        eqns the device-time profiler cannot attribute to a known
        named scope."""
        return {"eqns": self.eqns,
                "hbm-bytes-per-tick": self.hbm_bytes,
                "fusion-breakers": self.loops,
                "unattributed-eqns": self.unattributed_eqns,
                "phases": {k: self.phases[k]
                           for k in sorted(self.phases)}}


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def _sub_jaxprs(eqn) -> List[Tuple[Any, int]]:
    """(inner jaxpr, byte-weight multiplier) pairs of one equation.
    Scan bodies run ``length`` times per outer evaluation; every other
    nesting (cond branches, while bodies, pjit calls) weighs 1 — while
    trip counts are unknowable statically and cond branches are
    alternatives, so 1 is the deterministic choice."""
    mult = int(eqn.params.get("length", 1)) \
        if eqn.primitive.name == "scan" else 1
    out = []
    for v in eqn.params.values():
        for sub in (v if isinstance(v, (list, tuple)) else (v,)):
            inner = getattr(sub, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                out.append((inner, mult))     # ClosedJaxpr
            elif hasattr(sub, "eqns"):
                out.append((sub, mult))       # raw Jaxpr
    return out


_TRANSFORM_RE = re.compile(r"^\w+\((.*)\)$")


def _scope_root(eqn) -> str:
    """The equation's raw named_scope root: the first path component of
    its name stack, unwrapped of transform markers — under the
    batch-minor layout's instance vmap a scope renders as
    ``vmap(deliver)``. Empty string for scope-less equations."""
    stack = str(eqn.source_info.name_stack)
    root = stack.split("/", 1)[0] if stack else ""
    while True:
        m = _TRANSFORM_RE.match(root)
        if not m:
            break
        root = m.group(1)
    return root


def _phase_of(eqn) -> str:
    """Phase attribution from the equation's named_scope stack.
    Nested scopes inherit their root phase."""
    root = _scope_root(eqn)
    return root if root in PHASES else OTHER_PHASE


def cost_of_jaxpr(closed, carry=None) -> CostReport:
    """Walk one ClosedJaxpr (a traced tick) into a :class:`CostReport`.
    ``carry`` (a pytree of ShapeDtypeStructs) sizes the carry-relative
    thresholds the hazard pass uses."""
    import jax

    phases: Dict[str, int] = {p: 0 for p in PHASES + (OTHER_PHASE,)}
    ops: Dict[str, int] = {}
    scopes: Dict[str, int] = {}
    totals = {"eqns": 0, "bytes": 0, "max_bcast": 0, "loops": 0}

    def walk(jaxpr, phase: Optional[str], root: Optional[str],
             mult: int) -> None:
        for eqn in jaxpr.eqns:
            if phase is None:
                r = _scope_root(eqn)
                ph = r if r in PHASES else OTHER_PHASE
            else:
                ph, r = phase, root
            name = eqn.primitive.name
            totals["eqns"] += 1
            phases[ph] += 1
            scopes[r] = scopes.get(r, 0) + 1
            ops[name] = ops.get(name, 0) + 1
            out_bytes = sum(_aval_bytes(v) for v in eqn.outvars)
            totals["bytes"] += out_bytes * mult
            if name == "broadcast_in_dim":
                totals["max_bcast"] = max(totals["max_bcast"], out_bytes)
            if name == "while":
                totals["loops"] += 1
            elif name == "scan":
                # a scan survives lowering as an XLA while UNLESS its
                # body is fully unrolled (lax.scan(..., unroll=True) /
                # unroll >= length) — only the loop form breaks fusion
                length = int(eqn.params.get("length", 0))
                unroll = eqn.params.get("unroll", 1)
                unroll = length if unroll is True else int(unroll)
                if unroll < length:
                    totals["loops"] += 1
            for sub, sub_mult in _sub_jaxprs(eqn):
                walk(sub, ph, r, mult * sub_mult)

    walk(closed.jaxpr, None, None, 1)
    const_sizes = []
    for c in closed.consts:
        try:
            import numpy as np
            const_sizes.append(int(np.asarray(c).nbytes))
        except Exception:
            pass
    carry_bytes = 0
    if carry is not None:
        for leaf in jax.tree.leaves(carry):
            n = 1
            for d in getattr(leaf, "shape", ()):
                n *= int(d)
            carry_bytes += n * getattr(leaf, "dtype", None).itemsize \
                if getattr(leaf, "dtype", None) is not None else 0
    # the COST505 column: equations outside every KNOWN_SCOPES scope —
    # scope-less ones plus anything under an unknown (renamed) root
    unattributed = sum(n for r, n in scopes.items()
                       if r not in KNOWN_SCOPES)
    unknown = tuple(sorted(r for r in scopes
                           if r and r not in KNOWN_SCOPES))
    return CostReport(
        eqns=totals["eqns"], hbm_bytes=totals["bytes"],
        phases={k: v for k, v in phases.items() if v},
        ops=ops, const_bytes=sum(const_sizes),
        max_const_bytes=max(const_sizes, default=0),
        carry_bytes=carry_bytes,
        max_broadcast_bytes=totals["max_bcast"],
        loops=totals["loops"],
        scopes={k: scopes[k] for k in sorted(scopes)},
        unattributed_eqns=unattributed,
        unknown_scopes=unknown)


# --- tracing the tick ------------------------------------------------------


def audit_sim(model, node_count: int, layout: str = "lead"):
    """The small fixed audit config every static analysis shares (the
    contract audit's opts + an explicit carry layout) — cost numbers are
    comparable only under one config."""
    from .contract_audit import _audit_opts
    from ..tpu.harness import make_sim_config
    # range_horizon_check=False: the audit config is what the range
    # pass itself analyzes — a stale proven bound must not be able to
    # block its own re-proof
    return make_sim_config(model, {**_audit_opts(node_count),
                                   "layout": layout,
                                   "range_horizon_check": False})


def trace_tick(model, sim, params=None, cache=None):
    """``jax.make_jaxpr`` of the fused tick under ``sim`` — the same
    closure the executors scan. Returns ``(closed_jaxpr, carry_shapes,
    out_shapes)`` where ``carry_shapes`` is the input carry pytree of
    ShapeDtypeStructs and ``out_shapes`` the traced ``(carry', ys)``.
    ``cache`` (a mutable mapping, keyed by :func:`entry_key`) lets the
    combined ``lint --ir --cost --lanes`` gate trace each model x
    layout once instead of once per pass. The key does NOT capture the
    sim config, so pass a cache only with :func:`audit_sim`-built sims
    (the lint passes' shared convention); only default-``params``
    traces are cached (custom params change the graph)."""
    import jax
    import jax.numpy as jnp
    from ..tpu.runtime import init_carry, make_tick_fn

    key = None
    if cache is not None and params is None:
        key = entry_key(getattr(model, "name", type(model).__name__),
                        sim.net.n_nodes, sim.layout)
        hit = cache.get(key)
        if hit is not None:
            return hit
    if params is None:
        params = model.make_params(sim.net.n_nodes)
    tick = make_tick_fn(model, sim, params)
    carry = jax.eval_shape(lambda: init_carry(model, sim, 0, params))
    closed, out_shapes = jax.make_jaxpr(tick, return_shape=True)(
        carry, jnp.int32(0))
    if key is not None:
        cache[key] = (closed, carry, out_shapes)
    return closed, carry, out_shapes


def tick_cost(model, sim, params=None) -> CostReport:
    """One-call static cost of ``model``'s fused tick under ``sim`` —
    the bench.py / tools entry point."""
    closed, carry, _ = trace_tick(model, sim, params)
    return cost_of_jaxpr(closed, carry)


def tick_range_stats(model, sim, traced=None) -> Dict[str, int]:
    """Value-range stats of ``model``'s fused tick under ``sim`` —
    ``ovf_margin_bits`` (minimum proven counter headroom to int32 max
    at the production horizon; 0 = unproven), the figure bench.py
    prints next to the static-cost fields. Thin delegation so cost
    consumers need only this module; the analysis itself lives in
    :mod:`.absint`. ``traced`` (a :func:`trace_tick` triple) skips the
    duplicate abstract trace."""
    from .absint import tick_range_stats as _stats
    return _stats(model, sim, traced=traced)


def tick_lane_stats(model, sim, traced=None,
                    cost: Optional[CostReport] = None) -> Dict[str, int]:
    """Lane-liveness stats of ``model``'s fused tick under ``sim`` —
    ``lanes_live`` / ``lanes_dead`` / ``lanes_dead_bytes``, the figures
    bench.py and tools/tick_profile.py print next to ``ir_bytes_est``
    (``dead_bytes`` is the slice of the byte estimate that moves lanes
    nothing ever reads — ROADMAP item 2's measured headroom). Thin
    delegation so cost consumers need only this module; the analysis
    itself lives in :mod:`.lane_liveness`. ``traced`` (a
    :func:`trace_tick` triple) and ``cost`` (its :func:`cost_of_jaxpr`
    report) skip the duplicate trace when the caller already computed
    them for the same model x sim."""
    from .lane_liveness import lane_stats
    return lane_stats(model, sim, traced=traced, cost=cost)


def tick_shard_stats(model, sim, mesh_size: int = 8,
                     cache=None) -> Dict[str, int]:
    """Sharded-communication stats of ``model``'s production chunk
    step under ``sim`` — ``collectives_per_tick`` (collective count in
    the scanned tick hot loop) and ``ici_bytes_est`` (estimated
    inter-chip bytes one shard moves per tick at ``mesh_size`` shards,
    ring-collective formulas), the figures bench.py prints next to the
    static-cost fields. Thin delegation so cost consumers need only
    this module; the analysis itself lives in :mod:`.shard_audit`.
    ``cache`` is the shared bench/lint trace cache — the sharded
    census rides it under a ``shard:``-prefixed key (this traces the
    SHARDED dispatch under an abstract mesh, so the plain
    :func:`trace_tick` entries cannot serve it)."""
    from .shard_audit import shard_stats
    return shard_stats(model, sim, mesh_size=mesh_size, cache=cache)


# --- post-compile cost: the thunk count -------------------------------------
#
# ``eqns`` measures the tick BEFORE XLA fusion — a deterministic,
# baseline-able regression signal. What the accelerator actually
# launches is the OPTIMIZED executable: one thunk per instruction in
# the entry computation (fusions collapse whole eqn neighborhoods into
# one), re-launched per iteration inside any surviving while loop. The
# functions below compile the same tick closure and count that —
# ``ir_thunks`` is the direct launch-overhead metric the ROADMAP's
# "~1000 XLA thunks/tick" ceiling is stated in. It is XLA-version- and
# backend-volatile, so it is SURFACED (bench metric lines,
# tools/tick_profile.py) but never baselined.


_HLO_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?[%\w][\w.\-]*\s*=\s")
_HLO_REGION_RE = re.compile(r"(?:body|condition)=%?([\w.\-]+)")


def hlo_exec_stats(compiled_text: str) -> Dict[str, int]:
    """Parse optimized-HLO text into the launch-overhead stats:

    - ``ir_thunks``: instructions in the ENTRY computation plus the
      while body/condition computations — the ops the runtime actually
      launches (fusion-internal instructions execute inside their
      fusion's single thunk and are excluded). While bodies are found
      by resolving each while op's ``body=``/``condition=`` attributes
      (their computation NAMES are XLA-version noise — ``region_NN``
      here, ``while_body`` elsewhere). While-resident instructions
      RE-launch every trip, so at equal counts a while-free executable
      is strictly cheaper — read ``ir_thunks`` next to ``while_loops``.
    - ``hlo_instructions``: whole-module instruction count.
    - ``while_loops``: surviving while ops (each is a fusion boundary
      and a per-trip relaunch of its body).
    """
    # pass 1: instruction count per computation + the loop computations
    counts: Dict[str, int] = {}
    entry_name = ""
    loop_regions: set = set()
    whiles = 0
    section = ""
    for line in compiled_text.splitlines():
        if line.endswith("{") and not line.startswith("  "):
            toks = line.split()
            name_tok = (toks[1] if toks and toks[0] == "ENTRY"
                        else toks[0] if toks else "")
            section = name_tok.lstrip("%").split("(")[0]
            if line.startswith("ENTRY "):
                entry_name = section
            counts.setdefault(section, 0)
            continue
        if _HLO_INSTR_RE.match(line):
            counts[section] = counts.get(section, 0) + 1
            if " while(" in line:
                whiles += 1
                loop_regions.update(_HLO_REGION_RE.findall(line))
    total = sum(counts.values())
    in_body = sum(c for name, c in counts.items()
                  if name in loop_regions)
    return {"ir_thunks": counts.get(entry_name, 0) + in_body,
            "hlo_instructions": total, "while_loops": whiles}


def compiled_tick_stats(model, sim, params=None) -> Dict[str, int]:
    """Lower + COMPILE one fused tick (abstract inputs, current JAX
    backend) and return :func:`hlo_exec_stats` of the executable."""
    import jax
    import jax.numpy as jnp
    from ..tpu.runtime import init_carry, make_tick_fn

    if params is None:
        params = model.make_params(sim.net.n_nodes)
    tick = make_tick_fn(model, sim, params)
    carry = jax.eval_shape(lambda: init_carry(model, sim, 0, params))
    sds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), carry)
    compiled = jax.jit(tick).lower(
        sds, jax.ShapeDtypeStruct((), jnp.int32)).compile()
    return hlo_exec_stats(compiled.as_text())


# --- the audited model universe -------------------------------------------


def cost_specs() -> List[Tuple[str, int]]:
    """Every registered model: the contract audit's workload table plus
    the registered buggy variants (the same universe CON2xx audits) —
    each is costed in BOTH carry layouts."""
    from .contract_audit import AUDIT_WORKLOADS, _buggy_workloads
    return list(AUDIT_WORKLOADS) + _buggy_workloads()


def entry_key(workload: str, node_count: int, layout: str) -> str:
    return f"{workload}/n={node_count}/{layout}"


# --- baseline io -----------------------------------------------------------


def toolchain_note(recorded: Optional[str], what: str,
                   re_record_flag: str = "--update-baseline",
                   ) -> Optional[str]:
    """The self-explaining staleness downgrade (ROADMAP accepted-debt
    item): recorded baselines/manifests are jax-version-dependent, so
    when the recording version differs from the running one, drift is
    expected toolchain movement — the gate downgrades to a warning that
    says exactly how to re-record instead of failing as if code
    regressed. Consumers: COST501/COST503 (cost baseline), LNE606
    (lane manifest, ``--update-manifest``), and ABS705 (range
    manifest, ``--update-ranges`` — a toolchain move self-explains
    "re-record with --update-ranges" instead of hard-failing). Returns
    ``None`` when versions match (or nothing was recorded), else the
    note to append to drift findings."""
    import jax
    if recorded is None or recorded == jax.__version__:
        return None
    return (f"recorded under jax {recorded}, this run is jax "
            f"{jax.__version__} — toolchain drift, not necessarily a "
            f"code regression; re-record {what} with {re_record_flag} "
            f"and commit the result")


def load_cost_baseline(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or DEFAULT_COST_BASELINE
    if not os.path.exists(path):
        return {"version": 1, "tolerance": DEFAULT_TOLERANCE,
                "entries": {}}
    with open(path) as f:
        data = json.load(f)
    data.setdefault("tolerance", DEFAULT_TOLERANCE)
    data.setdefault("entries", {})
    return data


def save_cost_baseline(entries: Dict[str, Dict[str, Any]],
                       path: Optional[str] = None,
                       tolerance: float = DEFAULT_TOLERANCE) -> str:
    import jax
    path = path or DEFAULT_COST_BASELINE
    payload = {
        "version": 1,
        "_comment": (
            "Per-model static tick-cost baseline for `maelstrom lint "
            "--cost` (doc/lint.md). Keys: <workload>/n=<nodes>/"
            "<layout>; eqns = recursive jaxpr equation count of one "
            "fused tick, hbm-bytes-per-tick = summed intermediate "
            "output bytes (scan bodies weighted by trip count), phases "
            "= eqn count per jax.named_scope phase, unattributed-eqns "
            "= eqns outside every KNOWN_SCOPES named scope (the "
            "COST505 scope-coverage budget — device-time profiler "
            "attribution goes blind past it). Regenerate after "
            "an INTENTIONAL cost change with `maelstrom lint --cost "
            "--update-baseline`; a PR that regresses any entry by more "
            "than `tolerance` fails the gate (COST501/COST505). "
            "jax-version records the tracing toolchain: under a "
            "different jax the gate downgrades drift to a re-record "
            "warning."),
        "jax-version": jax.__version__,
        "tolerance": tolerance,
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path
