"""Abstract-eval contract audit: ``jax.eval_shape`` over every model.

The scan tick loop only works if each model's traced methods are *shape
fixed points*: the carry pytree that leaves a tick must be structurally
identical (treedef + shapes + dtypes) to the one that entered, and the
lane constants a model declares (``body_lanes``, ``op_lanes``,
``ev_vals``, ``max_out``, ``tick_out``) must match what its traced
functions actually produce — a mismatch either fails late inside
``lax.scan`` with an opaque error, or (dtype drift) silently triggers a
recompile per tick. This pass traces every registered model abstractly
(no FLOPs, no device) across its declared workload configurations and
audits the contract up front, with ``file:line``-free but symbol-precise
findings.

Rules (CON2xx):

=======  ======================  ========  =================================
rule     name                    severity  what it checks
=======  ======================  ========  =================================
CON200   trace-failure           error     a traced method raised during
                                           abstract evaluation
CON201   carry-fixed-point       error     scan carry treedef/shape/dtype
                                           is a fixed point of the tick
CON202   emit-shape-contract     error     ``handle``/``tick`` return
                                           ``(max_out|tick_out, lanes)``
                                           int32 rows and preserve the row
                                           pytree
CON203   client-lane-contract    error     ``sample_op``/``encode_request``
                                           /``decode_reply(_wide)`` match
                                           ``op_lanes``/``lanes``/
                                           ``ev_vals``; event tensor width
                                           is ``2 + ev_vals``
CON204   int32-counter-overflow  error     runtime counters (NETID stamp,
                                           client op ids, delivery-priority
                                           horizon, declared flake-id
                                           splits) stay inside int32 within
                                           the tick horizon
=======  ======================  ========  =================================

``fused_node`` models (the raft family) have no legacy
``handle``/``tick`` pair — CON202 probes their compartmentalized
protocol instead (``node_rng`` -> ``inbox_step`` -> ``fused_tick``,
exactly what ``runtime.node_phase`` drives), with ``inbox_step``'s
single reply row checked against the ``(max_out=1, lanes)`` contract.

The tick horizon used by CON204 is ``TICK_HORIZON = 1 << 20``: the
delivery priority in ``tpu/netsim.py`` ranks messages by
``((1 << 20) - deliver_tick) * S``, so any simulation past 2^20 ticks
would silently stop delivering — ``make_sim_config`` enforces the same
bound at config time.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

from .findings import Finding, SEV_ERROR, SEV_WARNING

PASS_NAME = "contract"

# The hard tick ceiling implied by netsim's delivery priority encoding.
TICK_HORIZON = 1 << 20

INT32_MAX = 2**31 - 1

# (workload, node_count) pairs audited by the repo-wide run; buggy
# variants are appended dynamically from the model registries.
AUDIT_WORKLOADS: List[Tuple[str, int]] = [
    ("echo", 1), ("echo", 2),
    ("unique-ids", 3),
    ("broadcast", 5),
    ("g-set", 5),
    ("pn-counter", 3),
    ("g-counter", 3),
    ("lin-kv", 5),
    ("txn-list-append", 3),
    ("txn-rw-register", 3),
    ("kafka", 1),
]


def _buggy_workloads() -> List[Tuple[str, int]]:
    from ..models.raft_buggy import BUGGY_MODELS
    from ..models.txn_raft import TXN_BUGGY_MODELS
    from ..models.kafka import KAFKA_BUGGY_MODELS
    out = [(f"lin-kv-bug-{k}", 5) for k in BUGGY_MODELS]
    for k in TXN_BUGGY_MODELS:
        if k.startswith("rw-"):
            out.append((f"txn-rw-register-bug-{k[3:]}", 3))
        else:
            out.append((f"txn-list-append-bug-{k}", 3))
    out.extend((f"kafka-bug-{k}", 1) for k in KAFKA_BUGGY_MODELS)
    return out


def _model_path(model) -> str:
    mod = type(model).__module__
    return mod.replace(".", os.sep) + ".py"


def _leaf_sig(tree) -> List[Tuple[str, Tuple[int, ...], str]]:
    """(path, shape, dtype) per leaf, with key paths for messages."""
    import jax
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        keystr = jax.tree_util.keystr(path)
        out.append((keystr, tuple(leaf.shape), str(leaf.dtype)))
    return out


def _tree_mismatches(a, b) -> List[str]:
    """Human-readable structural differences between two abstract trees."""
    import jax
    ta = jax.tree_util.tree_structure(a)
    tb = jax.tree_util.tree_structure(b)
    if ta != tb:
        return [f"pytree structure changed: {ta} -> {tb}"]
    msgs = []
    for (ka, sa, da), (kb, sb, db) in zip(_leaf_sig(a), _leaf_sig(b)):
        if sa != sb:
            msgs.append(f"leaf {ka or '<root>'} shape {sa} -> {sb}")
        if da != db:
            msgs.append(f"leaf {ka or '<root>'} dtype {da} -> {db}")
    return msgs


def _audit_opts(node_count: int) -> dict:
    return dict(node_count=node_count, concurrency=2, time_limit=0.25,
                rate=50.0, latency=5.0, n_instances=4,
                record_instances=2, journal_instances=0, layout="lead")


def audit_model(model, node_count: int, label: Optional[str] = None,
                opts: Optional[dict] = None) -> List[Finding]:
    """Audit ONE model instance; testable entry point."""
    import jax
    import jax.numpy as jnp
    from ..tpu.harness import make_sim_config
    from ..tpu.runtime import init_carry, make_tick_fn

    label = label or getattr(model, "name", type(model).__name__)
    path = _model_path(model)
    cls = type(model).__name__
    findings: List[Finding] = []

    def flag(rule, name, message, severity=SEV_ERROR, symbol=cls):
        findings.append(Finding(
            rule=rule, name=name, severity=severity, pass_name=PASS_NAME,
            path=path, line=0, symbol=symbol,
            message=f"[{label}] {message}"))

    sim = make_sim_config(model, opts or _audit_opts(node_count))
    cfg = sim.net
    try:
        params = model.make_params(cfg.n_nodes)
    except Exception as e:
        flag("CON200", "trace-failure",
             f"make_params({cfg.n_nodes}) raised: {e!r}")
        return findings

    # --- CON202/CON203: per-method probes ---------------------------------
    # fused_node models speak the compartmentalized protocol ONLY (the
    # legacy handle()/tick() formulation was deleted after PR 6's soak
    # window): probe node_rng -> inbox_step -> fused_tick, the exact
    # methods runtime.node_phase drives, with inbox_step's single reply
    # row widened to the (max_out, lanes) contract shape
    fused = bool(getattr(model, "fused_node", False))

    def probe():
        key = jax.random.PRNGKey(0)
        row = model.init_row(cfg.n_nodes, jnp.int32(0), key, params)
        msg = jnp.zeros((cfg.lanes,), jnp.int32)
        if fused:
            mkeys = jax.vmap(
                lambda i: jax.random.fold_in(key, i))(
                jnp.arange(cfg.inbox_k + 1, dtype=jnp.int32))
            slot_rng, tick_rng = model.node_rng(mkeys)
            rng0 = jax.tree_util.tree_map(lambda a: a[0], slot_rng)
            row_h, outs = model.inbox_step(row, jnp.int32(0), msg,
                                           rng0, jnp.int32(0), cfg,
                                           params)
            outs = outs[None]     # one reply row per slot (max_out==1)
            row_t, touts = model.fused_tick(row, jnp.int32(0),
                                            jnp.int32(0), tick_rng,
                                            cfg, params)
        else:
            row_h, outs = model.handle(row, jnp.int32(0), msg,
                                       jnp.int32(0), key, cfg, params)
            row_t, touts = model.tick(row, jnp.int32(0), jnp.int32(0),
                                      key, cfg, params)
        state = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (cfg.n_nodes,) + a.shape), row)
        inv = model.invariants(state, cfg, params)
        op = model.sample_op(key, jnp.int32(0), cfg, params)
        fop = model.sample_final_op(key, jnp.int32(0), cfg, params)
        req = model.encode_request(op, jnp.int32(0), jnp.int32(0), key,
                                   cfg, params)
        if model.ev_vals == 4:
            et, val = model.decode_reply(op, msg, cfg, params)
        else:
            et, val = model.decode_reply_wide(op, msg, cfg, params)
        return dict(row=row, row_h=row_h, row_t=row_t, outs=outs,
                    touts=touts, inv=inv, op=op, fop=fop, req=req,
                    et=et, val=val)

    shapes = None
    try:
        shapes = jax.eval_shape(probe)
    except Exception as e:
        flag("CON200", "trace-failure",
             f"abstract evaluation of the model's traced methods "
             f"raised {type(e).__name__}: {e}")

    handle_name = "inbox_step" if fused else "handle"
    tick_name = "fused_tick" if fused else "tick"
    if shapes is not None:
        outs, touts = shapes["outs"], shapes["touts"]
        if tuple(outs.shape) != (model.max_out, cfg.lanes) \
                or str(outs.dtype) != "int32":
            flag("CON202", "emit-shape-contract",
                 symbol=f"{cls}.{handle_name}",
                 message=f"{handle_name}() emits {tuple(outs.shape)} "
                         f"{outs.dtype}, declared (max_out={model.max_out}"
                         f", lanes={cfg.lanes}) int32")
        if tuple(touts.shape) != (model.tick_out, cfg.lanes) \
                or str(touts.dtype) != "int32":
            flag("CON202", "emit-shape-contract",
                 symbol=f"{cls}.{tick_name}",
                 message=f"{tick_name}() emits {tuple(touts.shape)} "
                         f"{touts.dtype}, declared (tick_out="
                         f"{model.tick_out}, lanes={cfg.lanes}) int32")
        for which, after in ((handle_name, shapes["row_h"]),
                             (tick_name, shapes["row_t"])):
            for m in _tree_mismatches(shapes["row"], after):
                flag("CON202", "emit-shape-contract",
                     symbol=f"{cls}.{which}",
                     message=f"row pytree is not a fixed point of "
                             f"{which}(): {m}")
        inv = shapes["inv"]
        if tuple(inv.shape) != () or str(inv.dtype) not in ("bool",):
            flag("CON202", "emit-shape-contract",
                 symbol=f"{cls}.invariants",
                 message=f"invariants() returns {tuple(inv.shape)} "
                         f"{inv.dtype}, expected scalar bool")
        for which in ("op", "fop"):
            o = shapes[which]
            if tuple(o.shape) != (model.op_lanes,) \
                    or str(o.dtype) != "int32":
                flag("CON203", "client-lane-contract",
                     symbol=f"{cls}.sample_op",
                     message=f"{'sample_final_op' if which == 'fop' else 'sample_op'}"
                             f"() returns {tuple(o.shape)} {o.dtype}, "
                             f"declared op_lanes={model.op_lanes} int32")
        req = shapes["req"]
        if tuple(req.shape) != (cfg.lanes,) or str(req.dtype) != "int32":
            flag("CON203", "client-lane-contract",
                 symbol=f"{cls}.encode_request",
                 message=f"encode_request() returns {tuple(req.shape)} "
                         f"{req.dtype}, expected wire row "
                         f"({cfg.lanes},) int32")
        want_val = (3,) if model.ev_vals == 4 else (model.ev_vals,)
        val = shapes["val"]
        decoder = ("decode_reply" if model.ev_vals == 4
                   else "decode_reply_wide")
        if tuple(val.shape) != want_val:
            flag("CON203", "client-lane-contract",
                 symbol=f"{cls}.{decoder}",
                 message=f"{decoder}() value lanes are "
                         f"{tuple(val.shape)}, declared ev_vals="
                         f"{model.ev_vals} implies {want_val}")

    # --- CON201: full-tick carry fixed point ------------------------------
    try:
        carry0 = jax.eval_shape(lambda: init_carry(model, sim, 0, params))
        tick_fn = make_tick_fn(model, sim, params)
        carry1, ys = jax.eval_shape(
            tick_fn, carry0, jax.ShapeDtypeStruct((), jnp.int32))
        for m in _tree_mismatches(carry0, carry1):
            flag("CON201", "carry-fixed-point",
                 message=f"scan carry is not a fixed point of the tick: "
                         f"{m}")
        ev = ys.events
        want_ev = (sim.record_instances, sim.client.n_clients, 2,
                   2 + model.ev_vals)
        if tuple(ev.shape) != want_ev:
            flag("CON203", "client-lane-contract",
                 message=f"per-tick event tensor is {tuple(ev.shape)}, "
                         f"declared ev_vals={model.ev_vals} implies "
                         f"{want_ev}")
    except Exception as e:
        flag("CON200", "trace-failure",
             f"abstract evaluation of the full tick raised "
             f"{type(e).__name__}: {e}")

    # --- CON204: int32 counter bounds at the tick horizon -----------------
    N, C, K = cfg.n_nodes, cfg.n_clients, cfg.inbox_k
    fanout = N * (K * model.max_out + model.tick_out) + C
    netid_max = TICK_HORIZON * fanout
    if netid_max > INT32_MAX:
        flag("CON204", "int32-counter-overflow",
             message=f"NETID stamp t * fanout ({fanout}/tick) reaches "
                     f"{netid_max} at the {TICK_HORIZON}-tick horizon "
                     f"> int32 max — journal send/recv pairing breaks")
    uniq_max = TICK_HORIZON * C + C
    if uniq_max > INT32_MAX:
        flag("CON204", "int32-counter-overflow",
             message=f"client op counter `uniq` (next_msg_id * {C} "
                     f"clients) reaches {uniq_max} at the horizon "
                     f"> int32 max — minted values collide")
    if sim.n_ticks > TICK_HORIZON:
        flag("CON204", "int32-counter-overflow",
             message=f"n_ticks={sim.n_ticks} exceeds the delivery-"
                     f"priority horizon {TICK_HORIZON} — messages past "
                     f"it rank negative and are never delivered")
    # models that partition an int32 id space declare the split
    bits = getattr(model, "flake_counter_bits", None)
    if bits is not None:
        per_node_max = TICK_HORIZON * K * model.max_out
        if per_node_max > (1 << bits):
            flag("CON204", "int32-counter-overflow",
                 message=f"flake counter field is {bits} bits but a "
                         f"node can handle {per_node_max} requests "
                         f"within the {TICK_HORIZON}-tick horizon — "
                         f"ids from different nodes collide past "
                         f"2^{bits} ops")
        if N << bits > INT32_MAX:
            flag("CON204", "int32-counter-overflow",
                 message=f"node_idx << {bits} overflows int32 at "
                         f"node_count={N}")
    return findings


def run_contract_audit(repo_root: str = ".",
                       workloads: Optional[List[Tuple[str, int]]] = None
                       ) -> List[Finding]:
    from ..models import get_model

    specs = list(workloads) if workloads is not None else (
        AUDIT_WORKLOADS + _buggy_workloads())
    findings: List[Finding] = []
    for workload, n in specs:
        try:
            model = get_model(workload, n, "grid")
        except Exception as e:
            findings.append(Finding(
                rule="CON200", name="trace-failure", severity=SEV_ERROR,
                pass_name=PASS_NAME, path="maelstrom_tpu/models/"
                "__init__.py", line=0, symbol="get_model",
                message=f"get_model({workload!r}, {n}) raised: {e!r}"))
            continue
        findings.extend(audit_model(model, n, label=f"{workload}/n={n}"))
    return findings
