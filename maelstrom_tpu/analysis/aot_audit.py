"""Certified-executable auditor: ``maelstrom lint --aot`` (pass 9).

The AOT store (``tpu/aot_store.py``) lets the fleet dispatch serialized
executables without re-tracing — which makes the store itself a new
attack surface for silent drift: a stored binary whose source has moved
on, whose donation aliasing was lost in serialization, or whose
collective census no longer matches what the SPMD auditor certified
would run WRONG (or wasteful) code with no compile step left to catch
it. This pass closes that loop statically:

- For the donation subjects (``ir_lint.DONATION_WORKLOAD`` x BOTH carry
  layouts pipelined, plus the lead layout sharded on a 1-device mesh —
  the same executables JXP403 and SHD804 already certify) it re-derives
  the **canonical jaxpr digest** from current source (``aot_store.
  jaxpr_digest`` of the ACTUAL production chunk dispatch, no compile
  needed) and pins it in the checked-in, jax-version-stamped
  ``analysis/aot_manifest.json``.
- Every entry of the on-disk store (the compile cache's ``.aot``
  sibling by default, or ``--aot-store DIR``) is audited: payload
  bytes re-hashed against the recorded sha, recorded toolchain /
  device kind matched against the running one, the stored fingerprint
  compared to the digest current source traces to, the executable
  DESERIALIZED and its ``input_output_alias`` re-verified, and its
  HLO collective census checked against what ``shard_manifest.json``
  promises (a collective kind the SPMD auditor never certified must
  not hide inside a stored binary).

Rules (EXE9xx):

=======  ===========================  ========  ========================
rule     name                         severity  what it flags
=======  ===========================  ========  ========================
EXE900   aot-manifest-updated         info      ``--update-aot``
                                                rewrote the manifest
EXE901   executable-fingerprint-      error     a stored / manifested
         drift                                  fingerprint no longer
                                                matches the jaxpr the
                                                current source traces
                                                to (or a payload whose
                                                bytes fail their
                                                recorded sha — tamper)
EXE902   donation-lost-in-stored-     error     the DESERIALIZED
         executable                             executable dropped
                                                input_output_alias on
                                                donated carry leaves
EXE903   stored-collective-census-    error     the stored HLO contains
         drift                                  a collective kind the
                                                shard manifest never
                                                certified (pipelined
                                                entries: any collective
                                                at all)
EXE904   toolchain-incompatible-      error     an entry recorded under
         entry                                  a different jax version
                                                / platform / device
                                                kind — refused by name;
                                                the runtime treats it
                                                as a miss
EXE905   aot-manifest-missing         error     an audit subject has no
                                                manifest entry
EXE906   aot-manifest-stale           warning   a manifest entry
                                                matches no audit
                                                subject
=======  ===========================  ========  ========================

``--update-aot`` re-records the manifest from current source (traces
only — cheap); given an explicit ``--aot-store DIR`` it ALSO compiles
the subjects and populates that store, which is how ``tools/
lint_gate.sh`` builds the throwaway store its tamper canary then
corrupts, and how ``tools/tpu_opportunist.sh`` pre-warms a fleet store
in a healthy-TPU window. A store entry that is merely ABSENT is never a
finding — the store is a cache and a fresh checkout must lint green.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any, Dict, List, Optional, Tuple

from . import cost_model
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "aot"

DEFAULT_AOT_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "aot_manifest.json")

# chunk length the audit subjects are lowered at — matches the donation
# audit (ir_lint) and the shard census so all three passes certify the
# same specialization
AOT_CHUNK_LEN = 4

# the donation audit's cap/unroll (ir_lint.audit_pipeline_donation):
# the subjects ARE that audit's executables, re-derived here
AOT_CAP = 64
AOT_UNROLL = 1

# mesh size of the sharded audit subject: 1 device, so the subject
# compiles (and its store entry populates) on any host — the census
# structure is size-invariant (verified by shard_audit per run)
AOT_MESH_SIZE = 1

_PIPELINE_PATH = "maelstrom_tpu/tpu/pipeline.py"
_MESH_PATH = "maelstrom_tpu/parallel/mesh.py"
_STORE_PATH = "maelstrom_tpu/tpu/aot_store.py"
_MANIFEST_REPO_PATH = "maelstrom_tpu/analysis/aot_manifest.json"

# jaxpr collective primitive -> optimized-HLO op kind: the bridge
# between shard_manifest.json's census (jaxpr names) and a stored
# executable's census (HLO names). XLA may ELIDE a promised collective
# (1-device mesh folds all-reduces away), so the gate is one-sided: an
# HLO kind with no promising primitive is drift, an un-realized promise
# is not.
_JAXPR_TO_HLO = {
    "psum": "all-reduce", "pmax": "all-reduce", "pmin": "all-reduce",
    "all_gather": "all-gather", "pgather": "all-gather",
    "psum_scatter": "reduce-scatter", "reduce_scatter": "reduce-scatter",
    "ppermute": "collective-permute", "all_to_all": "all-to-all",
}


def _finding(rule, name, severity, path, symbol, message) -> Finding:
    return Finding(rule=rule, name=name, severity=severity,
                   pass_name=PASS_NAME, path=path, line=0,
                   symbol=symbol, message=message)


# --- the audit subjects -----------------------------------------------------


def audit_subjects(layouts=cost_model.AUDIT_LAYOUTS
                   ) -> List[Dict[str, Any]]:
    """Build (without tracing) the subject list: per subject the model,
    sim, label, kind, and the anchor (path, symbol) its findings point
    at."""
    from .ir_lint import DONATION_WORKLOAD
    from ..models import get_model

    wl, n = DONATION_WORKLOAD
    subjects: List[Dict[str, Any]] = []
    for layout in layouts:
        model = get_model(wl, n)
        sim = cost_model.audit_sim(model, n, layout)
        subjects.append({
            "model": model, "sim": sim, "kind": "pipelined",
            "label": f"{wl}/n={n}/{layout}/pipelined",
            "path": _PIPELINE_PATH, "symbol": "make_chunk_fn"})
    model = get_model(wl, n)
    sim = cost_model.audit_sim(model, n, "lead")
    subjects.append({
        "model": model, "sim": sim, "kind": "sharded",
        "label": f"{wl}/n={n}/lead/sharded/s={AOT_MESH_SIZE}",
        "path": _MESH_PATH, "symbol": "make_sharded_chunk_fn"})
    return subjects


def _pipelined_lowerable(model, sim):
    """The jitted pipelined chunk dispatch + its abstract arguments —
    exactly what ``wrap_pipelined`` keys and compiles."""
    from . import ir_lint
    from ..tpu import pipeline
    from ..tpu.runtime import default_instance_ids

    params, carry_sds, t_sds = ir_lint._donation_args(model, sim)
    iids = default_instance_ids(sim)
    chunk_fn = pipeline.make_chunk_fn(model, sim, params, iids,
                                      AOT_CAP, AOT_UNROLL)
    from ..tpu.aot_store import pipelined_signature
    sig = pipelined_signature(model, sim, params, iids, AOT_CAP,
                              AOT_UNROLL, pipeline.DEFAULT_SCAN_TOP_K,
                              AOT_CHUNK_LEN, carry_sds)
    return chunk_fn, (carry_sds, t_sds), sig


def _sharded_lowerable(model, sim):
    """The jitted sharded chunk dispatch on a real 1-device mesh + its
    abstract arguments — what ``wrap_sharded`` keys and compiles. A
    real (not abstract) mesh so the traced jaxpr matches what a
    populate on this host records, and so ``--update-aot`` can
    actually compile it."""
    import jax
    import jax.numpy as jnp
    from ..parallel import mesh as mesh_mod

    params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)
    mesh = mesh_mod.make_mesh(AOT_MESH_SIZE)
    chunk_fn, _ = mesh_mod.make_sharded_chunk_fn(model, sim, mesh,
                                                 params)
    wire = mesh_mod.wire_template(model, sim, mesh)
    wsds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), wire)
    psds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                       jnp.asarray(l).dtype), params)
    t_sds = jax.ShapeDtypeStruct((), jnp.int32)
    from ..tpu.aot_store import sharded_signature
    sig = sharded_signature(model, sim, mesh, psds,
                            mesh_mod.DEFAULT_SCAN_TOP_K,
                            AOT_CHUNK_LEN, wsds)
    return chunk_fn, (wsds, t_sds, psds), sig


def trace_subject(subject: Dict[str, Any]
                  ) -> Tuple[Any, Tuple[Any, ...], Dict[str, Any], str]:
    """Lower one subject to ``(chunk_fn, abstract_args, store_sig,
    jaxpr_digest)`` — trace only, no compile."""
    import jax
    from ..tpu.aot_store import jaxpr_digest

    if subject["kind"] == "pipelined":
        chunk_fn, args, sig = _pipelined_lowerable(subject["model"],
                                                   subject["sim"])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            closed = jax.make_jaxpr(
                lambda c, t: chunk_fn(c, t, length=AOT_CHUNK_LEN))(*args)
    else:
        chunk_fn, args, sig = _sharded_lowerable(subject["model"],
                                                 subject["sim"])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            closed = jax.make_jaxpr(
                lambda w, t, p: chunk_fn(w, t, p,
                                         length=AOT_CHUNK_LEN))(*args)
    return chunk_fn, args, sig, jaxpr_digest(closed)


def live_entries(subjects: Optional[List[Dict[str, Any]]] = None,
                 trace_cache=None) -> Tuple[Dict[str, Dict[str, Any]],
                                            Dict[str, Tuple[str, str]],
                                            List[Finding]]:
    """Trace every subject into the manifest-shaped live map
    ``label -> {jaxpr-digest, chunk-length, donated-leaves, kind}``;
    returns ``(live, anchors, failures)``. The lowered subjects ride
    ``trace_cache`` under ``aot:<label>`` keys so ``--update-aot`` with
    a store does not re-trace what this sweep already paid for."""
    import jax

    subjects = audit_subjects() if subjects is None else subjects
    live: Dict[str, Dict[str, Any]] = {}
    anchors: Dict[str, Tuple[str, str]] = {}
    failures: List[Finding] = []
    for subject in subjects:
        label = subject["label"]
        cached = (trace_cache.get("aot:" + label)
                  if trace_cache is not None else None)
        try:
            if cached is None:
                cached = trace_subject(subject)
                if trace_cache is not None:
                    trace_cache["aot:" + label] = cached
        except Exception as e:
            failures.append(_finding(
                "EXE901", "executable-fingerprint-drift", SEV_ERROR,
                subject["path"], subject["symbol"],
                f"[{label}] lowering the audit subject raised "
                f"{type(e).__name__}: {e} — the production dispatch "
                f"no longer traces, so no stored executable for it can "
                f"be certified"))
            continue
        _fn, args, _sig, digest = cached
        live[label] = {
            "jaxpr-digest": digest,
            "chunk-length": AOT_CHUNK_LEN,
            "donated-leaves": len(jax.tree.leaves(args[0])),
            "kind": subject["kind"],
        }
        anchors[label] = (subject["path"], subject["symbol"])
    return live, anchors, failures


# --- manifest io + compare --------------------------------------------------


def load_aot_manifest(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or DEFAULT_AOT_MANIFEST
    if not os.path.exists(path):
        return {"version": 1, "entries": {}}
    with open(path) as f:
        data = json.load(f)
    data.setdefault("entries", {})
    return data


def save_aot_manifest(entries: Dict[str, Dict[str, Any]],
                      path: Optional[str] = None) -> str:
    import jax
    path = path or DEFAULT_AOT_MANIFEST
    payload = {
        "version": 1,
        "_comment": (
            "Canonical jaxpr digests of the AOT-certified production "
            "dispatch executables for `maelstrom lint --aot` "
            "(doc/lint.md). Keys: <workload>/n=<nodes>/<layout>/"
            "<pipelined|sharded>[/s=<mesh>]; jaxpr-digest = "
            "aot_store.jaxpr_digest of the chunk dispatch traced from "
            "current source at chunk-length ticks. A stored executable "
            "(or this manifest) whose digest no longer matches current "
            "source fails the gate (EXE901). Regenerate after an "
            "INTENTIONAL dispatch change with `maelstrom lint --aot "
            "--update-aot`. jax-version records the tracing toolchain: "
            "under a different jax the gate downgrades drift to a "
            "re-record warning."),
        "jax-version": jax.__version__,
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path


def compare_manifest(live: Dict[str, Dict[str, Any]],
                     manifest: Dict[str, Any],
                     anchors: Dict[str, Tuple[str, str]]
                     ) -> List[Finding]:
    """EXE905/901/906 — diff the live digests against the checked-in
    manifest."""
    entries = manifest.get("entries", {})
    note = cost_model.toolchain_note(manifest.get("jax-version"),
                                     "the AOT manifest", "--update-aot")
    findings: List[Finding] = []
    for label in sorted(live):
        path, symbol = anchors[label]
        base = entries.get(label)
        if base is None:
            findings.append(_finding(
                "EXE905", "aot-manifest-missing", SEV_ERROR, path,
                symbol,
                f"[{label}] no AOT-manifest entry — record one with "
                f"`maelstrom lint --aot --update-aot`"))
            continue
        if base.get("jaxpr-digest") != live[label]["jaxpr-digest"]:
            findings.append(_finding(
                "EXE901", "executable-fingerprint-drift",
                SEV_WARNING if note else SEV_ERROR, path, symbol,
                f"[{label}] the jaxpr current source traces to "
                f"({live[label]['jaxpr-digest']}) no longer matches "
                f"the certified manifest digest "
                f"({base.get('jaxpr-digest')}) — the production "
                f"dispatch changed; stored executables keyed on the "
                f"old source would run different code than a fresh "
                f"compile. If intentional, re-record with "
                f"--update-aot and justify it in the PR"
                + (f" ({note})" if note else "")))
    for label in sorted(set(entries) - set(live)):
        findings.append(_finding(
            "EXE906", "aot-manifest-stale", SEV_WARNING,
            _MANIFEST_REPO_PATH, "",
            f"[{label}] manifest entry matches no audit subject — "
            f"remove or re-record it"))
    return findings


# --- the store audit --------------------------------------------------------


def _entry_anchor(meta: Dict[str, Any]) -> Tuple[str, str]:
    if meta.get("kind") == "sharded":
        return _MESH_PATH, "make_sharded_chunk_fn"
    return _PIPELINE_PATH, "make_chunk_fn"


def _promised_hlo_kinds(meta: Dict[str, Any]) -> Optional[set]:
    """The HLO collective kinds the shard manifest certifies for this
    entry (empty set for pipelined entries — a single-device executable
    has no business containing ICI ops). ``None`` when the entry's
    sharded config has no shard-manifest entry to judge against."""
    if meta.get("kind") != "sharded":
        return set()
    # entry label <wl>/n=<n>/<layout>/sharded/s=<size> -> shard
    # manifest key <wl>/n=<n>/<layout>/s=<size>
    parts = (meta.get("entry") or "").split("/")
    if len(parts) != 5:
        return None
    shard_key = "/".join(parts[:3] + parts[4:])
    from .shard_audit import load_shard_manifest
    entry = load_shard_manifest().get("entries", {}).get(shard_key)
    if entry is None:
        return None
    prims = set(entry.get("tick-collectives", {})) \
        | set(entry.get("dispatch-collectives", {}))
    return {_JAXPR_TO_HLO[p] for p in prims if p in _JAXPR_TO_HLO}


def audit_store(store_dir: str, live: Dict[str, Dict[str, Any]]
                ) -> List[Finding]:
    """EXE901/902/903/904 over every entry of one on-disk store."""
    import jax
    from .ir_lint import aliased_params_of
    from ..tpu.aot_store import AotStore, _device_sig

    store = AotStore(store_dir)
    platform, kind = _device_sig()
    findings: List[Finding] = []
    for key, meta in store.entries():
        entry = meta.get("entry", key)
        path, symbol = _entry_anchor(meta)
        where = f"store entry {key} ({entry}) in {store_dir}"

        # EXE904: a foreign toolchain's binary — refused by name, never
        # deserialized (the runtime face already treats it as a miss)
        mismatches = [
            f"{field} {meta.get(field)!r} != {cur!r}"
            for field, cur in (("jax-version", jax.__version__),
                               ("platform", platform),
                               ("device-kind", kind))
            if meta.get(field) != cur]
        if mismatches:
            findings.append(_finding(
                "EXE904", "toolchain-incompatible-entry", SEV_ERROR,
                _STORE_PATH, "AotStore",
                f"{where}: recorded toolchain no longer matches the "
                f"running one ({'; '.join(mismatches)}) — the runtime "
                f"refuses this entry by name (treated as a miss); "
                f"delete it or re-populate with `maelstrom lint --aot "
                f"--update-aot --aot-store {store_dir}`"))
            continue

        # EXE901 (payload face): bytes must still hash to the recorded
        # sha — a flipped byte anywhere in the binary is a tamper
        triple = store.load_payload(key)
        if triple is None:
            findings.append(_finding(
                "EXE901", "executable-fingerprint-drift", SEV_ERROR,
                _STORE_PATH, "AotStore",
                f"{where}: serialized payload is missing, unreadable, "
                f"or no longer matches its recorded sha256 — the entry "
                f"was tampered with or truncated; the runtime refuses "
                f"it, delete and re-populate"))
            continue

        # EXE901 (source face): the certified fingerprint vs the jaxpr
        # current source traces to — only decidable for entries lowered
        # at the audit specialization
        fp = meta.get("fingerprint", {})
        subject = live.get(entry)
        if (subject is not None
                and fp.get("chunk-length") == subject["chunk-length"]
                and fp.get("jaxpr-digest")
                != subject["jaxpr-digest"]):
            findings.append(_finding(
                "EXE901", "executable-fingerprint-drift", SEV_ERROR,
                path, symbol,
                f"{where}: stored fingerprint "
                f"{fp.get('jaxpr-digest')} no longer matches the jaxpr "
                f"current source traces to "
                f"({subject['jaxpr-digest']}) — the store would "
                f"dispatch code the current tree does not describe; "
                f"delete the entry or re-populate with --update-aot"))

        # EXE903: collective kinds in the stored HLO that the SPMD
        # auditor never certified (one-sided: XLA may elide a promised
        # collective, it must never ADD one)
        promised = _promised_hlo_kinds(meta)
        if promised is not None:
            smuggled = sorted(set(meta.get("collectives", {}))
                              - promised)
            if smuggled:
                findings.append(_finding(
                    "EXE903", "stored-collective-census-drift",
                    SEV_ERROR, path, symbol,
                    f"{where}: stored executable contains collective "
                    f"op(s) {smuggled} that "
                    + ("a single-device pipelined dispatch must not "
                       "contain at all"
                       if meta.get("kind") != "sharded" else
                       "shard_manifest.json does not certify for this "
                       "config")
                    + " — new ICI traffic smuggled in through the "
                      "store; re-run `maelstrom lint --shard` and "
                      "re-populate"))

        # EXE902: donation on the DESERIALIZED executable — serialize/
        # deserialize must not drop input_output_alias, or every store
        # hit silently doubles carry HBM
        want = int(meta.get("donated-leaves", 0) or 0)
        if want <= 0:
            continue
        try:
            from jax.experimental import serialize_executable
            loaded = serialize_executable.deserialize_and_load(*triple)
            aliased = aliased_params_of(loaded.as_text())
        except Exception as e:
            findings.append(_finding(
                "EXE902", "donation-lost-in-stored-executable",
                SEV_WARNING, path, symbol,
                f"{where}: could not deserialize for donation "
                f"re-verification ({type(e).__name__}: {e}) — "
                f"delete the entry or re-populate"))
            continue
        missing = sorted(set(range(want)) - aliased)
        if missing:
            findings.append(_finding(
                "EXE902", "donation-lost-in-stored-executable",
                SEV_ERROR, path, symbol,
                f"{where}: {len(missing)} of {want} donated carry "
                f"leaves lost input_output_alias in the DESERIALIZED "
                f"executable (flat param indices {missing[:8]}"
                f"{'...' if len(missing) > 8 else ''}) — donation "
                f"certified at compile time does not survive this "
                f"entry; every store hit would double carry HBM. "
                f"Delete the entry and re-populate"))
    return findings


# --- populate (--update-aot --aot-store DIR) --------------------------------


def populate_store(store_dir: str, subjects: List[Dict[str, Any]],
                   trace_cache=None) -> Dict[str, str]:
    """Compile every audit subject and write its store entry —
    ``lint_gate.sh``'s canary store and ``tpu_opportunist.sh``'s fleet
    pre-warm both come through here. Returns ``label -> key`` for what
    was written (a subject whose executable does not serialize on this
    backend is skipped, not fatal)."""
    import jax
    from ..tpu.aot_store import (AotStore, build_meta, entry_label,
                                 store_key)

    store = AotStore(store_dir)
    written: Dict[str, str] = {}
    for subject in subjects:
        label = subject["label"]
        cached = (trace_cache.get("aot:" + label)
                  if trace_cache is not None else None)
        try:
            if cached is None:
                cached = trace_subject(subject)
                if trace_cache is not None:
                    trace_cache["aot:" + label] = cached
            chunk_fn, args, sig, digest = cached
            from ..tpu.aot_store import _uncached_compile
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                with _uncached_compile():
                    compiled = chunk_fn.lower(
                        *args, length=AOT_CHUNK_LEN).compile()
            key = store_key(sig)
            meta = build_meta(
                sig, key,
                entry_label(subject["model"], subject["sim"],
                            subject["kind"],
                            mesh_size=AOT_MESH_SIZE
                            if subject["kind"] == "sharded" else None),
                digest, compiled,
                donated_leaves=len(jax.tree.leaves(args[0])))
            if store.put(key, compiled, meta):
                written[label] = key
        except Exception:
            continue
    return written


# --- orchestration ----------------------------------------------------------


def run_aot_lint(repo_root: str = ".",
                 manifest_path: Optional[str] = None,
                 update_manifest: bool = False,
                 store_path: Optional[str] = None,
                 trace_cache=None) -> List[Finding]:
    """The aot pass: trace the audit subjects, gate the checked-in
    digest manifest (or re-record it under ``update_manifest``), and
    audit every entry of the resolved store. ``store_path=None`` rides
    the default compile-cache sibling; an absent store dir audits
    nothing (the store is a cache — a fresh checkout is green).
    ``update_manifest`` with an EXPLICIT ``store_path`` also compiles
    the subjects and populates that store."""
    from ..tpu.aot_store import resolve_store_dir

    subjects = audit_subjects()
    live, anchors, findings = live_entries(subjects,
                                           trace_cache=trace_cache)

    if update_manifest:
        path = save_aot_manifest(live, manifest_path)
        n_store = 0
        resolved = (resolve_store_dir(store_path)
                    if store_path is not None else None)
        if resolved is not None:
            n_store = len(populate_store(resolved, subjects,
                                         trace_cache=trace_cache))
        findings.append(_finding(
            "EXE900", "aot-manifest-updated", SEV_INFO,
            os.path.relpath(path, os.path.abspath(repo_root))
            if os.path.isabs(path) else path, "",
            f"recorded {len(live)} AOT-manifest entr"
            f"{'y' if len(live) == 1 else 'ies'}"
            + (f" and populated {n_store} store entr"
               f"{'y' if n_store == 1 else 'ies'} in {resolved}"
               if n_store else "")))
        return findings

    manifest = load_aot_manifest(manifest_path)
    findings.extend(compare_manifest(live, manifest, anchors))
    resolved = resolve_store_dir(store_path)
    if resolved is not None and os.path.isdir(resolved):
        findings.extend(audit_store(resolved, live))
    return findings
