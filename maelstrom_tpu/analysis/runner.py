"""Orchestration for ``maelstrom lint``: run passes, apply the baseline.

``run_lint`` is the programmatic face of the CLI subcommand: pick
passes, collect findings, split them into live / baselined / stale, and
hand back a :class:`~.findings.LintReport`. Exit-code policy lives in
``cli.cmd_lint``: ``--strict`` fails on any unsuppressed error-severity
finding.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from .findings import (Baseline, DEFAULT_BASELINE, Finding, LintReport,
                       sort_findings)

ALL_PASSES = ("trace", "contract", "schema")

# opt-in passes: the IR hazard audit, the cost gate, the lane-liveness
# slice, the value-range abstract interpreter, the SPMD shard auditor,
# and the AOT executable-store certifier trace (and, for
# JXP403/SHD804/EXE902, compile) every registered model — tens of
# seconds to minutes, so they run only when named (`--ir` / `--cost` /
# `--lanes` / `--ranges` / `--shard` / `--aot` / `--pass ir`), never
# as part of the default sweep
EXTRA_PASSES = ("ir", "cost", "lanes", "ranges", "shard", "aot")


def run_lint(repo_root: str = ".",
             passes: Optional[Sequence[str]] = None,
             paths: Optional[List[str]] = None,
             baseline_path: Optional[str] = DEFAULT_BASELINE,
             cost_baseline_path: Optional[str] = None,
             update_cost_baseline: bool = False,
             lane_manifest_path: Optional[str] = None,
             update_lane_manifest: bool = False,
             range_manifest_path: Optional[str] = None,
             update_range_manifest: bool = False,
             ranges_horizon_log2: Optional[int] = None,
             shard_manifest_path: Optional[str] = None,
             update_shard_manifest: bool = False,
             aot_manifest_path: Optional[str] = None,
             update_aot_manifest: bool = False,
             aot_store_path: Optional[str] = None,
             ) -> LintReport:
    """Run the requested passes and fold in the baseline.

    ``passes=None`` means "every default pass" (trace/contract/schema;
    the IR + cost passes are opt-in) — unless ``paths`` restricts the
    run to explicit files, in which case only the trace pass runs by
    default (pointing the linter at a file means "lint this file", not
    "re-audit the world"). Passes named explicitly always run.
    ``baseline_path=None`` disables baseline suppression entirely.
    ``cost_baseline_path`` / ``update_cost_baseline`` parameterize the
    cost pass (analysis/cost_baseline.json by default);
    ``lane_manifest_path`` / ``update_lane_manifest`` the lanes pass
    (analysis/lane_manifest.json); ``range_manifest_path`` /
    ``update_range_manifest`` / ``ranges_horizon_log2`` the ranges
    pass (analysis/range_manifest.json; the horizon override is the
    lint_gate canary's synthetic overflow budget);
    ``shard_manifest_path`` / ``update_shard_manifest`` the shard pass
    (analysis/shard_manifest.json); ``aot_manifest_path`` /
    ``update_aot_manifest`` / ``aot_store_path`` the AOT
    executable-store certifier (analysis/aot_manifest.json; the store
    path defaults to the compile cache's ``.aot`` sibling).
    """
    repo_root = os.path.abspath(repo_root)
    findings: List[Finding] = []
    if passes is not None:
        effective = tuple(passes)
    elif paths is not None:
        effective = ("trace",)
    else:
        effective = ALL_PASSES

    files_scanned = 0
    if "trace" in effective:
        from .trace_lint import default_trace_targets, run_trace_lint
        targets = paths if paths else default_trace_targets(repo_root)
        files_scanned += len(targets)
        findings.extend(run_trace_lint(repo_root, paths=targets))
    if "contract" in effective:
        from .contract_audit import run_contract_audit
        findings.extend(run_contract_audit(repo_root))
    if "schema" in effective:
        from .schema_lint import run_schema_lint
        findings.extend(run_schema_lint(repo_root))
    # the ir/cost and lanes passes each trace every registered model x
    # layout; a shared cache makes the combined gate pay that jaxpr
    # sweep once
    trace_cache: dict = {}
    if "ir" in effective or "cost" in effective:
        from .ir_lint import run_ir_lint
        findings.extend(run_ir_lint(
            repo_root,
            hazards="ir" in effective,
            cost="cost" in effective,
            cost_baseline_path=cost_baseline_path,
            update_baseline=update_cost_baseline,
            trace_cache=trace_cache))
    if "lanes" in effective:
        from .lane_liveness import run_lane_lint
        findings.extend(run_lane_lint(
            repo_root,
            manifest_path=lane_manifest_path,
            update_manifest=update_lane_manifest,
            trace_cache=trace_cache))
    if "ranges" in effective:
        from .absint import run_range_lint
        findings.extend(run_range_lint(
            repo_root,
            manifest_path=range_manifest_path,
            update_manifest=update_range_manifest,
            trace_cache=trace_cache,
            probe_log2=ranges_horizon_log2))
    if "shard" in effective:
        from .shard_audit import run_shard_lint
        findings.extend(run_shard_lint(
            repo_root,
            manifest_path=shard_manifest_path,
            update_manifest=update_shard_manifest,
            trace_cache=trace_cache))
    if "aot" in effective:
        from .aot_audit import run_aot_lint
        findings.extend(run_aot_lint(
            repo_root,
            manifest_path=aot_manifest_path,
            update_manifest=update_aot_manifest,
            store_path=aot_store_path,
            trace_cache=trace_cache))

    baseline = (Baseline.load(baseline_path) if baseline_path
                else Baseline())
    live, suppressed = [], []
    for f in sort_findings(findings):
        entry = baseline.match(f)
        if entry is not None:
            suppressed.append((f, entry))
        else:
            live.append(f)
    # staleness is only meaningful for a full-scope run: a partial
    # invocation (--pass / explicit paths) never sees the findings that
    # out-of-scope baseline entries suppress, and reporting those as
    # stale would tell the user to delete live entries. Staleness is
    # also PASS-scoped (findings.fingerprint_pass): a default run must
    # not report the ir/cost entries as stale just because those
    # opt-in passes did not run.
    full_scope = set(ALL_PASSES) <= set(effective) and paths is None
    return LintReport(findings=live, suppressed=suppressed,
                      stale=baseline.stale_entries(set(effective))
                      if full_scope else [],
                      files_scanned=files_scanned,
                      passes_run=effective)
