"""SPMD partition auditor: ``maelstrom lint --shard`` (pass 8).

The seven existing passes audit the *single-chip* tick exhaustively,
but none of them ever lowers the SHARDED path with real shardings — a
shard-unsafe refactor (an accidental cross-shard gather, a silently
replicated per-instance leaf, a new collective in the hot loop) would
sail through ``maelstrom lint --strict`` and only surface in a rare
healthy-TPU window. This pass closes that hole statically, no TPU (and
no devices at all) required:

- For every registered model x BOTH carry layouts it AOT-lowers the
  ACTUAL sharded production step — ``parallel/mesh.py::
  make_sharded_chunk_fn``, the same executable the donation audit
  compiles (PR-5 precedent) — under an **abstract mesh**
  (``jax.sharding.AbstractMesh``: carries axis names and sizes, binds
  no devices, and ``shard_map``/``jit.lower`` trace under it on this
  toolchain) and takes a **collective census** of the partitioned
  jaxpr: per-collective counts and payload bytes, split into the tick
  hot loop (inside the scanned tick body, scan-trip-weighted like the
  PR-5 cost model) vs per-dispatch plumbing.
- The census is converted into an **ICI-bytes-per-tick estimate** per
  mesh size in {1, 2, 4, 8} (ring-algorithm formulas, documented at
  :func:`ici_bytes_of`) and pinned in the checked-in
  ``analysis/shard_manifest.json`` — drift beyond the tolerance fails
  the gate, with the ``toolchain_note`` downgrade when the manifest
  was recorded under a different jax version.
- The pass is **load-bearing for cross-mesh resume**: per model x
  layout it derives the wire carry's per-leaf reshard kinds
  (``mesh.wire_leaf_kinds`` — the metadata ``campaign/checkpoint.py``
  records into ``state.npz``) and statically drives
  ``checkpoint.reshard_carry`` 4 -> 2 -> 4 and 4 -> 1 on zero-filled
  templates, proving every leaf of a checkpoint written at S shards
  re-chunks onto S' shards before any real campaign depends on it.

Census mechanics: the partitioned jaxpr of one chunk dispatch is
mesh-size-INVARIANT in collective structure (the shard body sees the
same per-shard shapes at any size; only axis-size constants and the
boundary sharding change), which this pass verifies once per run by
tracing the donation subject at two sizes and diffing the censuses.
Each model is therefore traced ONCE (at :data:`CENSUS_TRACE_SIZE`) and
the per-size manifest entries are derived analytically — and the plain
tick trace is taken from the shared ``trace_cache``, so the combined
``lint --ir --cost --lanes --shard`` gate still traces each model x
layout exactly once.

Rules (SHD8xx):

=======  ==========================  ========  =========================
rule     name                        severity  what it flags
=======  ==========================  ========  =========================
SHD800   shard-audit-failure         error     the sharded step failed
                                               to lower/trace at all
SHD801   tick-hot-loop-collective    error     a reduction collective
                                               (psum/pmax/pmin) inside
                                               the scanned tick body
                                               beyond the model's
                                               pinned budget — ICI
                                               traffic per tick where
                                               shards must be
                                               independent
SHD802   replicated-per-instance-    error     a params leaf crossing
         leaf                                  the shard_map boundary
                                               replicated (``P()``)
                                               whose leading dim is the
                                               per-shard instance count
                                               and size clears the
                                               floor — O(chips) memory
                                               for per-instance state
SHD803   cross-shard-dependence      error     a data-moving collective
                                               (all_gather / ppermute /
                                               all_to_all / psum_
                                               scatter) in the tick hot
                                               loop — a cross-shard
                                               data dependence on the
                                               instance-sharded axis,
                                               the correctness killer
SHD804   donation-lost-under-        error     the partitioned
         sharding                              executable (compiled on
                                               a real host-device mesh
                                               when enough devices are
                                               visible) dropped
                                               input_output_alias on
                                               wire-carry leaves
SHD805   shard-manifest-missing      error     a model x layout x size
                                               has no manifest entry
SHD806   shard-manifest-stale        warning   a manifest entry matches
                                               no registered
                                               model x layout x size
SHD807   shard-manifest-drift        error     collective census or
                                               ICI-bytes estimate
                                               drifted from the
                                               manifest (warning + a
                                               re-record hint under
                                               jax-version skew)
SHD808   shard-manifest-updated      info      ``--update-shard-
                                               manifest`` rewrote the
                                               manifest
SHD809   carry-not-reshardable       error     a wire-carry leaf cannot
                                               be re-chunked across
                                               shard counts (kind
                                               metadata missing or
                                               ``reshard_carry`` fails
                                               statically) — the
                                               checkpoint would be
                                               pinned to its shard
                                               count
=======  ==========================  ========  =========================

The shard-hazard fixtures (``models/ir_hazards.py``:
``SHARD_FIXTURE_MODELS``) are audited alongside the registered models
on full runs; their findings are carried as status="expected" in
``analysis/baseline.json`` and asserted by
``tests/test_analysis_shard.py`` — the planted-bug convention.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import cost_model
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "shard"

DEFAULT_SHARD_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "shard_manifest.json")

# the audited mesh sizes: 1 (the degenerate single-chip case must stay
# collective-free on ICI), 2/4 (host-device test meshes), 8 (one ring)
MESH_SIZES = (1, 2, 4, 8)

# the census is mesh-size-invariant (verified per run by
# _verify_size_invariance), so each model traces once at this size
CENSUS_TRACE_SIZE = 2

# chunk length the census subject is traced at — matches the donation
# audit so the two passes exercise the same specialization
CENSUS_CHUNK_LEN = 4

# manifest drift tolerance on the ICI-bytes estimate (collective
# COUNTS compare exactly — a count change is never noise)
DEFAULT_TOLERANCE = 0.10

# SHD802 floor: a replicated params leaf smaller than this is not worth
# flagging even when its leading dim happens to equal the per-shard
# instance count (tiny per-node tables can collide with n_instances)
SHD802_FLOOR_BYTES = 16 << 10            # 16 KiB

# collective vocabulary, split by what the rule means: reductions merge
# values (legitimate at dispatch boundaries, budgeted in the tick);
# data movers redistribute state across shards (never legitimate in the
# tick hot loop — instances are independent by construction)
REDUCTION_COLLECTIVES = ("pmax", "pmin", "psum")
DATA_COLLECTIVES = ("all_gather", "all_to_all", "pgather", "ppermute",
                    "psum_scatter", "reduce_scatter")
ALL_COLLECTIVES = REDUCTION_COLLECTIVES + DATA_COLLECTIVES

# per-model tick-hot-loop reduction budgets (SHD801), keyed by workload
# family prefix. The vectorized raft family merges heartbeats through
# detached per-shard snapshots (mesh.py's svec/scan outputs, gathered
# at the shard_map boundary) rather than in-loop psums, so its pinned
# set is EMPTY — any reduction collective appearing in a raft tick is
# new ICI traffic, not the known heartbeat merge.
TICK_COLLECTIVE_BUDGETS: Dict[str, Dict[str, int]] = {
    "raft": {},
}

_MESH_PATH = "maelstrom_tpu/parallel/mesh.py"
_MANIFEST_REPO_PATH = "maelstrom_tpu/analysis/shard_manifest.json"


def _model_path(model) -> str:
    return type(model).__module__.replace(".", os.sep) + ".py"


def _finding(rule, name, severity, path, symbol, message) -> Finding:
    return Finding(rule=rule, name=name, severity=severity,
                   pass_name=PASS_NAME, path=path, line=0,
                   symbol=symbol, message=message)


def _abstract_mesh(size: int):
    """A device-free 1-D mesh of ``size`` shards over the instance
    axis — traceable on any host, TPU or not."""
    from jax.sharding import AbstractMesh
    from ..parallel import mesh as mesh_mod
    return AbstractMesh(((mesh_mod.AXIS, int(size)),))


# --- collective census ------------------------------------------------------


def census_of_jaxpr(closed) -> Dict[str, Dict[str, Dict[str, int]]]:
    """Walk one traced sharded step into ``{"tick": {...},
    "dispatch": {...}}`` — per-collective ``{"count", "bytes"}``, where
    ``tick`` holds collectives inside the scanned tick body on a
    per-tick basis (nested scans below the tick multiply by their trip
    counts) and ``dispatch`` everything outside any scan (once per
    chunk dispatch). ``bytes`` is the collective's per-shard operand
    payload."""
    tick: Dict[str, Dict[str, int]] = {}
    dispatch: Dict[str, Dict[str, int]] = {}

    def record(bucket, name, payload, mult):
        e = bucket.setdefault(name, {"count": 0, "bytes": 0})
        e["count"] += mult
        e["bytes"] += payload * mult

    def subs(eqn):
        out = []
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                inner = getattr(sub, "jaxpr", sub)
                if hasattr(inner, "eqns"):
                    out.append(inner)
        return out

    def walk(jaxpr, in_tick: bool, mult: int) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in ALL_COLLECTIVES:
                payload = sum(cost_model._aval_bytes(v)
                              for v in eqn.invars)
                record(tick if in_tick else dispatch, name, payload,
                       mult)
            if name == "scan":
                length = int(eqn.params.get("length", 1))
                for inner in subs(eqn):
                    # entering the outermost scan switches to the
                    # per-tick basis (mult 1); scans nested below the
                    # tick weight by their trip count
                    walk(inner, True, mult * length if in_tick else 1)
            else:
                for inner in subs(eqn):
                    walk(inner, in_tick, mult)

    walk(closed.jaxpr, False, 1)
    return {"tick": tick, "dispatch": dispatch}


def ici_bytes_of(prim: str, payload: int, size: int) -> int:
    """Estimated inter-chip bytes ONE shard moves for one collective of
    per-shard operand payload ``payload`` on a ``size``-shard ring —
    the standard ring-algorithm figures, deterministic by construction:

    - all-reduce (psum/pmax/pmin): ``2 * b * (S-1) / S`` (reduce-
      scatter + all-gather phases);
    - all-gather: ``b * (S-1)`` (the shard receives every other
      shard's block);
    - reduce-scatter (psum_scatter): ``b * (S-1) / S``;
    - all-to-all: ``b * (S-1) / S`` (keeps 1/S locally);
    - ppermute: ``b`` (one neighbor hop).

    Size 1 moves nothing across ICI regardless of primitive."""
    if size <= 1:
        return 0
    s = int(size)
    if prim in REDUCTION_COLLECTIVES:
        return int(2 * payload * (s - 1) / s)
    if prim in ("all_gather", "pgather"):
        return int(payload * (s - 1))
    if prim in ("psum_scatter", "reduce_scatter", "all_to_all"):
        return int(payload * (s - 1) / s)
    return int(payload)                  # ppermute and conservatively
                                         # anything unrecognized


def _ici_total(bucket: Dict[str, Dict[str, int]], size: int) -> int:
    return sum(ici_bytes_of(p, e["bytes"], size)
               for p, e in bucket.items())


def entry_of_census(census, size: int) -> Dict[str, Any]:
    """One checked-in manifest entry for one model x layout x mesh
    size. Counts and payload bytes come straight from the (size-
    invariant) jaxpr census; the ICI estimates apply
    :func:`ici_bytes_of` at this size."""
    return {
        "tick-collectives": {p: census["tick"][p]["count"]
                             for p in sorted(census["tick"])},
        "tick-collective-bytes": sum(e["bytes"] for e in
                                     census["tick"].values()),
        "dispatch-collectives": {p: census["dispatch"][p]["count"]
                                 for p in sorted(census["dispatch"])},
        "ici-bytes-per-tick": _ici_total(census["tick"], size),
        "ici-bytes-per-dispatch": _ici_total(census["dispatch"], size),
    }


def size_key(workload: str, node_count: int, layout: str,
             size: int) -> str:
    return f"{cost_model.entry_key(workload, node_count, layout)}" \
           f"/s={size}"


# --- tracing the sharded subjects -------------------------------------------


def trace_sharded_chunk(model, sim, size: int = CENSUS_TRACE_SIZE,
                        params=None, length: int = CENSUS_CHUNK_LEN):
    """``jax.make_jaxpr`` of the ACTUAL sharded production dispatch —
    ``mesh.make_sharded_chunk_fn``'s jitted product — under an
    abstract ``size``-shard mesh. Returns ``(closed_jaxpr,
    wire_shapes)`` where ``wire_shapes`` is the gathered wire-carry
    template the step donates."""
    import jax
    import jax.numpy as jnp
    from ..parallel import mesh as mesh_mod

    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)    # the _prepare convention
    amesh = _abstract_mesh(size)
    chunk_fn, _ = mesh_mod.make_sharded_chunk_fn(model, sim, amesh,
                                                 params)
    wire = mesh_mod.wire_template(model, sim, amesh)
    wire_sds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), wire)
    p_sds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params)
    t_sds = jax.ShapeDtypeStruct((), jnp.int32)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")      # donation under make_jaxpr
        closed = jax.make_jaxpr(
            lambda w, t, p: chunk_fn(w, t, p, length=length))(
            wire_sds, t_sds, p_sds)
    return closed, wire


def trace_sharded_run(model, sim, size: int = CENSUS_TRACE_SIZE,
                      params=None):
    """``jax.make_jaxpr`` of the single-dispatch sharded runner body
    (``mesh._run_sharded``) under an abstract mesh — the subject whose
    dispatch-level census pins the fleet-stats merge set (one psum per
    NetStats counter)."""
    import jax
    import jax.numpy as jnp
    from ..parallel import mesh as mesh_mod

    if params is None:
        params = model.make_params(sim.net.n_nodes)
    if params is None:
        params = jnp.zeros((), jnp.int32)
    amesh = _abstract_mesh(size)
    p_sds = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params)
    fn = getattr(mesh_mod._run_sharded, "__wrapped__",
                 mesh_mod._run_sharded)
    return jax.make_jaxpr(lambda s, p: fn(model, sim, amesh, s, p))(
        jax.ShapeDtypeStruct((), jnp.int32), p_sds)


# --- per-model findings -----------------------------------------------------


def _tick_budget(workload: str) -> Dict[str, int]:
    for prefix, budget in TICK_COLLECTIVE_BUDGETS.items():
        if workload.startswith(prefix):
            return budget
    return {}


def hot_loop_findings(model, census, label: str,
                      workload: str) -> List[Finding]:
    """SHD801 (budgeted reductions) + SHD803 (data movers) over one
    tick census."""
    path = _model_path(model)
    cls = type(model).__name__
    budget = _tick_budget(workload)
    out: List[Finding] = []
    for prim in sorted(census["tick"]):
        count = census["tick"][prim]["count"]
        payload = census["tick"][prim]["bytes"]
        if prim in DATA_COLLECTIVES:
            out.append(_finding(
                "SHD803", "cross-shard-dependence", SEV_ERROR, path,
                cls,
                f"[{label}] {prim} x{count} ({payload} B/tick payload) "
                f"in the tick hot loop — a cross-shard data dependence "
                f"on the instance-sharded axis; shards must be "
                f"independent by construction (instances are pure "
                f"functions of (seed, global id)), so this either "
                f"changes results with the mesh size or serializes the "
                f"ring every tick"))
        elif count > budget.get(prim, 0):
            out.append(_finding(
                "SHD801", "tick-hot-loop-collective", SEV_ERROR, path,
                cls,
                f"[{label}] {prim} x{count} ({payload} B/tick payload) "
                f"in the tick hot loop exceeds the model's pinned "
                f"budget of {budget.get(prim, 0)} — per-tick ICI "
                f"latency on every chip; merge at the dispatch "
                f"boundary (the detached-snapshot idiom in "
                f"parallel/mesh.py) or pin the budget in "
                f"analysis/shard_audit.py with a justification"))
    return out


def replicated_leaf_findings(model, sim, label: str) -> List[Finding]:
    """SHD802: params cross the shard_map boundary replicated
    (``in_specs=P()`` in every sharded executor); a replicated leaf
    shaped like per-instance state wastes O(chips) memory."""
    import jax

    params = model.make_params(sim.net.n_nodes)
    if params is None:
        return []
    path = _model_path(model)
    cls = type(model).__name__
    out: List[Finding] = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        shape = tuple(getattr(leaf, "shape", ()))
        nbytes = int(getattr(leaf, "nbytes", 0) or 0)
        if (len(shape) >= 1 and shape[0] == sim.n_instances
                and nbytes >= SHD802_FLOOR_BYTES):
            out.append(_finding(
                "SHD802", "replicated-per-instance-leaf", SEV_ERROR,
                path, cls,
                f"[{label}] params leaf "
                f"{jax.tree_util.keystr(kp) or '<root>'} "
                f"{shape} ({nbytes} B) is replicated across the mesh "
                f"(params ride the shard_map boundary as P()) but its "
                f"leading dim equals the per-shard instance count "
                f"({sim.n_instances}) — per-instance state belongs in "
                f"the sharded carry, not replicated params: every chip "
                f"holds all of it (O(chips) waste) and it silently "
                f"stops scaling with the fleet"))
    return out


def reshard_findings(model, sim, label: str, params=None,
                     from_shards: int = 4,
                     to_shards: Sequence[int] = (2, 1)) -> List[Finding]:
    """SHD809: statically drive ``checkpoint.reshard_carry`` over this
    model's wire-carry template — kinds metadata from
    ``mesh.wire_leaf_kinds`` (what ``state.npz`` records at save time),
    zero-filled leaves at the gathered ``from_shards`` shapes,
    re-chunked to each target and round-tripped back. Proves a
    checkpoint written at S shards is not pinned to S before any
    campaign depends on it."""
    import jax
    import numpy as np
    from ..campaign import checkpoint as ckpt
    from ..parallel import mesh as mesh_mod

    path = _model_path(model)
    cls = type(model).__name__

    def fail(msg):
        return [_finding("SHD809", "carry-not-reshardable", SEV_ERROR,
                         path, cls, f"[{label}] {msg}")]

    try:
        kinds = mesh_mod.wire_leaf_kinds(model, sim, params)
        wire = mesh_mod.wire_template(model, sim,
                                      _abstract_mesh(from_shards))
        leaves = [np.zeros(l.shape, l.dtype)
                  for l in jax.tree.leaves(wire)]
    except Exception as e:
        return fail(f"wire template / leaf kinds failed to build: "
                    f"{type(e).__name__}: {e}")
    if len(kinds) != len(leaves):
        return fail(f"wire_leaf_kinds records {len(kinds)} kinds but "
                    f"the wire carry has {len(leaves)} leaves — "
                    f"checkpoints written now cannot be resharded")
    meta = {"n-shards": from_shards,
            "instances-per-shard": int(sim.n_instances),
            "interleaved": True, "leaf-kinds": list(kinds)}
    for target in to_shards:
        try:
            new_leaves, new_meta = ckpt.reshard_carry(leaves, meta,
                                                      target)
            back, _ = ckpt.reshard_carry(new_leaves, new_meta,
                                         from_shards)
        except Exception as e:
            return fail(f"reshard_carry {from_shards} -> {target} "
                        f"raised {type(e).__name__}: {e}")
        for i, (a, b) in enumerate(zip(leaves, back)):
            if a.shape != b.shape or a.dtype != b.dtype:
                return fail(
                    f"leaf {i} ({kinds[i]}) did not round-trip "
                    f"{from_shards} -> {target} -> {from_shards}: "
                    f"{a.shape}/{a.dtype} became {b.shape}/{b.dtype}")
    return []


def _verify_size_invariance(model, sim, workload: str,
                            sizes: Tuple[int, int]) -> List[Finding]:
    """The analytic per-size manifest derivation is sound only if the
    census really is mesh-size-invariant — verified here on the
    donation subject by tracing at two sizes and diffing."""
    a = census_of_jaxpr(trace_sharded_chunk(model, sim, sizes[0])[0])
    b = census_of_jaxpr(trace_sharded_chunk(model, sim, sizes[1])[0])
    if a == b:
        return []
    return [_finding(
        "SHD800", "shard-audit-failure", SEV_ERROR, _MESH_PATH,
        "make_sharded_chunk_fn",
        f"[{workload}] collective census differs between mesh sizes "
        f"{sizes[0]} and {sizes[1]} ({a} vs {b}) — the census is no "
        f"longer size-invariant, so the per-size manifest entries "
        f"derived from a single trace are unsound; shard_audit.py "
        f"must trace every size explicitly")]


# --- SHD804: the partitioned executable -------------------------------------


def hlo_collective_census(compiled_text: str) -> Dict[str, int]:
    """Collective-op census of optimized (partitioned) HLO text — the
    post-SPMD ground truth next to the jaxpr census. XLA-version-
    volatile (ops fold/elide per backend), so surfaced, never
    manifested."""
    counts: Dict[str, int] = {}
    for op in ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all"):
        n = compiled_text.count(f" {op}(")
        if n:
            counts[op] = n
    return counts


def compiled_shard_findings(mesh_sizes: Sequence[int] = MESH_SIZES,
                            chunk_len: int = CENSUS_CHUNK_LEN,
                            ) -> List[Finding]:
    """SHD804 over every mesh size the visible devices can host:
    compile the sharded chunk step on a REAL mesh and verify the wire
    carry stayed fully aliased (``input_output_alias``) on the
    partitioned executable — donation silently drops per-sharding, not
    just per-shape, so the 1-device JXP403 audit cannot stand in for
    this."""
    import jax
    import jax.numpy as jnp
    from . import ir_lint
    from ..models import get_model
    from ..parallel import mesh as mesh_mod

    wl, n = ir_lint.DONATION_WORKLOAD
    model = get_model(wl, n, "grid")
    sim = cost_model.audit_sim(model, n, "lead")
    params = model.make_params(n)
    if params is None:
        params = jnp.zeros((), jnp.int32)
    n_dev = len(jax.devices())
    findings: List[Finding] = []
    for size in mesh_sizes:
        if size > n_dev:
            continue
        label = f"{wl}/n={n}/lead/s={size}"
        try:
            mesh = mesh_mod.make_mesh(size)
            chunk_fn, _ = mesh_mod.make_sharded_chunk_fn(
                model, sim, mesh, params)
            wire = mesh_mod.wire_template(model, sim, mesh)
            wire_sds = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), wire)
            p_sds = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                params)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                compiled = chunk_fn.lower(
                    wire_sds, jax.ShapeDtypeStruct((), jnp.int32),
                    p_sds, length=chunk_len).compile()
        except Exception as e:
            findings.append(_finding(
                "SHD804", "donation-lost-under-sharding", SEV_ERROR,
                _MESH_PATH, "make_sharded_chunk_fn",
                f"[{label}] compiling the partitioned chunk step "
                f"raised {type(e).__name__}: {e}"))
            continue
        n_leaves = len(jax.tree.leaves(wire))
        aliased = ir_lint.aliased_params_of(compiled.as_text())
        missing = sorted(set(range(n_leaves)) - aliased)
        if missing:
            findings.append(_finding(
                "SHD804", "donation-lost-under-sharding", SEV_ERROR,
                _MESH_PATH, "make_sharded_chunk_fn",
                f"[{label}] {len(missing)} of {n_leaves} wire-carry "
                f"leaves lost input_output_alias on the PARTITIONED "
                f"executable (flat param indices {missing[:8]}"
                f"{'...' if len(missing) > 8 else ''}) — donation "
                f"that holds on one device silently drops under "
                f"sharding and doubles per-chip HBM"))
    return findings


# --- manifest io + drift gate -----------------------------------------------


def load_shard_manifest(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or DEFAULT_SHARD_MANIFEST
    if not os.path.exists(path):
        return {"version": 1, "tolerance": DEFAULT_TOLERANCE,
                "entries": {}}
    with open(path) as f:
        data = json.load(f)
    data.setdefault("tolerance", DEFAULT_TOLERANCE)
    data.setdefault("entries", {})
    return data


def save_shard_manifest(entries: Dict[str, Dict[str, Any]],
                        path: Optional[str] = None,
                        tolerance: float = DEFAULT_TOLERANCE) -> str:
    import jax
    path = path or DEFAULT_SHARD_MANIFEST
    payload = {
        "version": 1,
        "_comment": (
            "Per-model collective census + ICI cost manifest for "
            "`maelstrom lint --shard` (doc/lint.md). Keys: <workload>/"
            "n=<nodes>/<layout>/s=<mesh size> (plus run:* for the "
            "single-dispatch runner subject); tick-collectives = "
            "collective primitive counts inside the scanned tick body "
            "of the sharded chunk step (scan-trip-weighted, per tick), "
            "dispatch-collectives = per-dispatch plumbing outside the "
            "scan, ici-bytes-per-tick = estimated inter-chip bytes one "
            "shard moves per tick (ring-collective formulas, "
            "shard_audit.ici_bytes_of). Counts compare exactly; byte "
            "estimates drift within `tolerance`. Regenerate after an "
            "INTENTIONAL sharding change with `maelstrom lint --shard "
            "--update-shard-manifest`; drift fails the gate (SHD807). "
            "jax-version records the tracing toolchain: under a "
            "different jax the gate downgrades drift to a re-record "
            "warning."),
        "jax-version": jax.__version__,
        "tolerance": tolerance,
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path


def compare_manifest(live: Dict[str, Dict[str, Any]],
                     manifest: Dict[str, Any],
                     paths: Dict[str, Tuple[str, str]],
                     full_universe: bool = True,
                     errored: Set[str] = frozenset(),
                     ) -> List[Finding]:
    """SHD805/806/807 — diff live entries against the checked-in
    manifest. Collective counts are the safety-relevant fact and
    compare exactly; the ICI byte estimates tolerate ``tolerance``
    relative drift."""
    entries = manifest.get("entries", {})
    tol = float(manifest.get("tolerance", DEFAULT_TOLERANCE))
    note = cost_model.toolchain_note(manifest.get("jax-version"),
                                     "the shard manifest",
                                     "--update-shard-manifest")
    findings: List[Finding] = []
    for key in sorted(live):
        ent = live[key]
        path, symbol = paths[key]
        base = entries.get(key)
        if base is None:
            findings.append(_finding(
                "SHD805", "shard-manifest-missing", SEV_ERROR, path,
                symbol,
                f"[{key}] no shard-manifest entry — record one with "
                f"`maelstrom lint --shard --update-shard-manifest`"))
            continue
        drifts = []
        for field in ("tick-collectives", "dispatch-collectives"):
            want = base.get(field)
            if want is not None and want != ent[field]:
                drifts.append(f"{field}: live {ent[field]} vs manifest "
                              f"{want}")
        for field in ("ici-bytes-per-tick", "ici-bytes-per-dispatch",
                      "tick-collective-bytes"):
            want = base.get(field)
            got = ent[field]
            if want is None:
                continue
            if abs(got - want) > max(abs(want), 1) * tol:
                drifts.append(
                    f"{field}: live {got} vs manifest {want} "
                    f"({(got - want) / max(abs(want), 1) * 100:+.0f}%)")
        if drifts:
            findings.append(_finding(
                "SHD807", "shard-manifest-drift",
                SEV_WARNING if note else SEV_ERROR, path, symbol,
                f"[{key}] collective census / ICI estimate drifted "
                f"from the checked-in manifest: {'; '.join(drifts)} — "
                f"the sharded step's communication pattern changed; if "
                f"intentional, re-record with --update-shard-manifest "
                f"and justify it in the PR"
                + (f" ({note})" if note else "")))
    if full_universe:
        for key in sorted(set(entries) - set(live) - set(errored)):
            findings.append(_finding(
                "SHD806", "shard-manifest-stale", SEV_WARNING,
                _MANIFEST_REPO_PATH, "",
                f"[{key}] manifest entry matches no registered "
                f"model x layout x mesh size — remove or re-record it"))
    return findings


# --- orchestration ----------------------------------------------------------


def run_shard_lint(repo_root: str = ".",
                   manifest_path: Optional[str] = None,
                   update_manifest: bool = False,
                   workloads: Optional[List[Tuple[str, int]]] = None,
                   layouts: Sequence[str] = cost_model.AUDIT_LAYOUTS,
                   mesh_sizes: Sequence[int] = MESH_SIZES,
                   include_fixtures: bool = True,
                   compiled: bool = True,
                   trace_cache=None) -> List[Finding]:
    """The shard pass: census + SHD8xx audit of every registered
    model x layout (or a restricted list), manifest gate, fixture
    sweep, reshardability proof, and — devices permitting — the
    partitioned-executable donation check."""
    from ..models import get_model

    full = workloads is None
    specs = cost_model.cost_specs() if full else list(workloads)
    findings: List[Finding] = []
    live: Dict[str, Dict[str, Any]] = {}
    paths: Dict[str, Tuple[str, str]] = {}
    errored: Set[str] = set()

    for wl, n in specs:
        try:
            model = get_model(wl, n, "grid")
        except Exception as e:
            findings.append(_finding(
                "SHD800", "shard-audit-failure", SEV_ERROR,
                "maelstrom_tpu/models/__init__.py", "get_model",
                f"get_model({wl!r}, {n}) raised: {e!r}"))
            errored.update(size_key(wl, n, lay, s)
                           for lay in layouts for s in mesh_sizes)
            continue
        for layout in layouts:
            base_key = cost_model.entry_key(wl, n, layout)
            label = base_key
            sim = cost_model.audit_sim(model, n, layout)
            # the plain tick trace rides the shared cache — the
            # combined gate's single-trace-per-model pin (the sharded
            # chunk trace below embeds the same tick, so no pass
            # re-traces what another already paid for)
            if trace_cache is not None:
                try:
                    cost_model.trace_tick(model, sim,
                                          cache=trace_cache)
                except Exception:
                    pass
            census = (trace_cache.get("shard:" + base_key)
                      if trace_cache is not None else None)
            if census is None:
                try:
                    closed, _wire = trace_sharded_chunk(model, sim)
                except Exception as e:
                    findings.append(_finding(
                        "SHD800", "shard-audit-failure", SEV_ERROR,
                        _model_path(model), type(model).__name__,
                        f"[{label}] lowering the sharded chunk step "
                        f"raised {type(e).__name__}: {e}"))
                    errored.update(size_key(wl, n, layout, s)
                                   for s in mesh_sizes)
                    continue
                census = census_of_jaxpr(closed)
                if trace_cache is not None:
                    trace_cache["shard:" + base_key] = census
            findings.extend(hot_loop_findings(model, census, label,
                                              wl))
            findings.extend(replicated_leaf_findings(model, sim,
                                                     label))
            findings.extend(reshard_findings(model, sim, label))
            for s in mesh_sizes:
                key = size_key(wl, n, layout, s)
                live[key] = entry_of_census(census, s)
                paths[key] = (_model_path(model),
                              type(model).__name__)

    if full:
        # the single-dispatch runner subject: its dispatch census pins
        # the fleet-stats merge set (one psum per NetStats counter) —
        # an extra collective sneaking into _run_sharded shows up here
        # as manifest drift
        from .ir_lint import DONATION_WORKLOAD
        wl, n = DONATION_WORKLOAD
        try:
            model = get_model(wl, n, "grid")
            sim = cost_model.audit_sim(model, n, "lead")
            run_census = census_of_jaxpr(trace_sharded_run(model, sim))
            findings.extend(hot_loop_findings(
                model, run_census, f"run:{wl}/n={n}/lead", wl))
            findings.extend(_verify_size_invariance(
                model, sim, f"{wl}/n={n}", (CENSUS_TRACE_SIZE, 8)))
            for s in mesh_sizes:
                key = f"run:{size_key(wl, n, 'lead', s)}"
                live[key] = entry_of_census(run_census, s)
                paths[key] = (_MESH_PATH, "_run_sharded")
        except Exception as e:
            findings.append(_finding(
                "SHD800", "shard-audit-failure", SEV_ERROR, _MESH_PATH,
                "_run_sharded",
                f"[run:{wl}/n={n}] lowering the sharded runner raised "
                f"{type(e).__name__}: {e}"))

    if full and include_fixtures:
        from ..models.ir_hazards import SHARD_FIXTURE_MODELS
        for kind, cls in sorted(SHARD_FIXTURE_MODELS.items()):
            model = cls()
            for layout in layouts:
                label = f"fixture-{kind}/{layout}"
                try:
                    sim = cost_model.audit_sim(model, 2, layout)
                    closed, _ = trace_sharded_chunk(model, sim)
                except Exception as e:
                    findings.append(_finding(
                        "SHD800", "shard-audit-failure", SEV_ERROR,
                        _model_path(model), type(model).__name__,
                        f"[{label}] lowering the fixture chunk step "
                        f"raised {type(e).__name__}: {e}"))
                    continue
                census = census_of_jaxpr(closed)
                findings.extend(hot_loop_findings(model, census,
                                                  label, kind))
                findings.extend(replicated_leaf_findings(model, sim,
                                                         label))

    if full and compiled:
        findings.extend(compiled_shard_findings(mesh_sizes))

    if update_manifest:
        path = save_shard_manifest(live, manifest_path)
        findings.append(_finding(
            "SHD808", "shard-manifest-updated", SEV_INFO,
            os.path.relpath(path, os.path.abspath(repo_root))
            if os.path.isabs(path) else path, "",
            f"recorded {len(live)} shard-manifest entr"
            f"{'y' if len(live) == 1 else 'ies'}"))
    else:
        manifest = load_shard_manifest(manifest_path)
        findings.extend(compare_manifest(live, manifest, paths,
                                         full_universe=full,
                                         errored=errored))
    return findings


# --- bench surface ----------------------------------------------------------


def shard_stats(model, sim, mesh_size: int = 8,
                cache=None) -> Dict[str, int]:
    """One-call sharded-cost stats for bench.py metric lines:
    ``collectives_per_tick`` (tick-hot-loop collective count of the
    sharded chunk step under ``sim``) and ``ici_bytes_est`` (the
    per-tick ICI estimate at ``mesh_size`` shards). ``sim`` describes
    the per-shard block, so the figures price the configuration the
    bench measures. ``cache`` is the shared lint/bench trace cache —
    the sharded census rides it under a ``shard:``-prefixed key (the
    plain-tick entries cannot serve it: this traces the SHARDED
    dispatch)."""
    key = None
    if cache is not None:
        key = "shard:" + cost_model.entry_key(
            getattr(model, "name", type(model).__name__),
            sim.net.n_nodes, sim.layout)
        census = cache.get(key)
        if census is not None:
            return {
                "collectives_per_tick": sum(
                    e["count"] for e in census["tick"].values()),
                "ici_bytes_est": _ici_total(census["tick"], mesh_size),
            }
    closed, _ = trace_sharded_chunk(model, sim)
    census = census_of_jaxpr(closed)
    if key is not None:
        cache[key] = census
    return {
        "collectives_per_tick": sum(e["count"]
                                    for e in census["tick"].values()),
        "ici_bytes_est": _ici_total(census["tick"], mesh_size),
    }
