"""Finding model + baseline bookkeeping for ``maelstrom lint``.

A :class:`Finding` is one lint result: a rule id, a severity, a location
(repo-relative path + line), the enclosing symbol, and a line-free
message. Findings serialize to JSON (machine consumers / the checked-in
baseline) and render as severity-colored text (humans).

The baseline (``analysis/baseline.json``) is the escape hatch demanded
by the lint workflow: every error-severity finding on the *current* tree
must either be fixed or be listed there with a one-line justification.
Entries match findings by **fingerprint** — ``rule:path:symbol``,
deliberately excluding line numbers so unrelated edits don't invalidate
the baseline. Two entry statuses exist:

- ``accepted`` — justified debt (e.g. a bounded int32 counter with an
  enforced horizon);
- ``expected`` — the finding is the *point* (the intentional-bug lint
  fixtures in ``models/raft_buggy.py``); tests assert these fire.

Baseline entries that match nothing are reported as *stale* so the file
cannot silently rot.
"""

from __future__ import annotations

import json
import os
import sys
from dataclasses import dataclass, field, asdict
from typing import Dict, List, Optional, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"

_SEV_ORDER = {SEV_ERROR: 0, SEV_WARNING: 1, SEV_INFO: 2}


@dataclass
class Finding:
    rule: str            # e.g. "TRC101"
    name: str            # short slug, e.g. "traced-branch"
    severity: str        # error / warning / info
    pass_name: str       # trace / contract / schema
    path: str            # repo-relative
    line: int            # 1-based; 0 = whole-file / symbol-level
    symbol: str          # enclosing def/class ("" for file-level)
    message: str         # line-free description

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}"

    def to_dict(self) -> dict:
        d = asdict(self)
        d["fingerprint"] = self.fingerprint
        return d

    def location(self) -> str:
        return f"{self.path}:{self.line}" if self.line else self.path


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (_SEV_ORDER.get(f.severity, 9),
                                           f.path, f.line, f.rule))


# --- baseline ---------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


@dataclass
class BaselineEntry:
    fingerprint: str
    reason: str
    status: str = "accepted"     # accepted | expected


# rule-id prefix -> owning pass; staleness checks are scoped to the
# passes that actually RAN (a trace/contract/schema run must not call
# the opt-in ir/cost entries stale just because it skipped those
# passes)
_RULE_PASS_PREFIXES = (("TRC", "trace"), ("CON", "contract"),
                       ("SCH", "schema"), ("JXP", "ir"),
                       ("COST", "cost"), ("LNE", "lanes"),
                       ("ABS", "ranges"), ("SHD", "shard"),
                       ("EXE", "aot"))


def fingerprint_pass(fingerprint: str) -> Optional[str]:
    """The pass a baseline fingerprint's rule family belongs to (None
    for an unrecognized prefix — treated as always in scope)."""
    for prefix, pass_name in _RULE_PASS_PREFIXES:
        if fingerprint.startswith(prefix):
            return pass_name
    return None


class Baseline:
    """Fingerprint -> entry map with hit tracking (for staleness)."""

    def __init__(self, entries: Optional[List[BaselineEntry]] = None):
        self.entries: Dict[str, BaselineEntry] = {
            e.fingerprint: e for e in (entries or [])}
        self._hits: Dict[str, int] = {}

    @classmethod
    def load(cls, path: str = DEFAULT_BASELINE) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        entries = [BaselineEntry(fingerprint=e["fingerprint"],
                                 reason=e.get("reason", ""),
                                 status=e.get("status", "accepted"))
                   for e in data.get("entries", [])]
        return cls(entries)

    def match(self, finding: Finding) -> Optional[BaselineEntry]:
        e = self.entries.get(finding.fingerprint)
        if e is not None:
            self._hits[e.fingerprint] = self._hits.get(e.fingerprint, 0) + 1
        return e

    def stale_entries(self, passes=None) -> List[BaselineEntry]:
        """Unmatched entries — restricted, when ``passes`` is given, to
        entries whose rule family belongs to a pass that ran."""
        out = []
        for fp, e in sorted(self.entries.items()):
            if fp in self._hits:
                continue
            owner = fingerprint_pass(fp)
            if passes is not None and owner is not None \
                    and owner not in passes:
                continue
            out.append(e)
        return out


# --- report -----------------------------------------------------------------

@dataclass
class LintReport:
    findings: List[Finding] = field(default_factory=list)     # unsuppressed
    suppressed: List[Tuple[Finding, BaselineEntry]] = field(
        default_factory=list)
    stale: List[BaselineEntry] = field(default_factory=list)
    files_scanned: int = 0
    passes_run: Tuple[str, ...] = ()

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEV_ERROR]

    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEV_WARNING]

    def to_json(self) -> dict:
        return {
            "passes": list(self.passes_run),
            "files-scanned": self.files_scanned,
            "findings": [f.to_dict() for f in sort_findings(self.findings)],
            "suppressed": [
                {**f.to_dict(), "baseline-status": e.status,
                 "baseline-reason": e.reason}
                for f, e in self.suppressed],
            "stale-baseline-entries": [asdict(e) for e in self.stale],
            "summary": {
                "errors": len(self.errors()),
                "warnings": len(self.warnings()),
                "suppressed": len(self.suppressed),
                "stale": len(self.stale),
            },
        }


_COLORS = {SEV_ERROR: "\x1b[31m", SEV_WARNING: "\x1b[33m",
           SEV_INFO: "\x1b[36m"}
_RESET = "\x1b[0m"
_DIM = "\x1b[2m"


def render_text(report: LintReport, color: Optional[bool] = None) -> str:
    """Human-readable rendering; color defaults to stdout-is-a-tty."""
    if color is None:
        color = sys.stdout.isatty()

    def paint(code: str, s: str) -> str:
        return f"{code}{s}{_RESET}" if color else s

    lines = []
    for f in sort_findings(report.findings):
        sev = paint(_COLORS.get(f.severity, ""), f.severity.upper())
        sym = f" [{f.symbol}]" if f.symbol else ""
        lines.append(f"{sev} {f.rule} {f.name} {f.location()}{sym}: "
                     f"{f.message}")
    for f, e in sorted(report.suppressed,
                       key=lambda fe: fe[0].fingerprint):
        tag = "expected" if e.status == "expected" else "baselined"
        lines.append(paint(_DIM, f"{tag} {f.rule} {f.location()} "
                                 f"[{f.symbol}]: {e.reason}"))
    for e in report.stale:
        lines.append(paint(_COLORS[SEV_WARNING],
                           f"STALE baseline entry {e.fingerprint!r} "
                           f"matched no finding — remove or re-justify"))
    n_err, n_warn = len(report.errors()), len(report.warnings())
    n_exp = sum(1 for _, e in report.suppressed if e.status == "expected")
    summary = (f"{n_err} error(s), {n_warn} warning(s), "
               f"{len(report.suppressed)} baselined "
               f"({n_exp} expected-fixture), {len(report.stale)} stale "
               f"baseline entr{'y' if len(report.stale) == 1 else 'ies'}; "
               f"{report.files_scanned} file(s), "
               f"passes: {', '.join(report.passes_run) or 'none'}")
    lines.append(summary)
    return "\n".join(lines)
