"""Lane-liveness dataflow analyzer: ``maelstrom lint --lanes``.

ROADMAP item 2 wants per-family lane-width specialization of the
one-size-fits-all ``Msg``/carry (the r5 DRAM-bound regression: the Msg
grew ~1.6x to carry all ten workload families and native throughput
halved). Doing that refactor aggressively needs *static evidence* of
which lanes each family actually touches. The existing passes audit
hazards (TRC/CON/SCH/JXP) and cost (COST) — this one audits
**liveness**: a backward dataflow slice over the traced tick jaxpr,
from the tick's observable outputs (history events, telemetry, stats,
violations, and the carry fixed point) back through
slice/gather/scatter/``select_n``/index-update equations, resolving
lane indices through the ``tpu/wire.py`` header constants and each
model's dispatch-table constants baked into the IR.

Per model x carry layout it computes:

- the **live message-lane set** — which of the 8 header +
  ``body_lanes`` (+ optional trailing NETID) lanes are ever read on
  any reachable path;
- the **live carry-leaf map** — per-leaf live/dead/carried
  classification with byte attribution;
- **dead stores** — body lanes written by the node/client/enqueue
  phases but never read before being overwritten or dropped.

The per-model result is serialized into the checked-in
``analysis/lane_manifest.json`` (``--update-manifest`` re-records,
drift fails the gate — the ``cost_baseline.json`` workflow), which
doubles as the machine-readable input for the specialization PR: each
entry carries ``live_body_lanes``, ``dead_bytes_per_tick_est``, and a
projected narrow ``ir_bytes_est``.

Rules (LNE6xx):

=======  =======================  ========  ===============================
rule     name                     severity  what it flags
=======  =======================  ========  ===============================
LNE600   lane-manifest-updated    info      ``--update-manifest`` rewrote
                                            the manifest
LNE601   dead-body-lane           warning   a declared body lane is never
                                            read on any reachable path —
                                            pure HBM/DRAM headroom for the
                                            narrow-layout refactor
LNE602   dead-carry-leaf          warning   a carry leaf feeds no
                                            observable output (not even
                                            through the carry fixed point)
LNE603   dead-store               warning   a body lane is written but
                                            never read before being
                                            overwritten or dropped
LNE604   lane-overread            error     a resolved lane index reaches
                                            outside the model's declared
                                            lane universe (silently clamps
                                            under jit — reads the wrong
                                            lane)
LNE605   lane-unresolvable        warning   a lane index could not be
                                            resolved statically — the
                                            analysis fell back to
                                            conservative all-live for the
                                            model
LNE606   lane-manifest-drift      error     the live lane set differs from
                                            the checked-in manifest entry
                                            (warning + a re-record hint
                                            when the manifest was recorded
                                            under a different jax version)
LNE607   lane-manifest-missing    error     a registered model x layout
                                            has no manifest entry
LNE608   lane-manifest-stale      warning   a manifest entry matches no
                                            registered model
LNE609   lane-analysis-failure    error     ``get_model`` or the lane
                                            analysis itself raised — the
                                            model could not be audited at
                                            all (distinct from LNE605's
                                            in-model widening)
LNE610   native-width-divergence  error     the native engine's templated
                                            per-family ``BODY_LANES``/
                                            ``L_*`` constants, the Python
                                            width table (``native/
                                            wire.py``), the model
                                            registry's lane math, or the
                                            built ``libsim.so`` disagree —
                                            the C++ templates and JAX
                                            ``body_lanes`` must never
                                            silently diverge
=======  =======================  ========  ===============================

Safety direction: the live set OVERAPPROXIMATES — every transfer rule
either models an equation exactly or demands all lanes of its inputs,
and any unresolvable lane index widens the whole model to all-live
(LNE605). A lane the manifest calls dead is therefore *provably*
unread under the audit config, which is what makes the manifest a
safety proof the narrow-layout refactor can lean on
(``tests/test_analysis_lanes.py`` pins the end-to-end version:
narrowing a fixture model's ``body_lanes`` to its recorded live set
leaves trajectories bit-identical in both carry layouts).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import (AbstractSet, Any, Dict, List, Optional, Sequence,
                    Set, Tuple)

import numpy as np

from . import cost_model
from .findings import Finding, SEV_ERROR, SEV_INFO, SEV_WARNING

PASS_NAME = "lanes"

DEFAULT_LANE_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lane_manifest.json")

# demand lattice: NONE (absent) < mask (frozenset of lane ids) < FULL
FULL = "full"
CONFLICT = "conflict"

# constant folding stays cheap: arrays above this size are never
# materialized (lane-index operands are tiny — a few elements)
_CONST_FOLD_MAX_ELEMS = 8192

# elementwise primitives: same-shape operands and output share lane
# coordinates exactly (jaxprs carry explicit broadcasts, so same-rank
# operands of these really are aligned)
_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "and", "or", "xor",
    "not", "neg", "sign", "abs", "eq", "ne", "lt", "le", "gt", "ge",
    "select_n", "convert_element_type", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "clamp",
    "integer_pow", "pow", "exp", "log", "floor", "ceil", "round",
    "square", "sqrt", "rsqrt", "logistic", "tanh", "erf", "is_finite",
    "stop_gradient", "copy", "nextafter", "population_count", "clz",
})

_REDUCES = frozenset({"reduce_sum", "reduce_max", "reduce_min",
                      "reduce_or", "reduce_and", "reduce_prod"})


def _join(a, b):
    """Demand-lattice join."""
    if a is None:
        return b
    if b is None:
        return a
    if a == FULL or b == FULL:
        return FULL
    return a | b


def _aval(v):
    return getattr(v, "aval", None)


def _shape(v) -> Tuple[int, ...]:
    aval = _aval(v)
    return tuple(getattr(aval, "shape", ()))


def _is_var(v) -> bool:
    # Literals have a .val; DropVars are Vars whose demand is meaningless
    return not hasattr(v, "val")


def _sub_closed(eqn):
    """(name, ClosedJaxpr-or-Jaxpr) pairs nested in one equation."""
    out = []
    for k, v in eqn.params.items():
        for sub in (v if isinstance(v, (list, tuple)) else (v,)):
            if hasattr(sub, "eqns") or hasattr(getattr(sub, "jaxpr", None),
                                               "eqns"):
                out.append((k, sub))
    return out


def _inner_jaxpr(sub):
    return sub.jaxpr if hasattr(sub, "jaxpr") else sub


@dataclass
class LaneReport:
    """Liveness result for ONE model x layout."""
    label: str
    lanes: int                       # full lane universe of the audit
                                     # config's wire format (8 header
                                     # + body + optional NETID)
    body_lanes: int
    live_lanes: Set[int] = field(default_factory=set)
    reads: Dict[int, Set[str]] = field(default_factory=dict)
    writes: Dict[int, Set[str]] = field(default_factory=dict)
    dead_stores: List[Tuple[int, str]] = field(default_factory=list)
    overreads: List[Tuple[int, str]] = field(default_factory=list)
    carry_leaves: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    conservative: bool = False       # LNE605 fallback fired
    notes: List[str] = field(default_factory=list)
    ir_bytes_est: int = 0
    dead_bytes_est: int = 0

    @property
    def live_body_lanes(self) -> List[int]:
        from ..tpu import wire
        return sorted(l - wire.BODY for l in self.live_lanes
                      if l >= wire.BODY)

    @property
    def dead_body_lanes(self) -> List[int]:
        return sorted(set(range(self.body_lanes))
                      - set(self.live_body_lanes))

    @property
    def live_header_lanes(self) -> List[int]:
        from ..tpu import wire
        return sorted(l for l in self.live_lanes if l < wire.BODY)

    @property
    def dead_carry_leaves(self) -> List[str]:
        return sorted(p for p, e in self.carry_leaves.items()
                      if e["status"] == "dead")

    def to_entry(self) -> Dict[str, Any]:
        """The checked-in manifest representation. Key names follow the
        specialization contract (ROADMAP item 2): ``live_body_lanes``
        is the narrow-layout target, ``dead_bytes_per_tick_est`` the
        measured headroom, ``projected_narrow_ir_bytes_est`` the cost
        model's estimate of the tick after the refactor."""
        return {
            "lanes": self.lanes,
            "body_lanes": self.body_lanes,
            "live_header_lanes": self.live_header_lanes,
            "live_body_lanes": self.live_body_lanes,
            "dead_body_lanes": self.dead_body_lanes,
            "dead_carry_leaves": self.dead_carry_leaves,
            "dead_stores": sorted({f"{lane}:{phase}"
                                   for lane, phase in self.dead_stores}),
            "resolution": ("conservative" if self.conservative
                           else "exact"),
            "ir_bytes_est": self.ir_bytes_est,
            "dead_bytes_per_tick_est": self.dead_bytes_est,
            "projected_narrow_ir_bytes_est":
                self.ir_bytes_est - self.dead_bytes_est,
        }


class _Analyzer:
    """One backward lane-liveness pass over one traced tick jaxpr.

    Three cooperating fixpoints, all on finite lattices:

    1. constant folding (forward, once): small integer arrays derivable
       from literals/constvars — the lane-index operands of
       gather/scatter/dynamic-slice equations;
    2. lane-axis tagging (bidirectional, to fixpoint): which axis of
       which intermediate is message-lane-shaped, seeded from the carry
       pool leaf and propagated through structural equations both ways
       (messages are *built* lanes-last from zeros and only meet the
       pool at the enqueue select — forward-only tagging misses them);
    3. demand propagation (backward, to fixpoint): per-var demand is
       NONE, a set of live lanes, or FULL; the tick-level carry
       feedback (out-leaf demand joins into in-leaf demand) closes the
       "live = needed by any future tick's observables" loop.
    """

    def __init__(self, closed, n_lanes: int,
                 lane_invars: Dict[int, int],
                 phase_of=None):
        self.closed = closed
        self.L = n_lanes
        self.tags: Dict[Any, Any] = {}           # Var -> axis | CONFLICT
        self.demand: Dict[Any, Any] = {}         # Var -> None/mask/FULL
        self.consts: Dict[Any, np.ndarray] = {}  # Var -> concrete value
        # scan-body xs vars whose outer array is known: the set of
        # values a per-trip slice can take (resolves BODY+i loops)
        self.possible: Dict[Any, Set[int]] = {}
        self.reads: Dict[int, Set[str]] = {}
        self.writes: Dict[int, Set[str]] = {}
        self.dead_stores: List[Tuple[int, str]] = []
        self.overreads: List[Tuple[int, str]] = []
        self.notes: List[str] = []
        self.conservative = False
        self._changed = False
        self._record = False
        self._phase_ctx: Optional[str] = None
        self._phase_of = phase_of or cost_model._phase_of
        for idx, axis in lane_invars.items():
            self._set_tag(closed.jaxpr.invars[idx], axis)
        for cv, cval in zip(closed.jaxpr.constvars, closed.consts):
            self._remember_const(cv, cval)

    # --- constant folding --------------------------------------------------

    def _remember_const(self, var, val):
        try:
            arr = np.asarray(val)
        except Exception:
            return
        if arr.size <= _CONST_FOLD_MAX_ELEMS and \
                arr.dtype.kind in "iub":
            self.consts[var] = arr

    def _cval(self, v):
        """Concrete value of an operand, if known."""
        if hasattr(v, "val"):
            try:
                return np.asarray(v.val)
            except Exception:
                return None
        return self.consts.get(v)

    def fold_consts(self):
        self._fold(self.closed.jaxpr)

    def _fold(self, jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            vals = [self._cval(v) for v in eqn.invars]
            out = None
            try:
                if name == "iota":
                    shape = eqn.params["shape"]
                    if int(np.prod(shape)) <= _CONST_FOLD_MAX_ELEMS:
                        dim = eqn.params["dimension"]
                        out = np.broadcast_to(
                            np.arange(shape[dim]).reshape(
                                [-1 if i == dim else 1
                                 for i in range(len(shape))]),
                            shape).astype(np.int64)
                elif any(v is None for v in vals):
                    out = None
                elif name == "broadcast_in_dim":
                    shape = eqn.params["shape"]
                    if int(np.prod(shape)) <= _CONST_FOLD_MAX_ELEMS:
                        bdims = eqn.params["broadcast_dimensions"]
                        src = vals[0].reshape(
                            [vals[0].shape[bdims.index(i)]
                             if i in bdims else 1
                             for i in range(len(shape))])
                        out = np.broadcast_to(src, shape)
                elif name == "concatenate":
                    out = np.concatenate(vals,
                                         axis=eqn.params["dimension"])
                elif name == "convert_element_type":
                    out = vals[0]
                elif name == "add":
                    out = vals[0] + vals[1]
                elif name == "sub":
                    out = vals[0] - vals[1]
                elif name == "mul":
                    out = vals[0] * vals[1]
                elif name == "max":
                    out = np.maximum(vals[0], vals[1])
                elif name == "min":
                    out = np.minimum(vals[0], vals[1])
                elif name in ("eq", "ne", "lt", "le", "gt", "ge"):
                    import operator
                    out = {"eq": operator.eq, "ne": operator.ne,
                           "lt": operator.lt, "le": operator.le,
                           "gt": operator.gt,
                           "ge": operator.ge}[name](vals[0], vals[1])
                elif name == "select_n":
                    # the clamp jnp indexing wraps around traced
                    # indices: fold it so the lane value stays visible
                    out = np.choose(vals[0].astype(np.int64),
                                    vals[1:], mode="clip")
                elif name == "rem":
                    out = np.where(vals[1] == 0, 0,
                                   np.fmod(vals[0], np.where(
                                       vals[1] == 0, 1, vals[1])))
                elif name in ("and", "or", "xor"):
                    import operator
                    out = {"and": operator.and_, "or": operator.or_,
                           "xor": operator.xor}[name](vals[0], vals[1])
                elif name == "shift_left":
                    out = np.left_shift(vals[0], vals[1])
                elif name == "shift_right_logical":
                    # logical shift: shift the unsigned reinterpretation
                    u = vals[0].astype(np.uint64 if
                                       vals[0].dtype.itemsize == 8
                                       else np.uint32)
                    out = np.right_shift(u, vals[1].astype(u.dtype)
                                         ).astype(vals[0].dtype)
                elif name == "shift_right_arithmetic":
                    out = np.right_shift(vals[0], vals[1])
                elif name == "not":
                    out = np.invert(vals[0])
                elif name == "pow":
                    with np.errstate(over="ignore"):
                        out = np.power(vals[0], vals[1])
                elif name == "integer_pow":
                    with np.errstate(over="ignore"):
                        out = np.power(vals[0], eqn.params["y"])
                elif name == "neg":
                    out = -vals[0]
                elif name == "clamp":
                    out = np.clip(vals[1], vals[0], vals[2])
                elif name == "reshape":
                    out = vals[0].reshape(eqn.params["new_sizes"])
                elif name == "squeeze":
                    out = np.squeeze(
                        vals[0], axis=tuple(eqn.params["dimensions"]))
                elif name == "transpose":
                    out = np.transpose(vals[0],
                                       eqn.params["permutation"])
                elif name == "slice":
                    idx = tuple(
                        slice(s, l, st) for s, l, st in zip(
                            eqn.params["start_indices"],
                            eqn.params["limit_indices"],
                            eqn.params["strides"]
                            or (1,) * len(eqn.params["start_indices"])))
                    out = vals[0][idx]
            except Exception:
                out = None
            if out is not None and len(eqn.outvars) == 1 \
                    and _is_var(eqn.outvars[0]):
                arr = np.asarray(out)
                if arr.size <= _CONST_FOLD_MAX_ELEMS and \
                        arr.dtype.kind in "iub":
                    self.consts[eqn.outvars[0]] = arr
            # recurse: pjit bodies see the operand consts; scan bodies
            # see const operands plus per-trip value SETS for known xs
            for _, sub in _sub_closed(eqn):
                inner = _inner_jaxpr(sub)
                if name == "pjit" and \
                        len(inner.invars) == len(eqn.invars):
                    for bv, val in zip(inner.invars, vals):
                        if val is not None:
                            self._remember_const(bv, val)
                elif name == "scan":
                    nc = eqn.params["num_consts"]
                    ncar = eqn.params["num_carry"]
                    for bv, val in zip(inner.invars[:nc], vals[:nc]):
                        if val is not None:
                            self._remember_const(bv, val)
                    for k, bv in enumerate(inner.invars[nc + ncar:]):
                        val = vals[nc + ncar + k]
                        if val is not None and val.ndim >= 1:
                            self.possible[bv] = \
                                {int(x) for x in np.unique(val)}
                for cv, cval in zip(getattr(inner, "constvars", ()),
                                    getattr(sub, "consts", ())):
                    self._remember_const(cv, cval)
                self._fold(inner)
                # propagate foldable pjit RESULTS back out — jnp's
                # index clamping hides inside pjit(_where) bodies
                if name == "pjit" and \
                        len(inner.outvars) == len(eqn.outvars):
                    for bo, oo in zip(inner.outvars, eqn.outvars):
                        val = self._cval(bo)
                        if val is not None and _is_var(oo):
                            self.consts[oo] = val

    def _resolve_lane_values(self, v) -> Optional[Set[int]]:
        """The set of values a lane-index operand can take, or None."""
        val = self._cval(v)
        if val is not None:
            return {int(x) for x in np.unique(val)}
        if v in self.possible:
            return set(self.possible[v])
        return None

    # --- lane-axis tagging -------------------------------------------------

    def _set_tag(self, var, axis):
        if not _is_var(var) or axis is None:
            return
        cur = self.tags.get(var)
        if cur is None:
            self.tags[var] = axis
            self._changed = True
        elif cur != axis:
            if cur != CONFLICT:
                self.tags[var] = CONFLICT
                self._changed = True

    def _tag(self, var):
        t = self.tags.get(var) if _is_var(var) else None
        return t if t != CONFLICT else None

    def infer_tags(self, max_iters: int = 30):
        for _ in range(max_iters):
            self._changed = False
            self._tag_walk(self.closed.jaxpr)
            if not self._changed:
                return
        # a half-propagated tagging can narrow demand along a wrongly
        # tagged axis, so non-convergence must widen like run_demand's
        self.note("lane-axis tagging did not converge "
                  f"in {max_iters} sweeps — results widened")
        self.conservative = True

    def _unify(self, a, b):
        """Two vars share lane coordinates on the same axis."""
        ta, tb = self._tag(a), self._tag(b)
        if ta is not None:
            self._set_tag(b, ta)
        if tb is not None:
            self._set_tag(a, tb)

    def _unify_axis_map(self, src, dst, axis_map):
        """src axis a ↔ dst axis axis_map[a] (dict, both directions)."""
        ts = self._tag(src)
        if ts is not None and ts in axis_map:
            self._set_tag(dst, axis_map[ts])
        td = self._tag(dst)
        if td is not None:
            inv = {v: k for k, v in axis_map.items()}
            if td in inv:
                self._set_tag(src, inv[td])

    def _reshape_axis_map(self, in_shape, out_shape) -> Dict[int, int]:
        """Axes preserved by a reshape: same dim size AND same trailing
        element count (the unique axis-identity a reshape can keep)."""
        def trailing(shape):
            out, p = [], 1
            for d in reversed(shape):
                out.append(p)
                p *= d
            return list(reversed(out))
        t_in, t_out = trailing(in_shape), trailing(out_shape)
        amap = {}
        for a, (da, ta) in enumerate(zip(in_shape, t_in)):
            for b, (db, tb) in enumerate(zip(out_shape, t_out)):
                if da == db and ta == tb:
                    amap[a] = b
                    break
        return amap

    def _tag_eqn(self, eqn):
        name = eqn.primitive.name
        invars, outvars = eqn.invars, eqn.outvars
        if name in _ELEMENTWISE:
            shp = _shape(outvars[0])
            for v in invars:
                if _shape(v) == shp:
                    self._unify(v, outvars[0])
        elif name == "broadcast_in_dim":
            bdims = tuple(eqn.params["broadcast_dimensions"])
            in_shape, out_shape = _shape(invars[0]), _shape(outvars[0])
            amap = {a: b for a, b in enumerate(bdims)
                    if in_shape[a] == out_shape[b]}
            self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "transpose":
            perm = tuple(eqn.params["permutation"])
            amap = {p: i for i, p in enumerate(perm)}
            self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "squeeze":
            dims = set(int(d) for d in eqn.params["dimensions"])
            amap, b = {}, 0
            for a in range(len(_shape(invars[0]))):
                if a not in dims:
                    amap[a] = b
                    b += 1
            self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "reshape":
            if eqn.params.get("dimensions") is None:
                amap = self._reshape_axis_map(_shape(invars[0]),
                                              _shape(outvars[0]))
                self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "slice":
            in_shape, out_shape = _shape(invars[0]), _shape(outvars[0])
            amap = {a: a for a in range(len(in_shape))
                    if in_shape[a] == out_shape[a]}
            self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "concatenate":
            dim = int(eqn.params["dimension"])
            for v in invars:
                amap = {a: a for a in range(len(_shape(v)))
                        if a != dim}
                self._unify_axis_map(v, outvars[0], amap)
        elif name in _REDUCES or name in ("argmax", "argmin"):
            axes = set(int(a) for a in eqn.params.get("axes", ()))
            amap, b = {}, 0
            for a in range(len(_shape(invars[0]))):
                if a not in axes:
                    amap[a] = b
                    b += 1
            self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "sort":
            dim = int(eqn.params.get("dimension", -1))
            for v, o in zip(invars, outvars):
                amap = {a: a for a in range(len(_shape(v)))
                        if a != dim}
                self._unify_axis_map(v, o, amap)
        elif name == "gather":
            self._tag_gather(eqn)
        elif name in ("scatter", "scatter-add", "scatter-mul",
                      "scatter-min", "scatter-max"):
            self._tag_scatter(eqn)
        elif name == "dynamic_slice":
            in_shape, out_shape = _shape(invars[0]), _shape(outvars[0])
            amap = {a: a for a in range(len(in_shape))
                    if in_shape[a] == out_shape[a]}
            self._unify_axis_map(invars[0], outvars[0], amap)
        elif name == "dynamic_update_slice":
            self._unify(invars[0], outvars[0])
            in_shape, up_shape = _shape(invars[0]), _shape(invars[1])
            amap = {a: a for a in range(len(in_shape))
                    if in_shape[a] == up_shape[a]}
            self._unify_axis_map(invars[0], invars[1], amap)
        elif name == "pjit":
            for _, sub in _sub_closed(eqn):
                inner = _inner_jaxpr(sub)
                if len(inner.invars) == len(invars) and \
                        len(inner.outvars) == len(outvars):
                    for a, b in zip(invars, inner.invars):
                        self._unify(a, b)
                    for a, b in zip(outvars, inner.outvars):
                        self._unify(a, b)
                self._tag_walk(inner)
        elif name == "scan":
            self._tag_scan(eqn)
        elif name == "cond":
            for _, sub in _sub_closed(eqn):
                inner = _inner_jaxpr(sub)
                if len(inner.invars) == len(invars) - 1 and \
                        len(inner.outvars) == len(outvars):
                    for a, b in zip(invars[1:], inner.invars):
                        self._unify(a, b)
                    for a, b in zip(outvars, inner.outvars):
                        self._unify(a, b)
                self._tag_walk(inner)
        else:
            for _, sub in _sub_closed(eqn):
                self._tag_walk(_inner_jaxpr(sub))

    def _gather_offset_map(self, dnums, operand_rank) -> Dict[int, int]:
        """operand axis -> output axis for window (offset) dims."""
        collapsed = set(int(d) for d in dnums.collapsed_slice_dims)
        batching = set(int(d) for d in
                       getattr(dnums, "operand_batching_dims", ()))
        offset_dims = tuple(int(d) for d in dnums.offset_dims)
        amap, k = {}, 0
        for a in range(operand_rank):
            if a in collapsed or a in batching:
                continue
            if k < len(offset_dims):
                amap[a] = offset_dims[k]
            k += 1
        return amap

    def _tag_gather(self, eqn):
        dnums = eqn.params["dimension_numbers"]
        operand, out = eqn.invars[0], eqn.outvars[0]
        slice_sizes = tuple(int(s) for s in eqn.params["slice_sizes"])
        in_shape = _shape(operand)
        amap = {a: b for a, b in self._gather_offset_map(
            dnums, len(in_shape)).items()
            if slice_sizes[a] == in_shape[a]}
        self._unify_axis_map(operand, out, amap)

    def _scatter_window_map(self, dnums, operand_rank) -> Dict[int, int]:
        """operand axis -> updates axis for window dims."""
        inserted = set(int(d) for d in dnums.inserted_window_dims)
        batching = set(int(d) for d in
                       getattr(dnums, "operand_batching_dims", ()))
        window = tuple(int(d) for d in dnums.update_window_dims)
        amap, k = {}, 0
        for a in range(operand_rank):
            if a in inserted or a in batching:
                continue
            if k < len(window):
                amap[a] = window[k]
            k += 1
        return amap

    def _tag_scatter(self, eqn):
        operand, out = eqn.invars[0], eqn.outvars[0]
        self._unify(operand, out)
        dnums = eqn.params["dimension_numbers"]
        in_shape, up_shape = _shape(operand), _shape(eqn.invars[2])
        amap = {a: b for a, b in self._scatter_window_map(
            dnums, len(in_shape)).items()
            if b < len(up_shape) and up_shape[b] == in_shape[a]}
        self._unify_axis_map(operand, eqn.invars[2], amap)

    def _tag_scan(self, eqn):
        for _, sub in _sub_closed(eqn):
            inner = _inner_jaxpr(sub)
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            invars, outvars = eqn.invars, eqn.outvars
            # consts + carry align 1:1; xs/ys drop the leading scan axis
            for a, b in zip(invars[:nc + ncar], inner.invars[:nc + ncar]):
                self._unify(a, b)
            for a, b in zip(outvars[:ncar], inner.outvars[:ncar]):
                self._unify(a, b)
            # carry in <-> carry out of the body share coordinates
            for a, b in zip(inner.invars[nc:nc + ncar],
                            inner.outvars[:ncar]):
                self._unify(a, b)
            for a, b in zip(invars[nc + ncar:], inner.invars[nc + ncar:]):
                shp = _shape(a)
                amap = {ax: ax - 1 for ax in range(1, len(shp))}
                self._unify_axis_map(a, b, amap)
            for a, b in zip(outvars[ncar:], inner.outvars[ncar:]):
                shp = _shape(a)
                amap = {ax: ax - 1 for ax in range(1, len(shp))}
                self._unify_axis_map(a, b, amap)
            self._tag_walk(inner)

    def _tag_walk(self, jaxpr):
        for eqn in jaxpr.eqns:
            self._tag_eqn(eqn)

    # --- backward demand ---------------------------------------------------

    def note(self, msg: str):
        if msg not in self.notes:
            self.notes.append(msg)

    def _get_demand(self, var):
        if not _is_var(var):
            return None
        return self.demand.get(var)

    def _add_demand(self, var, d):
        if d is None or not _is_var(var) or \
                type(var).__name__ == "DropVar":
            return
        cur = self.demand.get(var)
        new = _join(cur, d)
        if new != cur:
            self.demand[var] = new
            self._changed = True

    def _record_read(self, lanes, eqn):
        if not self._record:
            return
        phase = self._phase_ctx or self._phase_of(eqn)
        for lane in lanes:
            if lane >= self.L or lane < 0:
                self.overreads.append((lane, phase))
                lane = max(0, min(lane, self.L - 1))
            self.reads.setdefault(lane, set()).add(phase)

    def _record_write(self, lanes, eqn, dead):
        if not self._record:
            return
        phase = self._phase_ctx or self._phase_of(eqn)
        for lane in lanes:
            if lane >= self.L or lane < 0:
                self.overreads.append((lane, phase))
                continue
            self.writes.setdefault(lane, set()).add(phase)
            if dead:
                self.dead_stores.append((lane, phase))

    def _demand_default(self, eqn, any_out):
        d = FULL if any_out else None
        for v in eqn.invars:
            self._add_demand(v, d)

    def _fallback_full(self, eqn, why: str):
        """LNE605: an unresolvable lane access — all lanes conservatively
        live, noted once per site kind."""
        self.conservative = True
        self.note(why)
        for v in eqn.invars:
            self._add_demand(v, FULL)

    def run_demand(self, out_demands: List[Any],
                   carry_pairs: Sequence[Tuple[int, int]],
                   max_iters: int = 60):
        """Backward fixpoint. ``out_demands`` aligns with
        ``jaxpr.outvars``; ``carry_pairs`` are (outvar_idx, invar_idx)
        feedback edges (demand on a carry input leaf joins into the
        matching output leaf — the next tick needs it)."""
        jaxpr = self.closed.jaxpr
        for v, d in zip(jaxpr.outvars, out_demands):
            self._add_demand(v, d)
        for _ in range(max_iters):
            self._changed = False
            for out_i, in_i in carry_pairs:
                self._add_demand(jaxpr.outvars[out_i],
                                 self._get_demand(jaxpr.invars[in_i]))
            self._demand_walk(jaxpr)
            if not self._changed:
                break
        else:
            self.note(f"demand propagation did not converge in "
                      f"{max_iters} sweeps — results widened")
            self.conservative = True
        # one recording sweep at the fixpoint
        self._record = True
        self._demand_walk(jaxpr)
        self._record = False

    def _demand_walk(self, jaxpr):
        outer = self._phase_ctx
        for eqn in reversed(jaxpr.eqns):
            self._phase_ctx = outer if outer is not None \
                else self._phase_of(eqn)
            self._demand_eqn(eqn)
        self._phase_ctx = outer

    def _demand_eqn(self, eqn):
        name = eqn.primitive.name
        invars, outvars = eqn.invars, eqn.outvars
        outs = [self._get_demand(v) for v in outvars]
        any_out = any(d is not None for d in outs)
        if not any_out and name not in ("pjit", "scan", "cond", "while"):
            return
        d0 = outs[0] if outs else None

        if name in _ELEMENTWISE:
            shp = _shape(outvars[0])
            for v in invars:
                self._add_demand(v, d0 if _shape(v) == shp
                                 else (FULL if d0 is not None else None))
        elif name in ("broadcast_in_dim", "transpose", "squeeze",
                      "reshape", "sort", "rev"):
            # lane coordinates survive exactly when the tagger connected
            # in and out; a masked demand otherwise widens
            if name == "sort":
                for v, o in zip(invars, outvars):
                    dd = self._get_demand(o)
                    if dd is None:
                        continue
                    if isinstance(dd, frozenset) and (
                            self._tag(v) is None or self._tag(o) is None):
                        dd = FULL
                    self._add_demand(v, dd)
            else:
                dd = d0
                if isinstance(dd, frozenset) and (
                        self._tag(invars[0]) is None
                        or self._tag(outvars[0]) is None):
                    dd = FULL
                self._add_demand(invars[0], dd)
        elif name == "concatenate":
            dim = int(eqn.params["dimension"])
            for v in invars:
                dd = d0
                if isinstance(dd, frozenset):
                    tv = self._tag(v)
                    if tv is None or tv == dim:
                        dd = FULL
                self._add_demand(v, dd)
        elif name in _REDUCES or name in ("argmax", "argmin"):
            axes = set(int(a) for a in eqn.params.get("axes", ()))
            t_in = self._tag(invars[0])
            dd = d0 if (isinstance(d0, frozenset)
                        and t_in is not None
                        and t_in not in axes) else \
                (FULL if any_out else None)
            self._add_demand(invars[0], dd)
        elif name == "slice":
            self._demand_slice(eqn, d0)
        elif name == "dynamic_slice":
            self._demand_dynamic_slice(eqn, d0)
        elif name == "gather":
            self._demand_gather(eqn, d0)
        elif name in ("scatter", "scatter-add", "scatter-mul",
                      "scatter-min", "scatter-max"):
            self._demand_scatter(eqn, d0, rmw=name != "scatter")
        elif name == "dynamic_update_slice":
            self._demand_dus(eqn, d0)
        elif name == "pjit":
            subs = _sub_closed(eqn)
            ok = False
            for _, sub in subs:
                inner = _inner_jaxpr(sub)
                if len(inner.invars) == len(invars) and \
                        len(inner.outvars) == len(outvars):
                    for bo, d in zip(inner.outvars, outs):
                        self._add_demand(bo, d)
                    self._demand_walk(inner)
                    for v, bv in zip(invars, inner.invars):
                        self._add_demand(v, self._get_demand(bv))
                    ok = True
            if not ok and any_out:
                self._demand_default(eqn, any_out)
        elif name == "scan":
            self._demand_scan(eqn, outs)
        elif name == "cond":
            branches = [_inner_jaxpr(s) for _, s in _sub_closed(eqn)]
            fit = [b for b in branches
                   if len(b.invars) == len(invars) - 1
                   and len(b.outvars) == len(outvars)]
            if fit and len(fit) == len(branches):
                self._add_demand(invars[0],
                                 FULL if any_out else None)
                for b in branches:
                    for bo, d in zip(b.outvars, outs):
                        self._add_demand(bo, d)
                    self._demand_walk(b)
                    for v, bv in zip(invars[1:], b.invars):
                        self._add_demand(v, self._get_demand(bv))
            else:
                for b in branches:
                    for bo in b.outvars:
                        self._add_demand(bo, FULL if any_out else None)
                    self._demand_walk(b)
                self._demand_default(eqn, any_out)
        elif name == "while":
            # no whiles in honest ticks (JXP404 polices them); any lane
            # array crossing one is conservatively all-live
            for _, sub in _sub_closed(eqn):
                inner = _inner_jaxpr(sub)
                for bo in inner.outvars:
                    self._add_demand(bo, FULL if any_out else None)
                self._demand_walk(inner)
            if any(self._tag(v) is not None for v in invars) and any_out:
                self._fallback_full(
                    eqn, "a lane-tagged array crosses a while_loop — "
                         "conservative all-live")
            else:
                self._demand_default(eqn, any_out)
        else:
            if any(self._tag(v) is not None for v in invars) and \
                    any_out and name not in (
                        "random_wrap", "random_unwrap", "random_bits",
                        "random_fold_in", "random_split",
                        "bitcast_convert_type", "top_k"):
                # an unmodeled primitive consuming a lane array: every
                # lane must be assumed read
                self._fallback_full(
                    eqn, f"unmodeled primitive '{name}' consumes a "
                         f"lane-tagged array — conservative all-live")
                if self._record:
                    self._record_read(range(self.L), eqn)
                return
            self._demand_default(eqn, any_out)

    # -- lane-precise transfer functions --

    def _demand_slice(self, eqn, d0):
        operand = eqn.invars[0]
        t = self._tag(operand)
        in_shape, out_shape = _shape(operand), _shape(eqn.outvars[0])
        if t is None or d0 is None:
            self._add_demand(operand,
                             FULL if d0 is not None else None)
            return
        start = eqn.params["start_indices"][t]
        limit = eqn.params["limit_indices"][t]
        stride = (eqn.params["strides"] or
                  (1,) * len(in_shape))[t]
        if (start, limit, stride) == (0, in_shape[t], 1):
            self._add_demand(operand, d0)     # lane axis untouched
            return
        window = frozenset(range(start, limit, stride))
        if isinstance(d0, frozenset) and self._tag(eqn.outvars[0]) == t:
            # narrowed but still tagged: demand maps straight through
            self._add_demand(operand, d0 & window or frozenset())
            lanes = d0 & window
        else:
            self._add_demand(operand, window)
            lanes = window
        self._record_read(sorted(lanes), eqn)

    def _demand_dynamic_slice(self, eqn, d0):
        operand = eqn.invars[0]
        t = self._tag(operand)
        in_shape = _shape(operand)
        out_shape = _shape(eqn.outvars[0])
        if t is None or d0 is None:
            self._demand_default(eqn, d0 is not None)
            return
        size = out_shape[t]
        for v in eqn.invars[1:]:
            self._add_demand(v, FULL)
        if size == in_shape[t]:
            self._add_demand(operand, d0)
            return
        idx = self._resolve_lane_values(eqn.invars[1 + t])
        if idx is None:
            self._fallback_full(
                eqn, "dynamic_slice along the lane axis with an "
                     "unresolvable start index — conservative all-live")
            self._record_read(range(self.L), eqn)
            return
        lanes = set()
        for i in idx:
            i = max(0, min(int(i), in_shape[t] - size))  # XLA clamps
            lanes.update(range(i, i + size))
        # a start whose (unclamped) window leaves the lane universe is
        # an overread: surface the extreme lane it aimed at
        over = sorted(v if v < 0 else v + size - 1 for v in idx
                      if not 0 <= v <= in_shape[t] - size)
        self._add_demand(operand, frozenset(lanes))
        self._record_read(over + sorted(lanes), eqn)

    def _demand_gather(self, eqn, d0):
        operand, indices = eqn.invars[0], eqn.invars[1]
        t = self._tag(operand)
        if t is None or d0 is None:
            self._demand_default(eqn, d0 is not None)
            return
        dnums = eqn.params["dimension_numbers"]
        slice_sizes = tuple(int(s) for s in eqn.params["slice_sizes"])
        in_shape = _shape(operand)
        self._add_demand(indices, FULL)
        if slice_sizes[t] == in_shape[t]:
            # lane axis rides the window whole: demand passes through
            dd = d0
            if isinstance(dd, frozenset) and \
                    self._tag(eqn.outvars[0]) is None:
                dd = FULL
            self._add_demand(operand, dd)
            return
        start_map = tuple(int(d) for d in dnums.start_index_map)
        if t in start_map:
            # lane-indexed gather (a vmapped dynamic_slice along the
            # lane axis lowers here too): resolve the lane column,
            # widen by the window size
            col = start_map.index(t)
            vals = self._resolve_lane_values(indices)
            if vals is None:
                self._fallback_full(
                    eqn, "gather along the lane axis with an "
                         "unresolvable index — conservative all-live")
                self._record_read(range(self.L), eqn)
                return
            col_exact = len(start_map) == 1
            if not col_exact:
                # the index array interleaves columns for several
                # axes; per-column resolution needs the raw array
                arr = self._cval(indices)
                if arr is not None and arr.ndim >= 1 and \
                        arr.shape[-1] == len(start_map):
                    vals = {int(x) for x in
                            np.unique(arr[..., col])}
                    col_exact = True
                # else: the unioned value set stays — overapproximate,
                # fine for liveness but too coarse for the
                # error-severity overread check (other columns' values
                # are not lane starts)
            w = slice_sizes[t]
            lanes_raw: Set[int] = set()
            for v in vals:
                # XLA clamps the start so the window stays in bounds
                v = max(0, min(int(v), in_shape[t] - w))
                lanes_raw.update(range(v, v + w))
            # a start whose (unclamped) window leaves the lane universe
            # is an overread: surface the extreme lane it aimed at
            over = sorted(v if v < 0 else v + w - 1 for v in vals
                          if not 0 <= v <= in_shape[t] - w) \
                if col_exact else []
            self._record_read(over + sorted(lanes_raw), eqn)
            self._add_demand(operand, frozenset(lanes_raw))
            return
        self._fallback_full(
            eqn, "gather takes a partial lane window — "
                 "conservative all-live")
        self._record_read(range(self.L), eqn)

    def _resolve_scatter_columns(self, eqn, dnums
                                 ) -> Optional[Dict[int, Set[int]]]:
        """operand axis -> set of written indices, for scattered dims."""
        indices = eqn.invars[1]
        arr = self._cval(indices)
        if arr is None:
            return None
        sdims = tuple(int(d) for d in dnums.scatter_dims_to_operand_dims)
        if arr.ndim == 0:
            arr = arr.reshape(1, 1)
        if arr.shape[-1] != len(sdims):
            if len(sdims) == 1:
                arr = arr.reshape(-1, 1)
            else:
                return None
        flat = arr.reshape(-1, len(sdims))
        return {axis: {int(x) for x in np.unique(flat[:, k])}
                for k, axis in enumerate(sdims)}

    def _demand_scatter(self, eqn, d0, rmw: bool):
        operand, indices, updates = eqn.invars[:3]
        t = self._tag(operand)
        if t is None or d0 is None:
            self._demand_default(eqn, d0 is not None)
            return
        self._add_demand(indices, FULL)
        dnums = eqn.params["dimension_numbers"]
        in_shape, up_shape = _shape(operand), _shape(updates)
        window_map = self._scatter_window_map(dnums, len(in_shape))
        inserted = set(int(d) for d in dnums.inserted_window_dims)
        if t in window_map:
            # lane axis rides the update window
            if up_shape[window_map[t]] == in_shape[t]:
                dd = d0
                if isinstance(dd, frozenset) and \
                        self._tag(updates) is None:
                    dd = FULL
                self._add_demand(updates, dd)
                self._add_demand(operand, d0)
                return
            # partial window (a slice-set like ``.at[0, BODY:BODY+2]``):
            # the window's lane start rides the scatter indices when
            # the lane axis is a scattered dim, else it pins to 0
            cols = self._resolve_scatter_columns(eqn, dnums)
            sdims = tuple(int(d)
                          for d in dnums.scatter_dims_to_operand_dims)
            if t not in sdims:
                cols = dict(cols or {})
                cols[t] = {0}
            w = up_shape[window_map[t]]
            if cols is not None and t in cols:
                window: Set[int] = set()
                for v in cols[t]:
                    v = max(0, min(int(v), in_shape[t] - w))
                    window.update(range(v, v + w))
                in_range = frozenset(window)
                full_cover = len(cols[t]) == 1 and all(
                    up_shape[b] == in_shape[a]
                    for a, b in window_map.items() if a != t) and all(
                    cols.get(a) == set(range(in_shape[a]))
                    for a in inserted)
                demanded = (d0 if d0 == FULL
                            else frozenset(d0) & in_range)
                dead = demanded is not FULL and not demanded
                self._record_write(sorted(window), eqn,
                                   dead=dead and not rmw)
                self._add_demand(updates, None if dead else FULL)
                if not rmw and full_cover and isinstance(d0, frozenset):
                    self._add_demand(operand, d0 - in_range)
                else:
                    self._add_demand(operand, d0)
                return
            self._fallback_full(
                eqn, "scatter writes a partial lane window with an "
                     "unresolvable start — conservative all-live")
            return
        if t not in inserted:
            # lane axis is an operand batching dim — nothing narrows
            self._add_demand(updates,
                             FULL if d0 is not None else None)
            self._add_demand(operand, d0)
            return
        cols = self._resolve_scatter_columns(eqn, dnums)
        if cols is None or t not in cols:
            self.note("scatter along the lane axis with unresolvable "
                      "indices — no dead-store credit taken")
            self._add_demand(updates, FULL)
            self._add_demand(operand, d0)
            return
        written = {w for w in cols[t]}
        in_range = frozenset(w for w in written
                             if 0 <= w < in_shape[t])
        # full coverage on every other axis = the write kills the lane:
        # window axes must span the operand, other scattered axes must
        # enumerate their full range
        full_cover = all(
            up_shape[b] == in_shape[a]
            for a, b in window_map.items() if a != t) and all(
            a == t or cols.get(a) == set(range(in_shape[a]))
            for a in inserted)
        demanded = (d0 if d0 == FULL
                    else frozenset(d0) & in_range)
        dead = demanded is not FULL and not demanded
        self._record_write(sorted(written), eqn, dead=dead and not rmw)
        self._add_demand(updates, None if dead else FULL)
        if not rmw and full_cover and isinstance(d0, frozenset):
            self._add_demand(operand, d0 - in_range)
        else:
            self._add_demand(operand, d0)

    def _demand_dus(self, eqn, d0):
        operand, update = eqn.invars[0], eqn.invars[1]
        t = self._tag(operand)
        if t is None or d0 is None:
            self._demand_default(eqn, d0 is not None)
            return
        in_shape, up_shape = _shape(operand), _shape(update)
        for v in eqn.invars[2:]:
            self._add_demand(v, FULL)
        if up_shape[t] == in_shape[t]:
            dd = d0
            if isinstance(dd, frozenset) and self._tag(update) is None:
                dd = FULL
            self._add_demand(update, dd)
            self._add_demand(operand, d0)
            return
        idx = self._resolve_lane_values(eqn.invars[2 + t])
        if idx is None:
            self.note("dynamic_update_slice along the lane axis with "
                      "an unresolvable start — no dead-store credit "
                      "taken")
            self._add_demand(update, FULL)
            self._add_demand(operand, d0)
            return
        window = set()
        for i in idx:
            i = max(0, min(int(i), in_shape[t] - up_shape[t]))
            window.update(range(i, i + up_shape[t]))
        window = frozenset(window)
        full_cover = all(up_shape[a] == in_shape[a]
                         for a in range(len(in_shape)) if a != t)
        demanded = d0 if d0 == FULL else frozenset(d0) & window
        dead = demanded is not FULL and not demanded
        self._record_write(sorted(window), eqn, dead=dead)
        self._add_demand(update, None if dead else FULL)
        if full_cover and isinstance(d0, frozenset) and len(idx) == 1:
            self._add_demand(operand, d0 - window)
        else:
            self._add_demand(operand, d0)

    def _demand_scan(self, eqn, outs):
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        invars, outvars = eqn.invars, eqn.outvars
        for _, sub in _sub_closed(eqn):
            inner = _inner_jaxpr(sub)
            # seed body outs: final-carry + ys demand (lane masks pass:
            # the tagger aligned coordinates across the scan axis)
            for k in range(ncar):
                self._add_demand(inner.outvars[k], outs[k])
            for k in range(len(outvars) - ncar):
                d = outs[ncar + k]
                bo = inner.outvars[ncar + k]
                if isinstance(d, frozenset) and (
                        self._tag(outvars[ncar + k]) is None
                        or self._tag(bo) is None):
                    d = FULL
                self._add_demand(bo, d)
            # inner fixpoint: carry-in demand feeds carry-out
            for _ in range(40):
                before = self._snapshot(inner)
                for k in range(ncar):
                    self._add_demand(inner.outvars[k],
                                     self._get_demand(
                                         inner.invars[nc + k]))
                self._demand_walk(inner)
                if self._snapshot(inner) == before:
                    break
            # eqn inputs from body inputs
            for k in range(nc):
                self._add_demand(invars[k],
                                 self._get_demand(inner.invars[k]))
            for k in range(ncar):
                self._add_demand(invars[nc + k],
                                 self._get_demand(
                                     inner.invars[nc + k]))
            for k in range(len(invars) - nc - ncar):
                d = self._get_demand(inner.invars[nc + ncar + k])
                xv = invars[nc + ncar + k]
                if isinstance(d, frozenset) and (
                        self._tag(xv) is None or
                        self._tag(inner.invars[nc + ncar + k]) is None):
                    d = FULL
                self._add_demand(xv, d)

    def _snapshot(self, jaxpr):
        return tuple(self.demand.get(v)
                     for v in list(jaxpr.invars) + list(jaxpr.outvars))


# --- per-model analysis ----------------------------------------------------


def _carry_paths(carry) -> List[str]:
    import jax
    return [jax.tree_util.keystr(kp) for kp, _ in
            jax.tree_util.tree_flatten_with_path(carry)[0]]


def _pool_lane_axis(layout: str, pool_shape: Tuple[int, ...],
                    n_lanes: int) -> int:
    axis = 1 if layout == "minor" else len(pool_shape) - 1
    if pool_shape[axis] != n_lanes:
        raise ValueError(
            f"pool leaf {pool_shape} does not carry {n_lanes} lanes at "
            f"axis {axis} (layout={layout!r})")
    return axis


# carry fields that are observables in their own right: fetched and
# reported by the harness after every run, so their demand is
# unconditional (everything else earns its liveness through the carry
# fixed point)
_OBSERVED_CARRY_FIELDS = ("stats", "violations", "telemetry", "key")


def analyze_model(model, node_count: int, layout: str = "lead",
                  label: Optional[str] = None, sim=None,
                  traced=None, cost=None,
                  trace_cache=None) -> LaneReport:
    """Run the lane-liveness slice for one model x layout. ``sim``
    overrides the shared audit config (bench.py passes its own, so the
    metric line prices the configuration it measures). ``traced`` (a
    ``cost_model.trace_tick`` triple) and ``cost`` (its
    ``cost_of_jaxpr`` report) let callers that already traced the SAME
    model x sim skip the duplicate abstract trace / cost walk."""
    import jax

    if sim is not None:
        # a caller-supplied sim changes the tick graph, but the shared
        # cache is keyed by (name, n, layout) from audit sims only —
        # never mix the two
        layout = sim.layout
        trace_cache = None
    label = label or f"{getattr(model, 'name', type(model).__name__)}" \
                     f"/{layout}"
    if sim is None:
        sim = cost_model.audit_sim(model, node_count, layout)
    closed, carry, out_shapes = traced or cost_model.trace_tick(
        model, sim, cache=trace_cache)
    n_lanes = sim.net.lanes
    carry_leaves = jax.tree_util.tree_leaves(carry)
    paths = _carry_paths(carry)
    n_carry = len(carry_leaves)

    pool_idx = paths.index(".pool")
    lane_axis = _pool_lane_axis(layout, carry_leaves[pool_idx].shape,
                                n_lanes)
    ana = _Analyzer(closed, n_lanes, {pool_idx: lane_axis})
    ana.fold_consts()
    ana.infer_tags()

    # observable seeding: ys (history events / journal rows) are FULL;
    # observed carry fields are FULL; the rest starts dead and earns
    # demand through the feedback edges
    n_out = len(closed.jaxpr.outvars)
    out_demands: List[Any] = [None] * n_out
    for i, p in enumerate(paths):
        field_name = p.split(".")[1].split("[")[0] if "." in p else p
        if field_name in _OBSERVED_CARRY_FIELDS:
            out_demands[i] = FULL
    for i in range(n_carry, n_out):
        out_demands[i] = FULL
    carry_pairs = [(i, i) for i in range(n_carry)]
    ana.run_demand(out_demands, carry_pairs)

    # live lanes = the carry pool's demand at the fixpoint, plus every
    # recorded read site (reads of rows that never reach the pool)
    pool_demand = ana.demand.get(closed.jaxpr.invars[pool_idx])
    live: Set[int] = set(ana.reads)
    if pool_demand == FULL:
        ana.conservative = True
        ana.note("the message pool's demand widened to all lanes")
        live = set(range(n_lanes))
    elif isinstance(pool_demand, frozenset):
        live |= set(pool_demand)
    if ana.conservative:
        live = set(range(n_lanes))

    report = LaneReport(label=label, lanes=n_lanes,
                        body_lanes=model.body_lanes,
                        live_lanes=live,
                        reads={k: set(v) for k, v in ana.reads.items()},
                        writes={k: set(v) for k, v in ana.writes.items()},
                        dead_stores=sorted(set(ana.dead_stores)),
                        overreads=sorted(set(ana.overreads)),
                        conservative=ana.conservative,
                        notes=list(ana.notes))

    # per-leaf classification + byte attribution
    for i, (p, leaf) in enumerate(zip(paths, carry_leaves)):
        nbytes = int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
        d = ana.demand.get(closed.jaxpr.invars[i])
        outvar = closed.jaxpr.outvars[i]
        written = outvar is not closed.jaxpr.invars[i]
        status = "dead" if d is None else \
            ("live" if written else "carried")
        report.carry_leaves[p] = {"status": status, "bytes": nbytes}

    # dead-byte attribution: every lane-tagged intermediate pays for
    # its dead lanes, scan bodies trip-weighted — the exact accounting
    # ir_bytes_est uses, so the two subtract meaningfully
    dead_lanes = set(range(n_lanes)) - live
    if cost is None and trace_cache is not None:
        # the ir/cost pass ran first in the combined gate and left its
        # report next to the shared trace
        cost = trace_cache.get(cost_model.entry_key(
            getattr(model, "name", type(model).__name__),
            sim.net.n_nodes, sim.layout) + "::cost")
    cost = cost or cost_model.cost_of_jaxpr(closed, carry)
    report.ir_bytes_est = cost.hbm_bytes
    dead_frac = len(dead_lanes) / n_lanes
    dead_bytes = 0.0
    if dead_frac:
        def walk(jaxpr, mult):
            nonlocal dead_bytes
            for eqn in jaxpr.eqns:
                for v in eqn.outvars:
                    if ana._tag(v) is not None and \
                            _shape(v)[ana._tag(v)] == n_lanes:
                        dead_bytes += cost_model._aval_bytes(v) \
                            * dead_frac * mult
                for sub, sub_mult in cost_model._sub_jaxprs(eqn):
                    walk(sub, mult * sub_mult)
        walk(closed.jaxpr, 1)
    # dead carry leaves are pure headroom too
    dead_bytes += sum(e["bytes"] for e in report.carry_leaves.values()
                      if e["status"] == "dead")
    report.dead_bytes_est = int(dead_bytes)
    return report


# --- findings --------------------------------------------------------------


def _model_path(model) -> str:
    return type(model).__module__.replace(".", os.sep) + ".py"


def _finding(rule, name, severity, path, symbol, message) -> Finding:
    return Finding(rule=rule, name=name, severity=severity,
                   pass_name=PASS_NAME, path=path, line=0,
                   symbol=symbol, message=message)


def findings_of_report(model, report: LaneReport) -> List[Finding]:
    """LNE601-LNE605 from one model's liveness result."""
    path = _model_path(model)
    cls = type(model).__name__
    out: List[Finding] = []

    def flag(rule, name, message, severity):
        out.append(_finding(rule, name, severity, path, cls,
                            f"[{report.label}] {message}"))

    for lane, phase in sorted(set(report.overreads)):
        flag("LNE604", "lane-overread",
             f"a resolved lane index reaches lane {lane}, outside the "
             f"declared universe of {report.lanes} lanes "
             f"(8 header + body_lanes={report.body_lanes}) — under jit "
             f"the access silently clamps to lane {report.lanes - 1} "
             f"and reads/writes the wrong lane ({phase} phase)",
             SEV_ERROR)
    if report.conservative:
        flag("LNE605", "lane-unresolvable",
             "a lane index could not be resolved statically — the "
             "model is conservatively ALL-LIVE (no dead-lane credit); "
             + "; ".join(report.notes[:3]), SEV_WARNING)
        return out
    if report.dead_body_lanes:
        flag("LNE601", "dead-body-lane",
             f"body lane(s) {report.dead_body_lanes} of "
             f"{report.body_lanes} are never read on any reachable "
             f"path — ~{report.dead_bytes_est} B/tick of dead lane "
             f"traffic; narrowing body_lanes to the live set "
             f"{report.live_body_lanes} is trajectory-preserving "
             f"(ROADMAP item 2 headroom)", SEV_WARNING)
    for leaf in report.dead_carry_leaves:
        flag("LNE602", "dead-carry-leaf",
             f"carry leaf {leaf} "
             f"({report.carry_leaves[leaf]['bytes']} B) feeds no "
             f"observable output — not even through the carry fixed "
             f"point; it is pure HBM ballast", SEV_WARNING)
    dead_stores = sorted({(lane, phase)
                          for lane, phase in report.dead_stores})
    from ..tpu import wire
    body_dead = [(lane, phase) for lane, phase in dead_stores
                 if lane >= wire.BODY]
    if body_dead:
        detail = ", ".join(f"lane {lane} ({phase})"
                           for lane, phase in body_dead[:6])
        flag("LNE603", "dead-store",
             f"body lane store(s) never read before being overwritten "
             f"or dropped: {detail} — wasted writes the narrow layout "
             f"would delete", SEV_WARNING)
    return out


# --- manifest io + drift gate ----------------------------------------------


def load_lane_manifest(path: Optional[str] = None) -> Dict[str, Any]:
    path = path or DEFAULT_LANE_MANIFEST
    if not os.path.exists(path):
        return {"version": 1, "entries": {}}
    with open(path) as f:
        data = json.load(f)
    data.setdefault("entries", {})
    return data


def save_lane_manifest(entries: Dict[str, Dict[str, Any]],
                       path: Optional[str] = None) -> str:
    import jax
    path = path or DEFAULT_LANE_MANIFEST
    payload = {
        "version": 1,
        "_comment": (
            "Per-model live-lane manifest for `maelstrom lint --lanes` "
            "(doc/lint.md). Keys: <workload>/n=<nodes>/<layout>; "
            "live_body_lanes = body lanes provably read on some "
            "reachable path of the tick under the audit config (the "
            "safe narrow-layout target), dead_bytes_per_tick_est = "
            "estimated HBM bytes/tick moved for dead lanes + dead "
            "carry leaves, projected_narrow_ir_bytes_est = ir_bytes_est "
            "minus that headroom. Regenerate after an INTENTIONAL "
            "lane-vocabulary change with `maelstrom lint --lanes "
            "--update-manifest`; live-set drift fails the gate "
            "(LNE606)."),
        "jax-version": jax.__version__,
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return path


def compare_manifest(live: Dict[str, LaneReport],
                     manifest: Dict[str, Any],
                     paths: Dict[str, Tuple[str, str]],
                     full_universe: bool = True,
                     errored: AbstractSet[str] = frozenset(),
                     ) -> List[Finding]:
    """Diff live lane reports against the checked-in manifest. The gate
    compares the LANE SETS (the safety-relevant fact); byte estimates
    are informational and re-recorded with --update-manifest.
    ``errored`` keys failed to analyze this run (they already carry an
    LNE609 error) — their manifest entries are NOT stale, so they are
    exempt from LNE608's remove-or-re-record advice."""
    entries = manifest.get("entries", {})
    note = cost_model.toolchain_note(manifest.get("jax-version"),
                                     "lane manifest",
                                     "--update-manifest")
    findings: List[Finding] = []
    for key in sorted(live):
        rep = live[key]
        path, symbol = paths[key]
        base = entries.get(key)
        if base is None:
            findings.append(_finding(
                "LNE607", "lane-manifest-missing", SEV_ERROR, path,
                symbol,
                f"[{key}] no lane-manifest entry — record one with "
                f"`maelstrom lint --lanes --update-manifest`"))
            continue
        drifts = []
        for field_name, got in (
                ("live_body_lanes", rep.live_body_lanes),
                ("live_header_lanes", rep.live_header_lanes),
                ("resolution", "conservative" if rep.conservative
                 else "exact")):
            want = base.get(field_name)
            if want is not None and want != got:
                drifts.append(f"{field_name}: live {got} vs manifest "
                              f"{want}")
        if drifts:
            findings.append(_finding(
                "LNE606", "lane-manifest-drift",
                SEV_WARNING if note else SEV_ERROR, path, symbol,
                f"[{key}] live lane set drifted from the checked-in "
                f"manifest: {'; '.join(drifts)} — a lane went "
                f"live/dead; if intentional, re-record with "
                f"--update-manifest and justify it in the PR"
                + (f" ({note})" if note else "")))
    if full_universe:
        for key in sorted(set(entries) - set(live) - set(errored)):
            findings.append(_finding(
                "LNE608", "lane-manifest-stale", SEV_WARNING,
                "maelstrom_tpu/analysis/lane_manifest.json", "",
                f"[{key}] manifest entry matches no registered "
                f"model x layout — remove or re-record it"))
    return findings


# --- LNE610: native width-class conformance --------------------------------


_NATIVE_WIRE_PATH = "maelstrom_tpu/native/wire.py"


def native_width_findings(cpp_src: Optional[str] = None,
                          table: Optional[Dict[str, int]] = None,
                          include_fixture: bool = True) -> List[Finding]:
    """LNE610: cross-check the native engine's templated per-family
    width constants (parsed from ``cpp/engine/sim.cpp``), the Python
    width table (``native/wire.py``), the registry's per-family lane
    math, and — when built — the compiled ``libsim.so``. The fixture
    table (:data:`..native.wire.FIXTURE_DIVERGENT_WIDTHS`) is audited
    alongside on full runs so the rule provably fires (expected-status
    baseline entry, the ir_hazards idiom)."""
    from ..native import wire as nwire

    findings: List[Finding] = []
    try:
        registry = nwire.registry_width_facts()
    except Exception as e:
        registry = None
        findings.append(_finding(
            "LNE610", "native-width-divergence", SEV_ERROR,
            _NATIVE_WIRE_PATH, "registry_width_facts",
            f"registry width facts unavailable: {e!r}"))
    compiled = None
    try:
        from ..native.engine import native_available, native_msg_lanes
        if native_available():
            compiled = {wl: native_msg_lanes(wl)
                        for wl in nwire.NATIVE_BODY_LANES}
    except Exception:
        compiled = None   # no toolchain — source/table checks still run
    for symbol, message in nwire.check_native_widths(
            cpp_src=cpp_src, table=table,
            registry_entry_lanes=registry, compiled_lanes=compiled):
        findings.append(_finding(
            "LNE610", "native-width-divergence", SEV_ERROR,
            "cpp/engine/sim.cpp", symbol, message))
    if include_fixture and table is None:
        fixture_table = dict(nwire.NATIVE_BODY_LANES,
                             **nwire.FIXTURE_DIVERGENT_WIDTHS)
        for symbol, message in nwire.check_native_widths(
                cpp_src=cpp_src, table=fixture_table):
            findings.append(_finding(
                "LNE610", "native-width-divergence", SEV_ERROR,
                _NATIVE_WIRE_PATH, "FIXTURE_DIVERGENT_WIDTHS",
                f"[fixture] {message}"))
    return findings


# --- orchestration ---------------------------------------------------------


def run_lane_lint(repo_root: str = ".",
                  manifest_path: Optional[str] = None,
                  update_manifest: bool = False,
                  workloads: Optional[List[Tuple[str, int]]] = None,
                  layouts: Sequence[str] = cost_model.AUDIT_LAYOUTS,
                  include_fixtures: bool = True,
                  trace_cache=None) -> List[Finding]:
    """The lanes pass: analyze every registered model x layout (or a
    restricted list), emit LNE6xx findings, and gate against (or
    re-record) the manifest."""
    from ..models import get_model

    full = workloads is None
    specs = cost_model.cost_specs() if full else list(workloads)
    findings: List[Finding] = []
    live: Dict[str, LaneReport] = {}
    paths: Dict[str, Tuple[str, str]] = {}
    errored: Set[str] = set()

    for wl, n in specs:
        try:
            model = get_model(wl, n, "grid")
        except Exception as e:
            findings.append(_finding(
                "LNE609", "lane-analysis-failure", SEV_ERROR,
                "maelstrom_tpu/models/__init__.py", "get_model",
                f"get_model({wl!r}, {n}) raised: {e!r}"))
            errored.update(cost_model.entry_key(wl, n, lay)
                           for lay in layouts)
            continue
        for layout in layouts:
            key = cost_model.entry_key(wl, n, layout)
            try:
                rep = analyze_model(model, n, layout,
                                    label=f"{wl}/n={n}/{layout}",
                                    trace_cache=trace_cache)
            except Exception as e:
                findings.append(_finding(
                    "LNE609", "lane-analysis-failure", SEV_ERROR,
                    _model_path(model), type(model).__name__,
                    f"[{key}] lane analysis raised "
                    f"{type(e).__name__}: {e}"))
                errored.add(key)
                continue
            findings.extend(findings_of_report(model, rep))
            live[key] = rep
            paths[key] = (_model_path(model), type(model).__name__)

    if full and include_fixtures:
        from ..models.ir_hazards import LANE_FIXTURE_MODELS
        for kind, cls in sorted(LANE_FIXTURE_MODELS.items()):
            model = cls()
            try:
                rep = analyze_model(model, 2, "lead",
                                    label=f"fixture-{kind}")
            except Exception as e:
                findings.append(_finding(
                    "LNE609", "lane-analysis-failure", SEV_ERROR,
                    _model_path(model), type(model).__name__,
                    f"[fixture-{kind}] lane analysis raised "
                    f"{type(e).__name__}: {e}"))
                continue
            findings.extend(findings_of_report(model, rep))

    if full:
        findings.extend(native_width_findings())

    if update_manifest:
        path = save_lane_manifest(
            {k: r.to_entry() for k, r in live.items()}, manifest_path)
        findings.append(_finding(
            "LNE600", "lane-manifest-updated", SEV_INFO,
            os.path.relpath(path, os.path.abspath(repo_root))
            if os.path.isabs(path) else path, "",
            f"recorded {len(live)} lane-manifest entr"
            f"{'y' if len(live) == 1 else 'ies'}"))
    else:
        manifest = load_lane_manifest(manifest_path)
        findings.extend(compare_manifest(live, manifest, paths,
                                         full_universe=full,
                                         errored=errored))
    return findings


# --- bench/profiler surface ------------------------------------------------


def lane_stats(model, sim, traced=None, cost=None) -> Dict[str, int]:
    """One-call liveness stats for bench.py / tools/tick_profile.py
    metric lines: live lane count, dead lane count, and the dead-byte
    estimate next to ``ir_bytes_est`` (same sim = same tick graph;
    pass the tools' already-computed ``trace_tick`` triple / cost
    report to skip re-tracing it)."""
    rep = analyze_model(model, sim.net.n_nodes, sim.layout, sim=sim,
                        traced=traced, cost=cost)
    return {"lanes_live": len(rep.live_lanes),
            "lanes_dead": rep.lanes - len(rep.live_lanes),
            "lanes_dead_bytes": rep.dead_bytes_est}
